"""End-to-end serving latency under offered load (VERDICT round-1 item #8).

Drives the full RecognizerService path — connector -> FrameBatcher ->
fused device pipeline -> async readback -> result publish — at fixed
offered frame rates and records the user-visible latency per frame
(send time -> result publish time), INCLUDING batching delay, device
compute, and device->host readback. This is the path the <15 ms p50
north-star target (BASELINE.json:5) is about; bench.py measures the bare
device step.

Prints one JSON line per offered rate and writes BENCH_SERVING.json.

Caveat recorded in the artifact: on this box the chip sits behind a
tunneled PJRT backend whose first device->host readback puts the process
into ~100 ms sync-poll mode (see runtime/recognizer.py docstring) — an
artifact of the tunnel, not the chip. The async-readback design keeps
throughput sustained with zero drops as offered load grows; end-to-end
latency still rises with queueing on top of the tunnel's readback floor
(the recorded artifact shows exactly that), which is why the artifact also
records a per-frame decomposition separating queue-wait, device dispatch,
readback, and publish.

The artifact now also carries an ``overlap_comparison`` section — the same
offered-load ladder driven through the legacy inline-poll serving loop and
through the overlapped pipeline (readback worker + continuous batching +
bucketed dispatch) — and ``--smoke`` runs a deterministic fake-backend
variant (``run_smoke``) that emulates the tunnel's sync-poll floor on CPU
and writes BENCH_SERVING_smoke.json (also invokable as
``scripts/bench_serving.py --smoke``), now with an ``overload_sweep``
section (``run_overload_sweep``): a 1x/2x/4x offered-load ladder against
a deterministic capacity wall with the admission/brownout/shedding stack
armed, recording per-priority completion and sheds by reason — and an
``ingest`` section (``run_ingest_smoke``, ISSUE 12): the staging-ring
H2D tail gate (ring uint8 p99 within 3x p50 at every bucket rung),
the uint8 completed-frames uplift vs the f32 baseline against a
transfer-bound fake backend, and the compressed-frame intake sanity arm.

Run:  PYTHONPATH=. python bench_serving.py [--rates 50 200 500]
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import time

import numpy as np


def build_pipeline(frame_hw=(256, 256), gallery_size=1024):
    """The expensive shared part: trained detector + embedder + gallery.
    Built once; serving configurations (batch/flush/depth) wrap it via
    ``make_service`` without repeating the ~60 s detector warm-train."""
    from opencv_facerecognizer_tpu.models.detector import CNNFaceDetector
    from opencv_facerecognizer_tpu.models.embedder import (
        SERVING_EMBEDDER_KWARGS, SERVING_FACE_SIZE, FaceEmbedNet,
        init_embedder,
    )
    from opencv_facerecognizer_tpu.parallel import ShardedGallery, make_mesh
    from opencv_facerecognizer_tpu.parallel.pipeline import RecognitionPipeline
    from opencv_facerecognizer_tpu.utils.dataset import make_synthetic_scenes

    h, w = frame_hw
    det = CNNFaceDetector(max_faces=8, score_threshold=0.3)
    scenes, boxes, counts = make_synthetic_scenes(
        num_scenes=48, scene_size=(h, w), max_faces=8,
        face_size_range=(24, 56), seed=7,
    )
    det.train(scenes, boxes, counts, steps=150, batch_size=16)

    net = FaceEmbedNet(**SERVING_EMBEDDER_KWARGS)
    emb_params = init_embedder(net, num_classes=16,
                               input_shape=SERVING_FACE_SIZE,
                               seed=0)["net"]
    rng = np.random.default_rng(0)
    dim = SERVING_EMBEDDER_KWARGS["embed_dim"]
    gal_emb = rng.normal(size=(gallery_size, dim)).astype(np.float32)
    mesh = make_mesh()
    import jax.numpy as jnp

    # bf16 rows: the ocvf-recognize serving default (gallery_dtype A/B)
    gallery = ShardedGallery(capacity=gallery_size, dim=dim, mesh=mesh,
                             store_dtype=jnp.bfloat16)
    gallery.add(gal_emb, rng.integers(0, 64, gallery_size).astype(np.int32))
    pipeline = RecognitionPipeline(det, net, emb_params, gallery,
                                   face_size=SERVING_FACE_SIZE)
    # Distinct frames to cycle (no same-buffer effects).
    frames = [np.asarray(s, np.float32) for s in make_synthetic_scenes(
        num_scenes=16, scene_size=(h, w), max_faces=8,
        face_size_range=(24, 56), seed=9,
    )[0]]
    return pipeline, frames


def make_service(pipeline, frame_hw, batch_size, flush_ms, inflight_depth,
                 readback_worker=True, target_latency_ms=None,
                 bucket_sizes=None):
    from opencv_facerecognizer_tpu.runtime.connector import FakeConnector
    from opencv_facerecognizer_tpu.runtime.recognizer import (
        DEFAULT_BUCKET_SIZES, RecognizerService,
    )
    from opencv_facerecognizer_tpu.utils.metrics import Metrics

    connector = FakeConnector()
    service = RecognizerService(
        pipeline, connector, batch_size=batch_size, frame_shape=frame_hw,
        flush_timeout=flush_ms / 1e3, inflight_depth=inflight_depth,
        similarity_threshold=0.0, metrics=Metrics(),
        readback_worker=readback_worker,
        target_latency_s=(None if target_latency_ms is None
                          else target_latency_ms / 1e3),
        bucket_sizes=(DEFAULT_BUCKET_SIZES if bucket_sizes is None
                      else bucket_sizes),
    )
    return service, connector


def drive_rate(service, connector, frames, rate_hz: float, duration_s: float):
    """Offer frames at rate_hz for duration_s; return latency stats."""
    from opencv_facerecognizer_tpu.runtime.recognizer import (
        FRAME_TOPIC, RESULT_TOPIC,
    )

    done = {}
    lock = threading.Lock()

    def on_result(topic, message):
        seq = (message.get("meta") or {}).get("seq")
        if seq is not None:
            with lock:
                done[seq] = time.perf_counter()

    connector.subscribe(RESULT_TOPIC, on_result)

    sent = {}
    interval = 1.0 / rate_hz
    n = int(duration_s * rate_hz)
    start = time.perf_counter()
    for i in range(n):
        target = start + i * interval
        now = time.perf_counter()
        if target > now:
            time.sleep(target - now)
        sent[i] = time.perf_counter()
        connector.inject(FRAME_TOPIC, {"frame": frames[i % len(frames)],
                                       "meta": {"seq": i}})
    # allow the tail to drain
    deadline = time.perf_counter() + 10.0
    while time.perf_counter() < deadline:
        with lock:
            if len(done) >= n:
                break
        time.sleep(0.02)

    with lock:
        lat = np.asarray([
            (done[i] - sent[i]) * 1e3 for i in sent if i in done
        ])
    completed = len(lat)
    stats = {
        "offered_hz": rate_hz,
        "offered_frames": n,
        "completed_frames": completed,
        "dropped_frames": n - completed,
        "achieved_hz": round(completed / duration_s, 1),
    }
    if completed:
        stats.update({
            "e2e_p50_ms": round(float(np.percentile(lat, 50)), 2),
            "e2e_p90_ms": round(float(np.percentile(lat, 90)), 2),
            "e2e_p99_ms": round(float(np.percentile(lat, 99)), 2),
            "e2e_mean_ms": round(float(lat.mean()), 2),
        })
    # Per-frame/batch decomposition from the service's own instrumentation
    # (recorded since the start of this rate run — the caller resets the
    # metrics object per rate): queue_wait (enqueue -> batch pop, the
    # batching delay), dispatch (host-side H2D + async enqueue), ready_wait
    # (dispatch -> readback complete: device compute + D2H + poll slack —
    # the tunnel's ~100 ms sync-poll floor lands here), publish (decode +
    # connector fan-out).
    summary = service.metrics.summary()
    decomp = {k: round(v, 2) for k, v in summary.items()
              if v is not None  # empty windows report explicit nulls
              and k.split("_p")[0] in ("queue_wait", "dispatch", "ready_wait",
                                       "publish")}
    if decomp:
        stats["decomposition_ms"] = decomp
    return stats


def measure_dispatch_quote(pipeline, frames, batch_size, n=20):
    """Host dispatch cost (p50 ms) of the packed step BEFORE this process's
    first device->host readback. The axon tunnel enters a ~100 ms sync-poll
    mode at the first readback, after which EVERY jax call — including the
    nominally-async dispatch — is quantized by the poll interval; measured
    in-service dispatch then reads ~17 ms where the true pre-readback cost
    is sub-millisecond. So the quote is taken first, in a dispatch-only
    phase (block_until_ready does not await on this backend and no value is
    materialized here)."""
    first = np.stack([frames[i % len(frames)] for i in range(batch_size)])
    pipeline.recognize_batch_packed(first)  # compile (async)
    ts = []
    for i in range(n):
        b = np.stack([frames[(i + j) % len(frames)]
                      for j in range(batch_size)])
        t0 = time.perf_counter()
        pipeline.recognize_batch_packed(b)
        ts.append(time.perf_counter() - t0)
    return round(float(np.percentile(ts, 50) * 1e3), 3)


def run_mode(pipeline, frames, frame_hw, *, name, batch_size, flush_ms,
             inflight_depth, rates, duration_s, device_ms_quote=None,
             dispatch_ms_quote=None, readback_worker=True,
             target_latency_ms=None, bucket_sizes=None):
    """Drive one serving configuration over the offered rates; fresh
    metrics per rate so each row's decomposition covers that rate only."""
    from opencv_facerecognizer_tpu.utils.metrics import Metrics

    service, connector = make_service(pipeline, frame_hw, batch_size,
                                      flush_ms, inflight_depth,
                                      readback_worker=readback_worker,
                                      target_latency_ms=target_latency_ms,
                                      bucket_sizes=bucket_sizes)
    service.start(warmup=True)
    rows = []
    try:
        for rate in rates:
            service.metrics = Metrics()
            print(f"[{name}] offered rate {rate} frames/s x {duration_s}s ...",
                  file=sys.stderr)
            stats = drive_rate(service, connector, frames, rate, duration_s)
            stats["faces_found"] = service.metrics.counter("faces_found")
            decomp = stats.get("decomposition_ms", {})
            if device_ms_quote is not None and decomp:
                # The <15 ms target decomposition (BASELINE.json:5). The
                # MEASURED terms above are themselves tunnel-polluted once
                # sync-poll mode is active (dispatch reads ~17 ms vs its
                # pre-readback ~1 ms; queue_wait balloons because the loop
                # is parked on 100 ms polls instead of popping batches), so
                # the non-tunnel pipeline cost is MODELED from terms each
                # measured free of the poll quantum: queue-wait bound =
                # min(flush window, batch fill time at the offered rate);
                # dispatch = the pre-sync-poll quote; device = bench.py's
                # chained-diff ms/batch; publish = the in-service
                # measurement (pure host numpy + callbacks, unquantized).
                # Everything else in e2e is the tunnel.
                queue_bound = min(
                    flush_ms, (batch_size - 1) / max(rate, 1e-9) * 1e3)
                model = {
                    "queue_wait_bound_ms": round(queue_bound, 3),
                    "dispatch_quote_ms": dispatch_ms_quote,
                    "device_compute_quote_ms": device_ms_quote,
                    "publish_measured_ms": decomp.get("publish_p50_ms", 0.0),
                }
                non_tunnel = sum(v for v in model.values() if v is not None)
                stats["non_tunnel_model"] = model
                stats["non_tunnel_modeled_p50_ms"] = round(non_tunnel, 2)
                stats["meets_15ms_target_ex_tunnel"] = bool(non_tunnel < 15.0)
            rows.append(stats)
            print(json.dumps(stats))
    finally:
        service.stop()
    return {
        "config": {"batch_size": batch_size, "flush_ms": flush_ms,
                   "inflight_depth": inflight_depth,
                   "frame": list(frame_hw), "duration_s": duration_s,
                   "readback_worker": readback_worker,
                   "target_latency_ms": target_latency_ms},
        "rates": rows,
    }


# ---- deterministic smoke (fake instant backend; no hardware, no training) ----


def run_smoke(out_path="BENCH_SERVING_smoke.json", frames_n=160,
              rate_hz=200.0, batch_size=8, frame_hw=(64, 64),
              sync_poll_floor_s=0.1, compute_s=0.002,
              modes=("overlapped", "legacy_poll"), write=True):
    """Fast, deterministic serving-loop perf check over the fake instant
    backend (``runtime.fakes.InstantPipeline``): the "device" completes a
    batch in ``compute_s`` but charges ``sync_poll_floor_s`` on every
    ``is_ready`` call — the tunneled backend's ~100 ms sync-poll readback
    floor, reproduced on CPU. The legacy inline-drain path pays that floor
    on the serving thread; the overlapped readback worker blocks on the
    array instead and never polls a healthy readback, so its ``ready_wait``
    p50 must sit far below the floor with zero drops (the tier-1 perf-smoke
    assertion, tests/test_serving_perf.py). Writes a machine-readable
    artifact to ``out_path``.
    """
    from opencv_facerecognizer_tpu.runtime.connector import FakeConnector
    from opencv_facerecognizer_tpu.runtime.fakes import InstantPipeline
    from opencv_facerecognizer_tpu.runtime.recognizer import RecognizerService
    from opencv_facerecognizer_tpu.utils.metrics import Metrics

    frames = [np.zeros(frame_hw, np.float32)]
    duration_s = frames_n / rate_hz
    results = {}
    for mode in modes:
        worker = mode == "overlapped"
        pipeline = InstantPipeline(frame_hw, compute_s=compute_s,
                                   sync_poll_floor_s=sync_poll_floor_s)
        connector = FakeConnector()
        service = RecognizerService(
            pipeline, connector, batch_size=batch_size, frame_shape=frame_hw,
            flush_timeout=0.05, inflight_depth=4, similarity_threshold=0.0,
            metrics=Metrics(), readback_worker=worker,
            target_latency_s=0.03 if worker else None,
        )
        service.start(warmup=False)  # the fake backend has nothing to compile
        try:
            stats = drive_rate(service, connector, frames, rate_hz, duration_s)
        finally:
            service.drain(timeout=60.0)
            service.stop()
        stats["batches"] = int(service.metrics.counter("batches_dispatched"))
        results[mode] = stats
    artifact = {
        "note": ("fake instant backend (runtime.fakes.InstantPipeline): "
                 f"compute {compute_s * 1e3:g} ms/batch, is_ready sync-poll "
                 f"cost {sync_poll_floor_s * 1e3:g} ms — the tunnel's "
                 "readback floor emulated on CPU. 'overlapped' = readback "
                 "worker (event-driven block) + continuous batching; "
                 "'legacy_poll' = the pre-worker inline is_ready drain. "
                 "ready_wait_p50_ms carries the floor in legacy mode only."),
        "config": {"frames": frames_n, "offered_hz": rate_hz,
                   "batch_size": batch_size, "frame": list(frame_hw),
                   "sync_poll_floor_ms": sync_poll_floor_s * 1e3,
                   "compute_ms": compute_s * 1e3},
        "modes": results,
    }
    if write:
        with open(out_path, "w") as fh:
            json.dump(artifact, fh, indent=2)
        print(f"wrote {out_path}", file=sys.stderr)
    return artifact


def run_tracing_overhead(frames_n=240, rate_hz=200.0, batch_size=8,
                         frame_hw=(64, 64), compute_s=0.002, warm_n=48,
                         trials=3, gate_ratio=1.03, gate_slack_ms=0.5):
    """Tracing-on vs tracing-off overhead comparison over the fake
    instant backend: the same offered load driven through the overlapped
    serving loop with no tracer, then with a ``Tracer`` at **sampling
    1.0** (every frame records receive/queue_wait/settle spans plus batch
    spans — the most expensive configuration). Each trial runs a short
    warm phase first and then ``Metrics.reset_window()`` so the measured
    percentiles cover steady state only.

    Noise handling: the e2e p50 at a paced offered rate is dominated by
    sleep/scheduler jitter on a 1-core host (observed ±10% run-to-run —
    far above tracing's true per-frame cost), so each mode runs
    ``trials`` times in ALTERNATING order and the gate compares the MIN
    p50 per mode: additive scheduler noise only inflates a trial, never
    deflates it, so the min is the noise-robust steady-state estimate.
    Per-trial p50s are recorded so the artifact shows the spread.

    The gate: min tracing-on p50 must stay within ``gate_ratio`` (3%) of
    min tracing-off, plus ``gate_slack_ms`` of absolute slack. Recorded
    as ``within_gate``; a missing measurement FAILS the gate (rc 3 from
    ``--smoke``) rather than skipping it."""
    from opencv_facerecognizer_tpu.runtime.connector import FakeConnector
    from opencv_facerecognizer_tpu.runtime.fakes import InstantPipeline
    from opencv_facerecognizer_tpu.runtime.recognizer import RecognizerService
    from opencv_facerecognizer_tpu.utils.metrics import Metrics
    from opencv_facerecognizer_tpu.utils.tracing import Tracer

    frames = [np.zeros(frame_hw, np.float32)]

    def one_trial(traced: bool):
        tracer = Tracer(ring_size=1 << 15, sample=1.0) if traced else None
        pipeline = InstantPipeline(frame_hw, compute_s=compute_s)
        connector = FakeConnector()
        service = RecognizerService(
            pipeline, connector, batch_size=batch_size, frame_shape=frame_hw,
            flush_timeout=0.05, inflight_depth=4, similarity_threshold=0.0,
            metrics=Metrics(), readback_worker=True, target_latency_s=0.03,
            tracer=tracer,
        )
        service.start(warmup=False)
        try:
            # Warm phase (compile-free here, but fills the EWMA + buffer
            # pool), then reset the latency windows so the measured stats
            # cover the steady state only — the reset_window contract.
            drive_rate(service, connector, frames, rate_hz, warm_n / rate_hz)
            service.metrics.reset_window()
            stats = drive_rate(service, connector, frames, rate_hz,
                               frames_n / rate_hz)
        finally:
            service.drain(timeout=60.0)
            service.stop()
        if tracer is not None:
            stats["spans_held"] = tracer.stats()["spans_held"]
        return stats

    rows = {"tracing_off": {"trial_p50_ms": []},
            "tracing_on": {"trial_p50_ms": []}}
    for _trial in range(trials):
        for mode in ("tracing_off", "tracing_on"):  # alternating order
            stats = one_trial(traced=mode == "tracing_on")
            p50 = stats.get("e2e_p50_ms")
            row = rows[mode]
            row["trial_p50_ms"].append(p50)
            if p50 is not None and (row.get("e2e_p50_ms") is None
                                    or p50 < row["e2e_p50_ms"]):
                row.update(stats)  # keep the full stats of the best trial
    p50_off = rows["tracing_off"].get("e2e_p50_ms")
    p50_on = rows["tracing_on"].get("e2e_p50_ms")
    result = {
        "note": ("same offered load, overlapped loop, fake instant "
                 "backend; tracing_on = Tracer(sample=1.0): every frame "
                 "records receive/queue_wait/settle spans + batch "
                 "dispatch/ready_wait/publish spans. Modes alternate for "
                 f"{trials} trials; the gate compares MIN p50 per mode "
                 "(scheduler noise is additive — see trial_p50_ms for "
                 f"the spread): on <= off * {gate_ratio} + "
                 f"{gate_slack_ms} ms slack."),
        "config": {"frames": frames_n, "offered_hz": rate_hz,
                   "batch_size": batch_size, "compute_ms": compute_s * 1e3,
                   "sample": 1.0, "trials": trials},
        "modes": rows,
    }
    if p50_off is not None and p50_on is not None and p50_off > 0:
        result["p50_ratio"] = round(p50_on / p50_off, 4)
        result["within_gate"] = bool(
            p50_on <= p50_off * gate_ratio + gate_slack_ms)
    else:
        # A missing measurement (empty latency window, zero completions)
        # must FAIL the gate, not skip it — a regression that breaks the
        # measurement itself would otherwise pass silently.
        result["within_gate"] = False
        result["gate_error"] = "e2e p50 unavailable in one or both modes"
    return result


def run_ingest_smoke(rungs=(8, 32, 128), frame_hw=(64, 64), h2d_iters=160,
                     h2d_trials=3, h2d_warmup=16, p99_slack_ms=0.25,
                     uplift_batches=(32, 128), uplift_seconds=1.6,
                     uplift_frame_hw=(128, 128), uplift_h2d_gb_s=0.01,
                     uplift_overdrive=1.3, jpeg_frames=48):
    """The ingest-pipeline gate (ISSUE 12): three deterministic arms.

    **h2d** — per dispatch-bucket rung, staging + H2D transfer latency of
    three paths: ``f32_fresh`` (the legacy float path: a fresh f32
    staging allocation per batch, 4x the bytes), ``uint8_unpinned`` (the
    OLD --transfer-uint8 shortcut: 1x bytes but still a fresh allocation
    per batch — the page-fault/allocator churn behind its measured
    118 ms p99 under load), and ``uint8_ring`` (the new path: one
    pre-allocated recycled StagingRing buffer, copied into and uploaded).
    The gate pins the RING arm's tail: p99 <= 3 x p50 (+ a small
    absolute slack so scheduler noise on a microsecond-scale p50 cannot
    fail a healthy run) at EVERY rung — the p99 pathology is gone.

    **uplift** — end-to-end completed frames against a transfer-bound
    fake backend (``InstantPipeline(h2d_gb_s=...)`` sleeps out each
    batch's actual bytes): the same offered overload driven through
    ``--ingest-mode f32`` and ``uint8`` services at b32/b128. Gates:
    uint8 completes >= 1.15x the f32 baseline at b32, ships >= 3.5x
    fewer bytes/frame, and the staging ring allocates NOTHING beyond its
    preallocation (the zero-steady-state-alloc counter assertion).

    **jpeg** — compressed intake sanity: seeded synthetic JPEG payloads
    through the decode pool into the ring; every offered frame must
    complete, with decode latency on the shared metrics surface.
    """
    import jax

    from opencv_facerecognizer_tpu.runtime.admission import (
        AdmissionController,
    )
    from opencv_facerecognizer_tpu.runtime.connector import FakeConnector
    from opencv_facerecognizer_tpu.runtime.fakes import (
        InstantPipeline, synthetic_jpeg_frames,
    )
    from opencv_facerecognizer_tpu.runtime.ingest import (
        IngestConfig, StagingRing, encode_jpeg_message,
    )
    from opencv_facerecognizer_tpu.runtime.recognizer import (
        FRAME_TOPIC, RecognizerService,
    )
    from opencv_facerecognizer_tpu.utils.metrics import Metrics

    import gc

    h, w = frame_hw
    rng = np.random.default_rng(0)
    h2d = {}
    h2d_ok = True

    def _make_arms(rung):
        base = rng.integers(0, 255, size=(rung, h, w)).astype(np.uint8)
        base_f32 = base.astype(np.float32)
        ring = StagingRing([rung], frame_hw, np.uint8, depth=2)
        buf = ring.acquire(rung)

        def legacy_f32():
            t0 = time.perf_counter()
            arr = base_f32.astype(np.float32)  # fresh staging alloc, 4 B/px
            jax.block_until_ready(jax.device_put(arr))
            return time.perf_counter() - t0

        def unpinned_u8():
            t0 = time.perf_counter()
            arr = base.copy()  # fresh staging alloc per batch (old path)
            jax.block_until_ready(jax.device_put(arr))
            return time.perf_counter() - t0

        def ring_u8():
            t0 = time.perf_counter()
            np.copyto(buf, base)  # recycled pre-allocated staging buffer
            jax.block_until_ready(jax.device_put(buf))
            return time.perf_counter() - t0

        return (("f32_fresh", legacy_f32, 4), ("uint8_unpinned", unpinned_u8, 1),
                ("uint8_ring", ring_u8, 1))

    # Best-of-``h2d_trials`` percentiles per (rung, arm), GC paused during
    # timing, trials INTERLEAVED across all cells: scheduler noise on a
    # 1-core box is strictly ADDITIVE (it inflates a trial's tail, never
    # deflates it), so — exactly like the tracing-overhead gate's min-p50
    # rule — the min-p99 trial is the noise-robust tail estimate, and
    # interleaving spreads one cell's trials seconds apart so a single
    # noise burst cannot eat all of them. Per-trial p99s are recorded so
    # the artifact shows the spread.
    cells = {rung: _make_arms(rung) for rung in rungs}
    samples = {(rung, tag): [] for rung in rungs
               for tag, _fn, _b in cells[rung]}
    for _trial in range(h2d_trials):
        for rung in rungs:
            for tag, fn, _bytes_per in cells[rung]:
                lat = []
                gc_was_enabled = gc.isenabled()
                gc.disable()
                try:
                    for _ in range(h2d_iters):
                        lat.append(fn())
                finally:
                    if gc_was_enabled:
                        gc.enable()
                samples[(rung, tag)].append(
                    np.asarray(lat[h2d_warmup:]) * 1e3)  # ms, sans warmup
    for rung in rungs:
        row = {}
        for tag, _fn, bytes_per in cells[rung]:
            trials = samples[(rung, tag)]
            trial_p99s = [float(np.percentile(t, 99)) for t in trials]
            best = trials[int(np.argmin(trial_p99s))]
            row[tag] = {
                "bytes_per_frame": h * w * bytes_per,
                "p50_ms": round(float(np.percentile(best, 50)), 4),
                "p99_ms": round(float(np.percentile(best, 99)), 4),
                "trial_p99_ms": [round(p, 4) for p in trial_p99s],
            }
        p50 = row["uint8_ring"]["p50_ms"]
        p99 = row["uint8_ring"]["p99_ms"]
        row["ring_p99_within_3x_p50"] = bool(p99 <= 3 * p50 + p99_slack_ms)
        row["ring_vs_unpinned_p99"] = (
            round(row["uint8_unpinned"]["p99_ms"] / p99, 2) if p99 else None)
        h2d_ok = h2d_ok and row["ring_p99_within_3x_p50"]
        h2d[str(rung)] = row
        print(json.dumps({"ingest_h2d_rung": rung, **{
            t: row[t] for t in ("f32_fresh", "uint8_unpinned",
                                "uint8_ring")}}), file=sys.stderr)

    def _drive_uplift(mode, batch, offered_hz):
        metrics = Metrics()
        pipeline = InstantPipeline(uplift_frame_hw, dispatch_s=0.002,
                                   h2d_gb_s=uplift_h2d_gb_s)
        connector = FakeConnector()
        service = RecognizerService(
            pipeline, connector, batch_size=batch,
            frame_shape=uplift_frame_hw, flush_timeout=0.03,
            inflight_depth=4, similarity_threshold=0.0, metrics=metrics,
            admission=AdmissionController(max_inflight_frames=4 * batch),
            shed_stale_after_s=0.5,
            ingest=IngestConfig(mode=mode),
        )
        service.start(warmup=False)
        frame = np.zeros(uplift_frame_hw, np.float32)
        try:
            interval = 1.0 / offered_hz
            n = int(uplift_seconds * offered_hz)
            start = time.monotonic()
            for i in range(n):
                target = start + i * interval
                now = time.monotonic()
                if target > now:
                    time.sleep(target - now)
                connector.inject(FRAME_TOPIC, {"frame": frame,
                                               "meta": {"seq": i}})
            service.drain(timeout=30.0)
        finally:
            service.stop()
        c = metrics.counters()
        processed = max(1.0, c.get("frames_processed", 0.0))
        return {
            "offered": n,
            "completed": int(c.get("frames_completed", 0.0)),
            "bytes_per_frame": round(
                c.get("ingest_upload_bytes", 0.0) / processed, 1),
            "staging_allocs": int(c.get("ingest_staging_allocs", 0.0)),
            "staging_preallocated": service.ingest.staging.preallocated,
            "ledger_in_system_after_drain": service.ledger()["in_system"],
        }

    uplift = {}
    uplift_ok = True
    fh, fw = uplift_frame_hw
    for batch in uplift_batches:
        # Saturate BOTH modes (offered = overdrive x the uint8 arm's own
        # capacity against the transfer wall), so each serves full
        # batches and bytes/frame compares staging dtypes, not batch
        # occupancy — the f32 arm is then deep in overload, which is
        # exactly the regime the 118 ms p99 pathology lived in.
        u8_batch_s = 0.002 + batch * fh * fw / (uplift_h2d_gb_s * 1e9)
        offered_hz = uplift_overdrive * batch / u8_batch_s
        f32_row = _drive_uplift("f32", batch, offered_hz)
        u8_row = _drive_uplift("uint8", batch, offered_hz)
        ratio = (u8_row["completed"] / f32_row["completed"]
                 if f32_row["completed"] else None)
        bytes_ratio = (f32_row["bytes_per_frame"] / u8_row["bytes_per_frame"]
                       if u8_row["bytes_per_frame"] else None)
        zero_allocs = (
            u8_row["staging_allocs"] == u8_row["staging_preallocated"]
            and f32_row["staging_allocs"] == f32_row["staging_preallocated"])
        row = {
            "offered_hz": round(offered_hz, 1),
            "f32": f32_row, "uint8": u8_row,
            "uplift": round(ratio, 3) if ratio else None,
            "bytes_ratio": round(bytes_ratio, 2) if bytes_ratio else None,
            "zero_steady_state_allocs": zero_allocs,
        }
        uplift[f"b{batch}"] = row
        if batch == 32:
            uplift_ok = (uplift_ok and ratio is not None and ratio >= 1.15
                         and bytes_ratio is not None and bytes_ratio >= 3.5)
        uplift_ok = uplift_ok and zero_allocs
        print(json.dumps({"ingest_uplift_batch": batch,
                          "uplift": row["uplift"],
                          "bytes_ratio": row["bytes_ratio"]}),
              file=sys.stderr)

    # -- jpeg intake sanity --
    from opencv_facerecognizer_tpu.runtime.ingest import jpeg_supported

    if not jpeg_supported():
        # No codec on this install (pyproject declares neither PIL nor
        # cv2): the arm is unmeasurable, not failed — mirror the test
        # suite's skipif so the other gates still produce a verdict.
        jpeg = {"skipped": "no JPEG codec (PIL/cv2) on this install"}
        jpeg_ok = True
    else:
        metrics = Metrics()
        pipeline = InstantPipeline(frame_hw, dispatch_s=0.002)
        connector = FakeConnector()
        service = RecognizerService(
            pipeline, connector, batch_size=8, frame_shape=frame_hw,
            flush_timeout=0.02, inflight_depth=4, similarity_threshold=0.0,
            metrics=metrics, ingest=IngestConfig(mode="jpeg"),
        )
        service.start(warmup=False)
        try:
            for i, (payload, _src) in enumerate(
                    synthetic_jpeg_frames(jpeg_frames, frame_hw, seed=11)):
                connector.inject(FRAME_TOPIC, {**encode_jpeg_message(payload),
                                               "meta": {"seq": i}})
                time.sleep(0.002)
            service.drain(timeout=30.0)
        finally:
            service.stop()
        c = metrics.counters()
        jpeg = {
            "offered": jpeg_frames,
            "completed": int(c.get("frames_completed", 0.0)),
            "decoded": int(c.get("decode_frames", 0.0)),
            "decode_p50_ms": metrics.summary().get("decode_latency_p50_ms"),
            "staging_allocs": int(c.get("ingest_staging_allocs", 0.0)),
            "staging_preallocated": service.ingest.staging.preallocated,
        }
        jpeg_ok = (jpeg["completed"] == jpeg_frames
                   and jpeg["staging_allocs"] == jpeg["staging_preallocated"])

    return {
        "note": ("ingest-pipeline gate: (1) h2d — staging+transfer "
                 "latency per rung for the legacy fresh-f32 path, the old "
                 "unpinned uint8 path, and the new pre-allocated recycled "
                 "StagingRing uint8 path; the ring arm's p99 must sit "
                 "within 3x its p50 (+slack) at every rung, taken over "
                 "the min-p99 trial (scheduler noise is additive — see "
                 "trial_p99_ms for the spread). (2) uplift — "
                 "completed frames through a transfer-bound fake backend "
                 "(h2d_gb_s sleeps out each batch's actual bytes): uint8 "
                 "mode must complete >= 1.15x f32 at b32 with >= 3.5x "
                 "fewer bytes/frame and zero steady-state staging "
                 "allocations. (3) jpeg — compressed payloads decoded off "
                 "the hot thread: every offered frame completes."),
        "config": {"rungs": list(rungs), "frame": list(frame_hw),
                   "h2d_iters": h2d_iters, "h2d_trials": h2d_trials,
                   "p99_slack_ms": p99_slack_ms,
                   "uplift": {"batches": list(uplift_batches),
                              "frame": list(uplift_frame_hw),
                              "h2d_gb_s": uplift_h2d_gb_s,
                              "overdrive": uplift_overdrive,
                              "seconds": uplift_seconds},
                   "jpeg_frames": jpeg_frames},
        "h2d": h2d,
        "h2d_ok": h2d_ok,
        "uplift": uplift,
        "uplift_ok": uplift_ok,
        "jpeg": jpeg,
        "jpeg_ok": jpeg_ok,
        "ingest_ok": bool(h2d_ok and uplift_ok and jpeg_ok),
    }


def run_cascade_smoke(densities=(0.0, 0.3, 0.7), seconds=1.5, batch_size=8,
                      frame_hw=(32, 32), dispatch_s=0.001,
                      dispatch_per_frame_s=0.002, cascade_score_s=0.001,
                      overdrive=4.0, uplift_gate_d0=2.0,
                      uplift_gate_d30=1.3, recall=True,
                      recall_min=0.99, recall_train_scenes=128,
                      recall_held_scenes=64, recall_gate_steps=400,
                      recall_detector_steps=250, watchdog_seconds=0.6):
    """The cascade early-exit gate (ISSUE 13): four deterministic arms.

    **uplift** — completed-frames (completed + completed_empty: every
    admitted frame still gets a result publish) at each face density,
    cascade on vs off, against a per-frame capacity wall
    (``InstantPipeline(dispatch_per_frame_s=...)``: the fake's dispatch
    cost scales with the bucket it carries, the way BENCH_DETAIL says
    detect does on the chip). The brightness-stub cascade is a
    deterministic oracle on ``synthetic_frame_stream``'s stamped blobs,
    so the measured uplift isolates the SERVING MECHANISM — early exit,
    survivor compaction into the bucket ladder, completed_empty
    settlement — from model quality. Gates: >= ``uplift_gate_d0``x at
    0% density, >= ``uplift_gate_d30``x at 30%, exact ledger settlement
    (in_system == 0 after drain) in every cell.

    **recall** — the model-quality half: a real ``FaceGate`` + full
    ``CNNFaceDetector`` trained on the shared synthetic scenes; stage-1
    recall vs the detector's own verdicts on held-out scenes must be
    >= ``recall_min`` at the default threshold (``evaluate_gate``: a
    frame stage 2 cannot detect a face in is not a cascade loss).

    **watchdog** — cascade on/off x ingest f32/uint8: every combination
    prewarms both stages across the ladder at its staging dtype and must
    serve with ZERO post-warmup recompiles.

    **reject_all** — the ``cascade: reject-all`` chaos fault: a
    pathological stage 1 (every frame scored face-free) must degrade to
    zero matches with exact ``completed_empty`` settlement — no wedge,
    no leaked frames, drain() still converges.
    """
    from opencv_facerecognizer_tpu.runtime.admission import (
        AdmissionController,
    )
    from opencv_facerecognizer_tpu.runtime.connector import FakeConnector
    from opencv_facerecognizer_tpu.runtime.fakes import (
        InstantPipeline, TrafficRecorder, synthetic_frame_stream,
    )
    from opencv_facerecognizer_tpu.runtime.recognizer import (
        RecognizerService,
    )
    from opencv_facerecognizer_tpu.utils.metrics import Metrics

    # One fixed offered load for every uplift cell: overdrive x the
    # NO-CASCADE configuration's capacity wall, so on/off rows compare
    # completions against the same pressure.
    base_batch_s = dispatch_s + batch_size * dispatch_per_frame_s
    capacity_fps = batch_size / base_batch_s
    offered_hz = overdrive * capacity_fps

    def _drive(density, cascade_on, run_seconds, ingest_mode=None,
               faults=None, hz=None):
        metrics = Metrics()
        pipeline = InstantPipeline(
            frame_hw, dispatch_s=dispatch_s,
            dispatch_per_frame_s=dispatch_per_frame_s,
            cascade_stub=cascade_on, cascade_score_s=cascade_score_s,
            faces_per_frame=1)
        kwargs = {}
        if ingest_mode is not None:
            from opencv_facerecognizer_tpu.runtime.ingest import IngestConfig

            kwargs["ingest"] = IngestConfig(mode=ingest_mode)
        connector = FakeConnector()
        service = RecognizerService(
            pipeline, connector, batch_size=batch_size,
            frame_shape=frame_hw, flush_timeout=0.02, inflight_depth=4,
            similarity_threshold=0.0, metrics=metrics,
            fault_injector=faults,
            admission=AdmissionController(
                max_inflight_frames=4 * batch_size),
            shed_stale_after_s=0.5,
            bucket_sizes=(max(1, batch_size // 4),
                          max(1, batch_size // 2), batch_size),
            **kwargs)
        # Warmup without real compiles: mark every (rung, staging dtype)
        # signature — BOTH stages — compiled, then arm the watchdog (the
        # same contract service.warmup() provides over a real pipeline).
        pipeline.prewarm_batch_shapes(service._bucket_ladder, frame_hw,
                                     service.batcher.dtype)
        service._warmed = True
        recorder = TrafficRecorder(connector)
        service.start(warmup=False)
        stream = synthetic_frame_stream(512, frame_hw, density, seed=5)
        rate = offered_hz if hz is None else hz
        try:
            interval = 1.0 / rate
            n = int(run_seconds * rate)
            start = time.monotonic()
            for i in range(n):
                target = start + i * interval
                now = time.monotonic()
                if target > now:
                    time.sleep(target - now)
                frame, _k = stream[i % len(stream)]
                recorder.offer(connector, {"frame": frame}, i,
                               "interactive")
            service.drain(timeout=30.0)
        finally:
            service.stop()
        ledger = service.ledger()
        c = metrics.counters()
        return {
            "offered": n,
            "completed": int(ledger["completed"]),
            "completed_empty": int(ledger["completed_empty"]),
            "completed_total": int(ledger["completed"]
                                   + ledger["completed_empty"]),
            "cascade_batch_exits": int(c.get("cascade_batch_exits", 0.0)),
            "recompiles_post_warmup": int(
                c.get("recompiles_post_warmup", 0.0)),
            "ledger_in_system_after_drain": ledger["in_system"],
            "faces_found": int(c.get("faces_found", 0.0)),
        }

    uplift = {}
    uplift_ok = True
    ledger_ok = True
    for density in densities:
        off_row = _drive(density, cascade_on=False, run_seconds=seconds)
        on_row = _drive(density, cascade_on=True, run_seconds=seconds)
        ratio = (on_row["completed_total"] / off_row["completed_total"]
                 if off_row["completed_total"] else None)
        row = {
            "offered_hz": round(offered_hz, 1),
            "cascade_off": off_row,
            "cascade_on": on_row,
            # ``is not None``, not truthiness: a measured 0.0 uplift is a
            # real (catastrophic) value the gates below must see, never a
            # missing measurement.
            "uplift": round(ratio, 3) if ratio is not None else None,
        }
        ledger_ok = (ledger_ok
                     and off_row["ledger_in_system_after_drain"] == 0
                     and on_row["ledger_in_system_after_drain"] == 0)
        key = f"d{int(round(density * 100))}"
        uplift[key] = row
        print(json.dumps({"cascade_density": density,
                          "uplift": row["uplift"]}), file=sys.stderr)
    # Both gates FAIL CLOSED: a swept density whose uplift could not be
    # measured (or measured 0.0) is a failure, never a skip. Only a
    # density that was not swept at all (no row) bypasses its gate.
    d0 = uplift.get("d0", {}).get("uplift")
    d30_row = uplift.get("d30")
    d30 = d30_row.get("uplift") if d30_row else None
    uplift_ok = (d0 is not None and d0 >= uplift_gate_d0
                 and (d30_row is None
                      or (d30 is not None and d30 >= uplift_gate_d30))
                 and ledger_ok)

    # -- recall: the real two-stage pair on shared synthetic scenes --
    if recall:
        from opencv_facerecognizer_tpu.models.cascade import (
            FaceGate, evaluate_gate,
        )
        from opencv_facerecognizer_tpu.models.detector import (
            CNNFaceDetector,
        )
        from opencv_facerecognizer_tpu.utils.dataset import (
            make_synthetic_scenes,
        )

        scenes, boxes, counts = make_synthetic_scenes(
            recall_train_scenes, (96, 96), max_faces=2, seed=3)
        detector = CNNFaceDetector(features=(8, 16, 32), head_features=32,
                                   max_faces=4, score_threshold=0.25)
        detector.train(scenes, boxes, counts,
                       steps=recall_detector_steps, batch_size=16,
                       learning_rate=2e-3)
        gate = FaceGate()
        gate.train(scenes, boxes, counts, steps=recall_gate_steps,
                   batch_size=32)
        held, _hb, held_counts = make_synthetic_scenes(
            recall_held_scenes, (96, 96), max_faces=2, seed=99)
        # gt_counts: recall is measured over stage-2-detectable FACE
        # frames — a detector false positive on a background frame is
        # not a face the cascade can lose (its suppression is reported
        # as detector_fp_suppressed, a precision win).
        recall_row = evaluate_gate(gate, detector, held,
                                   gt_counts=held_counts)
        recall_row["recall_ok"] = bool(
            recall_row["stage1_recall"] >= recall_min)
        print(json.dumps({"cascade_recall": recall_row}), file=sys.stderr)
    else:
        recall_row = {"skipped": "recall arm disabled for this run",
                      "recall_ok": True}

    # -- watchdog: cascade on/off x ingest modes, zero recompiles --
    watchdog = {}
    watchdog_ok = True
    for ingest_mode in ("f32", "uint8"):
        for cascade_on in (True, False):
            key = f"{ingest_mode}_cascade_{'on' if cascade_on else 'off'}"
            row = _drive(0.3, cascade_on, watchdog_seconds,
                         ingest_mode=ingest_mode,
                         hz=min(offered_hz, 2.0 * capacity_fps))
            watchdog[key] = {
                "recompiles_post_warmup": row["recompiles_post_warmup"],
                "completed_total": row["completed_total"],
                "ledger_in_system_after_drain":
                    row["ledger_in_system_after_drain"],
            }
            watchdog_ok = (watchdog_ok
                           and row["recompiles_post_warmup"] == 0
                           and row["ledger_in_system_after_drain"] == 0)

    # -- reject_all: the pathological stage 1, chaos-injected --
    from opencv_facerecognizer_tpu.runtime.faults import FaultInjector

    injector = FaultInjector(seed=7,
                             rates={"cascade": {"reject_all": 1.0}})
    reject_row = _drive(0.7, cascade_on=True, run_seconds=seconds,
                        faults=injector, hz=capacity_fps)
    reject_row["injected"] = injector.summary()
    reject_ok = (reject_row["completed"] == 0
                 and reject_row["faces_found"] == 0
                 and reject_row["completed_empty"] > 0
                 and reject_row["ledger_in_system_after_drain"] == 0)
    reject_row["reject_all_ok"] = reject_ok
    print(json.dumps({"cascade_reject_all": reject_row}), file=sys.stderr)

    return {
        "note": ("cascade early-exit gate: (1) uplift — completed frames "
                 "(incl. completed_empty results) at 0/30/70% face "
                 "density, cascade on vs off, against a per-frame "
                 "dispatch wall; gates >= "
                 f"{uplift_gate_d0}x at 0% and >= {uplift_gate_d30}x at "
                 "30% with exact ledger settlement. (2) recall — a real "
                 "FaceGate vs the full CNNFaceDetector's own verdicts on "
                 f"held-out scenes: stage-1 recall >= {recall_min} at "
                 "the default threshold. (3) watchdog — cascade on/off x "
                 "ingest f32/uint8 all serve with zero post-warmup "
                 "recompiles. (4) reject_all — the cascade:reject-all "
                 "chaos fault degrades to zero matches with exact "
                 "completed_empty settlement, no wedge."),
        "config": {"densities": list(densities), "seconds": seconds,
                   "batch_size": batch_size, "frame": list(frame_hw),
                   "dispatch_s": dispatch_s,
                   "dispatch_per_frame_s": dispatch_per_frame_s,
                   "cascade_score_s": cascade_score_s,
                   "capacity_fps": round(capacity_fps, 1),
                   "offered_hz": round(offered_hz, 1),
                   "overdrive": overdrive},
        "uplift": uplift,
        "uplift_ok": bool(uplift_ok),
        "recall": recall_row,
        "watchdog": watchdog,
        "watchdog_ok": bool(watchdog_ok),
        "reject_all": reject_row,
        "cascade_ok": bool(uplift_ok and recall_row.get("recall_ok")
                           and watchdog_ok and reject_ok),
    }


def run_video_smoke(coherences=(0.9, 0.5, 0.0), rounds=140, streams=8,
                    frame_hw=(64, 64), dispatch_s=0.001,
                    dispatch_per_frame_s=0.002, flush_timeout=0.002,
                    reverify_frames=8, warmup_rounds=10,
                    uplift_gate_c90=2.0, uplift_gate_c50=1.2,
                    p99_slack=1.5, stack_density=0.7):
    """The temporal-identity-cache gate (ISSUE 17): closed-loop video
    rounds, cache on vs off, against the per-frame dispatch wall.

    Each round offers ONE frame per camera stream (``streams`` frames)
    and drains before the next — the per-stream cadence of real video,
    where a 30 fps camera's frame interval comfortably exceeds the
    pipeline latency, so every frame's full-path result lands before
    that stream's next frame arrives. This keeps the measurement
    deterministic AND honest: overdriving with admission shedding would
    decimate each stream's motion chain (dropped frames break the very
    coherence being measured), turning the knob under test into an
    artifact of the load pattern.

    **uplift** — wall-clock to complete the post-warmup rounds, cache
    on vs off, at each coherence. The wall is per-frame
    (``dispatch_per_frame_s``), so a cached frame — settled
    ``completed_cached`` without dispatch — buys real capacity exactly
    like the cascade's compaction. Gates: >= ``uplift_gate_c90``x at
    coherence 0.9, >= ``uplift_gate_c50``x at 0.5; 0.0 (shuffled
    stills: nothing to associate) is reported, not gated.

    **latency** — interactive e2e p99 cache-on must stay within
    ``p99_slack``x of cache-off at every coherence (the lookup is host
    work on the dispatch thread; it must never cost the latency SLO).

    **watchdog** — zero post-warmup recompiles cache-on: survivor
    compaction lands on prewarmed ladder rungs, never a fresh shape.

    **ledger** — ``admitted == completed + completed_empty +
    completed_cached + drops`` with ``in_system == 0`` in EVERY arm.

    **cascade stacking** — one cell at ``stack_density`` face density
    with BOTH gates armed: face-free frames exit at stage 1
    (``completed_empty``), coherent faced frames exit at stage 0
    (``completed_cached``), and the extended ledger still settles
    exactly.
    """
    from opencv_facerecognizer_tpu.runtime.connector import FakeConnector
    from opencv_facerecognizer_tpu.runtime.fakes import (
        InstantPipeline, TrafficRecorder, synthetic_video_stream,
    )
    from opencv_facerecognizer_tpu.runtime.recognizer import (
        RecognizerService,
    )
    from opencv_facerecognizer_tpu.runtime.tracker import (
        IdentityTracker, TrackerConfig,
    )
    from opencv_facerecognizer_tpu.utils.metrics import Metrics

    batch_size = streams

    def _drive(coherence, cache_on, face_density=1.0):
        metrics = Metrics()
        pipeline = InstantPipeline(
            frame_hw, dispatch_s=dispatch_s,
            dispatch_per_frame_s=dispatch_per_frame_s,
            cascade_stub=True, video_oracle=True)
        connector = FakeConnector()
        tracker = None
        if cache_on:
            tracker = IdentityTracker(
                TrackerConfig(reverify_frames=reverify_frames),
                metrics=metrics)
        service = RecognizerService(
            pipeline, connector, batch_size=batch_size,
            frame_shape=frame_hw, flush_timeout=flush_timeout,
            inflight_depth=2, similarity_threshold=0.0, metrics=metrics,
            subject_names=["id0", "id1", "id2", "id3"],
            bucket_sizes=(max(1, batch_size // 4),
                          max(1, batch_size // 2), batch_size),
            cascade=True, tracker=tracker)
        pipeline.prewarm_batch_shapes(service._bucket_ladder, frame_hw,
                                      service.batcher.dtype)
        service._warmed = True
        recorder = TrafficRecorder(connector)
        service.start(warmup=False)
        stream = synthetic_video_stream(
            rounds * streams, frame_hw, streams=streams,
            coherence=coherence, face_density=face_density, seed=11)
        measured = []
        elapsed = 0.0
        try:
            for r in range(rounds):
                t0 = time.monotonic()
                for s in range(streams):
                    seq = r * streams + s
                    frame, key, _k = stream[seq]
                    recorder.offer(connector, {"frame": frame}, seq,
                                   "interactive",
                                   meta_extra={"stream": key})
                if not service.drain(timeout=10.0):
                    break
                if r >= warmup_rounds:
                    elapsed += time.monotonic() - t0
                    measured.extend(range(r * streams,
                                          (r + 1) * streams))
        finally:
            service.stop()
        ledger = service.ledger()
        c = metrics.counters()
        drops = sum(ledger["drops_by_reason"].values())
        settled = (ledger["completed"] + ledger["completed_empty"]
                   + ledger["completed_cached"] + drops)
        return {
            "offered": rounds * streams,
            "measured_frames": len(measured),
            "elapsed_s": round(elapsed, 4),
            "throughput_fps": (round(len(measured) / elapsed, 1)
                               if elapsed else None),
            "completed": int(ledger["completed"]),
            "completed_empty": int(ledger["completed_empty"]),
            "completed_cached": int(ledger["completed_cached"]),
            # hits/lookups from the counters (the hit-rate metric proper
            # is a /prom gauge, invisible to counters()).
            "cache_hit_rate": round(
                float(c.get("track_cache_hits", 0.0))
                / max(1.0, float(c.get("track_lookups", 0.0))), 3),
            "track_reverifies": int(c.get("track_reverifies", 0.0)),
            "track_batch_exits": int(c.get("track_batch_exits", 0.0)),
            "recompiles_post_warmup": int(
                c.get("recompiles_post_warmup", 0.0)),
            "interactive_p99_ms": round(
                recorder.percentile_ms(measured, 99), 2),
            "ledger_exact": bool(ledger["admitted"] == settled),
            "ledger_in_system_after_drain": ledger["in_system"],
        }

    cells = {}
    uplift_ok = True
    ledger_ok = True
    p99_ok = True
    watchdog_ok = True
    for coherence in coherences:
        off_row = _drive(coherence, cache_on=False)
        on_row = _drive(coherence, cache_on=True)
        ratio = None
        if off_row["throughput_fps"] and on_row["throughput_fps"]:
            ratio = round(on_row["throughput_fps"]
                          / off_row["throughput_fps"], 3)
        ledger_ok = (ledger_ok and off_row["ledger_exact"]
                     and on_row["ledger_exact"]
                     and off_row["ledger_in_system_after_drain"] == 0
                     and on_row["ledger_in_system_after_drain"] == 0)
        # NaN-safe latency gate: a NaN p99 (nothing completed in the
        # window) must FAIL, so the comparison is written to pass only
        # when both sides are real numbers within the slack.
        p99_ok = (p99_ok
                  and on_row["interactive_p99_ms"]
                  <= p99_slack * off_row["interactive_p99_ms"])
        watchdog_ok = (watchdog_ok
                       and on_row["recompiles_post_warmup"] == 0)
        key = f"c{int(round(coherence * 100))}"
        cells[key] = {"cache_off": off_row, "cache_on": on_row,
                      "uplift": ratio}
        print(json.dumps({"video_coherence": coherence,
                          "uplift": ratio,
                          "hit_rate": on_row["cache_hit_rate"]}),
              file=sys.stderr)
    # Both uplift gates FAIL CLOSED: an unmeasurable swept cell (None)
    # fails; only a coherence not swept at all bypasses its gate.
    c90 = cells.get("c90", {}).get("uplift")
    c50_row = cells.get("c50")
    c50 = c50_row.get("uplift") if c50_row else None
    uplift_ok = (c90 is not None and c90 >= uplift_gate_c90
                 and (c50_row is None
                      or (c50 is not None and c50 >= uplift_gate_c50)))

    # -- cascade stacking: both early exits live in one arm --
    stack = _drive(0.9, cache_on=True, face_density=stack_density)
    stack_ok = (stack["ledger_exact"]
                and stack["ledger_in_system_after_drain"] == 0
                and stack["completed_cached"] > 0
                and stack["completed_empty"] > 0)
    stack["stacking_ok"] = bool(stack_ok)
    print(json.dumps({"video_stacking": stack}), file=sys.stderr)

    return {
        "note": ("temporal identity cache gate: closed-loop video "
                 "rounds (one frame per stream per round, drained) "
                 "against a per-frame dispatch wall. Gates: "
                 f">= {uplift_gate_c90}x completed-frames uplift at "
                 f"coherence 0.9, >= {uplift_gate_c50}x at 0.5 "
                 "(0.0 reported), interactive p99 cache-on within "
                 f"{p99_slack}x of cache-off, zero post-warmup "
                 "recompiles cache-on, and the extended ledger "
                 "(admitted == completed + completed_empty + "
                 "completed_cached + drops) exact in every arm, "
                 "including the cascade-stacking cell."),
        "config": {"coherences": list(coherences), "rounds": rounds,
                   "streams": streams, "frame": list(frame_hw),
                   "dispatch_s": dispatch_s,
                   "dispatch_per_frame_s": dispatch_per_frame_s,
                   "flush_timeout": flush_timeout,
                   "reverify_frames": reverify_frames,
                   "warmup_rounds": warmup_rounds},
        "cells": cells,
        "stacking": stack,
        "uplift_ok": bool(uplift_ok),
        "ledger_ok": bool(ledger_ok),
        "p99_ok": bool(p99_ok),
        "watchdog_ok": bool(watchdog_ok),
        "video_ok": bool(uplift_ok and ledger_ok and p99_ok
                         and watchdog_ok and stack_ok),
    }


def run_overload_sweep(multipliers=(1.0, 2.0, 4.0), seconds=3.0,
                       batch_size=8, frame_hw=(32, 32), dispatch_s=0.04):
    """Offered-load ladder against a capacity-limited fake backend
    (``InstantPipeline(dispatch_s=...)``: hard capacity = batch_size /
    dispatch_s frames/s) with the full overload-protection stack armed —
    admission bound, priority shedding, brownout, stale drops. Per
    multiplier: interactive vs bulk completion, explicit sheds by reason,
    interactive e2e percentiles, and the admission-ledger remainder
    (must be 0 after the drain). Deterministic: no randomness, no
    hardware — the overload-sweep section of BENCH_SERVING_smoke.json."""
    from opencv_facerecognizer_tpu.runtime.fakes import build_overload_stack
    from opencv_facerecognizer_tpu.runtime.recognizer import (
        FRAME_TOPIC, RESULT_TOPIC, STATUS_TOPIC,
    )

    capacity_fps = batch_size / dispatch_s
    frame = np.zeros(frame_hw, np.float32)
    rows = []
    for mult in multipliers:
        # The canonical overload harness — shared with chaos_soak's
        # --scenario overload, so this sweep and the soak's pass criteria
        # describe the exact same configuration.
        pipeline, service, connector = build_overload_stack(
            frame_shape=frame_hw, batch_size=batch_size,
            dispatch_s=dispatch_s)
        send_t, done_t = {}, {}
        lock = threading.Lock()

        def on_result(topic, message, done_t=done_t, lock=lock):
            seq = (message.get("meta") or {}).get("seq")
            if seq is not None:
                with lock:
                    done_t.setdefault(seq, time.monotonic())

        connector.subscribe(RESULT_TOPIC, on_result)
        max_brownout = {"level": 0}
        connector.subscribe(
            STATUS_TOPIC,
            lambda t, m: max_brownout.__setitem__(
                "level", max(max_brownout["level"], m.get("level", 0)))
            if m.get("status") == "brownout" else None)
        service.start(warmup=False)
        interactive, bulk = [], []
        try:
            interval = 1.0 / (mult * capacity_fps)
            end = time.monotonic() + seconds
            seq = 0
            while time.monotonic() < end:
                pri = "interactive" if seq % 5 == 0 else "bulk"
                send_t[seq] = time.monotonic()
                connector.inject(FRAME_TOPIC, {
                    "frame": frame, "priority": pri,
                    "meta": {"seq": seq, "pri": pri}})
                (interactive if pri == "interactive" else bulk).append(seq)
                seq += 1
                time.sleep(interval)
            service.drain(timeout=30.0)
        finally:
            service.stop()
        lat_i = np.asarray([done_t[s] - send_t[s]
                            for s in interactive if s in done_t])
        ledger = service.ledger()
        row = {
            "offered_multiplier": mult,
            "offered_hz": round(mult * capacity_fps, 1),
            "interactive_offered": len(interactive),
            "interactive_completed": int(len(lat_i)),
            "bulk_offered": len(bulk),
            "bulk_completed": sum(1 for s in bulk if s in done_t),
            "rejected": {k: int(v) for k, v in service.metrics
                         .counters_with_prefix("frames_rejected_").items()},
            "drops_by_reason": {k: int(v)
                                for k, v in ledger["drops_by_reason"].items()},
            "max_brownout_level": max_brownout["level"],
            "ledger_in_system_after_drain": ledger["in_system"],
        }
        if len(lat_i):
            row["interactive_e2e_p50_ms"] = round(
                float(np.percentile(lat_i, 50)) * 1e3, 1)
            row["interactive_e2e_p99_ms"] = round(
                float(np.percentile(lat_i, 99)) * 1e3, 1)
        rows.append(row)
        print(json.dumps(row), file=sys.stderr)
    return {
        "note": ("offered-load ladder vs a deterministic capacity wall "
                 f"({capacity_fps:g} frames/s: InstantPipeline dispatch_s="
                 f"{dispatch_s:g}, batch {batch_size}) with admission bound "
                 "24, brownout at 50 ms queue-wait EWMA, stale shed at "
                 "250 ms. Above 1x, bulk is shed with explicit reasons "
                 "while interactive completion and latency hold; the "
                 "admission ledger remainder is 0 after every drain."),
        "config": {"batch_size": batch_size, "dispatch_s": dispatch_s,
                   "capacity_fps": capacity_fps, "seconds": seconds},
        "rows": rows,
    }


def run_replica_scaleout(replica_counts=(1, 2, 4), seconds=3.0,
                         batch_size=8, frame_hw=(32, 32), dispatch_s=0.04,
                         topics=48, offered_factor=4.0):
    """In-process replica scale-out ladder (the horizontal-scale-out
    analogue of the overload sweep): N serving replicas — each the
    canonical capacity-walled overload stack (``batch_size / dispatch_s``
    frames/s) — behind the rendezvous ``TopicRouter``
    (``runtime.fakes.build_replica_fleet``), driven at one FIXED offered
    load of ``offered_factor`` x a single replica's capacity spread over
    ``topics`` camera topics. One replica saturates; more replicas split
    the topics and the completed-frame count scales until the offered
    load itself is the ceiling. Deterministic: the rendezvous split is a
    pure hash of (topic, replica name), and the capacity wall is a
    scripted sleep, not real compute.

    ``scaling.x2`` (completed at 2 replicas / completed at 1) is the
    acceptance number: >= 1.6x proves the router + fleet actually spread
    load (ideal is ~2.0 — the hash split over 48 topics is 23/25).
    ``scaling_2x_ok`` gates the smoke's exit code;
    ``scripts/bench_compare.py`` tracks the ratio across artifacts."""
    from opencv_facerecognizer_tpu.runtime.fakes import (
        TrafficRecorder, build_replica_fleet,
    )
    from opencv_facerecognizer_tpu.utils.metrics import Metrics

    capacity_fps = batch_size / dispatch_s
    offered_hz = offered_factor * capacity_fps
    frame = np.zeros(frame_hw, np.float32)
    rows = []
    completed_by_n = {}
    for n in replica_counts:
        router, stacks = build_replica_fleet(
            n, frame_shape=frame_hw, batch_size=batch_size,
            dispatch_s=dispatch_s, router_metrics=Metrics())
        # The shared seq-stamped recorder (runtime.fakes.TrafficRecorder,
        # subscribed on the ROUTER so results from every replica fan in)
        # — the replication chaos scenario measures through the same
        # code, so the bench rows and the soak's criteria agree.
        recorder = TrafficRecorder(router)
        for _pipe, service, _conn, _metrics in stacks:
            service.start(warmup=False)
        router.start()
        try:
            n_frames = int(seconds * offered_hz)
            interval = 1.0 / offered_hz
            start = time.monotonic()
            for seq in range(n_frames):
                target = start + seq * interval
                now = time.monotonic()
                if target > now:
                    time.sleep(target - now)
                recorder.send_t[seq] = time.monotonic()
                router.publish(f"camera/{seq % topics}",
                               {"frame": frame, "meta": {"seq": seq}})
            for _pipe, service, _conn, _metrics in stacks:
                service.drain(timeout=30.0)
        finally:
            router.stop()
            for _pipe, service, _conn, _metrics in stacks:
                service.stop()
        lat = np.asarray(recorder.latencies(range(n_frames)))
        per_replica = []
        ledger_remainder = 0.0
        for _pipe, service, _conn, metrics in stacks:
            ledger = service.ledger()
            ledger_remainder += abs(ledger["in_system"])
            per_replica.append({
                "completed": int(ledger["completed"]),
                "admitted": int(ledger["admitted"]),
                "rejected": {k: int(v) for k, v in metrics
                             .counters_with_prefix("frames_rejected_")
                             .items()},
            })
        completed_by_n[n] = len(lat)
        row = {
            "replicas": n,
            "offered_hz": round(offered_hz, 1),
            "offered_frames": n_frames,
            "completed_frames": int(len(lat)),
            "completed_hz": round(len(lat) / seconds, 1),
            "per_replica": per_replica,
            "ledger_remainder_after_drain": ledger_remainder,
        }
        if len(lat):
            row["e2e_p50_ms"] = round(float(np.percentile(lat, 50)) * 1e3, 1)
            row["e2e_p99_ms"] = round(float(np.percentile(lat, 99)) * 1e3, 1)
        rows.append(row)
        print(json.dumps(row), file=sys.stderr)
    scaling = {}
    base = completed_by_n.get(replica_counts[0], 0)
    for n in replica_counts[1:]:
        if base:
            scaling[f"x{n}"] = round(completed_by_n[n] / base, 3)
    return {
        "note": (f"fixed offered load ({offered_factor:g}x one replica's "
                 f"{capacity_fps:g} frames/s capacity wall) over {topics} "
                 "camera topics, rendezvous-routed across N in-process "
                 "replicas (each the canonical overload stack). Completed "
                 "frames scale with N until the offered load is the "
                 "ceiling; p99 reflects per-replica admission keeping "
                 "queues shallow."),
        "config": {"batch_size": batch_size, "dispatch_s": dispatch_s,
                   "capacity_fps": capacity_fps, "offered_hz": offered_hz,
                   "topics": topics, "seconds": seconds},
        "rows": rows,
        "scaling": scaling,
        "scaling_2x_ok": bool(scaling.get("x2", 0.0) >= 1.6),
    }


def run_rollout_smoke(seconds: float = 2.0, batch_size: int = 8,
                      frame_hw=(32, 32), dispatch_s: float = 0.01,
                      topics: int = 12, offered_hz: float = 60.0,
                      n_rows: int = 24, seed: int = 7):
    """Live embedder-rollout smoke (ISSUE 11): a writer + 2 WAL-tailing
    read replicas behind the rendezvous router serve steady traffic while
    the writer runs a full rollout — staged re-embed, dual-score parity
    window, WAL-fenced atomic cutover, replica re-anchor through the
    router cordon. Two load-bearing numbers come out:

    - ``parity_agreement``: the dual-score window's old-vs-new top-1
      identity agreement on identity queries (the gate the cutover is
      allowed through — a fine-tune that actually changes identities
      shows up here first);
    - ``cutover_window_completed_ratio``: completed-frames/s through the
      cutover + re-anchor window over the steady-state rate — the
      serving-never-blanks number (1.0 = the fleet absorbed the rollout
      invisibly; the router cordon + epoch-fenced swap are what keep it
      there).

    Deterministic: InstantPipeline capacity walls, a seeded rotation as
    the "new embedder", synchronous phases. ``scripts/bench_compare.py``
    tracks both numbers across artifacts (baseline-predates skip for
    older files)."""
    import shutil
    import tempfile

    from opencv_facerecognizer_tpu.parallel import ShardedGallery, make_mesh
    from opencv_facerecognizer_tpu.runtime import (
        FakeConnector, ReadReplica, RecognizerService, ReplicaHandle,
        ResiliencePolicy, RolloutCoordinator, StateLifecycle, TopicRouter,
        WriterLease,
    )
    from opencv_facerecognizer_tpu.runtime.connector import encode_frame
    from opencv_facerecognizer_tpu.runtime.fakes import (
        InstantPipeline, TrafficRecorder,
    )
    from opencv_facerecognizer_tpu.runtime.replication import (
        service_health_probe,
    )
    from opencv_facerecognizer_tpu.utils.metrics import Metrics

    DIM = 8
    rng = np.random.default_rng(seed)
    mesh = make_mesh()
    state_dir = tempfile.mkdtemp(prefix="ocvf_rollout_bench_")
    Q, _ = np.linalg.qr(rng.normal(size=(DIM, DIM)))
    Q = Q.astype(np.float32)

    def old_embed(crops):
        return np.asarray(crops, np.float32).reshape(len(crops), -1)[:, :DIM]

    def new_embed(crops):
        return old_embed(crops) @ Q

    writer_metrics = Metrics()
    lease = WriterLease(state_dir, metrics=writer_metrics).acquire()
    gallery = ShardedGallery(capacity=256, dim=DIM, mesh=mesh)
    names = []
    state = StateLifecycle(state_dir, metrics=writer_metrics,
                           checkpoint_wal_rows=1 << 30,
                           checkpoint_every_s=1e9)
    state.bind(gallery, names)
    source_rows = []
    for i in range(n_rows):
        emb = rng.normal(size=(1, DIM)).astype(np.float32)
        names.append(f"s{i}")
        state.append_enrollment(
            emb, np.full(1, i, np.int32), subject=f"s{i}", label=i,
            apply_fn=lambda e=emb, i=i: gallery.add(
                e, np.full(1, i, np.int32)))
        source_rows.append(emb[0] / max(np.linalg.norm(emb[0]), 1e-12))
    state.checkpoint_now(wait=True)

    def make_service(g, metrics, replica=None):
        pipe = InstantPipeline(frame_hw, dispatch_s=dispatch_s)
        pipe.gallery = g
        return RecognizerService(
            pipe, FakeConnector(), batch_size=batch_size,
            frame_shape=frame_hw, flush_timeout=0.02, inflight_depth=2,
            similarity_threshold=0.0, metrics=metrics,
            resilience=ResiliencePolicy(readback_deadline_s=2.0),
            replica=replica)

    writer_svc = make_service(gallery, writer_metrics)
    readers = []
    for i in range(2):
        rmetrics = Metrics()
        rgallery = ShardedGallery(capacity=256, dim=DIM, mesh=mesh)
        rep = ReadReplica(state_dir, rgallery, [], metrics=rmetrics,
                          poll_interval_s=0.02, name=f"reader-{i}")
        rep.poll(force=True)
        readers.append({"replica": rep, "gallery": rgallery,
                        "svc": make_service(rgallery, rmetrics,
                                            replica=rep)})
    router_metrics = Metrics()
    handles = [ReplicaHandle("writer", writer_svc.connector,
                             health_fn=service_health_probe(writer_svc),
                             writer=True)]
    for i, reader in enumerate(readers):
        handles.append(ReplicaHandle(
            f"reader-{i}", reader["svc"].connector,
            health_fn=service_health_probe(reader["svc"])))
    router = TopicRouter(handles, metrics=router_metrics,
                         health_interval_s=0.05)
    for i, reader in enumerate(readers):
        reader["replica"].on_resync = router.cordon_hook(f"reader-{i}")
    recorder = TrafficRecorder(router)
    frame_msg = encode_frame(np.zeros(frame_hw, np.float32))
    seq_box = {"seq": 0}

    def pump(duration_s):
        interval = 1.0 / offered_hz
        end = time.monotonic() + duration_s
        while time.monotonic() < end:
            seq = seq_box["seq"]
            seq_box["seq"] = seq + 1
            recorder.send_t[seq] = time.monotonic()
            router.publish(f"camera/{seq % topics}",
                           {**frame_msg, "meta": {"seq": seq}})
            time.sleep(interval)

    def completions_in(t0, t1):
        return sum(1 for t in recorder.done_t.values() if t0 <= t <= t1)

    out = {"note": ("writer + 2 read replicas behind the rendezvous "
                    "router under steady offered load; the writer runs a "
                    "full embedder rollout (staged re-embed -> parity "
                    "gate -> WAL-fenced cutover -> replica re-anchor "
                    "through the router cordon) mid-traffic. The ratio "
                    "compares completed-frames/s through the cutover "
                    "window against steady state."),
           "config": {"offered_hz": offered_hz, "topics": topics,
                      "rows": n_rows, "seconds": seconds}}
    try:
        writer_svc.start(warmup=False)
        for reader in readers:
            reader["svc"].start(warmup=False)
        router.start()
        steady_t0 = time.monotonic()
        pump(max(1.0, seconds / 2))
        steady_t1 = time.monotonic()
        steady_hz = completions_in(steady_t0, steady_t1) / (
            steady_t1 - steady_t0)

        coordinator = RolloutCoordinator(
            state, gallery, lambda rows: rows @ Q, 2,
            old_embed_fn=old_embed, new_embed_fn=new_embed,
            parity_min_samples=8, parity_threshold=0.95, chunk_rows=8,
            metrics=writer_metrics)
        coordinator.run_stage()
        coordinator.score_parity([row.reshape(2, 4)
                                  for row in source_rows[:12]])
        out["parity_agreement"] = (coordinator.parity.agreement
                                   if coordinator.parity else None)
        cut_t0 = time.monotonic()
        coordinator.cutover()
        deadline = time.monotonic() + 15.0
        while (any(r["replica"].embedder_version != 2 for r in readers)
               and time.monotonic() < deadline):
            pump(0.1)
        pump(max(0.5, seconds / 4))  # post-re-anchor tail
        cut_t1 = time.monotonic()
        cutover_hz = completions_in(cut_t0, cut_t1) / (cut_t1 - cut_t0)
        out.update({
            "steady_completed_hz": round(steady_hz, 1),
            "cutover_window_completed_hz": round(cutover_hz, 1),
            "cutover_window_completed_ratio": (
                round(cutover_hz / steady_hz, 3) if steady_hz else None),
            "cutover_window_s": round(cut_t1 - cut_t0, 2),
            "readers_reanchored": all(
                r["replica"].embedder_version == 2 for r in readers),
            "router_cutover_drains": int(
                router_metrics.counter("router_cutover_drains")),
        })
        for svc in [writer_svc] + [r["svc"] for r in readers]:
            svc.drain(timeout=15.0)
    finally:
        router.stop()
        for svc in [writer_svc] + [r["svc"] for r in readers]:
            svc.stop()
        lease.release()
        state.close()
        shutil.rmtree(state_dir, ignore_errors=True)
    print(json.dumps(out), file=sys.stderr)
    return out


def run_registry_smoke(seconds: float = 2.0, batch_size: int = 8,
                       frame_hw=(32, 32), dispatch_s: float = 0.01,
                       topics: int = 12, offered_hz: float = 60.0,
                       n_rows: int = 16, seed: int = 7):
    """Versioned model-registry smoke (ISSUE 18): the same 3-replica
    fleet as the rollout smoke serves steady traffic while the writer
    swaps the DETECTOR through the registry — live detection-parity
    window fed from the publish path, ``registry_cutover`` WAL fence,
    atomic manifest install, replica re-anchor. No re-embed: gallery
    rows are untouched. The load-bearing numbers:

    - ``parity_agreement``: detection agreement (box-overlap verdict
      match) between serving and candidate detector on the live sampled
      window — the gate the swap is allowed through (>= 0.98);
    - ``swap_window_completed_ratio`` / ``swap_window_max_gap_s``: the
      serving-never-blanks numbers through the fence + re-anchor window;
    - ``recompiles_post_warmup``: fleet-wide recompile-watchdog trips —
      model params are jit ARGUMENTS, so a same-architecture swap must
      keep every compile cache warm (0 is the gate).

    ``registry_ok`` gates the smoke's exit code;
    ``scripts/bench_compare.py`` tracks the parity + ratio numbers
    (baseline-predates skip for older artifacts)."""
    import os
    import shutil
    import tempfile

    from opencv_facerecognizer_tpu.parallel import ShardedGallery, make_mesh
    from opencv_facerecognizer_tpu.runtime import (
        FakeConnector, ModelRegistry, ReadReplica, RecognizerService,
        RegistrySwapCoordinator, ReplicaHandle, ResiliencePolicy,
        StateLifecycle, TopicRouter, WriterLease, registry_params_path,
    )
    from opencv_facerecognizer_tpu.runtime.connector import encode_frame
    from opencv_facerecognizer_tpu.runtime.fakes import (
        InstantPipeline, TrafficRecorder,
    )
    from opencv_facerecognizer_tpu.runtime.replication import (
        service_health_probe,
    )
    from opencv_facerecognizer_tpu.utils import metric_names as mn
    from opencv_facerecognizer_tpu.utils.metrics import Metrics

    DIM = 8
    rng = np.random.default_rng(seed)
    mesh = make_mesh()
    state_dir = tempfile.mkdtemp(prefix="ocvf_registry_bench_")

    # Synthetic detectors over the smoke frames. The live parity window
    # reuses the SERVING pipeline's published verdict boxes as the old
    # side (the publish path already paid for them), and InstantPipeline
    # scripts its face at (2, 2, h-2, w-2) — so v1 matches it exactly
    # and the candidate agrees at IoU ~0.87 (the parity window's
    # verdict-match definition is what is under test, not a real CNN).
    def detect_v1(frame):
        del frame
        return [(2.0, 2.0, 30.0, 30.0)]

    def detect_v2(frame):
        del frame
        return [(3.0, 3.0, 31.0, 31.0)]

    writer_metrics = Metrics()
    lease = WriterLease(state_dir, metrics=writer_metrics).acquire()
    gallery = ShardedGallery(capacity=256, dim=DIM, mesh=mesh)
    names = []
    state = StateLifecycle(state_dir, metrics=writer_metrics,
                           checkpoint_wal_rows=1 << 30,
                           checkpoint_every_s=1e9)
    state.attach_registry(ModelRegistry(state_dir, metrics=writer_metrics))
    state.bind(gallery, names)
    for i in range(n_rows):
        emb = rng.normal(size=(1, DIM)).astype(np.float32)
        names.append(f"s{i}")
        state.append_enrollment(
            emb, np.full(1, i, np.int32), subject=f"s{i}", label=i,
            apply_fn=lambda e=emb, i=i: gallery.add(
                e, np.full(1, i, np.int32)))
    state.checkpoint_now(wait=True)

    def make_service(g, metrics, registry=None, replica=None):
        pipe = InstantPipeline(frame_hw, dispatch_s=dispatch_s,
                               faces_per_frame=1)
        pipe.gallery = g
        svc = RecognizerService(
            pipe, FakeConnector(), batch_size=batch_size,
            frame_shape=frame_hw, flush_timeout=0.02, inflight_depth=2,
            similarity_threshold=0.0, metrics=metrics,
            resilience=ResiliencePolicy(readback_deadline_s=2.0),
            replica=replica)
        svc.registry = registry
        return svc

    writer_svc = make_service(gallery, writer_metrics,
                              registry=state.registry)
    readers = []
    for i in range(2):
        rmetrics = Metrics()
        rgallery = ShardedGallery(capacity=256, dim=DIM, mesh=mesh)
        rep = ReadReplica(state_dir, rgallery, [], metrics=rmetrics,
                          poll_interval_s=0.02, name=f"reader-{i}")
        rep.registry = ModelRegistry(state_dir, metrics=rmetrics,
                                     readonly=True)
        rep.poll(force=True)
        svc = make_service(rgallery, rmetrics, registry=rep.registry,
                           replica=rep)
        rep.on_registry_change = svc.flush_model_caches
        readers.append({"replica": rep, "gallery": rgallery,
                        "svc": svc, "metrics": rmetrics})
    router_metrics = Metrics()
    handles = [ReplicaHandle("writer", writer_svc.connector,
                             health_fn=service_health_probe(writer_svc),
                             writer=True)]
    for i, reader in enumerate(readers):
        handles.append(ReplicaHandle(
            f"reader-{i}", reader["svc"].connector,
            health_fn=service_health_probe(reader["svc"])))
    router = TopicRouter(handles, metrics=router_metrics,
                         health_interval_s=0.05)
    for i, reader in enumerate(readers):
        reader["replica"].on_resync = router.cordon_hook(f"reader-{i}")
    recorder = TrafficRecorder(router)
    frame_msg = encode_frame(np.zeros(frame_hw, np.float32))
    seq_box = {"seq": 0}

    def pump(duration_s):
        interval = 1.0 / offered_hz
        end = time.monotonic() + duration_s
        while time.monotonic() < end:
            seq = seq_box["seq"]
            seq_box["seq"] = seq + 1
            recorder.send_t[seq] = time.monotonic()
            router.publish(f"camera/{seq % topics}",
                           {**frame_msg, "meta": {"seq": seq}})
            time.sleep(interval)

    def completions_in(t0, t1):
        return sum(1 for t in recorder.done_t.values() if t0 <= t <= t1)

    out = {"note": ("writer + 2 read replicas behind the rendezvous "
                    "router under steady offered load; the writer swaps "
                    "the detector through the versioned model registry "
                    "(live detection-parity gate -> WAL fence -> atomic "
                    "manifest install -> replica re-anchor) mid-traffic. "
                    "No re-embed; params are jit arguments, so the swap "
                    "must trip the recompile watchdog exactly zero "
                    "times."),
           "config": {"offered_hz": offered_hz, "topics": topics,
                      "rows": n_rows, "seconds": seconds}}
    try:
        writer_svc.start(warmup=False)
        for reader in readers:
            reader["svc"].start(warmup=False)
        router.start()
        steady_t0 = time.monotonic()
        pump(max(1.0, seconds / 2))
        steady_t1 = time.monotonic()
        steady_hz = completions_in(steady_t0, steady_t1) / (
            steady_t1 - steady_t0)

        params_path = registry_params_path(state_dir, "detector", 2)
        os.makedirs(os.path.dirname(params_path), exist_ok=True)
        with open(params_path, "wb") as fh:
            fh.write(b"detector-v2-smoke-params" * 64)
        coordinator = RegistrySwapCoordinator(
            state, state.registry, "detector", 2,
            old_detect_fn=detect_v1, new_detect_fn=detect_v2,
            params_path=params_path, parity_min_samples=12,
            live_sample_interval_s=0.01,
            flush_fn=writer_svc.flush_model_caches,
            metrics=writer_metrics)
        # Live window: the publish path samples frames into the
        # coordinator; the driver drains + scores them off-path.
        writer_svc.registry_swap = coordinator
        parity_deadline = time.monotonic() + 10.0
        while (not coordinator.parity_ok()
               and time.monotonic() < parity_deadline):
            pump(0.1)
            coordinator.drain_live()
        out["parity_agreement"] = (coordinator.parity.agreement
                                   if coordinator.parity else None)
        out["parity_samples"] = (coordinator.parity.samples
                                 if coordinator.parity else 0)
        swap_t0 = time.monotonic()
        coordinator.cutover()
        writer_svc.registry_swap = None
        deadline = time.monotonic() + 15.0
        while (any((r["replica"].stats()["registry"] or {})
                   .get("detector") != 2 for r in readers)
               and time.monotonic() < deadline):
            pump(0.1)
        pump(max(0.5, seconds / 4))  # post-re-anchor tail
        swap_t1 = time.monotonic()
        swap_hz = completions_in(swap_t0, swap_t1) / (swap_t1 - swap_t0)
        done_ts = sorted(t for t in recorder.done_t.values()
                         if swap_t0 - 0.2 <= t <= swap_t1)
        max_gap = (max(b - a for a, b in zip(done_ts, done_ts[1:]))
                   if len(done_ts) > 1 else None)
        recompiles = (
            writer_metrics.counter(mn.RECOMPILES_POST_WARMUP)
            + sum(r["metrics"].counter(mn.RECOMPILES_POST_WARMUP)
                  for r in readers))
        readers_reanchored = all(
            (r["replica"].stats()["registry"] or {}).get("detector") == 2
            for r in readers)
        out.update({
            "steady_completed_hz": round(steady_hz, 1),
            "swap_window_completed_hz": round(swap_hz, 1),
            "swap_window_completed_ratio": (
                round(swap_hz / steady_hz, 3) if steady_hz else None),
            "swap_window_s": round(swap_t1 - swap_t0, 2),
            "swap_window_max_gap_s": (round(max_gap, 3)
                                      if max_gap is not None else None),
            "readers_reanchored": readers_reanchored,
            "recompiles_post_warmup": int(recompiles),
            "registry_swaps": int(
                writer_metrics.counter(mn.REGISTRY_SWAPS)),
        })
        out["registry_ok"] = bool(
            out["parity_agreement"] is not None
            and out["parity_agreement"] >= 0.98
            and readers_reanchored
            and recompiles == 0
            and max_gap is not None and max_gap <= 2.0)
        for svc in [writer_svc] + [r["svc"] for r in readers]:
            svc.drain(timeout=15.0)
    finally:
        router.stop()
        for svc in [writer_svc] + [r["svc"] for r in readers]:
            svc.stop()
        lease.release()
        state.close()
        shutil.rmtree(state_dir, ignore_errors=True)
    print(json.dumps(out), file=sys.stderr)
    return out


def run_partition_smoke(seconds: float = 4.0, seed: int = 7):
    """Partition-tolerance smoke (ISSUE 16): runs the chaos driver's
    ``partition`` scenario at a pinned seed — 3 routed replicas, the
    busiest one partitioned and healed, a flapping second link, a 50%
    duplicate storm, a half-open writer losing its lease dir — and
    lifts the load-bearing numbers into the artifact:

    - ``failover_s``: partition onset to link-down detection (the link
      deadline + a few health cycles is the budget; tracked across
      artifacts by ``scripts/bench_compare.py`` as
      ``partition_failover_s``);
    - ``survivor_p99_ms`` vs ``baseline_p99_ms``: survivor interactive
      tail through the partition against the unloaded fleet (<= 2x is
      the scenario's own gate);
    - exactly-once accounting: hedges fired/won, total dedups absorbed,
      zero duplicate upstream publishes.

    ``partition_ok`` gates the smoke's exit code."""
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "chaos_soak", os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                   "scripts", "chaos_soak.py"))
    chaos_soak = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(chaos_soak)
    report = chaos_soak.run_partition(seconds=seconds, seed=seed)
    router = report.get("router", {})
    out = {
        "note": ("chaos partition scenario at a pinned seed: partition + "
                 "heal the busiest replica, flap a second link, 50% "
                 "duplicate storm, half-open writer fail-closed"),
        "config": {"seconds": seconds, "seed": seed},
        "failover_s": report.get("failover_s"),
        "baseline_p99_ms": report.get("baseline_p99_ms"),
        "survivor_p99_ms": report.get("survivor_p99_ms"),
        "blackout_offered": report.get("blackout_offered"),
        "blackout_rescued": report.get("blackout_rescued"),
        "router_hedges": router.get("router_hedges"),
        "router_hedge_wins": router.get("router_hedge_wins"),
        "router_hedge_wasted": router.get("router_hedge_wasted"),
        "deduped_total": report.get("deduped_total"),
        "duplicate_publishes": report.get("duplicate_publishes"),
        "link_failures": router.get("link_failures"),
        "link_recoveries": router.get("link_recoveries"),
        "split_brain": report.get("split_brain"),
        "failures": report.get("failures"),
        "partition_ok": bool(report.get("ok")),
    }
    print(json.dumps(out), file=sys.stderr)
    return out


def main(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--rates", type=float, nargs="+",
                        default=[25.0, 50.0, 100.0, 200.0])
    parser.add_argument("--duration", type=float, default=10.0)
    # Tunnel-aware throughput defaults: one device round-trip is ~300 ms
    # here, so serve full-ish batches (32) and let frames pool up to
    # 100 ms — tiny flushes would burn a whole round-trip per frame.
    parser.add_argument("--batch-size", type=int, default=32)
    parser.add_argument("--flush-ms", type=float, default=100.0)
    parser.add_argument("--latency-rates", type=float, nargs="+",
                        default=[25.0, 50.0])
    parser.add_argument("--skip-latency-mode", action="store_true")
    parser.add_argument("--compare-rates", type=float, nargs="+",
                        default=[25.0],
                        help="offered rates for the legacy-vs-overlapped "
                             "before/after section")
    parser.add_argument("--skip-compare", action="store_true")
    parser.add_argument("--smoke", action="store_true",
                        help="deterministic serving-loop smoke over the fake "
                             "instant backend only (no hardware, no detector "
                             "training); writes BENCH_SERVING_smoke.json and "
                             "exits")
    args = parser.parse_args(argv)

    if args.smoke:
        # Ingest first: its H2D tail gate is the most microsecond-scale
        # measurement in the smoke, so it runs in the freshest process
        # state (before the other sections accumulate service threads).
        ingest = run_ingest_smoke()
        artifact = run_smoke(write=False)
        artifact["ingest"] = ingest
        artifact["overload_sweep"] = run_overload_sweep()
        artifact["tracing_overhead"] = run_tracing_overhead()
        artifact["replica_scaleout"] = run_replica_scaleout()
        artifact["rollout"] = run_rollout_smoke()
        artifact["registry"] = run_registry_smoke()
        artifact["cascade"] = run_cascade_smoke()
        artifact["video"] = run_video_smoke()
        artifact["partition"] = run_partition_smoke()
        with open("BENCH_SERVING_smoke.json", "w") as fh:
            json.dump(artifact, fh, indent=2)
        print("wrote BENCH_SERVING_smoke.json", file=sys.stderr)
        legacy = artifact["modes"].get("legacy_poll", {})
        overlap = artifact["modes"].get("overlapped", {})
        sweep_4x = next((r for r in artifact["overload_sweep"]["rows"]
                         if r["offered_multiplier"] == 4.0), {})
        trace_cmp = artifact["tracing_overhead"]
        scaleout = artifact["replica_scaleout"]
        ingest = artifact["ingest"]
        print(json.dumps({
            "ingest_h2d_ring_p99_ms_b32": ingest["h2d"].get("32", {})
            .get("uint8_ring", {}).get("p99_ms"),
            "ingest_completed_uplift_b32": ingest["uplift"]
            .get("b32", {}).get("uplift"),
            "ingest_bytes_ratio_b32": ingest["uplift"]
            .get("b32", {}).get("bytes_ratio"),
            "ingest_ok": ingest["ingest_ok"],
            "legacy_e2e_p50_ms": legacy.get("e2e_p50_ms"),
            "overlapped_e2e_p50_ms": overlap.get("e2e_p50_ms"),
            "overlapped_ready_wait_p50_ms": overlap.get(
                "decomposition_ms", {}).get("ready_wait_p50_ms"),
            "overlapped_dropped": overlap.get("dropped_frames"),
            "overload_4x_interactive_completed": sweep_4x.get(
                "interactive_completed"),
            "overload_4x_interactive_p99_ms": sweep_4x.get(
                "interactive_e2e_p99_ms"),
            "overload_4x_bulk_shed": (
                sweep_4x.get("bulk_offered", 0)
                - sweep_4x.get("bulk_completed", 0)),
            "tracing_p50_ratio": trace_cmp.get("p50_ratio"),
            "tracing_within_gate": trace_cmp.get("within_gate"),
            "replica_scaleout_x2": scaleout.get("scaling", {}).get("x2"),
            "replica_scaleout_x4": scaleout.get("scaling", {}).get("x4"),
            "replica_scaleout_ok": scaleout.get("scaling_2x_ok"),
            "rollout_parity_agreement": artifact["rollout"].get(
                "parity_agreement"),
            "rollout_cutover_completed_ratio": artifact["rollout"].get(
                "cutover_window_completed_ratio"),
            "registry_parity_agreement": artifact["registry"].get(
                "parity_agreement"),
            "registry_swap_completed_ratio": artifact["registry"].get(
                "swap_window_completed_ratio"),
            "registry_recompiles": artifact["registry"].get(
                "recompiles_post_warmup"),
            "registry_ok": artifact["registry"].get("registry_ok"),
            "cascade_uplift_density0": artifact["cascade"]["uplift"]
            .get("d0", {}).get("uplift"),
            "cascade_uplift_density30": artifact["cascade"]["uplift"]
            .get("d30", {}).get("uplift"),
            "cascade_stage1_recall": artifact["cascade"]["recall"]
            .get("stage1_recall"),
            "cascade_ok": artifact["cascade"]["cascade_ok"],
            "video_cache_uplift_c90": artifact["video"]["cells"]
            .get("c90", {}).get("uplift"),
            "video_cache_uplift_c50": artifact["video"]["cells"]
            .get("c50", {}).get("uplift"),
            "video_cache_uplift_c0": artifact["video"]["cells"]
            .get("c0", {}).get("uplift"),
            "video_hit_rate_c90": artifact["video"]["cells"]
            .get("c90", {}).get("cache_on", {}).get("cache_hit_rate"),
            "video_ok": artifact["video"]["video_ok"],
            "partition_failover_s": artifact["partition"].get("failover_s"),
            "partition_survivor_p99_ms": artifact["partition"].get(
                "survivor_p99_ms"),
            "partition_deduped_total": artifact["partition"].get(
                "deduped_total"),
            "partition_ok": artifact["partition"].get("partition_ok"),
        }))
        # All six gates fail closed (False on a failed measurement):
        # tracing overhead, the 2-replica >= 1.6x completed-frames
        # scaling, the ingest gate (ring H2D p99 within 3x p50 at
        # every rung, >= 1.15x uint8 completed-frames uplift at b32 with
        # >= 3.5x fewer bytes/frame, zero steady-state staging allocs,
        # compressed intake completing every offered frame), the
        # cascade gate (>= 2x completed-frames uplift at 0% face
        # density / >= 1.3x at 30%, stage-1 recall >= 0.99 at the
        # default threshold, zero post-warmup recompiles across cascade
        # on/off x ingest modes, exact completed_empty settlement under
        # the reject-all chaos fault), the video gate (temporal identity
        # cache: >= 2x completed-frames uplift at coherence 0.9 /
        # >= 1.2x at 0.5 against the per-frame dispatch wall, p99
        # within slack of cache-off, zero post-warmup recompiles
        # cache-on, extended ledger exact in every arm), AND the
        # partition gate (the
        # chaos partition scenario's own verdicts: bounded failover,
        # survivor p99 <= 2x baseline, hedge rescue, exactly-once
        # publishes, exact ledgers under duplication, split-brain
        # fail-closed + re-arm), AND the registry gate (detector swap
        # mid-traffic on the 3-replica fleet: live detection-agreement
        # parity >= 0.98, every reader re-anchored onto the new
        # manifest, zero recompile-watchdog trips, bounded
        # completed-frames gap through the swap window).
        return (0 if trace_cmp.get("within_gate")
                and scaleout.get("scaling_2x_ok")
                and ingest.get("ingest_ok")
                and artifact["cascade"].get("cascade_ok")
                and artifact["video"].get("video_ok")
                and artifact["partition"].get("partition_ok")
                and artifact["registry"].get("registry_ok") else 3)

    import jax

    frame_hw = (256, 256)
    print("building pipeline (detector warm-training)...", file=sys.stderr)
    pipeline, frames = build_pipeline(frame_hw)

    # MUST run before anything reads a device value back (service warmup
    # does): the pre-sync-poll host dispatch cost for the latency model.
    dispatch_q8 = measure_dispatch_quote(pipeline, frames, 8)
    print(f"pre-sync-poll dispatch quote (batch 8): {dispatch_q8} ms",
          file=sys.stderr)

    # Device-compute quote for the latency decomposition: the chained-diff
    # ms/batch at batch 8 from the committed BENCH_DETAIL.json (same code,
    # measured without the tunnel's readback floor).
    device_ms_quote = None
    try:
        with open("BENCH_DETAIL.json") as fh:
            device_ms_quote = json.load(fh)["sweep"]["8"][
                "device_compute"]["min_diff_ms_per_batch"]
    except (OSError, KeyError, json.JSONDecodeError):
        pass

    sections = {}
    sections["throughput"] = run_mode(
        pipeline, frames, frame_hw, name="throughput",
        batch_size=args.batch_size, flush_ms=args.flush_ms,
        inflight_depth=4, rates=args.rates, duration_s=args.duration,
    )
    if not args.skip_compare:
        # Before/after on the SAME offered-load ladder: "legacy" is the
        # pre-worker serving loop (inline is_ready drain on the serving
        # thread, fixed flush window, no dispatch buckets); "overlapped"
        # is the event-driven readback worker + continuous batching
        # (adaptive deadline against a 50 ms target) + the bucket ladder.
        # queue_wait + ready_wait in each row's decomposition_ms show
        # where the difference lands.
        legacy = run_mode(
            pipeline, frames, frame_hw, name="compare/legacy",
            batch_size=args.batch_size, flush_ms=args.flush_ms,
            inflight_depth=4, rates=args.compare_rates,
            duration_s=args.duration, readback_worker=False,
            bucket_sizes=(),
        )
        overlapped = run_mode(
            pipeline, frames, frame_hw, name="compare/overlapped",
            batch_size=args.batch_size, flush_ms=args.flush_ms,
            inflight_depth=4, rates=args.compare_rates,
            duration_s=args.duration, readback_worker=True,
            target_latency_ms=50.0,
        )
        speedups = {}
        for before, after in zip(legacy["rates"], overlapped["rates"]):
            b, a = before.get("e2e_p50_ms"), after.get("e2e_p50_ms")
            if b and a:
                speedups[str(before["offered_hz"])] = round(b / a, 2)
        sections["overlap_comparison"] = {
            "note": ("same offered-load ladder; legacy = inline poll drain "
                     "+ fixed flush, overlapped = readback worker + "
                     "adaptive-deadline continuous batching + bucketed "
                     "dispatch. Caveat for CPU-backend runs: the device "
                     "itself saturates (ready_wait is real compute), so "
                     "e2e stays compute-bound for BOTH modes and the win "
                     "shows up as completed-frame throughput and "
                     "queue_wait instead; the overlap_comparison_smoke "
                     "section isolates the serving-loop overheads "
                     "deterministically with the tunnel's ~100 ms "
                     "sync-poll floor emulated."),
            "legacy_poll": legacy,
            "overlapped": overlapped,
            "e2e_p50_speedup": speedups,
        }
        # The deterministic loop-overhead comparison (fake instant backend
        # with the tunnel's sync-poll floor emulated): same artifact, so
        # the before/after verdict travels with the hardware rows.
        smoke = run_smoke(write=True)
        s_legacy = smoke["modes"].get("legacy_poll", {})
        s_over = smoke["modes"].get("overlapped", {})
        if s_legacy.get("e2e_p50_ms") and s_over.get("e2e_p50_ms"):
            smoke["e2e_p50_speedup"] = round(
                s_legacy["e2e_p50_ms"] / s_over["e2e_p50_ms"], 2)
        sections["overlap_comparison_smoke"] = smoke
    if not args.skip_latency_mode:
        # Latency mode (VERDICT round-2 item #3): small batches, short
        # flush, shallow in-flight queue — the configuration an operator
        # would pick for the <15 ms target on non-tunneled hardware.
        sections["latency"] = run_mode(
            pipeline, frames, frame_hw, name="latency",
            batch_size=8, flush_ms=5.0, inflight_depth=2,
            rates=args.latency_rates, duration_s=args.duration,
            device_ms_quote=device_ms_quote,
            dispatch_ms_quote=dispatch_q8,
        )

    artifact = {
        "device": str(jax.devices()[0]),
        "note": ("end-to-end: connector->batcher->fused device call->async "
                 "readback->publish; includes batching delay and D2H. "
                 "Throughput is sustained with zero drops as load grows; "
                 "e2e latency rises with queueing on the tunneled "
                 "backend's ~100 ms sync-poll readback floor (an "
                 "environment artifact — see each row's decomposition_ms: "
                 "ready_wait carries the floor, queue_wait/dispatch/"
                 "publish are the pipeline's own cost)."),
        **sections,
    }
    # MERGE over the existing artifact: scripts/probe_dispatch.py owns the
    # dispatch_decomposition section of this file, and a whole-file rewrite
    # here silently destroyed it once (r5 queue: serving ran last and
    # clobbered the probe's data).
    try:
        with open("BENCH_SERVING.json") as fh:
            existing = json.load(fh)
    except (OSError, json.JSONDecodeError):
        existing = {}
    existing.update(artifact)
    with open("BENCH_SERVING.json", "w") as fh:
        json.dump(existing, fh, indent=2)
    print("wrote BENCH_SERVING.json", file=sys.stderr)

    if not args.skip_latency_mode:
        # Operator tuning table (VERDICT round-2 item #6): the fused
        # pipeline swept over batch x flush at one offered rate — how the
        # two serving knobs trade batching delay against per-batch
        # round-trip amortization on this hardware. Merged into
        # BENCH_DETAIL.json (bench.py preserves foreign sections).
        sweep_rows = []
        for bs, fl in ((8, 5.0), (8, 100.0), (32, 5.0), (32, 100.0)):
            mode = run_mode(
                pipeline, frames, frame_hw, name=f"sweep b{bs}/f{fl:g}",
                batch_size=bs, flush_ms=fl, inflight_depth=4,
                rates=[50.0], duration_s=min(args.duration, 8.0),
            )
            row = mode["rates"][0]
            sweep_rows.append({
                "batch_size": bs, "flush_ms": fl,
                "offered_hz": row["offered_hz"],
                "achieved_hz": row.get("achieved_hz"),
                "dropped": row.get("dropped_frames"),
                "e2e_p50_ms": row.get("e2e_p50_ms"),
                "queue_wait_p50_ms": row.get("decomposition_ms", {}).get(
                    "queue_wait_p50_ms"),
                "ready_wait_p50_ms": row.get("decomposition_ms", {}).get(
                    "ready_wait_p50_ms"),
            })
        try:
            detail = json.load(open("BENCH_DETAIL.json"))
        except (OSError, json.JSONDecodeError):
            detail = {}
        detail["serving_tuning"] = {
            "note": ("fused pipeline, offered 50 Hz: batch x flush trade "
                     "batching delay (queue_wait) against round-trip "
                     "amortization (ready_wait carries the tunnel floor)"),
            "rows": sweep_rows,
        }
        with open("BENCH_DETAIL.json", "w") as fh:
            json.dump(detail, fh, indent=2)
        print("merged serving_tuning into BENCH_DETAIL.json", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
