"""End-to-end serving latency under offered load (VERDICT round-1 item #8).

Drives the full RecognizerService path — connector -> FrameBatcher ->
fused device pipeline -> async readback -> result publish — at fixed
offered frame rates and records the user-visible latency per frame
(send time -> result publish time), INCLUDING batching delay, device
compute, and device->host readback. This is the path the <15 ms p50
north-star target (BASELINE.json:5) is about; bench.py measures the bare
device step.

Prints one JSON line per offered rate and writes BENCH_SERVING.json.

Caveat recorded in the artifact: on this box the chip sits behind a
tunneled PJRT backend whose first device->host readback puts the process
into ~100 ms sync-poll mode (see runtime/recognizer.py docstring) — an
artifact of the tunnel, not the chip. The async-readback design keeps
throughput sustained with zero drops as offered load grows; end-to-end
latency still rises with queueing on top of the tunnel's readback floor
(the recorded artifact shows exactly that), which is why the artifact also
records a per-frame decomposition separating queue-wait, device dispatch,
readback, and publish.

Run:  PYTHONPATH=. python bench_serving.py [--rates 50 200 500]
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import time

import numpy as np


def build_stack(frame_hw=(256, 256), batch_size=8, flush_ms=10.0,
                gallery_size=1024):
    import jax

    from opencv_facerecognizer_tpu.models.detector import CNNFaceDetector
    from opencv_facerecognizer_tpu.models.embedder import FaceEmbedNet, init_embedder
    from opencv_facerecognizer_tpu.parallel import ShardedGallery, make_mesh
    from opencv_facerecognizer_tpu.parallel.pipeline import RecognitionPipeline
    from opencv_facerecognizer_tpu.runtime.connector import FakeConnector
    from opencv_facerecognizer_tpu.runtime.recognizer import RecognizerService
    from opencv_facerecognizer_tpu.utils.dataset import make_synthetic_scenes
    from opencv_facerecognizer_tpu.utils.metrics import Metrics

    h, w = frame_hw
    det = CNNFaceDetector(max_faces=8, score_threshold=0.3)
    scenes, boxes, counts = make_synthetic_scenes(
        num_scenes=48, scene_size=(h, w), max_faces=8,
        face_size_range=(24, 56), seed=7,
    )
    det.train(scenes, boxes, counts, steps=150, batch_size=16)

    net = FaceEmbedNet(embed_dim=128)
    emb_params = init_embedder(net, num_classes=16, input_shape=(112, 112),
                               seed=0)["net"]
    rng = np.random.default_rng(0)
    gal_emb = rng.normal(size=(gallery_size, 128)).astype(np.float32)
    mesh = make_mesh()
    gallery = ShardedGallery(capacity=gallery_size, dim=128, mesh=mesh)
    gallery.add(gal_emb, rng.integers(0, 64, gallery_size).astype(np.int32))
    pipeline = RecognitionPipeline(det, net, emb_params, gallery,
                                   face_size=(112, 112))
    connector = FakeConnector()
    service = RecognizerService(
        pipeline, connector, batch_size=batch_size, frame_shape=(h, w),
        flush_timeout=flush_ms / 1e3, similarity_threshold=0.0,
        metrics=Metrics(),
    )
    # Distinct frames to cycle (no same-buffer effects).
    frames = [np.asarray(s, np.float32) for s in make_synthetic_scenes(
        num_scenes=16, scene_size=(h, w), max_faces=8,
        face_size_range=(24, 56), seed=9,
    )[0]]
    return service, connector, frames


def drive_rate(service, connector, frames, rate_hz: float, duration_s: float):
    """Offer frames at rate_hz for duration_s; return latency stats."""
    from opencv_facerecognizer_tpu.runtime.recognizer import (
        FRAME_TOPIC, RESULT_TOPIC,
    )

    done = {}
    lock = threading.Lock()

    def on_result(topic, message):
        seq = (message.get("meta") or {}).get("seq")
        if seq is not None:
            with lock:
                done[seq] = time.perf_counter()

    connector.subscribe(RESULT_TOPIC, on_result)

    sent = {}
    interval = 1.0 / rate_hz
    n = int(duration_s * rate_hz)
    start = time.perf_counter()
    for i in range(n):
        target = start + i * interval
        now = time.perf_counter()
        if target > now:
            time.sleep(target - now)
        sent[i] = time.perf_counter()
        connector.inject(FRAME_TOPIC, {"frame": frames[i % len(frames)],
                                       "meta": {"seq": i}})
    # allow the tail to drain
    deadline = time.perf_counter() + 10.0
    while time.perf_counter() < deadline:
        with lock:
            if len(done) >= n:
                break
        time.sleep(0.02)

    with lock:
        lat = np.asarray([
            (done[i] - sent[i]) * 1e3 for i in sent if i in done
        ])
    completed = len(lat)
    stats = {
        "offered_hz": rate_hz,
        "offered_frames": n,
        "completed_frames": completed,
        "dropped_frames": n - completed,
        "achieved_hz": round(completed / duration_s, 1),
    }
    if completed:
        stats.update({
            "e2e_p50_ms": round(float(np.percentile(lat, 50)), 2),
            "e2e_p90_ms": round(float(np.percentile(lat, 90)), 2),
            "e2e_p99_ms": round(float(np.percentile(lat, 99)), 2),
            "e2e_mean_ms": round(float(lat.mean()), 2),
        })
    return stats


def main(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--rates", type=float, nargs="+",
                        default=[25.0, 50.0, 100.0, 200.0])
    parser.add_argument("--duration", type=float, default=10.0)
    # Tunnel-aware defaults: one device round-trip is ~300 ms here, so
    # serve full-ish batches (32) and let frames pool up to 100 ms — tiny
    # flushes would burn a whole round-trip per frame.
    parser.add_argument("--batch-size", type=int, default=32)
    parser.add_argument("--flush-ms", type=float, default=100.0)
    args = parser.parse_args(argv)

    import jax

    print("building stack (detector warm-training)...", file=sys.stderr)
    service, connector, frames = build_stack(
        batch_size=args.batch_size, flush_ms=args.flush_ms
    )
    service.start(warmup=True)
    try:
        results = []
        for rate in args.rates:
            print(f"offered rate {rate} frames/s x {args.duration}s ...",
                  file=sys.stderr)
            stats = drive_rate(service, connector, frames, rate, args.duration)
            stats["faces_found"] = service.metrics.counter("faces_found")
            results.append(stats)
            print(json.dumps(stats))
    finally:
        service.stop()

    artifact = {
        "device": str(jax.devices()[0]),
        "config": {"batch_size": args.batch_size,
                   "flush_ms": args.flush_ms,
                   "frame": [256, 256], "duration_s": args.duration},
        "note": ("end-to-end: connector->batcher->fused device call->async "
                 "readback->publish; includes batching delay and D2H. The "
                 "tunneled backend's ~100 ms sync-poll readback floor is an "
                 "environment artifact the async drain amortizes."),
        "rates": results,
        "metrics": service.metrics.summary(),
    }
    with open("BENCH_SERVING.json", "w") as fh:
        json.dump(artifact, fh, indent=2)
    print("wrote BENCH_SERVING.json", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
