"""The checked-in ratchet: ``LINT_BASELINE.json`` freezes a per-rule
finding count that may only SHRINK.

New rules land with whatever real findings survive triage frozen into the
baseline; the gate then fails on any rule whose live count exceeds its
frozen count — so the tree can only get cleaner, and a new hazard in
previously-clean territory fails CI even while an old, accepted one is
being paid down.  ``--update-baseline`` refuses to grow a count (that is
the ratchet); shrinking is always allowed and should be committed."""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Tuple

DEFAULT_BASELINE = "LINT_BASELINE.json"
_VERSION = 1


def load(path: str) -> Dict[str, int]:
    """The per-rule allowed counts.  A missing rule means 0 allowed."""
    with open(path, "r", encoding="utf-8") as fh:
        doc = json.load(fh)
    if not isinstance(doc, dict) or not isinstance(doc.get("rules"), dict):
        raise ValueError(f"{path}: not a lint baseline (missing 'rules' map)")
    return {str(k): int(v) for k, v in doc["rules"].items()}


def compare(rule_counts: Dict[str, int], allowed: Dict[str, int]
            ) -> Tuple[List[str], List[str]]:
    """(regressions, improvements) as human-readable lines."""
    regressions: List[str] = []
    improvements: List[str] = []
    for rule in sorted(set(rule_counts) | set(allowed)):
        have = rule_counts.get(rule, 0)
        limit = allowed.get(rule, 0)
        if have > limit:
            regressions.append(
                f"{rule}: {have} finding(s), baseline allows {limit}")
        elif have < limit:
            improvements.append(
                f"{rule}: {have} finding(s), baseline still reserves {limit} "
                f"— ratchet down with --update-baseline")
    return regressions, improvements


def update(path: str, rule_counts: Dict[str, int], all_rules: List[str],
           allow_growth: bool = False) -> Optional[str]:
    """Write the baseline with the current counts.  Returns an error
    message (and writes nothing) when a count would GROW and
    ``allow_growth`` is False — fix or suppress instead of re-freezing."""
    existing: Dict[str, int] = {}
    if os.path.exists(path):
        try:
            existing = load(path)
        except (OSError, ValueError) as exc:
            # A corrupt baseline must never silently disable the ratchet:
            # rewriting from scratch would freeze every current finding in.
            if not allow_growth:
                return (f"baseline {path} is unreadable ({exc}) — restore it "
                        f"from version control, or pass "
                        f"--baseline-allow-growth to rebuild from scratch")
            existing = {}
    if not allow_growth:
        grew = [r for r in rule_counts
                if rule_counts[r] > existing.get(r, 0) and existing]
        if grew:
            return ("baseline ratchet: refusing to grow "
                    + ", ".join(f"{r} ({existing.get(r, 0)} -> {rule_counts[r]})"
                                for r in sorted(grew))
                    + " — fix the findings or suppress with justification "
                      "(--baseline-allow-growth overrides)")
    # Merge over the existing baseline: a --rules subset run must update
    # only the rules it actually measured, never wipe the others' frozen
    # counts (a missing rule reads as 0 allowed — losing a reserve would
    # silently fail the next full gate run).
    merged = {r: int(v) for r, v in existing.items()}
    merged.update({r: int(rule_counts.get(r, 0)) for r in all_rules})
    doc = {
        "version": _VERSION,
        "comment": "per-rule finding counts frozen by the ocvf-lint ratchet; "
                   "counts may only shrink (scripts/run_lint.sh, "
                   "tests/test_lint.py enforce)",
        "rules": {r: merged[r] for r in sorted(merged)},
    }
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:  # ocvf-lint: disable=non-atomic-write -- tmp+rename IS the atomic pattern; this file is outside the package tree anyway
        json.dump(doc, fh, indent=2, sort_keys=True)
        fh.write("\n")
    os.replace(tmp, path)
    return None
