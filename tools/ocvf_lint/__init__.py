"""ocvf-lint — AST-based concurrency & durability analysis for the serving
runtime (stdlib only, no third-party deps).

The serving stack's correctness rests on hand-maintained invariants: lock
acquisition order, no blocking calls under a held lock, atomic
tmp+fsync+rename state writes, canonical metric names, and no silently
swallowed exceptions in supervised threads.  This package checks those
invariants statically so they scale with the codebase instead of with
reviewer vigilance.

Usage:  ``python -m tools.ocvf_lint [--json] PATH...``

Exit codes: 0 clean, 1 findings, 2 internal error.

Suppressions (justification after ``--`` is mandatory — a bare disable is
itself a finding and suppresses nothing):

    some_call()  # ocvf-lint: disable=blocking-under-lock -- WAL ack==durable
    with lock:  # ocvf-lint: disable-block=blocking-under-lock -- whole block
    # ocvf-lint: disable-file=non-atomic-write -- bench report, torn ok
"""

from tools.ocvf_lint.core import (  # noqa: F401
    Checker,
    Finding,
    REGISTRY,
    run,
)
