"""SARIF 2.1.0 serialization of a lint run — the machine-readable format
CI annotation surfaces (GitHub code scanning et al.) ingest natively.
Deliberately minimal: one run, one driver, one result per finding, with
``relatedLocations`` carrying each finding's ``also`` sites."""

from __future__ import annotations

from typing import Dict

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
                "master/Schemata/sarif-schema-2.1.0.json")


def _location(path: str, line: int, col: int = 0) -> Dict:
    region = {"startLine": max(1, line)}
    if col:
        region["startColumn"] = col + 1  # SARIF columns are 1-based
    return {
        "physicalLocation": {
            "artifactLocation": {"uri": path.replace("\\", "/")},
            "region": region,
        }
    }


def to_sarif(result, registry: Dict[str, type]) -> Dict:
    """``result`` is a ``core.RunResult``; ``registry`` maps rule name ->
    checker class (for descriptions)."""
    rules = []
    for rule in sorted(set(result.rules)
                       | {f.rule for f in result.findings}):
        cls = registry.get(rule)
        desc = getattr(cls, "description", "") or rule
        rules.append({
            "id": rule,
            "shortDescription": {"text": desc},
        })
    results = []
    for f in result.findings:
        entry = {
            "ruleId": f.rule,
            "level": "error",
            "message": {"text": f.message},
            "locations": [_location(f.path, f.line, f.col)],
        }
        if f.also:
            entry["relatedLocations"] = [_location(p, l) for p, l in f.also]
        results.append(entry)
    return {
        "version": SARIF_VERSION,
        "$schema": SARIF_SCHEMA,
        "runs": [{
            "tool": {
                "driver": {
                    "name": "ocvf-lint",
                    "informationUri":
                        "https://example.invalid/opencv_facerecognizer_tpu",
                    "rules": rules,
                }
            },
            "results": results,
        }],
    }
