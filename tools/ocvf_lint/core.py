"""Framework core: findings, the checker registry, suppression parsing and
the run loop.  Checkers live in ``tools.ocvf_lint.checkers`` and register
themselves via the ``@register`` decorator; everything here is
checker-agnostic."""

from __future__ import annotations

import ast
import dataclasses
import io
import os
import re
import tokenize
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

#: ``# ocvf-lint: disable=<rule>[,<rule>] -- <justification>``  (line-level;
#: covers the comment's own line and the line directly below it, so it works
#: both trailing the offending statement and on its own line above it),
#: ``disable-block=<rule> -- ...`` (covers the innermost statement enclosing
#: the comment — put it on a ``with`` header to cover the whole block), or
#: ``disable-file=<rule> -- ...`` (whole file).
#:
#: ``boundary=<rule>`` / ``boundary-block=<rule>`` is the shared sanctioned-site
#: annotation: same coverage and justification hygiene as ``disable``, but it
#: declares "this site IS the designed protocol boundary" (a WAL fsync under
#: its lock, the serving loop's one readback, a cache-keyed jit builder)
#: rather than "a finding we accept".  Only rules that define boundaries
#: (``Checker.boundary_capable``) honor it; boundaries are counted
#: separately in the report.
SUPPRESS_RE = re.compile(
    r"#\s*ocvf-lint:\s*(?P<kind>disable-file|disable-block|disable"
    r"|boundary-block|boundary)\s*=\s*"
    r"(?P<rules>[A-Za-z0-9_,-]+)"
    r"(?:\s*--\s*(?P<why>.*\S))?"
)

#: A justification shorter than this is treated as absent — "ok" or "x" is
#: not an explanation the next reader can act on.
MIN_JUSTIFICATION = 8

#: The meta-rule enforcing suppression hygiene; never itself suppressible.
SUPPRESSION_RULE = "suppression"

#: Files that fail ``ast.parse`` get a finding under this rule.
PARSE_RULE = "parse-error"


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at a concrete file:line.

    ``also`` lists additional participating sites (e.g. the other edges of a
    lock-order cycle); a suppression at any of them silences the finding."""

    rule: str
    path: str
    line: int
    col: int
    message: str
    also: Tuple[Tuple[str, int], ...] = ()

    def format(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: [{self.rule}] {self.message}"

    def to_dict(self) -> dict:
        out = {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
        }
        if self.also:
            out["also"] = [{"path": p, "line": l} for p, l in self.also]
        return out

    @staticmethod
    def from_dict(d: dict) -> "Finding":
        return Finding(
            rule=d["rule"], path=d["path"], line=d["line"], col=d["col"],
            message=d["message"],
            also=tuple((a["path"], a["line"]) for a in d.get("also", ())))


@dataclasses.dataclass
class Suppression:
    rules: Tuple[str, ...]
    line: int
    kind: str  # "disable" | "disable-block" | "disable-file" | "boundary[-block]"
    justification: str
    #: inclusive line span this suppression covers (block spans are resolved
    #: against the AST once the file parses; file-level covers everything)
    start: int = 0
    end: int = 0
    used: bool = False

    @property
    def file_level(self) -> bool:
        return self.kind == "disable-file"

    @property
    def boundary(self) -> bool:
        return self.kind in ("boundary", "boundary-block")

    @property
    def justified(self) -> bool:
        return len(self.justification.strip()) >= MIN_JUSTIFICATION

    def covers(self, line: int) -> bool:
        return self.file_level or self.start <= line <= self.end


class FileContext:
    """Everything a checker needs about one source file."""

    def __init__(self, path: str, source: str, tree: ast.Module):
        self.path = path
        self.source = source
        self.tree = tree
        self.module = module_name(path)

    def finding(self, rule: str, node: ast.AST, message: str,
                also: Tuple[Tuple[str, int], ...] = ()) -> Finding:
        return Finding(rule, self.path, getattr(node, "lineno", 1),
                       getattr(node, "col_offset", 0), message, also)


class Checker:
    """Base checker.  ``check_file`` runs once per file; ``finalize`` runs
    after every file has been seen (for project-wide rules like the lock
    graph).

    ``scope`` declares cacheability: a ``"file"`` checker's findings depend
    only on that one file's content (the incremental cache can replay them
    on a content-hash hit); a ``"project"`` checker sees cross-file state
    (call graphs, the metric registry) and always re-runs.

    ``boundary_capable`` opts the rule into the shared sanctioned-site
    annotation (``# ocvf-lint: boundary=<rule> -- why``).

    ``extra_cache_fingerprint(files)`` lets a checker declare out-of-tree
    inputs its verdict depends on (e.g. the metrics registry read as a
    fallback when it is not among the linted files) — the returned string
    is folded into the run-cache key so editing that input invalidates
    cached verdicts.

    ``needs_dataflow`` asks the runner for a ``dataflow.ProjectModel`` over
    every parsed file, injected as ``self.project`` before any
    ``check_file`` call (built once, shared by all checkers that want it)."""

    rule: str = ""
    description: str = ""
    scope: str = "file"
    boundary_capable: bool = False
    needs_dataflow: bool = False
    project = None  # dataflow.ProjectModel, injected when needs_dataflow

    def check_file(self, ctx: FileContext) -> List[Finding]:
        return []

    def finalize(self) -> List[Finding]:
        return []

    def extra_cache_fingerprint(self, files: Sequence[str]) -> str:
        return ""


REGISTRY: Dict[str, type] = {}


def register(cls: type) -> type:
    if not cls.rule:
        raise ValueError(f"checker {cls.__name__} has no rule name")
    if cls.rule in REGISTRY:
        raise ValueError(f"duplicate checker rule {cls.rule!r}")
    REGISTRY[cls.rule] = cls
    return cls


def module_name(path: str) -> str:
    """Stable dotted module id from a file path: strip ``.py`` and anchor at
    the package directory when present, so relative and absolute paths map
    to the SAME id — ``/any/checkout/opencv_facerecognizer_tpu/runtime/
    batcher.py`` and ``opencv_facerecognizer_tpu/runtime/batcher.py`` both
    become ``runtime.batcher``.  (The dynamic DebugLock cross-check names
    its locks with these ids; a checkout-dir-dependent prefix would silently
    disconnect the two graphs.)  Outside the package, the last components
    are used as-is."""
    parts = os.path.normpath(path).replace("\\", "/").split("/")
    if parts and parts[-1].endswith(".py"):
        parts[-1] = parts[-1][:-3]
    if "opencv_facerecognizer_tpu" in parts:
        anchor = len(parts) - 1 - parts[::-1].index("opencv_facerecognizer_tpu")
        parts = parts[anchor + 1:]
    parts = [p for p in parts if p not in ("", ".", "..")]
    return ".".join(parts[-3:]) if parts else "<unknown>"


def parse_suppressions(source: str) -> List[Suppression]:
    out: List[Suppression] = []
    reader = io.StringIO(source).readline
    try:
        tokens = tokenize.generate_tokens(reader)
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = SUPPRESS_RE.search(tok.string)
            if m is None:
                continue
            rules = tuple(r.strip() for r in m.group("rules").split(",") if r.strip())
            line = tok.start[0]
            out.append(Suppression(
                rules=rules,
                line=line,
                kind=m.group("kind"),
                justification=m.group("why") or "",
                start=line,
                end=line + 1,  # block spans widened once the AST is known
            ))
    except tokenize.TokenError:
        pass  # a finding for the parse failure is emitted separately
    return out


def _enclosing_stmt_span(tree: ast.Module, line: int) -> Tuple[int, int]:
    """Inclusive line span of the innermost statement whose extent contains
    ``line`` — how ``disable-block`` suppressions resolve their coverage."""
    best: Optional[Tuple[int, int]] = None
    for node in ast.walk(tree):
        if not isinstance(node, ast.stmt):
            continue
        end = getattr(node, "end_lineno", None)
        if end is None or not (node.lineno <= line <= end):
            continue
        if best is None or (end - node.lineno) < (best[1] - best[0]):
            best = (node.lineno, end)
    return best if best is not None else (line, line + 1)


def iter_py_files(paths: Sequence[str]) -> List[str]:
    files: List[str] = []
    for path in paths:
        if os.path.isfile(path):
            if path.endswith(".py"):
                files.append(path)
        elif os.path.isdir(path):
            for root, dirs, names in os.walk(path):
                dirs[:] = sorted(d for d in dirs
                                 if not d.startswith(".") and d != "__pycache__")
                for name in sorted(names):
                    if name.endswith(".py"):
                        files.append(os.path.join(root, name))
        # nonexistent paths are reported by the caller
    return files


@dataclasses.dataclass
class RunResult:
    findings: List[Finding]
    files_scanned: int
    rules: List[str]
    suppressions_used: int
    #: sanctioned-site annotations honored (``boundary=`` kind)
    boundaries_used: int = 0
    #: incremental-cache telemetry: {"run_hit": bool, "file_hits": int,
    #: "file_misses": int} — absent keys mean "no cache in play"
    cache: Dict[str, object] = dataclasses.field(default_factory=dict)

    def rule_counts(self) -> Dict[str, int]:
        counts: Dict[str, int] = {}
        for f in self.findings:
            counts[f.rule] = counts.get(f.rule, 0) + 1
        return counts

    def to_dict(self) -> dict:
        return {
            "findings": [f.to_dict() for f in self.findings],
            "files_scanned": self.files_scanned,
            "rules": self.rules,
            "rule_counts": self.rule_counts(),
            "suppressions_used": self.suppressions_used,
            "boundaries_used": self.boundaries_used,
            "cache": self.cache,
        }


def _load_builtin_checkers() -> None:
    from tools.ocvf_lint import checkers  # noqa: F401 — import registers


def run(paths: Sequence[str], rules: Optional[Iterable[str]] = None,
        cache=None) -> RunResult:
    """Lint every ``.py`` file under ``paths``.  Returns all unsuppressed
    findings, sorted by (path, line).

    ``cache`` (a ``tools.ocvf_lint.cache.LintCache``) enables the
    incremental layers: an unchanged project returns the memoized run
    wholesale; otherwise per-file findings of ``scope == "file"`` checkers
    replay from their content-hash entries and only project-scope analyses
    recompute."""
    _load_builtin_checkers()
    selected = sorted(REGISTRY) if rules is None else [r for r in sorted(REGISTRY)
                                                      if r in set(rules)]
    checkers = [REGISTRY[name]() for name in selected]

    for path in paths:
        if not os.path.exists(path):
            raise FileNotFoundError(f"lint path does not exist: {path}")

    files = iter_py_files(paths)
    sources: Dict[str, str] = {}
    hashes: Dict[str, str] = {}
    for path in files:
        with open(path, "r", encoding="utf-8", errors="replace") as fh:
            sources[path] = fh.read()
        hashes[path] = _sha256(sources[path])

    cache_info: Dict[str, object] = {}
    run_key = None
    if cache is not None:
        extra = "".join(c.extra_cache_fingerprint(files) for c in checkers)
        run_key = cache.run_key(selected, [(p, hashes[p]) for p in files],
                                extra=extra)
        hit = cache.get_run(run_key)
        if hit is not None:
            result = RunResult(
                findings=[Finding.from_dict(d) for d in hit["findings"]],
                files_scanned=hit["files_scanned"], rules=list(selected),
                suppressions_used=hit["suppressions_used"],
                boundaries_used=hit.get("boundaries_used", 0),
                cache={"run_hit": True})
            return result
        cache_info = {"run_hit": False, "file_hits": 0, "file_misses": 0}

    findings: List[Finding] = []
    suppressions: Dict[str, List[Suppression]] = {}
    contexts: List[FileContext] = []
    for path in files:
        source = sources[path]
        suppressions[path] = parse_suppressions(source)
        try:
            tree = ast.parse(source, filename=path)
        except SyntaxError as exc:
            findings.append(Finding(PARSE_RULE, path, exc.lineno or 1,
                                    exc.offset or 0, f"file does not parse: {exc.msg}"))
            continue
        for s in suppressions[path]:
            if s.kind in ("disable-block", "boundary-block"):
                s.start, s.end = _enclosing_stmt_span(tree, s.line)
        contexts.append(FileContext(path, source, tree))

    # One shared interprocedural model for every checker that wants it.
    if any(c.needs_dataflow for c in checkers):
        from tools.ocvf_lint import dataflow
        project = dataflow.ProjectModel(contexts)
        for checker in checkers:
            if checker.needs_dataflow:
                checker.project = project

    file_scope = [c for c in checkers if c.scope == "file"]
    project_scope = [c for c in checkers if c.scope != "file"]
    file_rules = [c.rule for c in file_scope]

    for ctx in contexts:
        # The file-layer key covers PATH as well as content: several
        # file-scope rules decide by location (tests/ exemption, owner- and
        # durability-module suffixes), so identical bytes at a different
        # path must never replay the old verdict.
        fkey = _sha256(ctx.path + "\0" + hashes[ctx.path])
        cached = (cache.get_file(fkey, file_rules)
                  if cache is not None and file_scope else None)
        if cached is not None:
            cache_info["file_hits"] = cache_info.get("file_hits", 0) + 1
            for dicts in cached.values():
                findings.extend(Finding.from_dict(d) for d in dicts)
            continue
        per_rule: Dict[str, List[Finding]] = {}
        for checker in file_scope:
            per_rule[checker.rule] = checker.check_file(ctx)
            findings.extend(per_rule[checker.rule])
        if cache is not None and file_scope:
            cache_info["file_misses"] = cache_info.get("file_misses", 0) + 1
            cache.store_file(fkey, {
                rule: [f.to_dict() for f in fs]
                for rule, fs in per_rule.items()})
    for checker in file_scope:
        findings.extend(checker.finalize())

    for checker in project_scope:
        for ctx in contexts:
            findings.extend(checker.check_file(ctx))
        findings.extend(checker.finalize())

    # Suppression hygiene: a disable without justification is a finding in
    # its own right, and suppresses nothing.  Unknown rule names are typos,
    # and a boundary annotation only exists for rules that define
    # sanctioned boundaries.
    known = set(REGISTRY) | {PARSE_RULE}
    for path, supps in suppressions.items():
        for s in supps:
            if not s.justified:
                word = "boundary annotation" if s.boundary else "suppression"
                findings.append(Finding(
                    SUPPRESSION_RULE, path, s.line, 0,
                    f"{word} for {','.join(s.rules)} lacks a justification "
                    f"(append ' -- <why this is safe>'); it is ignored"))
            for r in s.rules:
                if r not in known:
                    findings.append(Finding(
                        SUPPRESSION_RULE, path, s.line, 0,
                        f"suppression names unknown rule {r!r} "
                        f"(known: {', '.join(sorted(known))})"))
                elif s.boundary and not getattr(REGISTRY.get(r), "boundary_capable",
                                                False):
                    findings.append(Finding(
                        SUPPRESSION_RULE, path, s.line, 0,
                        f"rule {r!r} defines no sanctioned boundaries — use "
                        f"'disable={r}' to accept a finding instead"))

    def suppressed(f: Finding) -> bool:
        if f.rule == SUPPRESSION_RULE:
            return False
        capable = getattr(REGISTRY.get(f.rule), "boundary_capable", False)
        for path, line in ((f.path, f.line),) + f.also:
            for s in suppressions.get(path, ()):
                if not s.justified or f.rule not in s.rules:
                    continue
                if s.boundary and not capable:
                    continue
                if s.covers(line):
                    s.used = True
                    return True
        return False

    kept = [f for f in findings if not suppressed(f)]
    kept.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    used = sum(1 for supps in suppressions.values()
               for s in supps if s.used and not s.boundary)
    bounds = sum(1 for supps in suppressions.values()
                 for s in supps if s.used and s.boundary)
    result = RunResult(findings=kept, files_scanned=len(files),
                       rules=selected, suppressions_used=used,
                       boundaries_used=bounds, cache=cache_info)
    if cache is not None and run_key is not None:
        cache.store_run(run_key, {
            "findings": [f.to_dict() for f in kept],
            "files_scanned": len(files),
            "suppressions_used": used,
            "boundaries_used": bounds,
        })
        cache.save()
    return result


def _sha256(text: str) -> str:
    import hashlib

    return hashlib.sha256(text.encode("utf-8", "replace")).hexdigest()
