"""Framework core: findings, the checker registry, suppression parsing and
the run loop.  Checkers live in ``tools.ocvf_lint.checkers`` and register
themselves via the ``@register`` decorator; everything here is
checker-agnostic."""

from __future__ import annotations

import ast
import dataclasses
import io
import os
import re
import tokenize
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

#: ``# ocvf-lint: disable=rule1,rule2 -- justification``  (line-level; covers
#: the comment's own line and the line directly below it, so it works both
#: trailing the offending statement and on its own line above it),
#: ``# ocvf-lint: disable-block=rule -- justification`` (covers the innermost
#: statement enclosing the comment — put it on a ``with`` header to cover the
#: whole block), or
#: ``# ocvf-lint: disable-file=rule -- justification`` (whole file).
SUPPRESS_RE = re.compile(
    r"#\s*ocvf-lint:\s*(?P<kind>disable-file|disable-block|disable)\s*=\s*"
    r"(?P<rules>[A-Za-z0-9_,-]+)"
    r"(?:\s*--\s*(?P<why>.*\S))?"
)

#: A justification shorter than this is treated as absent — "ok" or "x" is
#: not an explanation the next reader can act on.
MIN_JUSTIFICATION = 8

#: The meta-rule enforcing suppression hygiene; never itself suppressible.
SUPPRESSION_RULE = "suppression"

#: Files that fail ``ast.parse`` get a finding under this rule.
PARSE_RULE = "parse-error"


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at a concrete file:line.

    ``also`` lists additional participating sites (e.g. the other edges of a
    lock-order cycle); a suppression at any of them silences the finding."""

    rule: str
    path: str
    line: int
    col: int
    message: str
    also: Tuple[Tuple[str, int], ...] = ()

    def format(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: [{self.rule}] {self.message}"

    def to_dict(self) -> dict:
        out = {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
        }
        if self.also:
            out["also"] = [{"path": p, "line": l} for p, l in self.also]
        return out


@dataclasses.dataclass
class Suppression:
    rules: Tuple[str, ...]
    line: int
    kind: str  # "disable" | "disable-block" | "disable-file"
    justification: str
    #: inclusive line span this suppression covers (block spans are resolved
    #: against the AST once the file parses; file-level covers everything)
    start: int = 0
    end: int = 0
    used: bool = False

    @property
    def file_level(self) -> bool:
        return self.kind == "disable-file"

    @property
    def justified(self) -> bool:
        return len(self.justification.strip()) >= MIN_JUSTIFICATION

    def covers(self, line: int) -> bool:
        return self.file_level or self.start <= line <= self.end


class FileContext:
    """Everything a checker needs about one source file."""

    def __init__(self, path: str, source: str, tree: ast.Module):
        self.path = path
        self.source = source
        self.tree = tree
        self.module = module_name(path)

    def finding(self, rule: str, node: ast.AST, message: str,
                also: Tuple[Tuple[str, int], ...] = ()) -> Finding:
        return Finding(rule, self.path, getattr(node, "lineno", 1),
                       getattr(node, "col_offset", 0), message, also)


class Checker:
    """Base checker.  ``check_file`` runs once per file; ``finalize`` runs
    after every file has been seen (for project-wide rules like the lock
    graph)."""

    rule: str = ""
    description: str = ""

    def check_file(self, ctx: FileContext) -> List[Finding]:
        return []

    def finalize(self) -> List[Finding]:
        return []


REGISTRY: Dict[str, type] = {}


def register(cls: type) -> type:
    if not cls.rule:
        raise ValueError(f"checker {cls.__name__} has no rule name")
    if cls.rule in REGISTRY:
        raise ValueError(f"duplicate checker rule {cls.rule!r}")
    REGISTRY[cls.rule] = cls
    return cls


def module_name(path: str) -> str:
    """Stable dotted module id from a file path: strip ``.py`` and anchor at
    the package directory when present, so relative and absolute paths map
    to the SAME id — ``/any/checkout/opencv_facerecognizer_tpu/runtime/
    batcher.py`` and ``opencv_facerecognizer_tpu/runtime/batcher.py`` both
    become ``runtime.batcher``.  (The dynamic DebugLock cross-check names
    its locks with these ids; a checkout-dir-dependent prefix would silently
    disconnect the two graphs.)  Outside the package, the last components
    are used as-is."""
    parts = os.path.normpath(path).replace("\\", "/").split("/")
    if parts and parts[-1].endswith(".py"):
        parts[-1] = parts[-1][:-3]
    if "opencv_facerecognizer_tpu" in parts:
        anchor = len(parts) - 1 - parts[::-1].index("opencv_facerecognizer_tpu")
        parts = parts[anchor + 1:]
    parts = [p for p in parts if p not in ("", ".", "..")]
    return ".".join(parts[-3:]) if parts else "<unknown>"


def parse_suppressions(source: str) -> List[Suppression]:
    out: List[Suppression] = []
    reader = io.StringIO(source).readline
    try:
        tokens = tokenize.generate_tokens(reader)
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = SUPPRESS_RE.search(tok.string)
            if m is None:
                continue
            rules = tuple(r.strip() for r in m.group("rules").split(",") if r.strip())
            line = tok.start[0]
            out.append(Suppression(
                rules=rules,
                line=line,
                kind=m.group("kind"),
                justification=m.group("why") or "",
                start=line,
                end=line + 1,  # block spans widened once the AST is known
            ))
    except tokenize.TokenError:
        pass  # a finding for the parse failure is emitted separately
    return out


def _enclosing_stmt_span(tree: ast.Module, line: int) -> Tuple[int, int]:
    """Inclusive line span of the innermost statement whose extent contains
    ``line`` — how ``disable-block`` suppressions resolve their coverage."""
    best: Optional[Tuple[int, int]] = None
    for node in ast.walk(tree):
        if not isinstance(node, ast.stmt):
            continue
        end = getattr(node, "end_lineno", None)
        if end is None or not (node.lineno <= line <= end):
            continue
        if best is None or (end - node.lineno) < (best[1] - best[0]):
            best = (node.lineno, end)
    return best if best is not None else (line, line + 1)


def iter_py_files(paths: Sequence[str]) -> List[str]:
    files: List[str] = []
    for path in paths:
        if os.path.isfile(path):
            if path.endswith(".py"):
                files.append(path)
        elif os.path.isdir(path):
            for root, dirs, names in os.walk(path):
                dirs[:] = sorted(d for d in dirs
                                 if not d.startswith(".") and d != "__pycache__")
                for name in sorted(names):
                    if name.endswith(".py"):
                        files.append(os.path.join(root, name))
        # nonexistent paths are reported by the caller
    return files


@dataclasses.dataclass
class RunResult:
    findings: List[Finding]
    files_scanned: int
    rules: List[str]
    suppressions_used: int

    def to_dict(self) -> dict:
        return {
            "findings": [f.to_dict() for f in self.findings],
            "files_scanned": self.files_scanned,
            "rules": self.rules,
            "suppressions_used": self.suppressions_used,
        }


def _load_builtin_checkers() -> None:
    from tools.ocvf_lint import checkers  # noqa: F401 — import registers


def run(paths: Sequence[str], rules: Optional[Iterable[str]] = None) -> RunResult:
    """Lint every ``.py`` file under ``paths``.  Returns all unsuppressed
    findings, sorted by (path, line)."""
    _load_builtin_checkers()
    selected = sorted(REGISTRY) if rules is None else [r for r in sorted(REGISTRY)
                                                      if r in set(rules)]
    checkers = [REGISTRY[name]() for name in selected]

    for path in paths:
        if not os.path.exists(path):
            raise FileNotFoundError(f"lint path does not exist: {path}")

    findings: List[Finding] = []
    suppressions: Dict[str, List[Suppression]] = {}
    contexts: List[FileContext] = []
    files = iter_py_files(paths)
    for path in files:
        with open(path, "r", encoding="utf-8", errors="replace") as fh:
            source = fh.read()
        suppressions[path] = parse_suppressions(source)
        try:
            tree = ast.parse(source, filename=path)
        except SyntaxError as exc:
            findings.append(Finding(PARSE_RULE, path, exc.lineno or 1,
                                    exc.offset or 0, f"file does not parse: {exc.msg}"))
            continue
        for s in suppressions[path]:
            if s.kind == "disable-block":
                s.start, s.end = _enclosing_stmt_span(tree, s.line)
        contexts.append(FileContext(path, source, tree))

    for checker in checkers:
        for ctx in contexts:
            findings.extend(checker.check_file(ctx))
        findings.extend(checker.finalize())

    # Suppression hygiene: a disable without justification is a finding in
    # its own right, and suppresses nothing.  Unknown rule names are typos.
    known = set(REGISTRY) | {PARSE_RULE}
    for path, supps in suppressions.items():
        for s in supps:
            if not s.justified:
                findings.append(Finding(
                    SUPPRESSION_RULE, path, s.line, 0,
                    f"suppression for {','.join(s.rules)} lacks a justification "
                    f"(append ' -- <why this is safe>'); it is ignored"))
            for r in s.rules:
                if r not in known:
                    findings.append(Finding(
                        SUPPRESSION_RULE, path, s.line, 0,
                        f"suppression names unknown rule {r!r} "
                        f"(known: {', '.join(sorted(known))})"))

    def suppressed(f: Finding) -> bool:
        if f.rule == SUPPRESSION_RULE:
            return False
        for path, line in ((f.path, f.line),) + f.also:
            for s in suppressions.get(path, ()):
                if not s.justified or f.rule not in s.rules:
                    continue
                if s.covers(line):
                    s.used = True
                    return True
        return False

    kept = [f for f in findings if not suppressed(f)]
    kept.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    used = sum(1 for supps in suppressions.values() for s in supps if s.used)
    return RunResult(findings=kept, files_scanned=len(files),
                     rules=selected, suppressions_used=used)
