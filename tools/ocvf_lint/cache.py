"""Per-file incremental cache keyed on content hashes.

Two layers, both invalidated by a fingerprint of the linter's OWN sources
(editing a checker must never replay stale findings):

- **run layer** — the common CI case: nothing changed since the last gate
  run, so the whole ``RunResult`` replays from one hash lookup.
- **file layer** — content-addressed per-file findings for ``scope ==
  "file"`` checkers; an edit to one file re-walks only that file (plus the
  project-scope analyses, which by definition need the whole tree).

Everything is one JSON file under the cache dir, written atomically
(tmp + ``os.replace``) so a crashed run can never leave a torn cache — a
torn/unreadable cache is treated as empty, never an error."""

from __future__ import annotations

import hashlib
import json
import os
import tempfile
from typing import Dict, List, Optional, Sequence, Tuple

#: bound the layers so the cache file cannot grow without limit
_MAX_RUNS = 8
_MAX_FILES = 2048

DEFAULT_CACHE_DIR = ".ocvf_lint_cache"


def tool_fingerprint() -> str:
    """sha256 over the linter's own source files — any edit to a checker,
    the core, or this module invalidates every cached result."""
    root = os.path.dirname(os.path.abspath(__file__))
    digest = hashlib.sha256()
    for dirpath, dirs, names in os.walk(root):
        dirs[:] = sorted(d for d in dirs if d != "__pycache__")
        for name in sorted(names):
            if not name.endswith(".py"):
                continue
            path = os.path.join(dirpath, name)
            digest.update(os.path.relpath(path, root).encode())
            with open(path, "rb") as fh:
                digest.update(fh.read())
    return digest.hexdigest()


class LintCache:
    def __init__(self, cache_dir: str = DEFAULT_CACHE_DIR):
        self.path = os.path.join(cache_dir, "cache.json")
        self.fingerprint = tool_fingerprint()
        self._dirty = False
        self.data = {"tool": self.fingerprint, "files": {}, "runs": {}}
        try:
            with open(self.path, "r", encoding="utf-8") as fh:
                loaded = json.load(fh)
            if (isinstance(loaded, dict)
                    and loaded.get("tool") == self.fingerprint):
                self.data = loaded
        except (OSError, ValueError):
            pass  # absent/torn/stale cache == empty cache

    # ---- keys ----

    def run_key(self, rules: Sequence[str],
                file_hashes: Sequence[Tuple[str, str]],
                extra: str = "") -> str:
        """``extra`` carries checker-declared out-of-tree inputs (the
        metrics registry read as a fallback) — a verdict can depend on
        files that are not in ``file_hashes``."""
        digest = hashlib.sha256()
        digest.update(",".join(rules).encode())
        for path, h in file_hashes:
            digest.update(f"\n{path}\0{h}".encode())
        digest.update(b"\x00extra\x00" + extra.encode())
        return digest.hexdigest()

    # ---- run layer ----

    def get_run(self, key: str) -> Optional[dict]:
        return self.data["runs"].get(key)

    def store_run(self, key: str, result: dict) -> None:
        runs = self.data["runs"]
        runs[key] = result
        while len(runs) > _MAX_RUNS:
            runs.pop(next(iter(runs)))
        self._dirty = True

    # ---- file layer ----

    def get_file(self, file_hash: str, rules: Sequence[str]
                 ) -> Optional[Dict[str, List[dict]]]:
        """The per-rule finding dicts for this (path, content) key — the
        caller hashes BOTH, because path-dependent rules make identical
        content mean different things at different locations — or None
        unless EVERY requested rule is present (a partial entry must not
        hide the missing rule's findings)."""
        entry = self.data["files"].get(file_hash)
        if entry is None or any(rule not in entry for rule in rules):
            return None
        return {rule: entry[rule] for rule in rules}

    def store_file(self, file_hash: str,
                   per_rule: Dict[str, List[dict]]) -> None:
        files = self.data["files"]
        entry = files.setdefault(file_hash, {})
        entry.update(per_rule)
        while len(files) > _MAX_FILES:
            files.pop(next(iter(files)))
        self._dirty = True

    # ---- persistence ----

    def save(self) -> None:
        if not self._dirty:
            return
        directory = os.path.dirname(self.path) or "."
        try:
            os.makedirs(directory, exist_ok=True)
            fd, tmp = tempfile.mkstemp(dir=directory, suffix=".tmp")
            with os.fdopen(fd, "w", encoding="utf-8") as fh:
                json.dump(self.data, fh)
            os.replace(tmp, self.path)
            self._dirty = False
        except OSError:
            pass  # read-only checkout: run uncached, never fail the lint
