"""Shared AST helpers: recognizing lock acquisitions and walking statement
bodies with the lexically-held lock stack."""

from __future__ import annotations

import ast
import re
from typing import Iterator, List, Optional, Tuple

#: An attribute/name is treated as a lock (or condition variable — acquiring
#: one acquires its underlying lock) when it matches this.  Covers the
#: runtime's ``_lock``/``_write_lock``/``_enroll_lock``/``_reject_lock``,
#: bare ``lock``, and the CV names ``_cv``/``_cond``/``_not_empty``.
LOCK_NAME_RE = re.compile(r"lock|mutex|(^|_)(cv|cond|not_empty)$")


def lock_attr_name(expr: ast.expr) -> Optional[str]:
    """The lock-ish terminal name of ``expr``, or None if it doesn't look
    like a lock."""
    if isinstance(expr, ast.Attribute):
        name = expr.attr
    elif isinstance(expr, ast.Name):
        name = expr.id
    else:
        return None
    return name if LOCK_NAME_RE.search(name) else None


def lock_base_is_self(expr: ast.expr) -> bool:
    return (isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id == "self")


def with_lock_items(node: ast.stmt) -> List[Tuple[ast.expr, str]]:
    """The ``(expr, lock_name)`` pairs of a With/AsyncWith statement's items
    that look like lock acquisitions (``with self._lock:``, ``with lock:``).
    Calls like ``with open(...)`` never match."""
    if not isinstance(node, (ast.With, ast.AsyncWith)):
        return []
    out = []
    for item in node.items:
        name = lock_attr_name(item.context_expr)
        if name is not None:
            out.append((item.context_expr, name))
    return out


def walk_with_lock_stack(body: List[ast.stmt],
                         stack: Tuple[str, ...] = (),
                         ) -> Iterator[Tuple[ast.AST, Tuple[str, ...]]]:
    """Yield ``(node, held_lock_names)`` for every expression-level node,
    tracking the lexical ``with <lock>`` nesting.  Nested function/lambda
    bodies restart with an empty stack — code defined under a lock does not
    *run* under it."""
    for stmt in body:
        yield from _walk_stmt(stmt, stack)


def _walk_stmt(node: ast.AST, stack: Tuple[str, ...]):
    if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
        yield node, stack
        yield from walk_with_lock_stack(node.body, ())
        return
    locks = with_lock_items(node) if isinstance(node, (ast.With, ast.AsyncWith)) else []
    if locks:
        yield node, stack
        inner = stack + tuple(name for _, name in locks)
        for child in node.body:
            yield from _walk_stmt(child, inner)
        return
    yield node, stack
    for child in ast.iter_child_nodes(node):
        if isinstance(child, ast.Lambda):
            yield child, stack
            yield from _walk_stmt(child.body, ())
        elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield from _walk_stmt(child, stack)
        elif isinstance(child, ast.stmt):
            yield from _walk_stmt(child, stack)
        else:
            yield from _walk_expr(child, stack)


def _walk_expr(node: ast.AST, stack: Tuple[str, ...]):
    yield node, stack
    for child in ast.iter_child_nodes(node):
        if isinstance(child, ast.Lambda):
            yield child, stack
            yield from _walk_stmt(child.body, ())
        else:
            yield from _walk_expr(child, stack)


def terminal_attr(expr: ast.expr) -> Optional[str]:
    """The terminal attribute/name of a receiver expression
    (``self.pipeline.gallery`` -> ``gallery``, ``gallery`` -> ``gallery``)
    — the ONE helper every wiring-based receiver test goes through."""
    if isinstance(expr, ast.Attribute):
        return expr.attr
    if isinstance(expr, ast.Name):
        return expr.id
    return None


def dotted_call_name(func: ast.expr) -> Optional[str]:
    """``a.b.c`` for an Attribute chain of Names, else None."""
    parts: List[str] = []
    cur = func
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
        return ".".join(reversed(parts))
    return None
