"""Acyclic exit-path enumeration over one function body (ocvf-lint v3).

The v1/v2 rules are *site* rules: a bad call is bad wherever it stands.
The protocol rules (settle-once, resource-pairing, fence-ordering) are
*path* properties — "every path from an acquire reaches a release",
"no path installs before the fence" — so they need to know which event
sequences a function can actually execute, not just which events exist.

This module is deliberately NOT a CFG solver.  It normalizes a function
body into a bounded set of acyclic exit paths under the same stdlib-ast
budget as the v2 dataflow layer:

- ``if``/``match`` fork; ``for``/``while`` bodies run zero-or-once (no
  back edges — a second iteration adds no *new* event orderings for the
  pairing rules, whose events are idempotent per path);
- ``while True`` runs once and exits only through ``break``/``return``;
  a body that would iterate again ends the path with the ``loop``
  terminal, which protocol checks skip (the path never reaches the
  function's exit);
- ``try`` bodies additionally fork *raising* edges: after the block
  entry and after every event-bearing top-level statement, control may
  jump into each handler (and, when no handler is catch-all, propagate
  out).  ``finally`` suffixes every outcome.  Raising edges are taken
  only at event boundaries — exceptions between two event-free
  statements cannot change a protocol verdict;
- simple constant propagation over local booleans/None prunes branches
  the runtime's flag idioms make infeasible (``accounted = True`` before
  the crash handler's ``if not accounted:``), and *optional-surface
  guards* (``if self.metrics:`` — observability objects that may be
  None by wiring) are taken as present, so a guarded ``incr`` still
  pairs with its unguarded settle span;
- enumeration is capped (``max_paths``); on overflow the caller gets
  ``truncated=True`` and should stay silent for that function
  (soundness of findings over completeness of coverage).

Checkers supply an ``extract(node)`` callback mapping statement-level
nodes to hashable *events* (tuples); the engine only orders them.  The
callback sees simple statements whole, ``if``/``while`` tests, ``for``
iterables, and ``with`` items — never nested function/lambda bodies
(use :func:`walk_events` to honor that rule inside the callback).
"""

from __future__ import annotations

import ast
import itertools
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence, Tuple

NEXT = "next"
RETURN = "return"
RAISE = "raise"
BREAK = "break"
CONTINUE = "continue"
LOOP = "loop"
FALL = "fall"

#: terminals on which a path truly reaches the function's normal exit —
#: balance/pairing checks that require "the function finished" test these.
NORMAL_TERMINALS = frozenset({RETURN, FALL})

#: hard ceiling on live states while enumerating one function.
_MAX_STATES = 32768


class ExitPath:
    """One acyclic way through a function: the ordered events it executes,
    how it leaves (``return``/``raise``/``fall``/``loop``), and the AST
    node it leaves at (None for implicit exits)."""

    __slots__ = ("events", "terminal", "end")

    def __init__(self, events: Tuple[Any, ...], terminal: str,
                 end: Optional[ast.AST]):
        self.events = events
        self.terminal = terminal
        self.end = end

    def __repr__(self) -> str:  # pragma: no cover — debugging aid
        return f"ExitPath({self.terminal}, {len(self.events)} events)"


def walk_events(node: ast.AST) -> Iterator[ast.AST]:
    """Source-ordered walk of ``node`` that does NOT descend into nested
    function/lambda bodies — code defined inside a statement does not run
    when the statement does.  Every extractor goes through this."""
    stack = [node]
    while stack:
        cur = stack.pop()
        yield cur
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda)):
            continue  # the def/lambda node itself was yielded; not its body
        stack.extend(reversed(list(ast.iter_child_nodes(cur))))


class _State:
    __slots__ = ("events", "env")

    def __init__(self, events: Tuple[Any, ...], env: Dict[str, Any]):
        self.events = events
        self.env = env

    def add(self, events: Sequence[Any]) -> "_State":
        if not events:
            return self
        return _State(self.events + tuple(events), self.env)

    def key(self) -> Tuple[Any, ...]:
        return (self.events,
                tuple(sorted(self.env.items(), key=lambda kv: kv[0])))


class _Truncated(Exception):
    pass


def _terminal_name(expr: ast.expr) -> Optional[str]:
    if isinstance(expr, ast.Attribute):
        return expr.attr
    if isinstance(expr, ast.Name):
        return expr.id
    return None


def _is_none(expr: ast.expr) -> bool:
    return isinstance(expr, ast.Constant) and expr.value is None


class _Enumerator:
    def __init__(self, extract: Callable[[ast.AST], Sequence[Any]],
                 optional_attrs: frozenset, max_paths: int):
        self.extract = extract
        self.optional = optional_attrs
        self.max_paths = max_paths
        self.states_made = 0

    # ---- branch-condition evaluation ----

    def _guard_value(self, test: ast.expr) -> Optional[bool]:
        """True/False when ``test`` is purely an optionality check on an
        optional-surface attribute (``if self.metrics:``, ``if tracer is
        not None:``) — those objects are modeled as wired, so the guarded
        code runs."""
        if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
            inner = self._guard_value(test.operand)
            return None if inner is None else not inner
        if isinstance(test, ast.Compare) and len(test.ops) == 1 \
                and len(test.comparators) == 1 \
                and _is_none(test.comparators[0]):
            name = _terminal_name(test.left)
            if name in self.optional:
                if isinstance(test.ops[0], ast.IsNot):
                    return True
                if isinstance(test.ops[0], ast.Is):
                    return False
            return None
        name = _terminal_name(test)
        if name in self.optional and not isinstance(test, ast.Call):
            return True
        return None

    def _test_value(self, test: ast.expr, env: Dict[str, Any]
                    ) -> Optional[bool]:
        if isinstance(test, ast.Constant):
            return bool(test.value)
        guard = self._guard_value(test)
        if guard is not None:
            return guard
        if isinstance(test, ast.Name):
            val = env.get(test.id, "?")
            if val is True or val == "T":
                return True
            if val is False or val is None or val == "F":
                return False
            return None
        if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
            inner = self._test_value(test.operand, env)
            return None if inner is None else not inner
        if isinstance(test, ast.Compare) and len(test.ops) == 1 \
                and len(test.comparators) == 1 \
                and _is_none(test.comparators[0]) \
                and isinstance(test.left, ast.Name):
            val = env.get(test.left.id, "?")
            if val is None:
                return isinstance(test.ops[0], ast.Is)
            if val is True or val is False or val == "T":
                # a known-bool / known-truthy value is never None
                return isinstance(test.ops[0], ast.IsNot)
            return None
        if isinstance(test, ast.BoolOp):
            vals = [self._test_value(v, env) for v in test.values]
            if isinstance(test.op, ast.And):
                if any(v is False for v in vals):
                    return False
                if all(v is True for v in vals):
                    return True
            else:
                if any(v is True for v in vals):
                    return True
                if all(v is False for v in vals):
                    return False
        return None

    # ---- statement walking ----

    def _bump(self, n: int = 1) -> None:
        self.states_made += n
        if self.states_made > _MAX_STATES:
            raise _Truncated()

    def block(self, stmts: Sequence[ast.stmt], states: List[_State]
              ) -> List[Tuple[_State, str, Optional[ast.AST]]]:
        out, _mids = self.block_collect(stmts, states)
        return out

    def block_collect(self, stmts: Sequence[ast.stmt], states: List[_State]
                      ) -> Tuple[List[Tuple[_State, str, Optional[ast.AST]]],
                                 List[_State]]:
        done: List[Tuple[_State, str, Optional[ast.AST]]] = []
        seen_done = set()
        live = list(states)
        mids: List[_State] = []
        seen_mid = set()
        for st in live:
            if st.key() not in seen_mid:
                seen_mid.add(st.key())
                mids.append(st)
        for stmt in stmts:
            if not live:
                break
            next_live: List[_State] = []
            seen_live = set()
            for st in live:
                for st2, term, node in self.stmt(stmt, st):
                    if term == NEXT:
                        # frontier dedup: states agreeing on (events, env)
                        # at the same program point have identical futures
                        # — keeping both only duplicates every downstream
                        # path (and blows the state budget exponentially).
                        k = st2.key()
                        if k not in seen_live:
                            seen_live.add(k)
                            next_live.append(st2)
                    else:
                        dk = (st2.key(), term, id(node))
                        if dk not in seen_done:
                            seen_done.add(dk)
                            done.append((st2, term, node))
            self._bump(len(next_live))
            live = next_live
            for st in live:
                k = st.key()
                if k not in seen_mid:
                    seen_mid.add(k)
                    mids.append(st)
        done.extend((st, NEXT, None) for st in live)
        return done, mids

    def stmt(self, node: ast.stmt, state: _State
             ) -> List[Tuple[_State, str, Optional[ast.AST]]]:
        if isinstance(node, ast.Return):
            return [(state.add(self.extract(node)), RETURN, node)]
        if isinstance(node, ast.Raise):
            return [(state.add(self.extract(node)), RAISE, node)]
        if isinstance(node, ast.Break):
            return [(state, BREAK, node)]
        if isinstance(node, ast.Continue):
            return [(state, CONTINUE, node)]
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Import, ast.ImportFrom,
                             ast.Global, ast.Nonlocal, ast.Pass)):
            return [(state, NEXT, None)]
        if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign,
                             ast.Expr, ast.Assert, ast.Delete)):
            ns = state.add(self.extract(node))
            env = self._env_after(node, ns.env)
            if env is not ns.env:
                ns = _State(ns.events, env)
            return [(ns, NEXT, None)]
        if isinstance(node, ast.If):
            return self._if(node, state)
        if isinstance(node, ast.While):
            return self._while(node, state)
        if isinstance(node, (ast.For, ast.AsyncFor)):
            return self._for(node, state)
        if isinstance(node, (ast.With, ast.AsyncWith)):
            evs: List[Any] = []
            for item in node.items:
                evs.extend(self.extract(item))
            return self.block(node.body, [state.add(evs)])
        if isinstance(node, ast.Try) or node.__class__.__name__ == "TryStar":
            return self._try(node, state)
        if isinstance(node, ast.Match):
            out: List[Tuple[_State, str, Optional[ast.AST]]] = []
            for case in node.cases:
                out.extend(self.block(case.body, [state]))
            out.append((state, NEXT, None))  # no case matched
            return out
        return [(state, NEXT, None)]

    def _env_after(self, node: ast.stmt, env: Dict[str, Any]
                   ) -> Dict[str, Any]:
        targets: List[ast.expr] = []
        value: Optional[ast.expr] = None
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        elif isinstance(node, ast.AugAssign):
            targets, value = [node.target], None
        names = []
        for tgt in targets:
            if isinstance(tgt, ast.Name):
                names.append(tgt.id)
            elif isinstance(tgt, (ast.Tuple, ast.List)):
                names.extend(e.id for e in tgt.elts
                             if isinstance(e, ast.Name))
        if not names:
            return env
        env = dict(env)
        const = (value.value if isinstance(value, ast.Constant)
                 and value.value in (True, False, None) else "?")
        for name in names:
            if const != "?" and len(names) == 1 \
                    and isinstance(node, ast.Assign) \
                    and all(isinstance(t, ast.Name) for t in node.targets):
                env[name] = const
            elif const != "?" and isinstance(node, ast.AnnAssign):
                env[name] = const
            else:
                env.pop(name, None)
        # chained `a = b = True` still sets every Name target
        if isinstance(node, ast.Assign) and const != "?":
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    env[tgt.id] = const
        return env

    def _if(self, node: ast.If, state: _State
            ) -> List[Tuple[_State, str, Optional[ast.AST]]]:
        ns = state.add(self.extract(node.test))
        val = self._test_value(node.test, ns.env)
        if val is True:
            return self.block(node.body, [ns])
        if val is False:
            return self.block(node.orelse, [ns])
        body_state = ns
        else_state = ns
        if isinstance(node.test, ast.Name):
            benv = dict(ns.env)
            benv[node.test.id] = "T"
            eenv = dict(ns.env)
            eenv[node.test.id] = "F"
            body_state = _State(ns.events, benv)
            else_state = _State(ns.events, eenv)
        self._bump()
        return (self.block(node.body, [body_state])
                + self.block(node.orelse, [else_state]))

    def _loop_exit(self, outcomes, after_orelse, node
                   ) -> List[Tuple[_State, str, Optional[ast.AST]]]:
        """Map one-iteration body outcomes to after-loop continuations."""
        out: List[Tuple[_State, str, Optional[ast.AST]]] = []
        for st, term, n in outcomes:
            if term == BREAK:
                out.append((st, NEXT, None))
            elif term in (NEXT, CONTINUE):
                out.extend(self.block(after_orelse, [st]))
        # RETURN / RAISE / LOOP propagate untouched
        out.extend((st, term, n) for st, term, n in outcomes
                   if term in (RETURN, RAISE, LOOP))
        return out

    def _while(self, node: ast.While, state: _State
               ) -> List[Tuple[_State, str, Optional[ast.AST]]]:
        ns = state.add(self.extract(node.test))
        infinite = isinstance(node.test, ast.Constant) and bool(node.test.value)
        body_out = self.block(node.body, [ns])
        out: List[Tuple[_State, str, Optional[ast.AST]]] = []
        for st, term, n in body_out:
            if term == BREAK:
                out.append((st, NEXT, None))
            elif term in (NEXT, CONTINUE):
                if infinite:
                    # would iterate again forever as far as this acyclic
                    # model can see: the path never reaches code below.
                    out.append((st, LOOP, None))
                else:
                    out.extend(self.block(node.orelse, [st]))
            else:
                out.append((st, term, n))
        if not infinite:
            out.extend(self.block(node.orelse, [ns]))  # zero iterations
        return out

    def _for(self, node, state: _State
             ) -> List[Tuple[_State, str, Optional[ast.AST]]]:
        ns = state.add(self.extract(node.iter))
        env = ns.env
        if isinstance(node.target, ast.Name) and node.target.id in env:
            env = dict(env)
            env.pop(node.target.id)
            ns = _State(ns.events, env)
        out = self._loop_exit(self.block(node.body, [ns]), node.orelse, node)
        nonempty = (isinstance(node.iter, ast.Name)
                    and ns.env.get(node.iter.id) in (True, "T"))
        if not nonempty:
            out.extend(self.block(node.orelse, [ns]))  # zero iterations
        return out

    def _try(self, node, state: _State
             ) -> List[Tuple[_State, str, Optional[ast.AST]]]:
        body_out, mids = self.block_collect(node.body, [state])

        continuing: List[Tuple[_State, str, Optional[ast.AST]]] = []
        raisers: List[Tuple[_State, Optional[ast.AST]]] = []
        seen_raise = set()

        def add_raiser(st: _State, n: Optional[ast.AST]) -> None:
            k = st.key()
            if k not in seen_raise:
                seen_raise.add(k)
                raisers.append((st, n))

        for st in mids:
            add_raiser(st, None)
        for st, term, n in body_out:
            if term == NEXT:
                if node.orelse:
                    continuing.extend(self.block(node.orelse, [st]))
                else:
                    continuing.append((st, NEXT, None))
            elif term == RAISE:
                add_raiser(st, n)
            else:
                continuing.append((st, term, n))

        handlers = list(getattr(node, "handlers", ()) or ())
        if handlers:
            catch_all = any(
                h.type is None
                or (_terminal_name(h.type) in ("Exception", "BaseException"))
                for h in handlers)
            for st, n in raisers:
                for h in handlers:
                    henv = dict(st.env)
                    if h.name:
                        henv.pop(h.name, None)
                    for st2, term2, n2 in self.block(
                            h.body, [_State(st.events, henv)]):
                        continuing.append((st2, term2, n2 if n2 is not None
                                           else (n2 or n or h)))
                if not catch_all:
                    continuing.append((st, RAISE, n))
        else:
            continuing.extend((st, RAISE, n) for st, n in raisers)

        if not getattr(node, "finalbody", None):
            return continuing
        out: List[Tuple[_State, str, Optional[ast.AST]]] = []
        seen_fin = set()
        for st, term, n in continuing:
            k = (st.key(), term)
            if k in seen_fin:
                continue
            seen_fin.add(k)
            for st2, term2, n2 in self.block(node.finalbody, [st]):
                if term2 == NEXT:
                    out.append((st2, term, n))
                else:  # a finally that returns/raises/breaks overrides
                    out.append((st2, term2, n2))
        return out


def enumerate_exit_paths(
        body: Sequence[ast.stmt],
        extract: Callable[[ast.AST], Sequence[Any]],
        optional_attrs: frozenset = frozenset(),
        max_paths: int = 512,
) -> Tuple[List[ExitPath], bool]:
    """All acyclic exit paths of ``body`` (a function's statement list).

    Returns ``(paths, truncated)``; when ``truncated`` is True the path
    set is partial (enumeration hit its budget) and callers should not
    report findings for this function."""
    enum = _Enumerator(extract, optional_attrs, max_paths)
    truncated = False
    try:
        outcomes = enum.block(body, [_State((), {})])
    except _Truncated:
        return [], True
    paths: List[ExitPath] = []
    seen = set()
    for st, term, n in outcomes:
        terminal = FALL if term in (NEXT, BREAK, CONTINUE) else term
        key = (st.events, terminal, id(n) if n is not None else 0)
        if key in seen:
            continue
        seen.add(key)
        paths.append(ExitPath(st.events, terminal, n))
        if len(paths) > max_paths:
            return paths, True
    return paths, truncated
