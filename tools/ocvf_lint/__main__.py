"""CLI: ``python -m tools.ocvf_lint [--json|--sarif] [--rules a,b]
[--baseline F [--update-baseline]] [--no-cache] PATH...``

Exit codes (stable, scripted against by scripts/run_lint.sh and CI):
  0 — clean (no findings; with --baseline: no count above its frozen limit)
  1 — findings reported (with --baseline: a rule regressed past its limit,
      or --update-baseline refused to grow a count)
  2 — internal error (bad invocation, crash in the linter itself)
"""

from __future__ import annotations

import argparse
import json
import sys
import traceback

from tools.ocvf_lint import core


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.ocvf_lint",
        description="AST-based concurrency, durability & JAX-dataflow lint "
                    "for the opencv_facerecognizer_tpu serving runtime.")
    parser.add_argument("paths", nargs="*", help="files or directories to lint")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable output on stdout")
    parser.add_argument("--sarif", action="store_true",
                        help="SARIF 2.1.0 output on stdout (CI annotations)")
    parser.add_argument("--rules", default=None,
                        help="comma-separated subset of rules to run")
    parser.add_argument("--list-rules", action="store_true",
                        help="print registered rules and exit 0")
    parser.add_argument("--baseline", default=None, metavar="FILE",
                        help="ratchet file (LINT_BASELINE.json): exit 0 while "
                             "every rule's finding count is <= its frozen "
                             "count; counts may only shrink")
    parser.add_argument("--update-baseline", action="store_true",
                        help="rewrite --baseline with current counts "
                             "(refuses to grow any count)")
    parser.add_argument("--baseline-allow-growth", action="store_true",
                        help="let --update-baseline raise a frozen count "
                             "(use only when landing a new rule)")
    parser.add_argument("--no-cache", action="store_true",
                        help="disable the incremental content-hash cache")
    parser.add_argument("--cache-dir", default=None, metavar="DIR",
                        help="cache directory (default: ./.ocvf_lint_cache)")
    args = parser.parse_args(argv)

    try:
        core._load_builtin_checkers()
        if args.list_rules:
            for rule in sorted(core.REGISTRY):
                print(f"{rule}: {core.REGISTRY[rule].description}")
            return 0
        if not args.paths:
            parser.error("no paths given (or use --list-rules)")
        if args.json and args.sarif:
            parser.error("--json and --sarif are mutually exclusive")
        if args.update_baseline and not args.baseline:
            parser.error("--update-baseline requires --baseline FILE")
        rules = [r.strip() for r in args.rules.split(",")] if args.rules else None
        if rules:
            unknown = [r for r in rules if r not in core.REGISTRY]
            if unknown:
                print(f"unknown rule(s): {', '.join(unknown)}", file=sys.stderr)
                return 2
        cache = None
        if not args.no_cache:
            from tools.ocvf_lint.cache import DEFAULT_CACHE_DIR, LintCache
            cache = LintCache(args.cache_dir or DEFAULT_CACHE_DIR)
        result = core.run(args.paths, rules=rules, cache=cache)

        baseline_rc = None
        baseline_notes = []
        if args.baseline:
            from tools.ocvf_lint import baseline as baseline_mod
            counts = result.rule_counts()
            if args.update_baseline:
                err = baseline_mod.update(
                    args.baseline, counts, list(result.rules),
                    allow_growth=args.baseline_allow_growth)
                if err:
                    print(f"ocvf-lint: {err}", file=sys.stderr)
                    return 1
                print(f"ocvf-lint: baseline written to {args.baseline}",
                      file=sys.stderr)
                return 0
            allowed = baseline_mod.load(args.baseline)
            regressions, improvements = baseline_mod.compare(counts, allowed)
            baseline_notes = [f"REGRESSION {r}" for r in regressions] + \
                             [f"note: {i}" for i in improvements]
            baseline_rc = 1 if regressions else 0
    except SystemExit:
        raise
    except FileNotFoundError as exc:
        print(f"ocvf-lint: {exc}", file=sys.stderr)
        return 2
    except Exception:  # noqa: BLE001 — any linter crash is exit 2 by contract
        traceback.print_exc()
        return 2

    if args.json:
        doc = result.to_dict()
        if args.baseline:
            doc["baseline"] = {"path": args.baseline,
                               "regressed": baseline_rc == 1,
                               "notes": baseline_notes}
        print(json.dumps(doc, indent=2, sort_keys=True))
    elif args.sarif:
        from tools.ocvf_lint.sarif import to_sarif
        print(json.dumps(to_sarif(result, core.REGISTRY), indent=2,
                         sort_keys=True))
    else:
        for finding in result.findings:
            print(finding.format())
            for path, line in finding.also:
                print(f"    also involves {path}:{line}")
        for note in baseline_notes:
            print(f"ocvf-lint: {note}", file=sys.stderr)
        cache_note = ""
        if result.cache.get("run_hit"):
            cache_note = "; cached run"
        print(f"ocvf-lint: {len(result.findings)} finding(s) in "
              f"{result.files_scanned} file(s) scanned "
              f"({result.suppressions_used} justified suppression(s) and "
              f"{result.boundaries_used} annotated boundary(ies) honored; "
              f"rules: {', '.join(result.rules)}{cache_note})",
              file=sys.stderr)
    if baseline_rc is not None:
        return baseline_rc
    return 1 if result.findings else 0


if __name__ == "__main__":
    sys.exit(main())
