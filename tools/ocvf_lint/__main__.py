"""CLI: ``python -m tools.ocvf_lint [--json] [--rules a,b] PATH...``

Exit codes (stable, scripted against by scripts/run_lint.sh and CI):
  0 — clean (no findings)
  1 — findings reported
  2 — internal error (bad invocation, crash in the linter itself)
"""

from __future__ import annotations

import argparse
import json
import sys
import traceback

from tools.ocvf_lint import core


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.ocvf_lint",
        description="AST-based concurrency & durability lint for the "
                    "opencv_facerecognizer_tpu serving runtime.")
    parser.add_argument("paths", nargs="*", help="files or directories to lint")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable output on stdout")
    parser.add_argument("--rules", default=None,
                        help="comma-separated subset of rules to run")
    parser.add_argument("--list-rules", action="store_true",
                        help="print registered rules and exit 0")
    args = parser.parse_args(argv)

    try:
        core._load_builtin_checkers()
        if args.list_rules:
            for rule in sorted(core.REGISTRY):
                print(f"{rule}: {core.REGISTRY[rule].description}")
            return 0
        if not args.paths:
            parser.error("no paths given (or use --list-rules)")
        rules = [r.strip() for r in args.rules.split(",")] if args.rules else None
        if rules:
            unknown = [r for r in rules if r not in core.REGISTRY]
            if unknown:
                print(f"unknown rule(s): {', '.join(unknown)}", file=sys.stderr)
                return 2
        result = core.run(args.paths, rules=rules)
    except SystemExit:
        raise
    except FileNotFoundError as exc:
        print(f"ocvf-lint: {exc}", file=sys.stderr)
        return 2
    except Exception:  # noqa: BLE001 — any linter crash is exit 2 by contract
        traceback.print_exc()
        return 2

    if args.json:
        print(json.dumps(result.to_dict(), indent=2, sort_keys=True))
    else:
        for finding in result.findings:
            print(finding.format())
            for path, line in finding.also:
                print(f"    also involves {path}:{line}")
        print(f"ocvf-lint: {len(result.findings)} finding(s) in "
              f"{result.files_scanned} file(s) scanned "
              f"({result.suppressions_used} justified suppression(s) honored; "
              f"rules: {', '.join(result.rules)})",
              file=sys.stderr)
    return 1 if result.findings else 0


if __name__ == "__main__":
    sys.exit(main())
