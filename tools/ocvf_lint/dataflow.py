"""Lightweight interprocedural dataflow on top of the stdlib-AST framework.

Two clients, one model:

- **jit tracing** (jit-recompile-hazard): find every function that jax traces
  (``jax.jit``/``jax.pmap`` call or decorator, including nested defs and
  lambdas), then walk each traced body — and the project-local functions it
  calls, resolved through the same known wiring the lock-order graph uses —
  tracking which values derive from traced arguments.  Python branching on a
  traced value, or host-materializing it (``np.*``, ``float()``, ``.item()``)
  inside the trace, is a finding.

- **host-sync taint** (host-sync): a module-set fixpoint that seeds device
  taint at dispatch sites (``recognize_batch_packed`` and friends, ``jnp.*``,
  anything assigned from ``jax.jit(...)``), propagates it through locals,
  tuple unpacking, attribute stores (``self._inflight.append((packed, ...))``)
  and resolved calls, and reports every synchronization sink it reaches.

Resolution is deliberately the same *kind* of heuristic PR 5 shipped:
``self.m()`` through the class and project-local bases, bare ``f()`` through
the module, ``x.attr.m()`` through ``wiring.ATTR_HINTS``, plus imported-module
aliases (``detector_mod.decode_detections``).  Bounded depth, memoized —
wrong answers are conservative (an unresolved call propagates taint; an
unknown callee is never walked)."""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Sequence, Set, Tuple

from tools.ocvf_lint import wiring

_CALL_DEPTH = 5
_FIXPOINT_ROUNDS = 12


# --------------------------------------------------------------------------
# model
# --------------------------------------------------------------------------


@dataclasses.dataclass
class FuncInfo:
    module: str
    path: str
    cls: Optional[str]
    name: str
    node: ast.AST  # FunctionDef | AsyncFunctionDef | Lambda
    params: Tuple[str, ...]

    @property
    def qual(self) -> str:
        return (f"{self.module}.{self.cls}.{self.name}" if self.cls
                else f"{self.module}.{self.name}")

    def body(self) -> List[ast.stmt]:
        body = self.node.body
        return body if isinstance(body, list) else [ast.Return(value=body)]


@dataclasses.dataclass
class ClassEntry:
    module: str
    name: str
    bases: Tuple[str, ...]
    methods: Dict[str, FuncInfo]


@dataclasses.dataclass
class JitRoot:
    fn: FuncInfo
    #: parameter names excluded from tracing (static_argnums/static_argnames)
    static: Tuple[str, ...]
    #: the jit-construction call/decorator site
    site: ast.AST


def _params_of(node: ast.AST) -> Tuple[str, ...]:
    args = node.args
    names = [a.arg for a in args.posonlyargs] + [a.arg for a in args.args]
    if args.vararg:
        names.append(args.vararg.arg)
    names += [a.arg for a in args.kwonlyargs]
    if args.kwarg:
        names.append(args.kwarg.arg)
    return tuple(names)


def _normalize_module(dotted: str) -> str:
    """``opencv_facerecognizer_tpu.models.detector`` -> ``models.detector``
    (the same last-3-components id ``core.module_name`` produces)."""
    parts = dotted.split(".")
    if "opencv_facerecognizer_tpu" in parts:
        parts = parts[parts.index("opencv_facerecognizer_tpu") + 1:]
    return ".".join(parts[-3:]) if parts else dotted


class ModuleInfo:
    def __init__(self, ctx) -> None:
        self.ctx = ctx
        self.functions: Dict[str, FuncInfo] = {}       # module-level defs
        self.all_funcs: List[FuncInfo] = []            # incl. methods/nested
        #: local alias -> normalized module id (``detector_mod`` ->
        #: ``models.detector``); only aliases of *modules* land here.
        self.mod_aliases: Dict[str, str] = {}
        self.np_aliases: Set[str] = set()
        self.jax_aliases: Set[str] = set()             # jax, jnp, lax
        #: names (attr or local) assigned from a jax.jit(...) result —
        #: calling them dispatches a compiled computation (device producer).
        self.jit_products: Set[str] = set()
        self.jit_roots: List[JitRoot] = []

    def collect_imports(self) -> None:
        for node in ast.walk(self.ctx.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    local = alias.asname or alias.name.split(".")[0]
                    dotted = alias.name
                    if dotted in ("numpy", "numpy.ma"):
                        self.np_aliases.add(local)
                    elif dotted == "jax" or dotted.startswith("jax."):
                        self.jax_aliases.add(local)
                    else:
                        self.mod_aliases[local] = _normalize_module(dotted)
            elif isinstance(node, ast.ImportFrom) and node.module:
                for alias in node.names:
                    local = alias.asname or alias.name
                    if node.module == "jax" and alias.name in ("numpy", "lax",
                                                               "random"):
                        self.jax_aliases.add(local)
                    elif node.module.startswith("jax"):
                        # from jax import jit / from jax.numpy import ...
                        if alias.name in ("jit", "pmap"):
                            self.jax_aliases.add(local)
                    elif alias.name[:1].islower():
                        # ``from pkg.sub import module as alias`` — treat as
                        # a module alias; resolution just misses otherwise.
                        self.mod_aliases[local] = _normalize_module(
                            node.module + "." + alias.name)


class ProjectModel:
    """Parsed-project index: functions, classes, imports, jit roots."""

    def __init__(self, contexts: Sequence) -> None:
        self.modules: Dict[str, ModuleInfo] = {}
        self.classes: Dict[str, List[ClassEntry]] = {}
        for ctx in contexts:
            mi = ModuleInfo(ctx)
            mi.collect_imports()
            self.modules[ctx.module] = mi
            self._collect_defs(mi)
        for mi in self.modules.values():
            self._collect_jit_roots(mi)

    # ---- collection ----

    def _collect_defs(self, mi: ModuleInfo) -> None:
        ctx = mi.ctx

        def visit(body, cls: Optional[str], scope: List[Dict[str, FuncInfo]],
                  top: bool) -> None:
            for stmt in body:
                if isinstance(stmt, ast.ClassDef) and top:
                    entry = ClassEntry(
                        module=ctx.module, name=stmt.name,
                        bases=tuple(b.id for b in stmt.bases
                                    if isinstance(b, ast.Name)),
                        methods={})
                    self.classes.setdefault(stmt.name, []).append(entry)
                    for sub in stmt.body:
                        if isinstance(sub, (ast.FunctionDef,
                                            ast.AsyncFunctionDef)):
                            fi = FuncInfo(ctx.module, ctx.path, stmt.name,
                                          sub.name, sub, _params_of(sub))
                            entry.methods[sub.name] = fi
                            mi.all_funcs.append(fi)
                            visit(sub.body, stmt.name, scope + [{}], False)
                elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    fi = FuncInfo(ctx.module, ctx.path, cls, stmt.name,
                                  stmt, _params_of(stmt))
                    mi.all_funcs.append(fi)
                    if top:
                        mi.functions[stmt.name] = fi
                    scope[-1][stmt.name] = fi
                    visit(stmt.body, cls, scope + [{}], False)
                else:
                    for child in ast.iter_child_nodes(stmt):
                        if isinstance(child, ast.stmt):
                            visit([child], cls, scope, top)

        visit(ctx.tree.body, None, [{}], True)

    # ---- jit roots ----

    def _jit_callee_kind(self, mi: ModuleInfo, func: ast.expr) -> Optional[str]:
        """'jit'/'pmap' when ``func`` is a jax jit/pmap reference."""
        if isinstance(func, ast.Attribute) and func.attr in ("jit", "pmap"):
            base = func.value
            if isinstance(base, ast.Name) and base.id in mi.jax_aliases:
                return func.attr
        if isinstance(func, ast.Name) and func.id in ("jit", "pmap") \
                and func.id in mi.jax_aliases:
            return func.id
        return None

    def _jit_call_info(self, mi: ModuleInfo, call: ast.Call
                       ) -> Optional[Tuple[ast.Call, List[ast.keyword]]]:
        """``jax.jit(...)`` -> (call, static kwargs); also unwraps
        ``functools.partial(jax.jit, static_argnames=...)``."""
        if self._jit_callee_kind(mi, call.func):
            return call, list(call.keywords)
        # functools.partial(jax.jit, ...)
        func = call.func
        is_partial = (isinstance(func, ast.Attribute) and func.attr == "partial") \
            or (isinstance(func, ast.Name) and func.id == "partial")
        if is_partial and call.args \
                and self._jit_callee_kind(mi, call.args[0]):
            return call, list(call.keywords)
        return None

    @staticmethod
    def _static_params(fn: FuncInfo, keywords: List[ast.keyword]
                       ) -> Tuple[str, ...]:
        static: List[str] = []

        def const_values(node):
            if isinstance(node, ast.Constant):
                return [node.value]
            if isinstance(node, (ast.Tuple, ast.List)):
                return [e.value for e in node.elts
                        if isinstance(e, ast.Constant)]
            return []

        for kw in keywords:
            if kw.arg == "static_argnames":
                static += [v for v in const_values(kw.value)
                           if isinstance(v, str)]
            elif kw.arg == "static_argnums":
                for v in const_values(kw.value):
                    if isinstance(v, int) and 0 <= v < len(fn.params):
                        static.append(fn.params[v])
        return tuple(static)

    def _collect_jit_roots(self, mi: ModuleInfo) -> None:
        ctx = mi.ctx

        # decorator form
        for fi in mi.all_funcs:
            node = fi.node
            for dec in getattr(node, "decorator_list", []):
                if self._jit_callee_kind(mi, dec):
                    mi.jit_roots.append(JitRoot(fi, (), dec))
                elif isinstance(dec, ast.Call):
                    info = self._jit_call_info(mi, dec)
                    if info is not None:
                        mi.jit_roots.append(
                            JitRoot(fi, self._static_params(fi, info[1]), dec))

        # call form: jax.jit(<ref>, ...) — resolve <ref> lexically
        def visit(body, scope: List[Dict[str, FuncInfo]]) -> None:
            local: Dict[str, FuncInfo] = {}
            chain = scope + [local]
            for stmt in body:
                for node in ast.walk(stmt):
                    if isinstance(node, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        fi = self._find_func(mi, node)
                        if fi is not None:
                            local[node.name] = fi
                    elif isinstance(node, ast.Call):
                        self._maybe_jit_root(mi, node, chain)

        visit(ctx.tree.body, [dict(mi.functions)])
        # assignment targets of jit products: x = jax.jit(...) /
        # self.y = jax.jit(...) — calling them later is a device dispatch.
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call) \
                    and self._jit_call_info(mi, node.value) is not None:
                for target in node.targets:
                    if isinstance(target, ast.Name):
                        mi.jit_products.add(target.id)
                    elif isinstance(target, ast.Attribute):
                        mi.jit_products.add(target.attr)

    def _find_func(self, mi: ModuleInfo, node: ast.AST) -> Optional[FuncInfo]:
        for fi in mi.all_funcs:
            if fi.node is node:
                return fi
        return None

    def _maybe_jit_root(self, mi: ModuleInfo, call: ast.Call,
                        scope: List[Dict[str, FuncInfo]]) -> None:
        info = self._jit_call_info(mi, call)
        if info is None:
            return
        _, keywords = info
        target = None
        if call.args:
            head = call.args[0]
            if self._jit_callee_kind(mi, head):
                # partial(jax.jit, ...): the wrapped fn arrives later (as a
                # decorator, handled above) — nothing to resolve here.
                return
            if isinstance(head, ast.Lambda):
                fi = FuncInfo(mi.ctx.module, mi.ctx.path, None, "<lambda>",
                              head, _params_of(head))
                mi.jit_roots.append(JitRoot(fi, self._static_params(fi, keywords),
                                            call))
                return
            if isinstance(head, ast.Name):
                for frame in reversed(scope):
                    if head.id in frame:
                        target = frame[head.id]
                        break
        if target is not None:
            mi.jit_roots.append(
                JitRoot(target, self._static_params(target, keywords), call))

    # ---- resolution ----

    def resolve_method(self, cls_name: str, method: str, module: str,
                       _seen=None) -> Optional[FuncInfo]:
        if _seen is None:
            _seen = set()
        if cls_name in _seen:
            return None
        _seen.add(cls_name)
        defs = sorted(self.classes.get(cls_name, []),
                      key=lambda c: c.module != module)
        for cdef in defs:
            if method in cdef.methods:
                return cdef.methods[method]
        for cdef in defs:
            for base in cdef.bases:
                found = self.resolve_method(base, method, module, _seen)
                if found is not None:
                    return found
        return None

    def resolve_call(self, call: ast.Call, caller: FuncInfo
                     ) -> Optional[FuncInfo]:
        func = call.func
        mi = self.modules.get(caller.module)
        if isinstance(func, ast.Name):
            if mi is not None and func.id in mi.functions:
                return mi.functions[func.id]
            return None
        if not isinstance(func, ast.Attribute):
            return None
        base = func.value
        if isinstance(base, ast.Name):
            if base.id == "self" and caller.cls is not None:
                return self.resolve_method(caller.cls, func.attr, caller.module)
            if mi is not None and base.id in mi.mod_aliases:
                target = self.modules.get(mi.mod_aliases[base.id])
                if target is not None:
                    return target.functions.get(func.attr)
            hint = wiring.ATTR_HINTS.get(base.id)
            if hint is not None:
                return self.resolve_method(hint, func.attr, caller.module)
            return None
        if isinstance(base, ast.Attribute):
            hint = wiring.ATTR_HINTS.get(base.attr)
            if hint is not None:
                return self.resolve_method(hint, func.attr, caller.module)
        return None


# --------------------------------------------------------------------------
# shared expression-taint machinery
# --------------------------------------------------------------------------


from tools.ocvf_lint.astutil import terminal_attr  # noqa: E402 — shared helper


class _Walker:
    """One function body, one taint environment, statement order.  Two
    passes per body so taint assigned late in a loop reaches uses earlier
    in it.  Subclasses define producer/sink policy."""

    def __init__(self, model: ProjectModel, fn: FuncInfo, env: Set[str]):
        self.model = model
        self.fn = fn
        self.env = set(env)
        self.mi = model.modules.get(fn.module)
        self.returns_tainted = False
        self.report: List[Tuple[ast.AST, str, str]] = []
        self.reporting = True

    # -- policy hooks --

    def call_taint(self, call: ast.Call, arg_tainted: bool) -> bool:
        raise NotImplementedError

    def on_branch(self, node: ast.AST) -> None:
        pass

    def store_attr(self, target_attr: str, is_self: bool, tainted: bool) -> None:
        pass

    def load_attr_tainted(self, node: ast.Attribute) -> bool:
        return False

    # -- engine --

    def run(self) -> None:
        # two passes: first silent (taint assigned late in a loop body must
        # reach uses textually earlier in it), second reporting
        self.reporting = False
        self._pass()
        self.reporting = True
        self.report = []
        self._pass()

    def _pass(self) -> None:
        for stmt in self.fn.body():
            self._stmt(stmt)

    def _stmt(self, node: ast.AST) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return  # nested defs run later; analyzed as their own entries
        if isinstance(node, ast.Assign):
            t = self._expr(node.value)
            for target in node.targets:
                self._assign(target, t)
            return
        if isinstance(node, ast.AugAssign):
            t = self._expr(node.value) or self._expr(node.target)
            self._assign(node.target, t)
            return
        if isinstance(node, ast.AnnAssign):
            t = self._expr(node.value) if node.value is not None else False
            self._assign(node.target, t)
            return
        if isinstance(node, (ast.If, ast.While)):
            if self._expr(node.test) and self.reporting:
                self.on_branch(node)
            for child in node.body + node.orelse:
                self._stmt(child)
            return
        if isinstance(node, ast.Assert):
            if self._expr(node.test) and self.reporting:
                self.on_branch(node)
            return
        if isinstance(node, ast.For):
            if self._expr(node.iter) and self.reporting:
                self.on_branch(node)
            self._assign(node.target, self._expr(node.iter))
            for child in node.body + node.orelse:
                self._stmt(child)
            return
        if isinstance(node, ast.Return):
            if node.value is not None and self._expr(node.value):
                self.returns_tainted = True
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                self._expr(item.context_expr)
                if item.optional_vars is not None:
                    self._assign(item.optional_vars, False)
            for child in node.body:
                self._stmt(child)
            return
        if isinstance(node, ast.Try):
            for child in (node.body + node.orelse + node.finalbody):
                self._stmt(child)
            for handler in node.handlers:
                for child in handler.body:
                    self._stmt(child)
            return
        if isinstance(node, ast.Expr):
            self._expr(node.value)
            return
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.stmt):
                self._stmt(child)
            elif isinstance(child, ast.expr):
                self._expr(child)

    def _assign(self, target: ast.expr, tainted: bool) -> None:
        if isinstance(target, ast.Name):
            if tainted:
                self.env.add(target.id)
            else:
                self.env.discard(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                inner = elt.value if isinstance(elt, ast.Starred) else elt
                self._assign(inner, tainted)
        elif isinstance(target, ast.Attribute):
            base = target.value
            is_self = isinstance(base, ast.Name) and base.id == "self"
            self.store_attr(target.attr, is_self, tainted)
        elif isinstance(target, ast.Subscript):
            self._expr(target.value)

    def _expr(self, node: Optional[ast.expr]) -> bool:
        if node is None:
            return False
        if isinstance(node, ast.Name):
            return node.id in self.env
        if isinstance(node, ast.Constant):
            return False
        if isinstance(node, ast.Attribute):
            if node.attr in wiring.STATIC_VALUE_ATTRS:
                self._expr(node.value)
                return False
            if self.load_attr_tainted(node):
                return True
            return self._expr(node.value)
        if isinstance(node, ast.Call):
            arg_tainted = False
            for arg in node.args:
                inner = arg.value if isinstance(arg, ast.Starred) else arg
                arg_tainted |= self._expr(inner)
            for kw in node.keywords:
                arg_tainted |= self._expr(kw.value)
            return self.call_taint(node, arg_tainted)
        if isinstance(node, ast.Lambda):
            return False
        if isinstance(node, ast.Subscript):
            # indexing: the CONTAINER's taint is the result's; a (possibly
            # tainted) index into a host container yields host data
            t = self._expr(node.value)
            self._expr(node.slice)
            return t
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp)):
            # the comprehension's value is its ELEMENTS — iterating a
            # tainted container of already-materialized elements is host
            for gen in node.generators:
                self._assign(gen.target, self._expr(gen.iter))
                for cond in gen.ifs:
                    self._expr(cond)
            return self._expr(node.elt)
        if isinstance(node, ast.DictComp):
            for gen in node.generators:
                self._assign(gen.target, self._expr(gen.iter))
                for cond in gen.ifs:
                    self._expr(cond)
            return self._expr(node.key) | self._expr(node.value)
        if isinstance(node, ast.IfExp):
            self._expr(node.test)
            return self._expr(node.body) | self._expr(node.orelse)
        tainted = False
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                tainted |= self._expr(child)
        return tainted

    def _is_np_call(self, call: ast.Call) -> bool:
        func = call.func
        return (isinstance(func, ast.Attribute)
                and isinstance(func.value, ast.Name)
                and self.mi is not None
                and func.value.id in self.mi.np_aliases)

    def _is_jaxish_call(self, call: ast.Call) -> bool:
        func = call.func
        cur = func
        while isinstance(cur, ast.Attribute):
            cur = cur.value
        return (isinstance(cur, ast.Name) and self.mi is not None
                and cur.id in self.mi.jax_aliases)


# --------------------------------------------------------------------------
# jit tracing (jit-recompile-hazard)
# --------------------------------------------------------------------------


class _TracedWalker(_Walker):
    """Inside a jax-traced body: params (minus statics) are tracers; any
    Python decision or host materialization on a tracer-derived value is a
    hazard."""

    def __init__(self, checker: "JitTraceChecker", fn: FuncInfo,
                 env: Set[str], depth: int):
        super().__init__(checker.model, fn, env)
        self.checker = checker
        self.depth = depth

    def on_branch(self, node: ast.AST) -> None:
        kind = ("assert" if isinstance(node, ast.Assert)
                else "loop" if isinstance(node, (ast.For, ast.While))
                else "branch")
        self.report.append((node, "branch",
                            f"Python {kind} on a traced value"))

    def call_taint(self, call: ast.Call, arg_tainted: bool) -> bool:
        func = call.func
        # len()/range() of a tracer are static Python under jit — shape
        # branching is the ladder's bread and butter, never a finding
        if isinstance(func, ast.Name) and func.id in wiring.HOST_BUILTIN_FUNCS:
            return False
        # host materialization sinks
        if isinstance(func, ast.Attribute) and func.attr in ("item", "tolist"):
            if self._expr(func.value):
                if self.reporting:
                    self.report.append((call, "materialize",
                                        f".{func.attr}() on a traced value"))
                return False
        if self._is_np_call(call):
            if arg_tainted and self.reporting:
                self.report.append((
                    call, "materialize",
                    f"numpy call {ast.unparse(func) if hasattr(ast, 'unparse') else func.attr}() "
                    f"on a traced value"))
            return False
        if isinstance(func, ast.Name) \
                and func.id in wiring.MATERIALIZE_NAME_FUNCS:
            if arg_tainted and self.reporting:
                self.report.append((call, "materialize",
                                    f"{func.id}() on a traced value"))
            return False
        if self._is_jaxish_call(call):
            return True  # any jax/jnp/lax op yields a tracer in-trace
        resolved = self.model.resolve_call(call, self.fn)
        if resolved is not None and self.reporting:
            return self.checker.check_callee(resolved, call, self)
        if isinstance(func, ast.Attribute) and self._expr(func.value):
            return True  # method on a tracer (x.astype, x.reshape, x.at[...])
        return arg_tainted


class JitTraceChecker:
    """Walks every jit root (and, transitively, resolved project callees
    whose arguments are traced) exactly once per distinct traced-param set."""

    def __init__(self, model: ProjectModel):
        self.model = model
        self.findings: List[Tuple[FuncInfo, ast.AST, str, str]] = []
        self._memo: Dict[Tuple[int, frozenset], bool] = {}

    def run(self) -> "JitTraceChecker":
        for mi in self.model.modules.values():
            for root in mi.jit_roots:
                traced = frozenset(p for p in root.fn.params
                                   if p not in root.static and p != "self")
                self._check(root.fn, traced, _CALL_DEPTH)
        return self

    def check_callee(self, callee: FuncInfo, call: ast.Call,
                     caller: _TracedWalker) -> bool:
        """Map per-argument taint onto the callee's params and recurse.
        Returns the callee's return-taint."""
        if caller.depth <= 0:
            return True  # conservatively a tracer
        params = list(callee.params)
        if params and params[0] == "self" \
                and isinstance(call.func, ast.Attribute):
            params = params[1:]
        traced: Set[str] = set()
        for i, arg in enumerate(call.args):
            inner = arg.value if isinstance(arg, ast.Starred) else arg
            if i < len(params) and caller._expr(inner):
                traced.add(params[i])
        for kw in call.keywords:
            if kw.arg is not None and kw.arg in callee.params \
                    and caller._expr(kw.value):
                traced.add(kw.arg)
        if not traced:
            return False  # nothing traced flows in; body runs on statics
        return self._check(callee, frozenset(traced), caller.depth - 1)

    def _check(self, fn: FuncInfo, traced: frozenset, depth: int) -> bool:
        key = (id(fn.node), traced)
        if key in self._memo:
            return self._memo[key]
        self._memo[key] = False  # cycle guard
        walker = _TracedWalker(self, fn, set(traced), depth)
        walker.run()
        for node, kind, detail in walker.report:
            self.findings.append((fn, node, kind, detail))
        self._memo[key] = walker.returns_tainted
        return walker.returns_tainted


# --------------------------------------------------------------------------
# host-sync taint (host-sync)
# --------------------------------------------------------------------------


class _HostSyncWalker(_Walker):
    def __init__(self, analysis: "HostSyncAnalysis", fn: FuncInfo,
                 env: Set[str]):
        super().__init__(analysis.model, fn, env)
        self.analysis = analysis

    def load_attr_tainted(self, node: ast.Attribute) -> bool:
        return node.attr in self.analysis.attr_taint

    def store_attr(self, attr: str, is_self: bool, tainted: bool) -> None:
        if tainted:
            self.analysis.taint_attr(attr)

    def call_taint(self, call: ast.Call, arg_tainted: bool) -> bool:
        func = call.func
        terminal = terminal_attr(func)
        # host-result probes and host builtins never carry device taint
        if isinstance(func, ast.Attribute) \
                and func.attr in wiring.HOST_RESULT_ATTRS:
            self._expr(func.value)
            return False
        if isinstance(func, ast.Name) and func.id in wiring.HOST_BUILTIN_FUNCS:
            return False
        # unconditional sync sinks: these calls exist only to wait on the
        # device (``.item()`` included — a scalar readback is a readback)
        if isinstance(func, ast.Attribute) and func.attr in wiring.SYNC_ATTRS:
            if self.reporting:
                self.report.append((call, "sync", f".{func.attr}()"))
            return False
        # numpy (or float/int/bool) applied to a device value IS the D2H
        # readback; its result is host data (taint stops here).
        if self._is_np_call(call):
            if arg_tainted and self.reporting:
                name = (ast.unparse(func) if hasattr(ast, "unparse")
                        else f"np.{func.attr}")
                self.report.append((call, "readback", f"{name}()"))
            return False
        if isinstance(func, ast.Name) \
                and func.id in wiring.MATERIALIZE_NAME_FUNCS:
            if arg_tainted and self.reporting:
                self.report.append((call, "readback", f"{func.id}()"))
            return False
        # device producers
        if terminal in wiring.DEVICE_PRODUCER_ATTRS:
            return True
        if terminal is not None and self.mi is not None \
                and terminal in self.mi.jit_products:
            return True
        if self._is_jaxish_call(call):
            return True
        # container stores: x.append(tainted) taints x
        if isinstance(func, ast.Attribute) \
                and func.attr in wiring.CONTAINER_STORE_METHODS and arg_tainted:
            recv = func.value
            if isinstance(recv, ast.Attribute):
                self.store_attr(recv.attr,
                                isinstance(recv.value, ast.Name)
                                and recv.value.id == "self", True)
            elif isinstance(recv, ast.Name):
                self.env.add(recv.id)
            return False
        # resolved project calls: propagate into params (fixpoint) and use
        # the callee's return taint; a callee OUTSIDE the analyzed module
        # set (e.g. ops.image.resize) degrades to the unresolved rule —
        # taint flows through, it is just not tracked inside
        resolved = self.model.resolve_call(call, self.fn)
        if resolved is not None:
            if resolved.qual not in self.analysis._quals:
                return arg_tainted
            if terminal not in ("recycle",):  # post-readback by contract
                params = list(resolved.params)
                if params and params[0] == "self" \
                        and isinstance(func, ast.Attribute):
                    params = params[1:]
                for i, arg in enumerate(call.args):
                    inner = arg.value if isinstance(arg, ast.Starred) else arg
                    if i < len(params) and self._expr(inner):
                        self.analysis.taint_param(resolved, params[i])
                for kw in call.keywords:
                    if kw.arg is not None and kw.arg in resolved.params \
                            and self._expr(kw.value):
                        self.analysis.taint_param(resolved, kw.arg)
            return self.analysis.ret_taint.get(resolved.qual, False)
        if isinstance(func, ast.Attribute) and self._expr(func.value):
            return True  # method on a device value stays on device
        return arg_tainted


class HostSyncAnalysis:
    """Module-set fixpoint: device taint from dispatch sites through locals,
    attributes and resolved calls, then one reporting pass over every sink."""

    def __init__(self, model: ProjectModel, module_names: Sequence[str]):
        self.model = model
        self.scope = [model.modules[m] for m in module_names
                      if m in model.modules]
        self.funcs: List[FuncInfo] = [fi for mi in self.scope
                                      for fi in mi.all_funcs]
        self._quals = {fi.qual for fi in self.funcs}
        self.param_taint: Dict[str, Set[str]] = {fi.qual: set()
                                                 for fi in self.funcs}
        self.ret_taint: Dict[str, bool] = {}
        self.attr_taint: Set[str] = set()
        self._changed = False

    def taint_param(self, fn: FuncInfo, param: str) -> None:
        if fn.qual in self._quals and param not in self.param_taint[fn.qual]:
            self.param_taint[fn.qual].add(param)
            self._changed = True

    def taint_attr(self, attr: str) -> None:
        if attr not in self.attr_taint:
            self.attr_taint.add(attr)
            self._changed = True

    def run(self) -> List[Tuple[FuncInfo, ast.AST, str, str]]:
        for _ in range(_FIXPOINT_ROUNDS):
            self._changed = False
            for fi in self.funcs:
                walker = _HostSyncWalker(self, fi,
                                         set(self.param_taint[fi.qual]))
                walker.reporting = False
                walker._pass()
                if walker.returns_tainted and not self.ret_taint.get(fi.qual):
                    self.ret_taint[fi.qual] = True
                    self._changed = True
            if not self._changed:
                break
        findings: List[Tuple[FuncInfo, ast.AST, str, str]] = []
        for fi in self.funcs:
            walker = _HostSyncWalker(self, fi, set(self.param_taint[fi.qual]))
            walker.reporting = True
            walker._pass()
            for node, kind, detail in walker.report:
                findings.append((fi, node, kind, detail))
        return findings
