"""The ONE known-wiring map of the serving stack, shared by every checker.

PR 5's lock-order rule carried its own ``ATTR_HINTS`` table; the v2 rules
(host-sync, jit-recompile-hazard, wal-before-mutate, epoch-pairing) all need
the same "what class does ``self.<attr>`` dispatch to" knowledge plus a few
scope sets of their own.  Keeping them per-checker would mean four slowly
diverging copies of the runtime's wiring — this module is the single source
of truth; checkers import, never redefine.
"""

from __future__ import annotations

from typing import Dict, FrozenSet, Tuple

#: Known wiring of ``self.<attr>`` (or any ``x.<attr>``) to the class whose
#: methods it dispatches to — the cross-module edges of the serving stack.
#: Used by lock-order call resolution AND the dataflow layer's
#: interprocedural call resolution.
ATTR_HINTS: Dict[str, str] = {
    "metrics": "Metrics",
    "tracer": "Tracer",
    "batcher": "FrameBatcher",
    "gallery": "ShardedGallery",
    "quantizer": "CoarseQuantizer",
    "journal": "DeadLetterJournal",
    "drop_log": "DeadLetterJournal",
    "wal": "EnrollmentWAL",
    "state": "StateLifecycle",
    "state_store": "StateLifecycle",
    "checkpoints": "CheckpointStore",
    "admission": "AdmissionController",
    "slo": "SLOMonitor",
    "connector": "JSONLConnector",
    "pipeline": "RecognitionPipeline",
    "replica": "ReadReplica",
    "router": "TopicRouter",
    "tailer": "WALTailer",
    "lease": "WriterLease",
    "rollout": "RolloutCoordinator",
    "stage": "ReEmbedStage",
    "parity": "DualScoreParity",
    # Ingest subsystem (PR 12): the service's ``self.ingest`` owns the
    # staging ring + decode pool; ``staging``/``staging_ring`` reach the
    # ring directly (the batcher holds it as ``_ring``), ``decoder`` is
    # the off-thread decode worker pool.
    "ingest": "IngestPipeline",
    "staging": "StagingRing",
    "staging_ring": "StagingRing",
    "decoder": "DecodeWorkerPool",
    # Cascade early-exit detection (ISSUE 13): the pipeline's
    # ``self.cascade`` is the stage-1 face-proposal model.
    "cascade": "FaceGate",
    # Degraded durability (ISSUE 15): the lifecycle's ``self.durability``
    # is the state machine whose probe thread owns the recovery tmp-file
    # write + fsync; ``span_sink`` is the tracer's JSONL journal (the
    # RotatingJournal base, with its own per-sink counters).
    "durability": "DurabilityMonitor",
    "span_sink": "RotatingJournal",
    # Partition tolerance (PR 16): link supervision and hedged dispatch
    # both live ON the router itself (per-replica state rides the
    # handles), so ``link``/``hedge`` attribute reads dispatch to
    # ``TopicRouter``; ``_faults`` is the shared injector whose transport
    # boundary the connector and router crossings call into (private
    # name on purpose — that is how every holder stores it).
    "link": "TopicRouter",
    "hedge": "TopicRouter",
    "_faults": "FaultInjector",
    # Temporal identity cache (ISSUE 17): the service's ``self.tracker``
    # is the per-replica track -> identity cache consulted on the
    # dispatch thread and updated on the readback worker.
    "tracker": "IdentityTracker",
    # Versioned model registry (ISSUE 18): ``self.registry`` is the durable
    # per-role version manifest every holder (lifecycle, service, replica)
    # consults; ``registry_swap`` is the live detector/cascade swap
    # coordinator whose parity window the readback worker feeds.
    "registry": "ModelRegistry",
    "registry_swap": "RegistrySwapCoordinator",
}

#: The serving hot path: the overlapped loop (PR 2) lives in these modules.
#: host-sync scans exactly these; a stray blocking readback anywhere else is
#: either offline tooling or already under blocking-under-lock.
HOT_PATH_SUFFIXES: Tuple[str, ...] = (
    "runtime/recognizer.py",
    "runtime/batcher.py",
    "runtime/ingest.py",
    "parallel/pipeline.py",
    # The stage-1 cascade's forward runs per serving batch (ISSUE 13):
    # a stray blocking sync in the model module would land on the
    # dispatch path, so it is scanned like the rest of the hot loop.
    "models/cascade.py",
    # The temporal identity cache (ISSUE 17) runs per serving batch on
    # the dispatch AND readback threads: pure host NumPy by contract —
    # any device sync sneaking in here would stall the serving loop.
    "runtime/tracker.py",
    # The model registry's live-parity window (ISSUE 18) is fed from the
    # readback worker (``offer_live`` per published batch): its scoring is
    # host-side box math by contract, so the module is scanned like the
    # rest of the hot loop.
    "runtime/registry.py",
)

#: Modules that OWN the epoch-pairing protocol (PR 6): only they may touch
#: the guarded fields directly; everyone else goes through
#: ``gallery.data`` + ``gallery._ivf_data(data)``.
EPOCH_OWNER_SUFFIXES: Tuple[str, ...] = (
    "parallel/gallery.py",
    "parallel/quantizer.py",
)

#: Attributes reserved for the epoch-checked snapshot protocol.  ``_epoch``
#: is the invalidation fence; ``_data`` is the atomically-published snapshot
#: slot (both the gallery's GalleryData and the quantizer's IVFDeviceData).
EPOCH_GUARDED_ATTRS: FrozenSet[str] = frozenset({"_epoch", "_data"})

#: Single-field gallery snapshot properties: each one is an independent
#: ``self._data`` read, so reading two of them non-atomically can pair
#: fields across a concurrent swap.  Outside the owner modules, more than
#: one of these per function is a pairing hazard.
GALLERY_FIELD_PROPS: FrozenSet[str] = frozenset({"embeddings", "labels", "valid"})

#: Receiver names that denote a ShardedGallery in the runtime's wiring
#: (``gallery.add(...)``, ``self.pipeline.gallery.add(...)``).
GALLERY_RECEIVERS: FrozenSet[str] = frozenset({"gallery"})

#: Receiver names that denote the enrollment WAL.  Direct writes to it
#: outside runtime/state_store.py bypass the lifecycle's sequencing lock.
WAL_RECEIVERS: FrozenSet[str] = frozenset({"wal"})

#: WAL methods that mutate durable state (reads — replay/verify — are fine).
WAL_WRITE_METHODS: FrozenSet[str] = frozenset({
    "append", "append_record", "truncate", "truncate_below", "rotate",
})

#: The durability layers whose gallery/WAL mutations ARE the sanctioned
#: path: state_store owns the _enroll_lock -> append_enrollment
#: sequencing, and replication's read replicas APPLY rows the writer
#: already WAL-sequenced and fsynced — write-ahead holds for every one of
#: their gallery.add calls by construction (the row was durable before
#: the replica could even see it), so flagging them would invert the
#: rule's own invariant.
WAL_EXEMPT_SUFFIXES: Tuple[str, ...] = (
    "runtime/state_store.py",
    "runtime/replication.py",
)

#: Calls whose result is a DEVICE value (taint seeds for host-sync):
#: terminal attribute names of producer calls in the serving runtime.
DEVICE_PRODUCER_ATTRS: FrozenSet[str] = frozenset({
    "recognize_batch", "recognize_batch_packed", "device_put",
    # Stage-1 cascade pass: its result is a device array whose ONE
    # sanctioned materialize is the serving gate's decision readback
    # (annotated boundary in runtime/recognizer.py).
    "cascade_scores", "score_batch",
})

#: Host-sync sinks that are flagged UNCONDITIONALLY in hot-path modules —
#: their only purpose is to synchronize with the device.
SYNC_ATTRS: FrozenSet[str] = frozenset({
    "block_until_ready", "device_get", "item",
})

#: Host-materialization calls that are findings only when their argument is
#: device-tainted (``np.asarray(host_frame)`` in the batcher is fine; the
#: same call on a dispatched batch IS the readback).
MATERIALIZE_NAME_FUNCS: FrozenSet[str] = frozenset({"float", "int", "bool"})
MATERIALIZE_NP_FUNCS: FrozenSet[str] = frozenset({
    "asarray", "array", "ascontiguousarray",
})

#: Attribute loads on a traced/device value that yield STATIC Python data
#: (shapes are compile-time constants under jit) — never taint through them.
STATIC_VALUE_ATTRS: FrozenSet[str] = frozenset({
    "shape", "ndim", "dtype", "size", "weak_type", "sharding",
})

#: Container mutators that store their argument into the receiver (taint
#: flows receiver <- argument).
CONTAINER_STORE_METHODS: FrozenSet[str] = frozenset({
    "append", "appendleft", "extend", "add", "insert", "put", "put_nowait",
})

#: Methods on a device value that return HOST data without blocking —
#: ``is_ready`` is the serving loop's designed non-blocking probe.
HOST_RESULT_ATTRS: FrozenSet[str] = frozenset({"is_ready"})

#: Builtins whose result is host data regardless of argument taint
#: (``range(count)``'s index must not taint every subscript it reaches).
HOST_BUILTIN_FUNCS: FrozenSet[str] = frozenset({
    "len", "range", "enumerate", "hasattr", "isinstance", "getattr", "id",
})


def path_matches(path: str, suffixes: Tuple[str, ...]) -> bool:
    norm = path.replace("\\", "/")
    return any(norm.endswith(s) for s in suffixes)
