"""The ONE known-wiring map of the serving stack, shared by every checker.

PR 5's lock-order rule carried its own ``ATTR_HINTS`` table; the v2 rules
(host-sync, jit-recompile-hazard, wal-before-mutate, epoch-pairing) all need
the same "what class does ``self.<attr>`` dispatch to" knowledge plus a few
scope sets of their own.  Keeping them per-checker would mean four slowly
diverging copies of the runtime's wiring — this module is the single source
of truth; checkers import, never redefine.
"""

from __future__ import annotations

from typing import Any, Dict, FrozenSet, Tuple

#: Known wiring of ``self.<attr>`` (or any ``x.<attr>``) to the class whose
#: methods it dispatches to — the cross-module edges of the serving stack.
#: Used by lock-order call resolution AND the dataflow layer's
#: interprocedural call resolution.
ATTR_HINTS: Dict[str, str] = {
    "metrics": "Metrics",
    "tracer": "Tracer",
    "batcher": "FrameBatcher",
    "gallery": "ShardedGallery",
    "quantizer": "CoarseQuantizer",
    "journal": "DeadLetterJournal",
    "drop_log": "DeadLetterJournal",
    "wal": "EnrollmentWAL",
    "state": "StateLifecycle",
    "state_store": "StateLifecycle",
    "checkpoints": "CheckpointStore",
    "admission": "AdmissionController",
    "slo": "SLOMonitor",
    "connector": "JSONLConnector",
    "pipeline": "RecognitionPipeline",
    "replica": "ReadReplica",
    "router": "TopicRouter",
    "tailer": "WALTailer",
    "lease": "WriterLease",
    "rollout": "RolloutCoordinator",
    "stage": "ReEmbedStage",
    "parity": "DualScoreParity",
    # Ingest subsystem (PR 12): the service's ``self.ingest`` owns the
    # staging ring + decode pool; ``staging``/``staging_ring`` reach the
    # ring directly (the batcher holds it as ``_ring``), ``decoder`` is
    # the off-thread decode worker pool.
    "ingest": "IngestPipeline",
    "staging": "StagingRing",
    "staging_ring": "StagingRing",
    "decoder": "DecodeWorkerPool",
    # Cascade early-exit detection (ISSUE 13): the pipeline's
    # ``self.cascade`` is the stage-1 face-proposal model.
    "cascade": "FaceGate",
    # Degraded durability (ISSUE 15): the lifecycle's ``self.durability``
    # is the state machine whose probe thread owns the recovery tmp-file
    # write + fsync; ``span_sink`` is the tracer's JSONL journal (the
    # RotatingJournal base, with its own per-sink counters).
    "durability": "DurabilityMonitor",
    "span_sink": "RotatingJournal",
    # Partition tolerance (PR 16): link supervision and hedged dispatch
    # both live ON the router itself (per-replica state rides the
    # handles), so ``link``/``hedge`` attribute reads dispatch to
    # ``TopicRouter``; ``_faults`` is the shared injector whose transport
    # boundary the connector and router crossings call into (private
    # name on purpose — that is how every holder stores it).
    "link": "TopicRouter",
    "hedge": "TopicRouter",
    "_faults": "FaultInjector",
    # Temporal identity cache (ISSUE 17): the service's ``self.tracker``
    # is the per-replica track -> identity cache consulted on the
    # dispatch thread and updated on the readback worker.
    "tracker": "IdentityTracker",
    # Versioned model registry (ISSUE 18): ``self.registry`` is the durable
    # per-role version manifest every holder (lifecycle, service, replica)
    # consults; ``registry_swap`` is the live detector/cascade swap
    # coordinator whose parity window the readback worker feeds.
    "registry": "ModelRegistry",
    "registry_swap": "RegistrySwapCoordinator",
    # Protocol rules (v3): the batcher holds the staging ring as
    # ``self._ring``; it stores its tracer privately as ``self._tracer``.
    "_ring": "StagingRing",
    "_tracer": "Tracer",
}

#: The serving hot path: the overlapped loop (PR 2) lives in these modules.
#: host-sync scans exactly these; a stray blocking readback anywhere else is
#: either offline tooling or already under blocking-under-lock.
HOT_PATH_SUFFIXES: Tuple[str, ...] = (
    "runtime/recognizer.py",
    "runtime/batcher.py",
    "runtime/ingest.py",
    "parallel/pipeline.py",
    # The stage-1 cascade's forward runs per serving batch (ISSUE 13):
    # a stray blocking sync in the model module would land on the
    # dispatch path, so it is scanned like the rest of the hot loop.
    "models/cascade.py",
    # The temporal identity cache (ISSUE 17) runs per serving batch on
    # the dispatch AND readback threads: pure host NumPy by contract —
    # any device sync sneaking in here would stall the serving loop.
    "runtime/tracker.py",
    # The model registry's live-parity window (ISSUE 18) is fed from the
    # readback worker (``offer_live`` per published batch): its scoring is
    # host-side box math by contract, so the module is scanned like the
    # rest of the hot loop.
    "runtime/registry.py",
)

#: Modules that OWN the epoch-pairing protocol (PR 6): only they may touch
#: the guarded fields directly; everyone else goes through
#: ``gallery.data`` + ``gallery._ivf_data(data)``.
EPOCH_OWNER_SUFFIXES: Tuple[str, ...] = (
    "parallel/gallery.py",
    "parallel/quantizer.py",
)

#: Attributes reserved for the epoch-checked snapshot protocol.  ``_epoch``
#: is the invalidation fence; ``_data`` is the atomically-published snapshot
#: slot (both the gallery's GalleryData and the quantizer's IVFDeviceData).
EPOCH_GUARDED_ATTRS: FrozenSet[str] = frozenset({"_epoch", "_data"})

#: Single-field gallery snapshot properties: each one is an independent
#: ``self._data`` read, so reading two of them non-atomically can pair
#: fields across a concurrent swap.  Outside the owner modules, more than
#: one of these per function is a pairing hazard.
GALLERY_FIELD_PROPS: FrozenSet[str] = frozenset({"embeddings", "labels", "valid"})

#: Receiver names that denote a ShardedGallery in the runtime's wiring
#: (``gallery.add(...)``, ``self.pipeline.gallery.add(...)``).
GALLERY_RECEIVERS: FrozenSet[str] = frozenset({"gallery"})

#: Receiver names that denote the enrollment WAL.  Direct writes to it
#: outside runtime/state_store.py bypass the lifecycle's sequencing lock.
WAL_RECEIVERS: FrozenSet[str] = frozenset({"wal"})

#: WAL methods that mutate durable state (reads — replay/verify — are fine).
WAL_WRITE_METHODS: FrozenSet[str] = frozenset({
    "append", "append_record", "truncate", "truncate_below", "rotate",
})

#: The durability layers whose gallery/WAL mutations ARE the sanctioned
#: path: state_store owns the _enroll_lock -> append_enrollment
#: sequencing, and replication's read replicas APPLY rows the writer
#: already WAL-sequenced and fsynced — write-ahead holds for every one of
#: their gallery.add calls by construction (the row was durable before
#: the replica could even see it), so flagging them would invert the
#: rule's own invariant.
WAL_EXEMPT_SUFFIXES: Tuple[str, ...] = (
    "runtime/state_store.py",
    "runtime/replication.py",
)

#: Calls whose result is a DEVICE value (taint seeds for host-sync):
#: terminal attribute names of producer calls in the serving runtime.
DEVICE_PRODUCER_ATTRS: FrozenSet[str] = frozenset({
    "recognize_batch", "recognize_batch_packed", "device_put",
    # Stage-1 cascade pass: its result is a device array whose ONE
    # sanctioned materialize is the serving gate's decision readback
    # (annotated boundary in runtime/recognizer.py).
    "cascade_scores", "score_batch",
})

#: Host-sync sinks that are flagged UNCONDITIONALLY in hot-path modules —
#: their only purpose is to synchronize with the device.
SYNC_ATTRS: FrozenSet[str] = frozenset({
    "block_until_ready", "device_get", "item",
})

#: Host-materialization calls that are findings only when their argument is
#: device-tainted (``np.asarray(host_frame)`` in the batcher is fine; the
#: same call on a dispatched batch IS the readback).
MATERIALIZE_NAME_FUNCS: FrozenSet[str] = frozenset({"float", "int", "bool"})
MATERIALIZE_NP_FUNCS: FrozenSet[str] = frozenset({
    "asarray", "array", "ascontiguousarray",
})

#: Attribute loads on a traced/device value that yield STATIC Python data
#: (shapes are compile-time constants under jit) — never taint through them.
STATIC_VALUE_ATTRS: FrozenSet[str] = frozenset({
    "shape", "ndim", "dtype", "size", "weak_type", "sharding",
})

#: Container mutators that store their argument into the receiver (taint
#: flows receiver <- argument).
CONTAINER_STORE_METHODS: FrozenSet[str] = frozenset({
    "append", "appendleft", "extend", "add", "insert", "put", "put_nowait",
})

#: Methods on a device value that return HOST data without blocking —
#: ``is_ready`` is the serving loop's designed non-blocking probe.
HOST_RESULT_ATTRS: FrozenSet[str] = frozenset({"is_ready"})

#: Builtins whose result is host data regardless of argument taint
#: (``range(count)``'s index must not taint every subscript it reaches).
HOST_BUILTIN_FUNCS: FrozenSet[str] = frozenset({
    "len", "range", "enumerate", "hasattr", "isinstance", "getattr", "id",
})


# --------------------------------------------------------------------------
# v3 protocol rules (exit-path settlement / resource pairing / fence order)
# --------------------------------------------------------------------------

#: Observability surfaces that may legitimately be None (``metrics=None``
#: stats-only mode, untraced runs).  The exit-path engine models them as
#: WIRED: ``if self.metrics:`` guards are taken, so a guarded terminal
#: ``incr`` still pairs with its unconditional settle span.  Path analysis
#: must see the fully-instrumented execution — the None configuration
#: executes a strict subset of it.
OPTIONAL_SURFACE_ATTRS: FrozenSet[str] = frozenset({
    "metrics", "tracer", "_tracer", "journal", "drop_log", "_drop_log",
    "slo", "span_sink", "durability",
})

#: Classes whose methods own the frame-settlement protocol: every terminal
#: ledger ``incr`` must ride with exactly one settle span of the same
#: status on every path (settle-once).
SETTLE_SCOPE_CLASSES: FrozenSet[str] = frozenset({
    "RecognizerService", "FrameBatcher",
})

#: Settlement sinks: method name -> (trace-basis arg index, status arg
#: index), counted from the call's own args (``self`` excluded).  The
#: recognizer settles runs of frames (``_trace_settle``); the batcher
#: settles one frame per drop (``_emit_settle``).
SETTLE_SINKS: Dict[str, Tuple[int, int]] = {
    "_trace_settle": (0, 1),
    "_emit_settle": (0, 1),
}

#: The one prefix family whose members are terminal ledger statuses
#: (``batcher_dropped_<reason>`` — both the counter and the settle outcome
#: are minted from it, so the pairing is checked symbolically).
LEDGER_PREFIX_CONSTANTS: FrozenSet[str] = frozenset({
    "BATCHER_DROPPED_PREFIX",
})

#: Acquire/release pairings the resource-pairing engine enforces.  Each
#: entry is pure data — a new paired resource is one more dict here:
#:
#: - kind "acquire-release": ``acquire_methods`` are (class, method) pairs
#:   resolved through ATTR_HINTS; the bound result must reach a call whose
#:   attr is in ``release_attrs`` (passed the buffer bare), be handed off
#:   bare into another call/container, or be returned, on EVERY path —
#:   including raising ones (the crash handler's forfeit is the point).
#: - kind "seq-burn": an assignment burning ``burn_attr`` must be followed
#:   on every path by a ``<release_receiver>.<release_attr_prefix>*`` call
#:   (the WAL record or its abort tombstone).
#: - kind "context": calls to the (class, method) pairs are contextmanagers
#:   and must be entered with ``with`` — a bare call leaks the span.
RESOURCE_PAIRINGS: Tuple[Dict[str, Any], ...] = (
    {
        "kind": "acquire-release",
        "name": "staging-buffer",
        "acquire_methods": (("StagingRing", "acquire"),),
        "release_attrs": ("recycle", "forfeit", "release"),
        "module_suffixes": ("runtime/batcher.py", "runtime/ingest.py",
                           "runtime/recognizer.py"),
        "what": "staging-ring buffer",
    },
    {
        "kind": "seq-burn",
        "name": "wal-seq",
        "burn_attr": "_wal_seq",
        "release_receiver": "wal",
        "release_attr_prefix": "append_",
        "module_suffixes": ("runtime/state_store.py",),
        "what": "burned WAL sequence number",
    },
    {
        "kind": "context",
        "name": "tracer-span",
        "context_methods": (("Tracer", "lifecycle"),),
        "module_suffixes": (),  # everywhere
        "what": "lifecycle span contextmanager",
    },
)

#: Modules that own the durable-swap fence protocol.
FENCE_MODULE_SUFFIXES: Tuple[str, ...] = (
    "runtime/state_store.py",
    "runtime/registry.py",
    "runtime/rollout.py",
)

#: Cutover scopes: functions implementing WAL-fence -> install.  Inside
#: them no install call may precede the fence append on any path.
FENCE_CUTOVER_FUNCS: FrozenSet[str] = frozenset({
    "perform_cutover", "perform_registry_cutover", "cutover",
})

#: The WAL fence records.
FENCE_APPEND_ATTRS: FrozenSet[str] = frozenset({
    "append_cutover", "append_registry_cutover",
})

#: Install calls fenced by them: the manifest write, the in-memory gallery
#: snapshot install, and the caller-supplied install hook.
FENCE_INSTALL_ATTRS: FrozenSet[str] = frozenset({
    "install", "load_snapshot",
})
FENCE_INSTALL_FN_NAMES: FrozenSet[str] = frozenset({"install_fn"})

#: Durable-install writers: these functions MUST write through the
#: ``atomic_write_*`` helpers (tmp+fsync+rename) and never a bare
#: ``open(..., "w")`` — a torn manifest/checkpoint is an unrecoverable
#: fence.
FENCE_DURABLE_WRITERS: Tuple[Tuple[str, str], ...] = (
    ("ModelRegistry", "_save_locked"),
    ("CheckpointStore", "save"),
)
ATOMIC_WRITE_PREFIX = "atomic_write_"

#: ledger-registry-coherence sites: where the terminal-status table from
#: utils/metric_names.py must be mirrored exactly.  Files absent from a
#: subset lint are skipped (run_lint.sh --changed).
COHERENCE_TRACING_SUFFIX = "utils/tracing.py"
COHERENCE_RECOGNIZER_SUFFIX = "runtime/recognizer.py"
COHERENCE_PROMTEXT_SUFFIX = "runtime/promtext.py"
COHERENCE_CHAOS_SUFFIX = "chaos_soak.py"


def path_matches(path: str, suffixes: Tuple[str, ...]) -> bool:
    norm = path.replace("\\", "/")
    return any(norm.endswith(s) for s in suffixes)
