"""host-sync: a device synchronization reachable from the serving loop,
outside an annotated readback boundary.

The overlapped pipeline (PR 2) earns its ~5.5x completed-frames win by
making the serving loop's device interaction fully asynchronous: dispatch
enqueues, the readback worker waits, and exactly ONE ``np.asarray`` per
batch materializes the packed result (each blocking sync costs ~100 ms on
the tunneled backend).  One stray ``.block_until_ready()``/``.item()``/
``np.asarray(device_value)`` anywhere in the hot path silently serializes
the whole overlap away again.

Device values are tracked by the shared dataflow layer: taint seeds at
dispatch sites (``recognize_batch_packed``, anything assigned from
``jax.jit(...)``, ``jnp.*``), flows through locals, tuple unpacking,
attribute stores (the in-flight deque) and resolved calls; ``np.*``/
``float()`` on a tainted value IS the readback (and stops the taint —
downstream host math is fine).  ``.block_until_ready()``, ``device_get``
and ``.item()`` are flagged wherever they appear in hot-path modules:
their only purpose is to synchronize.

The designed sync points — the sacrificial blocker thread, warmup,
prewarm (grow-worker thread), the single per-batch materialize, the
enrolment thread's embeds — carry
``# ocvf-lint: boundary=host-sync -- <why>`` annotations; that audit
trail is the rule's product."""

from __future__ import annotations

from typing import List

from tools.ocvf_lint import wiring
from tools.ocvf_lint.core import Checker, Finding, register


@register
class HostSyncChecker(Checker):
    rule = "host-sync"
    description = ("blocking device->host synchronization "
                   "(block_until_ready/device_get/.item()/np.asarray on a "
                   "device value) in the serving hot path outside annotated "
                   "readback boundaries")
    scope = "project"
    boundary_capable = True
    needs_dataflow = True

    def finalize(self) -> List[Finding]:
        if self.project is None:
            return []
        from tools.ocvf_lint import dataflow

        hot = [name for name, mi in self.project.modules.items()
               if wiring.path_matches(mi.ctx.path, wiring.HOT_PATH_SUFFIXES)]
        if not hot:
            return []
        analysis = dataflow.HostSyncAnalysis(self.project, hot)
        findings: List[Finding] = []
        for fn, node, kind, detail in analysis.run():
            if kind == "sync":
                message = (
                    f"{detail} in {fn.qual!r} blocks the serving hot path on "
                    f"the device (each sync costs ~100 ms on a tunneled "
                    f"backend and serializes the PR-2 overlap away); move it "
                    f"behind the readback worker, or annotate the designed "
                    f"boundary with '# ocvf-lint: boundary=host-sync -- "
                    f"<why this sync is the protocol>'")
            else:
                message = (
                    f"{detail} in {fn.qual!r} materializes a device value "
                    f"on the host — this IS a blocking readback; keep the "
                    f"serving loop to its one annotated per-batch "
                    f"materialize, or annotate this site as a host-sync "
                    f"boundary with justification")
            findings.append(Finding(self.rule, fn.path,
                                    getattr(node, "lineno", 1),
                                    getattr(node, "col_offset", 0), message))
        return findings
