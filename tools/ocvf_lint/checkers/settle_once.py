"""settle-once: inside the frame-settlement scopes (``RecognizerService``
and ``FrameBatcher``), every exit path that increments a TERMINAL admission-
ledger counter must reach exactly one settlement sink of the same status —
and no path may settle the same frame run twice.

The ledger invariant (``admitted == completed + completed_empty +
completed_cached + Σ drops``) has a span-level mirror: each admitted frame
emits exactly one terminal ``settle`` span whose outcome names the ledger
bucket it landed in (``tracing.account_spans`` reduces spans back to ledger
shape and chaos_soak asserts equality).  A terminal ``metrics.incr`` without
its settle span desynchronizes the two ledgers silently — the soak only
catches it hours later, under load, with the culprit long off-screen.  This
rule catches it at lint time, per exit path:

- events are paired on each path the exit-path engine enumerates
  (``tools.ocvf_lint.exitpaths``): balance is checked on paths that reach
  the function's normal exit (``return``/fall-through); raising paths are
  exempt from balance (a crash between two adjacent bookkeeping statements
  is the crash handler's job to settle) but double-settlement is flagged on
  EVERY path;
- statuses are matched through the source-of-truth tables in
  ``utils/metric_names.py`` (``LEDGER_COMPLETION_COUNTERS`` +
  ``LEDGER_DROP_COUNTERS``): a counter ``frames_<x>`` pairs with a settle
  outcome of either ``frames_<x>`` or ``<x>`` (the tracing-side
  ``OUTCOME_*`` mirror constants); the ``batcher_dropped_`` prefix family
  is paired symbolically (``PREFIX + reason`` on both sides);
- terminal-status hygiene: the settle outcome argument must be a
  ``metric_names`` constant, a ``tracing.OUTCOME_*`` mirror constant, or a
  registered ``*_PREFIX + suffix`` — a string literal or bare variable is
  drift waiting to happen and is flagged regardless of balance.

Functions whose path enumeration overflows the engine budget are skipped
(soundness of findings over completeness of coverage).  Designed
exceptions carry ``# ocvf-lint: boundary=settle-once -- why`` on the
path's exit statement."""

from __future__ import annotations

import ast
import os
from typing import Any, Dict, List, Optional, Set, Tuple

from tools.ocvf_lint import wiring
from tools.ocvf_lint.core import Checker, FileContext, Finding, register
from tools.ocvf_lint.exitpaths import (
    NORMAL_TERMINALS,
    enumerate_exit_paths,
    walk_events,
)

REGISTRY_SUFFIX = "utils/metric_names.py"
TRACING_SUFFIX = "utils/tracing.py"

#: source-of-truth tuple tables in utils/metric_names.py whose members are
#: the terminal ledger counters this rule pairs.
_TERMINAL_TABLES = ("LEDGER_COMPLETION_COUNTERS", "LEDGER_DROP_COUNTERS")


def _canon(value: str) -> str:
    """Counter value and settle outcome share a canonical key: the tracing
    mirror constants drop the ``frames_`` namespace (``frames_completed``
    settles as ``completed``)."""
    return value[7:] if value.startswith("frames_") else value


def _str_assigns(tree: ast.Module) -> Dict[str, str]:
    """Module-level ``NAME = "literal"`` constants."""
    out: Dict[str, str] = {}
    for stmt in tree.body:
        if (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                and isinstance(stmt.targets[0], ast.Name)
                and isinstance(stmt.value, ast.Constant)
                and isinstance(stmt.value.value, str)):
            out[stmt.targets[0].id] = stmt.value.value
    return out


def _tuple_tables(tree: ast.Module, consts: Dict[str, str]
                  ) -> Dict[str, List[str]]:
    """Module-level ``NAME = (A, B, ...)`` tables resolved to the string
    values of their Name elements."""
    out: Dict[str, List[str]] = {}
    for stmt in tree.body:
        if (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                and isinstance(stmt.targets[0], ast.Name)
                and isinstance(stmt.value, (ast.Tuple, ast.List))):
            values = [consts[e.id] for e in stmt.value.elts
                      if isinstance(e, ast.Name) and e.id in consts]
            out[stmt.targets[0].id] = values
    return out


class _Imports:
    """Local names referring to the metric_names / tracing modules (or to
    constants imported from them)."""

    def __init__(self, tree: ast.Module):
        self.mn_modules: Set[str] = set()
        self.mn_constants: Dict[str, str] = {}
        self.tr_modules: Set[str] = set()
        self.tr_constants: Dict[str, str] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom) and node.module:
                if node.module.endswith("metric_names"):
                    for alias in node.names:
                        self.mn_constants[alias.asname or alias.name] = alias.name
                elif node.module.endswith("tracing"):
                    for alias in node.names:
                        self.tr_constants[alias.asname or alias.name] = alias.name
                elif node.module.endswith("utils"):
                    for alias in node.names:
                        if alias.name == "metric_names":
                            self.mn_modules.add(alias.asname or alias.name)
                        elif alias.name == "tracing":
                            self.tr_modules.add(alias.asname or alias.name)
            elif isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.name.endswith("metric_names"):
                        self.mn_modules.add(alias.asname
                                            or alias.name.split(".")[0])
                    elif alias.name.endswith("tracing"):
                        self.tr_modules.add(alias.asname
                                            or alias.name.split(".")[0])


@register
class SettleOnceChecker(Checker):
    rule = "settle-once"
    description = ("every exit path incrementing a terminal ledger counter "
                   "in RecognizerService/FrameBatcher must reach exactly one "
                   "matching settle sink, and never two")
    scope = "project"  # verdicts depend on the metric_names/tracing tables
    boundary_capable = True

    def __init__(self) -> None:
        self._registry_tree: Optional[ast.Module] = None
        self._tracing_tree: Optional[ast.Module] = None
        #: (ctx, imports, class name, method FunctionDef)
        self._pending: List[Tuple[FileContext, _Imports, str, ast.AST]] = []

    # ---- collection ----

    def check_file(self, ctx: FileContext) -> List[Finding]:
        norm = ctx.path.replace("\\", "/")
        if norm.endswith(REGISTRY_SUFFIX):
            self._registry_tree = ctx.tree
        if norm.endswith(TRACING_SUFFIX):
            self._tracing_tree = ctx.tree
        imports: Optional[_Imports] = None
        for stmt in ctx.tree.body:
            if not (isinstance(stmt, ast.ClassDef)
                    and stmt.name in wiring.SETTLE_SCOPE_CLASSES):
                continue
            if imports is None:
                imports = _Imports(ctx.tree)
            for sub in stmt.body:
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    self._pending.append((ctx, imports, stmt.name, sub))
        return []

    # ---- out-of-tree inputs ----

    @staticmethod
    def _repo_file(*parts: str) -> str:
        here = os.path.dirname(os.path.abspath(__file__))
        repo_root = os.path.dirname(os.path.dirname(os.path.dirname(here)))
        return os.path.join(repo_root, "opencv_facerecognizer_tpu", *parts)

    def extra_cache_fingerprint(self, files) -> str:
        """The status tables are read from disk when the registry/tracing
        modules are not among the linted files — fold those fallback reads
        into the run-cache key (metrics-registry's invalidation pattern)."""
        import hashlib

        out = []
        for suffix, parts in ((REGISTRY_SUFFIX, ("utils", "metric_names.py")),
                              (TRACING_SUFFIX, ("utils", "tracing.py"))):
            if any(f.replace("\\", "/").endswith(suffix) for f in files):
                continue  # in-tree: content hash already in the key
            try:
                with open(self._repo_file(*parts), "rb") as fh:
                    out.append("settle-once:"
                               + hashlib.sha256(fh.read()).hexdigest())
            except OSError:
                out.append("settle-once:absent")
        return "".join(out)

    def _load_fallbacks(self) -> None:
        for attr, parts in (("_registry_tree", ("utils", "metric_names.py")),
                            ("_tracing_tree", ("utils", "tracing.py"))):
            if getattr(self, attr) is not None:
                continue
            candidate = self._repo_file(*parts)
            if os.path.exists(candidate):
                with open(candidate, "r", encoding="utf-8") as fh:
                    setattr(self, attr, ast.parse(fh.read()))

    # ---- status resolution ----

    def _build_tables(self) -> bool:
        self._load_fallbacks()
        if self._registry_tree is None:
            return False
        self._mn_consts = _str_assigns(self._registry_tree)
        tables = _tuple_tables(self._registry_tree, self._mn_consts)
        terminal: Set[str] = set()
        for name in _TERMINAL_TABLES:
            terminal.update(tables.get(name, ()))
        self._terminal_values = terminal
        self._terminal_prefixes = {
            self._mn_consts[name]
            for name in wiring.LEDGER_PREFIX_CONSTANTS
            if name in self._mn_consts}
        self._tr_consts = (_str_assigns(self._tracing_tree)
                           if self._tracing_tree is not None else {})
        return True

    def _const_value(self, expr: ast.expr, imports: _Imports
                     ) -> Optional[str]:
        """The string value of a metric_names / tracing constant reference,
        or None when ``expr`` is not one."""
        if isinstance(expr, ast.Attribute) and isinstance(expr.value, ast.Name):
            if expr.value.id in imports.mn_modules:
                return self._mn_consts.get(expr.attr)
            if expr.value.id in imports.tr_modules:
                return self._tr_consts.get(expr.attr)
        if isinstance(expr, ast.Name):
            original = imports.mn_constants.get(expr.id)
            if original is not None:
                return self._mn_consts.get(original)
            original = imports.tr_constants.get(expr.id)
            if original is not None:
                return self._tr_consts.get(original)
        return None

    def _incr_key(self, expr: ast.expr, imports: _Imports
                  ) -> Optional[Tuple[Any, ...]]:
        """Pairing key for a terminal-counter ``incr`` argument, or None
        when the counter is not terminal (non-terminal counters are outside
        this rule — metrics-registry already polices their names)."""
        value = self._const_value(expr, imports)
        if value is None and isinstance(expr, ast.Constant) \
                and isinstance(expr.value, str):
            value = expr.value
        if value is not None:
            return (("name", _canon(value))
                    if value in self._terminal_values else None)
        if isinstance(expr, ast.BinOp) and isinstance(expr.op, ast.Add):
            prefix = self._const_value(expr.left, imports)
            if prefix is None and isinstance(expr.left, ast.Constant) \
                    and isinstance(expr.left.value, str):
                prefix = expr.left.value
            if prefix in self._terminal_prefixes:
                return ("prefix", prefix, ast.dump(expr.right))
        return None

    def _settle_key(self, expr: ast.expr, imports: _Imports
                    ) -> Tuple[Tuple[Any, ...], Optional[str]]:
        """(pairing key, hygiene problem) for a settle outcome argument."""
        value = self._const_value(expr, imports)
        if value is not None:
            return ("name", _canon(value)), None
        if isinstance(expr, ast.BinOp) and isinstance(expr.op, ast.Add):
            prefix = self._const_value(expr.left, imports)
            if prefix in self._terminal_prefixes:
                return ("prefix", prefix, ast.dump(expr.right)), None
        if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
            return (("name", _canon(expr.value)),
                    f"terminal status is the string literal {expr.value!r} — "
                    f"thread a metric_names ledger constant (or its "
                    f"tracing OUTCOME_* mirror) through instead")
        return (("dyn", ast.dump(expr)),
                "terminal status is not statically resolvable to a "
                "metric_names / tracing constant — settle outcomes must "
                "come from the ledger's source-of-truth tables")

    # ---- per-method analysis ----

    def _events_for(self, node: ast.AST, imports: _Imports,
                    hygiene: List[Tuple[ast.AST, str]]) -> List[Tuple]:
        evs: List[Tuple] = []
        for sub in walk_events(node):
            if not (isinstance(sub, ast.Call)
                    and isinstance(sub.func, ast.Attribute)):
                continue
            attr = sub.func.attr
            if attr == "incr" and sub.args:
                key = self._incr_key(sub.args[0], imports)
                if key is not None:
                    evs.append(("incr", key, sub))
            elif attr in wiring.SETTLE_SINKS:
                basis_idx, status_idx = wiring.SETTLE_SINKS[attr]
                if len(sub.args) <= max(basis_idx, status_idx):
                    hygiene.append((sub, f"settlement sink {attr} called "
                                    f"without its trace-basis/status "
                                    f"arguments"))
                    continue
                key, problem = self._settle_key(sub.args[status_idx], imports)
                if problem is not None:
                    hygiene.append((sub, problem))
                evs.append(("settle", key,
                            ast.dump(sub.args[basis_idx]), sub))
        return evs

    def finalize(self) -> List[Finding]:
        if not self._pending:
            return []
        if not self._build_tables():
            ctx = self._pending[0][0]
            return [Finding(self.rule, ctx.path, 1, 0,
                            "no utils/metric_names.py registry found in the "
                            "scanned tree or the repository — terminal "
                            "statuses cannot be paired")]
        findings: List[Finding] = []
        for ctx, imports, cls, fn in self._pending:
            findings.extend(self._check_method(ctx, imports, cls, fn))
        return findings

    def _check_method(self, ctx: FileContext, imports: _Imports, cls: str,
                      fn: ast.AST) -> List[Finding]:
        hygiene: List[Tuple[ast.AST, str]] = []
        memo: Dict[int, List[Tuple]] = {}

        def extract(node: ast.AST) -> List[Tuple]:
            key = id(node)
            if key not in memo:
                memo[key] = self._events_for(node, imports, hygiene)
            return memo[key]

        paths, truncated = enumerate_exit_paths(
            fn.body, extract, optional_attrs=wiring.OPTIONAL_SURFACE_ATTRS)
        # Force one full extraction even when enumeration overflowed, so
        # hygiene findings (site properties, not path properties) survive.
        for stmt in ast.walk(fn):
            if isinstance(stmt, ast.stmt):
                extract(stmt)
        findings: List[Finding] = []
        seen_hyg = set()
        for node, problem in hygiene:
            if id(node) not in seen_hyg:
                seen_hyg.add(id(node))
                findings.append(ctx.finding(self.rule, node, problem))
        if truncated:
            return findings  # partial path set: stay silent on balance
        reported: Set[Tuple] = set()
        for path in paths:
            self._check_path(ctx, cls, fn, path, reported, findings)
        return findings

    def _check_path(self, ctx: FileContext, cls: str, fn: ast.AST, path,
                    reported: Set[Tuple], findings: List[Finding]) -> None:
        end_line = getattr(path.end, "lineno", None)
        also = ((ctx.path, end_line),) if end_line is not None else ()

        # double-settlement: the same trace basis settled twice with the
        # same status on one path — checked on EVERY path (a crash path
        # that settles twice is just as wrong as a normal one).
        seen_sig: Dict[Tuple, ast.AST] = {}
        for ev in path.events:
            if ev[0] != "settle":
                continue
            sig = (ev[1], ev[2])
            if sig in seen_sig:
                key = ("double", id(ev[3]))
                if key not in reported:
                    reported.add(key)
                    findings.append(ctx.finding(
                        self.rule, ev[3],
                        f"{cls}.{fn.name}: this path settles the same frame "
                        f"run twice with status {ev[1]!r} (first settlement "
                        f"at line {seen_sig[sig].lineno}) — every admitted "
                        f"frame settles exactly once", also=also))
            else:
                seen_sig[sig] = ev[3]

        if path.terminal not in NORMAL_TERMINALS:
            return  # raising/loop path: balance is the crash handler's job
        incrs: Dict[Tuple, List[ast.AST]] = {}
        settles: Dict[Tuple, List[ast.AST]] = {}
        for ev in path.events:
            if ev[0] == "incr":
                incrs.setdefault(ev[1], []).append(ev[2])
            else:
                settles.setdefault(ev[1], []).append(ev[3])
        for key, nodes in incrs.items():
            missing = len(nodes) - len(settles.get(key, ()))
            for node in nodes[:max(0, missing)]:
                fkey = ("unsettled", id(node))
                if fkey in reported:
                    continue
                reported.add(fkey)
                where = (f"line {end_line}" if end_line is not None
                         else "fall-through")
                findings.append(ctx.finding(
                    self.rule, node,
                    f"{cls}.{fn.name}: terminal ledger incr "
                    f"{self._key_str(key)} reaches the exit at {where} "
                    f"without a matching settle sink "
                    f"({'/'.join(sorted(wiring.SETTLE_SINKS))}) — the span "
                    f"ledger desynchronizes from the admission ledger",
                    also=also))
        for key, nodes in settles.items():
            extra = len(nodes) - len(incrs.get(key, ()))
            for node in nodes[:max(0, extra)]:
                fkey = ("orphan", id(node))
                if fkey in reported:
                    continue
                reported.add(fkey)
                findings.append(ctx.finding(
                    self.rule, node,
                    f"{cls}.{fn.name}: settle sink with status "
                    f"{self._key_str(key)} has no matching terminal ledger "
                    f"incr on this exit path — the span ledger counts a "
                    f"frame the admission ledger never will", also=also))

    @staticmethod
    def _key_str(key: Tuple) -> str:
        if key[0] == "name":
            return repr(key[1])
        if key[0] == "prefix":
            return f"{key[1]!r}+<reason>"
        return "<dynamic status>"
