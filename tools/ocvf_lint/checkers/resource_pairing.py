"""resource-pairing: generic acquire/release protocol engine, instantiated
from the declarative table in ``tools.ocvf_lint.wiring.RESOURCE_PAIRINGS``.

Three pairing disciplines ship today (adding a resource is a wiring edit,
not a checker edit — see README "declaring a new paired resource"):

- ``acquire-release`` (custody replay): a call like ``StagingRing.acquire``
  yields a buffer that must be discharged on every exit path — released
  through one of the declared release methods (``recycle``/``forfeit``/
  ``release``), handed off to another owner (passed into any call, stored
  into a container/attribute, or returned), or overwritten by a non-custody
  value.  Custody is tracked as a set of local alias names and replayed
  over every exit path the engine enumerates — INCLUDING raising paths,
  because leaking the staging buffer in a crash handler is exactly the bug
  this rule exists for (the ring leaks one slot per crash until admission
  wedges).
- ``seq-burn``: a WAL sequence number burned with the increment idiom
  (``self._wal_seq = self._wal_seq + 1``) must be released on every path
  by an ``append_*`` on the WAL (the record that justifies the burn, or an
  ``append_abort`` on failure).  A burned-but-unreleased sequence leaves a
  hole in the WAL that recovery must special-case forever.  Watermark
  seeding (``self._wal_seq = max(...)``) is not a burn and is ignored.
- ``context``: ``Tracer.lifecycle`` is a contextmanager; calling it
  anywhere but a ``with`` item produces a span that never closes.  This is
  a plain AST check, no path enumeration needed.

Functions whose path enumeration overflows the engine budget are skipped.
Designed exceptions (e.g. a fault-injection re-raise that intentionally
leaks a burned seq to exercise recovery) carry
``# ocvf-lint: boundary=resource-pairing -- why`` on the exiting statement."""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from tools.ocvf_lint import wiring
from tools.ocvf_lint.astutil import terminal_attr
from tools.ocvf_lint.core import Checker, FileContext, Finding, register
from tools.ocvf_lint.exitpaths import LOOP, enumerate_exit_paths, walk_events


def _names_in(expr: ast.expr) -> Set[str]:
    return {n.id for n in ast.walk(expr) if isinstance(n, ast.Name)}


def _call_arg_names(call: ast.Call) -> Set[str]:
    """Every Name appearing anywhere in a call's arguments (handoff is
    permissive: ``self._inflight.append((packed, frames, ...))`` discharges
    ``frames`` even though it is wrapped in a tuple)."""
    names: Set[str] = set()
    for arg in call.args:
        names |= _names_in(arg)
    for kw in call.keywords:
        names |= _names_in(kw.value)
    return names


@register
class ResourcePairingChecker(Checker):
    rule = "resource-pairing"
    description = ("acquired resources (staging buffers, burned WAL "
                   "sequence numbers, lifecycle spans) must be released, "
                   "handed off, or aborted on every exit path")
    boundary_capable = True

    # ---- pairing-table accessors ----

    def _pairings_for(self, path: str) -> List[dict]:
        out = []
        for pairing in wiring.RESOURCE_PAIRINGS:
            suffixes = pairing.get("module_suffixes", ())
            if suffixes and not wiring.path_matches(path, suffixes):
                continue
            out.append(pairing)
        return out

    @staticmethod
    def _matches_method(call: ast.Call,
                        methods: Tuple[Tuple[str, str], ...]) -> bool:
        if not isinstance(call.func, ast.Attribute):
            return False
        receiver = terminal_attr(call.func.value)
        hinted = wiring.ATTR_HINTS.get(receiver or "")
        return any(hinted == cls and call.func.attr == method
                   for cls, method in methods)

    def _is_acquire(self, call: ast.Call, pairing: dict) -> bool:
        return self._matches_method(call, pairing["acquire_methods"])

    # ---- entry point ----

    def check_file(self, ctx: FileContext) -> List[Finding]:
        pairings = self._pairings_for(ctx.path)
        if not pairings:
            return []
        findings: List[Finding] = []
        contexts = [p for p in pairings if p["kind"] == "context"]
        flows = [p for p in pairings if p["kind"] in
                 ("acquire-release", "seq-burn")]
        if contexts:
            findings.extend(self._check_contexts(ctx, contexts))
        if flows:
            for node in ast.walk(ctx.tree):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    findings.extend(self._check_function(ctx, node, flows))
        return findings

    # ---- context pairings (plain AST) ----

    def _check_contexts(self, ctx: FileContext,
                        pairings: Sequence[dict]) -> List[Finding]:
        with_items: Set[int] = set()
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    with_items.add(id(item.context_expr))
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call) or id(node) in with_items:
                continue
            for pairing in pairings:
                if self._matches_method(node, pairing["context_methods"]):
                    cls, method = pairing["context_methods"][0]
                    findings.append(ctx.finding(
                        self.rule, node,
                        f"{cls}.{method} is a contextmanager — call it as "
                        f"`with ....{method}(...):` or the "
                        f"{pairing['what']} opened here never closes"))
        return findings

    # ---- custody replay over exit paths ----

    def _check_function(self, ctx: FileContext, fn: ast.AST,
                        pairings: Sequence[dict]) -> List[Finding]:
        findings: List[Finding] = []
        relevant = [p for p in pairings
                    if self._has_events(fn, p, ctx, findings)]
        if not relevant:
            return findings
        memo: Dict[int, List[Tuple]] = {}

        def extract(node: ast.AST) -> List[Tuple]:
            key = id(node)
            if key not in memo:
                memo[key] = self._events_for(node, relevant)
            return memo[key]

        paths, truncated = enumerate_exit_paths(
            fn.body, extract, optional_attrs=wiring.OPTIONAL_SURFACE_ATTRS)
        if truncated:
            return findings
        reported: Set[Tuple] = set()
        for path in paths:
            if path.terminal == LOOP:
                continue  # body never exits; nothing escapes custody
            self._replay(ctx, fn, path, relevant, reported, findings)
        return findings

    def _has_events(self, fn: ast.AST, pairing: dict, ctx: FileContext,
                    findings: List[Finding]) -> bool:
        """Cheap pre-scan: does this function acquire/burn at all?  Also
        flags result-discarding acquires (custody dropped on the floor)."""
        found = False
        for stmt in ast.walk(fn):
            if pairing["kind"] == "acquire-release":
                if isinstance(stmt, ast.Call) \
                        and self._is_acquire(stmt, pairing):
                    found = True
                if isinstance(stmt, ast.Expr) \
                        and isinstance(stmt.value, ast.Call) \
                        and self._is_acquire(stmt.value, pairing):
                    cls, method = pairing["acquire_methods"][0]
                    findings.append(ctx.finding(
                        self.rule, stmt.value,
                        f"result of {cls}.{method} is discarded — the "
                        f"{pairing['what']} is acquired here but nothing "
                        f"holds it, so it can never be released"))
            elif pairing["kind"] == "seq-burn":
                if self._burn_node(stmt, pairing) is not None:
                    found = True
        return found

    @staticmethod
    def _burn_node(stmt: ast.AST, pairing: dict) -> Optional[ast.AST]:
        """A burn is the increment idiom only: an Assign whose value is a
        BinOp and whose targets include ``<obj>.<burn_attr>``.  Plain or
        ``max(...)`` assignments (watermark seeding during recovery) do not
        burn a sequence."""
        if not (isinstance(stmt, ast.Assign)
                and isinstance(stmt.value, ast.BinOp)):
            return None
        for target in stmt.targets:
            if isinstance(target, ast.Attribute) \
                    and target.attr == pairing["burn_attr"]:
                return stmt
        return None

    def _events_for(self, node: ast.AST,
                    pairings: Sequence[dict]) -> List[Tuple]:
        evs: List[Tuple] = []
        stmt = node
        # Acquire assignments: custody goes to the Name targets; an
        # Attribute/Subscript target is an immediate handoff into a
        # structure another owner manages.
        if isinstance(stmt, ast.Assign):
            acquire_of = None
            for pairing in pairings:
                if pairing["kind"] != "acquire-release":
                    continue
                for sub in walk_events(stmt.value):
                    if isinstance(sub, ast.Call) \
                            and self._is_acquire(sub, pairing):
                        acquire_of = (pairing, sub)
                        break
            if acquire_of is not None:
                pairing, call = acquire_of
                names = tuple(t.id for t in stmt.targets
                              if isinstance(t, ast.Name))
                handed_off = any(not isinstance(t, ast.Name)
                                 for t in stmt.targets)
                if names or not handed_off:
                    evs.append(("acq", pairing["name"], names, call))
                return evs
            for pairing in pairings:
                burn = self._burn_node(stmt, pairing) \
                    if pairing["kind"] == "seq-burn" else None
                if burn is not None:
                    evs.append(("burn", pairing["name"], burn))
                    return evs
            targets = tuple(t.id for t in stmt.targets
                            if isinstance(t, ast.Name))
            if targets:
                evs.append(("assign", targets,
                            frozenset(_names_in(stmt.value))))
            # fall through: calls inside the value are handoff candidates
        if isinstance(stmt, ast.Return) and stmt.value is not None:
            evs.append(("ret", frozenset(_names_in(stmt.value))))
            return evs
        for sub in walk_events(node):
            if not isinstance(sub, ast.Call):
                continue
            for pairing in pairings:
                if pairing["kind"] != "seq-burn":
                    continue
                if isinstance(sub.func, ast.Attribute) \
                        and sub.func.attr.startswith(
                            pairing["release_attr_prefix"]) \
                        and terminal_attr(sub.func.value) \
                        == pairing["release_receiver"]:
                    evs.append(("burnrel", pairing["name"]))
            names = _call_arg_names(sub)
            if names:
                evs.append(("call", frozenset(names)))
        return evs

    def _replay(self, ctx: FileContext, fn: ast.AST, path,
                pairings: Sequence[dict], reported: Set[Tuple],
                findings: List[Finding]) -> None:
        custody: Dict[str, Set[str]] = {}
        acq_node: Dict[str, ast.AST] = {}
        burned: Dict[str, ast.AST] = {}
        for ev in path.events:
            kind = ev[0]
            if kind == "acq":
                _, pname, names, node = ev
                custody[pname] = set(names)
                acq_node[pname] = node
            elif kind == "burn":
                burned[ev[1]] = ev[2]
            elif kind == "burnrel":
                burned.pop(ev[1], None)
            elif kind == "assign":
                _, targets, value_names = ev
                for pname, held in custody.items():
                    if held & value_names:
                        held.update(targets)  # alias propagation
                    else:
                        held.difference_update(targets)  # overwritten away
            elif kind in ("call", "ret"):
                names = ev[1]
                for held in custody.values():
                    if held & names:
                        held.clear()  # release or handoff
        end_line = getattr(path.end, "lineno", None)
        also = ((ctx.path, end_line),) if end_line is not None else ()
        where = (f"the exit at line {end_line}" if end_line is not None
                 else "function exit")
        by_name = {p["name"]: p for p in pairings}
        for pname, held in custody.items():
            if not held:
                continue
            node = acq_node[pname]
            key = ("leak", pname, id(node), end_line)
            if key in reported:
                continue
            reported.add(key)
            pairing = by_name[pname]
            findings.append(ctx.finding(
                self.rule, node,
                f"{fn.name}: {pairing['what']} acquired here "
                f"({'/'.join(sorted(held))}) is still held at {where} — "
                f"release it ({'/'.join(sorted(pairing['release_attrs']))}) "
                f"or hand it off on every path, including crash paths",
                also=also))
        for pname, node in burned.items():
            key = ("burn", pname, id(node), end_line)
            if key in reported:
                continue
            reported.add(key)
            pairing = by_name[pname]
            findings.append(ctx.finding(
                self.rule, node,
                f"{fn.name}: {pairing['what']} burned here reaches "
                f"{where} without a WAL "
                f"{pairing['release_attr_prefix']}* record — recovery sees "
                f"a hole in the sequence (append the record, or "
                f"append_abort on the failure path)", also=also))
