"""wal-before-mutate: persistent gallery/lifecycle mutations must ride the
``StateLifecycle._enroll_lock`` -> ``append_enrollment`` path.

PR 4's durability contract is *ack == durable*: an enrollment is
acknowledged only after its WAL record is fsynced, and the gallery
mutation happens as the ``apply_fn`` **inside** ``append_enrollment`` —
under the enroll lock, after the append — so a crash anywhere replays it
and a checkpoint can never snapshot unsequenced rows.  A bare
``gallery.add(...)`` (or a direct WAL write) anywhere else silently
reintroduces acknowledged-but-lost enrollments.

Sanctioned forms, in decreasing order of preference:

- ``state.append_enrollment(..., apply_fn=lambda: gallery.add(...))`` —
  the lambda is recognized and exempt;
- mutations inside ``runtime/state_store.py`` itself (replay/recovery);
- genuinely non-durable galleries (bench fixtures, offline builds, the
  explicit no-state-dir serving mode) annotated with
  ``# ocvf-lint: boundary=wal-before-mutate -- <why nothing durable is at
  stake>``."""

from __future__ import annotations

import ast
from typing import List, Tuple

from tools.ocvf_lint import wiring
from tools.ocvf_lint.astutil import terminal_attr as _receiver_terminal
from tools.ocvf_lint.core import Checker, FileContext, Finding, register


@register
class WalBeforeMutateChecker(Checker):
    rule = "wal-before-mutate"
    description = ("gallery/WAL mutations outside the StateLifecycle "
                   "_enroll_lock -> append_enrollment path")
    boundary_capable = True

    def check_file(self, ctx: FileContext) -> List[Finding]:
        if wiring.path_matches(ctx.path, wiring.WAL_EXEMPT_SUFFIXES):
            return []
        # spans of lambdas passed to append_enrollment(...) — the sanctioned
        # apply_fn route (any argument position; apply_fn= is the idiom)
        sanctioned: List[Tuple[int, int]] = []
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "append_enrollment"):
                continue
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                for sub in ast.walk(arg):
                    if isinstance(sub, ast.Lambda):
                        sanctioned.append((sub.lineno,
                                           getattr(sub, "end_lineno",
                                                   sub.lineno)))

        def in_sanctioned(line: int) -> bool:
            return any(a <= line <= b for a, b in sanctioned)

        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)):
                continue
            recv = _receiver_terminal(node.func.value)
            if node.func.attr == "add" \
                    and recv in wiring.GALLERY_RECEIVERS:
                if in_sanctioned(node.lineno):
                    continue
                findings.append(ctx.finding(
                    self.rule, node,
                    "gallery.add() outside the WAL-sequenced enrollment "
                    "path — a crash after this mutation loses rows no "
                    "replay can restore; route it through "
                    "state.append_enrollment(..., apply_fn=lambda: "
                    "gallery.add(...)), or annotate a genuinely "
                    "non-durable gallery with '# ocvf-lint: "
                    "boundary=wal-before-mutate -- <why>'"))
            elif node.func.attr in wiring.WAL_WRITE_METHODS \
                    and recv in wiring.WAL_RECEIVERS:
                findings.append(ctx.finding(
                    self.rule, node,
                    f"direct WAL write ({recv}.{node.func.attr}) outside "
                    f"runtime/state_store.py — WAL sequencing belongs to "
                    f"StateLifecycle under its _enroll_lock; a bare write "
                    f"can interleave with checkpoints and break replay "
                    f"dedup"))
        return findings
