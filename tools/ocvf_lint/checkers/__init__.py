"""Importing this package registers every built-in checker."""

from tools.ocvf_lint.checkers import (  # noqa: F401
    blocking_under_lock,
    lock_order,
    metrics_registry,
    non_atomic_write,
    swallowed_exception,
)
