"""Importing this package registers every built-in checker."""

from tools.ocvf_lint.checkers import (  # noqa: F401
    blocking_under_lock,
    epoch_pairing,
    fence_ordering,
    host_sync,
    jit_recompile_hazard,
    ledger_coherence,
    lock_order,
    metrics_registry,
    non_atomic_write,
    prng_discipline,
    resource_pairing,
    settle_once,
    swallowed_exception,
    wal_before_mutate,
)
