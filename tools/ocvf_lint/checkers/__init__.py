"""Importing this package registers every built-in checker."""

from tools.ocvf_lint.checkers import (  # noqa: F401
    blocking_under_lock,
    epoch_pairing,
    host_sync,
    jit_recompile_hazard,
    lock_order,
    metrics_registry,
    non_atomic_write,
    prng_discipline,
    swallowed_exception,
    wal_before_mutate,
)
