"""prng-discipline: jax.random key hygiene.

Two invariants, both load-bearing for the suite's reproducibility story
(seeded k-means in the quantizer, seeded augmentation in the embedder,
seed-logged chaos soaks whose failures must replay):

1. **No key reuse.**  Passing one PRNG key to two sampling calls makes the
   draws correlated (identical, for the same distribution) — the classic
   silent jax.random bug.  Every additional draw needs a ``split`` (or a
   distinct ``fold_in``).  ``split``/sampling each count as consuming the
   key; ``fold_in(key, n)`` derives and is always fine.  A sampling call
   inside a loop whose key was made outside (and is not re-split inside)
   is the same bug wearing a ``for`` statement.

2. **Deterministic seeds outside tests.**  A key seeded from wall-clock /
   os.urandom / np.random makes quantizer training, augmentation and soak
   schedules unreplayable; seeds must thread from configuration (the
   chaos soak logs its seed for exactly this reason)."""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set

from tools.ocvf_lint import astutil
from tools.ocvf_lint.core import Checker, FileContext, Finding, register

#: jax.random functions that DERIVE keys (never consume): safe any number
#: of times on the same parent key.
_DERIVE_FNS = frozenset({"fold_in", "key_data", "wrap_key_data", "clone"})
#: producers: their result IS a fresh key (assignment targets become keys)
_PRODUCER_FNS = frozenset({"PRNGKey", "key", "split", "fold_in"})

_NONDET_RE = re.compile(
    r"^(time\.(time|time_ns|monotonic|perf_counter)"
    r"|os\.urandom|os\.getpid"
    r"|secrets\.\w+|uuid\.uuid\w*"
    r"|datetime\.)")


def _np_aliases(tree: ast.Module) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name.split(".")[0] == "numpy":
                    out.add(alias.asname or "numpy")
    return out


def _random_fn(call: ast.Call, np_names: Set[str]) -> Optional[str]:
    """The jax.random function name for this call, or None.  Matches
    ``jax.random.X`` / ``random.X`` (``from jax import random``) /
    ``jrandom.X`` style dotted names while excluding numpy's ``np.random``
    namespace."""
    dotted = astutil.dotted_call_name(call.func)
    if dotted is None:
        return None
    parts = dotted.split(".")
    if parts[0] in np_names:
        return None
    if len(parts) >= 2 and parts[-2] in ("random", "jrandom"):
        return parts[-1]
    if len(parts) == 2 and parts[0] in ("jrandom", "jrand"):
        return parts[-1]
    return None


@register
class PrngDisciplineChecker(Checker):
    rule = "prng-discipline"
    description = ("jax.random key reused without split, and "
                   "nondeterministically-seeded keys outside tests")

    def check_file(self, ctx: FileContext) -> List[Finding]:
        self._np = _np_aliases(ctx.tree)
        self._in_tests = "tests" in ctx.path.replace("\\", "/").split("/")
        findings: List[Finding] = []
        self._scan_body(ctx, ctx.tree.body, findings)
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._scan_body(ctx, node.body, findings)
        return findings

    # ---- one scope (function or module body) ----

    def _scan_body(self, ctx, body, findings: List[Finding]) -> None:
        #: key var -> {"uses": int, "loop_depth": int}
        keys: Dict[str, Dict[str, int]] = {}
        self._walk(ctx, body, keys, findings, loop_depth=0,
                   loop_assigned=[])

    def _walk(self, ctx, body, keys, findings, loop_depth,
              loop_assigned) -> None:
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue  # scanned as its own scope
            if isinstance(stmt, (ast.For, ast.While)):
                assigned = {n.id for sub in ast.walk(stmt)
                            if isinstance(sub, ast.Name)
                            and isinstance(sub.ctx, ast.Store)
                            for n in [sub]}
                self._visit_exprs(ctx, stmt, keys, findings, loop_depth,
                                  loop_assigned, header_only=True)
                self._walk(ctx, stmt.body + stmt.orelse, keys, findings,
                           loop_depth + 1, loop_assigned + [assigned])
                continue
            if isinstance(stmt, ast.Assign):
                self._visit_exprs(ctx, stmt.value, keys, findings,
                                  loop_depth, loop_assigned)
                fn = (self._random_call_fn(stmt.value)
                      if isinstance(stmt.value, ast.Call) else None)
                is_key = fn in _PRODUCER_FNS
                for target in stmt.targets:
                    self._assign(target, is_key, keys, loop_depth)
                continue
            # generic statement: visit expressions once, recurse into bodies
            for field in ("test", "value", "iter", "exc"):
                sub = getattr(stmt, field, None)
                if isinstance(sub, ast.expr):
                    self._visit_exprs(ctx, sub, keys, findings, loop_depth,
                                      loop_assigned)
            for attr in ("body", "orelse", "finalbody"):
                sub = getattr(stmt, attr, None)
                if isinstance(sub, list):
                    self._walk(ctx, sub, keys, findings, loop_depth,
                               loop_assigned)
            for handler in getattr(stmt, "handlers", []):
                self._walk(ctx, handler.body, keys, findings, loop_depth,
                           loop_assigned)
            for item in getattr(stmt, "items", []):
                self._visit_exprs(ctx, item.context_expr, keys, findings,
                                  loop_depth, loop_assigned)

    def _assign(self, target, is_key: bool, keys, loop_depth: int) -> None:
        if isinstance(target, ast.Name):
            if is_key:
                keys[target.id] = {"uses": 0, "loop_depth": loop_depth}
            else:
                keys.pop(target.id, None)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._assign(elt, is_key, keys, loop_depth)

    def _random_call_fn(self, call: ast.Call) -> Optional[str]:
        return _random_fn(call, self._np)

    def _visit_exprs(self, ctx, node, keys, findings, loop_depth,
                     loop_assigned, header_only=False) -> None:
        it = ([getattr(node, "iter", None), getattr(node, "test", None)]
              if header_only and isinstance(node, (ast.For, ast.While))
              else [node])
        for root in it:
            if not isinstance(root, ast.AST):
                continue
            for sub in ast.walk(root):
                if not isinstance(sub, ast.Call):
                    continue
                fn = self._random_call_fn(sub)
                if fn is None:
                    continue
                if fn in ("PRNGKey", "key"):
                    self._check_seed(ctx, sub, findings)
                    continue
                if fn in _DERIVE_FNS:
                    continue
                # sampling or split: consumes its first-arg key
                if not sub.args or not isinstance(sub.args[0], ast.Name):
                    continue
                name = sub.args[0].id
                state = keys.get(name)
                if state is None:
                    continue
                reassigned_in_loop = any(name in assigned
                                         for assigned in loop_assigned)
                if state["uses"] >= 1:
                    findings.append(ctx.finding(
                        self.rule, sub,
                        f"PRNG key {name!r} is consumed again by "
                        f"jax.random.{fn} without an intervening split — "
                        f"correlated draws; split (or fold_in) a fresh key "
                        f"per sampling call"))
                elif (loop_depth > state["loop_depth"]
                        and not reassigned_in_loop):
                    findings.append(ctx.finding(
                        self.rule, sub,
                        f"PRNG key {name!r} (created outside this loop) is "
                        f"consumed by jax.random.{fn} every iteration — "
                        f"identical draws per pass; split inside the loop"))
                state["uses"] += 1

    def _check_seed(self, ctx, call: ast.Call, findings) -> None:
        if self._in_tests:
            return
        for arg in list(call.args) + [kw.value for kw in call.keywords]:
            for sub in ast.walk(arg):
                if not isinstance(sub, ast.Call):
                    continue
                dotted = astutil.dotted_call_name(sub.func) or ""
                parts = dotted.split(".")
                nondet = bool(_NONDET_RE.match(dotted)) or (
                    len(parts) >= 2 and parts[0] in self._np
                    and parts[1] == "random")
                if nondet:
                    findings.append(ctx.finding(
                        self.rule, call,
                        f"PRNG key seeded from {dotted}() — "
                        f"nondeterministic seeds make quantizer builds / "
                        f"augmentation / soak schedules unreplayable; "
                        f"thread a logged seed from configuration instead "
                        f"(tests are exempt)"))
                    return
