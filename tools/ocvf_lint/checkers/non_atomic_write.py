"""non-atomic-write: opening a file for (truncating) write outside the
designated durability layers.

A bare ``open(path, "w")`` + write can leave a torn half-file after a crash
— the seed's checkpointing bug.  All durable state must flow through
``utils/serialization.py`` (``atomic_write_bytes``/``atomic_write_text``,
tmp+fsync+rename) or ``runtime/state_store.py``; those two files are the
only ones allowed to open for write.  Deliberate non-durable writes (a
chaos script injecting corruption, a throwaway debug dump) carry justified
suppressions."""

from __future__ import annotations

import ast
from typing import List, Optional

from tools.ocvf_lint.core import Checker, FileContext, Finding, register

#: The durability layers themselves — the helpers everyone else must use.
EXEMPT_SUFFIXES = (
    "utils/serialization.py",
    "runtime/state_store.py",
)

WRITE_ATTRS = frozenset({"write_text", "write_bytes"})


def _write_mode(call: ast.Call) -> Optional[str]:
    """The mode string if this ``open()`` call truncates/creates, else None.
    Append mode ('a') is journal-style and exempt by design."""
    mode_node: Optional[ast.expr] = None
    if len(call.args) >= 2:
        mode_node = call.args[1]
    for kw in call.keywords:
        if kw.arg == "mode":
            mode_node = kw.value
    if mode_node is None:
        return None  # default 'r'
    if isinstance(mode_node, ast.Constant) and isinstance(mode_node.value, str):
        mode = mode_node.value
        if "w" in mode or "x" in mode:
            return mode
        return None
    return "<dynamic>"  # non-literal mode: flag it, prove it or suppress


@register
class NonAtomicWriteChecker(Checker):
    rule = "non-atomic-write"
    description = ("open(..., 'w')-style truncating writes outside the "
                   "atomic tmp+fsync+rename helpers in utils/serialization "
                   "and runtime/state_store")

    def check_file(self, ctx: FileContext) -> List[Finding]:
        norm = ctx.path.replace("\\", "/")
        if any(norm.endswith(suffix) for suffix in EXEMPT_SUFFIXES):
            return []
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if isinstance(func, ast.Name) and func.id == "open":
                mode = _write_mode(node)
                if mode is not None:
                    findings.append(ctx.finding(
                        self.rule, node,
                        f"open(..., {mode!r}) writes non-atomically — a crash "
                        f"can leave a torn file; use "
                        f"utils.serialization.atomic_write_bytes/"
                        f"atomic_write_text (or suppress with justification "
                        f"if a torn file is genuinely harmless)"))
            elif isinstance(func, ast.Attribute) and func.attr in WRITE_ATTRS:
                findings.append(ctx.finding(
                    self.rule, node,
                    f"Path.{func.attr}() writes non-atomically — use "
                    f"utils.serialization.atomic_write_bytes/atomic_write_text"))
        return findings
