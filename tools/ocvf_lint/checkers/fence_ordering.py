"""fence-ordering: model/registry cutovers must append the WAL fence record
BEFORE installing anything into live state, on every exit path.

The cutover protocol (state_store / registry / rollout) is: append the
cutover fence to the WAL (the durable declaration "a swap is happening"),
then install the new snapshot/detector into the live gallery or registry.
If the process crashes between the two, recovery replays the fence and
re-drives the install — the swap is exactly-once.  Inverting the order
breaks that: an install that lands before the fence is invisible to
recovery, so a crash in the window leaves live state ahead of the WAL and
the next replay serves stale identities against a new detector.

Two checks:

- path ordering: inside the designated cutover functions
  (``wiring.FENCE_CUTOVER_FUNCS``) in fence-bearing modules, no exit path
  may execute an install call (``install``/``load_snapshot``/a designated
  installer callback) before the fence append
  (``wiring.FENCE_APPEND_ATTRS``).  Raising paths count — installing and
  THEN crashing before the fence is precisely the broken window.
- durable writers: the methods that persist registry/checkpoint bytes
  (``wiring.FENCE_DURABLE_WRITERS``) must go through an ``atomic_write_*``
  helper and never a bare ``open(..., "w")`` — a torn registry file turns
  every later cutover into a parse error at recovery time.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set, Tuple

from tools.ocvf_lint import wiring
from tools.ocvf_lint.core import Checker, FileContext, Finding, register
from tools.ocvf_lint.exitpaths import enumerate_exit_paths, walk_events

_WRITE_MODES = ("w", "a", "x")


@register
class FenceOrderingChecker(Checker):
    rule = "fence-ordering"
    description = ("cutover functions must append the WAL fence before any "
                   "install; durable registry writers must use "
                   "atomic_write_* helpers")
    boundary_capable = True

    def check_file(self, ctx: FileContext) -> List[Finding]:
        if not wiring.path_matches(ctx.path, wiring.FENCE_MODULE_SUFFIXES):
            return []
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node.name in wiring.FENCE_CUTOVER_FUNCS:
                findings.extend(self._check_cutover(ctx, node))
        findings.extend(self._check_durable_writers(ctx))
        return findings

    # ---- path ordering ----

    @staticmethod
    def _classify(call: ast.Call) -> str:
        if isinstance(call.func, ast.Attribute):
            if call.func.attr in wiring.FENCE_APPEND_ATTRS:
                return "fence"
            if call.func.attr in wiring.FENCE_INSTALL_ATTRS:
                return "install"
        elif isinstance(call.func, ast.Name) \
                and call.func.id in wiring.FENCE_INSTALL_FN_NAMES:
            return "install"
        return ""

    def _check_cutover(self, ctx: FileContext, fn: ast.AST) -> List[Finding]:
        memo: Dict[int, List[Tuple]] = {}

        def extract(node: ast.AST) -> List[Tuple]:
            key = id(node)
            if key not in memo:
                evs = []
                for sub in walk_events(node):
                    if isinstance(sub, ast.Call):
                        kind = self._classify(sub)
                        if kind:
                            evs.append((kind, sub))
                memo[key] = evs
            return memo[key]

        paths, truncated = enumerate_exit_paths(
            fn.body, extract, optional_attrs=wiring.OPTIONAL_SURFACE_ATTRS)
        if truncated:
            return []
        findings: List[Finding] = []
        reported: Set[int] = set()
        for path in paths:
            fence_seen = False
            for kind, node in path.events:
                if kind == "fence":
                    fence_seen = True
                elif not fence_seen:
                    if id(node) not in reported:
                        reported.add(id(node))
                        end_line = getattr(path.end, "lineno", None)
                        also = (((ctx.path, end_line),)
                                if end_line is not None else ())
                        findings.append(ctx.finding(
                            self.rule, node,
                            f"{fn.name}: install executes before the WAL "
                            f"fence append on this path — a crash in the "
                            f"window leaves live state ahead of the WAL and "
                            f"recovery cannot re-drive the swap (append the "
                            f"{'/'.join(sorted(wiring.FENCE_APPEND_ATTRS))} "
                            f"record first)", also=also))
        return findings

    # ---- durable writers ----

    def _check_durable_writers(self, ctx: FileContext) -> List[Finding]:
        findings: List[Finding] = []
        classes = {node.name: node for node in ctx.tree.body
                   if isinstance(node, ast.ClassDef)}
        for cls_name, method_name in wiring.FENCE_DURABLE_WRITERS:
            cls = classes.get(cls_name)
            if cls is None:
                continue
            for sub in cls.body:
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                        and sub.name == method_name:
                    findings.extend(
                        self._check_writer(ctx, cls_name, sub))
        return findings

    def _check_writer(self, ctx: FileContext, cls_name: str,
                      fn: ast.AST) -> List[Finding]:
        findings: List[Finding] = []
        has_atomic = False
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            name = None
            if isinstance(node.func, ast.Name):
                name = node.func.id
            elif isinstance(node.func, ast.Attribute):
                name = node.func.attr
            if name and name.startswith(wiring.ATOMIC_WRITE_PREFIX):
                has_atomic = True
            if name == "open" and self._opens_for_write(node):
                findings.append(ctx.finding(
                    self.rule, node,
                    f"{cls_name}.{fn.name} opens its durable file for "
                    f"writing directly — a crash mid-write tears the "
                    f"registry; route through an "
                    f"{wiring.ATOMIC_WRITE_PREFIX}* helper "
                    f"(tmp-file + fsync + rename)"))
        if not has_atomic:
            findings.append(ctx.finding(
                self.rule, fn,
                f"{cls_name}.{fn.name} persists cutover-critical state but "
                f"never calls an {wiring.ATOMIC_WRITE_PREFIX}* helper — "
                f"durable installs must be atomic so recovery never parses "
                f"a torn file"))
        return findings

    @staticmethod
    def _opens_for_write(call: ast.Call) -> bool:
        mode = None
        if len(call.args) >= 2 and isinstance(call.args[1], ast.Constant):
            mode = call.args[1].value
        for kw in call.keywords:
            if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
                mode = kw.value.value
        return isinstance(mode, str) and any(m in mode for m in _WRITE_MODES)
