"""epoch-pairing: gallery/quantizer reads must flow through the
epoch-checked snapshot API.

PR 6's two-stage matcher is only correct because every serving read takes
ONE ``gallery.data`` snapshot and pairs it with ONE
``gallery._ivf_data(data)`` quantizer read — the epoch cross-check inside
``_ivf_data`` is what stops a ``swap_from`` + fast retrain between two
non-atomic reads from scoring OLD rows against NEW inverted lists
(plausible similarities, wrong identities).  Three ways code has
historically broken protocols like this, three checks:

1. Reaching into another object's ``_epoch``/``_data`` fields outside the
   owner modules (``parallel/gallery.py``, ``parallel/quantizer.py``) —
   those names are reserved for the protocol's own implementation.
2. Reading ``<...>.quantizer.data`` (or ``._data``) directly: an
   un-paired quantizer snapshot that no epoch check ties to the gallery
   arrays it will be scored against.
3. Reading two or more single-field gallery properties
   (``.embeddings``/``.labels``/``.valid``) in one function: each is an
   independent snapshot load, so the pair can straddle a concurrent swap
   — take one ``gallery.data`` and use its fields."""

from __future__ import annotations

import ast
from typing import Dict, List

from tools.ocvf_lint import wiring
from tools.ocvf_lint.astutil import terminal_attr as _receiver_terminal
from tools.ocvf_lint.core import Checker, FileContext, Finding, register


@register
class EpochPairingChecker(Checker):
    rule = "epoch-pairing"
    description = ("direct access to epoch-guarded gallery/quantizer state "
                   "(_epoch/_data, quantizer.data, mixed single-field "
                   "reads) outside the snapshot API and its owner modules")

    def check_file(self, ctx: FileContext) -> List[Finding]:
        if wiring.path_matches(ctx.path, wiring.EPOCH_OWNER_SUFFIXES):
            return []
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Attribute):
                continue
            # 1) reserved protocol fields on ANOTHER object (self._data in
            # an unrelated class is that class's own business)
            if node.attr in wiring.EPOCH_GUARDED_ATTRS and not (
                    isinstance(node.value, ast.Name)
                    and node.value.id == "self"):
                findings.append(ctx.finding(
                    self.rule, node,
                    f"direct access to epoch-guarded field "
                    f"{_receiver_terminal(node.value) or '<expr>'}."
                    f"{node.attr} outside parallel/gallery.py|quantizer.py "
                    f"— reads must go through gallery.data / "
                    f"gallery._ivf_data(data), which carry the epoch "
                    f"pairing check"))
            # 2) raw quantizer snapshot, un-paired with a gallery snapshot
            elif node.attr in ("data", "_data") \
                    and _receiver_terminal(node.value) == "quantizer":
                findings.append(ctx.finding(
                    self.rule, node,
                    "raw quantizer snapshot read (quantizer.data) — pair "
                    "it with the gallery snapshot via "
                    "gallery._ivf_data(data), or a swap+retrain between "
                    "the two reads scores old rows against new inverted "
                    "lists"))

        # 3) mixed single-field gallery reads within one function scope
        # (nested defs own their reads — they run at another time)
        scopes: List[List[ast.stmt]] = [ctx.tree.body]
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scopes.append(node.body)
        for body in scopes:
            fields: Dict[str, ast.Attribute] = {}
            stack: List[ast.AST] = list(body)
            while stack:
                node = stack.pop(0)
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue  # inner scope, scanned separately
                stack.extend(ast.iter_child_nodes(node))
                if (isinstance(node, ast.Attribute)
                        and isinstance(node.ctx, ast.Load)
                        and node.attr in wiring.GALLERY_FIELD_PROPS
                        and _receiver_terminal(node.value)
                        in wiring.GALLERY_RECEIVERS
                        and node.attr not in fields):
                    fields[node.attr] = node
                    if len(fields) == 2:
                        findings.append(ctx.finding(
                            self.rule, node,
                            f"second single-field gallery read "
                            f"(.{node.attr}) in one function — each "
                            f"property is an independent snapshot load, so "
                            f"the fields can straddle a concurrent swap; "
                            f"take one gallery.data snapshot and read its "
                            f"fields"))
        return findings
