"""jit-recompile-hazard: Python-value-dependent control flow or host
materialization inside jax-traced code, and unsanctioned jit construction
in the serving hot path.

The PR-2 bucket ladder exists so serving NEVER compiles mid-stream: every
dispatch shape is prewarmed, every jit executable is cache-keyed by
(batch, frame, capacity, matcher).  Two bug classes silently break that
contract:

1. **Inside a traced body** — branching on a traced value (``if x.sum() >
   0:``), or materializing one (``float(x)``, ``np.asarray(x)``,
   ``.item()``), concretizes at trace time: a TracerBoolConversionError at
   best, a silently-baked constant (stale after the next enrollment) at
   worst.  Found interprocedurally: the walk follows project-local calls
   (``decode_detections(outputs, ...)``) with the taint of their actual
   arguments, so a hazard three calls deep inside ``models/`` is reported
   where it lives.

2. **jit construction in the hot path** — a stray ``jax.jit(...)`` in
   recognizer/batcher/pipeline is a latent mid-serving compile (measured
   ~85 s on the tunneled backend).  The sanctioned builder sites — the
   bucket-ladder step factory, the packed-step cache fill, prewarm, the
   enrolment chunk built at construction — carry
   ``# ocvf-lint: boundary=jit-recompile-hazard`` annotations; anything
   else is a finding."""

from __future__ import annotations

import ast
from typing import List, Set, Tuple

from tools.ocvf_lint import wiring
from tools.ocvf_lint.core import Checker, Finding, register


@register
class JitRecompileHazardChecker(Checker):
    rule = "jit-recompile-hazard"
    description = ("traced-value branching / host materialization inside "
                   "jax.jit-reachable code, and jit construction in the "
                   "serving hot path outside sanctioned builder sites")
    scope = "project"
    boundary_capable = True
    needs_dataflow = True

    _KIND_MESSAGES = {
        "branch": ("{detail} inside the jax-traced function {fn!r} — the "
                   "branch concretizes at trace time (TracerBool error, or "
                   "a different executable per Python value: a recompile "
                   "the prewarmed bucket ladder can never absorb); use "
                   "jnp.where/lax.cond, or hoist the decision to the "
                   "cache-keyed builder"),
        "materialize": ("{detail} inside the jax-traced function {fn!r} — "
                        "host materialization during tracing either raises "
                        "or silently bakes the traced value in as a "
                        "compile-time constant (stale after the next "
                        "gallery mutation)"),
    }

    def finalize(self) -> List[Finding]:
        findings: List[Finding] = []
        seen: Set[Tuple[str, int, str]] = set()
        if self.project is None:
            return findings
        from tools.ocvf_lint import dataflow

        checker = dataflow.JitTraceChecker(self.project).run()
        for fn, node, kind, detail in checker.findings:
            message = self._KIND_MESSAGES[kind].format(detail=detail,
                                                       fn=fn.qual)
            key = (fn.path, getattr(node, "lineno", 1), message)
            if key in seen:
                continue
            seen.add(key)
            findings.append(Finding(self.rule, fn.path,
                                    getattr(node, "lineno", 1),
                                    getattr(node, "col_offset", 0), message))

        # hot-path jit construction outside annotated builder sites
        for mi in self.project.modules.values():
            if not wiring.path_matches(mi.ctx.path, wiring.HOT_PATH_SUFFIXES):
                continue
            # decorator Call nodes (@functools.partial(jax.jit, ...)) are
            # reported once by the decorator loop below, never twice
            decorator_ids = {id(dec) for fi in mi.all_funcs
                             for dec in getattr(fi.node, "decorator_list", [])}
            for node in ast.walk(mi.ctx.tree):
                if isinstance(node, ast.Call) \
                        and id(node) not in decorator_ids \
                        and self.project._jit_call_info(mi, node) is not None:
                    findings.append(Finding(
                        self.rule, mi.ctx.path, node.lineno, node.col_offset,
                        "jit construction in the serving hot path — a cold "
                        "call here is a mid-serving XLA compile; route it "
                        "through a prewarmed, cache-keyed builder and mark "
                        "that site with "
                        "'# ocvf-lint: boundary=jit-recompile-hazard -- "
                        "<why every serving call finds a warm cache>'"))
            for fi in mi.all_funcs:
                for dec in getattr(fi.node, "decorator_list", []):
                    if self.project._jit_callee_kind(mi, dec) or (
                            isinstance(dec, ast.Call)
                            and self.project._jit_call_info(mi, dec)
                            is not None):
                        findings.append(Finding(
                            self.rule, mi.ctx.path, fi.node.lineno,
                            fi.node.col_offset,
                            f"@jit-decorated {fi.name!r} in the serving hot "
                            f"path compiles per call shape — prewarm it or "
                            f"annotate the sanctioned builder site"))
        return findings
