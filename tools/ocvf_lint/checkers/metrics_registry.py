"""metrics-registry: every metric name that reaches the shared ``Metrics``
surface must be a constant from the canonical registry module
``opencv_facerecognizer_tpu/utils/metric_names.py``.

The chaos soaks and the admission ledger compare counters *by string name*
across 11+ files — one typo silently breaks an accounting invariant with no
error anywhere.  This rule kills the drift: write sites (``incr`` /
``observe`` / ``set_gauge``) and read sites (``counter`` / ``percentile`` /
``counters_with_prefix``) are both checked.  Accepted argument shapes:

- a string literal whose value is registered,
- ``mn.SOME_CONSTANT`` / an imported constant that exists in the registry,
- ``f"prefix_{x}"`` or ``PREFIX + x`` where the literal prefix is a
  registered ``*_PREFIX`` constant,
- a conditional expression whose branches each satisfy the above.

Anything else (a bare variable, a computed name) is flagged — thread the
name through a registry constant instead."""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Set, Tuple

from tools.ocvf_lint.core import Checker, FileContext, Finding, register

REGISTRY_SUFFIX = "utils/metric_names.py"

#: Metrics methods whose first positional argument is a metric name.
#: The distinctive ones are checked on any receiver; ``counter`` and
#: ``percentile`` collide with common APIs (``np.percentile``) and are only
#: checked when the receiver looks like a Metrics surface.
NAME_METHODS = frozenset({"incr", "observe", "set_gauge", "counter",
                          "percentile", "counters_with_prefix",
                          # the connectors' and tracker's None-guarded shims
                          "_count", "_incr"})
GENERIC_METHODS = frozenset({"counter", "percentile"})


def _metrics_ish_receiver(func: ast.Attribute) -> bool:
    base = func.value
    name = base.attr if isinstance(base, ast.Attribute) else \
        base.id if isinstance(base, ast.Name) else ""
    return "metric" in name.lower()


def _registry_from_tree(tree: ast.Module) -> Tuple[Set[str], Set[str]]:
    """(full names, prefix values) from module-level ``NAME = "literal"``."""
    names: Set[str] = set()
    prefixes: Set[str] = set()
    for stmt in tree.body:
        if not isinstance(stmt, ast.Assign):
            continue
        if not (len(stmt.targets) == 1 and isinstance(stmt.targets[0], ast.Name)):
            continue
        if not (isinstance(stmt.value, ast.Constant)
                and isinstance(stmt.value.value, str)):
            continue
        target = stmt.targets[0].id
        if target.startswith("_"):
            continue
        if target.endswith("_PREFIX"):
            prefixes.add(stmt.value.value)
        else:
            names.add(stmt.value.value)
    return names, prefixes


def _registry_constants(tree: ast.Module) -> Set[str]:
    return {stmt.targets[0].id for stmt in tree.body
            if isinstance(stmt, ast.Assign)
            and len(stmt.targets) == 1
            and isinstance(stmt.targets[0], ast.Name)
            and not stmt.targets[0].id.startswith("_")}


class _FileImports:
    """Which local names in a file refer to the metric_names module or to
    constants imported from it."""

    def __init__(self, tree: ast.Module):
        self.module_aliases: Set[str] = set()
        self.constant_aliases: Dict[str, str] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom) and node.module:
                if node.module.endswith("metric_names"):
                    for alias in node.names:
                        self.constant_aliases[alias.asname or alias.name] = alias.name
                elif node.module.endswith("utils"):
                    for alias in node.names:
                        if alias.name == "metric_names":
                            self.module_aliases.add(alias.asname or alias.name)
            elif isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.name.endswith("metric_names"):
                        self.module_aliases.add(alias.asname or alias.name.split(".")[0])


@register
class MetricsRegistryChecker(Checker):
    rule = "metrics-registry"
    description = ("metric names passed to Metrics.incr/observe/set_gauge "
                   "(and read sites) must come from "
                   "utils/metric_names.py")
    scope = "project"  # validity depends on the registry file's content

    def __init__(self) -> None:
        self._registry_tree: Optional[ast.Module] = None
        self._pending: List[Tuple[FileContext, _FileImports, ast.Call, str]] = []

    def check_file(self, ctx: FileContext) -> List[Finding]:
        norm = ctx.path.replace("\\", "/")
        if norm.endswith(REGISTRY_SUFFIX):
            self._registry_tree = ctx.tree
            return []
        imports = _FileImports(ctx.tree)
        for node in ast.walk(ctx.tree):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in NAME_METHODS
                    and node.args):
                if (node.func.attr in GENERIC_METHODS
                        and not _metrics_ish_receiver(node.func)):
                    continue
                self._pending.append((ctx, imports, node, node.func.attr))
        return []

    @staticmethod
    def _fallback_registry_path() -> str:
        here = os.path.dirname(os.path.abspath(__file__))
        repo_root = os.path.dirname(os.path.dirname(os.path.dirname(here)))
        return os.path.join(repo_root, "opencv_facerecognizer_tpu",
                            "utils", "metric_names.py")

    def extra_cache_fingerprint(self, files) -> str:
        """When the registry is NOT among the linted files, the verdict
        depends on the fallback registry read from disk — fold its content
        into the run-cache key so editing utils/metric_names.py can never
        replay a stale cached verdict for a subset lint."""
        if any(f.replace("\\", "/").endswith(REGISTRY_SUFFIX) for f in files):
            return ""  # in-tree: its content hash is already in the key
        candidate = self._fallback_registry_path()
        try:
            with open(candidate, "rb") as fh:
                import hashlib

                return "metrics-registry:" + hashlib.sha256(fh.read()).hexdigest()
        except OSError:
            return "metrics-registry:absent"

    def _load_fallback_registry(self) -> None:
        if self._registry_tree is not None:
            return
        candidate = self._fallback_registry_path()
        if os.path.exists(candidate):
            with open(candidate, "r", encoding="utf-8") as fh:
                self._registry_tree = ast.parse(fh.read())

    def finalize(self) -> List[Finding]:
        if not self._pending:
            return []
        self._load_fallback_registry()
        if self._registry_tree is None:
            ctx = self._pending[0][0]
            return [Finding(self.rule, ctx.path, 1, 0,
                            "no utils/metric_names.py registry found in the "
                            "scanned tree or the repository — metric names "
                            "cannot be validated")]
        values, prefixes = _registry_from_tree(self._registry_tree)
        constants = _registry_constants(self._registry_tree)
        findings: List[Finding] = []
        for ctx, imports, call, method in self._pending:
            problem = self._check_name_expr(call.args[0], method, values,
                                            prefixes, constants, imports)
            if problem is not None:
                findings.append(ctx.finding(self.rule, call, problem))
        return findings

    def _check_name_expr(self, arg, method, values, prefixes, constants,
                         imports) -> Optional[str]:
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            # counters_with_prefix takes a *_PREFIX value; everything else a
            # full name — the pools are deliberately disjoint checks, so a
            # bare prefix passed as a counter name (or vice versa) is drift.
            pool = prefixes if method == "counters_with_prefix" else values
            if arg.value in pool:
                return None
            kind = "prefix" if method == "counters_with_prefix" else "name"
            return (f"metric {kind} {arg.value!r} is not a registered "
                    f"{'*_PREFIX value' if kind == 'prefix' else 'full name'} "
                    f"in utils/metric_names.py — add it to the registry (typo?)")
        if isinstance(arg, ast.JoinedStr):
            head = arg.values[0] if arg.values else None
            if (isinstance(head, ast.Constant) and isinstance(head.value, str)
                    and head.value in prefixes):
                return None
            return ("f-string metric name must start with a registered "
                    "*_PREFIX constant's value from utils/metric_names.py")
        if isinstance(arg, ast.BinOp) and isinstance(arg.op, ast.Add):
            # PREFIX + suffix: the LEFT operand must be a registered prefix
            # — either its literal value, or a *_PREFIX registry constant.
            # (A full-name constant on the left would mint an unregistered
            # dynamic family, exactly the drift this rule exists to catch.)
            left = arg.left
            if (isinstance(left, ast.Constant) and isinstance(left.value, str)
                    and left.value in prefixes):
                return None
            if (isinstance(left, ast.Attribute)
                    and isinstance(left.value, ast.Name)
                    and left.value.id in imports.module_aliases
                    and left.attr in constants and left.attr.endswith("_PREFIX")):
                return None
            if (isinstance(left, ast.Name)
                    and left.id in imports.constant_aliases
                    and imports.constant_aliases[left.id] in constants
                    and imports.constant_aliases[left.id].endswith("_PREFIX")):
                return None
            return ("concatenated metric name must start with a registered "
                    "*_PREFIX constant (or its literal value) from "
                    "utils/metric_names.py")
        if isinstance(arg, ast.IfExp):
            return (self._check_name_expr(arg.body, method, values, prefixes,
                                          constants, imports)
                    or self._check_name_expr(arg.orelse, method, values,
                                             prefixes, constants, imports))
        if isinstance(arg, ast.Attribute) and isinstance(arg.value, ast.Name):
            if arg.value.id in imports.module_aliases:
                if arg.attr in constants:
                    return None
                return (f"metric_names.{arg.attr} does not exist in the "
                        f"registry module")
        if isinstance(arg, ast.Name):
            if arg.id in imports.constant_aliases:
                original = imports.constant_aliases[arg.id]
                if original in constants:
                    return None
                return f"metric_names.{original} does not exist in the registry"
            return (f"metric name is the bare variable {arg.id!r} — thread a "
                    f"registry constant (or a registered *_PREFIX + suffix) "
                    f"through instead")
        return ("metric name is not statically resolvable to a "
                "utils/metric_names.py constant")
