"""lock-order: build the inter-module lock-acquisition graph and flag
cycles (inversions) and nested re-acquisition of the same lock.

Edges come from two sources:

1. **Lexical nesting** — ``with self._a: ... with self._b:`` adds a->b.
2. **Calls under a lock** — a call made while holding lock ``a`` to a
   callable that (transitively, bounded depth) acquires lock ``b`` adds
   a->b.  Callees are resolved heuristically: ``self.m()`` through the
   class and its project-local bases, bare ``f()`` through the module, and
   ``<...>.attr.m()`` through ``ATTR_HINTS`` (the runtime's known wiring:
   ``self.metrics`` is a ``utils.metrics.Metrics``, etc.), which is what
   makes the graph *inter-module*.

Lock identity is ``module.Class.attr`` for ``self._lock`` and
``module[.func].name`` otherwise — instances of one class share a node,
which over-approximates (two distinct FrameBatcher instances cannot
deadlock each other) but is the right conservatism for a discipline
checker.  A two-node cycle is the classic AB/BA inversion; any larger SCC
is reported once with every participating edge site."""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Set, Tuple

from tools.ocvf_lint import astutil
from tools.ocvf_lint.core import Checker, FileContext, Finding, register

#: Known wiring of ``self.<attr>`` (or any ``x.<attr>``) to the class whose
#: methods it dispatches to — ONE map for the whole suite, shared with the
#: dataflow layer and every v2 checker (tools.ocvf_lint.wiring).
from tools.ocvf_lint.wiring import ATTR_HINTS  # noqa: F401 — re-exported

_CALL_DEPTH = 4


@dataclasses.dataclass
class CallableInfo:
    module: str
    cls: Optional[str]
    name: str
    #: (lock_id, line, lock-ids held when acquiring)
    acquisitions: List[Tuple[str, int, Tuple[str, ...]]]
    #: (descriptor, lock-ids held at the call, line)
    calls: List[Tuple[Tuple[str, ...], Tuple[str, ...], int]]


@dataclasses.dataclass
class ClassInfo:
    module: str
    name: str
    bases: Tuple[str, ...]
    methods: Dict[str, CallableInfo]


@register
class LockOrderChecker(Checker):
    rule = "lock-order"
    description = ("inter-module lock-acquisition graph cycles/inversions "
                   "and nested same-lock re-acquisition")
    scope = "project"  # the graph spans files; never cache per-file

    def __init__(self) -> None:
        self.classes: Dict[str, List[ClassInfo]] = {}  # class name -> defs
        self.functions: Dict[Tuple[str, str], CallableInfo] = {}
        self.callables: List[CallableInfo] = []

    # ---------------- collection ----------------

    def check_file(self, ctx: FileContext) -> List[Finding]:
        self._module_paths[ctx.module] = ctx.path
        for stmt in ctx.tree.body:
            if isinstance(stmt, ast.ClassDef):
                info = ClassInfo(
                    module=ctx.module, name=stmt.name,
                    bases=tuple(b.id for b in stmt.bases if isinstance(b, ast.Name)),
                    methods={})
                for sub in stmt.body:
                    if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        ci = self._collect(ctx, sub, cls=stmt.name)
                        info.methods[sub.name] = ci
                self.classes.setdefault(stmt.name, []).append(info)
            elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                ci = self._collect(ctx, stmt, cls=None)
                self.functions[(ctx.module, stmt.name)] = ci
        return []

    def _lock_id(self, ctx: FileContext, cls: Optional[str], fn: str,
                 expr: ast.expr, name: str) -> str:
        if astutil.lock_base_is_self(expr) and cls is not None:
            return f"{ctx.module}.{cls}.{name}"
        if isinstance(expr, ast.Name):
            return f"{ctx.module}.{name}"
        # non-self attribute chain (rare): qualify by terminal attr only
        return f"{ctx.module}.{fn}.{name}"

    def _collect(self, ctx: FileContext, fn: ast.AST,
                 cls: Optional[str]) -> CallableInfo:
        info = CallableInfo(module=ctx.module, cls=cls, name=fn.name,
                            acquisitions=[], calls=[])
        self.callables.append(info)
        self._walk(ctx, cls, fn, fn.body, (), info)
        return info

    def _walk(self, ctx, cls, fn, body, stack, info) -> None:
        """Like astutil.walk_with_lock_stack but tracking lock *ids* (not
        just names) and recording acquisitions/calls on ``info``."""
        for stmt in body:
            self._walk_node(ctx, cls, fn, stmt, stack, info)

    def _walk_node(self, ctx, cls, fn, node, stack, info) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            # Nested definitions run later, with no locks lexically held.
            # They are not independently callable by name here, so fold their
            # acquisitions into the enclosing callable with an empty stack —
            # transitive call analysis still sees them.
            body = node.body if isinstance(node.body, list) else [ast.Expr(node.body)]
            self._walk(ctx, cls, fn, body, (), info)
            return
        locks = astutil.with_lock_items(node)
        if locks:
            ids = []
            for expr, name in locks:
                lock_id = self._lock_id(ctx, cls, fn.name, expr, name)
                info.acquisitions.append((lock_id, node.lineno, stack))
                ids.append(lock_id)
            inner_stack = stack + tuple(ids)
            for child in node.body:
                self._walk_node(ctx, cls, fn, child, inner_stack, info)
            return
        if isinstance(node, ast.Call):
            desc = self._call_descriptor(node)
            if desc is not None:
                info.calls.append((desc, stack, node.lineno))
        for child in ast.iter_child_nodes(node):
            self._walk_node(ctx, cls, fn, child, stack, info)

    @staticmethod
    def _call_descriptor(node: ast.Call) -> Optional[Tuple[str, ...]]:
        func = node.func
        if isinstance(func, ast.Name):
            return ("func", func.id)
        if isinstance(func, ast.Attribute):
            base = func.value
            if isinstance(base, ast.Name) and base.id == "self":
                return ("self", func.attr)
            # terminal attribute before the method: self.pipeline.gallery.m()
            # -> ("attr", "gallery", "m"); plain name base works too.
            if isinstance(base, ast.Attribute):
                return ("attr", base.attr, func.attr)
            if isinstance(base, ast.Name):
                return ("attr", base.id, func.attr)
        return None

    # ---------------- resolution ----------------

    def _resolve_method(self, cls_name: str, method: str, module: str,
                        _seen=None) -> Optional[CallableInfo]:
        if _seen is None:
            _seen = set()
        if cls_name in _seen:
            return None
        _seen.add(cls_name)
        defs = self.classes.get(cls_name, [])
        ordered = sorted(defs, key=lambda c: c.module != module)
        for cdef in ordered:
            if method in cdef.methods:
                return cdef.methods[method]
        for cdef in ordered:
            for base in cdef.bases:
                found = self._resolve_method(base, method, module, _seen)
                if found is not None:
                    return found
        return None

    def _resolve(self, desc: Tuple[str, ...], caller: CallableInfo
                 ) -> Optional[CallableInfo]:
        kind = desc[0]
        if kind == "self" and caller.cls is not None:
            return self._resolve_method(caller.cls, desc[1], caller.module)
        if kind == "func":
            return self.functions.get((caller.module, desc[1]))
        if kind == "attr":
            hint = ATTR_HINTS.get(desc[1])
            if hint is not None:
                return self._resolve_method(hint, desc[2], caller.module)
        return None

    def _locks_acquired(self, info: CallableInfo, depth: int,
                        seen: Set[int]) -> Set[str]:
        if id(info) in seen or depth <= 0:
            return set()
        seen.add(id(info))
        out = {lock for lock, _, _ in info.acquisitions}
        for desc, _, _ in info.calls:
            target = self._resolve(desc, info)
            if target is not None:
                out |= self._locks_acquired(target, depth - 1, seen)
        return out

    # ---------------- graph + findings ----------------

    def derive_edges(self) -> Dict[Tuple[str, str],
                                   List[Tuple[str, int, str]]]:
        """The (held, acquired) -> [(module, line, note)] edge map — the ONE
        derivation, shared by ``finalize`` (findings) and
        ``build_lock_graph`` (the DebugLock backstop's cross-check), so the
        graph the tests validate can never diverge from the graph the
        linter enforces."""
        edges: Dict[Tuple[str, str], List[Tuple[str, int, str]]] = {}

        def add_edge(a: str, b: str, info: CallableInfo, line: int, note: str):
            edges.setdefault((a, b), []).append((info.module, line, note))

        for info in self.callables:
            for lock, line, stack in info.acquisitions:
                if stack:
                    add_edge(stack[-1], lock, info, line,
                             f"nested with in {info.qualname()}")
            for desc, stack, line in info.calls:
                if not stack:
                    continue
                target = self._resolve(desc, info)
                if target is None:
                    continue
                for lock in self._locks_acquired(target, _CALL_DEPTH, set()):
                    add_edge(stack[-1], lock, info, line,
                             f"call to {target.qualname()} from {info.qualname()}")
        return edges

    def finalize(self) -> List[Finding]:
        edges = self.derive_edges()
        findings: List[Finding] = []

        # self-loops: nested or indirect re-acquisition of one lock
        for (a, b), elist in sorted(edges.items()):
            if a == b:
                mod, line, note = elist[0]
                findings.append(Finding(
                    self.rule, self._path_for(mod), line, 0,
                    f"lock {a} may be re-acquired while already held "
                    f"({note}) — deadlock unless it is an RLock",
                    also=tuple((self._path_for(m), l) for m, l, _ in elist[1:])))

        # inversions: SCCs of size >= 2 in the directed graph
        graph: Dict[str, Set[str]] = {}
        for (a, b) in edges:
            if a != b:
                graph.setdefault(a, set()).add(b)
                graph.setdefault(b, set())
        for scc in _tarjan(graph):
            if len(scc) < 2:
                continue
            scc_set = set(scc)
            cycle_edges = sorted((a, b) for (a, b) in edges
                                 if a in scc_set and b in scc_set and a != b)
            all_sites = [s for e in cycle_edges for s in edges[e]]
            mod, line, _ = all_sites[0]
            detail = "; ".join(
                f"{a} -> {b} at {self._path_for(edges[(a, b)][0][0])}:"
                f"{edges[(a, b)][0][1]} ({edges[(a, b)][0][2]})"
                for a, b in cycle_edges)
            findings.append(Finding(
                self.rule, self._path_for(mod), line, 0,
                f"lock-order inversion among {{{', '.join(sorted(scc_set))}}}: "
                f"{detail}",
                also=tuple((self._path_for(m), l) for m, l, _ in all_sites[1:])))
        return findings

    def _path_for(self, module: str) -> str:
        return self._module_paths.get(module, module)

    # module -> path bookkeeping, filled lazily by check_file
    @property
    def _module_paths(self) -> Dict[str, str]:
        paths = getattr(self, "_module_paths_cache", None)
        if paths is None:
            paths = {}
            self._module_paths_cache = paths
        return paths


def _tarjan(graph: Dict[str, Set[str]]) -> List[List[str]]:
    """Iterative Tarjan SCC."""
    index_counter = [0]
    index: Dict[str, int] = {}
    lowlink: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    sccs: List[List[str]] = []

    for root in sorted(graph):
        if root in index:
            continue
        work = [(root, iter(sorted(graph.get(root, ()))))]
        index[root] = lowlink[root] = index_counter[0]
        index_counter[0] += 1
        stack.append(root)
        on_stack.add(root)
        while work:
            node, it = work[-1]
            advanced = False
            for succ in it:
                if succ not in index:
                    index[succ] = lowlink[succ] = index_counter[0]
                    index_counter[0] += 1
                    stack.append(succ)
                    on_stack.add(succ)
                    work.append((succ, iter(sorted(graph.get(succ, ())))))
                    advanced = True
                    break
                elif succ in on_stack:
                    lowlink[node] = min(lowlink[node], index[succ])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                lowlink[parent] = min(lowlink[parent], lowlink[node])
            if lowlink[node] == index[node]:
                scc = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    scc.append(w)
                    if w == node:
                        break
                sccs.append(scc)
    return sccs


def _qualname(self: CallableInfo) -> str:
    return (f"{self.module}.{self.cls}.{self.name}" if self.cls
            else f"{self.module}.{self.name}")


CallableInfo.qualname = _qualname


def build_lock_graph(paths) -> Dict[Tuple[str, str], List[Tuple[str, int, str]]]:
    """Standalone API: the (a, b) -> sites edge map for ``paths``.  Used by
    the DebugLock dynamic backstop in tests to cross-check observed
    acquisition order against the static graph.  Same derivation as the
    lock-order rule itself (``derive_edges``)."""
    from tools.ocvf_lint import core as _core

    checker = LockOrderChecker()
    for path in _core.iter_py_files(paths):
        with open(path, "r", encoding="utf-8", errors="replace") as fh:
            source = fh.read()
        try:
            tree = ast.parse(source, filename=path)
        except SyntaxError:
            continue
        checker.check_file(_core.FileContext(path, source, tree))
    return checker.derive_edges()
