"""ledger-registry-coherence: one source-of-truth table for terminal
statuses, and every consumer provably derived from it.

``utils/metric_names.py`` declares the terminal shape of the admission
ledger as data: ``LEDGER_COMPLETION_COUNTERS`` (the three completion
buckets), ``LEDGER_DROP_COUNTERS`` (the ten drop buckets) and
``PROM_FOLDED_PREFIXES`` (the labelled counter families promtext folds).
Four consumers mirror that shape and historically drifted one constant at
a time — each drift is invisible until an operator stares at a dashboard
where ``frames_in_system`` never drains:

- ``tracing.account_spans`` must handle every completion outcome (the
  ``OUTCOME_*`` mirror constants must exist, carry the registry's values,
  and be referenced by the reducer);
- ``RecognizerService.ledger`` / ``frames_in_system`` must cover all three
  completion counters, and the class's ``LEDGER_DROP_COUNTERS`` must BE
  the registry table (``mn.LEDGER_DROP_COUNTERS``) or literally equal it;
- ``promtext._LABEL_FAMILIES`` must fold exactly the registry's prefix
  families — one missing and its counters vanish from /metrics, one extra
  and promtext emits a family the registry never populates;
- ``scripts/chaos_soak`` span accounting must assert on every completion
  outcome, else the soak silently stops checking a bucket.

Project-scope: sites absent from a subset lint are skipped (you can lint a
single file); the registry itself falls back to a disk read, folded into
the cache fingerprint."""

from __future__ import annotations

import ast
import hashlib
import os
from typing import Dict, List, Optional, Set, Tuple

from tools.ocvf_lint import wiring
from tools.ocvf_lint.core import Checker, FileContext, Finding, register

_TABLES = ("LEDGER_COMPLETION_COUNTERS", "LEDGER_DROP_COUNTERS",
           "PROM_FOLDED_PREFIXES")


def _canon(value: str) -> str:
    return value[7:] if value.startswith("frames_") else value


def _str_assigns(tree: ast.Module) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for stmt in tree.body:
        if (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                and isinstance(stmt.targets[0], ast.Name)
                and isinstance(stmt.value, ast.Constant)
                and isinstance(stmt.value.value, str)):
            out[stmt.targets[0].id] = stmt.value.value
    return out


def _tuple_tables(tree: ast.Module) -> Dict[str, List[str]]:
    """Module-level ``NAME = (A, B, ...)`` tables as element NAMES."""
    out: Dict[str, List[str]] = {}
    for stmt in tree.body:
        if (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                and isinstance(stmt.targets[0], ast.Name)
                and isinstance(stmt.value, (ast.Tuple, ast.List))):
            out[stmt.targets[0].id] = [e.id for e in stmt.value.elts
                                       if isinstance(e, ast.Name)]
    return out


def _attr_names(node: ast.AST) -> Set[str]:
    return {n.attr for n in ast.walk(node) if isinstance(n, ast.Attribute)}


def _find_function(tree: ast.Module, name: str) -> Optional[ast.AST]:
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name == name:
            return node
    return None


def _find_class(tree: ast.Module, name: str) -> Optional[ast.ClassDef]:
    for node in tree.body:
        if isinstance(node, ast.ClassDef) and node.name == name:
            return node
    return None


@register
class LedgerCoherenceChecker(Checker):
    rule = "ledger-registry-coherence"
    description = ("the terminal-status tables in metric_names must agree "
                   "with tracing.account_spans, the recognizer ledger, "
                   "promtext folded families and chaos_soak span checks")
    scope = "project"

    def __init__(self) -> None:
        self._registry: Optional[Tuple[FileContext, ast.Module]] = None
        self._sites: Dict[str, FileContext] = {}

    def check_file(self, ctx: FileContext) -> List[Finding]:
        norm = ctx.path.replace("\\", "/")
        if norm.endswith("utils/metric_names.py"):
            self._registry = (ctx, ctx.tree)
        for key, suffix in (
                ("tracing", wiring.COHERENCE_TRACING_SUFFIX),
                ("recognizer", wiring.COHERENCE_RECOGNIZER_SUFFIX),
                ("promtext", wiring.COHERENCE_PROMTEXT_SUFFIX),
                ("chaos", wiring.COHERENCE_CHAOS_SUFFIX)):
            if norm.endswith(suffix):
                self._sites[key] = ctx
        return []

    # ---- registry fallback (metrics-registry pattern) ----

    @staticmethod
    def _fallback_registry_path() -> str:
        here = os.path.dirname(os.path.abspath(__file__))
        repo_root = os.path.dirname(os.path.dirname(os.path.dirname(here)))
        return os.path.join(repo_root, "opencv_facerecognizer_tpu", "utils",
                            "metric_names.py")

    def extra_cache_fingerprint(self, files) -> str:
        if any(f.replace("\\", "/").endswith("utils/metric_names.py")
               for f in files):
            return ""
        try:
            with open(self._fallback_registry_path(), "rb") as fh:
                return ("ledger-coherence:"
                        + hashlib.sha256(fh.read()).hexdigest())
        except OSError:
            return "ledger-coherence:absent"

    def finalize(self) -> List[Finding]:
        if not self._sites:
            return []
        if self._registry is None:
            candidate = self._fallback_registry_path()
            if os.path.exists(candidate):
                with open(candidate, "r", encoding="utf-8") as fh:
                    self._registry = (None, ast.parse(fh.read()))
        first_site = next(iter(self._sites.values()))
        if self._registry is None:
            return [Finding(self.rule, first_site.path, 1, 0,
                            "no utils/metric_names.py registry found — the "
                            "ledger source-of-truth tables are unreachable")]
        reg_ctx, reg_tree = self._registry
        consts = _str_assigns(reg_tree)
        tables = _tuple_tables(reg_tree)
        findings: List[Finding] = []
        anchor = reg_ctx if reg_ctx is not None else first_site
        for table in _TABLES:
            if table not in tables:
                findings.append(Finding(
                    self.rule, anchor.path, 1, 0,
                    f"metric_names does not declare the source-of-truth "
                    f"table {table} — consumers have nothing to derive "
                    f"from"))
        if findings:
            return findings
        completion_names = tables["LEDGER_COMPLETION_COUNTERS"]
        drop_names = tables["LEDGER_DROP_COUNTERS"]
        prefix_names = tables["PROM_FOLDED_PREFIXES"]
        completion_outcomes = {_canon(consts[n]) for n in completion_names
                               if n in consts}
        if "tracing" in self._sites:
            findings.extend(self._check_tracing(
                self._sites["tracing"], completion_outcomes))
        if "recognizer" in self._sites:
            findings.extend(self._check_recognizer(
                self._sites["recognizer"], completion_names, drop_names))
        if "promtext" in self._sites:
            findings.extend(self._check_promtext(
                self._sites["promtext"], prefix_names))
        if "chaos" in self._sites:
            findings.extend(self._check_chaos(
                self._sites["chaos"], completion_outcomes))
        return findings

    # ---- per-site checks ----

    def _check_tracing(self, ctx: FileContext,
                       completion_outcomes: Set[str]) -> List[Finding]:
        findings: List[Finding] = []
        outcome_consts = {name: value
                          for name, value in _str_assigns(ctx.tree).items()
                          if name.startswith("OUTCOME_")}
        mirrored = set(outcome_consts.values())
        for outcome in sorted(completion_outcomes - mirrored):
            findings.append(Finding(
                self.rule, ctx.path, 1, 0,
                f"tracing declares no OUTCOME_* mirror constant for the "
                f"registry completion outcome {outcome!r} — span "
                f"accounting cannot classify those settles"))
        fn = _find_function(ctx.tree, "account_spans")
        if fn is None:
            findings.append(Finding(
                self.rule, ctx.path, 1, 0,
                "tracing has no account_spans reducer — the span-side "
                "ledger mirror is gone"))
            return findings
        used = {n.id for n in ast.walk(fn) if isinstance(n, ast.Name)}
        used |= _attr_names(fn)
        for name, value in sorted(outcome_consts.items()):
            if value in completion_outcomes and name not in used:
                findings.append(ctx.finding(
                    self.rule, fn,
                    f"account_spans never references {name} — spans "
                    f"settled as {value!r} fall into the generic drop "
                    f"bucket and the ledger mirror drifts"))
        return findings

    def _check_recognizer(self, ctx: FileContext, completion_names: List[str],
                          drop_names: List[str]) -> List[Finding]:
        findings: List[Finding] = []
        cls = _find_class(ctx.tree, "RecognizerService")
        if cls is None:
            return findings
        attr_stmt = None
        for stmt in cls.body:
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name) \
                    and stmt.targets[0].id == "LEDGER_DROP_COUNTERS":
                attr_stmt = stmt
        if attr_stmt is None:
            findings.append(ctx.finding(
                self.rule, cls,
                "RecognizerService declares no LEDGER_DROP_COUNTERS class "
                "attribute — the ledger cannot enumerate drop buckets"))
        elif isinstance(attr_stmt.value, ast.Attribute):
            if attr_stmt.value.attr != "LEDGER_DROP_COUNTERS":
                findings.append(ctx.finding(
                    self.rule, attr_stmt,
                    f"RecognizerService.LEDGER_DROP_COUNTERS aliases "
                    f"{attr_stmt.value.attr!r} instead of the registry's "
                    f"LEDGER_DROP_COUNTERS table"))
        elif isinstance(attr_stmt.value, (ast.Tuple, ast.List)):
            local = [e.attr for e in attr_stmt.value.elts
                     if isinstance(e, ast.Attribute)]
            if sorted(local) != sorted(drop_names):
                missing = sorted(set(drop_names) - set(local))
                extra = sorted(set(local) - set(drop_names))
                detail = "; ".join(filter(None, (
                    f"missing {', '.join(missing)}" if missing else "",
                    f"extra {', '.join(extra)}" if extra else "")))
                findings.append(ctx.finding(
                    self.rule, attr_stmt,
                    f"RecognizerService.LEDGER_DROP_COUNTERS drifted from "
                    f"the registry table ({detail}) — alias "
                    f"mn.LEDGER_DROP_COUNTERS instead of hand-maintaining "
                    f"the tuple"))
        for method, need_drops in (("ledger", True),
                                   ("frames_in_system", True)):
            fn = next((s for s in cls.body
                       if isinstance(s, (ast.FunctionDef,
                                         ast.AsyncFunctionDef))
                       and s.name == method), None)
            if fn is None:
                findings.append(ctx.finding(
                    self.rule, cls,
                    f"RecognizerService has no {method}() — the admission "
                    f"ledger surface is gone"))
                continue
            used = _attr_names(fn)
            for name in completion_names:
                if name not in used:
                    findings.append(ctx.finding(
                        self.rule, fn,
                        f"RecognizerService.{method} never reads "
                        f"mn.{name} — that completion bucket is invisible "
                        f"to the ledger and the invariant check"))
            if need_drops and "LEDGER_DROP_COUNTERS" not in used:
                findings.append(ctx.finding(
                    self.rule, fn,
                    f"RecognizerService.{method} does not fold the "
                    f"LEDGER_DROP_COUNTERS table in — drop buckets escape "
                    f"the ledger"))
        return findings

    def _check_promtext(self, ctx: FileContext,
                        prefix_names: List[str]) -> List[Finding]:
        findings: List[Finding] = []
        families = None
        for stmt in ctx.tree.body:
            target = None
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
                target, value = stmt.targets[0], stmt.value
            elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                target, value = stmt.target, stmt.value
            else:
                continue
            if isinstance(target, ast.Name) and target.id == "_LABEL_FAMILIES":
                families = (stmt, value)
        if families is None:
            findings.append(Finding(
                self.rule, ctx.path, 1, 0,
                "promtext declares no _LABEL_FAMILIES — labelled counter "
                "families are not folded into /metrics"))
            return findings
        stmt, value = families
        local = sorted(a for a in _attr_names(value) if a.endswith("_PREFIX"))
        expected = sorted(prefix_names)
        if local != expected:
            missing = sorted(set(expected) - set(local))
            extra = sorted(set(local) - set(expected))
            detail = "; ".join(filter(None, (
                f"missing {', '.join(missing)}" if missing else "",
                f"extra {', '.join(extra)}" if extra else "")))
            findings.append(ctx.finding(
                self.rule, stmt,
                f"promtext._LABEL_FAMILIES drifted from the registry's "
                f"PROM_FOLDED_PREFIXES ({detail}) — folded families must "
                f"match the registry exactly"))
        return findings

    def _check_chaos(self, ctx: FileContext,
                     completion_outcomes: Set[str]) -> List[Finding]:
        findings: List[Finding] = []
        fn = _find_function(ctx.tree, "_check_span_accounting")
        if fn is None:
            findings.append(Finding(
                self.rule, ctx.path, 1, 0,
                "chaos_soak has no _check_span_accounting — the soak no "
                "longer cross-checks the span ledger mirror"))
            return findings
        literals = {n.value for n in ast.walk(fn)
                    if isinstance(n, ast.Constant)
                    and isinstance(n.value, str)}
        for outcome in sorted(completion_outcomes - literals):
            findings.append(ctx.finding(
                self.rule, fn,
                f"chaos_soak._check_span_accounting never asserts on the "
                f"completion outcome {outcome!r} — that bucket is "
                f"unchecked under fault injection"))
        return findings
