"""swallowed-exception: a broad ``except`` that makes the failure invisible.

The serving stack runs supervised threads whose loop bodies catch
``Exception`` by design — that is fine *as long as the failure is
accounted for*: re-raised, counted on the shared Metrics surface,
dead-lettered/journaled, logged, or at minimum the caught exception object
is actually read (stored into a status dict, formatted into an
announcement).  A handler that does none of those turns a real fault into
silence; under chaos soak that is the difference between an exact ledger
and an unexplainable wedge.

A handler passes if ANY of:
- it re-raises (bare ``raise`` or ``raise X``),
- it calls an accounting sink: ``*.incr/observe/log/warning/error/
  exception/critical/dead_letter/_dead_letter/record*`` or ``print``,
- it binds the exception (``as e``) and reads it somewhere in the body.

Intentional best-effort swallows (teardown paths) carry justified
suppressions."""

from __future__ import annotations

import ast
from typing import List

from tools.ocvf_lint.core import Checker, FileContext, Finding, register

BROAD_NAMES = frozenset({"Exception", "BaseException"})

ACCOUNTING_ATTRS = frozenset({
    "incr", "observe", "set_gauge", "log", "warning", "error", "exception",
    "critical", "dead_letter", "_dead_letter", "record", "record_drop",
    "_count",      # the connectors' metrics shim (None-guarded incr)
    "put_nowait",  # pushing the failure onto a result/status queue
    "print_exc",   # traceback.print_exc: the failure is fully visible
})
ACCOUNTING_NAMES = frozenset({"print"})


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    if isinstance(t, ast.Name):
        return t.id in BROAD_NAMES
    if isinstance(t, ast.Attribute):
        return t.attr in BROAD_NAMES
    if isinstance(t, ast.Tuple):
        return any(_is_broad(ast.ExceptHandler(type=e, name=None, body=[]))
                   for e in t.elts)
    return False


def _accounts(handler: ast.ExceptHandler) -> bool:
    for node in ast.walk(ast.Module(body=handler.body, type_ignores=[])):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            func = node.func
            if isinstance(func, ast.Attribute) and func.attr in ACCOUNTING_ATTRS:
                return True
            if isinstance(func, ast.Name) and func.id in ACCOUNTING_NAMES:
                return True
    if handler.name:
        for node in ast.walk(ast.Module(body=handler.body, type_ignores=[])):
            if isinstance(node, ast.Name) and node.id == handler.name \
                    and isinstance(node.ctx, ast.Load):
                return True
    return False


@register
class SwallowedExceptionChecker(Checker):
    rule = "swallowed-exception"
    description = ("broad except that neither re-raises, counts, "
                   "dead-letters, logs, nor reads the caught exception")

    def check_file(self, ctx: FileContext) -> List[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not _is_broad(node):
                continue
            if _accounts(node):
                continue
            caught = "bare except" if node.type is None else \
                f"except {ast.unparse(node.type)}" if hasattr(ast, "unparse") \
                else "broad except"
            findings.append(ctx.finding(
                self.rule, node,
                f"{caught} swallows the failure silently — re-raise, count it "
                f"on Metrics, dead-letter it, or read the exception into a "
                f"status; if best-effort-by-design, suppress with a "
                f"justification"))
        return findings
