"""blocking-under-lock: a call that can block the thread (sleep, file or
socket I/O, fsync, JAX device sync) made while a lock is lexically held.

A blocking call under a lock turns every other thread contending for that
lock into a convoy — the PR-2 overlap work exists precisely so the serving
loop never sleeps while holding shared state.  Sites where holding the lock
through the I/O *is* the invariant (the WAL's fsync-before-ack, a transport
lock that exists to serialize stream writes) carry justified suppressions —
that audit trail is the point of the rule."""

from __future__ import annotations

import ast
from typing import List

from tools.ocvf_lint import astutil
from tools.ocvf_lint.core import Checker, FileContext, Finding, register

#: Attribute names whose call plausibly blocks (``x.sleep(...)``,
#: ``fh.write(...)``, ``sock.recv(...)``, ``arr.block_until_ready()``).
BLOCKING_ATTRS = frozenset({
    "sleep", "fsync", "recv", "recv_into", "recvfrom", "sendall", "send",
    "accept", "connect", "select", "block_until_ready", "device_get",
    "write", "flush", "read", "readline", "readlines",
})

#: Bare-name calls that block.
BLOCKING_NAMES = frozenset({"open", "sleep", "fsync_directory", "input"})


@register
class BlockingUnderLockChecker(Checker):
    rule = "blocking-under-lock"
    description = ("time.sleep / file or socket I/O / fsync / JAX dispatch "
                   "inside a held-lock region")
    #: sites where holding the lock THROUGH the I/O is the invariant (WAL
    #: fsync-before-ack, transport write serialization) are sanctioned
    #: boundaries — the same annotation mechanism host-sync uses.
    boundary_capable = True

    def check_file(self, ctx: FileContext) -> List[Finding]:
        findings: List[Finding] = []
        for node, stack in astutil.walk_with_lock_stack(ctx.tree.body):
            if not stack or not isinstance(node, ast.Call):
                continue
            func = node.func
            name = None
            if isinstance(func, ast.Attribute) and func.attr in BLOCKING_ATTRS:
                # str.join-style noise guard: skip attribute calls whose base
                # is a string/bytes literal.
                if isinstance(func.value, ast.Constant):
                    continue
                name = astutil.dotted_call_name(func) or f"<expr>.{func.attr}"
            elif isinstance(func, ast.Name) and func.id in BLOCKING_NAMES:
                name = func.id
            if name is not None:
                findings.append(ctx.finding(
                    self.rule, node,
                    f"potentially blocking call {name}() while holding "
                    f"{stack[-1]!r} (locks held: {', '.join(stack)}) — move the "
                    f"I/O outside the lock or justify with a suppression"))
        return findings
