"""Decompose the serving dispatch quote (VERDICT r3 item #6): where do the
pre-readback milliseconds of one ``recognize_batch_packed`` call go?

Measured terms, all in the pre-sync-poll phase (NO blocking readback
happens anywhere in this process, so none of the numbers carry the
tunnel's ~100 ms poll quantum):

- ``full_np_f32``: the serving quote — numpy f32 frames in, packed step
  dispatched (H2D + pjit arg handling + dispatch).
- ``h2d_only``: ``jnp.asarray`` of the same batch alone.
- ``full_device``: same call with frames ALREADY device-resident — the
  pjit python/arg-handling cost without the transfer.
- ``bare_pjit``: the cached compiled function called directly with
  precomputed snapshot/args — subtracts the pipeline wrapper's
  key-lookup/snapshot overhead.
- ``full_np_u8``: uint8 frames in (4x fewer H2D bytes, in-graph cast).

Writes the table into BENCH_SERVING.json under "dispatch_decomposition".

Run:  PYTHONPATH=. python scripts/probe_dispatch.py [--batch 8]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _log(msg):
    print(msg, file=sys.stderr, flush=True)


def p50_ms(ts):
    return round(float(np.percentile(ts, 50) * 1e3), 3)


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--n", type=int, default=30)
    ap.add_argument("--compile-wait-s", type=float, default=30.0,
                    help="async-compile settle time (no readback allowed)")
    args = ap.parse_args(argv)

    import jax
    import jax.numpy as jnp

    from opencv_facerecognizer_tpu.models.detector import CNNFaceDetector
    from opencv_facerecognizer_tpu.models.embedder import (
        SERVING_EMBEDDER_KWARGS, SERVING_FACE_SIZE, FaceEmbedNet,
        init_embedder,
    )
    from opencv_facerecognizer_tpu.parallel import ShardedGallery, make_mesh
    from opencv_facerecognizer_tpu.parallel.pipeline import RecognitionPipeline
    from opencv_facerecognizer_tpu.utils.dataset import make_synthetic_scenes

    dev = jax.devices()[0]
    _log(f"device: {dev}")
    batch, h, w, max_faces = args.batch, 256, 256, 8
    dim = SERVING_EMBEDDER_KWARGS["embed_dim"]
    det = CNNFaceDetector(max_faces=max_faces, score_threshold=0.3)
    scenes, boxes, counts = make_synthetic_scenes(
        num_scenes=16, scene_size=(h, w), max_faces=max_faces,
        face_size_range=(24, 56), seed=7)
    det.train(scenes, boxes, counts, steps=20, batch_size=8)
    net = FaceEmbedNet(**SERVING_EMBEDDER_KWARGS)
    emb_params = init_embedder(net, num_classes=16,
                               input_shape=SERVING_FACE_SIZE, seed=0)["net"]
    rng = np.random.default_rng(0)
    # bf16 rows: the ocvf-recognize serving default (gallery_dtype A/B)
    gallery = ShardedGallery(capacity=16384, dim=dim, mesh=make_mesh(),
                             store_dtype=jnp.bfloat16)
    gallery.add(rng.normal(size=(16384, dim)).astype(np.float32),  # ocvf-lint: boundary=wal-before-mutate -- probe fixture: synthetic gallery for dispatch timing, no state dir
                rng.integers(0, 512, 16384).astype(np.int32))
    pipe = RecognitionPipeline(det, net, emb_params, gallery,
                               face_size=SERVING_FACE_SIZE)

    frames_np = [np.asarray(scenes[i % len(scenes)]).astype(np.float32)
                 for i in range(batch)]
    batch_np = np.stack(frames_np)
    pipe.recognize_batch_packed(batch_np)  # compile (async)
    time.sleep(args.compile_wait_s)

    N = args.n
    rows = {}

    ts = []
    for i in range(N):
        b = np.stack(frames_np)
        t0 = time.perf_counter(); pipe.recognize_batch_packed(b)
        ts.append(time.perf_counter() - t0)
    rows["full_np_f32_ms"] = p50_ms(ts)

    ts = []
    for i in range(N):
        b = np.stack(frames_np)
        t0 = time.perf_counter(); jnp.asarray(b)
        ts.append(time.perf_counter() - t0)
    rows["h2d_only_ms"] = p50_ms(ts)

    dev_frames = jnp.asarray(batch_np)
    ts = []
    for i in range(N):
        t0 = time.perf_counter(); pipe.recognize_batch_packed(dev_frames)
        ts.append(time.perf_counter() - t0)
    rows["full_device_ms"] = p50_ms(ts)

    data = gallery.data
    key = pipe._step_key(dev_frames, data)
    fn = pipe._packed_cache[key]
    ts = []
    for i in range(N):
        t0 = time.perf_counter()
        fn(det.params, emb_params, data.embeddings, data.valid, data.labels,
           dev_frames)
        ts.append(time.perf_counter() - t0)
    rows["bare_pjit_ms"] = p50_ms(ts)

    # Params/gallery CLOSED OVER as jit constants: per-call argument
    # processing shrinks to the frames leaf alone. bare_pjit - bound_pjit
    # isolates the pytree-flatten share of the dispatch quote (the
    # serving step passes ~hundreds of param leaves per call on a 1-core
    # host) — the measured basis for a pre-bound serving fast path
    # (VERDICT r4 #4: pre-bound compiled calls / snapshot reuse).
    det_p, emb_p = det.params, emb_params
    g_emb, g_val, g_lab = data.embeddings, data.valid, data.labels

    @jax.jit
    def bound(fr):
        return fn(det_p, emb_p, g_emb, g_val, g_lab, fr)

    bound(dev_frames)  # compile (async) — a FULL retrace of the serving
    # graph with constants folded, so give it the full settle window
    time.sleep(args.compile_wait_s)
    ts = []
    for i in range(N):
        t0 = time.perf_counter()
        bound(dev_frames)
        ts.append(time.perf_counter() - t0)
    rows["bound_pjit_ms"] = p50_ms(ts)

    frames_u8 = [f.astype(np.uint8) for f in frames_np]
    pipe.recognize_batch_packed(np.stack(frames_u8))  # compile u8 variant
    time.sleep(args.compile_wait_s / 2)
    ts = []
    for i in range(N):
        b = np.stack(frames_u8)
        t0 = time.perf_counter(); pipe.recognize_batch_packed(b)
        ts.append(time.perf_counter() - t0)
    rows["full_np_u8_ms"] = p50_ms(ts)

    result = {
        "batch": batch,
        "frame_hw": [h, w],
        "device": str(dev),
        "date": time.strftime("%Y-%m-%d"),
        "note": ("p50 over pre-sync-poll dispatch-only calls (no readback "
                 "in-process). wrapper overhead = full_device - bare_pjit; "
                 "H2D share = full_np_f32 - full_device (compare h2d_only); "
                 "pjit arg handling + dispatch = bare_pjit; pytree-flatten "
                 "share = bare_pjit - bound_pjit (params closed over as "
                 "constants)."),
        **rows,
    }
    path = os.path.join(REPO, "BENCH_SERVING.json")
    try:
        doc = json.load(open(path))
    except (OSError, json.JSONDecodeError):
        doc = {}
    doc.setdefault("dispatch_decomposition", {})[str(batch)] = result
    from opencv_facerecognizer_tpu.utils.serialization import atomic_write_json

    atomic_write_json(path, doc)
    _log("merged dispatch_decomposition into BENCH_SERVING.json")
    print(json.dumps(result, indent=2))


if __name__ == "__main__":
    main()
