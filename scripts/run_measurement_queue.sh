#!/bin/bash
# Runs the full on-chip measurement queue in priority order, waiting for
# the TPU backend to become reachable first (written during the round-4
# axon tunnel outage; useful any time the artifacts need a full refresh):
# accuracy row -> headline bench -> lifecycle -> trace -> dispatch
# decomposition -> embedder sweep -> serving bench. Logs to
# /tmp/chip_queue.log and /tmp/q_<job>.log.
cd /root/repo
export PYTHONPATH=/root/repo:$PYTHONPATH
LOG=/tmp/chip_queue.log
echo "queue start $(date)" >> $LOG

# wait for the backend (probe every 60s)
while true; do
  if timeout 90 python -c "import jax; jax.devices()" >/dev/null 2>&1; then
    echo "TPU BACK $(date)" >> $LOG
    break
  fi
  sleep 60
done

run() {
  name=$1; shift
  echo "=== $name start $(date)" >> $LOG
  "$@" > /tmp/q_$name.log 2>&1
  echo "=== $name exit=$? $(date)" >> $LOG
}

# 1. refresh the cnn accuracy row (fold_min; unblocks the band test)
run cnn_measure python scripts/measure_accuracy.py --only cnn
# 2. headline bench at the new serving default (+ per-batch attribution)
run bench python bench.py
# 3. lifecycle with async grow
run lifecycle python scripts/bench_lifecycle.py
# 4. profiler trace summary
run trace python scripts/trace_summary.py
# 5. dispatch decomposition (batch 8 = latency mode, batch 32 = headline)
run dispatch8 python scripts/probe_dispatch.py --batch 8
run dispatch32 python scripts/probe_dispatch.py --batch 32
# 6. embedder sweep with @64 rows (mfu_exploration refresh)
run sweep python scripts/explore_perf.py --skip-detector
# 7. serving bench (latency model with new dispatch quote)
run serving python bench_serving.py
echo "queue done $(date)" >> $LOG
