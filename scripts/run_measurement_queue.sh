#!/bin/bash
# Runs the full on-chip measurement queue in priority order, waiting (with a
# BOUNDED budget) for the TPU backend to become reachable first. Written
# during the round-4 axon tunnel outage; useful any time the artifacts need
# a full refresh: accuracy row -> headline bench -> lifecycle -> trace ->
# dispatch decomposition -> embedder sweep -> serving bench.
#
# Supervision (round-5 hardening of the round-4 fire-and-forget loop):
# - bounded WAIT budget OCVF_QUEUE_MAX_WAIT_S (default 6h): cumulative time
#   spent waiting for the backend (probe time + sleeps; job runtime is NOT
#   charged — a long healthy queue must not trip a spurious give-up late);
#   on exhaustion the queue exits rc=3 with a GIVE-UP log line;
# - backend usability is owned by utils/backend_probe.py (same deadline
#   semantics and env knobs as bench.py / the dryrun, allow_cpu=False since
#   every job here is an on-chip measurement) and re-checked before EVERY
#   job (two processes sharing the one chip serialize and look like hangs —
#   a mid-queue outage must pause the queue, not let a job time out against
#   a dead or busy backend);
# - OCVF_DRYRUN_FORCE_CPU set => refuse immediately with the env var named
#   (waiting 6h to report "backend down" would misdiagnose an env override);
# - each job gets a hard timeout so one wedged job cannot eat the queue.
#
# Relaunch: this script is idempotent — each job overwrites its own
# artifact. To (re)start:   nohup bash scripts/run_measurement_queue.sh &
# Progress: tail -f /tmp/chip_queue.log ; per-job logs /tmp/q_<job>.log
cd /root/repo
export PYTHONPATH=/root/repo:$PYTHONPATH
LOG=/tmp/chip_queue.log
MAX_WAIT_S=${OCVF_QUEUE_MAX_WAIT_S:-21600}
JOB_TIMEOUT_S=${OCVF_QUEUE_JOB_TIMEOUT_S:-5400}
WAITED_ACC=0
GAVE_UP=0
BACK_LOGGED=0
echo "queue start $(date) (wait budget ${MAX_WAIT_S}s, job timeout ${JOB_TIMEOUT_S}s)" >> $LOG

if [ -n "$OCVF_DRYRUN_FORCE_CPU" ] && [ "$OCVF_DRYRUN_FORCE_CPU" != "0" ]; then
  echo "REFUSED: OCVF_DRYRUN_FORCE_CPU is set — on-chip queue cannot run under a forced-CPU override $(date)" >> $LOG
  exit 3
fi

probe() {
  # One source of truth for "backend usable": the same subprocess-with-
  # deadline probe bench.py and the dryrun use (honors
  # OCVF_BACKEND_PROBE_TIMEOUT_S identically). allow_cpu=False: a silent
  # CPU fallback must read as "down", not launch CPU measurements.
  python -c "from opencv_facerecognizer_tpu.utils.backend_probe import probe_default_backend; import sys; sys.exit(0 if probe_default_backend(allow_cpu=False)[0] else 1)" >/dev/null 2>&1
}

# Wait for the backend, charging probe time + sleeps (NOT job runtime)
# against the shared budget. Returns 1 on exhaustion. Logs TPU BACK once.
wait_for_backend() {
  [ $GAVE_UP -eq 1 ] && return 1
  local t0=$(date +%s)
  while ! probe; do
    BACK_LOGGED=0  # backend observed down: log recovery when it returns
    if [ $(( WAITED_ACC + $(date +%s) - t0 )) -ge "$MAX_WAIT_S" ]; then
      echo "GIVE UP: backend still down after $(( WAITED_ACC + $(date +%s) - t0 ))s cumulative wait $(date)" >> $LOG
      GAVE_UP=1
      return 1
    fi
    sleep 60
  done
  WAITED_ACC=$(( WAITED_ACC + $(date +%s) - t0 ))
  if [ $BACK_LOGGED -eq 0 ]; then
    echo "TPU BACK (cumulative wait ${WAITED_ACC}s) $(date)" >> $LOG
    BACK_LOGGED=1
  fi
  return 0
}

run() {
  name=$1; shift
  LAST_EXIT=125  # assume failure unless the job actually runs
  # Re-verify the backend is up AND idle before every job: a job launched
  # into a dead tunnel burns its whole timeout; one launched while another
  # process holds the chip serializes behind it and looks hung.
  if ! wait_for_backend; then
    echo "=== $name SKIPPED (backend down, budget exhausted) $(date)" >> $LOG
    return
  fi
  echo "=== $name start $(date)" >> $LOG
  timeout $JOB_TIMEOUT_S "$@" > /tmp/q_$name.log 2>&1
  LAST_EXIT=$?
  echo "=== $name exit=$LAST_EXIT $(date)" >> $LOG
}

# 1. refresh the cnn accuracy row (fold_min; unblocks the band test)
run cnn_measure python scripts/measure_accuracy.py --only cnn
# 2. headline bench at the new serving default (+ per-batch attribution)
run bench python bench.py
# 3. lifecycle with async grow
run lifecycle python scripts/bench_lifecycle.py
# 4. profiler trace summary
run trace python scripts/trace_summary.py
# 5. dispatch decomposition (batch 8 = latency mode, batch 32 = headline)
run dispatch8 python scripts/probe_dispatch.py --batch 8
run dispatch32 python scripts/probe_dispatch.py --batch 32
# 6. embedder sweep with @64 rows (mfu_exploration refresh)
run sweep python scripts/explore_perf.py --skip-detector
# 6b. fused pallas sepblock schedule A/B (flip serving default on a win)
run sepblock python scripts/bench_sepblock.py
# 6c. if THIS run's sepblock job succeeded (gate on its exit status — a
# stale sepblock_fused section from a prior refresh must not trigger the
# re-run) and the fused schedule won the A/B (>=5% at any measured batch,
# decision logic unit-tested in tests/test_queue_gate.py), re-measure the
# full headline under it, recorded as a SIBLING section so the default
# schedule's sweep stays intact for comparison
if [ "$LAST_EXIT" = "0" ] && python scripts/check_sepblock_win.py; then
  run bench_fused env OCVF_FUSED_EMBEDDER=1 OCVF_DETAIL_SECTION=sweep_fused python bench.py
fi
# 7. serving bench (latency model with new dispatch quote)
run serving python bench_serving.py
if [ $GAVE_UP -eq 1 ]; then
  echo "queue gave up (budget exhausted; some jobs skipped) $(date)" >> $LOG
  exit 3
fi
echo "queue done $(date)" >> $LOG
