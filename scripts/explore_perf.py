"""Measurement-driven selection of the round-3 MFU attack (VERDICT item
#1): candidate detector stems (space_to_depth x features) and embedder
block types, each briefly trained on the bench workload's synthetic scenes,
quality-checked (detector recall/precision@IoU .5; embedder verification
canary), and timed at batch 32 with the chained-differencing instrument.

This is an operator/dev tool, not part of bench.py: it exists so the
serving default is chosen by numbers on this chip, not by vibes. Output is
a JSON table on stdout; the chosen config gets wired as the bench/serving
default and re-measured by bench.py.

Run:  PYTHONPATH=. python scripts/explore_perf.py [--skip-embedder]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _log(msg):
    print(msg, file=sys.stderr, flush=True)


def chained_ms(fn, args):
    """Shared chained-differencing instrument (utils.benchtime)."""
    from opencv_facerecognizer_tpu.utils.benchtime import scalar_chain_ms

    return scalar_chain_ms(fn, args)


def detector_variants():
    import jax.numpy as jnp

    from opencv_facerecognizer_tpu.models.detector import (
        CNNFaceDetector, evaluate_detector,
    )
    from opencv_facerecognizer_tpu.utils.dataset import make_synthetic_scenes

    h = w = 256
    max_faces = 8
    batch = 32
    train = make_synthetic_scenes(num_scenes=64, scene_size=(h, w),
                                  max_faces=max_faces,
                                  face_size_range=(24, 56), seed=7)
    test = make_synthetic_scenes(num_scenes=48, scene_size=(h, w),
                                 max_faces=max_faces,
                                 face_size_range=(24, 56), seed=1234)
    frames = jnp.asarray(test[0][:batch], jnp.float32)

    variants = {
        "baseline_s1_16-32-64": dict(features=(16, 32, 64), space_to_depth=1),
        "s2d4_64-64": dict(features=(64, 64), space_to_depth=4),
        "s2d4_64-96": dict(features=(64, 96), space_to_depth=4),
        "s2d4_96-96": dict(features=(96, 96), space_to_depth=4),
        "s2d8_96": dict(features=(96,), space_to_depth=8),
        "s2d2_32-64-64": dict(features=(32, 64, 64), space_to_depth=2),
    }
    rows = {}
    for name, cfg in variants.items():
        det = CNNFaceDetector(max_faces=max_faces, score_threshold=0.3, **cfg)
        t0 = time.perf_counter()
        det.train(*train, steps=200, batch_size=16)
        train_s = time.perf_counter() - t0
        quality = evaluate_detector(det, *test)

        def fwd(params, frames, _det=det):
            out = _det.net.apply({"params": params}, frames)
            return (jnp.sum(out["heatmap"]) + jnp.sum(out["size"])
                    + jnp.sum(out["offset"]))

        ms = chained_ms(fwd, (det.params, frames))
        n_params = sum(int(np.prod(p.shape)) for p in
                       __import__("jax").tree_util.tree_leaves(det.params))
        if ms is None:  # chain delta never cleared readback quantization
            # Quality/train columns stay: they are valid regardless of the
            # timing outcome.
            rows[name] = {
                "ms_per_batch32_fwd": None, "invalid": "under-resolved",
                "recall": round(quality["recall"], 4),
                "precision": round(quality["precision"], 4),
                "mean_iou": round(quality["mean_matched_iou"], 3),
                "params": n_params,
                "train_s": round(train_s, 1),
            }
            _log(f"[det {name}] UNRESOLVED timing ({n_params} params)")
            continue
        rows[name] = {
            "ms_per_batch32_fwd": round(ms, 3),
            "recall": round(quality["recall"], 4),
            "precision": round(quality["precision"], 4),
            "mean_iou": round(quality["mean_matched_iou"], 3),
            "params": n_params,
            "train_s": round(train_s, 1),
        }
        _log(f"[det {name}] {ms:.3f} ms/b32, recall {quality['recall']:.3f} "
             f"precision {quality['precision']:.3f} iou "
             f"{quality['mean_matched_iou']:.3f} ({n_params} params)")
    return rows


#: Round-4 structural grid (VERDICT r3 item #1): the round-3 sweep only
#: swapped block types; embed stayed the worst stage (0.403 ms of the
#:  0.917 ms batch at MFU 0.0998). These variants attack the two named
#: suspects — the 1-channel 112x112 stem (same MXU-starving pathology the
#: detector's s2d fixed) and the per-conv GroupNorms (VPU reductions
#: between MXU calls) — plus wider-channels-at-lower-resolution.
EMBEDDER_VARIANTS = {
    "baseline_sep_s1_full": dict(block="separable"),
    "sep_s1_light": dict(block="separable", norm="light"),
    "sep_s2d2_full": dict(block="separable", space_to_depth=2),
    "sep_s2d4_full": dict(block="separable", space_to_depth=4),
    "sep_s2d4_light": dict(block="separable", space_to_depth=4,
                           norm="light"),
    "sep_s2d2_light": dict(block="separable", space_to_depth=2,
                           norm="light"),
    "dense_s2d4": dict(block="dense", space_to_depth=4),
    "dense_s2d4_wide_96-192-256": dict(block="dense", space_to_depth=4,
                                       stage_features=(96, 192, 256)),
    "sep_s2d4_light_wide_96-192-256": dict(
        block="separable", space_to_depth=4, norm="light",
        stage_features=(96, 192, 256)),
    # @64 rows: the accuracy gate protocol (and its >=0.99 measured
    # configs) run at 64x64 input — serving at the GATED resolution is an
    # accuracy-neutral structural change, unlike the s2d/norm folds above.
    "acc_cfg_sep_s1_full_64-128-256@64": dict(
        block="separable", stage_features=(64, 128, 256), input_size=64,
        embed_dim=256),
    "dense_s2d4_64-128-256@64": dict(
        block="dense", space_to_depth=4, stage_features=(64, 128, 256),
        input_size=64, embed_dim=256),
    "dense_s2d2_64-128-256@64": dict(
        block="dense", space_to_depth=2, stage_features=(64, 128, 256),
        input_size=64, embed_dim=256),
}


def embedder_variants():
    import jax
    import jax.numpy as jnp

    from opencv_facerecognizer_tpu.models.embedder import (
        FaceEmbedNet, init_embedder, normalize_faces,
    )

    V5E_BF16_PEAK_TFLOPS = 197.0  # matches bench.py's MFU denominator
    batch = 256  # 32 frames x 8 slots, the fused graph's embed batch

    rows = {}
    for name, cfg in EMBEDDER_VARIANTS.items():
        sz = int(cfg.get("input_size", 112))
        size = (sz, sz)
        frames = jnp.asarray(
            np.random.default_rng(0).normal(120, 40, (batch, *size)),
            jnp.float32)
        net = FaceEmbedNet(embed_dim=cfg.get("embed_dim", 128),
                           stem_features=32,
                           stage_features=cfg.get("stage_features",
                                                  (64, 128, 128)),
                           stage_blocks=cfg.get("stage_blocks", (2, 2, 2)),
                           block=cfg.get("block", "separable"),
                           space_to_depth=cfg.get("space_to_depth", 1),
                           norm=cfg.get("norm", "full"))
        params = init_embedder(net, num_classes=8, input_shape=size,
                               seed=0)["net"]

        def fwd(p, x, _net=net, _size=size):
            return jnp.sum(_net.apply({"params": p},
                                      normalize_faces(x, _size)))

        # Per-variant FLOPs from XLA's cost analysis of the standalone
        # forward, so the table carries an MFU column directly comparable
        # to bench.py's stage attribution.
        try:
            compiled = jax.jit(fwd).lower(params, frames).compile()
            flops = float(compiled.cost_analysis().get("flops", float("nan")))
        except Exception:  # ocvf-lint: disable=swallowed-exception -- cost_analysis is optional diagnostics on some backends; the NaN MFU column in the report IS the visible record of the failure
            flops = float("nan")
        ms = chained_ms(fwd, (params, frames))
        n_params = sum(int(np.prod(p.shape))
                       for p in jax.tree_util.tree_leaves(params))
        if ms is None:  # chain delta never cleared readback quantization
            rows[name] = {"ms_per_256crops_fwd": None,
                          "invalid": "under-resolved", "params": n_params}
            _log(f"[emb {name}] UNRESOLVED timing ({n_params} params)")
            continue
        tflops = flops / (ms / 1e3) / 1e12 if np.isfinite(flops) else float("nan")
        mfu = tflops / V5E_BF16_PEAK_TFLOPS
        rows[name] = {
            "ms_per_256crops_fwd": round(ms, 3),
            "gflop": round(flops / 1e9, 3) if np.isfinite(flops) else None,
            "mfu_vs_bf16_peak": round(mfu, 4) if np.isfinite(mfu) else None,
            "params": n_params,
        }
        _log(f"[emb {name}] {ms:.3f} ms/256 crops, MFU {mfu:.3f} "
             f"({n_params} params)")
    return rows


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--skip-embedder", action="store_true")
    ap.add_argument("--skip-detector", action="store_true")
    args = ap.parse_args(argv)
    import jax

    out = {"device": str(jax.devices()[0]), "date": time.strftime("%Y-%m-%d")}
    if not args.skip_detector:
        out["detector"] = detector_variants()
    if not args.skip_embedder:
        out["embedder"] = embedder_variants()
    print(json.dumps(out, indent=2))


if __name__ == "__main__":
    main()
