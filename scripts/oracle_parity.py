"""Oracle parity for the classic models (VERDICT r3 missing #1 / item #3).

The reference's Eigenfaces/Fisherfaces/LBPH have never run on the same data
as this framework (its mount is empty, and the real ORL/Yale-B/LFW images
are unreachable), so "matching the reference" needs a same-data baseline
column. This script is that column: an INDEPENDENT pure NumPy/SciPy
implementation of the three classic algorithms — the same published math
the reference family implements (Turk-Pentland PCA, Belhumeur PCA(N-c)+LDA,
Ahonen LBPH with chi-square) — run k-fold on the SAME synthetic datasets
and the SAME stratified folds as the framework, on both the easy and hard
protocols.

Deliberately shared with the framework (data plumbing, not the algorithm
under test): `make_synthetic_faces` and `stratified_kfold_indices`.
Everything algorithmic — preprocessing, subspace fits, LBP codes,
histograms, distances, classification — is re-derived here in NumPy with
no imports from the framework's ops/models.

Agreement bar (VERDICT): any framework-vs-oracle gap > ~2 pts must be
fixed or root-caused. Output: JSON to stdout + the ORACLE block of
BASELINE.md rewritten in place + cache at scripts/.oracle_cache.json.

Run:  PYTHONPATH=. python scripts/oracle_parity.py [--only CONFIG] [--skip-framework]
"""

from __future__ import annotations

import argparse
import json
import math
import os
import re
import sys
import time

import numpy as np
from scipy import linalg as sla
from scipy import ndimage

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

BEGIN = "<!-- ORACLE:BEGIN (scripts/oracle_parity.py) -->"
END = "<!-- ORACLE:END -->"
CACHE = os.path.join(REPO, "scripts", ".oracle_cache.json")


def _log(msg):
    print(msg, file=sys.stderr, flush=True)


# ---------------------------------------------------------------------------
# Oracle algorithm implementations (NumPy/SciPy only)
# ---------------------------------------------------------------------------


def tan_triggs_np(x: np.ndarray, alpha=0.1, tau=10.0, gamma=0.2,
                  sigma0=2.0, sigma1=4.0) -> np.ndarray:
    """Tan & Triggs 2010 illumination normalization: gamma -> DoG ->
    two-stage contrast equalization -> tanh squash. [N, H, W] float."""
    x = np.asarray(x, np.float32)
    xg = np.power(x + 1.0, gamma)
    # truncate=3.0 + mode="nearest" mirrors a radius-ceil(3 sigma),
    # edge-replicated blur.
    blur = lambda img, s: ndimage.gaussian_filter(
        img, sigma=(0, s, s), mode="nearest", truncate=3.0)
    dog = blur(xg, sigma0) - blur(xg, sigma1)
    m1 = np.mean(np.abs(dog) ** alpha, axis=(-2, -1), keepdims=True)
    dog = dog / np.maximum(m1, 1e-12) ** (1.0 / alpha)
    m2 = np.mean(np.minimum(np.abs(dog), tau) ** alpha, axis=(-2, -1),
                 keepdims=True)
    dog = dog / np.maximum(m2, 1e-12) ** (1.0 / alpha)
    return tau * np.tanh(dog / tau)


def pca_fit_np(X: np.ndarray, k: int):
    """Turk-Pentland eigenfaces fit on row-vectors [N, D] via SVD."""
    mean = X.mean(axis=0)
    Xc = X - mean
    # economy SVD: right singular vectors are the eigenfaces
    _, s, vt = np.linalg.svd(Xc, full_matrices=False)
    return mean, vt[:k].T  # [D, k]


def fisherfaces_fit_np(X: np.ndarray, y: np.ndarray):
    """Belhumeur Fisherfaces: PCA to (N - c) dims, then LDA to (c - 1).

    LDA solved as the generalized symmetric eigenproblem Sb v = l Sw v via
    scipy.linalg.eigh — an independent route from the framework's
    Cholesky-whitening implementation."""
    classes = np.unique(y)
    c = len(classes)
    n = X.shape[0]
    mean, Wpca = pca_fit_np(X, max(1, n - c))
    Z = (X - mean) @ Wpca  # [N, n-c]
    gmean = Z.mean(axis=0)
    d = Z.shape[1]
    Sw = np.zeros((d, d), np.float64)
    Sb = np.zeros((d, d), np.float64)
    for cls in classes:
        Zi = Z[y == cls]
        mi = Zi.mean(axis=0)
        Zc = Zi - mi
        Sw += Zc.T @ Zc
        dm = (mi - gmean)[:, None]
        Sb += len(Zi) * (dm @ dm.T)
    # Shrinkage-regularized Sw (standard regularized-LDA practice: the
    # PCA'd scatter is near-singular in its trailing directions) ...
    Sw += np.eye(d) * 1e-4 * np.trace(Sw) / d
    evals, evecs = sla.eigh(Sb, Sw)
    order = np.argsort(evals)[::-1][: c - 1]
    Wlda = evecs[:, order]  # [n-c, c-1]
    # ... and unit-norm projection columns: generalized eigvecs come back
    # Sw-orthonormal (v' Sw v = 1), which scales low-variance (noise)
    # directions up by orders of magnitude — a Euclidean NN on such
    # coordinates is dominated by noise. Unit-norm is the published
    # convention for Fisherfaces projection bases.
    Wlda = Wlda / np.maximum(np.linalg.norm(Wlda, axis=0, keepdims=True),
                             1e-12)
    return mean, Wpca @ Wlda  # [D, c-1]


def lbp_codes_np(x: np.ndarray, radius: int = 2, neighbors: int = 8) -> np.ndarray:
    """Ahonen extended/circular LBP codes with bilinear sampling.

    [N, H, W] -> [N, H-2r, W-2r] uint8-range ints. Sample k at angle
    2 pi k / P, (dy, dx) = (-r sin, r cos), >= comparison to the center."""
    x = np.asarray(x, np.float32)
    n, h, w = x.shape
    c = x[:, radius:h - radius, radius:w - radius]
    code = np.zeros(c.shape, np.int32)
    for k in range(neighbors):
        theta = 2.0 * math.pi * k / neighbors
        dy, dx = -radius * math.sin(theta), radius * math.cos(theta)
        fy, fx = math.floor(dy), math.floor(dx)
        ty, tx = dy - fy, dx - fx
        patch = np.zeros_like(c)
        for (oy, ox, wgt) in ((0, 0, (1 - ty) * (1 - tx)),
                              (0, 1, (1 - ty) * tx),
                              (1, 0, ty * (1 - tx)),
                              (1, 1, ty * tx)):
            if wgt == 0.0:
                continue
            y0, x0 = radius + fy + oy, radius + fx + ox
            patch += wgt * x[:, y0:y0 + c.shape[1], x0:x0 + c.shape[2]]
        code += (1 << k) * (patch >= c).astype(np.int32)
    return code


def spatial_hist_np(codes: np.ndarray, grid=(8, 8), num_bins=256) -> np.ndarray:
    """Per-cell L1-normalized histograms over a center-cropped grid,
    concatenated: [N, Hc, Wc] -> [N, gy*gx*num_bins]."""
    n, h, w = codes.shape
    gy, gx = grid
    ch, cw = h // gy, w // gx
    y0, x0 = (h - gy * ch) // 2, (w - gx * cw) // 2
    codes = codes[:, y0:y0 + gy * ch, x0:x0 + gx * cw]
    cells = codes.reshape(n, gy, ch, gx, cw).transpose(0, 1, 3, 2, 4)
    cells = cells.reshape(n, gy * gx, ch * cw)
    out = np.zeros((n, gy * gx, num_bins), np.float32)
    for i in range(n):
        for j in range(gy * gx):
            out[i, j] = np.bincount(cells[i, j], minlength=num_bins)
    out /= np.maximum(out.sum(axis=-1, keepdims=True), 1e-12)
    return out.reshape(n, gy * gx * num_bins)


def nn_classify_np(train_f, train_y, test_f, metric: str) -> np.ndarray:
    """1-NN under euclidean, chi-square, or cosine, blocked to bound
    memory."""
    preds = np.empty(len(test_f), train_y.dtype)
    if metric == "cosine":  # loop-invariant: normalize the train side once
        train_n = train_f / np.maximum(
            np.linalg.norm(train_f, axis=-1, keepdims=True), 1e-12)
    for i0 in range(0, len(test_f), 64):
        t = test_f[i0:i0 + 64]
        if metric == "euclidean":
            d = ((t[:, None, :] - train_f[None, :, :]) ** 2).sum(-1)
        elif metric == "chi_square":
            diff = t[:, None, :] - train_f[None, :, :]
            s = np.maximum(t[:, None, :] + train_f[None, :, :], 1e-12)
            d = (diff * diff / s).sum(-1)
        elif metric == "cosine":
            tn = t / np.maximum(
                np.linalg.norm(t, axis=-1, keepdims=True), 1e-12)
            d = 1.0 - tn @ train_n.T
        else:
            raise ValueError(metric)
        preds[i0:i0 + 64] = train_y[np.argmin(d, axis=1)]
    return preds


# ---------------------------------------------------------------------------
# Oracle k-fold drivers (same folds as the framework's validation)
# ---------------------------------------------------------------------------


def oracle_kfold(kind: str, X: np.ndarray, y: np.ndarray, k: int) -> float:
    from opencv_facerecognizer_tpu.utils.validation import (
        stratified_kfold_indices,
    )

    X = np.asarray(X, np.float32)
    y = np.asarray(y)
    if kind == "lbph":
        # descriptors are per-image and fold-independent: compute once
        feats_all = spatial_hist_np(lbp_codes_np(X, radius=2, neighbors=8))
    elif kind == "lbp_fisherfaces":
        # the round-5 robustness config: RAW r=3 codes, coarse 6x6 grid
        feats_all = spatial_hist_np(lbp_codes_np(X, radius=3, neighbors=8),
                                    grid=(6, 6))
    folds = stratified_kfold_indices(y, k, seed=0)
    correct = total = 0
    for test_idx in folds:
        if len(test_idx) == 0:
            continue
        mask = np.ones(len(y), bool)
        mask[test_idx] = False
        if kind == "eigenfaces":
            Xtr = X[mask].reshape(mask.sum(), -1)
            Xte = X[test_idx].reshape(len(test_idx), -1)
            mean, W = pca_fit_np(Xtr, min(Xtr.shape))
            ftr, fte = (Xtr - mean) @ W, (Xte - mean) @ W
            preds = nn_classify_np(ftr, y[mask], fte, "euclidean")
        elif kind == "fisherfaces":
            # trainer default chain: TanTriggs(sigma0=2, sigma1=4) first
            Xp = tan_triggs_np(X)
            Xtr = Xp[mask].reshape(mask.sum(), -1)
            Xte = Xp[test_idx].reshape(len(test_idx), -1)
            mean, W = fisherfaces_fit_np(Xtr, y[mask])
            ftr, fte = (Xtr - mean) @ W, (Xte - mean) @ W
            preds = nn_classify_np(ftr, y[mask], fte, "euclidean")
        elif kind == "lbph":
            preds = nn_classify_np(feats_all[mask], y[mask],
                                   feats_all[test_idx], "chi_square")
        elif kind == "lbp_fisherfaces":
            mean, W = fisherfaces_fit_np(feats_all[mask], y[mask])
            ftr = (feats_all[mask] - mean) @ W
            fte = (feats_all[test_idx] - mean) @ W
            preds = nn_classify_np(ftr, y[mask], fte, "cosine")
        else:
            raise ValueError(kind)
        correct += int((preds == y[test_idx]).sum())
        total += len(test_idx)
    return correct / total


def framework_kfold(kind: str, X, y, names, k: int) -> float:
    from opencv_facerecognizer_tpu.runtime.trainer import (
        TheTrainer, TrainerConfig,
    )

    trainer = TheTrainer(TrainerConfig(model=kind, kfold=k))
    trainer.train(X, y, names, validate=True)
    return float(trainer.mean_accuracy)


# ---------------------------------------------------------------------------
# Protocol matrix: identical datasets for both columns
# ---------------------------------------------------------------------------

#: mirrors scripts/measure_accuracy.py HARD_POSE / HARD_WILD
HARD_POSE = dict(rotation=8.0, scale_jitter=0.08, elastic=1.2, occlusion=0.25)
HARD_WILD = dict(rotation=12.0, scale_jitter=0.12, elastic=1.8, occlusion=0.3)

CONFIGS = {
    # key -> (kind, dataset kwargs, k)
    "eigenfaces_easy": ("eigenfaces", dict(num_subjects=40, per_subject=10,
                                           seed=1), 10),
    "eigenfaces_hard": ("eigenfaces", dict(num_subjects=40, per_subject=10,
                                           seed=1, **HARD_POSE), 10),
    "fisherfaces_easy": ("fisherfaces", dict(num_subjects=30, per_subject=12,
                                             seed=2, illumination=0.7,
                                             noise=14.0), 10),
    "fisherfaces_hard": ("fisherfaces", dict(num_subjects=30, per_subject=12,
                                             seed=2, illumination=0.7,
                                             noise=14.0, **HARD_POSE), 10),
    "lbph_easy": ("lbph", dict(num_subjects=40, per_subject=8, seed=3,
                               noise=18.0), 10),
    "lbph_hard": ("lbph", dict(num_subjects=40, per_subject=8, seed=3,
                               noise=18.0, **HARD_WILD), 10),
    "lbp_fisherfaces_easy": ("lbp_fisherfaces",
                             dict(num_subjects=30, per_subject=12, seed=2,
                                  illumination=0.7, noise=14.0), 10),
    "lbp_fisherfaces_hard": ("lbp_fisherfaces",
                             dict(num_subjects=30, per_subject=12, seed=2,
                                  illumination=0.7, noise=14.0,
                                  **HARD_POSE), 10),
}


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--only", action="append", choices=sorted(CONFIGS))
    ap.add_argument("--skip-framework", action="store_true",
                    help="oracle column only (framework rows keep cache)")
    ap.add_argument("--cpu", action="store_true",
                    help="force the host backend for the framework column "
                         "(accuracy is backend-independent; see "
                         "measure_accuracy.py --cpu)")
    args = ap.parse_args(argv)
    if args.cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")
    selected = args.only or sorted(CONFIGS)

    from opencv_facerecognizer_tpu.utils.dataset import make_synthetic_faces

    results = {}
    if os.path.exists(CACHE):
        try:
            results.update(json.load(open(CACHE)))
        except (json.JSONDecodeError, OSError) as e:
            _log(f"ignoring unreadable cache {CACHE}: {e}")

    for key in selected:
        kind, data_kwargs, k = CONFIGS[key]
        X, y, names = make_synthetic_faces(size=(70, 70), **data_kwargs)
        row = dict(results.get(key) or {})
        t0 = time.perf_counter()
        row["oracle"] = round(oracle_kfold(kind, X, y, k), 4)
        row["oracle_s"] = round(time.perf_counter() - t0, 1)
        if not args.skip_framework:
            t0 = time.perf_counter()
            row["framework"] = round(framework_kfold(kind, X, y, names, k), 4)
            row["framework_s"] = round(time.perf_counter() - t0, 1)
        if "framework" in row:
            row["delta"] = round(row["framework"] - row["oracle"], 4)
        row["dataset"] = (f"synthetic 70x70 "
                          + ", ".join(f"{kk}={vv}" for kk, vv in
                                      data_kwargs.items()) + f", {k}-fold")
        results[key] = row
        _log(f"[{key}] oracle {row['oracle']:.4f}"
             + (f" framework {row['framework']:.4f} "
                f"delta {row['delta']:+.4f}" if "framework" in row else ""))

    results["_meta"] = {"date": time.strftime("%Y-%m-%d")}
    from opencv_facerecognizer_tpu.utils.serialization import atomic_write_json

    atomic_write_json(CACHE, results)
    print(json.dumps(results, indent=2))

    # -- render the BASELINE.md ORACLE block --
    label = {
        "eigenfaces": "Eigenfaces (PCA+NN)",
        "fisherfaces": "Fisherfaces (TanTriggs + PCA+LDA+NN)",
        "lbph": "LBPH (ExtendedLBP r=2 + ChiSquare NN)",
        "lbp_fisherfaces": "LBP-Fisherfaces (raw r=3 6x6 + PCA+LDA + cosine)",
    }
    lines = [BEGIN, "",
             "| Config | Protocol | Framework (TPU) | Oracle (NumPy/SciPy) "
             "| Delta |", "|---|---|---|---|---|"]
    for key in sorted(CONFIGS):
        if key not in results:
            continue
        r = results[key]
        kind = CONFIGS[key][0]
        proto = "hard" if key.endswith("hard") else "easy"
        fw = f"{r['framework']:.4f}" if "framework" in r else "—"
        dl = f"{r['delta']:+.4f}" if "delta" in r else "—"
        lines.append(f"| {label[kind]} | {proto} | **{fw}** | {r['oracle']:.4f} "
                     f"| {dl} |")
    lines += [
        "",
        "Same synthetic datasets, same stratified folds "
        "(`utils.validation.stratified_kfold_indices`), independent NumPy/"
        "SciPy implementations of the published algorithms "
        "(`scripts/oracle_parity.py`). Easy rows use each config's "
        "pre-round-3 distribution (noise/illumination only); hard rows add "
        "the round-3 pose/scale/elastic/occlusion axes. Agreement within "
        "~2 pts means the framework's numbers are the algorithms' ceiling "
        "on that data, not implementation artifacts. Refreshed "
        f"{results['_meta']['date']}.", END]
    block = "\n".join(lines)

    path = os.path.join(REPO, "BASELINE.md")
    text = open(path).read()
    if BEGIN in text:
        text = re.sub(re.escape(BEGIN) + r".*?" + re.escape(END), block, text,
                      flags=re.S)
    else:
        text = (text.rstrip()
                + "\n\n## Oracle parity (classic models, same data/folds)\n\n"
                + block + "\n")
    from opencv_facerecognizer_tpu.utils.serialization import atomic_write_text

    atomic_write_text(path, text)
    _log("BASELINE.md oracle block updated")


if __name__ == "__main__":
    main()
