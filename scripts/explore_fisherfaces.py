"""Fisherfaces robustness attack (VERDICT r4 next-step #8).

The hard Yale-B-analog row (30x12, illumination 0.7, noise 14, HARD_POSE)
measures 0.8283 with TanTriggs -> Fisherfaces -> NN, and the independent
oracle confirms 0.8306 is the LINEAR subspace's ceiling on this
distribution — so this script attacks the *algorithm*, not the
implementation, with the robustness toolbox the framework already ships:

- locality: SpatialHistogram(LBP) features survive occluding rectangles
  (a cutout corrupts a few cells, not every projection coefficient the
  way it corrupts a global Fisher axis);
- discriminative locality: SpatialHistogram -> Fisherfaces (PCA->LDA on
  the histogram vector) keeps the local robustness while re-adding the
  supervised projection;
- occlusion-robust distances: chi-square / histogram-intersection / BRD
  family on histogram features;
- nonlinear decision: KernelSVM(rbf) over the Fisher projection.

Every candidate runs the EXACT BASELINE protocol (same generator, seed,
folds: scripts/measure_accuracy.py fisherfaces row) via the public
PredictableModel + KFoldCrossValidation surface. Results append to
scripts/.fisher_attack.jsonl; the winner (if it clears the 0.87 bar)
graduates to a measured row in BASELINE.md.

Accuracy is backend-independent (same math on CPU and TPU; the classic
models' device graphs are identical modulo fp reassociation), so this
sweep runs wherever it is launched — use --cpu to force the host backend
when the TPU tunnel is down.

Run:  PYTHONPATH=. python scripts/explore_fisherfaces.py [--cpu]
      [--only NAME ...]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

OUT = os.path.join(REPO, "scripts", ".fisher_attack.jsonl")

#: the BASELINE fisherfaces_yaleb protocol, verbatim (measure_accuracy.py)
PROTOCOL = dict(num_subjects=30, per_subject=12, size=(70, 70), seed=2,
                illumination=0.7, noise=14.0, rotation=8.0,
                scale_jitter=0.08, elastic=1.2, occlusion=0.25)
FOLDS = 10


def candidates():
    """name -> thunk building (feature, classifier). Thunks import lazily so
    --only doesn't pay for unused graphs."""
    from opencv_facerecognizer_tpu.models.classifier import (
        KernelSVM, NearestNeighbor,
    )
    from opencv_facerecognizer_tpu.models.feature import (
        Fisherfaces, SpatialHistogram, TanTriggsPreprocessing,
    )
    from opencv_facerecognizer_tpu.models.operators import (
        ChainOperator, CombineOperator,
    )
    from opencv_facerecognizer_tpu.ops import lbp as lbp_ops
    from opencv_facerecognizer_tpu.ops.distance import (
        ChiSquareDistance, CosineDistance, EuclideanDistance,
        HistogramIntersection, L1BinRatioDistance,
    )

    tt = lambda: TanTriggsPreprocessing(sigma0=2.0, sigma1=4.0)  # noqa: E731
    elbp = lambda r: lbp_ops.ExtendedLBP(radius=r, neighbors=8)  # noqa: E731

    def hist(r=2, sz=(8, 8)):
        return SpatialHistogram(elbp(r), sz=sz)

    return {
        # the measured baseline, re-run here so every comparison shares one
        # code path + session
        "baseline_fisher_nn": lambda: (
            ChainOperator(tt(), Fisherfaces()),
            NearestNeighbor(EuclideanDistance()),
        ),
        # nonlinear decision over the same linear feature
        "fisher_rbf_svm": lambda: (
            ChainOperator(tt(), Fisherfaces()),
            KernelSVM(kernel="rbf"),
        ),
        # locality only (the lbph recipe pointed at THIS protocol)
        "lbp_chi2": lambda: (
            ChainOperator(tt(), hist()),
            NearestNeighbor(ChiSquareDistance()),
        ),
        "lbp_histint": lambda: (
            ChainOperator(tt(), hist()),
            NearestNeighbor(HistogramIntersection()),
        ),
        "lbp_l1brd": lambda: (
            ChainOperator(tt(), hist()),
            NearestNeighbor(L1BinRatioDistance()),
        ),
        # discriminative locality: LDA over the local histograms
        "lbp_fisher_cosine": lambda: (
            ChainOperator(tt(), ChainOperator(hist(), Fisherfaces())),
            NearestNeighbor(CosineDistance()),
        ),
        "lbp_fisher_nn": lambda: (
            ChainOperator(tt(), ChainOperator(hist(), Fisherfaces())),
            NearestNeighbor(EuclideanDistance()),
        ),
        # finer grid: more cells -> finer occlusion containment
        "lbp10_fisher_cosine": lambda: (
            ChainOperator(tt(), ChainOperator(hist(sz=(10, 10)), Fisherfaces())),
            NearestNeighbor(CosineDistance()),
        ),
        "lbp10_chi2": lambda: (
            ChainOperator(tt(), hist(sz=(10, 10))),
            NearestNeighbor(ChiSquareDistance()),
        ),
        # round 2 (after every round-1 challenger measured BELOW the 0.8283
        # baseline): ensembles + preprocessing ablations
        # global Fisher axes and local LBP-Fisher axes see different error
        # modes (illumination gradient vs occlusion); concatenate them
        "combine_fisher_lbpfisher": lambda: (
            CombineOperator(
                ChainOperator(tt(), Fisherfaces()),
                ChainOperator(tt(), ChainOperator(hist(), Fisherfaces())),
            ),
            NearestNeighbor(CosineDistance()),
        ),
        # LBP is illumination-invariant by construction — TanTriggs's
        # gamma+DoG may be destroying the texture LBP codes
        "rawlbp_chi2": lambda: (
            hist(),
            NearestNeighbor(ChiSquareDistance()),
        ),
        "rawlbp_fisher_cosine": lambda: (
            ChainOperator(hist(), Fisherfaces()),
            NearestNeighbor(CosineDistance()),
        ),
        # k=3 neighbor voting over the strong baseline feature
        "fisher_knn3": lambda: (
            ChainOperator(tt(), Fisherfaces()),
            NearestNeighbor(EuclideanDistance(), k=3),
        ),
        "fisher_cosine": lambda: (
            ChainOperator(tt(), Fisherfaces()),
            NearestNeighbor(CosineDistance()),
        ),
        # round 3: refine the round-2 winner (rawlbp_fisher_cosine 0.93)
        "rawlbp1_fisher_cosine": lambda: (
            ChainOperator(hist(r=1), Fisherfaces()),
            NearestNeighbor(CosineDistance()),
        ),
        "rawlbp10_fisher_cosine": lambda: (
            ChainOperator(hist(sz=(10, 10)), Fisherfaces()),
            NearestNeighbor(CosineDistance()),
        ),
        "rawlbp6_fisher_cosine": lambda: (
            ChainOperator(hist(sz=(6, 6)), Fisherfaces()),
            NearestNeighbor(CosineDistance()),
        ),
        "rawlbp_fisher_euclid": lambda: (
            ChainOperator(hist(), Fisherfaces()),
            NearestNeighbor(EuclideanDistance()),
        ),
        "rawlbp_fisher_knn3": lambda: (
            ChainOperator(hist(), Fisherfaces()),
            NearestNeighbor(CosineDistance(), k=3),
        ),
        # round 4: grid/radius around the 6x6 winner (0.9617)
        "rawlbp4_fisher_cosine": lambda: (
            ChainOperator(hist(sz=(4, 4)), Fisherfaces()),
            NearestNeighbor(CosineDistance()),
        ),
        "rawlbp5_fisher_cosine": lambda: (
            ChainOperator(hist(sz=(5, 5)), Fisherfaces()),
            NearestNeighbor(CosineDistance()),
        ),
        "rawlbp6r3_fisher_cosine": lambda: (
            ChainOperator(hist(r=3, sz=(6, 6)), Fisherfaces()),
            NearestNeighbor(CosineDistance()),
        ),
    }


def run_candidate(name, build):
    from opencv_facerecognizer_tpu.models.model import PredictableModel
    from opencv_facerecognizer_tpu.utils.dataset import make_synthetic_faces
    from opencv_facerecognizer_tpu.utils.validation import KFoldCrossValidation

    X, y, _ = make_synthetic_faces(**PROTOCOL)
    feature, classifier = build()
    model = PredictableModel(feature, classifier)
    t0 = time.perf_counter()
    cv = KFoldCrossValidation(k=FOLDS).validate(model, X, y)
    return {
        "name": name,
        "accuracy": round(float(cv.mean_accuracy), 4),
        "folds": FOLDS,
        "protocol": "fisherfaces_yaleb HARD (BASELINE row)",
        "seconds": round(time.perf_counter() - t0, 1),
        "date": time.strftime("%Y-%m-%d"),
    }


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--cpu", action="store_true",
                    help="force the host backend (accuracy is backend-"
                         "independent; use when the TPU tunnel is down)")
    ap.add_argument("--only", action="append")
    args = ap.parse_args(argv)

    import jax

    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    print(f"backend: {jax.devices()[0].platform}", file=sys.stderr)

    cands = candidates()
    selected = args.only or list(cands)
    for name in selected:
        if name not in cands:
            raise SystemExit(f"unknown candidate {name!r}; have {sorted(cands)}")
        row = run_candidate(name, cands[name])
        row["backend"] = jax.devices()[0].platform
        with open(OUT, "a") as fh:
            fh.write(json.dumps(row) + "\n")
        print(json.dumps(row), flush=True)


if __name__ == "__main__":
    main()
