"""A/B the gallery store dtype (f32 vs bf16 vs int8) at the 1M-row tier:
in-graph match cost (chained differencing — block_until_ready does not
await on this tunneled backend, see bench.py) and upload wall (device_put
+ the residency await the grow worker uses). f32 and bf16 compute
bf16 x bf16 -> f32 regardless of storage, so bf16 storage should halve
HBM traffic and upload bytes at identical math. The int8 arm measures the
IVF quantizer's storage format (``parallel.quantizer.quantize_rows``:
per-row scale, dequantized to bf16 in-graph before the same exact
kernel) — quarter the bytes of f32 with a measured, not assumed,
accuracy column (tie-aware top-1 agreement + max |sim diff| vs the f32
arm, the same comparator as the IVF recall gate).

Run:  PYTHONPATH=. python scripts/bench_gallery_dtype.py
Merges a "gallery_dtype" section into BENCH_DETAIL.json.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _log(msg):
    print(msg, file=sys.stderr, flush=True)


def main():
    import jax
    import jax.numpy as jnp

    from opencv_facerecognizer_tpu.parallel import ShardedGallery, make_mesh

    rows, dim, q_batch, k = 1_048_576, 256, 256, 1
    dev = jax.devices()[0]
    _log(f"device: {dev}; {rows} rows x {dim}")
    rng = np.random.default_rng(0)
    emb = rng.standard_normal((rows, dim), dtype=np.float32)
    emb /= np.linalg.norm(emb, axis=-1, keepdims=True)
    lab = rng.integers(0, 4096, rows).astype(np.int32)
    q = emb[:q_batch]

    result = {"rows": rows, "dim": dim, "q_batch": q_batch, "k": k,
              "device": str(dev), "date": time.strftime("%Y-%m-%d")}
    # Warm the H2D path first: the tunnel's FIRST put of a given shape
    # class runs ~40x slower than steady state (measured 36 vs 1564 MB/s),
    # which poisoned the first A/B's upload column for whichever arm ran
    # second-cold. GC between arms so host RSS from arm 1 can't distort
    # arm 2 on this 1-core/limited-RAM box.
    import gc

    warm = jax.device_put(emb[:65536])
    while not warm.is_ready():
        time.sleep(0.01)
    del warm

    # PHASE 1 — time BOTH installs before ANY device->host readback: the
    # first sync readback drops the process into the tunnel's ~100 ms
    # poll mode, where H2D collapses to ~36 MB/s (measured) — timing one
    # arm's install pre-readback and the other's post-readback charged a
    # 25x transfer-mode penalty to whichever arm ran second (the first
    # two A/B attempts did exactly that, in both orders).
    arms = ((jnp.float32, "f32"), (jnp.bfloat16, "bf16"))
    galleries = {}
    for dtype, name in arms:
        gc.collect()
        g = ShardedGallery(capacity=rows, dim=dim, mesh=make_mesh(),
                           store_dtype=dtype)
        g.add(emb, lab)
        ok = g._await_residency(g.data, 600.0)
        t0 = time.perf_counter()
        g._install(g._host_emb, g._host_lab, g._host_val, g.size)
        ok = g._await_residency(g.data, 600.0) and ok
        upload_s = time.perf_counter() - t0
        result[name] = {
            "upload_s": round(upload_s, 2), "residency_ok": bool(ok),
            "gallery_bytes": int(rows * dim * jnp.dtype(dtype).itemsize),
        }
        _log(f"[{name}] install (pre-readback) {upload_s:.2f}s")
        galleries[name] = g

    # int8 arm (still phase 1 — upload before any readback): the IVF
    # quantizer's storage format, per-row scale + int8 rows.
    from opencv_facerecognizer_tpu.parallel.quantizer import quantize_rows

    gc.collect()
    q8_host, scale_host = quantize_rows(emb)
    t0 = time.perf_counter()
    q8_dev = jax.device_put(q8_host)
    scale_dev = jax.device_put(scale_host)
    int8_ok = True
    deadline = time.monotonic() + 600.0
    while time.monotonic() < deadline:
        try:
            if q8_dev.is_ready() and scale_dev.is_ready():
                break
        except (AttributeError, NotImplementedError):
            break
        time.sleep(0.01)
    else:
        int8_ok = False
    result["int8"] = {
        "upload_s": round(time.perf_counter() - t0, 2),
        "residency_ok": int8_ok,
        "gallery_bytes": int(rows * dim + rows * 4),  # q8 + f32 scales
    }
    _log(f"[int8] install (pre-readback) {result['int8']['upload_s']:.2f}s")

    # PHASE 2 — chained match timing (readbacks allowed from here on).
    q_dev = jnp.asarray(q)
    for dtype, name in arms:
        g = galleries[name]
        match = g._matcher(k, g.data)

        def chain(n):
            labels, vals, idx = match(q_dev, g.data.embeddings,
                                      g.data.valid, g.data.labels)
            for _ in range(n - 1):
                q2 = q_dev + vals[0, 0] * 1e-30  # device-side dependency
                labels, vals, idx = match(q2, g.data.embeddings,
                                          g.data.valid, g.data.labels)
            return np.asarray(vals).sum()

        chain(2)  # compile + warm
        k1, k2 = 4, 64
        t1s, t2s = [], []
        for _ in range(3):
            t0 = time.perf_counter(); chain(k1); t1s.append(time.perf_counter() - t0)
            t0 = time.perf_counter(); chain(k2); t2s.append(time.perf_counter() - t0)
        ms = (min(t2s) - min(t1s)) / (k2 - k1) * 1e3
        result[name]["match_ms_per_call"] = round(ms, 3)
        _log(f"[{name}] match {ms:.3f} ms/call")
        if name == "f32":
            # Reference top-1 for the int8 accuracy column below.
            f32_vals, f32_idx = (np.asarray(v) for v in
                                 match(q_dev, g.data.embeddings,
                                       g.data.valid, g.data.labels)[1:])
        del galleries[name], g

    # int8 match arm: dequantize in-graph (bf16) then the SAME exact
    # streaming kernel — the IVF stage-2 cost model at full-gallery scale.
    from opencv_facerecognizer_tpu.ops.ivf_match import tie_aware_agreement
    from opencv_facerecognizer_tpu.ops.pallas_match import streaming_match_topk

    valid_dev = jnp.ones((rows,), bool)
    interpret = jax.devices()[0].platform != "tpu"

    @jax.jit
    def int8_match(q, q8d, sd, valid):
        gal = q8d.astype(jnp.bfloat16) * sd.astype(jnp.bfloat16)[:, None]
        return streaming_match_topk(q, gal, valid, k=k, interpret=interpret)

    def chain8(n):
        vals, idx = int8_match(q_dev, q8_dev, scale_dev, valid_dev)
        for _ in range(n - 1):
            vals, idx = int8_match(q_dev + vals[0, 0] * 1e-30, q8_dev,
                                   scale_dev, valid_dev)
        return np.asarray(vals).sum()

    chain8(2)
    k1, k2 = 4, 64
    t1s, t2s = [], []
    for _ in range(3):
        t0 = time.perf_counter(); chain8(k1); t1s.append(time.perf_counter() - t0)
        t0 = time.perf_counter(); chain8(k2); t2s.append(time.perf_counter() - t0)
    ms = (min(t2s) - min(t1s)) / (k2 - k1) * 1e3
    result["int8"]["match_ms_per_call"] = round(ms, 3)
    i8_vals, i8_idx = (np.asarray(v) for v in
                       int8_match(q_dev, q8_dev, scale_dev, valid_dev))
    result["int8"]["tie_aware_top1_agreement_vs_f32"] = round(
        tie_aware_agreement(i8_vals, i8_idx, f32_vals, f32_idx), 4)
    result["int8"]["max_abs_sim_diff_vs_f32"] = round(
        float(np.max(np.abs(i8_vals.reshape(-1) - f32_vals.reshape(-1)))), 6)
    _log(f"[int8] match {ms:.3f} ms/call, top-1 agreement "
         f"{result['int8']['tie_aware_top1_agreement_vs_f32']}")

    f, b = result["f32"], result["bf16"]
    result["upload_speedup"] = round(f["upload_s"] / b["upload_s"], 2)
    result["match_speedup"] = round(
        f["match_ms_per_call"] / b["match_ms_per_call"], 2)
    result["int8_match_speedup_vs_f32"] = round(
        f["match_ms_per_call"] / result["int8"]["match_ms_per_call"], 2)
    path = os.path.join(REPO, "BENCH_DETAIL.json")
    try:
        detail = json.load(open(path))
    except (OSError, json.JSONDecodeError):
        detail = {}
    detail["gallery_dtype"] = result
    from opencv_facerecognizer_tpu.utils.serialization import atomic_write_json

    atomic_write_json(path, detail)
    _log("merged gallery_dtype into BENCH_DETAIL.json")
    print(json.dumps(result, indent=2))


if __name__ == "__main__":
    main()
