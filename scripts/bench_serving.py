"""Thin forwarder so serving benches live with the other measurement
entrypoints: ``python scripts/bench_serving.py [--smoke] ...`` runs the
repo-root ``bench_serving.py`` (which owns the artifact format — see its
docstring for the sections and the smoke contract)."""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench_serving import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
