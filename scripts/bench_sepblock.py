"""On-chip A/B of the fused separable-block serving path (VERDICT r4 #6).

Measures ms/forward of the serving embedder at serving crop shapes
(batch x max_faces crops at SERVING_FACE_SIZE) under the shared
chained-differencing instrument, for:

- ``flax``: the training graph, ``net.apply`` (XLA grouped-conv depthwise
  lowering, per-op HBM roundtrips);
- ``fused``: ``models.embedder.fused_forward`` (one pallas call per block,
  VMEM-resident activations, dw conv as unrolled VPU FMAs, GDC einsum).

Equivalence is pinned by tests/test_pallas_sepblock.py; this script only
decides whether the fused schedule is FASTER on real hardware — the
serving default flips only on a measured win (the same
measured-or-it-didn't-happen bar every other perf claim in this repo
clears). Writes BENCH_DETAIL.json["sepblock_fused"].

Run:  PYTHONPATH=. python scripts/bench_sepblock.py [--batches 64,256]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

V5E_BF16_PEAK_TFLOPS = 197.0


def _log(msg):
    print(msg, file=sys.stderr, flush=True)


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--batches", default="64,256")
    ap.add_argument("--tiny", action="store_true",
                    help="small net + interpret mode: smoke-tests the "
                         "measurement path on CPU without touching "
                         "BENCH_DETAIL.json")
    args = ap.parse_args(argv)

    import jax
    import jax.numpy as jnp

    from opencv_facerecognizer_tpu.models.embedder import (
        SERVING_EMBEDDER_KWARGS, SERVING_FACE_SIZE, FaceEmbedNet,
        fused_forward, init_embedder,
    )
    from opencv_facerecognizer_tpu.utils.benchtime import scalar_chain_ms

    if args.tiny:
        jax.config.update("jax_platforms", "cpu")
        net = FaceEmbedNet(embed_dim=16, stem_features=8,
                           stage_features=(8, 16), stage_blocks=(1, 1))
        face = (32, 32)
        batches = [4]
        interpret = True
    else:
        net = FaceEmbedNet(**SERVING_EMBEDDER_KWARGS)
        face = SERVING_FACE_SIZE
        batches = [int(b) for b in args.batches.split(",")]
        interpret = False
    dev = jax.devices()[0]
    _log(f"device: {dev}")
    params = init_embedder(net, num_classes=16, input_shape=face,
                           seed=0)["net"]
    rng = np.random.default_rng(0)

    def flax_scalar(p, x):
        return jnp.sum(net.apply({"params": p}, x))

    def fused_scalar(p, x):
        return jnp.sum(fused_forward(net, p, x, interpret=interpret))

    # analytic FLOPs of the flax forward = the work both schedules do
    flops = float("nan")
    try:
        x0 = jnp.zeros((batches[0], *face), jnp.float32)
        lowered = jax.jit(lambda p, x: net.apply({"params": p}, x)).lower(
            params, x0)
        ca = lowered.compile().cost_analysis()
        ca = ca[0] if isinstance(ca, list) else ca
        flops = float(ca.get("flops", float("nan"))) / batches[0]
    except Exception as e:  # noqa: BLE001 — cost analysis is best-effort
        _log(f"cost analysis unavailable: {e}")

    results = {}
    for batch in batches:
        x = jnp.asarray(rng.normal(size=(batch, *face)).astype(np.float32))
        row = {}
        for name, scalar in (("flax", flax_scalar), ("fused", fused_scalar)):
            try:
                ms = scalar_chain_ms(scalar, (params, x))
            except Exception as e:  # noqa: BLE001 — a Mosaic lowering
                # rejection on real hardware must land in the artifact,
                # not kill the queue job.
                row[name] = {"error": repr(e)[:500]}
                _log(f"batch {batch} {name}: FAILED {e!r}")
                continue
            entry = {"ms_per_forward": None if ms is None else round(ms, 4)}
            if ms and np.isfinite(flops):
                tflops = flops * batch / (ms / 1e3) / 1e12
                entry["tflops"] = round(tflops, 2)
                entry["mfu_vs_bf16_peak"] = round(
                    tflops / V5E_BF16_PEAK_TFLOPS, 4)
            row[name] = entry
            _log(f"batch {batch} {name}: {entry}")
        f_ms = row.get("flax", {}).get("ms_per_forward")
        p_ms = row.get("fused", {}).get("ms_per_forward")
        if f_ms and p_ms:
            row["speedup"] = round(f_ms / p_ms, 3)
        results[str(batch)] = row

    doc = {
        "device": str(dev),
        "date": time.strftime("%Y-%m-%d"),
        "face_size": list(face),
        "flops_per_sample": None if not np.isfinite(flops) else flops,
        "note": ("chained-differencing ms/forward of the serving embedder: "
                 "flax graph vs fused pallas schedule (same params, "
                 "equivalence pinned in tests). Flip the serving default "
                 "only on a measured speedup here."),
        "batches": results,
    }
    print(json.dumps(doc, indent=2))
    if args.tiny:
        return
    detail_path = os.path.join(REPO, "BENCH_DETAIL.json")
    try:
        detail = json.load(open(detail_path))
    except (OSError, json.JSONDecodeError):
        detail = {}
    detail["sepblock_fused"] = doc
    from opencv_facerecognizer_tpu.utils.serialization import atomic_write_json

    atomic_write_json(detail_path, detail)
    _log("merged sepblock_fused into BENCH_DETAIL.json")


if __name__ == "__main__":
    main()
