"""Profiler-trace evidence for the fused serving step (VERDICT r3 item #2):
capture a jax.profiler trace of the batch-32 fused graph, parse it with
jax.profiler.ProfileData (no TensorBoard needed), and land a trace_summary
— top device ops by self time and the device busy/idle fraction — in
BENCH_DETAIL.json. This is the "why is the chip 87% idle" artifact the
stage attribution (which explains *where the milliseconds* go) cannot
answer on its own.

Run:  PYTHONPATH=. python scripts/trace_summary.py [--steps 64] [--batch 32]
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import time
from collections import defaultdict

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _log(msg):
    print(msg, file=sys.stderr, flush=True)


def build_pipeline(batch, h, w, max_faces, dim, tiny=False):
    import jax.numpy as jnp

    from opencv_facerecognizer_tpu.models.detector import CNNFaceDetector
    from opencv_facerecognizer_tpu.models.embedder import (
        FaceEmbedNet, init_embedder,
    )
    from opencv_facerecognizer_tpu.parallel import ShardedGallery, make_mesh
    from opencv_facerecognizer_tpu.parallel.pipeline import RecognitionPipeline
    from opencv_facerecognizer_tpu.utils.dataset import make_synthetic_scenes

    if tiny:
        det = CNNFaceDetector(features=(8, 8), head_features=8,
                              max_faces=max_faces, score_threshold=0.0,
                              space_to_depth=2)
        import jax as _jax
        det.load_params(det.net.init(_jax.random.PRNGKey(0),
                                     jnp.zeros((1, h, w)))["params"])
        face = (32, 32)
        cap = 256
        scenes = make_synthetic_scenes(num_scenes=batch, scene_size=(h, w),
                                       max_faces=max_faces, seed=7)[0]
        net = FaceEmbedNet(embed_dim=dim, stem_features=8,
                           stage_features=(8,), stage_blocks=(1,))
        emb_params = init_embedder(net, num_classes=4, input_shape=face,
                                   seed=0)["net"]
    else:
        # The SERVING-default pipeline, via the one shared constructor
        # (bench_serving.build_pipeline) so this artifact can never drift
        # from the config the serving benches measure.
        import bench_serving

        pipe, frame_pool = bench_serving.build_pipeline(
            frame_hw=(h, w), gallery_size=16384)
        frames = jnp.asarray(np.stack(
            [frame_pool[i % len(frame_pool)] for i in range(batch)]),
            jnp.float32)
        return pipe, frames
    rng = np.random.default_rng(0)
    # bf16 rows: the ocvf-recognize serving default (gallery_dtype A/B)
    gallery = ShardedGallery(capacity=cap, dim=dim, mesh=make_mesh(),
                             store_dtype=jnp.bfloat16)
    gallery.add(rng.normal(size=(cap, dim)).astype(np.float32),  # ocvf-lint: boundary=wal-before-mutate -- trace fixture: synthetic gallery, traces are the artifact, nothing durable
                rng.integers(0, 512, cap).astype(np.int32))
    pipe = RecognitionPipeline(det, net, emb_params, gallery,
                               face_size=face)
    frames = jnp.asarray(scenes[:batch], jnp.float32)
    return pipe, frames


def _line_self_times(events):
    """True per-op SELF time for one trace line: each event's duration minus
    the durations of events nested directly inside it. Summing raw
    durations would double-count nested events (a parent op enclosing its
    children on the same line), inflating top-op totals relative to the
    busy-fraction path, which unions intervals. Assumes proper nesting
    within a line, which xplane guarantees per-line."""
    self_ns = defaultdict(int)
    stack = []  # [end_ns, name, duration_ns, direct_child_ns]

    def _close(frame):
        end, name, dur, child_ns = frame
        self_ns[name] += max(dur - child_ns, 0)
        if stack:
            stack[-1][3] += dur  # charge full duration to direct parent

    for e in sorted(events, key=lambda e: (e.start_ns, -e.end_ns)):
        dur = e.duration_ns or max(e.end_ns - e.start_ns, 0)
        while stack and stack[-1][0] <= e.start_ns:
            _close(stack.pop())
        stack.append([e.end_ns, e.name, dur, 0])
    while stack:
        _close(stack.pop())
    return self_ns


def summarize_xspace(trace_dir, top_n=20):
    """Parse the newest .xplane.pb under trace_dir into {planes, per-plane
    busy fraction, top ops}. Works purely through jax.profiler.ProfileData."""
    from jax.profiler import ProfileData

    paths = sorted(glob.glob(os.path.join(trace_dir, "**", "*.xplane.pb"),
                             recursive=True), key=os.path.getmtime)
    if not paths:
        return {"error": f"no .xplane.pb produced under {trace_dir}"}
    data = ProfileData.from_file(paths[-1])
    out = {"xplane": os.path.relpath(paths[-1], trace_dir), "planes": []}
    for plane in data.planes:
        lines_summary = []
        plane_span_start, plane_span_end = None, None
        op_self_ns = defaultdict(int)
        total_event_ns = 0
        for line in plane.lines:
            events = list(line.events)
            if not events:
                continue
            start = min(e.start_ns for e in events)
            end = max(e.end_ns for e in events)
            plane_span_start = (start if plane_span_start is None
                                else min(plane_span_start, start))
            plane_span_end = (end if plane_span_end is None
                              else max(plane_span_end, end))
            # busy = union of event intervals on this line (events on one
            # line can nest; union avoids double-counting parents)
            ivals = sorted((e.start_ns, e.end_ns) for e in events)
            busy = 0
            cur_s, cur_e = ivals[0]
            for s, e in ivals[1:]:
                if s > cur_e:
                    busy += cur_e - cur_s
                    cur_s, cur_e = s, e
                else:
                    cur_e = max(cur_e, e)
            busy += cur_e - cur_s
            for name, ns in _line_self_times(events).items():
                op_self_ns[name] += ns
                total_event_ns += ns
            lines_summary.append({
                "line": line.name, "events": len(events),
                "busy_ms": round(busy / 1e6, 3),
                "span_ms": round((end - start) / 1e6, 3),
                "busy_fraction": round(busy / max(end - start, 1), 4),
            })
        top = sorted(op_self_ns.items(), key=lambda kv: -kv[1])[:top_n]
        out["planes"].append({
            "name": plane.name,
            "lines": lines_summary,
            "top_ops_ms": [
                {"op": k, "total_ms": round(v / 1e6, 3),
                 "share_of_events": round(v / max(total_event_ns, 1), 4)}
                for k, v in top
            ],
        })
    return out


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=64)
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--trace-dir", default="/tmp/ocvf_trace")
    ap.add_argument("--tiny", action="store_true",
                    help="small nets/gallery + few steps: smoke-tests the "
                         "capture+parse path on any backend (CPU included) "
                         "without writing BENCH_DETAIL.json")
    args = ap.parse_args(argv)

    import jax

    dev = jax.devices()[0]
    _log(f"device: {dev}")
    if args.tiny:
        pipe, frames = build_pipeline(4, 96, 96, 2, 32, tiny=True)
        args.steps = min(args.steps, 4)
    else:
        pipe, frames = build_pipeline(args.batch, 256, 256, 8, 128)
    # warm/compile OUTSIDE the trace
    _ = np.asarray(pipe.recognize_batch_packed(frames))
    t0 = time.perf_counter()
    with jax.profiler.trace(args.trace_dir):
        for _i in range(args.steps):
            out = pipe.recognize_batch_packed(frames)
        _ = np.asarray(out)  # one readback closes the chain
    wall_s = time.perf_counter() - t0
    _log(f"traced {args.steps} steps in {wall_s:.2f}s")

    summary = summarize_xspace(args.trace_dir)
    summary["steps"] = args.steps
    summary["batch"] = args.batch
    summary["wall_s_traced_region"] = round(wall_s, 3)
    summary["device"] = str(dev)
    summary["date"] = time.strftime("%Y-%m-%d")
    summary["note"] = (
        "jax.profiler trace of the steady-state fused step (compile outside "
        "the trace; steps dispatched back-to-back, ONE readback at the end "
        "so the tunnel's sync-poll floor sits outside the dispatch stream). "
        "busy_fraction is per trace line (union of event intervals / line "
        "span); top_ops_ms aggregates TRUE self time by op name (each "
        "event's duration minus its direct children's), so nested events "
        "are not double-counted and totals are comparable to busy time."
    )

    if args.tiny:
        print(json.dumps(summary, indent=2)[:4000])
        return
    detail_path = os.path.join(REPO, "BENCH_DETAIL.json")
    try:
        detail = json.load(open(detail_path))
    except (OSError, json.JSONDecodeError):
        detail = {}
    # Batch 32 (the headline) keeps the long-standing top-level key;
    # other batch sizes land beside it instead of clobbering it.
    key = ("trace_summary" if args.batch == 32
           else f"trace_summary_b{args.batch}")
    detail[key] = summary
    from opencv_facerecognizer_tpu.utils.serialization import atomic_write_json

    atomic_write_json(detail_path, detail)
    _log(f"merged {key} into BENCH_DETAIL.json")
    print(json.dumps(summary, indent=2)[:4000])


if __name__ == "__main__":
    main()
