"""Accuracy gate for embedder structural variants (VERDICT r3 item #1).

Runs scripts/measure_accuracy.py's EXACT cnn_verification protocol (HARD
distribution, disjoint identities, 6000 pairs, 10-fold) with a
parameterized net structure, so an explore_perf winner can be admitted as
a serving/accuracy default only on measured equal-or-better accuracy.

Run:  PYTHONPATH=. python scripts/gate_embedder.py --block dense \
          --space-to-depth 4 [--norm full] [--steps 9000] [--tag name]
Appends one JSON line per run to scripts/.gate_embedder.jsonl.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

OUT = os.path.join(REPO, "scripts", ".gate_embedder.jsonl")


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--block", default="separable")
    ap.add_argument("--space-to-depth", type=int, default=1)
    ap.add_argument("--norm", default="full")
    ap.add_argument("--steps", type=int, default=9000)
    ap.add_argument("--stage-features", default="64,128,256")
    ap.add_argument("--stage-blocks", default="2,2,2")
    ap.add_argument("--embed-dim", type=int, default=256)
    ap.add_argument("--batch-size", type=int, default=128)
    ap.add_argument("--learning-rate", type=float, default=2e-3)
    ap.add_argument("--margin", type=float, default=None,
                    help="unused unless the train step grows a flag; "
                         "recorded for provenance")
    ap.add_argument("--seed", type=int, default=3)
    ap.add_argument("--input-size", type=int, default=64,
                    help="embedder input resolution; the 64x64 dataset is "
                         "resized up in normalize_faces, so 112 gates the "
                         "SERVING-exact structure at serving resolution")
    ap.add_argument("--tag", default=None)
    args = ap.parse_args(argv)

    from opencv_facerecognizer_tpu.models.embedder import CNNEmbedding
    from opencv_facerecognizer_tpu.utils.dataset import make_synthetic_faces
    from opencv_facerecognizer_tpu.utils.verification import (
        make_verification_pairs, verification_accuracy,
    )

    # EXACT mirror of measure_accuracy.cnn_verification's data protocol
    HARD_WILD = dict(rotation=12.0, scale_jitter=0.12, elastic=1.8,
                     occlusion=0.3)
    size = (64, 64)
    X_tr, y_tr, _ = make_synthetic_faces(
        num_subjects=300, per_subject=12, size=size, seed=11, noise=10.0,
        **HARD_WILD)
    X_te, y_te, _ = make_synthetic_faces(
        num_subjects=48, per_subject=12, size=size, seed=77, noise=10.0,
        **HARD_WILD)

    emb = CNNEmbedding(
        embed_dim=args.embed_dim,
        input_size=(args.input_size, args.input_size), stem_features=32,
        stage_features=tuple(int(v) for v in args.stage_features.split(",")),
        stage_blocks=tuple(int(v) for v in args.stage_blocks.split(",")),
        block=args.block, space_to_depth=args.space_to_depth, norm=args.norm,
        train_steps=args.steps, batch_size=args.batch_size,
        learning_rate=args.learning_rate, seed=args.seed,
        augment=True, lr_schedule="cosine", tta=True,
    )
    t0 = time.perf_counter()
    emb.compute(X_tr, y_tr)
    train_s = time.perf_counter() - t0
    e = np.array(emb._extract_batch(np.asarray(X_te, np.float32)))
    a, b, same = make_verification_pairs(y_te, num_pairs=6000, seed=5)
    acc, std, thr, fold_accs = verification_accuracy(e[a], e[b], same,
                                                     folds=10,
                                                     return_folds=True)
    # fold-min gate support (VERDICT item #4: gate on the spread's lower
    # edge, not the mean)
    row = {
        "tag": args.tag or f"{args.block}_s2d{args.space_to_depth}_{args.norm}",
        "accuracy": round(float(acc), 4),
        "std": round(float(std), 4),
        "mean_minus_2std": round(float(acc - 2 * std), 4),
        "fold_min": round(float(min(fold_accs)), 4),
        "threshold": round(float(thr), 3),
        "train_s": round(train_s, 1),
        "config": {
            "block": args.block, "space_to_depth": args.space_to_depth,
            "norm": args.norm, "steps": args.steps,
            "stage_features": args.stage_features,
            "stage_blocks": args.stage_blocks,
            "embed_dim": args.embed_dim, "batch_size": args.batch_size,
            "learning_rate": args.learning_rate, "seed": args.seed,
            "input_size": args.input_size,
        },
        "date": time.strftime("%Y-%m-%d"),
    }
    with open(OUT, "a") as f:
        f.write(json.dumps(row) + "\n")
    print(json.dumps(row, indent=2))


if __name__ == "__main__":
    main()
