"""Perf-regression gate: diff two ``BENCH_SERVING_smoke.json`` artifacts
with per-metric thresholds — nonzero rc on regression.

``bench_serving.py --smoke`` writes a deterministic serving-loop perf
artifact on every run, but until now nothing ever COMPARED two of them:
the BENCH_*.json history records absolute numbers, not trajectories, so a
slow regression (e2e p50 creeping 5% per PR, tracing overhead ratio
drifting toward its gate) is invisible until a hard gate blows. This
script is the start of an actual bench trajectory: run the smoke on a
baseline commit and on a candidate, then::

    python scripts/bench_compare.py BASELINE.json CANDIDATE.json

exits **0** when every tracked metric is within its threshold, **1** with
one line per regression when not, **2** on unusable input. Self-compare
is exact-zero-regression by construction (every ratio is 1.0), which the
tests pin.

Tracked metrics (the smoke artifact's load-bearing numbers) and their
default thresholds:

=============================== =========== ==============================
metric                          direction   default threshold
=============================== =========== ==============================
overlapped e2e p50              lower       <= 1.10x baseline + 0.5 ms
overlapped ready_wait p50       lower       <= 1.25x baseline + 0.5 ms
overlapped dropped frames       lower       <= baseline (absolute)
overload 4x interactive p99     lower       <= 1.25x baseline + 5 ms
overload 4x completion ratio    higher      >= 0.98x baseline
tracing overhead p50 ratio      lower       <= baseline + 0.02 (absolute)
=============================== =========== ==============================

Latency thresholds are ratio + absolute-slack (tiny baselines must not
turn scheduler noise into a failed gate — the same reasoning as the
tracing-overhead gate's 0.5 ms slack). Override any threshold with
``--threshold NAME=VALUE`` (the ratio/absolute part only; slacks are
fixed). Missing metrics are asymmetric: absent from BOTH files or from
the BASELINE only (an older artifact predating the metric) is skipped
with a note — there is nothing to regress from; absent from the
CANDIDATE only is a structural regression (it stopped measuring
something the baseline had) and fails unless ``--allow-missing``.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Callable, Dict, List, Optional, Tuple


def _overload_row(doc: dict, multiplier: float) -> dict:
    for row in (doc.get("overload_sweep") or {}).get("rows", ()):
        if row.get("offered_multiplier") == multiplier:
            return row
    return {}


def _completion_ratio(row: dict) -> Optional[float]:
    done = row.get("interactive_completed")
    offered = row.get("interactive_offered")
    if done is None or not offered:
        return None
    return done / offered


#: metric name -> (extractor, kind, default_threshold, abs_slack).
#: kind: "ratio_max"  — candidate <= baseline * thr + slack (lower=better)
#:       "ratio_min"  — candidate >= baseline * thr         (higher=better)
#:       "abs_max"    — candidate <= baseline + thr         (lower=better)
METRICS: Dict[str, Tuple[Callable[[dict], Any], str, float, float]] = {
    "overlapped_e2e_p50_ms": (
        lambda d: (d.get("modes") or {}).get("overlapped", {})
        .get("e2e_p50_ms"),
        "ratio_max", 1.10, 0.5),
    "overlapped_ready_wait_p50_ms": (
        lambda d: (d.get("modes") or {}).get("overlapped", {})
        .get("decomposition_ms", {}).get("ready_wait_p50_ms"),
        "ratio_max", 1.25, 0.5),
    "overlapped_dropped_frames": (
        lambda d: (d.get("modes") or {}).get("overlapped", {})
        .get("dropped_frames"),
        "abs_max", 0.0, 0.0),
    "overload_4x_interactive_p99_ms": (
        lambda d: _overload_row(d, 4.0).get("interactive_e2e_p99_ms"),
        "ratio_max", 1.25, 5.0),
    # Completion RATIO, not the raw completed count: the smoke's offer
    # loop is time-based, so interactive_offered drifts run-to-run
    # (231 vs 244 on back-to-back clean runs) and an absolute-count gate
    # fails healthy runs. Rows predating interactive_offered read None
    # and ride the baseline-predates-metric skip.
    "overload_4x_interactive_completion": (
        lambda d: _completion_ratio(_overload_row(d, 4.0)),
        "ratio_min", 0.98, 0.0),
    "tracing_p50_ratio": (
        lambda d: (d.get("tracing_overhead") or {}).get("p50_ratio"),
        "abs_max", 0.02, 0.0),
    # Replica scale-out: completed-frames ratio at 2 replicas vs 1 (the
    # router/fleet win). A candidate may not quietly lose the scaling the
    # baseline demonstrated; artifacts predating the section ride the
    # baseline-predates-metric skip.
    "replica_scaleout_x2": (
        lambda d: (d.get("replica_scaleout") or {})
        .get("scaling", {}).get("x2"),
        "ratio_min", 0.90, 0.0),
    # Embedder rollout (ISSUE 11): the dual-score parity agreement on the
    # smoke's identity queries (a candidate quietly degrading old-vs-new
    # agreement is a rollout-gate regression) and the completed-frames
    # ratio through the cutover + re-anchor window (the serving-never-
    # blanks number — the router cordon + epoch-fenced swap must keep it
    # near 1.0). Artifacts predating the rollout section ride the
    # baseline-predates-metric skip.
    "rollout_parity_agreement": (
        lambda d: (d.get("rollout") or {}).get("parity_agreement"),
        "ratio_min", 0.98, 0.0),
    "rollout_cutover_completed_ratio": (
        lambda d: (d.get("rollout") or {})
        .get("cutover_window_completed_ratio"),
        "ratio_min", 0.80, 0.0),
    # Versioned model registry (ISSUE 18): the live detection-agreement
    # parity on the detector-swap smoke (a candidate quietly degrading
    # box-verdict agreement is a registry-gate regression) and the
    # completed-frames ratio through the fence + re-anchor window (the
    # serving-never-blanks number for non-embedder swaps — no re-embed,
    # params are jit arguments, so it must track the rollout ratio or
    # better). Artifacts predating the registry section ride the
    # baseline-predates-metric skip.
    "registry_parity_agreement": (
        lambda d: (d.get("registry") or {}).get("parity_agreement"),
        "ratio_min", 0.98, 0.0),
    "registry_swap_completed_ratio": (
        lambda d: (d.get("registry") or {})
        .get("swap_window_completed_ratio"),
        "ratio_min", 0.80, 0.0),
    # Ingest pipeline (ISSUE 12): the staging-ring uint8 H2D tail at the
    # b32 rung (the old --transfer-uint8 path's 118 ms p99 pathology must
    # never creep back — ratio + absolute slack, same reasoning as the
    # other microsecond-scale latency gates) and the end-to-end
    # completed-frames uplift of uint8 mode over the f32 baseline against
    # the transfer-bound fake backend. Artifacts predating the ingest
    # section ride the baseline-predates-metric skip.
    "ingest_h2d_p99_ms": (
        lambda d: (d.get("ingest") or {})
        .get("h2d", {}).get("32", {}).get("uint8_ring", {}).get("p99_ms"),
        "ratio_max", 1.25, 0.5),
    "ingest_completed_uplift": (
        lambda d: (d.get("ingest") or {})
        .get("uplift", {}).get("b32", {}).get("uplift"),
        "ratio_min", 0.90, 0.0),
    # Cascade early-exit detection (ISSUE 13): completed-frames uplift at
    # 0% face density, cascade on vs off, against the per-frame dispatch
    # wall — the headline early-exit win. A candidate may not quietly
    # lose it (a gate that stops rejecting, a compaction that stops
    # shrinking buckets). Artifacts predating the cascade section ride
    # the baseline-predates-metric skip.
    "cascade_uplift_density0": (
        lambda d: (d.get("cascade") or {})
        .get("uplift", {}).get("d0", {}).get("uplift"),
        "ratio_min", 0.90, 0.0),
    # Temporal identity cache (ISSUE 17): completed-frames uplift at
    # coherence 0.9, cache on vs off, against the per-frame dispatch
    # wall — the headline track-cache win. A candidate may not quietly
    # lose it (an association that stops matching, a re-verify cadence
    # gone pathological, a gate that stops compacting). Artifacts
    # predating the video section ride the baseline-predates-metric
    # skip.
    "video_cache_uplift": (
        lambda d: (d.get("video") or {})
        .get("cells", {}).get("c90", {}).get("uplift"),
        "ratio_min", 0.90, 0.0),
    # Partition tolerance (ISSUE 16): partition onset to link-down
    # detection in the chaos scenario. A candidate may not quietly slow
    # the failover the baseline demonstrated (a longer deadline, a lazier
    # health loop) — ratio + half-second absolute slack, since at a
    # ~0.25 s detection floor a scheduler hiccup is a large ratio.
    # Artifacts predating the partition section ride the
    # baseline-predates-metric skip.
    "partition_failover_s": (
        lambda d: (d.get("partition") or {}).get("failover_s"),
        "ratio_max", 1.50, 0.5),
}


def compare(baseline: dict, candidate: dict,
            overrides: Optional[Dict[str, float]] = None,
            allow_missing: bool = False) -> dict:
    """Structured comparison report: per-metric verdicts plus the overall
    ``ok``. Pure — the CLI around it owns I/O and exit codes."""
    overrides = overrides or {}
    rows: List[dict] = []
    regressions: List[str] = []
    for name, (extract, kind, default_thr, slack) in METRICS.items():
        thr = overrides.get(name, default_thr)
        base = extract(baseline)
        cand = extract(candidate)
        row = {"metric": name, "baseline": base, "candidate": cand,
               "kind": kind, "threshold": thr}
        if base is None and cand is None:
            row["verdict"] = "skipped"
            row["note"] = "absent from both artifacts"
            rows.append(row)
            continue
        if base is None:
            # Asymmetric by design: a baseline that predates a tracked
            # metric (comparing against an older commit's artifact) has
            # nothing to regress FROM — only the candidate dropping a
            # measurement is the structural failure.
            row["verdict"] = "skipped"
            row["note"] = "baseline predates this metric"
            rows.append(row)
            continue
        if cand is None:
            row["verdict"] = "ok" if allow_missing else "regression"
            row["note"] = "candidate stopped measuring this"
            if not allow_missing:
                regressions.append(
                    f"{name}: candidate stopped measuring this "
                    f"(baseline={base!r})")
            rows.append(row)
            continue
        base_f, cand_f = float(base), float(cand)
        if kind == "ratio_max":
            limit = base_f * thr + slack
            ok = cand_f <= limit
        elif kind == "ratio_min":
            limit = base_f * thr
            ok = cand_f >= limit
        else:  # abs_max
            limit = base_f + thr
            ok = cand_f <= limit
        row["limit"] = round(limit, 4)
        row["verdict"] = "ok" if ok else "regression"
        if not ok:
            word = "below" if kind == "ratio_min" else "above"
            regressions.append(
                f"{name}: candidate {cand_f:g} is {word} the limit "
                f"{limit:g} (baseline {base_f:g}, threshold {thr:g})")
        rows.append(row)
    return {"ok": not regressions, "metrics": rows,
            "regressions": regressions}


def _load(path: str) -> dict:
    with open(path) as fh:
        doc = json.load(fh)
    if not isinstance(doc, dict):
        raise ValueError(f"{path}: artifact root is not an object")
    return doc


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="diff two BENCH_SERVING_smoke.json artifacts; "
                    "rc 1 on regression, 2 on unusable input")
    parser.add_argument("baseline", help="the reference smoke artifact")
    parser.add_argument("candidate", help="the artifact under test")
    parser.add_argument("--threshold", action="append", default=[],
                        metavar="NAME=VALUE",
                        help="override one metric's threshold (ratio or "
                             "absolute per its kind); repeatable")
    parser.add_argument("--allow-missing", action="store_true",
                        help="a metric present in only one artifact is a "
                             "note, not a regression")
    parser.add_argument("--json", action="store_true",
                        help="print the full report as JSON instead of "
                             "the human summary")
    args = parser.parse_args(argv)

    overrides: Dict[str, float] = {}
    for item in args.threshold:
        key, sep, value = item.partition("=")
        if not sep or key not in METRICS:
            print(f"bench_compare: unknown threshold {item!r} "
                  f"(metrics: {', '.join(METRICS)})", file=sys.stderr)
            return 2
        try:
            overrides[key] = float(value)
        except ValueError:
            print(f"bench_compare: threshold {item!r} is not a number",
                  file=sys.stderr)
            return 2
    try:
        baseline = _load(args.baseline)
        candidate = _load(args.candidate)
    except (OSError, ValueError, json.JSONDecodeError) as exc:
        print(f"bench_compare: {exc}", file=sys.stderr)
        return 2
    report = compare(baseline, candidate, overrides=overrides,
                     allow_missing=args.allow_missing)
    if args.json:
        print(json.dumps(report, indent=2))
    else:
        for row in report["metrics"]:
            mark = {"ok": "ok  ", "skipped": "skip",
                    "regression": "FAIL"}[row["verdict"]]
            print(f"[{mark}] {row['metric']}: baseline={row['baseline']} "
                  f"candidate={row['candidate']}"
                  + (f" limit={row['limit']}" if "limit" in row else "")
                  + (f" ({row['note']})" if "note" in row else ""))
        for line in report["regressions"]:
            print(f"REGRESSION: {line}", file=sys.stderr)
        print("bench_compare: "
              + ("no regressions" if report["ok"]
                 else f"{len(report['regressions'])} regression(s)"))
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
