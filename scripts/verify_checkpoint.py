"""Offline checkpoint verification — CI / ops integrity gate.

Verifies durable state without loading any model code onto a device:

- a **state directory** (``ocvf-recognize --state-dir``): every installed
  gallery checkpoint's magic/header/sha256 is checked
  (``runtime.state_store``), and the enrollment WAL is scanned for
  decodable records. Unparseable lines are reported as ``torn_lines``
  (warning only): every acknowledged append ends as a complete fsynced
  line, so a torn line — at the tail, or sealed mid-file by a later
  restart — can only be an unacknowledged crash remnant that replay
  skips. A PARSEABLE enroll record failing its crc/base64, however, was
  acknowledged and is now unreadable: that is real loss and fails the
  verification;
- a **model checkpoint file** (``ocvf-train`` output): decoded through
  ``utils.serialization.load_model``'s validation (raises
  ``CheckpointCorruptError`` on truncation/garbage).

Exit status: 0 when everything verified, 2 when any corrupt file/record
was found, 3 for **cannot verify** — the bytes could not be READ
(EACCES/EIO/a vanished file), which proves nothing about their
integrity. The distinction matters operationally: rc 2 means restore
from backup, rc 3 means fix the mount/permissions and re-run — reporting
an unreadable checkpoint as corrupt could condemn perfectly good state
(and real corruption alongside unreadable files still exits 2). Wire it
into CI after a backup job, or run it before trusting a state dir for
recovery::

    python scripts/verify_checkpoint.py /var/lib/ocvf/state
    python scripts/verify_checkpoint.py model.ckpt
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def verify_state_dir(path: str) -> dict:
    """Verify a --state-dir layout (or a bare checkpoints directory).
    Returns a JSON-able report with ``ok`` as the verdict.

    STRICTLY READ-ONLY: safe against a live service's state dir. The WAL
    is scanned directly from its files — never through the
    ``EnrollmentWAL`` writer class, whose constructor seals torn tails
    (a write that could split a record a live writer is mid-append on) —
    and nothing is quarantined, created, or pruned."""
    from opencv_facerecognizer_tpu.runtime.state_store import (
        CHECKPOINT_SUFFIX, CheckpointStore, decode_enroll_record,
    )

    ckpt_dir = os.path.join(path, "checkpoints")
    if not os.path.isdir(ckpt_dir):
        # Accept being pointed straight at the checkpoints directory.
        has_ckpts = any(n.endswith(CHECKPOINT_SUFFIX)
                        for n in os.listdir(path))
        ckpt_dir = path if has_ckpts else None
    report = {"path": path, "checkpoints": [], "corrupt": [],
              "newer_version": [], "unreadable": [], "wal": None,
              "ok": True}
    if ckpt_dir is not None and os.path.isdir(ckpt_dir):
        sweep = CheckpointStore(ckpt_dir).verify()  # verify() never mutates
        report["checkpoints"] = sweep["ok"]
        report["corrupt"] = [{"path": p, "reason": r}
                             for p, r in sweep["corrupt"]]
        # Newer-format files are intact, just unreadable by THIS binary
        # (downgrade) — reported, but not a corruption failure.
        report["newer_version"] = [{"path": p, "reason": r}
                                   for p, r in sweep["newer_version"]]
        # UNREADABLE (EACCES/EIO: the read failed) is "cannot verify",
        # never "corrupt" — the bytes were not seen, so no verdict on
        # them is honest. Fails the verification with its own rc (3).
        report["unreadable"] = [{"path": p, "reason": r}
                                for p, r in sweep.get("unreadable", ())]
        if sweep["corrupt"]:
            report["ok"] = False
        if report["unreadable"]:
            report["ok"] = False
            report["cannot_verify"] = True
        # Embedder-version header validation (rollout fencing): every
        # verified checkpoint must carry a sane version field (absent =
        # pre-rollout v1). A non-integer / non-positive field is a
        # corrupt fence — replay would mis-anchor on it. The newest
        # verified checkpoint's version is reported for the operator.
        from opencv_facerecognizer_tpu.runtime.state_store import (
            CheckpointCorruptError, CheckpointVersionError,
            read_checkpoint_header, scan_checkpoint_files,
        )

        ckpt_embedder_version = None
        for _seq, ckpt_path in scan_checkpoint_files(ckpt_dir):
            if ckpt_path not in sweep["ok"]:
                continue
            try:
                meta = read_checkpoint_header(ckpt_path).get("meta", {})
                version = int(meta.get("embedder_version", 1))
                if version < 1:
                    raise ValueError(f"embedder_version {version} < 1")
            except (OSError, CheckpointCorruptError,
                    CheckpointVersionError, TypeError, ValueError) as exc:
                report["ok"] = False
                report.setdefault("version_errors", []).append(
                    {"path": ckpt_path,
                     "reason": f"bad embedder_version header: {exc}"})
                continue
            if ckpt_embedder_version is None:
                ckpt_embedder_version = version  # newest verified wins
        report["embedder_version"] = ckpt_embedder_version

    manifest_path = os.path.join(path, "registry.json")
    if os.path.exists(manifest_path):
        # Model-registry manifest (ISSUE 18): checksum over the canonical
        # roles bytes + per-role shape/monotonicity. Torn/unreadable
        # (the bytes could not be parsed) is "cannot verify" (rc 3);
        # a checksum/shape mismatch is corruption (rc 2) — same contract
        # as the checkpoint sweep.
        from opencv_facerecognizer_tpu.runtime.registry import (
            ModelRegistry, RegistryStateError,
        )

        try:
            roles = ModelRegistry.read_manifest(manifest_path)["roles"]
            entry = {"path": manifest_path,
                     "roles": {r: int(v["version"])
                               for r, v in roles.items()}}
            bad = [r for r, v in roles.items()
                   if int(v.get("version", 0)) < 1
                   or int(v.get("retired", 0) or 0) < 0]
            if bad:
                entry["error"] = (f"non-monotonic version fields for "
                                  f"role(s) {bad}")
                entry["reason"] = "corrupt"
                report["ok"] = False
                report["registry_corrupt"] = True
            report["registry"] = entry
        except RegistryStateError as exc:
            report["ok"] = False
            report["registry"] = {"path": manifest_path,
                                  "error": str(exc),
                                  "reason": exc.reason}
            if exc.reason == "unreadable":
                report["cannot_verify"] = True
            else:
                report["registry_corrupt"] = True

    wal_path = os.path.join(path, "enroll.wal")
    if os.path.exists(wal_path):
        torn_lines = enroll_records = valid_records = 0
        cutover_records = 0
        version_violations = []
        # Multi-role version walk (ISSUE 18): enroll rows stamp the
        # non-embedder roles they were served under (``registry``), and
        # a ``registry_cutover`` record is the only sanctioned way a
        # role's version moves — a ``registry_abort`` tombstone voids
        # its fence (the role reverts to the fence's from_version).
        # Rows spanning a role's versions without an intervening fence
        # mean replay could mix model sets: rc 2.
        registry_cutover_records = 0
        cur_roles = {}
        fence_from = {}  # (role, to_version) -> from_version, for aborts
        # Version walk (rollout fencing): rows carry the embedder version
        # they were enrolled under; a ``cutover`` record is the only
        # sanctioned way the stream switches versions. Rows spanning
        # versions WITHOUT an intervening cutover mean the fence is
        # damaged — a replica replaying this WAL could mix embedding
        # spaces. Seeded from the first row: pre-cutover leftovers below
        # a new checkpoint's anchor legitimately predate it, so the walk
        # follows the stream's own fences, not the anchor.
        cur_version = None
        try:
            with open(wal_path, "r", encoding="utf-8",
                      errors="replace") as fh:
                lines = [l.rstrip("\n") for l in fh]
        except OSError as exc:
            # The WAL exists but cannot be read: cannot verify (rc 3),
            # not corruption — same contract as the checkpoint sweep.
            report["wal"] = {"path": wal_path, "unreadable": str(exc)}
            report["ok"] = False
            report["cannot_verify"] = True
            return report
        for line in lines:
            if not line.strip():
                continue
            try:
                record = json.loads(line)
                if not isinstance(record, dict):
                    raise json.JSONDecodeError("not an object", line, 0)
            except json.JSONDecodeError:
                # Every acknowledged append ended as a complete fsynced
                # line + newline, so an unparseable/non-object line —
                # tail OR sealed mid-file — can only be a TORN
                # (never-acknowledged) append: the expected crash
                # signature, skipped by replay. A warning, not a failure.
                torn_lines += 1
                continue
            if record.get("kind") == "cutover":
                cutover_records += 1
                try:
                    from_v = int(record["from_version"])
                    to_v = int(record["to_version"])
                except (KeyError, TypeError, ValueError):
                    version_violations.append(
                        {"seq": record.get("seq"),
                         "reason": "cutover record with unreadable "
                                   "from/to versions"})
                    continue
                if cur_version is not None and from_v != cur_version:
                    version_violations.append(
                        {"seq": record.get("seq"),
                         "reason": f"cutover claims from_version {from_v} "
                                   f"but the stream is at {cur_version}"})
                cur_version = to_v
                continue
            if record.get("kind") == "registry_cutover":
                registry_cutover_records += 1
                try:
                    role = str(record["role"])
                    from_v = int(record["from_version"])
                    to_v = int(record["to_version"])
                except (KeyError, TypeError, ValueError):
                    version_violations.append(
                        {"seq": record.get("seq"),
                         "reason": "registry_cutover record with "
                                   "unreadable role/versions"})
                    continue
                if to_v <= from_v:
                    version_violations.append(
                        {"seq": record.get("seq"),
                         "reason": f"registry_cutover {role} "
                                   f"v{from_v} -> v{to_v} is not "
                                   f"monotonic"})
                if role in cur_roles and from_v != cur_roles[role]:
                    version_violations.append(
                        {"seq": record.get("seq"),
                         "reason": f"registry_cutover claims {role} "
                                   f"from_version {from_v} but the "
                                   f"stream is at v{cur_roles[role]}"})
                fence_from[(role, to_v)] = from_v
                cur_roles[role] = to_v
                continue
            if record.get("kind") == "registry_abort":
                # Recovery abandoned the fence this tombstone names: the
                # role reverts to the fence's from_version (the version
                # number stays burned — the manifest's retired floor).
                role = str(record.get("role"))
                try:
                    to_v = int(record.get("to_version", -1))
                except (TypeError, ValueError):
                    to_v = -1
                key = (role, to_v)
                if key in fence_from and cur_roles.get(role) == to_v:
                    cur_roles[role] = fence_from[key]
                continue
            if record.get("kind") != "enroll":
                continue
            enroll_records += 1
            if decode_enroll_record(record) is not None:
                valid_records += 1
            try:
                row_version = int(record.get("embedder_version", 1))
            except (TypeError, ValueError):
                version_violations.append(
                    {"seq": record.get("seq"),
                     "reason": f"unreadable embedder_version "
                               f"{record.get('embedder_version')!r}"})
                continue
            if cur_version is None:
                cur_version = row_version
            elif row_version != cur_version:
                version_violations.append(
                    {"seq": record.get("seq"),
                     "reason": f"row at embedder v{row_version} follows "
                               f"v{cur_version} rows with no intervening "
                               f"cutover record (version fence breached)"})
            row_stamp = record.get("registry")
            if isinstance(row_stamp, dict):
                for role, ver in row_stamp.items():
                    role = str(role)
                    try:
                        ver = int(ver)
                    except (TypeError, ValueError):
                        version_violations.append(
                            {"seq": record.get("seq"),
                             "reason": f"unreadable registry stamp for "
                                       f"role {role!r}: "
                                       f"{row_stamp.get(role)!r}"})
                        continue
                    if role not in cur_roles:
                        cur_roles[role] = ver  # seed, like the embedder
                    elif ver != cur_roles[role]:
                        version_violations.append(
                            {"seq": record.get("seq"),
                             "reason": f"row at {role} v{ver} follows "
                                       f"v{cur_roles[role]} rows with no "
                                       f"intervening registry_cutover "
                                       f"record (registry fence "
                                       f"breached)"})
        # A PARSEABLE enroll record failing crc/base64 was acknowledged
        # and is now unreadable — that is real loss of acked data.
        corrupt_records = enroll_records - valid_records
        report["wal"] = {"path": wal_path, "lines": len(lines),
                         "enroll_records": enroll_records,
                         "valid_records": valid_records,
                         "torn_lines": torn_lines,
                         "corrupt_records": corrupt_records,
                         "cutover_records": cutover_records,
                         "registry_cutover_records":
                             registry_cutover_records,
                         "version_violations": version_violations}
        if corrupt_records:
            report["ok"] = False
        if version_violations:
            # Rows spanning embedder versions without a cutover fence:
            # replaying this WAL could serve a mixed-space gallery — the
            # exact failure the rollout machinery exists to prevent.
            report["ok"] = False
    if (not report["checkpoints"] and not report["corrupt"]
            and not report["newer_version"] and report["wal"] is None):
        # A mistyped/empty directory must not green-light a backup job:
        # "nothing found" is a failed verification, not a vacuous pass.
        report["ok"] = False
        report["reason"] = "no durable state found (no checkpoints, no WAL)"
    return report


def follow_wal(state_dir: str, duration_s: float = 10.0,
               poll_s: float = 0.25) -> dict:
    """``--follow``: validate a LIVE-tailed WAL exactly the way a read
    replica reads it (``runtime.replication.WALTailer`` — complete lines
    only, compaction detected on the open fd, checkpoint re-anchoring on
    the published ``wal_seq``), so an operator can check what a reader
    would see without stopping the writer. Strictly read-only, like the
    static sweep.

    Verdict: a PARSEABLE enroll record past the anchor that fails its
    crc/base64 was acknowledged and is now unreadable to every replica —
    real loss, ``ok: False``. Torn remnants, abort tombstones and
    anchor-covered rows are counted, not failures."""
    import time

    from opencv_facerecognizer_tpu.runtime.replication import (
        WALTailer, newest_checkpoint_wal_seq,
    )
    from opencv_facerecognizer_tpu.runtime.state_store import (
        decode_enroll_record,
    )

    wal_path = os.path.join(state_dir, "enroll.wal")
    ckpt_dir = os.path.join(state_dir, "checkpoints")
    anchor = newest_checkpoint_wal_seq(ckpt_dir)
    tailer = WALTailer(wal_path)
    applied = anchor
    report = {"path": wal_path, "mode": "follow",
              "duration_s": duration_s, "anchor_wal_seq": anchor,
              "polls": 0, "valid_records": 0, "valid_rows": 0,
              "corrupt_records": 0, "aborted_records": 0,
              "anchor_covered": 0, "reanchors": 0, "ok": True}
    aborted: set = set()
    deadline = time.monotonic() + duration_s
    while True:
        records, info = tailer.poll()
        report["polls"] += 1
        if info.get("reopened"):
            # Compaction swapped a rewritten WAL in: re-anchor at the
            # newest checkpoint's published wal_seq, exactly as a replica
            # that lagged past the truncation point would.
            new_anchor = newest_checkpoint_wal_seq(ckpt_dir)
            if new_anchor > applied:
                applied = new_anchor
                report["reanchors"] += 1
                report["anchor_wal_seq"] = new_anchor
        for record in records:
            seq = record.get("seq")
            if record.get("kind") == "abort" and isinstance(seq, (int, float)):
                aborted.add(int(seq))
        for record in records:
            seq = record.get("seq")
            if record.get("kind") != "enroll" or not isinstance(
                    seq, (int, float)):
                continue
            seq = int(seq)
            if seq <= applied and seq not in aborted:
                report["anchor_covered"] += 1
                continue
            if seq in aborted:
                report["aborted_records"] += 1
                applied = max(applied, seq)
                continue
            decoded = decode_enroll_record(record)
            if decoded is None:
                report["corrupt_records"] += 1
                report["ok"] = False
            else:
                report["valid_records"] += 1
                report["valid_rows"] += int(decoded["n"])
            applied = max(applied, seq)
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            break
        time.sleep(min(poll_s, remaining))
    report["torn_lines"] = tailer.malformed_lines
    report["wal_reopens"] = tailer.reopens
    report["final_seq"] = applied
    return report


def verify_model_file(path: str) -> dict:
    from opencv_facerecognizer_tpu.utils.serialization import (
        CheckpointCorruptError, load_model,
    )

    report = {"path": path, "ok": True}
    try:
        load_model(path)
    except CheckpointCorruptError as exc:
        report["ok"] = False
        report["reason"] = str(exc)
    except ValueError as exc:
        # e.g. a future format version: not corrupt, but not loadable here.
        report["ok"] = False
        report["reason"] = f"unloadable: {exc}"
    except OSError as exc:
        # Read failure: cannot verify (rc 3) — the bytes were never seen,
        # so calling them corrupt would be a false condemnation.
        report["ok"] = False
        report["reason"] = f"unreadable: {exc}"
        report["cannot_verify"] = True
    return report


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("path", help="state directory (--state-dir layout or "
                                     "a checkpoints dir) or a model .ckpt file")
    parser.add_argument("--follow", action="store_true",
                        help="live-tail the state dir's WAL for --duration "
                             "seconds, validating each new record the way a "
                             "read replica applies it (complete lines only, "
                             "compaction-aware, checkpoint re-anchoring); "
                             "read-only and safe against a live writer")
    parser.add_argument("--duration", type=float, default=10.0,
                        help="--follow window in seconds")
    parser.add_argument("--poll-ms", type=float, default=250.0,
                        help="--follow poll interval")
    args = parser.parse_args(argv)
    if args.follow:
        if not os.path.isdir(args.path):
            report = {"path": args.path, "ok": False,
                      "reason": "--follow needs a state directory"}
        else:
            report = follow_wal(args.path, duration_s=args.duration,
                                poll_s=args.poll_ms / 1e3)
    elif os.path.isdir(args.path):
        report = verify_state_dir(args.path)
    elif os.path.exists(args.path):
        report = verify_model_file(args.path)
    else:
        # The rc contract is 0/2 with a JSON report — a typo'd path must
        # not traceback with rc 1 (nor pass).
        report = {"path": args.path, "ok": False,
                  "reason": "path does not exist"}
    print(json.dumps(report, indent=2))
    if report["ok"]:
        return 0
    # rc 3 = "cannot verify": the ONLY failures were read errors
    # (EACCES/EIO). Any actual corruption evidence alongside them keeps
    # rc 2 — restore-from-backup beats fix-the-mount when both apply.
    wal = report.get("wal") or {}
    corruption = bool(report.get("corrupt") or report.get("version_errors")
                      or report.get("registry_corrupt")
                      or wal.get("corrupt_records")
                      or wal.get("version_violations"))
    if report.get("cannot_verify") and not corruption:
        return 3
    return 2


if __name__ == "__main__":
    sys.exit(main())
