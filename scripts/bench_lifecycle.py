"""Gallery lifecycle at scale, mid-serving, on the real chip (VERDICT
round-2 item #5): serve at 16k enrolled rows -> enroll past
``PALLAS_MIN_CAPACITY`` (auto-grow doubles capacity AND switches the
matcher from the XLA materialize form to the pallas streaming kernel) ->
keep growing to 1M rows -> measure the steady in-pipeline cost at each
stage and the one-off stall each growth causes.

What the artifact records (merged into BENCH_DETAIL.json under
"lifecycle"; bench.py preserves the section):

- ``steady_ms_per_batch`` at 16k / 128k / 1M rows, timed by the same
  chained-differencing instrument bench.py uses (the tunneled backend's
  ~100 ms readback floor would otherwise swamp per-batch numbers);
- ``grow_stall_ms`` per growth event: wall time of the FIRST
  ``recognize_batch_packed`` call after ``gallery.add`` crossed capacity —
  the XLA recompile + (at 64k->128k) the matcher switch the serving thread
  actually eats; subsequent-call time recorded alongside to show recovery;
- ``install_ms``: host->device install cost of the grown snapshot
  (``ShardedGallery._install`` device_put of the doubled arrays).

Run:  PYTHONPATH=. python scripts/bench_lifecycle.py
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _log(msg):
    print(msg, file=sys.stderr, flush=True)


def chained_ms_per_batch(pipeline, frames_stack):
    """Shared chained-differencing instrument (utils.benchtime) over the
    fused recognize step, folding every output into the chain scalar."""
    import jax.numpy as jnp

    from opencv_facerecognizer_tpu.utils.benchtime import scalar_chain_ms

    data = pipeline.gallery.data
    key = pipeline._step_key(frames_stack[0], data)
    if key not in pipeline._step_cache:
        pipeline._step_cache[key] = pipeline._build_step(
            *frames_stack[0].shape, capacity=data.capacity)
    step = pipeline._step_cache[key]

    def scalar(det_p, emb_p, g_emb, g_valid, g_lab, frames):
        res = step(det_p, emb_p, g_emb, g_valid, g_lab, frames)
        return (jnp.sum(res.similarities) + jnp.sum(res.boxes) * 1e-6
                + jnp.sum(res.valid))

    return scalar_chain_ms(scalar, (
        pipeline.detector.params, pipeline.embed_params, data.embeddings,
        data.valid, data.labels, frames_stack[0],
    ))


def main():
    import jax
    import jax.numpy as jnp

    from opencv_facerecognizer_tpu.models.detector import CNNFaceDetector
    from opencv_facerecognizer_tpu.models.embedder import (
        FaceEmbedNet, init_embedder,
    )
    from opencv_facerecognizer_tpu.parallel import ShardedGallery, make_mesh
    from opencv_facerecognizer_tpu.parallel.pipeline import RecognitionPipeline
    from opencv_facerecognizer_tpu.utils.dataset import make_synthetic_scenes

    dev = jax.devices()[0]
    _log(f"device: {dev}")
    from opencv_facerecognizer_tpu.models.embedder import (
        SERVING_EMBEDDER_KWARGS, SERVING_FACE_SIZE,
    )

    batch, h, w, max_faces = 32, 256, 256, 8
    dim = SERVING_EMBEDDER_KWARGS["embed_dim"]

    det = CNNFaceDetector(max_faces=max_faces, score_threshold=0.3)
    scenes, boxes, counts = make_synthetic_scenes(
        num_scenes=48, scene_size=(h, w), max_faces=max_faces,
        face_size_range=(24, 56), seed=7)
    det.train(scenes, boxes, counts, steps=150, batch_size=16)
    net = FaceEmbedNet(**SERVING_EMBEDDER_KWARGS)
    emb_params = init_embedder(net, num_classes=16,
                               input_shape=SERVING_FACE_SIZE,
                               seed=0)["net"]

    rng = np.random.default_rng(0)
    mesh = make_mesh()
    # async_grow: the serving configuration — overflow stages rows, a
    # background worker compiles the next tier (pipeline prewarm hook) and
    # installs it off the serving path (VERDICT r3 item #5).
    # bf16 rows = the ocvf-recognize serving default (half the grow-upload
    # bytes and HBM; measured 1.24x faster 1M-row match — gallery_dtype
    # section); this artifact must measure the configuration that ships.
    gallery = ShardedGallery(capacity=16384, dim=dim, mesh=mesh,
                             async_grow=True, store_dtype=jnp.bfloat16)
    gallery.add(rng.standard_normal((16384, dim), dtype=np.float32),  # ocvf-lint: boundary=wal-before-mutate -- bench fixture: synthetic throwaway gallery, no state dir, nothing durable at stake
                rng.integers(0, 512, 16384).astype(np.int32))
    pipeline = RecognitionPipeline(det, net, emb_params, gallery,
                                   face_size=SERVING_FACE_SIZE)

    frames_stack = jnp.stack([
        jnp.asarray(make_synthetic_scenes(
            num_scenes=batch, scene_size=(h, w), max_faces=max_faces,
            face_size_range=(24, 56), seed=100 + i)[0], jnp.float32)
        for i in range(4)
    ])
    one_batch = np.asarray(frames_stack[0])

    result = {"batch": batch, "stages": [], "grow_events": []}

    def steady(tag):
        ms = chained_ms_per_batch(pipeline, frames_stack)
        if ms is None:  # chain delta never cleared readback quantization
            result["stages"].append({
                "rows": gallery.size, "capacity": gallery.capacity,
                "pallas": gallery._pallas_enabled(),
                "steady_ms_per_batch": None, "invalid": "under-resolved",
            })
            _log(f"[{tag}] UNRESOLVED steady timing")
            return
        result["stages"].append({
            "rows": gallery.size, "capacity": gallery.capacity,
            "pallas": gallery._pallas_enabled(),
            "steady_ms_per_batch": round(ms, 3),
        })
        _log(f"[{tag}] rows={gallery.size} cap={gallery.capacity} "
             f"pallas={gallery._pallas_enabled()} steady {ms:.3f} ms/batch")

    # serve at 16k (XLA matcher), establish steady state
    _ = np.asarray(pipeline.recognize_batch_packed(one_batch))  # warm
    steady("16k")

    def grow_to(total_rows, tag):
        """Enroll up to total_rows mid-serving. With async_grow the add
        stages the rows and returns; serving continues on the OLD tier
        (every call timed) while the worker compiles + installs the new
        one; the first call at the NEW tier is the residual stall."""
        need = total_rows - gallery.size
        # Generate OUTSIDE the timed window: 920k f64 gaussians measured
        # 107 s on this 1-core host — timing it inside the add() window
        # reported the bench's own data generation as a 113 s "stall"
        # (r5 first lifecycle capture). f32 generation is also ~4x faster.
        rows = rng.standard_normal((need, dim), dtype=np.float32)
        labs = rng.integers(0, 512, need).astype(np.int32)
        t_add0 = time.perf_counter()
        gallery.add(rows, labs)  # ocvf-lint: boundary=wal-before-mutate -- bench fixture: the measured grow path itself, synthetic rows, no durability contract
        add_return_ms = (time.perf_counter() - t_add0) * 1e3
        # serve continuously until the grow lands; record every call
        during = []
        while not gallery.wait_ready(timeout=0):
            t0 = time.perf_counter()
            _ = np.asarray(pipeline.recognize_batch_packed(one_batch))
            during.append((time.perf_counter() - t0) * 1e3)
        visibility_s = time.perf_counter() - t_add0
        t0 = time.perf_counter()
        _ = np.asarray(pipeline.recognize_batch_packed(one_batch))
        first_ms = (time.perf_counter() - t0) * 1e3  # first NEW-tier call
        t0 = time.perf_counter()
        _ = np.asarray(pipeline.recognize_batch_packed(one_batch))
        second_ms = (time.perf_counter() - t0) * 1e3
        result["grow_events"].append({
            "to_rows": gallery.size, "to_capacity": gallery.capacity,
            "pallas_after": gallery._pallas_enabled(),
            "add_return_ms": round(add_return_ms, 1),
            "serving_calls_during_grow": len(during),
            "during_grow_ms_max": round(max(during), 1) if during else None,
            "during_grow_ms_p50": round(float(np.median(during)), 1)
                                  if during else None,
            "enroll_visibility_s": round(visibility_s, 2),
            "grow_stall_ms": round(first_ms, 1),
            "next_call_ms": round(second_ms, 1),
            "worker_decomposition_s": dict(gallery.last_grow_info),
        })
        _log(f"[{tag}] grew to {gallery.size} rows (cap {gallery.capacity}, "
             f"pallas={gallery._pallas_enabled()}): add returned in "
             f"{add_return_ms:.0f} ms, {len(during)} serving calls during "
             f"grow (max {max(during) if during else 0:.0f} ms), visible "
             f"after {visibility_s:.1f} s, first new-tier call "
             f"{first_ms:.0f} ms, next {second_ms:.0f} ms; worker "
             f"{gallery.last_grow_info}")

    # cross PALLAS_MIN_CAPACITY: 16k -> 80k rows => capacity doubles past
    # 64k and the matcher switches to the streaming kernel
    grow_to(80_000, "grow->128k")
    steady("128k")
    # then to 1M rows (capacity 1,048,576)
    grow_to(1_000_000, "grow->1M")
    steady("1M")

    detail_path = os.path.join(REPO, "BENCH_DETAIL.json")
    try:
        detail = json.load(open(detail_path))
    except (OSError, json.JSONDecodeError):
        detail = {}
    detail["lifecycle"] = {
        "device": str(dev),
        "date": time.strftime("%Y-%m-%d"),
        "note": ("serve@16k -> enroll past PALLAS_MIN_CAPACITY (matcher "
                 "switch) -> 1M rows, all mid-serving on one pipeline "
                 "object with async_grow: the overflowing add returns in "
                 "milliseconds, serving continues on the old tier while "
                 "the grow worker compiles the new tier (pipeline prewarm "
                 "hook) and installs it; grow_stall_ms is the first "
                 "recognize call at the NEW tier (wall-clock incl. the "
                 "tunneled ~100 ms readback floor), enroll_visibility_s "
                 "is the staged-rows-to-matchable latency, and "
                 "worker_decomposition_s breaks the background work into "
                 "prewarm (compile) / copy / normalize (staged rows) / "
                 "upload_wait (H2D + residency poll, off the serving "
                 "path) / install (the atomic publish)"),
        **result,
    }
    from opencv_facerecognizer_tpu.utils.serialization import atomic_write_json

    atomic_write_json(detail_path, detail)
    _log("merged lifecycle section into BENCH_DETAIL.json")
    print(json.dumps(detail["lifecycle"], indent=2))


if __name__ == "__main__":
    main()
