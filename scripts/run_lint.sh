#!/usr/bin/env bash
# ocvf-lint wrapper with stable exit codes, for CI and the verify recipe.
#
#   ./scripts/run_lint.sh            # lint the package + scripts (the gate)
#   ./scripts/run_lint.sh PATH...    # lint specific files/dirs
#   ./scripts/run_lint.sh --json     # machine-readable output
#
# Exit codes (the CLI's contract, passed through verbatim):
#   0  clean — no findings
#   1  findings reported (see stdout)
#   2  internal error (linter crash, bad path, bad invocation)
set -u

REPO="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"
cd "$REPO" || exit 2

args=()
paths=0
expect_value=0
for a in "$@"; do
    args+=("$a")
    if [ "$expect_value" -eq 1 ]; then
        expect_value=0           # this token is an option's value, not a path
        continue
    fi
    case "$a" in
        --rules) expect_value=1 ;;   # space-separated value follows
        --*) ;;
        *) paths=1 ;;
    esac
done
if [ "$paths" -eq 0 ]; then
    args+=(opencv_facerecognizer_tpu scripts)
fi

python -m tools.ocvf_lint "${args[@]}"
exit $?
