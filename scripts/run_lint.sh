#!/usr/bin/env bash
# ocvf-lint wrapper with stable exit codes, for CI and the verify recipe.
#
#   ./scripts/run_lint.sh            # lint the package + scripts (the gate,
#                                    # ratcheted against LINT_BASELINE.json)
#   ./scripts/run_lint.sh --changed  # lint only git-changed .py files
#                                    # (staged + unstaged + untracked) —
#                                    # the fast pre-commit path; note the
#                                    # cross-file rules see only the subset
#   ./scripts/run_lint.sh PATH...    # lint specific files/dirs
#   ./scripts/run_lint.sh --json     # machine-readable output
#
# Exit codes (the CLI's contract, passed through verbatim):
#   0  clean — no findings (full run: nothing above its baselined count)
#   1  findings reported (see stdout)
#   2  internal error (linter crash, bad path, bad invocation)
set -u

REPO="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"
cd "$REPO" || exit 2

args=()
paths=0
changed=0
baseline_given=0
expect_value=0
for a in "$@"; do
    if [ "$a" = "--changed" ]; then
        changed=1
        continue
    fi
    args+=("$a")
    if [ "$expect_value" -eq 1 ]; then
        expect_value=0           # this token is an option's value, not a path
        continue
    fi
    case "$a" in
        --rules|--baseline|--cache-dir) expect_value=1 ;;  # value follows
        --baseline=*) baseline_given=1 ;;
        --*) ;;
        *) paths=1 ;;
    esac
    [ "$a" = "--baseline" ] && baseline_given=1
done

if [ "$changed" -eq 1 ]; then
    if [ "$paths" -eq 1 ]; then
        echo "run_lint.sh: --changed and explicit paths are mutually exclusive" >&2
        exit 2
    fi
    # Changed = modified/added vs HEAD (staged or not) + untracked, limited
    # to the linted trees. Deleted files drop out via --diff-filter.
    mapfile -t files < <(
        {
            git diff --name-only --diff-filter=d HEAD -- \
                'opencv_facerecognizer_tpu/*.py' 'opencv_facerecognizer_tpu/**/*.py' \
                'scripts/*.py' 'tools/**/*.py' 'tools/*.py'
            git ls-files --others --exclude-standard -- \
                'opencv_facerecognizer_tpu/*.py' 'opencv_facerecognizer_tpu/**/*.py' \
                'scripts/*.py' 'tools/**/*.py' 'tools/*.py'
        } | sort -u
    )
    if [ "${#files[@]}" -eq 0 ]; then
        echo "run_lint.sh: no changed .py files under the linted trees" >&2
        exit 0
    fi
    python -m tools.ocvf_lint "${args[@]}" "${files[@]}"
    exit $?
fi

if [ "$paths" -eq 0 ]; then
    args+=(opencv_facerecognizer_tpu scripts)
    # The gate run rides the checked-in ratchet: per-rule finding counts
    # may only shrink (LINT_BASELINE.json).
    if [ "$baseline_given" -eq 0 ] && [ -f LINT_BASELINE.json ]; then
        args+=(--baseline LINT_BASELINE.json)
    fi
fi

python -m tools.ocvf_lint "${args[@]}"
exit $?
