"""Gate for the measurement queue's conditional fused-schedule re-run:
exit 0 iff BENCH_DETAIL.json's sepblock_fused A/B (scripts/
bench_sepblock.py) recorded a >= 5% speedup at any measured batch.
Kept as a script (not a heredoc in run_measurement_queue.sh) so the
decision logic is unit-testable — tests/test_queue_gate.py."""

from __future__ import annotations

import json
import os
import sys

WIN_THRESHOLD = 1.05


def sepblock_won(detail_path: str) -> bool:
    try:
        doc = json.load(open(detail_path))
    except (OSError, json.JSONDecodeError):
        return False
    batches = doc.get("sepblock_fused", {}).get("batches", {})
    speedups = [row.get("speedup") or 0 for row in batches.values()]
    return bool(speedups) and max(speedups) >= WIN_THRESHOLD


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    path = argv[0] if argv else os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "BENCH_DETAIL.json")
    return 0 if sepblock_won(path) else 1


if __name__ == "__main__":
    sys.exit(main())
