"""Chaos soak: the serving loop under randomized, seed-logged fault
injection — exits nonzero on wedge or crash.

Builds a tiny (untrained — detection quality is irrelevant here) CPU
serving stack over ``FakeConnector``, installs a ``FaultInjector`` with
randomized rates drawn from the logged seed, wraps the service in a
``ServiceSupervisor``, and pounds frames at it for ``--seconds``. The
whole run is reproducible: rerun with the printed ``--seed`` and the exact
same fault sequence replays.

Pass criteria (any miss exits rc=2 with the reason in the JSON report):

1. **no wedge** — after the chaos window the injector is disarmed and a
   probe burst of clean frames must all come back as results within a
   bounded wait (a deadlocked/crashed-and-unrestarted loop fails here);
2. **no unsupervised crash** — every loop crash must be matched by a
   supervisor restart (``loop_crashes`` == ``supervisor_restarts``, and
   the supervisor never gave up);
3. **accounting sane** — dead-letters/abandons/dispatches reconcile with
   the batcher's delivered count (no silently vanished batch).

The fast deterministic variant (``--seconds 2 --seed 7``) runs in tier-1
via ``tests/test_chaos.py``; the long randomized soak is the ``slow``-
marked test (or run this script directly).

``--scenario overload`` runs the overload-protection soak instead: a
seed-logged ``receive: flood`` fault amplifies a mixed interactive/bulk
stream to ~4x a deterministic fake backend's capacity, and the run passes
only if the admission/brownout/journal stack sheds explicitly (no wedge,
no crash, interactive p99 within 2x unloaded, exact admission ledger,
journal covering every shed) — see ``run_overload``.

Usage::

    python scripts/chaos_soak.py --seconds 30            # random seed
    python scripts/chaos_soak.py --seconds 30 --seed 7   # replay
    python scripts/chaos_soak.py --scenario overload --seconds 6
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _check_flight_dumps(trace_dir: str, failures: list,
                        require: int = 1) -> list:
    """Every scenario must leave at least ``require`` flight-recorder
    dumps that parse as JSON with a span map — the observability
    acceptance: after an induced crash/wedge/dead-letter there is always
    evidence of what was in flight. Returns the dump paths."""
    dumps = sorted(glob.glob(os.path.join(trace_dir, "flight-*.json")))
    parsed = 0
    for path in dumps:
        try:
            with open(path) as fh:
                record = json.load(fh)
            if not isinstance(record.get("spans"), dict):
                raise ValueError("no span map")
            parsed += 1
        except (OSError, ValueError) as exc:
            failures.append(f"flight dump unparseable: {path} ({exc})")
    if parsed < require:
        failures.append(f"flight recorder: {parsed} parseable dumps, "
                        f"expected >= {require}")
    return dumps


def _check_span_accounting(dump_path: str, ring_size: int, ledger: dict,
                           failures: list, where: str) -> dict:
    """Span-level mirror of the admission-ledger invariant, read from the
    FLIGHT DUMP itself (the acceptance artifact, not live tracer state):
    with sample=1.0 and no ring eviction, the dump's terminal ``settle``
    spans must reproduce ``completed`` and every per-reason drop count
    exactly — each admitted frame has exactly one terminal span."""
    from opencv_facerecognizer_tpu.runtime.recognizer import FRAME_TOPIC
    from opencv_facerecognizer_tpu.utils import tracing

    try:
        with open(dump_path) as fh:
            spans = json.load(fh)["spans"].get(FRAME_TOPIC, [])
    except (OSError, ValueError, KeyError, TypeError) as exc:
        failures.append(f"{where}: final dump unreadable: {exc}")
        return {}
    acct = tracing.account_spans(spans)
    if len(spans) >= ring_size:
        # The ring wrapped: early spans were evicted, exact accounting is
        # no longer provable — size the ring up instead of asserting lies.
        acct["ring_wrapped"] = True
        return acct
    if acct["completed"] != int(ledger["completed"]):
        failures.append(
            f"{where}: {acct['completed']} completed settle spans != "
            f"ledger completed {ledger['completed']}")
    if acct["completed_empty"] != int(ledger.get("completed_empty", 0)):
        failures.append(
            f"{where}: {acct['completed_empty']} completed_empty settle "
            f"spans != ledger completed_empty "
            f"{ledger.get('completed_empty', 0)}")
    if acct["completed_cached"] != int(ledger.get("completed_cached", 0)):
        failures.append(
            f"{where}: {acct['completed_cached']} completed_cached settle "
            f"spans != ledger completed_cached "
            f"{ledger.get('completed_cached', 0)}")
    want_drops = {k: int(v) for k, v in ledger["drops_by_reason"].items()}
    if acct["drops"] != want_drops:
        failures.append(f"{where}: settle-span drops {acct['drops']} != "
                        f"ledger drops {want_drops}")
    if acct["traced"] != int(ledger["admitted"]):
        failures.append(f"{where}: {acct['traced']} admitted receive "
                        f"spans != ledger admitted {ledger['admitted']}")
    return acct


def _finish_observability(tracer, trace_dir: str, reason: str, ledger: dict,
                          quiesced: bool, failures: list,
                          report: dict) -> None:
    """The shared end-of-scenario observability acceptance: force a final
    dump (rate limits must never suppress the LAST dump of a run), verify
    every dump parses, cross-check the final dump's settle spans against
    the settled ledger (only when the run actually quiesced), and clean
    the temp trace dir. One body — the soak and overload scenarios must
    enforce the identical contract."""
    final_dump = tracer.dump(reason, extra={"ledger": ledger}, force=True)
    flight_dumps = _check_flight_dumps(trace_dir, failures, require=1)
    report["flight_dumps"] = len(flight_dumps)
    if quiesced and final_dump:
        report["span_accounting"] = _check_span_accounting(
            final_dump, tracer.ring_size, ledger, failures,
            "span accounting")
    shutil.rmtree(trace_dir, ignore_errors=True)


def build_stack(frame_shape=(64, 64), face=(16, 16), capacity=64, seed=0):
    """Tiny untrained serving stack (CPU-mesh): chaos cares about the
    loop's control flow, not recognition quality — untrained nets keep
    startup in seconds while exercising the full dispatch/readback path."""
    import jax
    import numpy as np

    from opencv_facerecognizer_tpu.models.detector import CNNFaceDetector
    from opencv_facerecognizer_tpu.models.embedder import FaceEmbedNet, init_embedder
    from opencv_facerecognizer_tpu.parallel import ShardedGallery, make_mesh
    from opencv_facerecognizer_tpu.parallel.pipeline import RecognitionPipeline

    det = CNNFaceDetector(features=(4, 8), head_features=8, max_faces=2,
                          score_threshold=0.5, space_to_depth=4)
    rng = jax.random.PRNGKey(seed)
    det.load_params(det.net.init(
        rng, jax.numpy.zeros((1, *frame_shape), jax.numpy.float32))["params"])
    net = FaceEmbedNet(embed_dim=16, stem_features=4, stage_features=(4, 8),
                       stage_blocks=(1, 1))
    params = init_embedder(net, 4, face, seed=seed)
    mesh = make_mesh()
    gallery = ShardedGallery(capacity=capacity, dim=16, mesh=mesh)
    g_rng = np.random.default_rng(seed)
    emb = g_rng.normal(size=(8, 16)).astype(np.float32)
    gallery.add(emb, np.arange(8, dtype=np.int32) % 4)  # ocvf-lint: boundary=wal-before-mutate -- pre-lifecycle seed rows for the soak stack; the recovery scenario's durable enrollments all ride append_enrollment below
    pipe = RecognitionPipeline(det, net, params["net"], gallery, face_size=face)
    return pipe, mesh


def run_soak(seconds: float = 10.0, seed: int | None = None,
             frame_shape=(64, 64)) -> dict:
    """One supervised chaos run; returns the JSON-able report dict with
    ``report["ok"]`` as the overall verdict."""
    import random as random_mod

    import numpy as np

    from opencv_facerecognizer_tpu.runtime import (
        FakeConnector, FaultInjector, RecognizerService, ResiliencePolicy,
        ServiceSupervisor,
    )
    from opencv_facerecognizer_tpu.runtime.connector import encode_frame
    from opencv_facerecognizer_tpu.runtime.recognizer import (
        FRAME_TOPIC, RESULT_TOPIC,
    )

    if seed is None:
        seed = random_mod.SystemRandom().randrange(1 << 31)
    print(f"chaos_soak seed={seed} seconds={seconds}", file=sys.stderr)

    # Moderate randomized rates: every boundary sees faults in a run of a
    # few hundred frames, but healthy traffic still dominates, so the
    # liveness probe has signal that serving continued THROUGH the chaos.
    rate_rng = random_mod.Random(seed)
    rates = {
        "receive": {"corrupt": 0.05 * rate_rng.random(),
                    "drop": 0.05 * rate_rng.random(),
                    "duplicate": 0.05 * rate_rng.random()},
        "put": {"corrupt": 0.05 * rate_rng.random()},
        "dispatch": {"unavailable": 0.10 * rate_rng.random()},
        "readback": {"stuck": 0.05 * rate_rng.random()},
    }
    injector = FaultInjector(seed=seed, rates=rates)
    pipe, _mesh = build_stack(frame_shape=frame_shape, seed=seed % 997)
    connector = FakeConnector()
    # Full-fidelity tracing (sample=1.0): the soak's span accounting must
    # cover EVERY admitted frame, and every induced dead-letter/crash must
    # leave a parseable flight-recorder dump behind.
    from opencv_facerecognizer_tpu.utils.tracing import Tracer

    trace_dir = tempfile.mkdtemp(prefix="ocvf_flight_")
    tracer = Tracer(ring_size=1 << 16, sample=1.0, seed=seed,
                    dump_dir=trace_dir, min_dump_interval_s=0.1)
    service = RecognizerService(
        pipe, connector, batch_size=2, frame_shape=frame_shape,
        flush_timeout=0.02, inflight_depth=2,
        resilience=ResiliencePolicy(
            dispatch_retries=2, backoff_base_s=0.01, backoff_max_s=0.05,
            readback_deadline_s=0.5, degraded_after=3,
        ),
        fault_injector=injector,
        tracer=tracer,
    )
    supervisor = ServiceSupervisor(service, max_restarts=1000,
                                   poll_interval_s=0.05)
    supervisor.start()

    frame_rng = np.random.default_rng(seed)
    report = {"seed": seed, "seconds": seconds, "rates": rates, "ok": False}
    try:
        sent = 0
        deadline = time.monotonic() + seconds
        while time.monotonic() < deadline:
            frame = frame_rng.uniform(0, 255, frame_shape).astype(np.float32)
            connector.inject(FRAME_TOPIC,
                             {**encode_frame(frame), "meta": {"seq": sent}})
            sent += 1
            time.sleep(0.01)

        # ---- liveness probe: clean traffic after the chaos window ----
        injector.disarm()
        # Clear the chaos-window backlog first: on a slow host the sender
        # outpaces the loop, and liveness means "still making progress", not
        # "zero queue depth the instant chaos ends". drain() is bounded; the
        # probe below is the actual verdict either way.
        service.drain(timeout=max(15.0, 3.0 * seconds))
        probe_n = 6
        for i in range(probe_n):
            frame = frame_rng.uniform(0, 255, frame_shape).astype(np.float32)
            connector.inject(FRAME_TOPIC,
                             {**encode_frame(frame), "meta": {"probe": i}})
        # Wait on the probe-tagged results specifically — counting raw
        # result volume would let backlog results satisfy the wait while
        # the probe frames are still queued (observed false wedge on the
        # 8-virtual-device CPU mesh tier-1 runs).
        probe_deadline = time.monotonic() + 15.0
        probe_results: list = []
        while time.monotonic() < probe_deadline:
            probe_results = [
                r for r in connector.messages(RESULT_TOPIC)
                if isinstance(r.get("meta"), dict) and "probe" in r["meta"]
            ]
            if len(probe_results) >= probe_n:
                break
            time.sleep(0.05)
        results = connector.messages(RESULT_TOPIC)
        wedged = len(probe_results) < probe_n
        # Quiesce once more, then read the admission ledger while the
        # service is still up: every admitted frame must sit in exactly
        # one bucket (completed or a named drop reason) — in_system == 0.
        ledger_quiesced = service.drain(timeout=15.0)
        ledger = service.ledger()
    finally:
        supervisor.stop()

    counters = service.metrics.counters()
    report["sent"] = sent
    report["results"] = len(results)
    report["injected"] = injector.summary()
    report["counters"] = counters
    report["ledger"] = ledger
    report["supervisor_restarts"] = supervisor.restarts

    failures = []
    _finish_observability(tracer, trace_dir, "soak_end", ledger,
                          ledger_quiesced, failures, report)
    if wedged:
        failures.append(f"wedged: liveness probe got {len(probe_results)}/"
                        f"{probe_n} results")
    crashes = counters.get("loop_crashes", 0)
    if crashes != counters.get("supervisor_restarts", 0) or supervisor.gave_up:
        failures.append(f"unsupervised crash: {crashes} crashes vs "
                        f"{counters.get('supervisor_restarts', 0)} restarts "
                        f"(gave_up={supervisor.gave_up})")
    delivered = service.batcher.delivered_batches
    # Every popped batch must end dispatched (then published or dead-
    # lettered) or abandoned (batches_failed) — nothing silently vanishes.
    accounted = (counters.get("batches_dispatched", 0)
                 + counters.get("batches_failed", 0))
    if delivered != accounted:
        failures.append(f"accounting: delivered={delivered} != "
                        f"dispatched+failed={accounted}")
    # Admission ledger (ISSUE 3 invariant): at quiescence every admitted
    # frame is completed or in exactly one named drop bucket. Only checked
    # when the final drain actually quiesced — an un-drained service has
    # frames legitimately in flight (and is already flagged wedged above
    # if the probe stalled too).
    if ledger_quiesced and abs(ledger["in_system"]) > 1e-6:
        failures.append(f"ledger: admitted={ledger['admitted']} != "
                        f"completed={ledger['completed']} + drops="
                        f"{ledger['drops_by_reason']} "
                        f"(in_system={ledger['in_system']})")
    report["failures"] = failures
    report["ok"] = not failures
    return report


def run_overload(seconds: float = 6.0, seed: int | None = None,
                 journal_path: str | None = None) -> dict:
    """Overload scenario (ISSUE 3 acceptance): a ~4x offered-load flood —
    seed-logged ``receive: flood`` fault amplifying a mixed interactive/
    bulk stream — against the full overload-protection stack (admission
    bound, priority shedding, brownout, stale drops, dead-letter journal)
    over a deterministic capacity-limited fake backend.

    Pass criteria (any miss -> ``ok: False``):

    1. **no wedge** — post-flood liveness probe completes;
    2. **no crash** — ``loop_crashes == 0``;
    3. **interactive latency held** — flood-phase interactive e2e p99 stays
       within 2x the unloaded baseline (+50 ms scheduler-noise floor);
    4. **bulk actually shed** — a 4x flood must produce explicit sheds;
    5. **ledger exact** — at quiescence ``admitted == completed +
       Σ drops_by_reason`` (every shed frame has a named reason);
    6. **journal covers the sheds** — journaled frame count equals the
       shed/dead-letter counters it mirrors.
    """
    import random as random_mod

    import numpy as np

    from opencv_facerecognizer_tpu.runtime import (
        DeadLetterJournal, FaultInjector, ServiceSupervisor,
    )
    from opencv_facerecognizer_tpu.runtime.fakes import (
        TrafficRecorder, build_overload_stack,
    )

    if seed is None:
        seed = random_mod.SystemRandom().randrange(1 << 31)
    print(f"chaos_soak overload seed={seed} seconds={seconds}",
          file=sys.stderr)

    frame_shape = (32, 32)
    batch_size = 8
    dispatch_s = 0.04          # hard capacity: 8 / 0.04 = 200 frames/s
    capacity_fps = batch_size / dispatch_s
    flood_factor = 8
    # Effective offered load ~= base * (1 + p*(factor-1)); p in [0.4, 0.6]
    # from the logged seed lands the total at roughly 3-4.5x capacity.
    rate_rng = random_mod.Random(seed)
    flood_p = 0.4 + 0.2 * rate_rng.random()
    base_hz = 4.0 * capacity_fps / (1.0 + flood_p * (flood_factor - 1))

    injector = FaultInjector(seed=seed,
                             rates={"receive": {"flood": flood_p}},
                             flood_factor=flood_factor)
    injector.disarm()  # armed only for the flood phase
    temp_journal = journal_path is None
    if temp_journal:
        fd, journal_path = tempfile.mkstemp(prefix="ocvf_dead_letter_",
                                            suffix=".jsonl")
        os.close(fd)
    journal = DeadLetterJournal(journal_path, max_bytes=1 << 20)
    # Full-fidelity tracing through the flood: shed frames must still
    # settle exactly once each, and the run must leave a parseable
    # flight-recorder dump.
    from opencv_facerecognizer_tpu.utils.tracing import Tracer

    trace_dir = tempfile.mkdtemp(prefix="ocvf_flight_")
    tracer = Tracer(ring_size=1 << 17, sample=1.0, seed=seed,
                    dump_dir=trace_dir, min_dump_interval_s=0.25)
    # SLO burn-rate monitor under test (signals-layer acceptance): tight
    # windows + fine-sliced metrics rings so a few seconds of flood
    # provably burns the budget to critical AND a few seconds of calm
    # provably recovers it — the production defaults just stretch the
    # same clocks. The monitor is ticked by the serving loop (the wiring
    # under test), fires the critical flight dump via this tracer, and at
    # critical adds one level of brownout intake pressure.
    from opencv_facerecognizer_tpu.runtime.recognizer import RecognizerService
    from opencv_facerecognizer_tpu.runtime.slo import SLO, SLOMonitor
    from opencv_facerecognizer_tpu.utils import metric_names as mn
    from opencv_facerecognizer_tpu.utils.metrics import Metrics

    metrics = Metrics(window_s=6.0, window_slices=12)
    slo = SLOMonitor(metrics, [
        SLO(name="queue_wait_p99", kind="latency", window=mn.QUEUE_WAIT,
            threshold_s=0.05, target=0.9, short_s=1.0, long_s=3.0,
            warn_burn=1.0, critical_burn=2.5),
        SLO(name="completion", kind="ratio", target=0.95,
            bad_counters=RecognizerService.LEDGER_DROP_COUNTERS,
            total_counters=(mn.FRAMES_ADMITTED,),
            short_s=1.0, long_s=3.0, warn_burn=1.0, critical_burn=2.5),
    ], tracer=tracer, interval_s=0.25, recovery_evals=2)
    # The service-under-test: the canonical overload harness (shared with
    # bench_serving.run_overload_sweep so both exercise one config).
    pipeline, service, connector = build_overload_stack(
        frame_shape=frame_shape, batch_size=batch_size,
        dispatch_s=dispatch_s, fault_injector=injector, journal=journal,
        tracer=tracer, slo_monitor=slo, metrics=metrics)
    supervisor = ServiceSupervisor(service, max_restarts=100,
                                   poll_interval_s=0.05)
    supervisor.start(warmup=False)

    # Shared seq-tagged recorder (runtime.fakes.TrafficRecorder): the
    # bench's overload_sweep measures through the same code.
    recorder = TrafficRecorder(connector)
    frame = np.zeros(frame_shape, np.float32)
    from opencv_facerecognizer_tpu.runtime.connector import encode_frame
    frame_msg = encode_frame(frame)

    def offer(seq, priority):
        recorder.offer(connector, frame_msg, seq, priority)

    report = {"scenario": "overload", "seed": seed, "seconds": seconds,
              "flood_p": round(flood_p, 3), "flood_factor": flood_factor,
              "capacity_fps": capacity_fps,
              "offered_base_hz": round(base_hz, 1), "ok": False}
    try:
        # ---- phase A: unloaded interactive baseline ----
        base_seqs = []
        seq = 0
        base_end = time.monotonic() + min(1.5, seconds)
        while time.monotonic() < base_end:
            offer(seq, "interactive")
            base_seqs.append(seq)
            seq += 1
            time.sleep(1.0 / 40.0)
        service.drain(timeout=15.0)
        base_p99_ms = recorder.percentile_ms(base_seqs, 99)

        # SLO baseline sanity: after the clean phase the monitor must be
        # sitting at ok (a monitor that starts alarmed proves nothing
        # about the flood).
        slo_baseline_state = slo.state

        # ---- phase B: the flood (seed-logged fault amplification) ----
        injector.arm()
        flood_interactive, flood_bulk = [], []
        interval = 1.0 / base_hz
        flood_end = time.monotonic() + seconds
        i = 0
        slo_max_state = slo.state_code
        while time.monotonic() < flood_end:
            if i % 10 == 0:
                offer(seq, "interactive")
                flood_interactive.append(seq)
            else:
                offer(seq, "bulk")
                flood_bulk.append(seq)
            seq += 1
            i += 1
            slo_max_state = max(slo_max_state, slo.state_code)
            time.sleep(interval)
        injector.disarm()

        # ---- phase C: recovery, liveness probe, ledger ----
        service.drain(timeout=max(15.0, 3.0 * seconds))
        # Brownout must recover on its own once the flood stops (the
        # hysteresis path) — and the probe below must run OUTSIDE
        # brownout, or the level-2 ladder cap would legitimately trim
        # probe frames and read as a false wedge.
        recover_deadline = time.monotonic() + 15.0
        while (service.brownout_level > 0
               and time.monotonic() < recover_deadline):
            slo_max_state = max(slo_max_state, slo.state_code)
            time.sleep(0.05)
        brownout_recovered = service.brownout_level == 0
        # The SLO state machine must also walk back to ok once the
        # rolling windows clear the flood (hysteresis: recovery_evals
        # consecutive calmer evaluations per level) — bounded wait, the
        # serving loop keeps ticking the monitor on idle iterations.
        slo_deadline = time.monotonic() + 20.0
        while slo.state_code > 0 and time.monotonic() < slo_deadline:
            time.sleep(0.05)
        slo_recovered_state = slo.state
        probe_seqs = []
        for _ in range(6):
            offer(seq, "interactive")
            probe_seqs.append(seq)
            seq += 1
        probe_deadline = time.monotonic() + 15.0
        while time.monotonic() < probe_deadline:
            if recorder.completed(probe_seqs) == len(probe_seqs):
                break
            time.sleep(0.05)
        wedged = recorder.completed(probe_seqs) < len(probe_seqs)
        quiesced = service.drain(timeout=15.0)
        ledger = service.ledger()
        flood_p99_ms = recorder.percentile_ms(flood_interactive, 99)
        bulk_completed = recorder.completed(flood_bulk)
    finally:
        supervisor.stop()
        journal.close()

    counters = service.metrics.counters()
    journaled = sum(len(r.get("frames", ())) for r in journal.records())
    journal_expected = sum(counters.get(k, 0) for k in (
        "frames_dead_lettered", "frames_failed", "frames_dropped_brownout",
        "batcher_dropped_stale", "batcher_dropped_overflow"))
    rejected = service.metrics.counters_with_prefix("frames_rejected_")
    shed_total = journal_expected + sum(rejected.values())
    if temp_journal:
        for path in ([journal.path]
                     + [f"{journal.path}.{i}" for i in range(1, 4)]):
            try:
                os.remove(path)
            except OSError:
                pass

    def _ms_or_none(value):
        # NaN (no completions) must not leak into the JSON report —
        # json.dumps would emit the non-RFC 'NaN' token.
        return None if value != value else round(value, 1)

    report.update({
        "offered": seq,
        "baseline_interactive_p99_ms": _ms_or_none(base_p99_ms),
        "flood_interactive_p99_ms": _ms_or_none(flood_p99_ms),
        "flood_bulk_offered": len(flood_bulk),
        "flood_bulk_completed": bulk_completed,
        "rejected": rejected,
        "injected": injector.summary(),
        "ledger": ledger,
        "journal_frames": journaled,
        "journal_path": journal.path,
        "counters": counters,
    })

    report["brownout_recovered"] = brownout_recovered
    from opencv_facerecognizer_tpu.runtime.slo import (
        STATE_CRITICAL, STATE_NAMES, STATE_OK,
    )

    report["slo"] = {
        "baseline_state": slo_baseline_state,
        "max_state": STATE_NAMES[slo_max_state],
        "recovered_state": slo_recovered_state,
        "evaluations": slo.verdict().get("evaluations"),
        "transitions": int(counters.get("slo_transitions", 0)),
    }
    # The critical-transition flight dump: globbed BEFORE
    # _finish_observability tears the trace dir down.
    slo_dumps = sorted(glob.glob(
        os.path.join(trace_dir, "flight-*slo_critical*.json")))
    slo_dump_ok = False
    for path in slo_dumps:
        try:
            with open(path) as fh:
                rec = json.load(fh)
            verdict = rec.get("extra", {}).get("verdict", {})
            if (isinstance(rec.get("spans"), dict)
                    and verdict.get("objectives")):
                slo_dump_ok = True
        except (OSError, ValueError):
            continue
    report["slo"]["critical_dumps"] = len(slo_dumps)
    failures = []
    _finish_observability(tracer, trace_dir, "overload_end", ledger,
                          quiesced, failures, report)
    if wedged:
        failures.append(f"wedged: liveness probe got "
                        f"{recorder.completed(probe_seqs)}/"
                        f"{len(probe_seqs)} results")
    if not brownout_recovered:
        failures.append("brownout never recovered after the flood stopped")
    # ---- SLO acceptance (signals layer) ----
    if slo_baseline_state != STATE_NAMES[STATE_OK]:
        failures.append(f"SLO monitor not ok after the clean baseline "
                        f"phase (was {slo_baseline_state})")
    if slo_max_state < STATE_CRITICAL:
        failures.append(f"SLO monitor never reached critical under a ~4x "
                        f"flood (max {STATE_NAMES[slo_max_state]})")
    if slo_recovered_state != STATE_NAMES[STATE_OK]:
        failures.append(f"SLO monitor never recovered to ok after the "
                        f"flood (stuck at {slo_recovered_state})")
    if slo_max_state >= STATE_CRITICAL and not slo_dump_ok:
        failures.append("critical transition left no parseable "
                        "slo_critical flight dump with a verdict")
    if counters.get("loop_crashes", 0):
        failures.append(f"crashed: loop_crashes={counters['loop_crashes']}")
    # NaN percentiles mean zero completions in that phase — each is its
    # own failure; the latency comparison only runs with both present (a
    # NaN baseline must not let the criterion pass vacuously).
    if base_p99_ms != base_p99_ms:
        failures.append("no baseline interactive frame completed")
    if flood_p99_ms != flood_p99_ms:
        failures.append("no flood-phase interactive frame completed")
    elif (base_p99_ms == base_p99_ms
          and flood_p99_ms > 2.0 * base_p99_ms + 50.0):
        failures.append(f"interactive p99 blew the budget: flood "
                        f"{flood_p99_ms:.0f} ms > 2x baseline "
                        f"{base_p99_ms:.0f} ms + 50 ms")
    if shed_total <= 0:
        failures.append("a 4x flood produced zero explicit sheds/rejects")
    if quiesced and abs(ledger["in_system"]) > 1e-6:
        failures.append(f"ledger: in_system={ledger['in_system']} != 0 "
                        f"(admitted={ledger['admitted']}, "
                        f"completed={ledger['completed']}, "
                        f"drops={ledger['drops_by_reason']})")
    if not quiesced:
        failures.append("final drain never quiesced")
    if journaled != journal_expected:
        failures.append(f"journal: {journaled} frames journaled != "
                        f"{journal_expected} counted sheds")
    report["failures"] = failures
    report["ok"] = not failures
    return report


def run_recovery(seconds: float = 4.0, seed: int | None = None,
                 state_dir: str | None = None) -> dict:
    """Crash-recovery scenario (state lifecycle acceptance): seeded kill
    points injected at every durability boundary — mid-WAL-append (torn
    and before-write), mid-checkpoint (torn tmp, crash-before-rename, and
    crash-after-rename-before-WAL-truncate), post-rename media corruption
    of the newest checkpoint, and a kill mid-restore — across repeated
    simulated process lifetimes over ONE state directory.

    Invariant asserted every "restart": recovery lands on a
    checksum-verified gallery holding EXACTLY the acknowledged enrollment
    history (an ``append_enrollment`` that returned, WAL at ``always``) —
    bit-equal rows, zero loss, zero phantoms — with ``checkpoints_corrupt``
    incremented whenever a corrupt newest checkpoint forced fallback.

    Ends with a **graceful-drain phase**: a live service (deterministic
    ``InstantPipeline`` backend) takes frames, then the SIGTERM path
    (``state_store.graceful_shutdown``) must complete in-flight frames,
    settle the admission ledger exactly, write a final checkpoint, and
    leave the WAL empty.
    """
    import random as random_mod

    import numpy as np

    from opencv_facerecognizer_tpu.parallel import ShardedGallery, make_mesh
    from opencv_facerecognizer_tpu.runtime import (
        FakeConnector, FaultInjector, RecognizerService, StateLifecycle,
        graceful_shutdown,
    )
    from opencv_facerecognizer_tpu.runtime.connector import encode_frame
    from opencv_facerecognizer_tpu.runtime.fakes import InstantPipeline
    from opencv_facerecognizer_tpu.runtime.faults import InjectedCrashError
    from opencv_facerecognizer_tpu.runtime.recognizer import (
        FRAME_TOPIC, RESULT_TOPIC,
    )
    from opencv_facerecognizer_tpu.utils.metrics import Metrics

    if seed is None:
        seed = random_mod.SystemRandom().randrange(1 << 31)
    print(f"chaos_soak recovery seed={seed} seconds={seconds}",
          file=sys.stderr)
    rng = random_mod.Random(seed)
    frame_rng = np.random.default_rng(seed)

    temp_dir = state_dir is None
    if temp_dir:
        state_dir = tempfile.mkdtemp(prefix="ocvf_recovery_")
    mesh = make_mesh()
    DIM = 8

    #: acknowledged history: (seq, raw embeddings, labels, subject, label)
    #: — only appended AFTER append_enrollment returns (the fsync ack).
    acked: list = []
    report = {"scenario": "recovery", "seed": seed, "seconds": seconds,
              "state_dir": state_dir, "ok": False}
    failures: list = []
    counts = {"rounds": 0, "kills": 0, "wal_torn": 0, "wal_crash": 0,
              "ckpt_torn": 0, "ckpt_crash": 0, "ckpt_late": 0,
              "media_corrupt": 0, "mid_restore_kills": 0,
              "checkpoints_corrupt": 0, "replayed_rows": 0}

    def expected_rows():
        """The normalized row matrix + labels recovery must reproduce."""
        if not acked:
            return np.zeros((0, DIM), np.float32), np.zeros((0,), np.int32)
        emb = np.concatenate([e for _s, e, _l, _su, _la in acked])
        lab = np.concatenate([l for _s, _e, l, _su, _la in acked])
        norm = emb / np.maximum(
            np.linalg.norm(emb, axis=-1, keepdims=True), 1e-12)
        return norm.astype(np.float32), lab.astype(np.int32)

    def verify_recovered(gallery, where: str) -> None:
        want_emb, want_lab = expected_rows()
        got_emb, got_lab, got_val, got_size = gallery.snapshot()
        if got_size != len(want_lab):
            failures.append(
                f"{where}: recovered {got_size} rows, expected "
                f"{len(want_lab)} acknowledged rows (seed={seed})")
            return
        if got_size and not np.array_equal(got_lab[:got_size], want_lab):
            failures.append(f"{where}: recovered labels differ")
            return
        if got_size and not np.allclose(got_emb[:got_size], want_emb,
                                        rtol=0, atol=1e-6):
            failures.append(f"{where}: recovered embeddings differ")

    # Rounds derive from the time budget DETERMINISTICALLY (not from the
    # wall clock): the kill schedule is a pure function of (seed, seconds),
    # so a replay with the printed seed reproduces the exact same crash
    # sequence regardless of machine speed.
    n_rounds = max(6, min(60, int(seconds * 5)))
    metrics = None
    try:
        while counts["rounds"] < n_rounds:
            counts["rounds"] += 1
            injector = FaultInjector(seed=seed + counts["rounds"])
            metrics = Metrics()
            # ---- "restart": fresh process state over the same dir ----
            if acked and rng.random() < 0.25:
                # Kill mid-restore: run a recovery, discard everything it
                # built, restart again — recovery is read-only on the
                # durable files (quarantine renames are idempotent), so a
                # second restore must land identically.
                counts["mid_restore_kills"] += 1
                counts["kills"] += 1
                scratch = ShardedGallery(capacity=64, dim=DIM, mesh=mesh)
                # Shares the round's metrics: a corrupt checkpoint
                # quarantined by THIS (killed) restore must still show up
                # in the counted fallbacks.
                StateLifecycle(state_dir, metrics=metrics).recover(
                    scratch, [])
            gallery = ShardedGallery(capacity=64, dim=DIM, mesh=mesh)
            names: list = []
            state = StateLifecycle(
                state_dir, metrics=metrics, keep_checkpoints=3,
                # Manual checkpoints only: the kill schedule owns timing.
                checkpoint_wal_rows=1 << 30, checkpoint_every_s=1e9,
                fault_injector=injector)
            rec = state.recover(gallery, names)
            counts["checkpoints_corrupt"] += int(
                metrics.counter("checkpoints_corrupt"))
            counts["replayed_rows"] += rec["replayed_rows"]
            verify_recovered(gallery, f"round {counts['rounds']} recovery")
            # Subject names must match the acknowledged mapping too.
            for _seq, _e, _l, subject, label in acked:
                if label < len(names) and names[label] != subject:
                    failures.append(
                        f"round {counts['rounds']}: name[{label}] = "
                        f"{names[label]!r}, expected {subject!r}")
                    break

            # ---- live phase: enrollments with seeded kill points ----
            died = False
            for _ in range(rng.randint(2, 5)):
                n = rng.randint(1, 3)
                emb = frame_rng.normal(size=(n, DIM)).astype(np.float32)
                label = len(names)
                subject = f"subject_{len(acked)}"
                labels = np.full(n, label, np.int32)
                kill = rng.random()
                if kill < 0.15:
                    injector.script("wal", "torn")
                elif kill < 0.25:
                    injector.script("wal", "crash")
                try:
                    seq = state.append_enrollment(
                        emb, labels, subject=subject, label=label,
                        apply_fn=lambda e=emb, l=labels: gallery.add(e, l))
                except InjectedCrashError:
                    # Process died mid-append: NOT acknowledged — the
                    # enrollment may or may not survive; what recovery
                    # must never do is lose an ACKED one or invent rows
                    # (a torn record never replays: crc/json guard).
                    counts["kills"] += 1
                    counts["wal_torn" if kill < 0.15 else "wal_crash"] += 1
                    died = True
                    break
                names.append(subject)
                acked.append((seq, emb, labels, subject, label))
            if died:
                continue  # abandoned without close(): a real crash

            # ---- checkpoint attempts with seeded kill points ----
            if rng.random() < 0.7:
                kill = rng.random()
                fault = None
                if kill < 0.2:
                    fault, key = "torn", "ckpt_torn"
                elif kill < 0.35:
                    fault, key = "crash", "ckpt_crash"
                elif kill < 0.5:
                    fault, key = "late", "ckpt_late"
                if fault is not None:
                    injector.script("checkpoint", fault)
                try:
                    state.checkpoint_now(wait=True)
                except InjectedCrashError:
                    counts["kills"] += 1
                    counts[key] += 1
                    if fault == "late":
                        # The checkpoint INSTALLED; the WAL truncate never
                        # ran. Sometimes additionally corrupt the newest
                        # file on disk (the torn-rename/media shape): the
                        # next recovery must fall back past it — the WAL
                        # still covers everything.
                        if rng.random() < 0.6:
                            files = state.store.checkpoint_files()
                            if files:
                                path = files[0][1]
                                blob = open(path, "rb").read()
                                # ocvf-lint: disable=non-atomic-write -- deliberately injecting a torn checkpoint: the whole point is to corrupt the newest file and prove recovery falls back past it
                                with open(path, "wb") as fh:
                                    fh.write(blob[:int(len(blob) * 0.6)])
                                counts["media_corrupt"] += 1
                    continue  # died: next round restarts
            # Clean shutdown of this lifetime (no close: daemon-style exit)

        # ---- final full verification over a clean recovery ----
        final_metrics = Metrics()
        gallery = ShardedGallery(capacity=64, dim=DIM, mesh=mesh)
        names = []
        state = StateLifecycle(state_dir, metrics=final_metrics)
        state.recover(gallery, names)
        # A media corruption injected in the LAST round is quarantined by
        # THIS recovery — fold its fallback count in too.
        counts["checkpoints_corrupt"] += int(
            final_metrics.counter("checkpoints_corrupt"))
        verify_recovered(gallery, "final recovery")
        if not state.checkpoint_now(wait=True):
            failures.append("final checkpoint failed")
        # Offline verification must pass on what recovery left installed.
        import importlib.util as _ilu

        spec = _ilu.spec_from_file_location(
            "verify_checkpoint",
            os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "verify_checkpoint.py"))
        verify_mod = _ilu.module_from_spec(spec)
        spec.loader.exec_module(verify_mod)
        vreport = verify_mod.verify_state_dir(state_dir)
        report["verify"] = {"ok": vreport["ok"],
                            "checkpoints": len(vreport["checkpoints"]),
                            "corrupt": vreport["corrupt"]}
        if not vreport["ok"]:
            failures.append(f"offline verification failed: "
                            f"{vreport['corrupt']}")

        # ---- graceful-drain phase (the SIGTERM path) ----
        frame_shape = (16, 16)
        drain_metrics = Metrics()
        pipe = InstantPipeline(frame_shape, dispatch_s=0.002)
        pipe.gallery = gallery
        connector = FakeConnector()
        # Tracing through the drain: SIGTERM must force a final flight
        # dump whose lifecycle spans show the WAL append + final
        # checkpoint this phase performs.
        from opencv_facerecognizer_tpu.utils.tracing import Tracer

        trace_dir = tempfile.mkdtemp(prefix="ocvf_flight_")
        tracer = Tracer(ring_size=1 << 14, sample=1.0, seed=seed,
                        dump_dir=trace_dir)
        drain_state = StateLifecycle(state_dir, metrics=drain_metrics,
                                     checkpoint_wal_rows=1 << 30,
                                     checkpoint_every_s=1e9,
                                     tracer=tracer)
        service = RecognizerService(
            pipe, connector, batch_size=4, frame_shape=frame_shape,
            flush_timeout=0.02, state_store=drain_state, tracer=tracer)
        # recover() was already run for this dir; bind fresh seq state so
        # the drain-phase enrollment sequences continue, not collide.
        drain_state.recover(gallery, names)
        service.subject_names = names
        service.start(warmup=False)
        frame = np.zeros(frame_shape, np.float32)
        sent = 24
        for i in range(sent):
            connector.inject(FRAME_TOPIC,
                             {**encode_frame(frame), "meta": {"seq": i}})
        # One in-flight enrollment through the write-ahead path.
        emb = frame_rng.normal(size=(2, DIM)).astype(np.float32)
        label = len(names)
        drain_state.append_enrollment(
            emb, np.full(2, label, np.int32), subject="drain_subject",
            label=label,
            apply_fn=lambda: gallery.add(emb, np.full(2, label, np.int32)))
        names.append("drain_subject")
        acked.append((drain_state.wal_seq, emb, np.full(2, label, np.int32),
                      "drain_subject", label))
        shutdown = graceful_shutdown(service, state=drain_state,
                                     drain_timeout=30.0)
        results = len(connector.messages(RESULT_TOPIC))
        report["drain"] = {"sent": sent, "results": results,
                           "shutdown": {k: v for k, v in shutdown.items()}}
        # Observability acceptance for the recovery scenario: the SIGTERM
        # drain forces a flight dump; it must parse, and its lifecycle
        # spans must show the durable work this phase performed.
        _check_flight_dumps(trace_dir, failures, require=1)
        dump_path = shutdown.get("flight_dump")
        if not dump_path:
            failures.append("graceful shutdown produced no flight dump")
        else:
            try:
                with open(dump_path) as fh:
                    dump_rec = json.load(fh)
                life = [s["stage"] for s in
                        dump_rec["spans"].get("_lifecycle", ())]
                if "wal_append" not in life or "checkpoint" not in life:
                    failures.append(f"drain dump lifecycle spans missing "
                                    f"wal_append/checkpoint: {life}")
                report["drain"]["flight_dump_lifecycle"] = sorted(set(life))
            except (OSError, ValueError, KeyError) as exc:
                failures.append(f"drain flight dump unreadable: {exc}")
        shutil.rmtree(trace_dir, ignore_errors=True)
        if not shutdown["drained"]:
            failures.append("graceful drain timed out")
        if results != sent:
            failures.append(f"drain: {results}/{sent} frames published")
        if abs(shutdown["ledger"]["in_system"]) > 1e-6:
            failures.append(f"drain ledger unsettled: "
                            f"{shutdown['ledger']}")
        if not shutdown.get("final_checkpoint"):
            failures.append("no final checkpoint on graceful shutdown")
        # WAL must be empty after the final checkpoint truncated it.
        leftover = sum(1 for _ in drain_state.wal.enrollments())
        if leftover:
            failures.append(f"WAL holds {leftover} records after final "
                            f"checkpoint")
        # And the post-shutdown state must recover the drain enrollment.
        g2 = ShardedGallery(capacity=64, dim=DIM, mesh=mesh)
        StateLifecycle(state_dir, metrics=Metrics()).recover(g2, [])
        verify_recovered(g2, "post-drain recovery")
    finally:
        if temp_dir:
            shutil.rmtree(state_dir, ignore_errors=True)

    if counts["checkpoints_corrupt"] < 1 <= counts["media_corrupt"]:
        failures.append("corrupt newest checkpoint never counted "
                        "checkpoints_corrupt")
    report["counts"] = counts
    report["acked_enrollments"] = len(acked)
    report["failures"] = failures
    report["ok"] = not failures
    return report


def run_replication(seconds: float = 6.0, seed: int | None = None,
                    state_dir: str | None = None) -> dict:
    """Replication scenario (ISSUE 10 acceptance): 1 writer + 2
    WAL-tailing read replicas serving one logical gallery out of a shared
    state dir, camera topics spread across all three by the rendezvous
    topic router, enrollment traffic riding the writer's WAL — then kill
    a read replica mid-traffic AND kill the writer mid-enrollment.

    Pass criteria (any miss -> ``ok: False``):

    1. **failover holds latency** — interactive p99 over frames routed
       after each kill stays within 2x the unloaded baseline (+100 ms
       absolute floor: the restart window carries recovery/jit churn on
       a 1-core box) on the surviving replicas;
    2. **zero acked loss** — after the dust settles, every enrollment
       whose ``append_enrollment`` returned is present, bit-equal and in
       order, on EVERY survivor (the restarted writer's recovery, the
       surviving reader's tail, and a freshly resynced replacement
       replica), with replay-dedup exactness (no phantom rows);
    3. **split-brain fails closed** — while the writer lease is held, a
       second writer in a REAL second process must refuse to start;
    4. **ledgers settle** — each replica's admission ledger reaches
       ``in_system == 0`` (the killed reader settles what it had);
    5. **observability** — the failover leaves a parseable flight dump
       and the replicas' ``wal_tail`` lifecycle spans recorded the tail.
    """
    import random as random_mod
    import subprocess
    import threading

    import numpy as np

    from opencv_facerecognizer_tpu.parallel import ShardedGallery, make_mesh
    from opencv_facerecognizer_tpu.runtime import (
        FakeConnector, FaultInjector, ReadReplica, RecognizerService,
        ReplicaHandle, ResiliencePolicy, StateLifecycle, TopicRouter,
        WriterLease,
    )
    from opencv_facerecognizer_tpu.runtime.connector import encode_frame
    from opencv_facerecognizer_tpu.runtime.fakes import (
        InstantPipeline, TrafficRecorder,
    )
    from opencv_facerecognizer_tpu.runtime.faults import InjectedCrashError
    from opencv_facerecognizer_tpu.runtime.replication import (
        service_health_probe,
    )
    from opencv_facerecognizer_tpu.utils.metrics import Metrics
    from opencv_facerecognizer_tpu.utils.tracing import Tracer

    if seed is None:
        seed = random_mod.SystemRandom().randrange(1 << 31)
    print(f"chaos_soak replication seed={seed} seconds={seconds}",
          file=sys.stderr)
    rng = random_mod.Random(seed)
    frame_rng = np.random.default_rng(seed)

    temp_dir = state_dir is None
    if temp_dir:
        state_dir = tempfile.mkdtemp(prefix="ocvf_replication_")
    trace_dir = tempfile.mkdtemp(prefix="ocvf_flight_")
    tracer = Tracer(ring_size=1 << 16, sample=1.0, seed=seed,
                    dump_dir=trace_dir, min_dump_interval_s=0.1)
    mesh = make_mesh()
    DIM = 8
    frame_shape = (32, 32)
    dispatch_s = 0.01  # 800 frames/s per replica: traffic stays unloaded
    offered_hz = 60.0
    topics = 12

    report = {"scenario": "replication", "seed": seed, "seconds": seconds,
              "state_dir": state_dir, "ok": False}
    failures: list = []

    #: acknowledged enrollment history (seq, emb, labels, subject, label)
    #: — appended only AFTER append_enrollment returns.
    acked: list = []

    def expected_rows():
        if not acked:
            return (np.zeros((0, DIM), np.float32),
                    np.zeros((0,), np.int32))
        emb = np.concatenate([e for _s, e, _l, _su, _la in acked])
        lab = np.concatenate([l for _s, _e, l, _su, _la in acked])
        norm = emb / np.maximum(
            np.linalg.norm(emb, axis=-1, keepdims=True), 1e-12)
        return norm.astype(np.float32), lab.astype(np.int32)

    def verify_gallery(gallery, where: str) -> None:
        want_emb, want_lab = expected_rows()
        got_emb, got_lab, _v, got_size = gallery.snapshot()
        if got_size != len(want_lab):
            failures.append(f"{where}: {got_size} rows, expected "
                            f"{len(want_lab)} acked (seed={seed})")
            return
        if got_size and not np.array_equal(got_lab[:got_size], want_lab):
            failures.append(f"{where}: labels differ")
        elif got_size and not np.allclose(got_emb[:got_size], want_emb,
                                          rtol=0, atol=1e-6):
            failures.append(f"{where}: embeddings differ")

    def make_service(gallery, metrics, replica=None):
        pipe = InstantPipeline(frame_shape, dispatch_s=dispatch_s)
        pipe.gallery = gallery
        svc = RecognizerService(
            pipe, FakeConnector(), batch_size=8, frame_shape=frame_shape,
            flush_timeout=0.02, inflight_depth=2, similarity_threshold=0.0,
            metrics=metrics,
            resilience=ResiliencePolicy(readback_deadline_s=2.0),
            replica=replica)
        return svc

    # ---- writer: lease + lifecycle + a serving service over the same
    # gallery (enrollment rides a dedicated thread through the WAL) ----
    injector = FaultInjector(seed=seed)
    writer_metrics = Metrics()
    lease = WriterLease(state_dir, metrics=writer_metrics).acquire()
    writer_gallery = ShardedGallery(capacity=1024, dim=DIM, mesh=mesh)
    writer_names: list = []
    state = StateLifecycle(state_dir, metrics=writer_metrics,
                           checkpoint_wal_rows=16, checkpoint_every_s=1e9,
                           fault_injector=injector, tracer=tracer)
    state.bind(writer_gallery, writer_names)
    writer_box = {"svc": make_service(writer_gallery, writer_metrics)}

    # ---- two read replicas over the same state dir ----
    readers = []
    for i in range(2):
        rmetrics = Metrics()
        rgallery = ShardedGallery(capacity=1024, dim=DIM, mesh=mesh)
        rnames: list = []
        rep = ReadReplica(state_dir, rgallery, rnames, metrics=rmetrics,
                          tracer=tracer, poll_interval_s=0.05,
                          name=f"reader-{i}")
        rep.poll(force=True)  # initial sync before serving starts
        readers.append({"replica": rep, "gallery": rgallery,
                        "names": rnames, "metrics": rmetrics,
                        "svc": make_service(rgallery, rmetrics,
                                            replica=rep)})

    # ---- router over all three serving replicas ----
    router_metrics = Metrics()
    handles = [ReplicaHandle(
        "writer", writer_box["svc"].connector,
        health_fn=lambda: service_health_probe(writer_box["svc"])(),
        writer=True)]
    for i, reader in enumerate(readers):
        handles.append(ReplicaHandle(
            f"reader-{i}", reader["svc"].connector,
            health_fn=service_health_probe(reader["svc"])))
    router = TopicRouter(handles, metrics=router_metrics, tracer=tracer,
                         health_interval_s=0.05)
    recorder = TrafficRecorder(router)
    frame_msg = encode_frame(np.zeros(frame_shape, np.float32))

    seq_box = {"seq": 0}

    def offer() -> int:
        seq = seq_box["seq"]
        seq_box["seq"] = seq + 1
        recorder.send_t[seq] = time.monotonic()
        router.publish(f"camera/{seq % topics}",
                       {**frame_msg, "priority": "interactive",
                        "meta": {"seq": seq}})
        return seq

    # ---- enrollment traffic thread (the writer's WAL write path) ----
    enroll_stop = threading.Event()
    writer_died = threading.Event()

    def enroll_loop():
        while not enroll_stop.is_set():
            n = rng.randint(1, 2)
            emb = frame_rng.normal(size=(n, DIM)).astype(np.float32)
            label = len(writer_names)
            subject = f"subject_{len(acked)}"
            labels = np.full(n, label, np.int32)
            writer_names.append(subject)
            try:
                seq = state.append_enrollment(
                    emb, labels, subject=subject, label=label,
                    apply_fn=lambda e=emb, l=labels:
                        writer_gallery.add(e, l))
            except InjectedCrashError:
                # The writer process "died" mid-enrollment: NOT acked.
                writer_names.pop()
                writer_died.set()
                return
            acked.append((seq, emb, labels, subject, label))
            time.sleep(0.015)

    writer_box["svc"].start(warmup=False)
    for reader in readers:
        reader["svc"].start(warmup=False)
    router.start()
    enroll_thread = threading.Thread(target=enroll_loop, daemon=True)

    def warm_enroll(n: int) -> None:
        """Synchronous enrollments BEFORE the baseline clock: the first
        gallery.add per shape pays a jit compile (seconds on this box),
        and charging that one-off to the baseline p99 would inflate the
        whole latency budget into meaninglessness."""
        for _ in range(n):
            emb = frame_rng.normal(size=(1, DIM)).astype(np.float32)
            label = len(writer_names)
            subject = f"subject_{len(acked)}"
            labels = np.full(1, label, np.int32)
            writer_names.append(subject)
            seq = state.append_enrollment(
                emb, labels, subject=subject, label=label,
                apply_fn=lambda e=emb, l=labels: writer_gallery.add(e, l))
            acked.append((seq, emb, labels, subject, label))
        for reader in readers:
            reader["replica"].poll(force=True)

    try:
        warm_enroll(3)
        # ---- phase A: baseline interactive p99 across the healthy
        # fleet. Enrollment churn runs from the START — the baseline and
        # the survivor phases must differ only in the kills, or the
        # comparison charges replication's background gallery applies to
        # the failover ----
        enroll_thread.start()
        base_seqs = []
        base_end = time.monotonic() + min(1.0, seconds / 4)
        while time.monotonic() < base_end:
            base_seqs.append(offer())
            time.sleep(1.0 / 40.0)
        for svc in [writer_box["svc"]] + [r["svc"] for r in readers]:
            svc.drain(timeout=15.0)
        base_p99_ms = recorder.percentile_ms(base_seqs, 99)

        # ---- phase B: traffic + enrollment, kill a reader, kill the
        # writer ----
        interval = 1.0 / offered_hz
        t0 = time.monotonic()
        reader_kill_at = t0 + seconds * 0.33
        writer_kill_at = t0 + seconds * 0.62
        end_at = t0 + seconds
        reader_killed_t = writer_killed_t = None
        writer_restarted_t = None
        writer_lost_at_death = 0
        survivor_seqs_a: list = []   # after the reader kill
        survivor_seqs_b: list = []   # after the writer kill + failover
        split_brain_rc = None
        while True:
            now = time.monotonic()
            if now >= end_at and writer_restarted_t is not None \
                    and now >= writer_restarted_t + max(0.6, seconds * 0.15):
                break
            if now >= t0 + seconds * 3 + 30.0:
                break  # hard stop: the kill schedule wedged somewhere
            seq = offer()
            if reader_killed_t is not None and now > reader_killed_t + 0.3 \
                    and (writer_killed_t is None):
                survivor_seqs_a.append(seq)
            if writer_restarted_t is not None \
                    and now > writer_restarted_t + 0.5:
                survivor_seqs_b.append(seq)
            if reader_killed_t is None and now >= reader_kill_at:
                # Kill read replica 1 mid-traffic (simulated process
                # death: its serving loop and WAL tail stop cold).
                readers[1]["svc"].stop()
                reader_killed_t = time.monotonic()
            if writer_killed_t is None and now >= writer_kill_at:
                # Kill the writer mid-enrollment: the next WAL append
                # dies torn (the enrollment thread exits un-acked), and
                # the writer's serving side stops with it.
                injector.script("wal", "torn")
                deadline = time.monotonic() + 10.0
                while (not writer_died.is_set()
                       and time.monotonic() < deadline):
                    time.sleep(0.005)
                writer_box["svc"].stop()
                # Frames physically inside the dying writer (queued or
                # in-flight) die with the "process": the shared writer
                # metrics will carry them as in_system forever — record
                # the exact remainder for the ledger check.
                dead_writer = writer_box["svc"]
                with dead_writer._inflight_cv:
                    writer_lost_at_death = (
                        dead_writer.batcher.pending
                        + sum(entry[3] for entry in dead_writer._inflight))
                lease.release()  # a dead process's flock vanishes with it
                writer_killed_t = time.monotonic()
            if (writer_killed_t is not None and writer_restarted_t is None
                    and now >= writer_killed_t + max(0.3, seconds * 0.08)):
                # ---- writer restart: recover + re-acquire the lease ----
                new_gallery = ShardedGallery(capacity=1024, dim=DIM,
                                             mesh=mesh)
                new_names: list = []
                lease = WriterLease(state_dir,
                                    metrics=writer_metrics).acquire()
                state = StateLifecycle(
                    state_dir, metrics=writer_metrics,
                    checkpoint_wal_rows=16, checkpoint_every_s=1e9,
                    tracer=tracer)
                state.recover(new_gallery, new_names)
                verify_gallery(new_gallery, "writer recovery")
                writer_gallery = new_gallery
                writer_names = new_names
                new_svc = make_service(new_gallery, writer_metrics)
                new_svc.start(warmup=False)
                # Rewire the router at the restarted service's fresh
                # connector (fan-in re-subscribes there — results from
                # the new writer must reach the recorder, or the
                # post-restart p99 would silently measure readers only);
                # the dynamic probe sees the new service via writer_box.
                writer_box["svc"] = new_svc
                router.replace_connector("writer", new_svc.connector)
                writer_restarted_t = time.monotonic()
                # Resume enrollment on the recovered writer.
                enroll_stop.clear()
                writer_died.clear()

                def enroll_loop2(state=state, gallery=new_gallery,
                                 names=new_names):
                    while not enroll_stop.is_set():
                        n = rng.randint(1, 2)
                        emb = frame_rng.normal(size=(n, DIM)).astype(
                            np.float32)
                        label = len(names)
                        subject = f"subject_{len(acked)}"
                        labels = np.full(n, label, np.int32)
                        names.append(subject)
                        try:
                            seq = state.append_enrollment(
                                emb, labels, subject=subject, label=label,
                                apply_fn=lambda e=emb, l=labels:
                                    gallery.add(e, l))
                        except InjectedCrashError:
                            names.pop()
                            return
                        acked.append((seq, emb, labels, subject, label))
                        time.sleep(0.015)

                enroll_thread = threading.Thread(target=enroll_loop2,
                                                 daemon=True)
                enroll_thread.start()
            time.sleep(interval)
        enroll_stop.set()
        enroll_thread.join(timeout=5.0)

        # ---- phase C: settle, catch up, verify ----
        # Split-brain probe in a REAL second process while the
        # (re-acquired) lease is live: acquiring must fail closed (rc 3).
        # Run here, not mid-traffic — the child pays ~4 s of imports and
        # must probe while a lease is provably held, never during the
        # crash window between release and re-acquire.
        code = (
            "import sys\n"
            "from opencv_facerecognizer_tpu.runtime.replication "
            "import WriterLease, WriterLeaseHeldError\n"
            "try:\n"
            f"    WriterLease({state_dir!r}).acquire()\n"
            "except WriterLeaseHeldError:\n"
            "    sys.exit(3)\n"
            "sys.exit(0)\n")
        proc = subprocess.run(
            [sys.executable, "-c", code], capture_output=True,
            text=True, timeout=120,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        split_brain_rc = proc.returncode
        writer_box["svc"].drain(timeout=15.0)
        readers[0]["svc"].drain(timeout=15.0)
        target_seq = state.wal_seq
        catch_deadline = time.monotonic() + 15.0
        while (readers[0]["replica"].applied_seq < target_seq
               and time.monotonic() < catch_deadline):
            time.sleep(0.02)
        # A replacement replica (the killed reader's "process restart"):
        # fresh gallery, full resync from checkpoint + WAL.
        replacement_gallery = ShardedGallery(capacity=1024, dim=DIM,
                                             mesh=mesh)
        replacement = ReadReplica(state_dir, replacement_gallery, [],
                                  metrics=Metrics(), tracer=tracer,
                                  poll_interval_s=0.0, name="replacement")
        replacement.poll(force=True)

        verify_gallery(writer_gallery, "writer (post-restart)")
        verify_gallery(readers[0]["gallery"], "surviving reader")
        verify_gallery(replacement_gallery, "replacement replica")
        if readers[0]["replica"].applied_seq < target_seq:
            failures.append(
                f"surviving reader never caught up: applied "
                f"{readers[0]['replica'].applied_seq} < {target_seq}")

        p99_a = recorder.percentile_ms(survivor_seqs_a, 99)
        p99_b = recorder.percentile_ms(survivor_seqs_b, 99)
        ledgers = {
            "writer": writer_box["svc"].ledger(),
            "reader-0": readers[0]["svc"].ledger(),
            "reader-1": readers[1]["svc"].ledger(),
        }
        # The killed reader's remainder: frames physically inside the
        # dead service — queued in its batcher or riding an in-flight
        # batch its readback worker never completed. A real kill loses
        # them with the process; the in-process emulation keeps the
        # metrics alive, so its exactness check is in_system == that
        # remainder (every OTHER admitted frame is completed or in a
        # named drop bucket).
        dead_svc = readers[1]["svc"]
        with dead_svc._inflight_cv:
            inflight_frames = sum(entry[3] for entry in dead_svc._inflight)
        reader1_queued_at_death = dead_svc.batcher.pending + inflight_frames
    finally:
        enroll_stop.set()
        router.stop()
        for svc in [writer_box["svc"]] + [r["svc"] for r in readers]:
            try:
                svc.stop()
            except Exception:  # noqa: BLE001 — teardown must finish
                import traceback

                traceback.print_exc()
        lease.release()
        state.close()

    report.update({
        "offered": seq_box["seq"],
        "acked_enrollments": len(acked),
        "baseline_p99_ms": None if base_p99_ms != base_p99_ms
        else round(base_p99_ms, 1),
        "survivor_p99_after_reader_kill_ms":
            None if p99_a != p99_a else round(p99_a, 1),
        "survivor_p99_after_writer_restart_ms":
            None if p99_b != p99_b else round(p99_b, 1),
        "split_brain_rc": split_brain_rc,
        "ledgers": ledgers,
        "router": {k: v for k, v in router_metrics.counters().items()},
        "reader0": readers[0]["replica"].stats(),
        "replacement": replacement.stats(),
    })

    # ---- pass criteria ----
    if base_p99_ms != base_p99_ms:
        failures.append("no baseline frame completed")
    # +100 ms absolute floor (vs the overload soak's 50): the writer
    # restart window legitimately carries recovery/jit churn on a 1-core
    # box, and a sub-50 ms baseline would turn that scheduler noise into
    # a false failure.
    for label, p99 in (("reader kill", p99_a), ("writer restart", p99_b)):
        if p99 != p99:
            failures.append(f"no survivor frame completed after {label}")
        elif base_p99_ms == base_p99_ms and p99 > 2.0 * base_p99_ms + 100.0:
            failures.append(
                f"survivor p99 after {label} blew the budget: "
                f"{p99:.0f} ms > 2x baseline {base_p99_ms:.0f} ms + 100 ms")
    if split_brain_rc != 3:
        failures.append(f"split-brain second writer did NOT fail closed "
                        f"(subprocess rc={split_brain_rc}, expected 3)")
    for name, ledger in ledgers.items():
        # Survivors settle to exactly zero; the KILLED reader settles to
        # exactly its queued-at-death remainder (those frames died with
        # the "process" — every other admitted frame is completed or in a
        # named drop bucket).
        expect = {"reader-1": reader1_queued_at_death,
                  "writer": writer_lost_at_death}.get(name, 0)
        if abs(ledger["in_system"] - expect) > 1e-6:
            failures.append(f"{name} ledger unsettled (expected in_system="
                            f"{expect}): {ledger}")
    report["reader1_queued_at_death"] = reader1_queued_at_death
    report["writer_lost_at_death"] = writer_lost_at_death
    wal_tail_spans = [s for s in tracer.snapshot(topic="_lifecycle")
                     if s.get("stage") == "wal_tail"]
    if not wal_tail_spans:
        failures.append("no wal_tail lifecycle spans recorded")
    failover_dumps = glob.glob(os.path.join(trace_dir,
                                            "flight-*failover*.json"))
    if not failover_dumps:
        failures.append("failover left no flight-recorder dump")
    _check_flight_dumps(trace_dir, failures, require=1)
    tracer.dump("replication_end", extra={"acked": len(acked)}, force=True)
    shutil.rmtree(trace_dir, ignore_errors=True)
    if temp_dir:
        shutil.rmtree(state_dir, ignore_errors=True)

    report["failures"] = failures
    report["ok"] = not failures
    return report


def run_rollout(seconds: float = 6.0, seed: int | None = None,
                state_dir: str | None = None) -> dict:
    """Embedder-rollout scenario (ISSUE 11 acceptance): 1 writer + 2
    WAL-tailing read replicas behind the topic router serve live traffic
    while the writer rolls a NEW embedder out — staged background
    re-embed, dual-score parity window, WAL cutover fence, atomic swap,
    replica re-anchor — with deterministic kills at every rollout
    boundary:

    - **kill mid-re-embed** (torn stage append + full writer restart):
      the restarted writer's coordinator must RESUME from the durable
      watermark and the fleet stays on the old version, zero acked loss;
    - **kill mid-cutover** (crash after the WAL fence record, before the
      in-memory swap/checkpoint): the restarted writer's recovery must
      COMPLETE the cutover from the staged shard set — the fleet lands on
      the new version with every acked enrollment re-embedded, zero loss;
    - **kill a reader mid-re-anchor** (stopped while parked on the
      fence): its replacement resyncs straight onto the new-version
      checkpoint (the late-start shape) and matches bit-for-bit.

    Pass criteria (any miss -> ``ok: False``):

    1. **zero acked loss** — after the dust settles, writer, surviving
       reader and the replacement replica all hold exactly: every
       pre-cutover acked enrollment RE-EMBEDDED into the new space, plus
       every post-cutover acked enrollment, in order;
    2. **no mixed-version scores** — every published result carries the
       ``embedder_version`` its batch was scored against, and each
       replica's stamp stream is a clean old->new monotonic step (never
       interleaved, never any version outside {old, new});
    3. **serving never blanks** — fleet-wide, the gap between consecutive
       completed frames through the whole cutover window stays bounded,
       and every surviving replica keeps completing frames after its
       re-anchor (the router cordon drained it through the checkpoint
       reload instead of letting its queue rot);
    4. **fencing live** — an enrollment stamped with the OLD embedder
       version after the cutover is refused closed
       (``EmbedderVersionMismatchError``), and the offline verifier's
       version walk passes over the final state dir (rc 0).
    """
    import random as random_mod
    import threading

    import numpy as np

    from opencv_facerecognizer_tpu.parallel import ShardedGallery, make_mesh
    from opencv_facerecognizer_tpu.runtime import (
        EmbedderVersionMismatchError, FakeConnector, FaultInjector,
        ReadReplica, RecognizerService, ReplicaHandle, ResiliencePolicy,
        RolloutCoordinator, StateLifecycle, TopicRouter, WriterLease,
    )
    from opencv_facerecognizer_tpu.runtime.connector import encode_frame
    from opencv_facerecognizer_tpu.runtime.fakes import (
        InstantPipeline, TrafficRecorder,
    )
    from opencv_facerecognizer_tpu.runtime.faults import InjectedCrashError
    from opencv_facerecognizer_tpu.runtime.recognizer import RESULT_TOPIC
    from opencv_facerecognizer_tpu.runtime.replication import (
        service_health_probe,
    )
    from opencv_facerecognizer_tpu.utils.metrics import Metrics
    from opencv_facerecognizer_tpu.utils.tracing import Tracer

    if seed is None:
        seed = random_mod.SystemRandom().randrange(1 << 31)
    print(f"chaos_soak rollout seed={seed} seconds={seconds}",
          file=sys.stderr)
    rng = random_mod.Random(seed)
    frame_rng = np.random.default_rng(seed)

    temp_dir = state_dir is None
    if temp_dir:
        state_dir = tempfile.mkdtemp(prefix="ocvf_rollout_")
    trace_dir = tempfile.mkdtemp(prefix="ocvf_flight_")
    tracer = Tracer(ring_size=1 << 16, sample=1.0, seed=seed,
                    dump_dir=trace_dir, min_dump_interval_s=0.1)
    mesh = make_mesh()
    DIM = 8
    frame_shape = (32, 32)
    dispatch_s = 0.01
    offered_hz = 50.0
    topics = 12
    OLD_V, NEW_V = 1, 2

    # The two embedding spaces: old = the row itself; new = a fixed
    # orthogonal rotation of it (seeded — deterministic across the
    # scenario's restarts, as the stage-resume contract requires). The
    # parity embedders map a synthetic "crop" (an identity's code folded
    # to 2x4) into each space the same way.
    Q, _ = np.linalg.qr(frame_rng.normal(size=(DIM, DIM)))
    Q = Q.astype(np.float32)

    def reembed(rows):
        return np.asarray(rows, np.float32) @ Q

    def old_embed(crops):
        return np.asarray(crops, np.float32).reshape(len(crops), -1)[:, :DIM]

    def new_embed(crops):
        return old_embed(crops) @ Q

    report = {"scenario": "rollout", "seed": seed, "seconds": seconds,
              "state_dir": state_dir, "ok": False}
    failures: list = []

    #: acked enrollments: (version_at_ack, emb, labels, subject, label)
    acked: list = []

    def expected_rows(current_version):
        """Every acked row in the CURRENT version's space: pre-cutover
        rows re-embedded through Q, post-cutover rows as enrolled."""
        if not acked:
            return (np.zeros((0, DIM), np.float32),
                    np.zeros((0,), np.int32))
        embs, labs = [], []
        for ver, emb, labels, _su, _la in acked:
            norm = emb / np.maximum(
                np.linalg.norm(emb, axis=-1, keepdims=True), 1e-12)
            if current_version == NEW_V and ver == OLD_V:
                norm = norm @ Q
                norm = norm / np.maximum(
                    np.linalg.norm(norm, axis=-1, keepdims=True), 1e-12)
            embs.append(norm)
            labs.append(labels)
        return (np.concatenate(embs).astype(np.float32),
                np.concatenate(labs).astype(np.int32))

    def verify_gallery(gallery, current_version, where):
        want_emb, want_lab = expected_rows(current_version)
        got_emb, got_lab, _v, got_size = gallery.snapshot()
        if got_size != len(want_lab):
            failures.append(f"{where}: {got_size} rows, expected "
                            f"{len(want_lab)} acked (seed={seed})")
            return
        if got_size and not np.array_equal(got_lab[:got_size], want_lab):
            failures.append(f"{where}: labels differ")
        elif got_size and not np.allclose(got_emb[:got_size], want_emb,
                                          rtol=0, atol=1e-5):
            failures.append(f"{where}: embeddings differ")

    def make_service(gallery, metrics):
        pipe = InstantPipeline(frame_shape, dispatch_s=dispatch_s,
                               faces_per_frame=1)
        pipe.gallery = gallery
        return RecognizerService(
            pipe, FakeConnector(), batch_size=8, frame_shape=frame_shape,
            flush_timeout=0.02, inflight_depth=2, similarity_threshold=0.0,
            metrics=metrics,
            resilience=ResiliencePolicy(readback_deadline_s=2.0))

    # ---- fleet: writer + 2 readers + router ----
    injector = FaultInjector(seed=seed)
    writer_metrics = Metrics()
    lease = WriterLease(state_dir, metrics=writer_metrics).acquire()
    writer_gallery = ShardedGallery(capacity=1024, dim=DIM, mesh=mesh,
                                    embedder_version=OLD_V)
    writer_names: list = []
    state = StateLifecycle(state_dir, metrics=writer_metrics,
                           checkpoint_wal_rows=1 << 30,
                           checkpoint_every_s=1e9,
                           fault_injector=injector, tracer=tracer)
    state.bind(writer_gallery, writer_names)
    writer_box = {"svc": make_service(writer_gallery, writer_metrics)}

    def enroll_burst(n):
        """Synchronous acked enrollments in the CURRENT serving space
        (after the cutover the 'new model' produces new-space vectors
        directly). Deterministic — the kill schedule owns all timing."""
        for _ in range(n):
            rows = rng.randint(1, 2)
            emb = frame_rng.normal(size=(rows, DIM)).astype(np.float32)
            label = len(writer_names)
            subject = f"subject_{len(acked)}"
            labels = np.full(rows, label, np.int32)
            version = int(writer_gallery.embedder_version)
            writer_names.append(subject)
            state.append_enrollment(
                emb, labels, subject=subject, label=label,
                embedder_version=version,
                apply_fn=lambda e=emb, l=labels: writer_gallery.add(e, l))
            acked.append((version, emb, labels, subject, label))

    enroll_burst(4)

    readers = []
    for i in range(2):
        rmetrics = Metrics()
        rgallery = ShardedGallery(capacity=1024, dim=DIM, mesh=mesh)
        rnames: list = []
        rep = ReadReplica(state_dir, rgallery, rnames, metrics=rmetrics,
                          tracer=tracer, poll_interval_s=0.02,
                          name=f"reader-{i}")
        rep.poll(force=True)
        readers.append({"replica": rep, "gallery": rgallery,
                        "names": rnames, "metrics": rmetrics,
                        "svc": None})
        readers[i]["svc"] = RecognizerService(
            InstantPipeline(frame_shape, dispatch_s=dispatch_s,
                            faces_per_frame=1),
            FakeConnector(), batch_size=8, frame_shape=frame_shape,
            flush_timeout=0.02, inflight_depth=2,
            similarity_threshold=0.0, metrics=rmetrics,
            resilience=ResiliencePolicy(readback_deadline_s=2.0),
            replica=rep)
        readers[i]["svc"].pipeline.gallery = rgallery

    router_metrics = Metrics()
    handles = [ReplicaHandle(
        "writer", writer_box["svc"].connector,
        health_fn=lambda: service_health_probe(writer_box["svc"])(),
        writer=True)]
    for i, reader in enumerate(readers):
        handles.append(ReplicaHandle(
            f"reader-{i}", reader["svc"].connector,
            health_fn=service_health_probe(reader["svc"])))
    router = TopicRouter(handles, metrics=router_metrics, tracer=tracer,
                         health_interval_s=0.05)
    # Cordon choreography: each reader's re-anchor drains its topics to
    # peers through the checkpoint reload (the never-blanks contract).
    for i, reader in enumerate(readers):
        reader["replica"].on_resync = router.cordon_hook(f"reader-{i}")
    recorder = TrafficRecorder(router)
    frame_msg = encode_frame(np.zeros(frame_shape, np.float32))

    #: per-replica-name published (monotonic time, embedder_version)
    #: stamps — the no-mixed-scores evidence.
    stamps: dict = {"writer": [], "reader-0": [], "reader-1": []}
    stamp_lock = threading.Lock()

    def watch_stamps(name, connector):
        def on_result(_t, message, _name=name):
            ver = message.get("embedder_version")
            if ver is not None:
                with stamp_lock:
                    stamps[_name].append((time.monotonic(), int(ver)))

        connector.subscribe(RESULT_TOPIC, on_result)

    watch_stamps("writer", writer_box["svc"].connector)
    for i, reader in enumerate(readers):
        watch_stamps(f"reader-{i}", reader["svc"].connector)

    seq_box = {"seq": 0}

    def pump(duration_s):
        """Offer interactive frames across the topic set for a while —
        traffic flows through EVERY phase, kills included."""
        interval = 1.0 / offered_hz
        end = time.monotonic() + duration_s
        while time.monotonic() < end:
            seq = seq_box["seq"]
            seq_box["seq"] = seq + 1
            recorder.send_t[seq] = time.monotonic()
            router.publish(f"camera/{seq % topics}",
                           {**frame_msg, "priority": "interactive",
                            "meta": {"seq": seq}})
            time.sleep(interval)

    def restart_writer(where):
        """Full writer 'process' restart: stop, drop the lease, recover a
        fresh gallery/lifecycle from disk, re-acquire, rewire the
        router."""
        nonlocal lease, state, writer_gallery, writer_names
        writer_box["svc"].stop()
        lease.release()
        state.close()
        new_gallery = ShardedGallery(capacity=1024, dim=DIM, mesh=mesh)
        new_names: list = []
        lease = WriterLease(state_dir, metrics=writer_metrics).acquire()
        state = StateLifecycle(state_dir, metrics=writer_metrics,
                               checkpoint_wal_rows=1 << 30,
                               checkpoint_every_s=1e9,
                               fault_injector=injector, tracer=tracer)
        recovery = state.recover(new_gallery, new_names)
        writer_gallery = new_gallery
        writer_names = new_names
        new_svc = make_service(new_gallery, writer_metrics)
        new_svc.start(warmup=False)
        writer_box["svc"] = new_svc
        router.replace_connector("writer", new_svc.connector)
        watch_stamps("writer", new_svc.connector)
        verify_gallery(new_gallery,
                       int(recovery.get("embedder_version", OLD_V)),
                       f"writer recovery ({where})")
        return recovery

    phase_t = {}
    try:
        writer_box["svc"].start(warmup=False)
        for reader in readers:
            reader["svc"].start(warmup=False)
        router.start()

        # ---- phase A: steady state on the old embedder ----
        pump(max(0.5, seconds * 0.15))
        enroll_burst(3)

        # ---- phase B: staged re-embed, killed mid-chunk ----
        coordinator = RolloutCoordinator(
            state, writer_gallery, reembed, NEW_V,
            old_embed_fn=old_embed, new_embed_fn=new_embed,
            parity_min_samples=8, parity_threshold=0.95, chunk_rows=3,
            metrics=writer_metrics, tracer=tracer, fault_injector=injector)
        coordinator.run_stage(max_chunks=2)  # some durable progress first
        if coordinator.stage.watermark <= 0:
            failures.append("stage made no durable progress before the "
                            "scripted kill")
        injector.script("stage", "torn")
        killed_mid_stage = False
        try:
            coordinator.run_stage(max_chunks=2)
        except InjectedCrashError:
            killed_mid_stage = True
        if not killed_mid_stage:
            failures.append("scripted stage kill never fired")
        watermark_at_kill = coordinator.stage.watermark
        report["watermark_at_stage_kill"] = watermark_at_kill
        pump(max(0.3, seconds * 0.1))  # fleet serves on through the kill
        restart_writer("after stage kill")
        # The restarted writer resumes staging from the durable watermark.
        coordinator = RolloutCoordinator(
            state, writer_gallery, reembed, NEW_V,
            old_embed_fn=old_embed, new_embed_fn=new_embed,
            parity_min_samples=8, parity_threshold=0.95, chunk_rows=3,
            metrics=writer_metrics, tracer=tracer, fault_injector=injector)
        writer_box["svc"].rollout = coordinator  # live-parity publish hook
        if not coordinator.stage.resumed \
                or coordinator.stage.watermark < watermark_at_kill:
            failures.append(
                f"stage did not resume from the durable watermark "
                f"(resumed={coordinator.stage.resumed}, watermark "
                f"{coordinator.stage.watermark} < {watermark_at_kill})")
        enroll_burst(2)  # rows landing BEHIND the stage: the delta path
        coordinator.run_stage()
        if not coordinator.caught_up:
            failures.append("stage never caught up after resume")

        # ---- phase C: dual-score parity window over live traffic ----
        pump(max(0.3, seconds * 0.1))  # the publish hook samples crops
        # Direct identity queries: noisy copies of enrolled rows folded
        # into crop shape — the parity signal the gate decides on.
        crops = []
        for ver, emb, _labels, _su, _la in acked[:8]:
            row = emb[0] / max(np.linalg.norm(emb[0]), 1e-12)
            if ver != OLD_V:
                continue  # queries arrive in the OLD space pre-cutover
            crops.append(row.reshape(2, 4))
        coordinator.score_parity(crops)
        report["parity"] = coordinator.status()["parity"]
        if not coordinator.parity_ok():
            failures.append(f"parity gate never opened: "
                            f"{report['parity']}")

        # ---- phase D: cutover, killed after the WAL fence ----
        phase_t["cutover_start"] = time.monotonic()
        injector.script("cutover", "crash_after_record")
        try:
            coordinator.cutover()
            failures.append("scripted cutover kill never fired")
        except InjectedCrashError:
            pass
        pump(max(0.3, seconds * 0.1))  # readers park on the fence; serve on
        awaiting = [bool(r["replica"].stats()["awaiting_cutover"])
                    for r in readers]
        report["readers_awaiting_at_fence"] = awaiting
        if not any(awaiting):
            failures.append("no reader parked on the cutover fence while "
                            "the writer was down")
        # Kill reader-1 mid-re-anchor: parked on the fence, dies before
        # the new-version checkpoint ever lands.
        readers[1]["svc"].stop()
        recovery = restart_writer("after cutover kill")
        if not recovery.get("completed_cutover"):
            failures.append(f"recovery did not complete the fenced "
                            f"cutover: {recovery}")
        if int(recovery.get("embedder_version", 0)) != NEW_V:
            failures.append(f"writer recovered at v"
                            f"{recovery.get('embedder_version')}, not "
                            f"v{NEW_V}")
        # The post-cutover checkpoint (recover latched a forced one; take
        # it synchronously so the reader re-anchor window is bounded).
        if not state.checkpoint_now(wait=True):
            failures.append("post-cutover checkpoint failed")
        enroll_burst(3)  # the new model enrolls straight into v2

        # ---- phase E: surviving reader re-anchors through the cordon ----
        deadline = time.monotonic() + 15.0
        while (readers[0]["replica"].embedder_version != NEW_V
               and time.monotonic() < deadline):
            pump(0.1)
        phase_t["reanchor_end"] = time.monotonic()
        if readers[0]["replica"].embedder_version != NEW_V:
            failures.append("surviving reader never re-anchored onto the "
                            "new-version checkpoint")
        pump(max(0.3, seconds * 0.1))  # post-re-anchor serving
        # Catch-up: the reader applies the post-cutover v2 enrollments.
        target = state.wal_seq
        deadline = time.monotonic() + 10.0
        while (readers[0]["replica"].applied_seq < target
               and time.monotonic() < deadline):
            readers[0]["replica"].poll(force=True)
            time.sleep(0.02)

        # ---- phase F: live fence + replacement replica + verification --
        try:
            state.append_enrollment(
                np.zeros((1, DIM), np.float32), np.zeros(1, np.int32),
                embedder_version=OLD_V)
            failures.append("old-version enrollment was NOT refused after "
                            "the cutover (fence breach)")
        except EmbedderVersionMismatchError:
            report["stale_enroll_refused"] = True
        replacement_gallery = ShardedGallery(capacity=1024, dim=DIM,
                                             mesh=mesh)
        replacement = ReadReplica(state_dir, replacement_gallery, [],
                                  metrics=Metrics(), tracer=tracer,
                                  poll_interval_s=0.0, name="replacement")
        replacement.poll(force=True)
        for svc in [writer_box["svc"], readers[0]["svc"]]:
            svc.drain(timeout=15.0)
        verify_gallery(writer_gallery, NEW_V, "writer (post-rollout)")
        verify_gallery(readers[0]["gallery"], NEW_V, "surviving reader")
        verify_gallery(replacement_gallery, NEW_V, "replacement replica")
        if replacement.embedder_version != NEW_V:
            failures.append("late-start replacement did not anchor at the "
                            "new version")
    finally:
        router.stop()
        for svc in [writer_box["svc"]] + [r["svc"] for r in readers]:
            try:
                svc.stop()
            except Exception:  # noqa: BLE001 — teardown must finish
                import traceback

                traceback.print_exc()
        lease.release()
        state.close()

    # ---- verdicts ----
    with stamp_lock:
        stamp_view = {k: list(v) for k, v in stamps.items()}
    report["result_stamps"] = {
        k: {"total": len(v),
            "versions": sorted({ver for _t, ver in v})}
        for k, v in stamp_view.items()}
    for name, series in stamp_view.items():
        versions = [ver for _t, ver in series]
        if not versions:
            failures.append(f"{name}: published no version-stamped results")
            continue
        if any(v not in (OLD_V, NEW_V) for v in versions):
            failures.append(f"{name}: stamp outside {{v1, v2}}: "
                            f"{sorted(set(versions))}")
        if versions != sorted(versions):
            # One clean old->new step per replica — an interleaved stream
            # means a result was scored against one version while the
            # stamp (or the gallery) said another.
            failures.append(f"{name}: version stamps interleave "
                            f"(mixed-version serving): {versions}")
    # reader-1 died pre-cutover: it must never have stamped v2.
    if any(ver == NEW_V for _t, ver in stamp_view["reader-1"]):
        failures.append("the reader killed mid-re-anchor published a "
                        "new-version result")
    # Serving continuity through the cutover window: fleet-wide completed
    # frames never gap beyond a bound, and the survivors kept completing
    # AFTER their re-anchor.
    window = (phase_t.get("cutover_start"), phase_t.get("reanchor_end"))
    if None not in window:
        done_ts = sorted(t for t in recorder.done_t.values()
                         if window[0] - 0.5 <= t <= window[1] + 0.5)
        report["cutover_window_completions"] = len(done_ts)
        if len(done_ts) < 2:
            failures.append("serving blanked through the cutover window "
                            f"({len(done_ts)} completions)")
        else:
            max_gap = max(b - a for a, b in zip(done_ts, done_ts[1:]))
            report["cutover_window_max_gap_s"] = round(max_gap, 3)
            if max_gap > 2.0:
                failures.append(f"completed-frames gap {max_gap:.2f}s "
                                f"through the cutover (serving blanked)")
        for name in ("writer", "reader-0"):
            after = [1 for t, _v in stamp_view[name]
                     if t > window[1]]
            if not after:
                failures.append(f"{name}: no completions after its "
                                f"re-anchor (never drained back in)")
    if not router_metrics.counter("router_cutover_drains"):
        failures.append("router never cordoned a replica through its "
                        "re-anchor (the drain choreography is unwired)")

    # Offline verifier: the final state dir's version fences must parse
    # clean (checkpoint header + WAL version walk).
    import importlib.util as _ilu

    spec = _ilu.spec_from_file_location(
        "verify_checkpoint",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "verify_checkpoint.py"))
    verify_mod = _ilu.module_from_spec(spec)
    spec.loader.exec_module(verify_mod)
    vreport = verify_mod.verify_state_dir(state_dir)
    report["verify"] = {"ok": vreport["ok"],
                        "embedder_version": vreport.get("embedder_version"),
                        "violations": (vreport.get("wal") or {}).get(
                            "version_violations")}
    if not vreport["ok"]:
        failures.append(f"offline verifier failed on the final state dir: "
                        f"{report['verify']}")
    if vreport.get("embedder_version") != NEW_V:
        failures.append(f"final checkpoint serves v"
                        f"{vreport.get('embedder_version')}, not v{NEW_V}")

    cutover_spans = [s for s in tracer.snapshot(topic="_lifecycle")
                     if s.get("stage") in ("cutover", "rollout_phase")]
    if not cutover_spans:
        failures.append("no rollout lifecycle spans recorded")
    _check_flight_dumps(trace_dir, failures, require=0)
    tracer.dump("rollout_end", extra={"acked": len(acked)}, force=True)
    shutil.rmtree(trace_dir, ignore_errors=True)
    if temp_dir:
        shutil.rmtree(state_dir, ignore_errors=True)

    report["acked_enrollments"] = len(acked)
    report["offered"] = seq_box["seq"]
    report["failures"] = failures
    report["ok"] = not failures
    return report


def run_registry(seconds: float = 6.0, seed: int | None = None,
                 state_dir: str | None = None) -> dict:
    """Model-registry scenario (ISSUE 18 acceptance): 1 writer + 2
    WAL-tailing read replicas behind the topic router serve live traffic
    while the writer swaps the DETECTOR through the versioned model
    registry — live detection-parity window, ``registry_cutover`` WAL
    fence, atomic manifest install, replica re-anchor — with
    deterministic kills at every swap boundary:

    - **kill before the fence** (``crash_before_record``): nothing was
      fenced, the fleet stays on the old detector, no seq burned;
    - **kill mid-swap** (``crash_after_record`` — after the WAL fence,
      before the manifest install): the restarted writer's recovery must
      COMPLETE the swap from the fence + staged params (sha256 verifies),
      and the parked readers re-anchor onto the post-swap checkpoint;
    - **kill mid-swap with damaged params** (cascade role): recovery must
      CLEANLY ABANDON — ``registry_abort`` tombstone, the role stays at
      the old version, the candidate number is retired, never reused;
    - **parity-regressing candidate**: a detector that passes the
      pre-cutover gate but regresses on post-cutover traffic is
      auto-rolled-back inside the watch window at the next monotonic
      version, with a parseable flight-recorder dump carrying the full
      swap status.

    Pass criteria (any miss -> ``ok: False``):

    1. **zero acked loss, bit-equal** — writer, both surviving readers
       and a late-start replacement replica hold byte-identical
       galleries covering every acked enrollment (registry swaps never
       re-embed: rows are untouched by construction, so equality is
       EXACT, not approximate);
    2. **never mixed-version serving** — every published result carries
       the full registry stamp of the model set its batch was dispatched
       under; per replica the detector stamp stream is monotonic
       non-decreasing, every stamped version was fenced, and the
       ABANDONED cascade candidate version never appears in any
       published result (no result from an unfenced model version);
    3. **exact per-replica ledgers** — each replica's stamped-result
       ledger and applied-row count are reported exactly, and the
       offline verifier's multi-role registry walk passes over the final
       state dir (manifest checksum + per-role fence continuity, rc 0).
    """
    import random as random_mod
    import threading

    import numpy as np

    from opencv_facerecognizer_tpu.parallel import ShardedGallery, make_mesh
    from opencv_facerecognizer_tpu.runtime import (
        FakeConnector, FaultInjector, ModelRegistry, ReadReplica,
        RecognizerService, RegistrySwapCoordinator, ReplicaHandle,
        ResiliencePolicy, StateLifecycle, TopicRouter, WriterLease,
        registry_params_path,
    )
    from opencv_facerecognizer_tpu.runtime.connector import encode_frame
    from opencv_facerecognizer_tpu.runtime.fakes import (
        InstantPipeline, TrafficRecorder,
    )
    from opencv_facerecognizer_tpu.runtime.faults import InjectedCrashError
    from opencv_facerecognizer_tpu.runtime.recognizer import RESULT_TOPIC
    from opencv_facerecognizer_tpu.runtime.replication import (
        service_health_probe,
    )
    from opencv_facerecognizer_tpu.utils.metrics import Metrics
    from opencv_facerecognizer_tpu.utils.tracing import Tracer

    if seed is None:
        seed = random_mod.SystemRandom().randrange(1 << 31)
    print(f"chaos_soak registry seed={seed} seconds={seconds}",
          file=sys.stderr)
    rng = random_mod.Random(seed)
    frame_rng = np.random.default_rng(seed)

    temp_dir = state_dir is None
    if temp_dir:
        state_dir = tempfile.mkdtemp(prefix="ocvf_registry_")
    trace_dir = tempfile.mkdtemp(prefix="ocvf_flight_")
    tracer = Tracer(ring_size=1 << 16, sample=1.0, seed=seed,
                    dump_dir=trace_dir, min_dump_interval_s=0.1)
    mesh = make_mesh()
    DIM = 8
    frame_shape = (32, 32)
    dispatch_s = 0.01
    offered_hz = 50.0
    topics = 12

    # Synthetic detectors: yxyx verdict boxes over the 32x32 frames. The
    # serving detector is a version-keyed closure over ``serving_box`` —
    # install_fn IS the one attribute publish the real pipeline does
    # (params are jit arguments; same-architecture swap, zero recompiles).
    serving_box = {"detector": 1}

    def detect_v1(frame):
        del frame
        return [(8.0, 8.0, 24.0, 24.0)]

    def detect_v2(frame):
        del frame
        return [(9.0, 9.0, 25.0, 25.0)]  # IoU ~0.78 vs v1: agrees

    #: the regressing candidate: agrees while the pre-cutover parity
    #: window looks, then drifts on post-cutover traffic (the exact
    #: failure the watch window + auto-rollback exist for).
    behave = {"good": True}

    def detect_v3(frame):
        if behave["good"]:
            return [(8.0, 9.0, 24.0, 25.0)]
        return [(0.0, 0.0, 6.0, 6.0)]  # disjoint: verdict mismatch

    report = {"scenario": "registry", "seed": seed, "seconds": seconds,
              "state_dir": state_dir, "ok": False}
    failures: list = []

    #: acked enrollments: (emb, labels, subject, label, detector_version)
    acked: list = []

    def make_service(gallery, metrics, registry=None, replica=None):
        pipe = InstantPipeline(frame_shape, dispatch_s=dispatch_s,
                               faces_per_frame=1)
        pipe.gallery = gallery
        svc = RecognizerService(
            pipe, FakeConnector(), batch_size=8, frame_shape=frame_shape,
            flush_timeout=0.02, inflight_depth=2, similarity_threshold=0.0,
            metrics=metrics,
            resilience=ResiliencePolicy(readback_deadline_s=2.0),
            replica=replica)
        svc.registry = registry
        return svc

    # ---- fleet: writer (registry attached) + 2 readers + router ----
    injector = FaultInjector(seed=seed)
    writer_metrics = Metrics()
    lease = WriterLease(state_dir, metrics=writer_metrics).acquire()
    writer_gallery = ShardedGallery(capacity=1024, dim=DIM, mesh=mesh)
    writer_names: list = []
    state = StateLifecycle(state_dir, metrics=writer_metrics,
                           checkpoint_wal_rows=1 << 30,
                           checkpoint_every_s=1e9,
                           fault_injector=injector, tracer=tracer)
    state.attach_registry(ModelRegistry(state_dir, metrics=writer_metrics))
    state.bind(writer_gallery, writer_names)
    writer_box = {"svc": make_service(writer_gallery, writer_metrics,
                                      registry=state.registry)}

    def enroll_burst(n):
        """Synchronous acked enrollments, stamped with the CURRENT
        registry (rows are never re-embedded by a registry swap — the
        ledger is bit-exact)."""
        for _ in range(n):
            rows = rng.randint(1, 2)
            emb = frame_rng.normal(size=(rows, DIM)).astype(np.float32)
            label = len(writer_names)
            subject = f"subject_{len(acked)}"
            labels = np.full(rows, label, np.int32)
            writer_names.append(subject)
            state.append_enrollment(
                emb, labels, subject=subject, label=label,
                embedder_version=1,
                apply_fn=lambda e=emb, l=labels: writer_gallery.add(e, l))
            acked.append((emb, labels, subject, label,
                          state.registry.version("detector")))

    enroll_burst(4)

    readers = []
    for i in range(2):
        rmetrics = Metrics()
        rgallery = ShardedGallery(capacity=1024, dim=DIM, mesh=mesh)
        rnames: list = []
        rep = ReadReplica(state_dir, rgallery, rnames, metrics=rmetrics,
                          tracer=tracer, poll_interval_s=0.02,
                          name=f"reader-{i}")
        rep.registry = ModelRegistry(state_dir, metrics=rmetrics,
                                     readonly=True)
        rep.poll(force=True)
        svc = make_service(rgallery, rmetrics, registry=rep.registry,
                           replica=rep)
        rep.on_registry_change = svc.flush_model_caches
        readers.append({"replica": rep, "gallery": rgallery,
                        "names": rnames, "metrics": rmetrics, "svc": svc})

    router_metrics = Metrics()
    handles = [ReplicaHandle(
        "writer", writer_box["svc"].connector,
        health_fn=lambda: service_health_probe(writer_box["svc"])(),
        writer=True)]
    for i, reader in enumerate(readers):
        handles.append(ReplicaHandle(
            f"reader-{i}", reader["svc"].connector,
            health_fn=service_health_probe(reader["svc"])))
    router = TopicRouter(handles, metrics=router_metrics, tracer=tracer,
                         health_interval_s=0.05)
    for i, reader in enumerate(readers):
        reader["replica"].on_resync = router.cordon_hook(f"reader-{i}")
    recorder = TrafficRecorder(router)
    frame_msg = encode_frame(np.zeros(frame_shape, np.float32))

    #: per-replica published (monotonic time, detector_v, cascade_v)
    #: registry-stamp ledger — the never-mixed-version evidence.
    stamps: dict = {"writer": [], "reader-0": [], "reader-1": []}
    stamp_lock = threading.Lock()

    def watch_stamps(name, connector):
        def on_result(_t, message, _name=name):
            reg = message.get("registry")
            if isinstance(reg, dict):
                with stamp_lock:
                    stamps[_name].append(
                        (time.monotonic(), int(reg.get("detector", 0)),
                         int(reg.get("cascade", 0))))

        connector.subscribe(RESULT_TOPIC, on_result)

    watch_stamps("writer", writer_box["svc"].connector)
    for i, reader in enumerate(readers):
        watch_stamps(f"reader-{i}", reader["svc"].connector)

    seq_box = {"seq": 0}

    def pump(duration_s):
        interval = 1.0 / offered_hz
        end = time.monotonic() + duration_s
        while time.monotonic() < end:
            seq = seq_box["seq"]
            seq_box["seq"] = seq + 1
            recorder.send_t[seq] = time.monotonic()
            router.publish(f"camera/{seq % topics}",
                           {**frame_msg, "priority": "interactive",
                            "meta": {"seq": seq}})
            time.sleep(interval)

    def stage_params(role, version):
        """Stage a deterministic candidate params blob at the runbook
        path and return (path, bytes) — durable BEFORE any fence, as the
        swap protocol requires."""
        from opencv_facerecognizer_tpu.utils.serialization import (
            atomic_write_bytes,
        )
        path = registry_params_path(state_dir, role, version)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        blob = f"{role}-v{version}-params-{seed}".encode() * 64
        atomic_write_bytes(path, blob)
        return path

    def restart_writer(where):
        """Full writer 'process' restart: stop, drop the lease, recover
        from disk (recovery completes or abandons any fenced swap),
        re-acquire, rewire the router."""
        nonlocal lease, state, writer_gallery, writer_names
        writer_box["svc"].stop()
        lease.release()
        state.close()
        new_gallery = ShardedGallery(capacity=1024, dim=DIM, mesh=mesh)
        new_names: list = []
        lease = WriterLease(state_dir, metrics=writer_metrics).acquire()
        state = StateLifecycle(state_dir, metrics=writer_metrics,
                               checkpoint_wal_rows=1 << 30,
                               checkpoint_every_s=1e9,
                               fault_injector=injector, tracer=tracer)
        recovery = state.recover(new_gallery, new_names)
        if state.registry is None:
            failures.append(f"writer recovery ({where}) attached no "
                            f"registry despite the durable manifest")
            state.attach_registry(
                ModelRegistry(state_dir, metrics=writer_metrics))
        writer_gallery = new_gallery
        writer_names = new_names
        new_svc = make_service(new_gallery, writer_metrics,
                               registry=state.registry)
        new_svc.start(warmup=False)
        writer_box["svc"] = new_svc
        router.replace_connector("writer", new_svc.connector)
        watch_stamps("writer", new_svc.connector)
        return recovery

    def await_reader_registry(role, version, where, deadline_s=15.0):
        """Poll the readers through their re-anchor until both manifest
        views serve ``role`` at ``version``."""
        deadline = time.monotonic() + deadline_s
        while time.monotonic() < deadline:
            views = [r["replica"].stats()["registry"] for r in readers]
            if all(v is not None and v.get(role) == version for v in views):
                return
            pump(0.1)
            for r in readers:
                r["replica"].poll(force=True)
        failures.append(
            f"{where}: readers never re-anchored onto {role} v{version}: "
            f"{[r['replica'].stats()['registry'] for r in readers]}")

    parity_frames = [frame_rng.normal(size=frame_shape).astype(np.float32)
                     for _ in range(24)]
    phase_t = {}
    try:
        writer_box["svc"].start(warmup=False)
        for reader in readers:
            reader["svc"].start(warmup=False)
        router.start()

        # ---- phase A: steady state, everything at v1 ----
        pump(max(0.4, seconds * 0.1))

        # ---- phase B0: kill BEFORE the fence (nothing durable moves) --
        v2_path = stage_params("detector", 2)
        coordinator = RegistrySwapCoordinator(
            state, state.registry, "detector", 2,
            old_detect_fn=detect_v1, new_detect_fn=detect_v2,
            params_path=v2_path, parity_min_samples=8,
            install_fn=lambda: serving_box.__setitem__("detector", 2),
            metrics=writer_metrics, tracer=tracer)
        coordinator.score_parity(parity_frames[:12])
        if not coordinator.parity_ok():
            failures.append("detector v2 parity gate never opened: "
                            f"{coordinator.status()['parity']}")
        injector.script("cutover", "crash_before_record")
        try:
            coordinator.cutover()
            failures.append("scripted pre-fence kill never fired")
        except InjectedCrashError:
            pass
        if state.registry.version("detector") != 1:
            failures.append("pre-fence kill moved the manifest")
        seq_before = state.wal_seq

        # ---- phase B: kill mid-swap (fenced, manifest not installed) --
        phase_t["swap_start"] = time.monotonic()
        injector.script("cutover", "crash_after_record")
        try:
            coordinator.cutover()
            failures.append("scripted mid-swap kill never fired")
        except InjectedCrashError:
            pass
        if state.wal_seq <= seq_before:
            failures.append("mid-swap kill burned no fence seq")
        pump(max(0.3, seconds * 0.1))  # readers hit the fence; serve on
        for r in readers:
            r["replica"].poll(force=True)
        awaiting = [bool(r["replica"].stats()["awaiting_cutover"])
                    for r in readers]
        report["readers_parked_at_fence"] = awaiting
        if not any(awaiting):
            failures.append("no reader parked on the registry fence "
                            "while the writer was down")
        recovery = restart_writer("after mid-swap kill")
        completed = recovery.get("completed_registry_swaps") or []
        if not any(e["role"] == "detector" and e["to_version"] == 2
                   for e in completed):
            failures.append(f"recovery did not complete the fenced "
                            f"detector swap: {recovery}")
        if state.registry.version("detector") != 2:
            failures.append(f"writer recovered with detector v"
                            f"{state.registry.version('detector')}, not v2")
        if not state.checkpoint_now(wait=True):
            failures.append("post-swap checkpoint failed")
        enroll_burst(3)  # rows stamped under detector v2
        await_reader_registry("detector", 2, "after completed swap")

        # ---- phase C: kill mid-swap, candidate DAMAGED -> abandon ----
        c2_path = stage_params("cascade", 2)
        coordinator = RegistrySwapCoordinator(
            state, state.registry, "cascade", 2, params_path=c2_path,
            metrics=writer_metrics, tracer=tracer)
        injector.script("cutover", "crash_after_record")
        try:
            coordinator.cutover(force=True)  # cascade: no parity fns wired
            failures.append("scripted cascade-swap kill never fired")
        except InjectedCrashError:
            pass
        with open(c2_path, "ab") as fh:
            fh.write(b"bitrot")  # the staged candidate no longer verifies
        recovery = restart_writer("after damaged-candidate kill")
        abandoned = recovery.get("abandoned_registry_swaps") or []
        if not any(e["role"] == "cascade" and e["to_version"] == 2
                   for e in abandoned):
            failures.append(f"recovery did not cleanly abandon the "
                            f"damaged cascade swap: {recovery}")
        if state.registry.version("cascade") != 1:
            failures.append(f"abandoned swap moved cascade to v"
                            f"{state.registry.version('cascade')}")
        if not state.checkpoint_now(wait=True):
            failures.append("post-abandon checkpoint failed")
        enroll_burst(2)  # still stamped cascade v1
        await_reader_registry("cascade", 1, "after abandoned swap")
        try:
            state.registry.install("cascade", 2)
            failures.append("retired cascade v2 was re-installable "
                            "(fence ambiguity)")
        except ValueError:
            report["retired_version_refused"] = True

        # ---- phase D: parity-regressing candidate -> auto-rollback ----
        v3_path = stage_params("detector", 3)
        coordinator = RegistrySwapCoordinator(
            state, state.registry, "detector", 3,
            old_detect_fn=detect_v2, new_detect_fn=detect_v3,
            params_path=v3_path, parity_min_samples=8,
            watch_min_samples=8,
            install_fn=lambda: serving_box.__setitem__("detector", 3),
            rollback_install_fn=lambda: serving_box.__setitem__(
                "detector", 2),
            flush_fn=writer_box["svc"].flush_model_caches,
            metrics=writer_metrics, tracer=tracer)
        writer_box["svc"].registry_swap = coordinator
        coordinator.score_parity(parity_frames[:12])
        if not coordinator.parity_ok():
            failures.append("regressing candidate failed the PRE-cutover "
                            "gate (the watch window is what must catch it)")
        coordinator.cutover()
        pump(max(0.3, seconds * 0.1))  # fleet serves v3 inside the watch
        behave["good"] = False  # the candidate drifts on live traffic
        coordinator.score_parity(parity_frames[12:])
        if coordinator.phase != "rolled_back":
            failures.append(f"watch regression did not auto-roll-back "
                            f"(phase {coordinator.phase})")
        if state.registry.version("detector") != 4:
            failures.append(f"rollback landed detector v"
                            f"{state.registry.version('detector')}, "
                            f"not the next monotonic v4")
        if serving_box["detector"] != 2:
            failures.append("rollback did not restore the previous "
                            "params in memory")
        writer_box["svc"].registry_swap = None
        report["auto_rollback"] = coordinator.status()
        enroll_burst(2)  # stamped detector v4
        await_reader_registry("detector", 4, "after auto-rollback")
        phase_t["swap_end"] = time.monotonic()
        pump(max(0.3, seconds * 0.1))

        # ---- phase E: drain + replacement replica + verification ----
        target = state.wal_seq
        deadline = time.monotonic() + 10.0
        while (any(r["replica"].applied_seq < target for r in readers)
               and time.monotonic() < deadline):
            for r in readers:
                r["replica"].poll(force=True)
            time.sleep(0.02)
        replacement_gallery = ShardedGallery(capacity=1024, dim=DIM,
                                             mesh=mesh)
        replacement_names: list = []
        replacement = ReadReplica(state_dir, replacement_gallery,
                                  replacement_names, metrics=Metrics(),
                                  tracer=tracer, poll_interval_s=0.0,
                                  name="replacement")
        replacement.registry = ModelRegistry(state_dir, readonly=True)
        replacement.poll(force=True)
        for svc in [writer_box["svc"]] + [r["svc"] for r in readers]:
            svc.drain(timeout=15.0)

        # Zero acked loss, bit-equal: registry swaps never touch rows,
        # so every gallery must hold byte-identical state.
        want_rows = sum(len(labels) for _e, labels, _s, _l, _d in acked)
        w_emb, w_lab, _v, w_size = writer_gallery.snapshot()
        if w_size != want_rows:
            failures.append(f"writer holds {w_size} rows, "
                            f"{want_rows} acked")
        ledgers = {}
        for name, gal, names_list in (
                [("writer", writer_gallery, writer_names)]
                + [(f"reader-{i}", r["gallery"], r["names"])
                   for i, r in enumerate(readers)]
                + [("replacement", replacement_gallery,
                    replacement_names)]):
            emb, lab, _v, size = gal.snapshot()
            ledgers[name] = {"rows": int(size),
                             "subjects": len(names_list)}
            if size != w_size:
                failures.append(f"{name}: {size} rows, writer has "
                                f"{w_size} (acked loss)")
                continue
            if not np.array_equal(emb[:size], w_emb[:w_size]) \
                    or not np.array_equal(lab[:size], w_lab[:w_size]):
                failures.append(f"{name}: gallery differs from the "
                                f"writer's bit-for-bit")
            if list(names_list) != list(writer_names):
                failures.append(f"{name}: subject ledger differs")
        report["replica_ledgers"] = ledgers
        final_stamp = replacement.stats()["registry"]
        if final_stamp is None or final_stamp.get("detector") != 4 \
                or final_stamp.get("cascade") != 1:
            failures.append(f"late-start replacement anchored on "
                            f"{final_stamp}, expected detector v4 / "
                            f"cascade v1")
    finally:
        router.stop()
        for svc in [writer_box["svc"]] + [r["svc"] for r in readers]:
            try:
                svc.stop()
            except Exception:  # noqa: BLE001 — teardown must finish
                import traceback

                traceback.print_exc()
        lease.release()
        state.close()

    # ---- verdicts ----
    with stamp_lock:
        stamp_view = {k: list(v) for k, v in stamps.items()}
    #: detector versions that were ever FENCED into the manifest; the
    #: abandoned cascade v2 is deliberately absent from the cascade set.
    fenced_detector = {1, 2, 3, 4}
    report["result_stamps"] = {
        k: {"total": len(v),
            "detector_versions": sorted({d for _t, d, _c in v}),
            "cascade_versions": sorted({c for _t, _d, c in v})}
        for k, v in stamp_view.items()}
    for name, series in stamp_view.items():
        if not series:
            failures.append(f"{name}: published no registry-stamped "
                            f"results")
            continue
        detectors = [d for _t, d, _c in series]
        if any(d not in fenced_detector for d in detectors):
            failures.append(f"{name}: detector stamp outside the fenced "
                            f"set: {sorted(set(detectors))}")
        if detectors != sorted(detectors):
            failures.append(f"{name}: detector stamps interleave "
                            f"(mixed-version serving): {detectors}")
        if any(c != 1 for _t, _d, c in series):
            failures.append(f"{name}: a result was published under the "
                            f"ABANDONED cascade candidate (unfenced "
                            f"model version)")
    # Serving continuity across all three swap windows.
    window = (phase_t.get("swap_start"), phase_t.get("swap_end"))
    if None not in window:
        done_ts = sorted(t for t in recorder.done_t.values()
                         if window[0] - 0.5 <= t <= window[1] + 0.5)
        report["swap_window_completions"] = len(done_ts)
        if len(done_ts) < 2:
            failures.append("serving blanked through the swap window "
                            f"({len(done_ts)} completions)")
        else:
            max_gap = max(b - a for a, b in zip(done_ts, done_ts[1:]))
            report["swap_window_max_gap_s"] = round(max_gap, 3)
            if max_gap > 2.0:
                failures.append(f"completed-frames gap {max_gap:.2f}s "
                                f"through the swaps (serving blanked)")

    # Offline verifier: manifest checksum + the multi-role registry walk
    # over the final WAL must pass (fence continuity per role).
    import importlib.util as _ilu

    spec = _ilu.spec_from_file_location(
        "verify_checkpoint",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "verify_checkpoint.py"))
    verify_mod = _ilu.module_from_spec(spec)
    spec.loader.exec_module(verify_mod)
    vreport = verify_mod.verify_state_dir(state_dir)
    report["verify"] = {"ok": vreport["ok"],
                        "registry": vreport.get("registry"),
                        "violations": (vreport.get("wal") or {}).get(
                            "version_violations")}
    if not vreport["ok"]:
        failures.append(f"offline verifier failed on the final state "
                        f"dir: {report['verify']}")
    roles = (vreport.get("registry") or {}).get("roles") or {}
    if roles.get("detector") != 4 or roles.get("cascade") != 1:
        failures.append(f"verifier read manifest {roles}, expected "
                        f"detector v4 / cascade v1")

    # The auto-rollback's forensic artifact: a parseable flight dump
    # whose extra carries the full swap status.
    dumps = _check_flight_dumps(trace_dir, failures, require=1)
    rollback_dumps = [p for p in dumps if "registry_auto_rollback" in p]
    if not rollback_dumps:
        failures.append("auto-rollback left no flight dump")
    else:
        with open(rollback_dumps[-1]) as fh:
            dump = json.load(fh)
        swap_status = (dump.get("extra") or {}).get("registry_swap")
        if not isinstance(swap_status, dict) \
                or swap_status.get("role") != "detector" \
                or swap_status.get("to_version") != 3:
            failures.append(f"auto-rollback flight dump carries no "
                            f"parseable swap status: {swap_status}")
        else:
            report["rollback_dump"] = {
                "path": os.path.basename(rollback_dumps[-1]),
                "role": swap_status["role"],
                "to_version": swap_status["to_version"],
                "parity": swap_status.get("parity")}
    tracer.dump("registry_end", extra={"acked": len(acked)}, force=True)
    shutil.rmtree(trace_dir, ignore_errors=True)
    if temp_dir:
        shutil.rmtree(state_dir, ignore_errors=True)

    report["acked_enrollments"] = len(acked)
    report["offered"] = seq_box["seq"]
    report["failures"] = failures
    report["ok"] = not failures
    return report


def run_disk(seconds: float = 6.0, seed: int | None = None,
             state_dir: str | None = None) -> dict:
    """Storage-fault scenario (ISSUE 15 acceptance): the disk STAYS broken
    — ENOSPC mid-enrollment, EIO mid-checkpoint, slow fsync under load,
    disk-watermark pressure — and the writer must degrade, not die:

    - sustained WAL ENOSPC flips ``durability_degraded``: every
      enrollment is refused CLOSED (explicit status, zero acked loss),
      serving traffic keeps completing, non-critical sinks (dead-letter
      journal, span JSONL, flight dumps) shed with exact per-sink
      counters;
    - EIO on a checkpoint save counts ``checkpoint_failures`` and keeps
      the previous checkpoint last-known-good;
    - slow fsync slows acks but never lies (enrollments still durable);
    - the watermark ladder (deterministic fake statvfs): warn fires one
      preemptive WAL compaction + retention shrink, critical pre-empts
      the degraded flip BEFORE ENOSPC and 503s ``/health``; recovery
      restores retention, the probe re-arms, and a final restart
      recovers EXACTLY the acknowledged history bit-equal with offline
      verification rc 0.
    """
    import random as random_mod
    import types
    import urllib.error
    import urllib.request

    import numpy as np

    from opencv_facerecognizer_tpu.parallel import ShardedGallery, make_mesh
    from opencv_facerecognizer_tpu.runtime import (
        DurabilityDegradedError, DurabilityMonitor, ExpoServer,
        FakeConnector, FaultInjector, RecognizerService, SLOMonitor,
        StateLifecycle, disk_free_objective, graceful_shutdown,
    )
    from opencv_facerecognizer_tpu.runtime.connector import encode_frame
    from opencv_facerecognizer_tpu.runtime.fakes import InstantPipeline
    from opencv_facerecognizer_tpu.runtime.journal import DeadLetterJournal
    from opencv_facerecognizer_tpu.runtime.recognizer import (
        CONTROL_TOPIC, FRAME_TOPIC, RESULT_TOPIC, STATUS_TOPIC,
    )
    from opencv_facerecognizer_tpu.utils.metrics import Metrics
    from opencv_facerecognizer_tpu.utils.tracing import (
        Tracer, make_span_journal,
    )

    if seed is None:
        seed = random_mod.SystemRandom().randrange(1 << 31)
    print(f"chaos_soak disk seed={seed} seconds={seconds}", file=sys.stderr)
    frame_rng = np.random.default_rng(seed)

    temp_dir = state_dir is None
    if temp_dir:
        state_dir = tempfile.mkdtemp(prefix="ocvf_disk_")
    trace_dir = tempfile.mkdtemp(prefix="ocvf_flight_")
    mesh = make_mesh()
    DIM = 8
    frame_shape = (16, 16)
    #: deterministic pump size per phase, derived from the budget (not the
    #: wall clock) so a replay with the printed seed is exact.
    burst = max(12, min(48, int(seconds * 4)))
    watermark = 64 << 20

    report = {"scenario": "disk", "seed": seed, "seconds": seconds,
              "state_dir": state_dir, "ok": False}
    failures: list = []
    acked: list = []  # (seq, emb, labels, subject, label) — fsync-acked only

    metrics = Metrics(window_s=60.0, window_slices=20)
    injector = FaultInjector(seed=seed, slow_fsync_s=0.02)
    span_journal = make_span_journal(os.path.join(state_dir, "spans.jsonl"),
                                    metrics=metrics, fault_injector=injector)
    tracer = Tracer(ring_size=1 << 14, sample=1.0, seed=seed,
                    dump_dir=trace_dir, span_sink=span_journal,
                    metrics=metrics, fault_injector=injector)
    journal = DeadLetterJournal(os.path.join(state_dir, "dead_letter.jsonl"),
                                metrics=metrics, fault_injector=injector)
    gallery = ShardedGallery(capacity=64, dim=DIM, mesh=mesh)
    names: list = []
    state = StateLifecycle(state_dir, metrics=metrics,
                           checkpoint_wal_rows=1 << 30,
                           checkpoint_every_s=1e9,
                           fault_injector=injector, tracer=tracer)
    state.recover(gallery, names)

    # Deterministic disk: the watermark ladder runs on a scripted statvfs
    # (the real volume's free space must not decide a chaos verdict).
    fake_disk = {"free": float(watermark * 10)}

    def statvfs_fn(_path):
        return types.SimpleNamespace(f_bavail=int(fake_disk["free"]),
                                     f_frsize=1)

    monitor = DurabilityMonitor(state, metrics=metrics, tracer=tracer,
                                degraded_after=2, probe_interval_s=0.05,
                                low_watermark_bytes=watermark,
                                fault_injector=injector,
                                statvfs_fn=statvfs_fn)
    monitor.attach_sinks(journal=journal, span_sink=span_journal,
                         tracer=tracer)
    slo = SLOMonitor(metrics,
                     [disk_free_objective(monitor.free_bytes, watermark,
                                          short_s=0.2, long_s=0.4)],
                     tracer=tracer, interval_s=0.05)

    pipe = InstantPipeline(frame_shape, dispatch_s=0.002)
    pipe.gallery = gallery
    connector = FakeConnector()
    service = RecognizerService(
        pipe, connector, batch_size=4, frame_shape=frame_shape,
        flush_timeout=0.02, state_store=state, dead_letter_journal=journal,
        tracer=tracer, slo_monitor=slo, metrics=metrics)
    service.subject_names = names
    service.start(warmup=False)
    expo = ExpoServer(service, tracer=tracer, metrics=metrics, slo=slo,
                      port=0)
    expo.start()

    frame = np.zeros(frame_shape, np.float32)

    def pump(n: int, tag: str) -> None:
        before = len(connector.messages(RESULT_TOPIC))
        for i in range(n):
            connector.inject(FRAME_TOPIC, {**encode_frame(frame),
                                           "meta": {"seq": f"{tag}-{i}"}})
        deadline = time.monotonic() + 30
        while (len(connector.messages(RESULT_TOPIC)) < before + n
               and time.monotonic() < deadline):
            time.sleep(0.01)
        got = len(connector.messages(RESULT_TOPIC)) - before
        if got != n:
            failures.append(f"{tag}: serving stalled — {got}/{n} frames "
                            f"published")

    def enroll(tag: str):
        """One write-ahead enrollment; returns the refusal exception or
        None (acked — appended to the acknowledged history)."""
        emb = frame_rng.normal(size=(2, DIM)).astype(np.float32)
        label = len(names)
        subject = f"{tag}_{len(acked)}"
        labels = np.full(2, label, np.int32)
        try:
            seq = state.append_enrollment(
                emb, labels, subject=subject, label=label,
                apply_fn=lambda e=emb, l=labels: gallery.add(e, l))
        except (DurabilityDegradedError, OSError) as exc:
            return exc
        names.append(subject)
        acked.append((seq, emb, labels, subject, label))
        return None

    def statuses(kind: str) -> list:
        return [s for s in connector.messages(STATUS_TOPIC)
                if s.get("status") == kind]

    def health_code() -> int:
        try:
            with urllib.request.urlopen(
                    f"http://{expo.host}:{expo.port}/health",
                    timeout=2.0) as resp:
                return resp.status
        except urllib.error.HTTPError as exc:
            return exc.code
        except OSError:
            return -1

    try:
        # ---- phase A: clean baseline ----
        pump(burst, "baseline")
        for _ in range(3):
            if enroll("baseline") is not None:
                failures.append("baseline enrollment refused on a healthy "
                                "disk")
        if not state.checkpoint_now(wait=True):
            failures.append("baseline checkpoint failed")

        # ---- phase B: full disk mid-enrollment (sustained ENOSPC) ----
        injector.rates["storage"] = {"enospc": 1.0}
        refused_os = refused_closed = 0
        for _ in range(6):
            exc = enroll("fulldisk")
            if exc is None:
                failures.append("enrollment ACKED against a full disk — "
                                "the ack lied")
            elif isinstance(exc, DurabilityDegradedError):
                refused_closed += 1
            else:
                refused_os += 1
        report["enospc_refusals"] = {"oserror": refused_os,
                                     "closed": refused_closed}
        if refused_os != monitor.degraded_after:
            failures.append(
                f"expected exactly {monitor.degraded_after} OSError "
                f"refusals before the flip, got {refused_os}")
        if refused_closed != 6 - monitor.degraded_after:
            failures.append(f"expected {6 - monitor.degraded_after} "
                            f"refused-closed, got {refused_closed}")
        if int(metrics.counter("wal_append_errors")) != refused_os:
            failures.append(
                f"wal_append_errors {metrics.counter('wal_append_errors')} "
                f"!= {refused_os} failed appends (exact accounting)")
        if not monitor.degraded:
            failures.append("sustained ENOSPC never flipped "
                            "durability_degraded")
        if not statuses("durability_degraded"):
            failures.append("no durability_degraded announcement")
        # Serving continues straight through the storage outage.
        pump(burst, "during_enospc")
        # The enroll COMMAND is refused closed at the front door.
        connector.inject(CONTROL_TOPIC, {"cmd": "enroll",
                                         "subject": "must_refuse",
                                         "count": 1})
        time.sleep(0.2)
        if not any(s.get("reason") == "durability_degraded"
                   for s in statuses("rejected")):
            failures.append("enroll command not refused with an explicit "
                            "durability_degraded status")
        # Non-critical sinks shed with exact per-sink accounting.
        if tracer.dump("degraded_probe") is not None:
            failures.append("flight dump landed while degraded (must shed)")
        journal.append("disk_chaos", [])
        for counter in ("trace_dumps_shed", "journal_shed",
                        "trace_spans_shed"):
            if metrics.counter(counter) < 1:  # ocvf-lint: disable=metrics-registry -- iterating three literal names from the registry (TRACE_DUMPS_SHED/JOURNAL_SHED/TRACE_SPANS_SHED), all registered
                failures.append(f"{counter} never counted while degraded")
        if int(metrics.counter("enrollments_refused_degraded")) < refused_closed + 1:
            failures.append("enrollments_refused_degraded undercounts the "
                            "closed refusals")

        # ---- phase B': space returns — the probe re-arms ----
        injector.rates["storage"] = {}
        deadline = time.monotonic() + 10
        while monitor.degraded and time.monotonic() < deadline:
            time.sleep(0.02)
        if monitor.degraded:
            failures.append("recovery probe never re-armed durability "
                            "after the fault cleared")
        if not statuses("durability_restored"):
            failures.append("no durability_restored announcement")
        if enroll("rearmed") is not None:
            failures.append("enrollment refused after re-arm")

        # ---- phase C: EIO mid-checkpoint ----
        # The span sink is the only background storage writer; detach it
        # for the scripted window so the one queued EIO deterministically
        # lands on the checkpoint save.
        saved_sink, tracer.span_sink = tracer.span_sink, None
        before_fail = metrics.counter("checkpoint_failures")
        injector.script("storage", "eio")
        if state.checkpoint_now(wait=True):
            failures.append("checkpoint save succeeded under injected EIO")
        tracer.span_sink = saved_sink
        if metrics.counter("checkpoint_failures") != before_fail + 1:
            failures.append("EIO checkpoint not counted checkpoint_failures")
        if state.store.load_latest() is None:
            failures.append("previous checkpoint lost after the EIO save")

        # ---- phase D: slow fsync under load ----
        injector.rates["storage"] = {"slow_fsync": 1.0}
        pump(burst, "slow_fsync")
        if enroll("slowfsync") is not None:
            failures.append("enrollment refused under slow_fsync (slow "
                            "durable is still durable)")
        if monitor.degraded:
            failures.append("slow fsync flipped durability (latency is "
                            "not loss)")
        injector.rates["storage"] = {}

        # ---- phase E: disk-pressure watermark ladder (scripted statvfs) --
        # Ticks are claim-serialized against the monitor's background
        # thread (a manual forced tick may lose the claim and skip), so
        # every transition is awaited, never asserted off one tick —
        # while the exactly-once counters stay exact BECAUSE of that
        # serialization.
        from opencv_facerecognizer_tpu.runtime.resilience import (
            DISK_CRITICAL, DISK_OK, DISK_WARN,
        )

        def await_disk(predicate, what: str) -> None:
            deadline = time.monotonic() + 10
            while not predicate() and time.monotonic() < deadline:
                monitor.tick(force=True)
                time.sleep(0.01)
            if not predicate():
                failures.append(f"disk watermark ladder never reached "
                                f"{what}")

        await_disk(lambda: monitor.disk_state == DISK_OK, "baseline ok")
        ckpts_before = metrics.counter("checkpoints_written")
        fake_disk["free"] = watermark * 0.5  # below low watermark: warn
        await_disk(lambda: monitor.disk_state == DISK_WARN, "warn")
        if metrics.counter("disk_pressure_retention_shrinks") != 1:
            failures.append("warn watermark did not shrink retention "
                            "exactly once")
        if metrics.counter("disk_pressure_compactions") != 1:
            failures.append("warn watermark did not force one WAL "
                            "compaction checkpoint")
        if state.store.keep != 1:
            failures.append("checkpoint retention not shrunk under disk "
                            "pressure")
        deadline = time.monotonic() + 10
        while (metrics.counter("checkpoints_written") <= ckpts_before
               and time.monotonic() < deadline):
            time.sleep(0.02)  # the forced compaction checkpoint lands
        if metrics.counter("checkpoints_written") <= ckpts_before:
            failures.append("preemptive compaction checkpoint never landed")
        fake_disk["free"] = watermark / 12.0  # below watermark/6: critical
        await_disk(lambda: (monitor.disk_state == DISK_CRITICAL
                            and monitor.degraded
                            and monitor.degraded_reason == "disk_critical"),
                   "critical degraded flip")
        if not isinstance(enroll("critical"), DurabilityDegradedError):
            failures.append("enrollment not refused closed at the critical "
                            "watermark")
        slo.evaluate()
        critical_code = health_code()
        if critical_code != 503:
            failures.append(f"/health did not 503 at critical disk "
                            f"pressure (got {critical_code})")
        fake_disk["free"] = float(watermark * 10)  # space returns
        deadline = time.monotonic() + 10
        while monitor.degraded and time.monotonic() < deadline:
            time.sleep(0.02)
        if monitor.degraded:
            failures.append("durability never re-armed after disk pressure "
                            "cleared")
        if state.store.keep == 1:
            failures.append("retention not restored after pressure cleared")
        deadline = time.monotonic() + 10
        while health_code() != 200 and time.monotonic() < deadline:
            slo.evaluate()
            time.sleep(0.05)
        if health_code() != 200:
            failures.append("/health never recovered after the pressure "
                            "cleared")
        if enroll("final") is not None:
            failures.append("enrollment refused after full recovery")

        # ---- settle + verify: zero acked loss, exact ledger ----
        shutdown = graceful_shutdown(service, state=state, drain_timeout=30.0)
        report["shutdown"] = {"drained": shutdown["drained"],
                              "ledger": shutdown["ledger"]}
        if not shutdown["drained"]:
            failures.append("graceful drain timed out")
        ledger = shutdown["ledger"]
        if abs(ledger["in_system"]) > 1e-6:
            failures.append(f"ledger unsettled at shutdown: {ledger}")
        if ledger["drops_by_reason"]:
            failures.append(f"clean traffic dropped frames: "
                            f"{ledger['drops_by_reason']}")
        g2 = ShardedGallery(capacity=64, dim=DIM, mesh=mesh)
        names2: list = []
        StateLifecycle(state_dir, metrics=Metrics()).recover(g2, names2)
        want_emb = (np.concatenate([e for _s, e, _l, _su, _la in acked])
                    if acked else np.zeros((0, DIM), np.float32))
        want_emb = want_emb / np.maximum(
            np.linalg.norm(want_emb, axis=-1, keepdims=True), 1e-12)
        want_lab = (np.concatenate([l for _s, _e, l, _su, _la in acked])
                    if acked else np.zeros((0,), np.int32))
        got_emb, got_lab, _val, got_size = g2.snapshot()
        if got_size != len(want_lab):
            failures.append(f"recovered {got_size} rows, expected "
                            f"{len(want_lab)} acked rows (zero-loss breach)")
        elif got_size and (
                not np.array_equal(got_lab[:got_size], want_lab)
                or not np.allclose(got_emb[:got_size],
                                   want_emb.astype(np.float32),
                                   rtol=0, atol=1e-6)):
            failures.append("recovered rows differ from the acknowledged "
                            "history (bit-exactness breach)")
        for i, (_seq, _e, _l, subject, label) in enumerate(acked):
            if label >= len(names2) or names2[label] != subject:
                failures.append(f"subject name {i} lost: "
                                f"{names2[label] if label < len(names2) else None!r}"
                                f" != {subject!r}")
                break
        import importlib.util as _ilu

        spec = _ilu.spec_from_file_location(
            "verify_checkpoint",
            os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "verify_checkpoint.py"))
        verify_mod = _ilu.module_from_spec(spec)
        spec.loader.exec_module(verify_mod)
        vreport = verify_mod.verify_state_dir(state_dir)
        report["verify"] = {"ok": vreport["ok"],
                            "checkpoints": len(vreport["checkpoints"])}
        if not vreport["ok"]:
            failures.append(f"offline verification failed: "
                            f"{vreport.get('corrupt')}")
        report["acked_enrollments"] = len(acked)
        report["injected"] = injector.summary()
        report["durability"] = monitor.status()
        report["sink_accounting"] = {
            k: int(metrics.counter(k))  # ocvf-lint: disable=metrics-registry -- report comprehension over literal registered names (the per-sink accounting the scenario asserts on)
            for k in ("journal_shed", "trace_spans_shed", "trace_dumps_shed",
                      "trace_span_errors", "journal_errors",
                      "wal_append_errors", "checkpoint_failures",
                      "enrollments_refused_degraded", "durability_rearms",
                      "durability_degraded_transitions")}
        _finish_observability(
            tracer, trace_dir, "disk_done", ledger,
            quiesced=shutdown["drained"] and abs(ledger["in_system"]) < 1e-6,
            failures=failures, report=report)
    finally:
        try:
            expo.stop()
        except Exception:  # ocvf-lint: disable=swallowed-exception -- teardown-best-effort by design: a failed expo stop on the cleanup path must not mask the scenario's real verdict
            pass
        span_journal.close()
        if temp_dir:
            shutil.rmtree(state_dir, ignore_errors=True)
        shutil.rmtree(trace_dir, ignore_errors=True)

    report["failures"] = failures
    report["ok"] = not failures
    return report


def run_partition(seconds: float = 8.0, seed: int | None = None,
                  state_dir: str | None = None) -> dict:
    """Partition scenario (ISSUE 16 acceptance): a 3-replica serving
    fleet behind the topic router with link supervision, hedged
    interactive dispatch and frame-id dedup armed, pounded through a
    transport fault boundary — then the network, not any process, is
    what fails.

    Phases: (B) hard partition of the busiest replica (both directions)
    → pong deadline fails the link, its topics reroute, the blackout's
    interactive frames are rescued by hedging; heal → link recovers.
    (C) flapping link (partition toggled faster than traffic can adapt)
    — the fleet must simply survive it and converge link-up. (D)
    duplicate storm (rate-drawn ``transport: duplicate`` on every
    crossing) — intake dedup + fan-in dedup must keep delivery
    exactly-once. (E) half-open writer: a ``StateLifecycle`` whose state
    dir (home of ``writer.lease``) stops answering reads flips
    durability-degraded (reason ``lease_unreachable``) instead of
    acking enrollments, and re-arms when the volume heals.

    Pass criteria (any miss -> ``ok: False``):

    1. **failover is bounded** — link-down detection within
       ``link_deadline + 4 health cycles`` of the partition (+0.5 s
       scheduler floor), and survivor interactive p99 after detection
       stays within 2x the unloaded baseline (+100 ms floor);
    2. **hedging rescues the blackout** — at least one hedge fired and
       won during the detection window;
    3. **exactly-once delivery** — a raw result-delivery counter above
       the router's fan-in sees EVERY completed seq exactly once (zero
       duplicate publishes), while the dedup counters prove duplicates
       actually arrived and were absorbed;
    4. **ledgers settle exactly** — every replica ends
       ``in_system == 0`` with ``admitted == completed +
       completed_empty + Σ drops``;
    5. **split-brain fails closed** — the half-open writer refuses
       enrollment while degraded and recovers on heal;
    6. **observability** — the link failure leaves a parseable
       ``failover`` flight dump; link state is visible in the registry.
    """
    import random as random_mod
    import threading

    import numpy as np

    from opencv_facerecognizer_tpu.runtime import (
        FaultInjector, StateLifecycle,
    )
    from opencv_facerecognizer_tpu.runtime.connector import encode_frame
    from opencv_facerecognizer_tpu.runtime.fakes import (
        TrafficRecorder, build_replica_fleet,
    )
    from opencv_facerecognizer_tpu.runtime.recognizer import RESULT_TOPIC
    from opencv_facerecognizer_tpu.runtime.resilience import (
        DurabilityDegradedError, DurabilityMonitor,
    )
    from opencv_facerecognizer_tpu.utils.metrics import Metrics
    from opencv_facerecognizer_tpu.utils.tracing import Tracer

    if seed is None:
        seed = random_mod.SystemRandom().randrange(1 << 31)
    print(f"chaos_soak partition seed={seed} seconds={seconds}",
          file=sys.stderr)

    trace_dir = tempfile.mkdtemp(prefix="ocvf_flight_")
    tracer = Tracer(ring_size=1 << 16, sample=1.0, seed=seed,
                    dump_dir=trace_dir, min_dump_interval_s=0.1)
    link_deadline_s = 0.25
    hedge_deadline_s = 0.12
    health_interval_s = 0.05
    offered_hz = 60.0
    topics = 12

    report = {"scenario": "partition", "seed": seed, "seconds": seconds,
              "ok": False}
    failures: list = []

    netfi = FaultInjector(seed=seed)
    router_metrics = Metrics()
    router, stacks = build_replica_fleet(
        3, dispatch_s=0.01, health_interval_s=health_interval_s,
        router_metrics=router_metrics, tracer=tracer,
        router_fault_injector=netfi, link_deadline_s=link_deadline_s,
        hedge_deadline_s=hedge_deadline_s)
    recorder = TrafficRecorder(router)
    #: raw delivery counter ABOVE the fan-in dedup — ``TrafficRecorder``
    #: setdefaults duplicate results away silently, so the exactly-once
    #: assertion needs its own count of every upstream dispatch.
    raw_lock = threading.Lock()
    raw_deliveries: dict = {}

    def count_raw(topic, message):
        seq = (message.get("meta") or {}).get("seq")
        if seq is not None:
            with raw_lock:
                raw_deliveries[seq] = raw_deliveries.get(seq, 0) + 1

    router.subscribe(RESULT_TOPIC, count_raw)
    frame_msg = encode_frame(np.zeros((32, 32), np.float32))
    seq_box = {"seq": 0}

    def offer() -> int:
        seq = seq_box["seq"]
        seq_box["seq"] = seq + 1
        recorder.send_t[seq] = time.monotonic()
        router.publish(f"camera/{seq % topics}",
                       {**frame_msg, "priority": "interactive",
                        "meta": {"seq": seq}})
        return seq

    def link_up(name: str) -> bool:
        return next(r["link_up"] for r in router.registry()
                    if r["name"] == name)

    def drain_all(timeout: float = 15.0) -> None:
        for _p, svc, _c, _m in stacks:
            svc.drain(timeout=timeout)

    interval = 1.0 / offered_hz
    base_p99_ms = p99_survivor = float("nan")
    failover_s = None
    blackout_seqs: list = []
    survivor_seqs: list = []
    storm_seqs: list = []
    try:
        for _p, svc, _c, _m in stacks:
            svc.start(warmup=False)
        router.start()

        # ---- phase A: unloaded baseline across the healthy fleet ----
        base_seqs = []
        base_end = time.monotonic() + min(1.0, seconds / 4)
        while time.monotonic() < base_end:
            base_seqs.append(offer())
            time.sleep(interval)
        drain_all()
        base_p99_ms = recorder.percentile_ms(base_seqs, 99)

        # ---- phase B: hard partition of the busiest replica ----
        busiest = max(router.registry(), key=lambda r: len(r["topics"]))
        victim = busiest["name"]
        netfi.set_partition(victim)
        t_part = time.monotonic()
        detect_budget = link_deadline_s + 4 * health_interval_s + 0.5
        heal_at = t_part + max(1.0, seconds * 0.2)
        t_detect = None
        while time.monotonic() < heal_at:
            seq = offer()
            if t_detect is None:
                if not link_up(victim):
                    t_detect = time.monotonic()
                    failover_s = t_detect - t_part
                else:
                    blackout_seqs.append(seq)
            elif time.monotonic() > t_detect + 2 * health_interval_s:
                survivor_seqs.append(seq)
            time.sleep(interval)
        if t_detect is None:
            failures.append(f"link to {victim} never failed over "
                            f"(partitioned at t+0, waited "
                            f"{heal_at - t_part:.1f}s)")
        elif failover_s > detect_budget:
            failures.append(f"failover took {failover_s:.2f}s > "
                            f"{detect_budget:.2f}s budget")
        netfi.heal_partition(victim)
        recover_deadline = time.monotonic() + detect_budget
        while (not link_up(victim)
               and time.monotonic() < recover_deadline):
            time.sleep(health_interval_s)
        if not link_up(victim):
            failures.append(f"link to {victim} never recovered after heal")

        # ---- phase C: flapping link on a second replica ----
        others = [r["name"] for r in router.registry() if r["name"] != victim]
        flappy = others[0]
        for _ in range(3):
            netfi.set_partition(flappy)
            flap_end = time.monotonic() + 2 * health_interval_s
            while time.monotonic() < flap_end:
                offer()
                time.sleep(interval)
            netfi.heal_partition(flappy)
            flap_end = time.monotonic() + 2 * health_interval_s
            while time.monotonic() < flap_end:
                offer()
                time.sleep(interval)
        recover_deadline = time.monotonic() + detect_budget
        while (not link_up(flappy)
               and time.monotonic() < recover_deadline):
            time.sleep(health_interval_s)
        if not link_up(flappy):
            failures.append(f"flapped link to {flappy} never converged up")

        # ---- phase D: duplicate storm on every transport crossing ----
        netfi.rates["transport"] = {"duplicate": 0.5}
        storm_end = time.monotonic() + max(1.0, seconds * 0.2)
        while time.monotonic() < storm_end:
            storm_seqs.append(offer())
            time.sleep(interval)
        netfi.rates["transport"] = {}
        drain_all()
        # Let straggler hedge results and pongs settle before judging.
        time.sleep(4 * health_interval_s)
        p99_survivor = recorder.percentile_ms(survivor_seqs, 99)
    finally:
        try:
            router.stop()
        finally:
            for _p, svc, _c, _m in stacks:
                try:
                    svc.stop()
                except Exception:  # noqa: BLE001 — teardown must finish
                    import traceback

                    traceback.print_exc()

    # ---- phase E: half-open writer — split-brain fails closed ----
    temp_dir = state_dir is None
    if temp_dir:
        state_dir = tempfile.mkdtemp(prefix="ocvf_partition_")
    from opencv_facerecognizer_tpu.parallel import ShardedGallery, make_mesh

    storefi = FaultInjector(seed=seed)
    writer_metrics = Metrics()
    DIM = 8
    writer_gallery = ShardedGallery(capacity=64, dim=DIM, mesh=make_mesh())
    state = StateLifecycle(state_dir, metrics=writer_metrics,
                           checkpoint_every_s=1e9, fault_injector=storefi)
    state.bind(writer_gallery, [])
    monitor = DurabilityMonitor(state, metrics=writer_metrics,
                                degraded_after=2, probe_interval_s=0.01,
                                fault_injector=storefi)
    frame_rng = np.random.default_rng(seed)
    split_brain = {"refused": False, "degraded_reason": None,
                   "rearmed": False, "recovered_ack": False}

    def enroll_once(tag: str):
        emb = frame_rng.normal(size=(1, DIM)).astype(np.float32)
        return state.append_enrollment(
            emb, np.zeros(1, np.int32), subject=tag, label=0)

    try:
        enroll_once("pre_partition")  # the volume provably works first
        # The state dir goes half-open: reads fail (the lease can no
        # longer be proven held), writes fail (the probe cannot re-arm).
        storefi.rates["storage"] = {"read_error": 1.0, "eio": 1.0}
        flip_deadline = time.monotonic() + 5.0
        while not monitor.degraded and time.monotonic() < flip_deadline:
            monitor.tick(force=True, probe=True)
            time.sleep(0.01)
        split_brain["degraded_reason"] = monitor.degraded_reason
        if not monitor.degraded:
            failures.append("half-open writer never flipped degraded")
        elif monitor.degraded_reason != "lease_unreachable":
            failures.append(f"writer degraded for the wrong reason: "
                            f"{monitor.degraded_reason!r}")
        try:
            enroll_once("during_partition")
            failures.append("degraded writer ACKED an enrollment — "
                            "split-brain window is open")
        except DurabilityDegradedError:
            split_brain["refused"] = True
        # Heal the volume: the recovery probe re-arms, enrollment flows.
        storefi.rates["storage"] = {}
        rearm_deadline = time.monotonic() + 5.0
        while monitor.degraded and time.monotonic() < rearm_deadline:
            monitor.tick(force=True, probe=True)
            time.sleep(0.01)
        split_brain["rearmed"] = not monitor.degraded
        if monitor.degraded:
            failures.append("healed writer never re-armed")
        else:
            try:
                enroll_once("post_heal")
                split_brain["recovered_ack"] = True
            except Exception as exc:  # noqa: BLE001 — any refusal here is the failure being tested
                failures.append(f"healed writer refused enrollment: {exc!r}")
    finally:
        state.close()
        if temp_dir:
            shutil.rmtree(state_dir, ignore_errors=True)

    # ---- verdicts over the fleet phases ----
    rc = router_metrics.counters()
    per_replica = []
    deduped_total = 0.0
    for i, (_p, svc, _c, metrics) in enumerate(stacks):
        ledger = svc.ledger()
        deduped = metrics.counters().get("frames_deduped", 0.0)
        deduped_total += deduped
        per_replica.append({"name": f"replica-{i}", "ledger": ledger,
                            "frames_deduped": deduped})
        if abs(ledger["in_system"]) > 1e-6:
            failures.append(f"replica-{i} ledger unsettled: {ledger}")
    deduped_total += rc.get("router_results_deduped", 0.0)

    if base_p99_ms != base_p99_ms:
        failures.append("no baseline frame completed")
    if p99_survivor != p99_survivor:
        failures.append("no survivor frame completed after failover")
    elif base_p99_ms == base_p99_ms \
            and p99_survivor > 2.0 * base_p99_ms + 100.0:
        failures.append(f"survivor p99 after failover blew the budget: "
                        f"{p99_survivor:.0f} ms > 2x baseline "
                        f"{base_p99_ms:.0f} ms + 100 ms")
    if not rc.get("router_hedges"):
        failures.append("no hedge fired during the blackout window")
    dup_seqs = {s: n for s, n in raw_deliveries.items() if n > 1}
    if dup_seqs:
        failures.append(f"duplicate result publishes for "
                        f"{len(dup_seqs)} seq(s): "
                        f"{dict(list(dup_seqs.items())[:5])}")
    if deduped_total < 1:
        failures.append("duplicate storm produced zero dedups — the "
                        "dedup layer was never exercised")
    if not rc.get("link_failures") or not rc.get("link_recoveries"):
        failures.append(f"link supervision never cycled: {rc}")

    failover_dumps = glob.glob(os.path.join(trace_dir,
                                            "flight-*failover*.json"))
    if not failover_dumps:
        failures.append("link failover left no flight-recorder dump")
    _check_flight_dumps(trace_dir, failures, require=1)
    shutil.rmtree(trace_dir, ignore_errors=True)

    report.update({
        "offered": seq_box["seq"],
        "baseline_p99_ms": None if base_p99_ms != base_p99_ms
        else round(base_p99_ms, 1),
        "survivor_p99_ms": None if p99_survivor != p99_survivor
        else round(p99_survivor, 1),
        "failover_s": None if failover_s is None else round(failover_s, 3),
        "blackout_offered": len(blackout_seqs),
        "blackout_rescued": recorder.completed(blackout_seqs),
        "storm_offered": len(storm_seqs),
        "storm_completed": recorder.completed(storm_seqs),
        "deduped_total": deduped_total,
        "duplicate_publishes": len(dup_seqs),
        "split_brain": split_brain,
        "router": {k: v for k, v in rc.items()},
        "replicas": per_replica,
        "transport_injected": {k: v for k, v in netfi.injected.items()},
    })
    report["failures"] = failures
    report["ok"] = not failures
    return report


def run_video(seconds: float = 6.0, seed: int | None = None,
              state_dir: str | None = None) -> dict:
    """Video scenario (ISSUE 17 acceptance): the temporal identity cache
    under the attacks it was designed to survive. Four arms, all
    closed-loop (one frame offered, drained, then the next — so every
    full result lands before the following lookup, making the guarantees
    exactly checkable with zero pipeline-lag slack):

    1. **identity swap, drift armed** — coherent single-stream video
       whose subject is swapped IN PLACE (same box, new identity)
       mid-run: the appearance-drift check must force the full verify on
       the very next frame — ZERO cached publishes of the old identity
       after the swap frame, with an ``identity`` flush recorded.
    2. **identity swap, drift disabled** — the same attack with the
       drift check neutered (threshold inf): the scheduled re-verify is
       now the only defense, and every stale cached publish must fall
       WITHIN the re-verify window after the swap — never past it — and
       the cache must recover onto the new identity afterwards.
    3. **ambiguity** — two identities converge until their tracks
       overlap above the IoU ceiling: the next full-path frame (at
       latest the scheduled re-verify) flushes BOTH tracks
       (``ambiguity`` x2 minimum) and no cached serve lands past the
       window edge — poisoning cannot cross tracks.
    4. **failover cold-start** — replica A serves the stream cache-hot,
       is killed, and the stream resumes on fresh replica B (PR 10's
       rendezvous routing pins topic->replica, so failover lands on a
       replica whose tracker is empty by construction): B's first frames
       MUST take the full path before any cached serve, both replicas'
       extended ledgers settle exactly, and an embedder-version bump on
       B flushes its cache (``version``) without serving a stale entry.

    Observability: arm 1 runs traced at sample=1.0 and must leave a
    parseable flight dump whose settle spans reproduce the extended
    ledger (``completed_cached`` included) exactly.
    """
    import random as random_mod

    import numpy as np

    from opencv_facerecognizer_tpu.runtime.connector import FakeConnector
    from opencv_facerecognizer_tpu.runtime.fakes import (
        InstantPipeline, synthetic_video_stream,
    )
    from opencv_facerecognizer_tpu.runtime.recognizer import (
        FRAME_TOPIC, RESULT_TOPIC, RecognizerService,
    )
    from opencv_facerecognizer_tpu.runtime.tracker import (
        IdentityTracker, TrackerConfig,
    )
    from opencv_facerecognizer_tpu.utils import metric_names as mn
    from opencv_facerecognizer_tpu.utils.metrics import Metrics
    from opencv_facerecognizer_tpu.utils.tracing import Tracer

    if seed is None:
        seed = random_mod.SystemRandom().randrange(1 << 31)
    print(f"chaos_soak video seed={seed} seconds={seconds}",
          file=sys.stderr)

    frame_hw = (64, 64)
    reverify = 6
    n_frames = max(36, int(seconds * 12))
    # Offset the swap off the re-verify period (6): were they aligned,
    # the scheduled verify would land ON the swap frame and the
    # drift-disabled arm would never observe the stale window it exists
    # to bound.
    swap_at = n_frames // 2 + 3
    names = ["id0", "id1", "id2", "id3"]
    trace_dir = tempfile.mkdtemp(prefix="ocvf_flight_")
    tracer = Tracer(ring_size=1 << 15, sample=1.0, seed=seed,
                    dump_dir=trace_dir, min_dump_interval_s=0.1)
    report = {"scenario": "video", "seed": seed, "seconds": seconds,
              "reverify_frames": reverify, "ok": False}
    failures: list = []

    def build(drift_threshold=None, svc_tracer=None):
        metrics = Metrics()
        pipeline = InstantPipeline(frame_hw, cascade_stub=True,
                                   video_oracle=True)
        connector = FakeConnector()
        kwargs = {"reverify_frames": reverify}
        if drift_threshold is not None:
            kwargs["drift_threshold"] = drift_threshold
        tracker = IdentityTracker(TrackerConfig(**kwargs), metrics=metrics)
        service = RecognizerService(
            pipeline, connector, batch_size=4, frame_shape=frame_hw,
            flush_timeout=0.01, inflight_depth=2,
            similarity_threshold=0.0, metrics=metrics, tracer=svc_tracer,
            bucket_sizes=(1, 2, 4), cascade=True, subject_names=names,
            tracker=tracker)
        pipeline.prewarm_batch_shapes(service._bucket_ladder, frame_hw,
                                      service.batcher.dtype)
        service._warmed = True
        results = []
        connector.subscribe(RESULT_TOPIC,
                            lambda t, m: results.append(m))
        service.start(warmup=False)
        return service, connector, metrics, tracker, results

    def drive(service, connector, frames, start_seq, where):
        """Closed-loop offer: one frame, one drain — full determinism."""
        for i, (frame, key, _k) in enumerate(frames):
            connector.inject(FRAME_TOPIC, {
                "frame": frame,
                "meta": {"seq": start_seq + i, "stream": key}})
            if not service.drain(timeout=10.0):
                failures.append(f"{where}: drain wedged at frame "
                                f"{start_seq + i}")
                return False
        return True

    def cached_of(results, label=None, min_seq=None):
        out = []
        for m in results:
            if m.get("exit") != "track_cache":
                continue
            if min_seq is not None and m["meta"]["seq"] < min_seq:
                continue
            if label is not None and not any(
                    f["label"] == label for f in m["faces"]):
                continue
            out.append(m["meta"]["seq"])
        return out

    def check_ledger(service, where):
        ledger = service.ledger()
        drops = sum(ledger["drops_by_reason"].values())
        settled = (ledger["completed"] + ledger["completed_empty"]
                   + ledger["completed_cached"] + drops)
        if ledger["admitted"] != settled or ledger["in_system"] != 0:
            failures.append(f"{where}: extended ledger not exact: {ledger}")
        return ledger

    # -- arm 1: identity swap with the drift check armed (traced) --
    service, conn, metrics, _tracker, results = build(svc_tracer=tracer)
    stream = synthetic_video_stream(
        n_frames, frame_hw, streams=1, coherence=1.0,
        identity_swap_at=swap_at, seed=seed % 100003)
    quiesced = drive(service, conn, stream, 0, "swap/drift")
    service.stop()
    # The generator's first identity is 0; the in-place swap moves it to
    # 1 — a cached publish of label 0 at or past the swap frame IS the
    # stale serve the drift check exists to prevent (the swap frame
    # itself counts: its content is already the new identity when the
    # lookup runs).
    stale = cached_of(results, label=0, min_seq=swap_at)
    warm = cached_of(results, min_seq=None)
    if not warm or (warm and min(warm) > swap_at):
        failures.append("swap/drift: cache never engaged before the swap")
    if stale:
        failures.append(f"swap/drift: stale identity served from cache "
                        f"after the swap at seqs {stale[:5]}")
    if metrics.counter(mn.TRACK_FLUSHES_PREFIX + "identity") < 1:
        failures.append("swap/drift: no identity flush recorded")
    ledger = check_ledger(service, "swap/drift")
    report["swap_drift"] = {
        "frames": n_frames, "swap_at": swap_at,
        "cached_total": len(warm), "stale_after_swap": len(stale),
        "identity_flushes": int(metrics.counter(
            mn.TRACK_FLUSHES_PREFIX + "identity")),
        "reverifies": int(metrics.counter(mn.TRACK_REVERIFIES)),
    }

    # -- arm 2: same swap, drift DISABLED -> the window is the bound --
    service2, conn2, _m2, _t2, results2 = build(drift_threshold=1e9)
    stream2 = synthetic_video_stream(
        n_frames, frame_hw, streams=1, coherence=1.0,
        identity_swap_at=swap_at, seed=seed % 100003)
    drive(service2, conn2, stream2, 0, "swap/window")
    service2.stop()
    stale2 = cached_of(results2, label=0, min_seq=swap_at)
    recovered = cached_of(results2, label=1, min_seq=swap_at + 1)
    if stale2 and max(stale2) > swap_at + reverify:
        failures.append(
            f"swap/window: stale identity served PAST the re-verify "
            f"window (seq {max(stale2)} > {swap_at + reverify})")
    if not recovered:
        failures.append("swap/window: cache never recovered onto the "
                        "new identity after the verify")
    check_ledger(service2, "swap/window")
    report["swap_window"] = {
        "stale_within_window": len(stale2),
        "last_stale_seq": max(stale2) if stale2 else None,
        "window_edge_seq": swap_at + reverify,
        "recovered_cached": len(recovered),
    }

    # -- arm 3: nested faces -> ambiguity flushes BOTH --
    # Two live tracks over the IoU ceiling, neither failing the identity
    # cross-check: a smaller face moves INSIDE a larger one (think a
    # face passing in front of a close-up). The big blob's border ring
    # stays visible so its detected box stays full-size; the nested box
    # overlaps it at IoU ~0.69 while both faces keep matching their own
    # tracks — only the ambiguity sweep can catch this. The contract is
    # the bounded one the cache is designed around: the overlap is
    # detected on the next FULL-path frame (drift-forced, or at latest
    # the scheduled re-verify), BOTH tracks flush, and the cache stays
    # off for the rest of the overlap — so no cached serve can land
    # more than one re-verify interval past the merge. (Whether the
    # march's drift trips early is noise-sensitive — a stale track box
    # over a sliding fill straddles the median threshold — so the
    # window edge, not the merge frame, is the assertable line.)
    service3, conn3, m3, _t3, results3 = build()
    rng = np.random.default_rng(seed ^ 0x5EED)
    path = ([(10, 36)] * 6                       # separate: confirm + cache
            + [(10, 28), (10, 20), (12, 12), (12, 6)]  # march inside
            + [(12, 6)] * (reverify + 4))        # hold nested past the window
    merge_seq = 9                                # (12, 6) first nests here
    conv = []
    for yb, xb in path:
        frame = rng.integers(20, 90, size=frame_hw).astype(np.uint8)
        frame[10:34, 4:28] = 160                 # identity 0: 24x24, static
        frame[yb:yb + 20, xb:xb + 20] = 184      # identity 1: 20x20, moving
        conv.append((frame, "cam0", 2))
    drive(service3, conn3, conv, 0, "ambiguity")
    service3.stop()
    amb_flushes = int(m3.counter(mn.TRACK_FLUSHES_PREFIX + "ambiguity"))
    if amb_flushes < 2:
        failures.append(f"ambiguity: expected both tracks flushed, got "
                        f"{amb_flushes} ambiguity flushes")
    overlapped = cached_of(results3, min_seq=merge_seq + reverify + 1)
    if overlapped:
        failures.append(f"ambiguity: cached serve past the re-verify "
                        f"window edge (seq {merge_seq + reverify}), "
                        f"seqs {overlapped[:5]}")
    warm3 = cached_of(results3)
    if not warm3 or min(warm3) > merge_seq:
        failures.append("ambiguity: cache never engaged before the merge")
    check_ledger(service3, "ambiguity")
    report["ambiguity"] = {"flushes": amb_flushes,
                           "cached_before_merge": len(warm3),
                           "cached_past_window": len(overlapped)}

    # -- arm 4: replica kill -> failover cold-start + version fence --
    svc_a, conn_a, _ma, _ta, res_a = build()
    svc_b, conn_b, mb, _tb, res_b = build()
    # Stamp a concrete embedder version on B before it serves: entries
    # verified under version None are fence-exempt by design (the fence
    # only fires on a MISMATCH of known versions), and the cutover
    # sub-check below needs stamped entries to invalidate.
    svc_b.pipeline.gallery.embedder_version = 1
    half = max(16, n_frames // 2)
    stream4 = synthetic_video_stream(2 * half, frame_hw, streams=1,
                                     coherence=1.0, seed=(seed + 7) % 100003)
    drive(svc_a, conn_a, stream4[:half], 0, "failover/A")
    svc_a.stop()  # the kill: rendezvous routing re-pins the topic to B
    hot_a = cached_of(res_a)
    if not hot_a:
        failures.append("failover/A: cache never engaged before the kill")
    drive(svc_b, conn_b, stream4[half:], half, "failover/B")
    cached_b = cached_of(res_b)
    # Cold start: B cannot serve from cache until its own tracker has
    # confirmed the track from full frames (confirm_hits=2) — the first
    # two frames after failover MUST be full-path.
    early = [s for s in cached_b if s < half + 2]
    if early:
        failures.append(f"failover/B: cached serve before the cold "
                        f"cache could have confirmed (seqs {early})")
    if not cached_b:
        failures.append("failover/B: cache never re-engaged after "
                        "failover")
    # Embedder-version fence: a cutover bump on B's gallery must flush
    # its tracks (reason ``version``) instead of serving entries
    # verified under the old embedder.
    svc_b.pipeline.gallery.embedder_version = 2
    tail_seq = half + len(stream4[half:])
    extra = synthetic_video_stream(6, frame_hw, streams=1, coherence=1.0,
                                   seed=(seed + 7) % 100003)
    drive(svc_b, conn_b, extra, tail_seq, "failover/version")
    svc_b.stop()
    if int(mb.counter(mn.TRACK_FLUSHES_PREFIX + "version")) < 1:
        failures.append("failover/version: no version flush after the "
                        "embedder bump")
    check_ledger(svc_a, "failover/A")
    check_ledger(svc_b, "failover/B")
    report["failover"] = {
        "a_cached": len(hot_a), "b_cached": len(cached_b),
        "b_first_cached_seq": min(cached_b) if cached_b else None,
        "version_flushes": int(mb.counter(
            mn.TRACK_FLUSHES_PREFIX + "version")),
    }

    # -- observability: arm 1's dump mirrors the extended ledger --
    _finish_observability(tracer, trace_dir, "video_end", ledger,
                          quiesced, failures, report)

    report["failures"] = failures
    report["ok"] = not failures
    return report


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--seconds", type=float, default=10.0)
    parser.add_argument("--seed", type=int, default=None,
                        help="replay a previous run exactly (logged on stderr)")
    parser.add_argument("--scenario", choices=["soak", "overload", "recovery",
                                               "replication", "rollout",
                                               "registry",
                                               "disk", "partition",
                                               "video"],
                        default="soak",
                        help="soak: randomized fault soak (default); "
                             "overload: 4x flood against the admission/"
                             "brownout/journal stack (run_overload); "
                             "recovery: seeded kills at every durability "
                             "boundary, zero-loss recovery + graceful "
                             "drain (run_recovery); replication: 1 writer "
                             "+ 2 WAL-tailing read replicas behind the "
                             "topic router — kill a reader mid-traffic "
                             "and the writer mid-enrollment, assert "
                             "survivor p99, zero acked loss, split-brain "
                             "fail-closed (run_replication); rollout: "
                             "live embedder rollout — kills mid-re-embed, "
                             "mid-cutover, and a reader mid-re-anchor; "
                             "assert zero acked loss, no mixed-version "
                             "scores, serving continuity (run_rollout); "
                             "registry: versioned model-registry swaps — "
                             "kill before/after the detector-swap fence "
                             "(recovery completes), damaged candidate "
                             "(recovery cleanly abandons), parity-"
                             "regressing candidate (auto-rollback + "
                             "flight dump); assert bit-equal zero acked "
                             "loss, never mixed-version serving, exact "
                             "per-replica ledgers (run_registry); "
                             "disk: the disk STAYS broken — ENOSPC "
                             "mid-enrollment, EIO mid-checkpoint, slow "
                             "fsync under load, watermark pressure; "
                             "assert refused-closed enrollments, serving "
                             "continuity, exact per-sink shed accounting, "
                             "automatic re-arm, zero acked loss "
                             "(run_disk); partition: the NETWORK fails — "
                             "router<->replica partition + heal, flapping "
                             "link, duplicate storm, half-open writer; "
                             "assert bounded failover, hedge rescue, "
                             "exactly-once delivery, exact ledgers, "
                             "split-brain fail-closed (run_partition); "
                             "video: the temporal identity cache under "
                             "attack — in-place identity swap with the "
                             "drift check armed (zero stale) and disabled "
                             "(stale bounded by the re-verify window), "
                             "ambiguity flushing both tracks, replica "
                             "kill + failover cold-start, embedder-"
                             "version fence; exact extended ledgers and "
                             "span accounting incl. completed_cached "
                             "(run_video)")
    parser.add_argument("--journal", default=None,
                        help="overload scenario: write the dead-letter "
                             "journal here instead of a temp file")
    parser.add_argument("--state-dir", default=None,
                        help="recovery scenario: run over this state dir "
                             "(kept afterwards) instead of a temp dir")
    args = parser.parse_args(argv)
    if args.scenario == "overload":
        report = run_overload(seconds=args.seconds, seed=args.seed,
                              journal_path=args.journal)
    elif args.scenario == "recovery":
        report = run_recovery(seconds=args.seconds, seed=args.seed,
                              state_dir=args.state_dir)
    elif args.scenario == "replication":
        report = run_replication(seconds=args.seconds, seed=args.seed,
                                 state_dir=args.state_dir)
    elif args.scenario == "rollout":
        report = run_rollout(seconds=args.seconds, seed=args.seed,
                             state_dir=args.state_dir)
    elif args.scenario == "registry":
        report = run_registry(seconds=args.seconds, seed=args.seed,
                              state_dir=args.state_dir)
    elif args.scenario == "disk":
        report = run_disk(seconds=args.seconds, seed=args.seed,
                          state_dir=args.state_dir)
    elif args.scenario == "partition":
        report = run_partition(seconds=args.seconds, seed=args.seed,
                               state_dir=args.state_dir)
    elif args.scenario == "video":
        report = run_video(seconds=args.seconds, seed=args.seed,
                           state_dir=args.state_dir)
    else:
        report = run_soak(seconds=args.seconds, seed=args.seed)
    print(json.dumps(report, indent=2, default=str))
    return 0 if report["ok"] else 2


if __name__ == "__main__":
    sys.exit(main())
