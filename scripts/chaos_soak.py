"""Chaos soak: the serving loop under randomized, seed-logged fault
injection — exits nonzero on wedge or crash.

Builds a tiny (untrained — detection quality is irrelevant here) CPU
serving stack over ``FakeConnector``, installs a ``FaultInjector`` with
randomized rates drawn from the logged seed, wraps the service in a
``ServiceSupervisor``, and pounds frames at it for ``--seconds``. The
whole run is reproducible: rerun with the printed ``--seed`` and the exact
same fault sequence replays.

Pass criteria (any miss exits rc=2 with the reason in the JSON report):

1. **no wedge** — after the chaos window the injector is disarmed and a
   probe burst of clean frames must all come back as results within a
   bounded wait (a deadlocked/crashed-and-unrestarted loop fails here);
2. **no unsupervised crash** — every loop crash must be matched by a
   supervisor restart (``loop_crashes`` == ``supervisor_restarts``, and
   the supervisor never gave up);
3. **accounting sane** — dead-letters/abandons/dispatches reconcile with
   the batcher's delivered count (no silently vanished batch).

The fast deterministic variant (``--seconds 2 --seed 7``) runs in tier-1
via ``tests/test_chaos.py``; the long randomized soak is the ``slow``-
marked test (or run this script directly).

Usage::

    python scripts/chaos_soak.py --seconds 30            # random seed
    python scripts/chaos_soak.py --seconds 30 --seed 7   # replay
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_stack(frame_shape=(64, 64), face=(16, 16), capacity=64, seed=0):
    """Tiny untrained serving stack (CPU-mesh): chaos cares about the
    loop's control flow, not recognition quality — untrained nets keep
    startup in seconds while exercising the full dispatch/readback path."""
    import jax
    import numpy as np

    from opencv_facerecognizer_tpu.models.detector import CNNFaceDetector
    from opencv_facerecognizer_tpu.models.embedder import FaceEmbedNet, init_embedder
    from opencv_facerecognizer_tpu.parallel import ShardedGallery, make_mesh
    from opencv_facerecognizer_tpu.parallel.pipeline import RecognitionPipeline

    det = CNNFaceDetector(features=(4, 8), head_features=8, max_faces=2,
                          score_threshold=0.5, space_to_depth=4)
    rng = jax.random.PRNGKey(seed)
    det.load_params(det.net.init(
        rng, jax.numpy.zeros((1, *frame_shape), jax.numpy.float32))["params"])
    net = FaceEmbedNet(embed_dim=16, stem_features=4, stage_features=(4, 8),
                       stage_blocks=(1, 1))
    params = init_embedder(net, 4, face, seed=seed)
    mesh = make_mesh()
    gallery = ShardedGallery(capacity=capacity, dim=16, mesh=mesh)
    g_rng = np.random.default_rng(seed)
    emb = g_rng.normal(size=(8, 16)).astype(np.float32)
    gallery.add(emb, np.arange(8, dtype=np.int32) % 4)
    pipe = RecognitionPipeline(det, net, params["net"], gallery, face_size=face)
    return pipe, mesh


def run_soak(seconds: float = 10.0, seed: int | None = None,
             frame_shape=(64, 64)) -> dict:
    """One supervised chaos run; returns the JSON-able report dict with
    ``report["ok"]`` as the overall verdict."""
    import random as random_mod

    import numpy as np

    from opencv_facerecognizer_tpu.runtime import (
        FakeConnector, FaultInjector, RecognizerService, ResiliencePolicy,
        ServiceSupervisor,
    )
    from opencv_facerecognizer_tpu.runtime.connector import encode_frame
    from opencv_facerecognizer_tpu.runtime.recognizer import (
        FRAME_TOPIC, RESULT_TOPIC,
    )

    if seed is None:
        seed = random_mod.SystemRandom().randrange(1 << 31)
    print(f"chaos_soak seed={seed} seconds={seconds}", file=sys.stderr)

    # Moderate randomized rates: every boundary sees faults in a run of a
    # few hundred frames, but healthy traffic still dominates, so the
    # liveness probe has signal that serving continued THROUGH the chaos.
    rate_rng = random_mod.Random(seed)
    rates = {
        "receive": {"corrupt": 0.05 * rate_rng.random(),
                    "drop": 0.05 * rate_rng.random(),
                    "duplicate": 0.05 * rate_rng.random()},
        "put": {"corrupt": 0.05 * rate_rng.random()},
        "dispatch": {"unavailable": 0.10 * rate_rng.random()},
        "readback": {"stuck": 0.05 * rate_rng.random()},
    }
    injector = FaultInjector(seed=seed, rates=rates)
    pipe, _mesh = build_stack(frame_shape=frame_shape, seed=seed % 997)
    connector = FakeConnector()
    service = RecognizerService(
        pipe, connector, batch_size=2, frame_shape=frame_shape,
        flush_timeout=0.02, inflight_depth=2,
        resilience=ResiliencePolicy(
            dispatch_retries=2, backoff_base_s=0.01, backoff_max_s=0.05,
            readback_deadline_s=0.5, degraded_after=3,
        ),
        fault_injector=injector,
    )
    supervisor = ServiceSupervisor(service, max_restarts=1000,
                                   poll_interval_s=0.05)
    supervisor.start()

    frame_rng = np.random.default_rng(seed)
    report = {"seed": seed, "seconds": seconds, "rates": rates, "ok": False}
    try:
        sent = 0
        deadline = time.monotonic() + seconds
        while time.monotonic() < deadline:
            frame = frame_rng.uniform(0, 255, frame_shape).astype(np.float32)
            connector.inject(FRAME_TOPIC,
                             {**encode_frame(frame), "meta": {"seq": sent}})
            sent += 1
            time.sleep(0.01)

        # ---- liveness probe: clean traffic after the chaos window ----
        injector.disarm()
        # Clear the chaos-window backlog first: on a slow host the sender
        # outpaces the loop, and liveness means "still making progress", not
        # "zero queue depth the instant chaos ends". drain() is bounded; the
        # probe below is the actual verdict either way.
        service.drain(timeout=max(15.0, 3.0 * seconds))
        probe_n = 6
        for i in range(probe_n):
            frame = frame_rng.uniform(0, 255, frame_shape).astype(np.float32)
            connector.inject(FRAME_TOPIC,
                             {**encode_frame(frame), "meta": {"probe": i}})
        # Wait on the probe-tagged results specifically — counting raw
        # result volume would let backlog results satisfy the wait while
        # the probe frames are still queued (observed false wedge on the
        # 8-virtual-device CPU mesh tier-1 runs).
        probe_deadline = time.monotonic() + 15.0
        probe_results: list = []
        while time.monotonic() < probe_deadline:
            probe_results = [
                r for r in connector.messages(RESULT_TOPIC)
                if isinstance(r.get("meta"), dict) and "probe" in r["meta"]
            ]
            if len(probe_results) >= probe_n:
                break
            time.sleep(0.05)
        results = connector.messages(RESULT_TOPIC)
        wedged = len(probe_results) < probe_n
    finally:
        supervisor.stop()

    counters = service.metrics.counters()
    report["sent"] = sent
    report["results"] = len(results)
    report["injected"] = injector.summary()
    report["counters"] = counters
    report["supervisor_restarts"] = supervisor.restarts

    failures = []
    if wedged:
        failures.append(f"wedged: liveness probe got {len(probe_results)}/"
                        f"{probe_n} results")
    crashes = counters.get("loop_crashes", 0)
    if crashes != counters.get("supervisor_restarts", 0) or supervisor.gave_up:
        failures.append(f"unsupervised crash: {crashes} crashes vs "
                        f"{counters.get('supervisor_restarts', 0)} restarts "
                        f"(gave_up={supervisor.gave_up})")
    delivered = service.batcher.delivered_batches
    # Every popped batch must end dispatched (then published or dead-
    # lettered) or abandoned (batches_failed) — nothing silently vanishes.
    accounted = (counters.get("batches_dispatched", 0)
                 + counters.get("batches_failed", 0))
    if delivered != accounted:
        failures.append(f"accounting: delivered={delivered} != "
                        f"dispatched+failed={accounted}")
    report["failures"] = failures
    report["ok"] = not failures
    return report


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--seconds", type=float, default=10.0)
    parser.add_argument("--seed", type=int, default=None,
                        help="replay a previous run exactly (logged on stderr)")
    args = parser.parse_args(argv)
    report = run_soak(seconds=args.seconds, seed=args.seed)
    print(json.dumps(report, indent=2, default=str))
    return 0 if report["ok"] else 2


if __name__ == "__main__":
    sys.exit(main())
