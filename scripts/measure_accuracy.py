"""Measure the five BASELINE.json config accuracies and write them into
BASELINE.md (VERDICT round-1 item #3; SURVEY.md §6 "first build milestone").

The real AT&T/Yale-B/LFW images are unreachable (zero egress — SURVEY.md
§0), so each config runs on its synthetic analog from
``utils.dataset.make_synthetic_faces``, with the variation axes chosen to
mirror what the real set stresses (Yale-B -> strong illumination; LFW ->
higher noise). Numbers are therefore *this framework's measured accuracy on
the stated synthetic protocol* — directly comparable run-over-run (the
regression bands in tests/test_accuracy.py guard them), not claims about
the physical datasets.

Run on the real chip:  PYTHONPATH=. python scripts/measure_accuracy.py
Updates the MEASURED block of BASELINE.md in place and prints the JSON.
"""

from __future__ import annotations

import json
import os
import re
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

BEGIN = "<!-- MEASURED:BEGIN (scripts/measure_accuracy.py) -->"
END = "<!-- MEASURED:END -->"


def classic_kfold(model_kind: str, num_subjects: int, per_subject: int,
                  kfold: int, **faces_kwargs):
    from opencv_facerecognizer_tpu.runtime.trainer import TheTrainer, TrainerConfig
    from opencv_facerecognizer_tpu.utils.dataset import make_synthetic_faces

    X, y, names = make_synthetic_faces(
        num_subjects=num_subjects, per_subject=per_subject, size=(70, 70),
        **faces_kwargs,
    )
    trainer = TheTrainer(TrainerConfig(model=model_kind, kfold=kfold))
    t0 = time.perf_counter()
    trainer.train(X, y, names, validate=True)
    return {
        "accuracy": round(trainer.mean_accuracy, 4),
        "folds": kfold,
        "dataset": f"synthetic {num_subjects}x{per_subject} 70x70 "
                   + ", ".join(f"{k}={v}" for k, v in faces_kwargs.items()),
        "seconds": round(time.perf_counter() - t0, 1),
    }


#: The round-3 hard protocol (VERDICT round-2 missing #1: the previous
#: smooth-gaussian + noise/illumination/±2px distribution was "a recipe-
#: works signal, not a north-star proof"): every config now adds in-plane
#: pose rotation, scale jitter, smooth elastic deformation (expression/3-D
#: pose analog), and random occluding rectangles (sunglasses/scarf analog).
#: LFW-analog configs get the strongest settings.
HARD_POSE = dict(rotation=8.0, scale_jitter=0.08, elastic=1.2, occlusion=0.25)
HARD_WILD = dict(rotation=12.0, scale_jitter=0.12, elastic=1.8, occlusion=0.3)


def cnn_verification():
    """ArcFace CNN on disjoint identities, 6000-pair 10-fold protocol, on
    the hard (pose/elastic/occlusion) distribution with hundreds of
    training identities."""
    from opencv_facerecognizer_tpu.models.embedder import CNNEmbedding
    from opencv_facerecognizer_tpu.utils.dataset import make_synthetic_faces
    from opencv_facerecognizer_tpu.utils.verification import (
        make_verification_pairs, verification_accuracy,
    )

    size = (64, 64)
    X_tr, y_tr, _ = make_synthetic_faces(
        num_subjects=300, per_subject=12, size=size, seed=11, noise=10.0,
        **HARD_WILD,
    )
    # Held-out identities: disjoint seed -> disjoint subject structures.
    X_te, y_te, _ = make_synthetic_faces(
        num_subjects=48, per_subject=12, size=size, seed=77, noise=10.0,
        **HARD_WILD,
    )
    # Hard-protocol config: without train-time augmentation the round-2 net
    # measured 0.9342 here (2000 steps) — the 10 fixed views per identity
    # cannot teach occlusion/pose invariance. augment=True turns on the
    # in-graph flip/shift/cutout pipe (models.embedder.augment_batch), with
    # a cosine decay over a longer run and a wider trunk. r4 margin attack
    # (scripts/.gate_embedder.jsonl): 9000 steps/b128 measured
    # 0.9937 +/- 0.0036 (mean-2sigma 0.9865, ON the >=0.99 bar);
    # 30000 steps/b192 measured 0.9943 +/- 0.0020, mean-2sigma 0.9903 and
    # fold_min 0.9917 — decisively above it. Structural speedups (s2d
    # stem folds, light norm, dense blocks) were all gated here and all
    # measured BELOW baseline accuracy (0.9655-0.987), so the accuracy
    # config keeps the s1/full/separable structure.
    emb = CNNEmbedding(
        embed_dim=256, input_size=size, stem_features=32,
        stage_features=(64, 128, 256), stage_blocks=(2, 2, 2),
        train_steps=30000, batch_size=192, learning_rate=2e-3, seed=3,
        augment=True, lr_schedule="cosine", tta=True,
    )
    t0 = time.perf_counter()
    emb.compute(X_tr, y_tr)
    train_s = time.perf_counter() - t0
    e = np.array(emb._extract_batch(np.asarray(X_te, np.float32)))
    a, b, same = make_verification_pairs(y_te, num_pairs=6000, seed=5)
    acc, std, thr, fold_accs = verification_accuracy(e[a], e[b], same,
                                                     folds=10,
                                                     return_folds=True)
    return {
        "accuracy": round(acc, 4), "std": round(std, 4),
        "fold_min": round(float(min(fold_accs)), 4),
        "threshold": round(thr, 3),
        "dataset": "synthetic verification, HARD protocol (rot 12deg, "
                   "scale 0.12, elastic 1.8px, occlusion p=0.3): train 300 "
                   "identities x12, eval 48 disjoint x12, 6000 pairs, "
                   "10-fold; embed_dim=256, stages 64/128/256, 30000 steps "
                   "batch 192, in-graph flip/rot/scale/shift/cutout "
                   "augmentation, cosine lr, flip-TTA — vs the >=0.99 "
                   "north star (BASELINE.json:5)",
        "seconds": round(train_s, 1),
    }


#: measurement key -> thunk; --only selects a subset (full run ~12 min on
#: the chip can exceed an execution window — rows refresh independently and
#: merge with the cache at scripts/.accuracy_cache.json).
CONFIGS = {
    "eigenfaces": ("eigenfaces_orl",
                   lambda: classic_kfold("eigenfaces", 40, 10, 10, seed=1,
                                         **HARD_POSE)),
    "fisherfaces": ("fisherfaces_yaleb",
                    lambda: classic_kfold("fisherfaces", 30, 12, 10, seed=2,
                                          illumination=0.7, noise=14.0,
                                          **HARD_POSE)),
    "lbph": ("lbph_lfw",
             lambda: classic_kfold("lbph", 40, 8, 10, seed=3, noise=18.0,
                                   **HARD_WILD)),
    # the Fisherfaces robustness winner (scripts/explore_fisherfaces.py):
    # raw-LBP spatial histograms -> Fisherfaces -> cosine NN on the SAME
    # hard Yale-B-analog protocol as the fisherfaces row
    "lbp_fisherfaces": ("lbp_fisherfaces_yaleb",
                        lambda: classic_kfold("lbp_fisherfaces", 30, 12, 10,
                                              seed=2, illumination=0.7,
                                              noise=14.0, **HARD_POSE)),
    # the same config on the lbph row's LFW-analog protocol (it beats that
    # row's chi-square recipe there too: 0.9625 vs 0.9250)
    "lbp_fisherfaces_lfw": ("lbp_fisherfaces_lfw",
                            lambda: classic_kfold("lbp_fisherfaces", 40, 8,
                                                  10, seed=3, noise=18.0,
                                                  **HARD_WILD)),
    # ... and on the eigenfaces row's ORL-analog protocol (0.9975 vs 0.8950)
    "lbp_fisherfaces_orl": ("lbp_fisherfaces_orl",
                            lambda: classic_kfold("lbp_fisherfaces", 40, 10,
                                                  10, seed=1, **HARD_POSE)),
    "cnn": ("cnn_verification", cnn_verification),
}

CACHE = os.path.join(REPO, "scripts", ".accuracy_cache.json")


def main(argv=None):
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--only", action="append", choices=sorted(CONFIGS),
                    help="measure only these configs; others keep their "
                         "cached values (repeatable)")
    ap.add_argument("--cpu", action="store_true",
                    help="force the host backend. Accuracy is backend-"
                         "independent (verified: the fisherfaces row "
                         "reproduces to 4 decimals on CPU); use for the "
                         "classic rows when the TPU tunnel is down. The "
                         "cnn row is chip-scale training — refresh it on "
                         "hardware.")
    args = ap.parse_args(argv)
    if args.cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")
    selected = args.only or sorted(CONFIGS)

    results = {}
    if os.path.exists(CACHE):
        try:
            results.update(json.load(open(CACHE)))
        except (json.JSONDecodeError, OSError) as e:
            # a run killed mid-write must not wedge later runs
            print(f"ignoring unreadable cache {CACHE}: {e}", file=sys.stderr)
    missing = [k for k, (rk, _) in CONFIGS.items()
               if k not in selected and rk not in results]
    if missing:
        # Rows can be seeded incrementally across execution windows: just
        # note what the rendered table will be missing this time.
        print(f"note: no cached value yet for {missing}; the BASELINE.md "
              f"table will omit those rows until they are measured",
              file=sys.stderr)

    import jax

    stamp = {"device": str(jax.devices()[0]),
             "date": time.strftime("%Y-%m-%d")}
    for i, key in enumerate(selected):
        result_key, thunk = CONFIGS[key]
        print(f"[{i + 1}/{len(selected)}] {key} ...", file=sys.stderr)
        results[result_key] = {**thunk(), **stamp}  # per-row provenance

    results["_meta"] = dict(stamp)
    from opencv_facerecognizer_tpu.utils.serialization import atomic_write_json

    atomic_write_json(CACHE, results)  # atomic: a killed run can't truncate the cache
    print(json.dumps(results, indent=2))

    all_rows = [
        ("Eigenfaces (PCA+NN) k-fold, ORL-analog", "eigenfaces_orl"),
        ("Fisherfaces (TanTriggs s0=2,s1=4 + PCA+LDA+NN) k-fold, Yale-B-analog",
         "fisherfaces_yaleb"),
        ("LBPH (SpatialHistogram r=2 + ChiSquare NN) k-fold, LFW-analog",
         "lbph_lfw"),
        ("LBP-Fisherfaces (raw ExtendedLBP r=3 6x6 + PCA+LDA + cosine NN) "
         "k-fold, Yale-B-analog", "lbp_fisherfaces_yaleb"),
        ("LBP-Fisherfaces, same config on the LFW-analog protocol",
         "lbp_fisherfaces_lfw"),
        ("LBP-Fisherfaces, same config on the ORL-analog protocol",
         "lbp_fisherfaces_orl"),
        ("CNN ArcFace embedding, 6000-pair verification, disjoint identities",
         "cnn_verification"),
    ]
    rows = [(label, results[rk]) for label, rk in all_rows if rk in results]
    lines = [BEGIN, "",
             "| Config (synthetic analog — see scripts/measure_accuracy.py) "
             "| Measured accuracy | Protocol |",
             "|---|---|---|"]
    for label, r in rows:
        acc = f"{r['accuracy']:.4f}"
        if "std" in r:
            acc += f" ± {r['std']:.4f}"
        lines.append(f"| {label} | **{acc}** | {r['dataset']} |")
    lines += ["",
              f"Last refreshed {results['_meta']['date']} on "
              f"{results['_meta']['device']}; per-row measurement dates in "
              "`scripts/.accuracy_cache.json`. Regression bands asserted in "
              "`tests/test_accuracy.py`. The ROS live-stream config "
              "(BASELINE.json row 4) is measured by `bench_serving.py` "
              "(end-to-end latency/throughput artifact).", END]
    block = "\n".join(lines)

    path = os.path.join(REPO, "BASELINE.md")
    text = open(path).read()
    if BEGIN in text:
        text = re.sub(re.escape(BEGIN) + r".*?" + re.escape(END), block,
                      text, flags=re.S)
    else:
        text = text.rstrip() + "\n\n## Measured accuracy (this framework)\n\n" + block + "\n"
    from opencv_facerecognizer_tpu.utils.serialization import atomic_write_text

    atomic_write_text(path, text)
    print(f"BASELINE.md measured block updated", file=sys.stderr)


if __name__ == "__main__":
    main()
