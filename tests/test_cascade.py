"""Cascade early-exit detection (ISSUE 13): stage-1 FaceGate model,
the serving gate's ``completed_empty`` ledger settlement (exact
accounting mixed with drops/dead-letters, settle-span mirror, journal
rows), the ``cascade: reject-all`` chaos fault, brownout threshold
tightening, recompile-watchdog coverage of both stages, and the
face-density traffic-mix generator."""

import json

import numpy as np
import pytest

from opencv_facerecognizer_tpu.runtime.connector import FakeConnector
from opencv_facerecognizer_tpu.runtime.fakes import (
    InstantPipeline,
    synthetic_frame_stream,
)
from opencv_facerecognizer_tpu.runtime.faults import BOUNDARIES, FaultInjector
from opencv_facerecognizer_tpu.runtime.recognizer import (
    FRAME_TOPIC,
    RESULT_TOPIC,
    RecognizerService,
)
from opencv_facerecognizer_tpu.utils import metric_names as mn
from opencv_facerecognizer_tpu.utils import tracing
from opencv_facerecognizer_tpu.utils.metrics import Metrics

HW = (32, 32)


def _service(metrics=None, tracer=None, journal=None, faults=None,
             cascade_stub=True, cascade=True, batch_size=8,
             bucket_sizes=(2, 4, 8), max_pending=None, **pipe_kwargs):
    metrics = metrics or Metrics()
    pipeline = InstantPipeline(HW, cascade_stub=cascade_stub,
                               faces_per_frame=1, **pipe_kwargs)
    connector = FakeConnector()
    service = RecognizerService(
        pipeline, connector, batch_size=batch_size, frame_shape=HW,
        flush_timeout=0.02, inflight_depth=2, similarity_threshold=0.0,
        metrics=metrics, tracer=tracer, dead_letter_journal=journal,
        fault_injector=faults, bucket_sizes=bucket_sizes, cascade=cascade)
    if max_pending is not None:
        service.batcher.max_pending = max_pending
    pipeline.prewarm_batch_shapes(service._bucket_ladder, HW,
                                  service.batcher.dtype)
    service._warmed = True
    return pipeline, service, connector, metrics


def _faced(seed=0):
    frame = np.random.default_rng(seed).integers(
        20, 90, size=HW).astype(np.uint8).astype(np.float32)
    frame[8:20, 8:20] = 200.0
    return frame


def _facefree(seed=0):
    return np.random.default_rng(seed).integers(
        20, 90, size=HW).astype(np.uint8).astype(np.float32)


def _drain_stop(service):
    assert service.drain(timeout=20.0)
    service.stop()


# ---- traffic-mix generator -------------------------------------------------


def test_synthetic_frame_stream_density_and_determinism():
    a = synthetic_frame_stream(40, HW, face_density=0.3, seed=11)
    b = synthetic_frame_stream(40, HW, face_density=0.3, seed=11)
    assert len(a) == 40
    # EXACT density (a seeded permutation, not bernoulli): 12 of 40.
    assert sum(1 for _f, k in a if k) == 12
    # Interleaved, not a prefix.
    faced_idx = [i for i, (_f, k) in enumerate(a) if k]
    assert faced_idx != list(range(12))
    for (fa, ka), (fb, kb) in zip(a, b):
        assert ka == kb
        np.testing.assert_array_equal(fa, fb)
    # Face frames carry the bright blob the stub cascade keys on.
    for frame, k in a:
        assert (frame.max() >= 150) == bool(k)


def test_synthetic_frame_stream_jpeg_composes():
    pytest.importorskip("PIL")
    from opencv_facerecognizer_tpu.runtime.ingest import decode_jpeg

    rows = synthetic_frame_stream(6, HW, face_density=0.5, seed=2,
                                  jpeg=True)
    assert len(rows) == 6
    for payload, frame, _k in rows:
        decoded = decode_jpeg(payload)
        assert decoded.shape == frame.shape


# ---- serving gate: settlement, compaction, spans, journal ------------------


def test_cascade_rejects_settle_completed_empty_with_results():
    _pipe, service, connector, metrics = _service()
    results = []
    connector.subscribe(RESULT_TOPIC, lambda t, m: results.append(m))
    service.start(warmup=False)
    for i in range(8):
        frame = _faced(i) if i % 2 == 0 else _facefree(i)
        connector.inject(FRAME_TOPIC, {"frame": frame, "meta": {"seq": i}})
    _drain_stop(service)
    ledger = service.ledger()
    assert ledger["completed"] == 4
    assert ledger["completed_empty"] == 4
    assert ledger["in_system"] == 0
    # Every admitted frame got a result publish; rejected ones are empty
    # and stamped with the exit stage.
    assert len(results) == 8
    by_seq = {m["meta"]["seq"]: m for m in results}
    for i in range(8):
        if i % 2 == 0:
            assert by_seq[i].get("exit") != "cascade"
        else:
            assert by_seq[i]["faces"] == []
            assert by_seq[i]["exit"] == "cascade"


def test_cascade_compaction_dispatches_smaller_bucket():
    """Survivor compaction: a full batch with 2 face frames must reach
    stage 2 as the SMALLEST ladder bucket that fits the survivors, with
    metas still aligned to the right frames."""
    pipe, service, connector, _metrics = _service()
    results = []
    connector.subscribe(RESULT_TOPIC, lambda t, m: results.append(m))
    service.start(warmup=False)
    for i in range(8):
        frame = _faced(i) if i in (1, 6) else _facefree(i)
        connector.inject(FRAME_TOPIC, {"frame": frame, "meta": {"seq": i}})
    _drain_stop(service)
    # 2 survivors out of 8 -> the b2 rung (ladder 2/4/8).
    assert 2 in pipe.batch_sizes_seen
    assert 8 not in pipe.batch_sizes_seen
    faced_seqs = {m["meta"]["seq"] for m in results if m.get("faces")}
    assert faced_seqs == {1, 6}


def test_cascade_full_batch_exit_skips_stage2():
    pipe, service, connector, metrics = _service()
    service.start(warmup=False)
    for i in range(16):
        connector.inject(FRAME_TOPIC, {"frame": _facefree(i),
                                       "meta": {"seq": i}})
    _drain_stop(service)
    assert pipe.dispatches == 0  # stage 2 never ran
    assert pipe.cascade_calls > 0
    c = metrics.counters()
    assert c[mn.FRAMES_COMPLETED_EMPTY] == 16
    assert c[mn.CASCADE_BATCH_EXITS] > 0
    assert c[mn.CASCADE_FRAMES_SCORED] == 16
    # /prom rate gauges reflect the all-rejected stream.
    assert metrics.gauge(mn.CASCADE_REJECT_RATE) == 1.0
    assert metrics.gauge(mn.CASCADE_PASS_RATE) == 0.0


def test_cascade_disabled_by_flag_and_without_gate():
    # --no-cascade: the stub is present but the gate never runs.
    pipe, service, connector, metrics = _service(cascade=False)
    service.start(warmup=False)
    for i in range(8):
        connector.inject(FRAME_TOPIC, {"frame": _facefree(i),
                                       "meta": {"seq": i}})
    _drain_stop(service)
    assert pipe.cascade_calls == 0
    assert pipe.dispatches > 0
    assert metrics.counter(mn.FRAMES_COMPLETED) == 8
    assert metrics.counter(mn.FRAMES_COMPLETED_EMPTY) == 0
    # No gate on the pipeline: cascade=True is the unchanged behavior.
    pipe2, service2, connector2, metrics2 = _service(cascade_stub=False)
    assert not service2._cascade_active
    service2.start(warmup=False)
    connector2.inject(FRAME_TOPIC, {"frame": _facefree(1), "meta": {}})
    _drain_stop(service2)
    assert metrics2.counter(mn.FRAMES_COMPLETED) == 1


def test_cascade_exact_ledger_with_drops_dead_letters_and_spans(tmp_path):
    """The accounting satellite: cascade rejections mixed with a stuck
    readback (dead-letter) and malformed frames must reconcile exactly —
    ledger, settle-span mirror (account_spans incl. completed_empty),
    and journal rows for every drop."""
    from opencv_facerecognizer_tpu.runtime.journal import DeadLetterJournal

    metrics = Metrics()
    tracer = tracing.Tracer(ring_size=1 << 12, sample=1.0)
    journal = DeadLetterJournal(str(tmp_path / "dead.jsonl"),
                                metrics=metrics)
    faults = FaultInjector(seed=3)
    faults.script("readback", "stuck")
    _pipe, service, connector, _ = _service(
        metrics=metrics, tracer=tracer, journal=journal, faults=faults)
    service.resilience.readback_deadline_s = 0.3
    service.start(warmup=False)
    # A full batch of faced frames first: it dispatches and its readback
    # sticks -> dead-letter.
    for i in range(8):
        connector.inject(FRAME_TOPIC, {"frame": _faced(i),
                                       "meta": {"seq": i}})
    # Then a mixed wave (cascade rejects the face-free half) plus two
    # malformed frames (wrong shape).
    for i in range(8, 24):
        frame = _faced(i) if i % 2 else _facefree(i)
        connector.inject(FRAME_TOPIC, {"frame": frame, "meta": {"seq": i}})
    for i in (90, 91):
        connector.inject(FRAME_TOPIC, {"frame": np.zeros((3, 3)),
                                       "meta": {"seq": i}})
    _drain_stop(service)
    ledger = service.ledger()
    assert ledger["in_system"] == 0, ledger
    assert ledger["completed_empty"] == 8
    drops = ledger["drops_by_reason"]
    assert drops[mn.FRAMES_DEAD_LETTERED] == 8
    assert drops[mn.BATCHER_DROPPED_MALFORMED] == 2
    # Settle-span mirror: with sample=1.0 the spans reproduce the ledger
    # exactly, completed_empty included.
    spans = tracer.snapshot(FRAME_TOPIC)
    acct = tracing.account_spans(spans)
    assert acct["completed"] == int(ledger["completed"])
    assert acct["completed_empty"] == 8
    assert acct["drops"] == {k: int(v) for k, v in drops.items()}
    assert acct["traced"] == int(ledger["admitted"])
    # Journal rows cover the dead-lettered frames (cascade rejections are
    # completions, not drops — they must NOT be journaled).
    journal.close()
    rows = [json.loads(line)
            for line in (tmp_path / "dead.jsonl").read_text().splitlines()]
    assert sum(len(r["frames"]) for r in rows
               if r["reason"] == "dead_letter") == 8
    assert not any("cascade" in r["reason"] for r in rows)


def test_cascade_reject_all_chaos_degrades_cleanly():
    """A pathological stage 1 (the ``cascade: reject-all`` fault) must
    degrade to zero matches — every frame settles completed_empty, no
    wedge, no leaked frames, stage 2 never dispatches."""
    assert BOUNDARIES["cascade"] == ("reject_all",)
    faults = FaultInjector(seed=5, rates={"cascade": {"reject_all": 1.0}})
    pipe, service, connector, metrics = _service(faults=faults)
    service.start(warmup=False)
    for i in range(32):
        frame = _faced(i) if i % 2 else _facefree(i)
        connector.inject(FRAME_TOPIC, {"frame": frame, "meta": {"seq": i}})
    _drain_stop(service)
    ledger = service.ledger()
    assert ledger["in_system"] == 0
    assert ledger["completed"] == 0
    assert ledger["completed_empty"] == 32
    assert pipe.dispatches == 0
    assert metrics.counter(mn.FACES_FOUND) == 0
    assert not service.loop_crashed
    assert faults.injected["cascade:reject_all"] > 0


def test_cascade_error_fails_open_to_full_detector():
    pipe, service, connector, metrics = _service()

    def broken(frames):
        raise RuntimeError("stage-1 backend blew up")

    pipe.cascade_scores = broken
    service.start(warmup=False)
    for i in range(8):
        connector.inject(FRAME_TOPIC, {"frame": _facefree(i),
                                       "meta": {"seq": i}})
    _drain_stop(service)
    # Fail OPEN: the full detector served every frame.
    assert metrics.counter(mn.FRAMES_COMPLETED) == 8
    assert metrics.counter(mn.FRAMES_COMPLETED_EMPTY) == 0
    assert metrics.counter(mn.CASCADE_ERRORS) > 0
    assert service.ledger()["in_system"] == 0


def test_cascade_brownout_tightens_threshold():
    from opencv_facerecognizer_tpu.runtime.resilience import BrownoutPolicy

    pipeline = InstantPipeline(HW, cascade_stub=True)
    service = RecognizerService(
        pipeline, FakeConnector(), batch_size=8, frame_shape=HW,
        similarity_threshold=0.0, metrics=Metrics(),
        brownout=BrownoutPolicy(queue_wait_s=0.05),
        cascade_threshold=0.4, cascade_brownout_notch=0.2)
    assert service._effective_cascade_threshold() == 0.4
    service._brownout_level = 1
    assert service._effective_cascade_threshold() == pytest.approx(0.6)
    service._brownout_level = 0
    assert service._effective_cascade_threshold() == 0.4
    # Notch disabled -> no tightening.
    service.cascade_brownout_notch = 0.0
    service._brownout_level = 2
    assert service._effective_cascade_threshold() == 0.4


def test_cascade_recompile_watchdog_covers_stage1():
    pipe, service, connector, metrics = _service()
    service.start(warmup=False)
    # Forget the stage-1 compiles only: the next scored batch must read
    # as a post-warmup recompile even though stage 2 stays warm.
    pipe.compiled_cascade_sigs.clear()
    for i in range(8):
        connector.inject(FRAME_TOPIC, {"frame": _facefree(i),
                                       "meta": {"seq": i}})
    _drain_stop(service)
    assert metrics.counter(mn.RECOMPILES_POST_WARMUP) >= 1


def test_cascade_in_system_counts_empty_completions():
    _pipe, service, connector, _m = _service()
    service.start(warmup=False)
    for i in range(8):
        connector.inject(FRAME_TOPIC, {"frame": _facefree(i),
                                       "meta": {"seq": i}})
    _drain_stop(service)
    assert service.frames_in_system() == 0.0


# ---- registry / plumbing ---------------------------------------------------


def test_cascade_metric_names_registered():
    names = set(mn.all_names())
    for name in (mn.FRAMES_COMPLETED_EMPTY, mn.CASCADE_FRAMES_SCORED,
                 mn.CASCADE_BATCH_EXITS, mn.CASCADE_ERRORS,
                 mn.CASCADE_SCORE, mn.CASCADE_REJECT_RATE,
                 mn.CASCADE_PASS_RATE, mn.CASCADE_THRESHOLD):
        assert name in names
    from tools.ocvf_lint.wiring import ATTR_HINTS, HOT_PATH_SUFFIXES

    assert ATTR_HINTS["cascade"] == "FaceGate"
    assert any(s.endswith("models/cascade.py") for s in HOT_PATH_SUFFIXES)


def test_bench_compare_tracks_cascade_uplift():
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "bench_compare", os.path.join(os.path.dirname(__file__), "..",
                                      "scripts", "bench_compare.py"))
    bench_compare = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench_compare)
    assert "cascade_uplift_density0" in bench_compare.METRICS
    doc = {"cascade": {"uplift": {"d0": {"uplift": 3.1}}}}
    extract = bench_compare.METRICS["cascade_uplift_density0"][0]
    assert extract(doc) == 3.1
    # Regression direction: candidate losing the uplift fails.
    report = bench_compare.compare(doc, {"cascade": {"uplift": {
        "d0": {"uplift": 1.0}}}})
    assert any(r["metric"] == "cascade_uplift_density0"
               and r["verdict"] == "regression" for r in report["metrics"])


def test_cascade_smoke_section_shape():
    """Fast variant of the bench_serving cascade section (the full gated
    run is ``bench_serving.py --smoke``; this keeps tier-1 quick and
    unflaky — structure and ledger exactness, not the timing gates)."""
    import bench_serving

    out = bench_serving.run_cascade_smoke(
        densities=(0.0, 0.3), seconds=0.4, watchdog_seconds=0.25,
        recall=False)
    assert set(out["uplift"]) == {"d0", "d30"}
    for row in out["uplift"].values():
        assert row["cascade_on"]["ledger_in_system_after_drain"] == 0
        assert row["cascade_off"]["ledger_in_system_after_drain"] == 0
        assert row["cascade_off"]["completed_empty"] == 0
    assert out["watchdog_ok"], out["watchdog"]
    assert out["reject_all"]["reject_all_ok"], out["reject_all"]
    assert out["recall"]["skipped"]
    assert "cascade_ok" in out


# ---- stage-1 model ---------------------------------------------------------


def test_tile_targets_mark_face_tiles():
    from opencv_facerecognizer_tpu.models.cascade import tile_targets

    boxes = np.array([[[16, 16, 48, 48], [0, 0, 0, 0]]], np.float32)
    t = tile_targets(boxes, np.array([1]), (96, 96), tile_px=16)
    assert t.shape == (1, 6, 6)
    # Center tile (2, 2) and its 1-tile dilation are positive.
    assert t[0, 2, 2] == 1.0
    assert t[0, 1, 1] == 1.0 and t[0, 3, 3] == 1.0
    assert t[0, 5, 5] == 0.0
    assert t.sum() == 9.0


def test_gate_loss_prefers_correct_tiles():
    import jax.numpy as jnp

    from opencv_facerecognizer_tpu.models.cascade import gate_loss

    targets = np.zeros((1, 4, 4), np.float32)
    targets[0, 1, 1] = 1.0
    good = np.full((1, 4, 4), -5.0, np.float32)
    good[0, 1, 1] = 5.0
    assert float(gate_loss(jnp.asarray(good), jnp.asarray(targets))) < float(
        gate_loss(jnp.asarray(-good), jnp.asarray(targets)))


@pytest.fixture(scope="module")
def trained_gate():
    from opencv_facerecognizer_tpu.models.cascade import FaceGate
    from opencv_facerecognizer_tpu.utils.dataset import make_synthetic_scenes

    scenes, boxes, counts = make_synthetic_scenes(96, (96, 96), max_faces=2,
                                                  seed=3)
    return FaceGate().train(scenes, boxes, counts, steps=300, batch_size=32)


def test_face_gate_separates_scenes(trained_gate):
    from opencv_facerecognizer_tpu.utils.dataset import make_synthetic_scenes

    held, _b, counts = make_synthetic_scenes(48, (96, 96), max_faces=2,
                                             seed=99)
    scores = np.asarray(trained_gate.score_batch(held))
    has = counts > 0
    # Recall-first operating point: EVERY face scene survives the default
    # threshold; most face-free scenes fall below it.
    assert (scores[has] >= trained_gate.threshold).all()
    assert (scores[~has] < trained_gate.threshold).mean() >= 0.75


def test_evaluate_gate_detector_fp_is_not_recall_loss(trained_gate):
    """A detector false positive on a background frame is not a face the
    cascade can lose: with gt_counts it moves out of the recall
    denominator and into detector_fp_suppressed (a precision win)."""
    from opencv_facerecognizer_tpu.models.cascade import evaluate_gate
    from opencv_facerecognizer_tpu.utils.dataset import make_synthetic_scenes

    class FiresEverywhere:
        def detect_batch(self, chunk):
            n = len(chunk)
            return (np.zeros((n, 1, 4)), np.ones((n, 1)),
                    np.ones((n, 1), bool))

    held, _b, counts = make_synthetic_scenes(32, (96, 96), max_faces=2,
                                             seed=99)
    no_gt = evaluate_gate(trained_gate, FiresEverywhere(), held)
    with_gt = evaluate_gate(trained_gate, FiresEverywhere(), held,
                            gt_counts=counts)
    assert with_gt["stage1_recall"] == 1.0
    assert with_gt["detector_fp_frames"] == int((counts == 0).sum())
    assert with_gt["detector_fp_suppressed"] >= 1
    # The label-free form counts every stage-2 firing as detectable, so
    # the same gate scores lower — the conservative direction.
    assert no_gt["stage1_recall"] < with_gt["stage1_recall"]
    assert "detector_fp_frames" not in no_gt


def test_face_gate_save_load_roundtrip(tmp_path, trained_gate):
    from opencv_facerecognizer_tpu.models.cascade import FaceGate
    from opencv_facerecognizer_tpu.utils.dataset import make_synthetic_scenes

    path = str(tmp_path / "gate.msgpack")
    trained_gate.save(path)
    loaded = FaceGate.load(path)
    assert loaded.threshold == trained_gate.threshold
    held, _b, _c = make_synthetic_scenes(8, (96, 96), max_faces=2, seed=5)
    np.testing.assert_allclose(np.asarray(trained_gate.score_batch(held)),
                               np.asarray(loaded.score_batch(held)),
                               atol=1e-6)


def test_real_pipeline_cascade_scores_prewarm_and_serve():
    """The REAL RecognitionPipeline path: cascade_scores compiles
    cache-keyed per rung, warmup() covers both stages, and a service
    over it serves with zero post-warmup recompiles — an untrained gate
    (negative bias init) rejects everything, exercising the full-batch
    early exit + buffer recycle on the real staging path."""
    import jax
    import jax.numpy as jnp

    from opencv_facerecognizer_tpu.models.cascade import FaceGate
    from opencv_facerecognizer_tpu.models.detector import CNNFaceDetector
    from opencv_facerecognizer_tpu.models.embedder import (
        FaceEmbedNet, init_embedder,
    )
    from opencv_facerecognizer_tpu.parallel import ShardedGallery, make_mesh
    from opencv_facerecognizer_tpu.parallel.pipeline import (
        RecognitionPipeline,
    )

    det = CNNFaceDetector(features=(8, 16), head_features=8, max_faces=2,
                          space_to_depth=4)
    det.load_params(det.net.init(jax.random.PRNGKey(0),
                                 jnp.zeros((1, *HW)))["params"])
    net = FaceEmbedNet(embed_dim=8, stem_features=4, stage_features=(4,),
                       stage_blocks=(1,))
    emb_params = init_embedder(net, num_classes=2, input_shape=(8, 8),
                               seed=0)["net"]
    gallery = ShardedGallery(capacity=16, dim=8, mesh=make_mesh(tp=8))
    gallery.add(np.random.default_rng(0).normal(size=(4, 8)).astype(
        np.float32), np.arange(4, dtype=np.int32))
    gate = FaceGate(features=(4, 8))
    gate.load_params(gate.net.init(jax.random.PRNGKey(1),
                                   jnp.zeros((1, *HW)))["params"])
    pipeline = RecognitionPipeline(det, net, emb_params, gallery,
                                   face_size=(8, 8), cascade=gate)
    metrics = Metrics()
    connector = FakeConnector()
    service = RecognizerService(
        pipeline, connector, batch_size=4, frame_shape=HW,
        flush_timeout=0.02, similarity_threshold=0.0, metrics=metrics,
        bucket_sizes=(2, 4))
    service.start(warmup=True)  # compiles ladder + BOTH cascade stages
    try:
        assert len(pipeline._cascade_cache) == 2  # one per rung
        for i in range(8):
            connector.inject(FRAME_TOPIC, {"frame": _facefree(i),
                                           "meta": {"seq": i}})
        assert service.drain(timeout=30.0)
    finally:
        service.stop()
    ledger = service.ledger()
    assert ledger["in_system"] == 0
    # Untrained gate (bias -2.0): every frame scores face-unlikely and
    # early-exits; no stage-2 dispatch, no post-warmup recompiles.
    assert ledger["completed_empty"] == 8
    assert metrics.counter(mn.RECOMPILES_POST_WARMUP) == 0
