"""Native C++ loader vs PIL oracle (native/ocvf_loader.cpp via utils.native).

Builds the .so on first use (g++ is in the image); if the toolchain were
ever absent, utils.native reports unavailable and read_images falls back to
PIL — the skip below keeps the suite honest about which path ran.
"""

import os
import struct

import numpy as np
import pytest

from opencv_facerecognizer_tpu.utils import native

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native loader unavailable (no g++?)"
)

RNG = np.random.default_rng(7)


def _write_pgm(path, img, maxval=255):
    h, w = img.shape
    with open(path, "wb") as f:
        f.write(f"P5\n# comment\n{w} {h}\n{maxval}\n".encode())
        if maxval > 255:
            f.write(img.astype(">u2").tobytes())
        else:
            f.write(img.astype(np.uint8).tobytes())


def _write_ppm(path, rgb):
    h, w, _ = rgb.shape
    with open(path, "wb") as f:
        f.write(f"P6\n{w} {h}\n255\n".encode())
        f.write(rgb.astype(np.uint8).tobytes())


def _write_bmp24(path, rgb):
    h, w, _ = rgb.shape
    row = (w * 3 + 3) & ~3
    data_size = row * h
    with open(path, "wb") as f:
        f.write(b"BM")
        f.write(struct.pack("<IHHI", 54 + data_size, 0, 0, 54))
        f.write(struct.pack("<IiiHHIIiiII", 40, w, h, 1, 24, 0, data_size,
                            2835, 2835, 0, 0))
        pad = b"\x00" * (row - w * 3)
        for y in range(h - 1, -1, -1):  # bottom-up
            bgr = rgb[y, :, ::-1].astype(np.uint8).tobytes()
            f.write(bgr + pad)


def test_pgm_roundtrip_exact(tmp_path):
    img = RNG.integers(0, 256, size=(37, 29)).astype(np.uint8)
    p = str(tmp_path / "a.pgm")
    _write_pgm(p, img)
    out = native.load_gray(p)
    np.testing.assert_array_equal(out, img.astype(np.float32))


def test_pgm_16bit_scales_to_255(tmp_path):
    img = RNG.integers(0, 65536, size=(16, 16)).astype(np.uint16)
    p = str(tmp_path / "a16.pgm")
    _write_pgm(p, img, maxval=65535)
    out = native.load_gray(p)
    np.testing.assert_allclose(out, img * (255.0 / 65535.0), atol=1e-3)


def test_ppm_luminance_matches_pil(tmp_path):
    from PIL import Image

    rgb = RNG.integers(0, 256, size=(24, 31, 3)).astype(np.uint8)
    p = str(tmp_path / "c.ppm")
    _write_ppm(p, rgb)
    out = native.load_gray(p)
    with Image.open(p) as im:
        ref = np.asarray(im.convert("L"), np.float32)
    # PIL rounds to uint8; we keep float — allow 1 level
    np.testing.assert_allclose(out, ref, atol=1.0)


def test_bmp_matches_pil(tmp_path):
    from PIL import Image

    rgb = RNG.integers(0, 256, size=(20, 26, 3)).astype(np.uint8)
    p = str(tmp_path / "d.bmp")
    _write_bmp24(p, rgb)
    out = native.load_gray(p)
    with Image.open(p) as im:
        ref = np.asarray(im.convert("L"), np.float32)
    assert out.shape == ref.shape
    np.testing.assert_allclose(out, ref, atol=1.0)


def test_fused_resize_matches_separate(tmp_path):
    from opencv_facerecognizer_tpu.utils.dataset import _resize_gray

    img = RNG.integers(0, 256, size=(70, 60)).astype(np.uint8)
    p = str(tmp_path / "r.pgm")
    _write_pgm(p, img)
    out = native.load_gray(p, size=(32, 32))
    assert out.shape == (32, 32)
    ref = _resize_gray(img.astype(np.float32), (32, 32))
    # Same half-pixel bilinear convention as PIL: small interpolation slack
    np.testing.assert_allclose(out, ref, atol=2.0)


def test_load_batch_packs_and_flags_failures(tmp_path):
    imgs = [RNG.integers(0, 256, size=(40, 40)).astype(np.uint8)
            for _ in range(3)]
    paths = []
    for i, img in enumerate(imgs):
        p = str(tmp_path / f"s{i}.pgm")
        _write_pgm(p, img)
        paths.append(p)
    bad = str(tmp_path / "bad.pgm")
    open(bad, "wb").write(b"P5\nnot really\n")
    paths.insert(2, bad)
    batch, ok = native.load_batch(paths, (40, 40))
    assert batch.shape == (4, 40, 40)
    np.testing.assert_array_equal(ok, [True, True, False, True])
    np.testing.assert_array_equal(batch[0], imgs[0].astype(np.float32))
    np.testing.assert_array_equal(batch[3], imgs[2].astype(np.float32))


def test_read_images_uses_native_path(tmp_path):
    from opencv_facerecognizer_tpu.utils.dataset import read_images

    for subj in ("alice", "bob"):
        d = tmp_path / subj
        d.mkdir()
        for i in range(3):
            _write_pgm(str(d / f"{i}.pgm"),
                       RNG.integers(0, 256, size=(50, 44)).astype(np.uint8))
    X, y, names = read_images(str(tmp_path), image_size=(32, 32))
    assert X.shape == (6, 32, 32) and names == ["alice", "bob"]
    np.testing.assert_array_equal(np.unique(y), [0, 1])


def test_malformed_inputs_rejected():
    assert native.decode_gray(b"") is None
    assert native.decode_gray(b"P5\n10 10\n255\nshort") is None
    assert native.decode_gray(b"\x89PNG\r\n") is None  # unsupported magic
    # truncated BMP header
    assert native.decode_gray(b"BM" + b"\x00" * 20) is None


def test_decoder_fuzz_no_crash():
    """The C++ decoder must fail closed (None), never crash, on arbitrary
    bytes — including buffers that start with valid magic numbers."""
    rng = np.random.default_rng(0)
    for i in range(300):
        n = int(rng.integers(0, 2048))
        buf = bytes(rng.integers(0, 256, size=n, dtype=np.uint8))
        for prefix in (b"", b"P5\n", b"P6\n", b"P2\n", b"BM"):
            out = native.decode_gray(prefix + buf, size=(16, 16))
            assert out is None or out.shape == (16, 16)
    # headers that declare more pixels than the buffer holds
    assert native.decode_gray(b"P5\n60000 60000\n255\n\x00") is None
    assert native.decode_gray(b"P5\n4 4\n65535\n" + b"\x00" * 8) is None
