"""Ingest-pipeline suite (ISSUE 12): the pre-allocated staging ring (zero
steady-state allocations, exhaustion backpressure), uint8 end-to-end
staging with the recompile watchdog green, compressed-frame intake through
the off-thread decode pool (corrupt payloads dead-letter with exact ledger
settlement), the ``decode: slow``/``decode: corrupt`` chaos pair, the
``--transfer-uint8`` deprecation alias, and the bench_compare tracking of
the ingest gate's numbers.

Everything runs over ``runtime.fakes.InstantPipeline`` — the ingest layer
is host-side control flow; nothing here needs hardware.
"""

import time
import warnings

import numpy as np
import pytest

from opencv_facerecognizer_tpu.runtime import (
    AdmissionController,
    FakeConnector,
    FaultInjector,
    IngestConfig,
    RecognizerService,
    ResiliencePolicy,
    StagingRing,
    resolve_ingest_mode,
)
from opencv_facerecognizer_tpu.runtime import ingest as ingest_mod
from opencv_facerecognizer_tpu.runtime.fakes import (
    InstantPipeline,
    synthetic_jpeg_frames,
)
from opencv_facerecognizer_tpu.runtime.ingest import (
    decode_jpeg,
    encode_jpeg,
    encode_jpeg_message,
    jpeg_supported,
)
from opencv_facerecognizer_tpu.runtime.recognizer import (
    FRAME_TOPIC,
    RESULT_TOPIC,
)
from opencv_facerecognizer_tpu.utils import metric_names as mn
from opencv_facerecognizer_tpu.utils.metrics import Metrics

FRAME_HW = (16, 16)

needs_jpeg = pytest.mark.skipif(not jpeg_supported(),
                                reason="no JPEG codec (PIL/cv2) available")


def _wait(cond, timeout=10.0, interval=0.01) -> bool:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return cond()


def _frame():
    return np.zeros(FRAME_HW, np.float32)


def _service(pipeline=None, **kwargs):
    pipeline = pipeline or InstantPipeline(FRAME_HW)
    connector = FakeConnector()
    kwargs.setdefault("batch_size", 4)
    kwargs.setdefault("metrics", Metrics())
    kwargs.setdefault("resilience", ResiliencePolicy(readback_deadline_s=2.0))
    service = RecognizerService(
        pipeline, connector, frame_shape=FRAME_HW,
        flush_timeout=0.02, similarity_threshold=0.0, **kwargs,
    )
    return pipeline, service, connector


def _assert_settled(service):
    ledger = service.ledger()
    assert ledger["in_system"] == 0, ledger


# ---------- StagingRing ----------


def test_staging_ring_preallocates_per_rung_and_recycles():
    metrics = Metrics()
    ring = StagingRing([4, 8], FRAME_HW, np.uint8, depth=2, metrics=metrics)
    assert ring.preallocated == 4
    assert metrics.counter(mn.INGEST_STAGING_ALLOCS) == 4
    # Smallest fitting rung wins; the buffer is rung-sized, not padded.
    buf = ring.acquire(3)
    assert buf.shape == (4, *FRAME_HW) and buf.dtype == np.uint8
    big = ring.acquire(5)
    assert big.shape == (8, *FRAME_HW)
    ring.release(buf)
    again = ring.acquire(2)
    assert again is not None and again.shape == (4, *FRAME_HW)
    assert ring.alloc_count == ring.preallocated  # recycled, no new alloc
    assert metrics.counter(mn.INGEST_STAGING_REUSE) >= 3
    # Foreign shapes/dtypes are dropped silently, like the legacy pool.
    ring.release(np.zeros((4, 3, 3), np.uint8))
    ring.release(np.zeros((4, *FRAME_HW), np.float32))
    assert ring.stats()["free"] == {4: 1, 8: 1}


def test_staging_ring_exhaustion_never_allocates_and_heals_on_forfeit():
    metrics = Metrics()
    ring = StagingRing([4], FRAME_HW, np.uint8, depth=1, metrics=metrics)
    held = ring.acquire(4)
    assert held is not None
    # Every buffer in flight: acquire refuses (backpressure), no alloc.
    assert ring.acquire(1) is None
    assert ring.alloc_count == ring.preallocated
    assert metrics.counter(mn.INGEST_STAGING_EXHAUSTED) == 1
    assert ring.free_slots() == 0
    # A release notification wakes parked consumers.
    woken = []
    ring.add_notify(lambda: woken.append(1))
    ring.release(held)
    assert woken == [1]
    assert ring.acquire(1) is not None
    # Forfeit (dead-letter path): the lost buffer opens ONE replacement
    # allocation credit — the ring heals instead of shrinking forever.
    lost = ring.acquire(4)
    assert lost is None  # still held by the earlier acquire
    ring.forfeit(held)
    replacement = ring.acquire(4)
    assert replacement is not None and replacement is not held
    assert ring.alloc_count == ring.preallocated + 1
    assert metrics.counter(mn.INGEST_STAGING_FORFEITS) == 1
    assert metrics.counter(mn.INGEST_STAGING_ALLOCS) == ring.preallocated + 1


def test_batcher_rejects_mismatched_ring():
    from opencv_facerecognizer_tpu.runtime.batcher import FrameBatcher

    ring = StagingRing([4], FRAME_HW, np.uint8, depth=1)
    with pytest.raises(ValueError):
        FrameBatcher(4, FRAME_HW, dtype=np.float32, staging_ring=ring)
    with pytest.raises(ValueError):
        FrameBatcher(8, FRAME_HW, dtype=np.uint8, staging_ring=ring)


# ---------- uint8 mode end-to-end ----------


def test_uint8_mode_zero_steady_state_allocs_and_watchdog_green():
    metrics = Metrics()
    pipeline, service, connector = _service(
        metrics=metrics, ingest=IngestConfig(mode="uint8"))
    assert service.batcher.dtype == np.uint8
    # warmup() prewarms the ladder at the INGEST dtype (the uint8 entry
    # signatures), then the watchdog arms — mirrored here without jax.
    pipeline.prewarm_batch_shapes(service._bucket_ladder, FRAME_HW,
                                  service.batcher.dtype)
    service._warmed = True
    service.start(warmup=False)
    try:
        for i in range(64):
            connector.inject(FRAME_TOPIC, {"frame": _frame(),
                                           "meta": {"seq": i}})
        assert service.drain(timeout=20.0)
    finally:
        service.stop()
    c = metrics.counters()
    assert c[mn.FRAMES_COMPLETED] == 64
    # The acceptance assertion: steady-state staging allocated NOTHING
    # beyond the ring's construction-time preallocation, and every
    # dispatch was a jit-cache hit at the uint8 signature.
    assert c[mn.INGEST_STAGING_ALLOCS] == service.ingest.staging.preallocated
    assert c[mn.INGEST_STAGING_REUSE] > 0
    assert c.get(mn.RECOMPILES_POST_WARMUP, 0) == 0
    assert c[mn.INGEST_UPLOAD_BYTES] > 0  # frames crossed as uint8
    _assert_settled(service)


def test_f32_prewarm_with_uint8_serving_trips_watchdog():
    """The dtype IS a compile signature: prewarming only f32 while the
    ingest mode stages uint8 must read as a post-warmup recompile — the
    exact hole the uint8 prewarm coverage exists to close."""
    metrics = Metrics()
    pipeline, service, connector = _service(
        metrics=metrics, ingest=IngestConfig(mode="uint8"))
    pipeline.prewarm_batch_shapes(service._bucket_ladder, FRAME_HW,
                                  np.float32)  # the WRONG dtype
    service._warmed = True
    service.start(warmup=False)
    try:
        connector.inject(FRAME_TOPIC, {"frame": _frame(), "meta": {}})
        assert service.drain(timeout=10.0)
    finally:
        service.stop()
    assert metrics.counter(mn.RECOMPILES_POST_WARMUP) >= 1


# ---------- compressed-frame intake ----------


@needs_jpeg
def test_synthetic_jpeg_generator_is_seeded_and_roundtrips():
    a = synthetic_jpeg_frames(3, FRAME_HW, seed=5, faces_per_frame=1)
    b = synthetic_jpeg_frames(3, FRAME_HW, seed=5, faces_per_frame=1)
    assert [p for p, _ in a] == [p for p, _ in b]  # byte-identical
    assert [p for p, _ in a] != [
        p for p, _ in synthetic_jpeg_frames(3, FRAME_HW, seed=6,
                                            faces_per_frame=1)]
    payload, src = a[0]
    decoded = decode_jpeg(payload)
    assert decoded.shape == FRAME_HW
    # Lossy but close: the decoded frame is the source frame, not noise.
    assert float(np.abs(decoded.astype(np.int32)
                        - src.astype(np.int32)).mean()) < 16.0


@needs_jpeg
def test_jpeg_intake_decodes_off_thread_and_completes():
    metrics = Metrics()
    from opencv_facerecognizer_tpu.utils.tracing import Tracer

    tracer = Tracer(sample=1.0)
    pipeline, service, connector = _service(
        metrics=metrics, tracer=tracer, ingest=IngestConfig(mode="jpeg"))
    service.start(warmup=False)
    n = 16
    try:
        for i, (payload, _src) in enumerate(
                synthetic_jpeg_frames(n, FRAME_HW, seed=2)):
            connector.inject(FRAME_TOPIC, {**encode_jpeg_message(payload),
                                           "meta": {"seq": i}})
        assert service.drain(timeout=20.0)
    finally:
        service.stop()
    c = metrics.counters()
    assert c[mn.DECODE_FRAMES] == n
    assert c[mn.FRAMES_COMPLETED] == n
    assert not np.isnan(metrics.percentile(mn.DECODE_LATENCY, 50))
    # Every frame carries a decode span off the connector thread.
    spans = [s for s in tracer.snapshot(topic=FRAME_TOPIC)
             if s["stage"] == "decode"]
    assert len(spans) == n and all(s["ok"] for s in spans)
    assert len(connector.messages(RESULT_TOPIC)) == n
    _assert_settled(service)


@needs_jpeg
def test_corrupt_jpeg_dead_letters_with_exact_settlement(tmp_path):
    from opencv_facerecognizer_tpu.runtime import DeadLetterJournal
    from opencv_facerecognizer_tpu.utils.tracing import Tracer

    metrics = Metrics()
    tracer = Tracer(sample=1.0)
    journal = DeadLetterJournal(str(tmp_path / "dead.jsonl"),
                                metrics=metrics)
    pipeline, service, connector = _service(
        metrics=metrics, tracer=tracer, dead_letter_journal=journal,
        ingest=IngestConfig(mode="jpeg"))
    service.start(warmup=False)
    good = synthetic_jpeg_frames(4, FRAME_HW, seed=9)
    try:
        for i, (payload, _src) in enumerate(good):
            connector.inject(FRAME_TOPIC, {**encode_jpeg_message(payload),
                                           "meta": {"seq": i}})
        # Truncated and garbage payloads: both must dead-letter.
        connector.inject(FRAME_TOPIC, {
            **encode_jpeg_message(good[0][0][:12]), "meta": {"seq": 96}})
        connector.inject(FRAME_TOPIC, {
            **encode_jpeg_message(b"not a jpeg"), "meta": {"seq": 97}})
        assert service.drain(timeout=20.0)
    finally:
        service.stop()
        journal.close()
    c = metrics.counters()
    assert c[mn.FRAMES_COMPLETED] == 4
    assert c[mn.FRAMES_DROPPED_DECODE] == 2
    assert c[mn.DECODE_ERRORS] == 2
    _assert_settled(service)  # admitted == completed + drops, exactly
    # Journal rows carry the decode_error reason + the frame's meta.
    records = [r for r in journal.records() if r["reason"] == "decode_error"]
    assert len(records) == 2
    seqs = {e["meta"]["seq"] for r in records for e in r["frames"]}
    assert seqs == {96, 97}
    assert all(e["stage"] == "ingest.decode"
               for r in records for e in r["frames"])
    # Terminal spans mirror the ledger split.
    outcomes = [s.get("outcome") for s in tracer.snapshot(topic=FRAME_TOPIC)
                if s["stage"] == "settle"]
    assert outcomes.count(mn.FRAMES_DROPPED_DECODE) == 2
    assert outcomes.count("completed") == 4


@needs_jpeg
def test_decode_fault_pair_slow_and_corrupt_chaos():
    """The fast chaos variant of the ``decode`` boundary: one scripted
    slow decode (completes, just late — absorbed off the hot thread) and
    one scripted corrupt decode (dead-letters), with the injector's
    counts matching the metrics exactly."""
    injector = FaultInjector(slow_decode_s=0.15)
    injector.script("decode", "slow", "corrupt")
    metrics = Metrics()
    pipeline, service, connector = _service(
        metrics=metrics, fault_injector=injector,
        ingest=IngestConfig(mode="jpeg", decode_workers=1))
    service.start(warmup=False)
    payloads = synthetic_jpeg_frames(3, FRAME_HW, seed=4)
    t0 = time.monotonic()
    try:
        for i, (payload, _src) in enumerate(payloads):
            connector.inject(FRAME_TOPIC, {**encode_jpeg_message(payload),
                                           "meta": {"seq": i}})
        assert service.drain(timeout=20.0)
    finally:
        service.stop()
    assert time.monotonic() - t0 >= 0.15  # the slow fault really stalled
    c = metrics.counters()
    assert injector.injected == {"decode:slow": 1, "decode:corrupt": 1}
    assert c[mn.FRAMES_COMPLETED] == 2  # slow one still completed
    assert c[mn.FRAMES_DROPPED_DECODE] == 1
    _assert_settled(service)


@needs_jpeg
def test_decode_backlog_overflow_is_an_explicit_ledger_drop():
    metrics = Metrics()
    injector = FaultInjector(slow_decode_s=0.2)
    injector.script("decode", *["slow"] * 8)
    pipeline, service, connector = _service(
        metrics=metrics, fault_injector=injector,
        ingest=IngestConfig(mode="jpeg", decode_workers=1, decode_queue=2))
    service.start(warmup=False)
    payloads = synthetic_jpeg_frames(8, FRAME_HW, seed=7)
    try:
        for i, (payload, _src) in enumerate(payloads):
            connector.inject(FRAME_TOPIC, {**encode_jpeg_message(payload),
                                           "meta": {"seq": i}})
        assert service.drain(timeout=30.0)
    finally:
        service.stop()
    c = metrics.counters()
    assert c[mn.FRAMES_DROPPED_DECODE] >= 1  # backlog overflow, counted
    _assert_settled(service)


@needs_jpeg
def test_raising_sink_never_kills_a_decode_worker():
    """A raising intake continuation (journal IOError under stress, a
    brownout-path bug) must cost that FRAME — settled through on_error —
    never the worker thread: a dead pool with submit() still accepting
    would silently stop all camera traffic."""
    from opencv_facerecognizer_tpu.runtime import DecodeWorkerPool
    from opencv_facerecognizer_tpu.runtime.ingest import encode_jpeg_message

    metrics = Metrics()
    pool = DecodeWorkerPool(workers=1, metrics=metrics)
    settled = []

    def bad_sink(frame, message, priority, tid):
        raise RuntimeError("intake bug")

    def on_error(message, priority, tid, reason):
        settled.append((message.get("meta"), reason))
        if len(settled) == 2:
            raise RuntimeError("settlement bug too")  # worker survives this

    pool.start(bad_sink, on_error)
    try:
        payloads = synthetic_jpeg_frames(3, FRAME_HW, seed=8)
        for i, (p, _src) in enumerate(payloads):
            assert pool.submit({**encode_jpeg_message(p),
                                "meta": {"seq": i}}, 0, 0)
        assert _wait(pool.idle, timeout=10.0)
    finally:
        pool.stop()
    # Every frame hit the failing sink; each one was routed to on_error
    # (even after on_error itself raised once) and the worker outlived
    # all of it.
    assert [m["seq"] for m, _r in settled] == [0, 1, 2]
    assert all(r == "decode_error" for _m, r in settled)
    assert metrics.counter(mn.DECODE_ERRORS) >= 3


def test_publish_crash_recycles_the_staging_buffer():
    """A publish crash after a COMPLETED readback must return the
    staging buffer to the bounded ring — dropping it would shrink the
    ring by one per crash (no heal credit) until every frame sheds
    against a ring that can never refill."""
    from opencv_facerecognizer_tpu.runtime.recognizer import STATUS_TOPIC

    class ExplodingConnector(FakeConnector):
        explode = True

        def publish(self, topic, message):
            if topic == RESULT_TOPIC and self.explode:
                raise RuntimeError("result sink down")
            super().publish(topic, message)

    metrics = Metrics()
    connector = ExplodingConnector()
    service = RecognizerService(
        InstantPipeline(FRAME_HW), connector, batch_size=4,
        frame_shape=FRAME_HW, flush_timeout=0.02, similarity_threshold=0.0,
        metrics=metrics,
        resilience=ResiliencePolicy(readback_deadline_s=2.0),
        ingest=IngestConfig(mode="uint8", ring_depth=1))
    service.start(warmup=False)
    try:
        connector.inject(FRAME_TOPIC, {"frame": _frame(), "meta": {"seq": 0}})
        assert _wait(lambda: service.loop_crashed, timeout=10.0)
        # The crash path recycled: the depth-1 ring is whole again.
        assert _wait(lambda: service.ingest.staging.free_slots() == 1,
                     timeout=5.0)
        assert service.ingest.staging.alloc_count == 1
        # And after the supervisor-style restart, the SAME buffer serves.
        connector.explode = False
        service.restart_loop()
        connector.inject(FRAME_TOPIC, {"frame": _frame(), "meta": {"seq": 1}})
        assert _wait(lambda: metrics.counter(mn.FRAMES_COMPLETED) >= 1,
                     timeout=10.0)
    finally:
        service.stop()
    assert any(m.get("status") == "crashed"
               for m in connector.messages(STATUS_TOPIC))


# ---------- ring exhaustion under flood -> admission backpressure ----------


def test_ring_exhaustion_floods_backpressure_through_admission():
    """Flood a slow backend with a depth-1 ring: in-flight batches hold
    every staging buffer, the exhausted ring keeps new batches queued,
    and admission rejects at the front door with reason ``staging`` —
    zero allocations beyond the preallocation, exact settlement after."""
    metrics = Metrics()
    pipeline, service, connector = _service(
        pipeline=InstantPipeline(FRAME_HW, compute_s=0.15),
        metrics=metrics, inflight_depth=4,
        admission=AdmissionController(),
        ingest=IngestConfig(mode="uint8", ring_depth=1))
    assert (service.admission.staging_free_fn.__self__
            is service.ingest.staging)
    service.start(warmup=False)
    offered = 0
    staging_reason = mn.FRAMES_REJECTED_PREFIX + "staging"
    try:
        # Opening burst: admitted while the ring still has its one free
        # buffer, so several batches' worth QUEUE — the consumer then
        # finds the ring exhausted and waits, never allocates. (The
        # exhaustion-episode COUNTER is pinned by the deterministic ring
        # unit tests above; asserting it here would race serve-loop
        # scheduling on a noisy box.)
        for _ in range(16):
            connector.inject(FRAME_TOPIC, {"frame": _frame(),
                                           "meta": {"seq": offered}})
            offered += 1
        # Paced flood until the front door demonstrably closed: each
        # in-flight batch holds the only buffer for compute_s at a time,
        # so offers keep landing while free_slots == 0 until admission
        # rejects one with reason ``staging`` — deadline-bounded instead
        # of a fixed count, so a scheduler stall between batches cannot
        # let every offer slip through a momentarily-free ring.
        deadline = time.monotonic() + 20.0
        while (metrics.counter(staging_reason) == 0
               and time.monotonic() < deadline):
            connector.inject(FRAME_TOPIC, {"frame": _frame(),
                                           "meta": {"seq": offered}})
            offered += 1
            time.sleep(0.005)
        assert service.drain(timeout=60.0)
    finally:
        service.stop()
    c = metrics.counters()
    rejected = c.get(staging_reason, 0)
    assert rejected > 0, c
    # Never an allocation: the flood was absorbed by shedding, not memory.
    assert c[mn.INGEST_STAGING_ALLOCS] == service.ingest.staging.preallocated
    assert c[mn.FRAMES_COMPLETED] + rejected == offered
    _assert_settled(service)


# ---------- --transfer-uint8 deprecation alias ----------


def test_transfer_uint8_flag_aliases_to_uint8_ingest_mode():
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        assert resolve_ingest_mode(None, transfer_uint8=True) == "uint8"
    assert any(issubclass(w.category, DeprecationWarning) for w in caught)
    # An explicit --ingest-mode always wins over the legacy alias.
    assert resolve_ingest_mode("jpeg", transfer_uint8=True,
                               warn=False) == "jpeg"
    assert resolve_ingest_mode(None, transfer_uint8=False) == "f32"
    with pytest.raises(ValueError):
        resolve_ingest_mode("bf16")
    # The CLI wires the alias through build_parser -> IngestConfig.
    from opencv_facerecognizer_tpu.apps.recognize import build_parser

    args = build_parser().parse_args(
        ["--model", "m", "--detector", "d", "--gallery", "g",
         "--transfer-uint8"])
    assert args.ingest_mode is None and args.transfer_uint8
    mode = resolve_ingest_mode(args.ingest_mode, args.transfer_uint8,
                               warn=False)
    cfg = IngestConfig(mode=mode, ring_depth=args.ingest_ring_depth or None,
                       decode_workers=args.ingest_decode_workers)
    assert cfg.transfer_dtype == np.uint8
    assert cfg.ring_depth is None  # CLI default 0 = auto-size


def test_ring_depth_auto_sizes_to_cover_pipeline_overlap():
    """The default (auto) ring depth must never cap overlap below the
    in-flight window: every overlapped batch holds a buffer, plus the
    batch being assembled — inflight_depth + 2 per rung. An explicit
    depth is honored as given."""
    assert IngestConfig(mode="uint8").resolve_ring_depth(4) == 6
    assert IngestConfig(mode="uint8", ring_depth=1).resolve_ring_depth(4) == 1
    pipeline, service, connector = _service(
        inflight_depth=3, ingest=IngestConfig(mode="uint8"))
    assert service.ingest.staging.depth == 5


def test_free_slots_tracks_the_top_rung_only():
    """The admission 'staging' signal is the TOP rung's availability:
    acquire only falls upward, so small-rung buffers can never stage a
    full batch — counting them would leave the front door open while
    every full-batch flush is parked."""
    ring = StagingRing([4, 8], FRAME_HW, np.uint8, depth=1)
    assert ring.free_slots() == 1  # one top-rung buffer, not two buffers
    held = ring.acquire(8)
    assert ring.free_slots() == 0  # the rung-4 buffer doesn't count
    assert ring.acquire(2) is not None  # ...but partial batches still stage
    ring.forfeit(held)
    assert ring.free_slots() == 1  # heal credit: not wedged


def test_exhaustion_counts_episodes_not_polls():
    metrics = Metrics()
    ring = StagingRing([4], FRAME_HW, np.uint8, depth=1, metrics=metrics)
    ring.acquire(4)
    assert ring.acquire(4) is None  # episode starts: counted
    for _ in range(10):  # the parked consumer's re-checks: quiet
        assert ring.acquire(4, quiet=True) is None
    assert metrics.counter(mn.INGEST_STAGING_EXHAUSTED) == 1


def test_transfer_uint8_alias_routes_through_the_staging_ring():
    """The regression pin: the old flag's path IS the new path — uint8
    staging rides the pre-allocated ring (the fresh-allocation staging
    behind the 118 ms p99 is structurally unreachable), and the batcher
    never allocates a batch array once the ring is warm."""
    cfg = IngestConfig(mode=resolve_ingest_mode(None, transfer_uint8=True,
                                                warn=False))
    metrics = Metrics()
    pipeline, service, connector = _service(metrics=metrics, ingest=cfg)
    assert service.batcher._ring is service.ingest.staging
    assert service.batcher.dtype == np.uint8
    service.start(warmup=False)
    try:
        for i in range(24):
            connector.inject(FRAME_TOPIC, {"frame": _frame(),
                                           "meta": {"seq": i}})
        assert service.drain(timeout=20.0)
    finally:
        service.stop()
    c = metrics.counters()
    assert c[mn.FRAMES_COMPLETED] == 24
    assert c[mn.INGEST_STAGING_ALLOCS] == service.ingest.staging.preallocated
    _assert_settled(service)


# ---------- registry / wiring / bench plumbing ----------


def test_ingest_metric_names_registered_and_in_ledger():
    names = set(mn.all_names())
    for name in (mn.INGEST_STAGING_ALLOCS, mn.INGEST_STAGING_REUSE,
                 mn.INGEST_STAGING_EXHAUSTED, mn.INGEST_STAGING_FORFEITS,
                 mn.INGEST_STAGING_FREE, mn.INGEST_UPLOAD,
                 mn.INGEST_UPLOAD_BYTES, mn.DECODE_LATENCY,
                 mn.DECODE_QUEUE_DEPTH, mn.DECODE_FRAMES, mn.DECODE_ERRORS,
                 mn.FRAMES_DROPPED_DECODE):
        assert name in names
    assert mn.FRAMES_DROPPED_DECODE in RecognizerService.LEDGER_DROP_COUNTERS


def test_lint_wiring_knows_the_ingest_attrs():
    from tools.ocvf_lint.wiring import ATTR_HINTS, HOT_PATH_SUFFIXES

    assert ATTR_HINTS["ingest"] == "IngestPipeline"
    assert ATTR_HINTS["staging"] == "StagingRing"
    assert ATTR_HINTS["decoder"] == "DecodeWorkerPool"
    assert any(s.endswith("runtime/ingest.py") for s in HOT_PATH_SUFFIXES)


def test_bench_compare_tracks_ingest_metrics():
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "bench_compare", os.path.join(os.path.dirname(__file__), "..",
                                      "scripts", "bench_compare.py"))
    bench_compare = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench_compare)

    def artifact(p99, uplift):
        return {"ingest": {
            "h2d": {"32": {"uint8_ring": {"p99_ms": p99}}},
            "uplift": {"b32": {"uplift": uplift}}}}

    # Self-compare: exact zero regression.
    report = bench_compare.compare(artifact(0.5, 2.0), artifact(0.5, 2.0))
    verdicts = {r["metric"]: r["verdict"] for r in report["metrics"]}
    assert verdicts["ingest_h2d_p99_ms"] == "ok"
    assert verdicts["ingest_completed_uplift"] == "ok"
    # A blown p99 tail or lost uplift is a tracked regression.
    report = bench_compare.compare(artifact(0.5, 2.0), artifact(5.0, 2.0))
    assert not report["ok"]
    report = bench_compare.compare(artifact(0.5, 2.0), artifact(0.5, 1.0))
    assert not report["ok"]
    # The candidate silently dropping the measurement fails structurally.
    report = bench_compare.compare(artifact(0.5, 2.0), {})
    assert not report["ok"]


@needs_jpeg
def test_ingest_smoke_section_shape():
    """A miniature run of the smoke's ingest section: structure + the
    load-bearing verdicts exist (the full-size gate runs in
    ``bench_serving.py --smoke``; this keeps tier-1 fast and unflaky)."""
    import bench_serving

    out = bench_serving.run_ingest_smoke(
        rungs=(4, 8), frame_hw=FRAME_HW, h2d_iters=48, h2d_warmup=8,
        uplift_batches=(8,), uplift_seconds=0.5, uplift_frame_hw=(64, 64),
        uplift_h2d_gb_s=0.005, jpeg_frames=8)
    for rung in ("4", "8"):
        row = out["h2d"][rung]
        for arm in ("f32_fresh", "uint8_unpinned", "uint8_ring"):
            assert row[arm]["p50_ms"] > 0
        assert row["f32_fresh"]["bytes_per_frame"] == (
            4 * row["uint8_ring"]["bytes_per_frame"])
    b8 = out["uplift"]["b8"]
    assert b8["uint8"]["completed"] > 0 and b8["f32"]["completed"] > 0
    assert b8["uplift"] is not None and b8["uplift"] > 1.0
    assert b8["zero_steady_state_allocs"]
    assert out["jpeg"]["completed"] == out["jpeg"]["offered"] == 8
    assert isinstance(out["ingest_ok"], bool)


def test_jpeg_payload_without_decode_pool_counts_malformed():
    """A compressed payload hitting a non-jpeg service is a loud,
    counted malformed frame — never a silent hang."""
    metrics = Metrics()
    pipeline, service, connector = _service(
        metrics=metrics, ingest=IngestConfig(mode="uint8"))
    service.start(warmup=False)
    try:
        connector.inject(FRAME_TOPIC, {ingest_mod.JPEG_KEY: "AAAA",
                                       "meta": {"seq": 0}})
        assert service.drain(timeout=10.0)
    finally:
        service.stop()
    assert metrics.counter(mn.FRAMES_MALFORMED) == 1
    _assert_settled(service)
