"""Accuracy regression bands (VERDICT round-1 item #3; SURVEY.md §6
"first build milestone").

Two layers of guard:

1. ``test_measured_block_*`` parses the MEASURED block that
   ``scripts/measure_accuracy.py`` wrote into BASELINE.md (full-scale runs
   on the real chip) and asserts each recorded number sits above its band —
   so a regressed re-measurement cannot be silently recorded.
2. ``test_canary_*`` re-runs scaled-down versions of the same configs in
   the CPU suite so an algorithmic regression (PCA/LDA/LBP/k-NN math) fails
   fast here, without waiting for the next full measurement.

Bands leave margin below the measured values (BASELINE.md: eigenfaces
0.9575, fisherfaces 0.9717 with the sigma=2/4 TanTriggs default, lbph
0.9719 with the radius-2 default, cnn 0.9990 with the widened net) to
absorb seed/backend jitter while still catching real regressions.
"""

import os
import re

import numpy as np
import pytest

from opencv_facerecognizer_tpu.runtime.trainer import TheTrainer, TrainerConfig
from opencv_facerecognizer_tpu.utils.dataset import make_synthetic_faces

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# config key -> (BASELINE.md row label prefix, minimum acceptable accuracy)
MEASURED_BANDS = {
    "eigenfaces": ("Eigenfaces", 0.90),
    "fisherfaces": ("Fisherfaces", 0.85),  # sigma-2/4 TT measured 0.9717; 0.8117 was sigma-1/2
    "lbph": ("LBPH", 0.85),  # radius-2 default measured 0.95+; 0.525 was radius-1
    # band == the north star: a recorded measurement below >=0.99 must fail
    # even if it's otherwise plausible (measured 0.9990 +/- 0.0015, ~6 std
    # of margin above the band)
    "cnn": ("CNN ArcFace", 0.99),
}


def _measured_rows():
    text = open(os.path.join(REPO, "BASELINE.md")).read()
    m = re.search(r"<!-- MEASURED:BEGIN.*?-->(.*?)<!-- MEASURED:END -->",
                  text, flags=re.S)
    assert m, "BASELINE.md lacks the MEASURED block (run scripts/measure_accuracy.py)"
    rows = {}
    for line in m.group(1).splitlines():
        cells = [c.strip() for c in line.strip().strip("|").split("|")]
        if len(cells) >= 2 and "**" in cells[1]:
            acc = float(re.search(r"\*\*([0-9.]+)", cells[1]).group(1))
            rows[cells[0]] = acc
    return rows


@pytest.mark.parametrize("key", sorted(MEASURED_BANDS))
def test_measured_block_above_band(key):
    label, band = MEASURED_BANDS[key]
    rows = _measured_rows()
    matching = [acc for name, acc in rows.items() if name.startswith(label)]
    assert matching, f"no measured row starting with {label!r} in BASELINE.md"
    assert matching[0] >= band, (
        f"{label}: measured {matching[0]} fell below band {band} — "
        "accuracy regressed; investigate before re-recording")


def _canary_kfold(model_kind, num_subjects, per_subject, kfold, **kw):
    X, y, names = make_synthetic_faces(
        num_subjects=num_subjects, per_subject=per_subject, size=(48, 48), **kw)
    trainer = TheTrainer(TrainerConfig(model=model_kind, kfold=kfold))
    trainer.train(X, y, names, validate=True)
    return trainer.mean_accuracy


def test_canary_eigenfaces():
    acc = _canary_kfold("eigenfaces", 12, 8, 3, seed=1)
    assert acc >= 0.90, f"eigenfaces canary accuracy {acc:.3f}"


def test_canary_fisherfaces_illumination():
    # 48x48 under-resolves the TanTriggs DoG band for this config
    # (measured 0.64 there vs 0.88+ at 56x56), so this canary keeps 56x56.
    X, y, names = make_synthetic_faces(num_subjects=10, per_subject=8,
                                       size=(56, 56), seed=2,
                                       illumination=0.7, noise=14.0)
    trainer = TheTrainer(TrainerConfig(model="fisherfaces", kfold=3))
    trainer.train(X, y, names, validate=True)
    acc = trainer.mean_accuracy
    # the sigma0=2/sigma1=4 TanTriggs default measures 1.0 here
    assert acc >= 0.85, f"fisherfaces canary accuracy {acc:.3f}"


def test_canary_lbph_noise():
    acc = _canary_kfold("lbph", 12, 8, 3, seed=3, noise=18.0)
    # radius-2 LBP default measures 1.0 here (radius-1 sat at ~0.5)
    assert acc >= 0.85, f"lbph canary accuracy {acc:.3f}"


def test_canary_cnn_verification():
    """Tiny ArcFace train + disjoint-identity verification (the CNN row's
    canary; full 6000-pair protocol runs in scripts/measure_accuracy.py)."""
    from opencv_facerecognizer_tpu.models.embedder import CNNEmbedding
    from opencv_facerecognizer_tpu.utils.verification import (
        make_verification_pairs, verification_accuracy)

    size = (32, 32)
    X_tr, y_tr, _ = make_synthetic_faces(num_subjects=12, per_subject=8,
                                         size=size, seed=11, noise=10.0)
    X_te, y_te, _ = make_synthetic_faces(num_subjects=8, per_subject=8,
                                         size=size, seed=77, noise=10.0)
    emb = CNNEmbedding(embed_dim=32, input_size=size, stem_features=8,
                       stage_features=(16, 32), stage_blocks=(1, 1),
                       train_steps=150, batch_size=32, learning_rate=2e-3,
                       seed=3)
    emb.compute(X_tr, y_tr)
    e = np.array(emb._extract_batch(np.asarray(X_te, np.float32)))
    a, b, same = make_verification_pairs(y_te, num_pairs=600, seed=5)
    acc, _, _ = verification_accuracy(e[a], e[b], same, folds=5)
    # This tiny config plateaus at 0.82-0.85 (vs 0.9990 at full scale);
    # an algorithmic break lands near 0.5, so 0.75 separates cleanly.
    assert acc >= 0.75, f"cnn verification canary accuracy {acc:.3f}"
