"""Accuracy regression bands (VERDICT round-1 item #3; SURVEY.md §6
"first build milestone").

Two layers of guard:

1. ``test_measured_block_*`` parses the MEASURED block that
   ``scripts/measure_accuracy.py`` wrote into BASELINE.md (full-scale runs
   on the real chip) and asserts each recorded number sits above its band —
   so a regressed re-measurement cannot be silently recorded.
2. ``test_canary_*`` re-runs scaled-down versions of the same configs in
   the CPU suite so an algorithmic regression (PCA/LDA/LBP/k-NN math) fails
   fast here, without waiting for the next full measurement.

Bands sit ~3 points below the round-3 HARD-protocol measurements
(BASELINE.md, 2026-07-30: pose rotation + scale jitter + elastic
deformation + occlusion on every config — see scripts/measure_accuracy.py
HARD_POSE/HARD_WILD): eigenfaces 0.895, fisherfaces 0.8283, lbph 0.925,
cnn 0.9943 (300 train identities, in-graph augmentation, flip-TTA). The
classics drop honestly under occlusion/pose — linear templates cannot
model either — while the CNN band stays pinned at the >=0.99 north star.
"""

import os
import re

import numpy as np
import pytest

from opencv_facerecognizer_tpu.runtime.trainer import TheTrainer, TrainerConfig
from opencv_facerecognizer_tpu.utils.dataset import make_synthetic_faces

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# config key -> (BASELINE.md row label prefix, minimum acceptable accuracy);
# ~3 points under the hard-protocol measurement (round-2 verdict: the old
# 7-10-point slack let real regressions pass silently)
MEASURED_BANDS = {
    "eigenfaces": ("Eigenfaces", 0.86),  # hard protocol measured 0.895
    "fisherfaces": ("Fisherfaces", 0.80),  # hard protocol measured 0.8283
    "lbph": ("LBPH (", 0.89),  # hard protocol measured 0.925
    # robustness winner (r5): measured 0.9817 seed=2, 0.9817/0.9950 on
    # unseen seeds 22/42 (scripts/explore_fisherfaces.py + confirmation)
    "lbp_fisherfaces": ("LBP-Fisherfaces (raw", 0.95),
    # same config transfers to the other rows' protocols: LFW-analog
    # measured 0.9625 (vs lbph 0.9250), ORL-analog 0.9975 (vs eigenfaces
    # 0.8950)
    "lbp_fisherfaces_lfw": ("LBP-Fisherfaces, same config on the LFW", 0.93),
    "lbp_fisherfaces_orl": ("LBP-Fisherfaces, same config on the ORL", 0.96),
    # band == the north star: a recorded measurement below >=0.99 must fail
    # even if it's otherwise plausible (hard protocol measured 0.9943
    # +/- 0.0020 at 30000 steps/b192, on-chip 2026-07-31, with
    # augmentation + TTA)
    "cnn": ("CNN ArcFace", 0.99),
}


def _measured_rows():
    text = open(os.path.join(REPO, "BASELINE.md")).read()
    m = re.search(r"<!-- MEASURED:BEGIN.*?-->(.*?)<!-- MEASURED:END -->",
                  text, flags=re.S)
    assert m, "BASELINE.md lacks the MEASURED block (run scripts/measure_accuracy.py)"
    rows = {}
    for line in m.group(1).splitlines():
        cells = [c.strip() for c in line.strip().strip("|").split("|")]
        if len(cells) >= 2 and "**" in cells[1]:
            acc = float(re.search(r"\*\*([0-9.]+)", cells[1]).group(1))
            rows[cells[0]] = acc
    return rows


@pytest.mark.parametrize("key", sorted(MEASURED_BANDS))
def test_measured_block_above_band(key):
    label, band = MEASURED_BANDS[key]
    rows = _measured_rows()
    matching = [acc for name, acc in rows.items() if name.startswith(label)]
    assert matching, f"no measured row starting with {label!r} in BASELINE.md"
    assert matching[0] >= band, (
        f"{label}: measured {matching[0]} fell below band {band} — "
        "accuracy regressed; investigate before re-recording")


def _canary_kfold(model_kind, num_subjects, per_subject, kfold, **kw):
    X, y, names = make_synthetic_faces(
        num_subjects=num_subjects, per_subject=per_subject, size=(48, 48), **kw)
    trainer = TheTrainer(TrainerConfig(model=model_kind, kfold=kfold))
    trainer.train(X, y, names, validate=True)
    return trainer.mean_accuracy


def test_canary_eigenfaces():
    acc = _canary_kfold("eigenfaces", 12, 8, 3, seed=1)
    assert acc >= 0.90, f"eigenfaces canary accuracy {acc:.3f}"


def test_canary_fisherfaces_illumination():
    # 48x48 under-resolves the TanTriggs DoG band for this config
    # (measured 0.64 there vs 0.88+ at 56x56), so this canary keeps 56x56.
    X, y, names = make_synthetic_faces(num_subjects=10, per_subject=8,
                                       size=(56, 56), seed=2,
                                       illumination=0.7, noise=14.0)
    trainer = TheTrainer(TrainerConfig(model="fisherfaces", kfold=3))
    trainer.train(X, y, names, validate=True)
    acc = trainer.mean_accuracy
    # the sigma0=2/sigma1=4 TanTriggs default measures 1.0 here
    assert acc >= 0.85, f"fisherfaces canary accuracy {acc:.3f}"


def test_canary_lbp_fisherfaces():
    # The robustness winner survives illumination+noise at canary scale;
    # 56x56 for the same resolution reason as the fisherfaces canary.
    X, y, names = make_synthetic_faces(num_subjects=10, per_subject=8,
                                       size=(56, 56), seed=2,
                                       illumination=0.7, noise=14.0)
    trainer = TheTrainer(TrainerConfig(model="lbp_fisherfaces", kfold=3))
    trainer.train(X, y, names, validate=True)
    acc = trainer.mean_accuracy
    assert acc >= 0.85, f"lbp_fisherfaces canary accuracy {acc:.3f}"


def test_canary_lbph_noise():
    acc = _canary_kfold("lbph", 12, 8, 3, seed=3, noise=18.0)
    # radius-2 LBP default measures 1.0 here (radius-1 sat at ~0.5)
    assert acc >= 0.85, f"lbph canary accuracy {acc:.3f}"


def test_canary_cnn_verification():
    """Tiny ArcFace train + disjoint-identity verification (the CNN row's
    canary; full 6000-pair protocol runs in scripts/measure_accuracy.py)."""
    from opencv_facerecognizer_tpu.models.embedder import CNNEmbedding
    from opencv_facerecognizer_tpu.utils.verification import (
        make_verification_pairs, verification_accuracy)

    size = (32, 32)
    X_tr, y_tr, _ = make_synthetic_faces(num_subjects=12, per_subject=8,
                                         size=size, seed=11, noise=10.0)
    X_te, y_te, _ = make_synthetic_faces(num_subjects=8, per_subject=8,
                                         size=size, seed=77, noise=10.0)
    emb = CNNEmbedding(embed_dim=32, input_size=size, stem_features=8,
                       stage_features=(16, 32), stage_blocks=(1, 1),
                       train_steps=150, batch_size=32, learning_rate=2e-3,
                       seed=3)
    emb.compute(X_tr, y_tr)
    e = np.array(emb._extract_batch(np.asarray(X_te, np.float32)))
    a, b, same = make_verification_pairs(y_te, num_pairs=600, seed=5)
    acc, _, _ = verification_accuracy(e[a], e[b], same, folds=5)
    # This tiny config plateaus at 0.82-0.85 (vs 0.9990 at full scale);
    # an algorithmic break lands near 0.5, so 0.75 separates cleanly.
    assert acc >= 0.75, f"cnn verification canary accuracy {acc:.3f}"


def test_cnn_fold_min_above_north_star():
    """The >=0.99 bar gates the verification spread's LOWER edge, not the
    mean (VERDICT r3 item #4). Measured live on-chip 2026-07-31 (30000
    steps, batch 192): mean 0.9943 +/- 0.0020, fold_min 0.9917 — exactly
    reproducing the r4 gate-run artifact.

    Reads ONLY scripts/.accuracy_cache.json (the live measurement cache
    that scripts/measure_accuracy.py --only cnn refreshes). The r4-outage
    fallback to the committed .gate_embedder.jsonl artifact was burned
    down once the on-chip refresh landed (VERDICT r4 item #7): a
    regression band that gates a checked-in artifact can't catch a
    regression until the refresh lands."""
    import json

    cache = os.path.join(REPO, "scripts", ".accuracy_cache.json")
    assert os.path.exists(cache), (
        "no accuracy cache: run scripts/measure_accuracy.py --only cnn")
    fold_min = json.load(open(cache)).get(
        "cnn_verification", {}).get("fold_min")
    assert fold_min is not None, (
        "accuracy cache lacks cnn_verification.fold_min: re-run "
        "scripts/measure_accuracy.py --only cnn")
    assert fold_min >= 0.99, (
        f"CNN verification fold minimum {fold_min} fell below the "
        ">=0.99 north star — the spread's lower edge regressed")
