"""Multi-replica serving tests (``runtime.replication``): writer lease,
WAL tailer, read replicas over a shared state dir, the rendezvous topic
router with health failover, the ``/replicas`` expo surface, the
``verify_checkpoint.py --follow`` live-tail mode, and the fast
deterministic tier-1 variant of the replication chaos scenario
(``scripts/chaos_soak.py --scenario replication``; the slow randomized
soak lives in ``tests/test_chaos.py``)."""

import importlib.util
import json
import os
import threading
import time

import numpy as np
import pytest

from opencv_facerecognizer_tpu.parallel import ShardedGallery, make_mesh
from opencv_facerecognizer_tpu.runtime import (
    FakeConnector,
    ReadReplica,
    RecognizerService,
    ReplicaHandle,
    StateLifecycle,
    TopicRouter,
    WALTailer,
    WriterLease,
    WriterLeaseHeldError,
)
from opencv_facerecognizer_tpu.runtime.fakes import InstantPipeline
from opencv_facerecognizer_tpu.runtime.recognizer import (
    CONTROL_TOPIC,
    FRAME_TOPIC,
    RESULT_TOPIC,
    STATUS_TOPIC,
)
from opencv_facerecognizer_tpu.runtime.slo import STATE_CRITICAL, STATE_OK
from opencv_facerecognizer_tpu.utils.metrics import Metrics

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_script(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(_REPO, "scripts", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


DIM = 8


@pytest.fixture(scope="module")
def mesh():
    return make_mesh()


def _writer(tmp_path, mesh, **kw):
    gallery = ShardedGallery(capacity=64, dim=DIM, mesh=mesh)
    names = []
    state = StateLifecycle(str(tmp_path), metrics=Metrics(),
                           checkpoint_wal_rows=kw.pop("wal_rows", 1 << 30),
                           checkpoint_every_s=1e9, **kw)
    state.bind(gallery, names)
    return state, gallery, names


def _enroll(state, gallery, names, rng, i, n=1):
    emb = rng.normal(size=(n, DIM)).astype(np.float32)
    labels = np.full(n, i, np.int32)
    names.append(f"s{i}")
    state.append_enrollment(emb, labels, subject=f"s{i}", label=i,
                            apply_fn=lambda e=emb, l=labels:
                                gallery.add(e, l))


def _assert_galleries_equal(a, b):
    ae, al, _av, asz = a.snapshot()
    be, bl, _bv, bsz = b.snapshot()
    assert asz == bsz
    assert np.array_equal(al[:asz], bl[:bsz])
    assert np.allclose(ae[:asz], be[:bsz], rtol=0, atol=1e-6)


# ---------- writer lease ----------


def test_writer_lease_second_writer_fails_closed(tmp_path):
    lease = WriterLease(str(tmp_path), metrics=Metrics()).acquire()
    assert lease.held
    # flock conflicts across file descriptors, even within one process.
    with pytest.raises(WriterLeaseHeldError):
        WriterLease(str(tmp_path)).acquire()
    # Holder info is diagnostics: pid of the live holder.
    with open(os.path.join(str(tmp_path), "writer.lease")) as fh:
        assert json.load(fh)["pid"] == os.getpid()
    lease.release()
    assert not lease.held
    # Release hands ownership over cleanly.
    second = WriterLease(str(tmp_path)).acquire()
    second.release()


def test_writer_lease_acquire_is_idempotent_and_ctx(tmp_path):
    lease = WriterLease(str(tmp_path))
    with lease:
        assert lease.acquire() is lease  # no self-deadlock
        assert lease.held
    assert not lease.held


# ---------- WAL tailer ----------


def test_tailer_reads_complete_lines_only(tmp_path):
    path = str(tmp_path / "w.wal")
    tailer = WALTailer(path)
    records, info = tailer.poll()
    assert records == [] and info.get("missing")
    with open(path, "w") as fh:
        fh.write('{"kind": "enroll", "seq": 1}\n{"kind": "enr')
        fh.flush()
    records, info = tailer.poll()
    assert [r["seq"] for r in records] == [1]
    assert info["partial"]
    # The torn tail completes: only then is the second record visible.
    with open(path, "a") as fh:
        fh.write('oll", "seq": 2}\n')
    records, _info = tailer.poll()
    assert [r["seq"] for r in records] == [2]


def test_tailer_skips_garbage_and_detects_swap(tmp_path):
    path = str(tmp_path / "w.wal")
    with open(path, "w") as fh:
        fh.write('garbage-torn-line\n{"kind": "enroll", "seq": 5}\n')
    tailer = WALTailer(path)
    records, info = tailer.poll()
    assert [r["seq"] for r in records] == [5]
    assert tailer.malformed_lines == 1
    assert not info["reopened"]
    # Compaction: an atomically swapped-in rewrite (new inode).
    with open(path + ".tmp", "w") as fh:
        fh.write('{"kind": "enroll", "seq": 6}\n')
    os.replace(path + ".tmp", path)
    records, info = tailer.poll()
    assert info["reopened"]
    assert [r["seq"] for r in records] == [6]
    assert tailer.reopens == 1


# ---------- read replica over a live writer ----------


def test_replica_tails_dedups_and_reanchors(tmp_path, mesh):
    rng = np.random.default_rng(0)
    state, wg, wnames = _writer(tmp_path, mesh)
    for i in range(3):
        _enroll(state, wg, wnames, rng, i, n=2)
    rg = ShardedGallery(capacity=64, dim=DIM, mesh=mesh)
    rnames = []
    metrics = Metrics()
    rep = ReadReplica(str(tmp_path), rg, rnames, metrics=metrics,
                      poll_interval_s=0.0, name="r0")
    rep.resync()
    _assert_galleries_equal(wg, rg)
    assert rnames == wnames
    # Incremental tail; polling again applies nothing twice (seq dedup).
    for i in range(3, 6):
        _enroll(state, wg, wnames, rng, i)
    out = rep.poll(force=True)
    assert out["rows"] == 3
    assert rep.poll(force=True)["rows"] == 0
    _assert_galleries_equal(wg, rg)
    assert rep.lag_rows == 0
    # Checkpoint + compaction: the replica detects the swapped WAL; a
    # LATE replica anchors on the checkpoint and lands identical.
    assert state.checkpoint_now(wait=True)
    for i in range(6, 8):
        _enroll(state, wg, wnames, rng, i)
    rep.poll(force=True)
    _assert_galleries_equal(wg, rg)
    late_g = ShardedGallery(capacity=64, dim=DIM, mesh=mesh)
    late = ReadReplica(str(tmp_path), late_g, [], poll_interval_s=0.0,
                      name="late")
    late.poll(force=True)
    assert late.anchor_checkpoint is not None
    _assert_galleries_equal(wg, late_g)
    assert metrics.gauge("replication_lag_rows") == 0
    state.close()


def test_replica_abort_tombstones(tmp_path, mesh):
    """An abort in the same poll batch filters its enroll; an abort for an
    already-applied seq forces a resync that removes the phantom rows."""
    rng = np.random.default_rng(1)
    state, wg, wnames = _writer(tmp_path, mesh)
    _enroll(state, wg, wnames, rng, 0)
    rg = ShardedGallery(capacity=64, dim=DIM, mesh=mesh)
    metrics = Metrics()
    rep = ReadReplica(str(tmp_path), rg, [], metrics=metrics,
                      poll_interval_s=0.0, name="r")
    rep.poll(force=True)
    assert rg.size == 1
    # Same-batch abort: appended enroll + its tombstone land in one poll
    # (the writer's failed-apply shape) — nothing is applied.
    emb = rng.normal(size=(1, DIM)).astype(np.float32)
    state.wal.append_enroll(2, emb, np.zeros(1, np.int32))
    state.wal.append_abort(2)
    out = rep.poll(force=True)
    assert out["rows"] == 0
    assert rg.size == 1
    # Abort arriving a poll LATER than its (applied) enroll: the replica
    # must resync rather than serve rows the writer rolled back.
    state.wal.append_enroll(3, emb, np.zeros(1, np.int32))
    assert rep.poll(force=True)["rows"] == 1
    assert rg.size == 2
    state.wal.append_abort(3)
    out = rep.poll(force=True)
    assert metrics.counter("replication_aborts_after_apply") == 1
    assert rg.size == 1  # the resync rebuilt without the aborted row
    state.close()


def test_replica_service_applies_while_serving(tmp_path, mesh):
    """RecognizerService(replica=...): the serving loop itself polls the
    WAL tail between batches, and enroll commands are rejected."""
    rng = np.random.default_rng(2)
    state, wg, wnames = _writer(tmp_path, mesh)
    _enroll(state, wg, wnames, rng, 0)
    rg = ShardedGallery(capacity=64, dim=DIM, mesh=mesh)
    rep = ReadReplica(str(tmp_path), rg, [], metrics=Metrics(),
                      poll_interval_s=0.01, name="r")
    rep.poll(force=True)
    pipe = InstantPipeline((16, 16))
    pipe.gallery = rg
    connector = FakeConnector()
    service = RecognizerService(pipe, connector, batch_size=4,
                                frame_shape=(16, 16), flush_timeout=0.02,
                                metrics=Metrics(), replica=rep)
    service.start(warmup=False)
    try:
        for i in range(1, 4):
            _enroll(state, wg, wnames, rng, i)
        deadline = time.monotonic() + 5.0
        while rg.size < wg.size and time.monotonic() < deadline:
            time.sleep(0.02)
        _assert_galleries_equal(wg, rg)
        # Enrollment is writer-only on a read replica.
        connector.inject(CONTROL_TOPIC, {"cmd": "enroll", "subject": "x"})
        statuses = connector.messages(STATUS_TOPIC)
        assert any(m.get("reason") == "read_replica" for m in statuses)
        assert service.metrics.counter("replication_enroll_rejected") == 1
    finally:
        service.stop()
    state.close()


def test_replica_reanchors_onto_newer_embedder_version(tmp_path, mesh):
    """Rollout re-anchor (ISSUE 11): a replica serving v1 sees the cutover
    fence in the tail, PARKS (keeps serving pure v1, applies nothing),
    and re-anchors through the resync path the moment the writer's
    v2 checkpoint lands — with the cordon hook draining it around the
    reload."""
    rng = np.random.default_rng(5)
    state, wg, wnames = _writer(tmp_path, mesh)
    for i in range(3):
        _enroll(state, wg, wnames, rng, i)
    rg = ShardedGallery(capacity=64, dim=DIM, mesh=mesh)
    metrics = Metrics()
    rep = ReadReplica(str(tmp_path), rg, [], metrics=metrics,
                      poll_interval_s=0.0, name="r")
    cordon_calls = []
    rep.on_resync = cordon_calls.append
    rep.poll(force=True)
    assert rep.embedder_version == 1
    # The writer cuts over (same rows re-stamped — fence mechanics only).
    emb, lab, val, size = wg.snapshot()
    state.perform_cutover(2, lambda: (emb, lab, val, size))
    _enroll(state, wg, wnames, rng, 3)  # a v2 row behind the fence
    rep.poll(force=True)
    rep.poll(force=True)
    # Parked: nothing applied across the fence, v1 stays served.
    assert rep.stats()["awaiting_cutover"]["to_version"] == 2
    assert rep.embedder_version == 1 and rg.size == 3
    assert metrics.gauge("rollout_replica_awaiting") == 1
    # The v2 checkpoint lands: re-anchor + catch up the v2 tail.
    assert state.checkpoint_now(wait=True)
    rep.poll(force=True)
    assert rep.embedder_version == 2
    assert metrics.counter("rollout_replica_reanchors") == 1
    deadline = time.monotonic() + 5.0
    while rep.applied_seq < state.wal_seq and time.monotonic() < deadline:
        rep.poll(force=True)
        time.sleep(0.01)
    _assert_galleries_equal(wg, rg)
    # The drain hook bracketed every resync (initial + re-anchor).
    assert cordon_calls.count("begin") == cordon_calls.count("end") >= 2
    state.close()


def test_parked_replica_unparks_on_stacked_cutover(tmp_path, mesh):
    """A replica parked awaiting v2 must NOT strand when cutovers stack
    (the first post-cutover checkpoint never landed and a second rollout
    cut over to v3 before any checkpoint): ANY checkpoint whose wal_seq
    covers the fence carries a post-cutover version, so the unpark keys
    on the sequence, not the exact awaited version."""
    rng = np.random.default_rng(7)
    state, wg, wnames = _writer(tmp_path, mesh)
    for i in range(3):
        _enroll(state, wg, wnames, rng, i)
    rg = ShardedGallery(capacity=64, dim=DIM, mesh=mesh)
    rep = ReadReplica(str(tmp_path), rg, [], metrics=Metrics(),
                      poll_interval_s=0.0, name="r")
    rep.poll(force=True)
    emb, lab, val, size = wg.snapshot()
    state.perform_cutover(2, lambda: (emb, lab, val, size))
    rep.poll(force=True)
    rep.poll(force=True)
    assert rep.stats()["awaiting_cutover"]["to_version"] == 2
    # The v2 checkpoint never lands; a SECOND cutover (v3) does, and ITS
    # checkpoint succeeds.
    emb2, lab2, val2, size2 = wg.snapshot()
    state.perform_cutover(3, lambda: (emb2, lab2, val2, size2))
    assert state.checkpoint_now(wait=True)
    rep.poll(force=True)
    assert rep.embedder_version == 3
    assert rep.stats()["awaiting_cutover"] is None
    _assert_galleries_equal(wg, rg)
    state.close()


def test_late_start_replica_never_saw_old_version(tmp_path, mesh):
    """A replica born AFTER the cutover anchors straight on the v2
    checkpoint: no fence parking, no v1 residue — and the WAL's surviving
    pre-cutover rows below the anchor are dedup'd, never applied."""
    rng = np.random.default_rng(6)
    state, wg, wnames = _writer(tmp_path, mesh)
    for i in range(3):
        _enroll(state, wg, wnames, rng, i)
    emb, lab, val, size = wg.snapshot()
    state.perform_cutover(2, lambda: (emb, lab, val, size))
    assert state.checkpoint_now(wait=True)
    _enroll(state, wg, wnames, rng, 3)  # post-checkpoint v2 tail row
    late_g = ShardedGallery(capacity=64, dim=DIM, mesh=mesh)
    late = ReadReplica(str(tmp_path), late_g, [], metrics=Metrics(),
                       poll_interval_s=0.0, name="late")
    late.poll(force=True)
    assert late.embedder_version == 2
    assert late.stats()["awaiting_cutover"] is None
    _assert_galleries_equal(wg, late_g)
    state.close()


# ---------- topic router ----------


def _handles(n, health=None, budget_fps=None):
    out = []
    for i in range(n):
        out.append(ReplicaHandle(
            f"replica-{i}", FakeConnector(),
            health_fn=(health[i] if health else None),
            budget_fps=budget_fps, writer=i == 0))
    return out


def test_router_rendezvous_is_stable_and_minimal():
    """Rendezvous property: removing one replica moves ONLY the topics
    that hashed to it; every other topic keeps its assignment."""
    handles = _handles(3)
    router = TopicRouter(handles, metrics=Metrics())
    topics = [f"camera/{i}" for i in range(64)]
    before = {t: router.route(t).name for t in topics}
    assert len(set(before.values())) == 3  # all replicas used
    handles[1].healthy = False
    after = {t: router.route(t).name for t in topics}
    for t in topics:
        if before[t] != "replica-1":
            assert after[t] == before[t]
        else:
            assert after[t] != "replica-1"


def test_router_forwards_and_fans_in():
    handles = _handles(2)
    router = TopicRouter(handles, metrics=Metrics())
    got = []
    router.subscribe(RESULT_TOPIC, lambda t, m: got.append(m))
    router.publish("camera/a", {"frame": "x", "meta": {"seq": 1}})
    # The chosen replica's connector received it on FRAME_TOPIC.
    fwd = [(h, m) for h in handles
           for t, m in h.connector.sent if t == FRAME_TOPIC]
    assert len(fwd) == 1
    handle, msg = fwd[0]
    assert msg["_route_topic"] == "camera/a" and msg["meta"]["seq"] == 1
    # Results fan back in to the router's subscribers.
    handle.connector.publish(RESULT_TOPIC, {"meta": {"seq": 1}, "faces": []})
    assert got and got[0]["meta"]["seq"] == 1
    # Control traffic goes to the writer replica only.
    router.publish(CONTROL_TOPIC, {"cmd": "enroll"})
    assert any(t == CONTROL_TOPIC for t, _m in handles[0].connector.sent)
    assert not any(t == CONTROL_TOPIC for t, _m in handles[1].connector.sent)
    # Status fan-in stamps the originating replica; results stay clean.
    handle.connector.publish(STATUS_TOPIC, {"status": "degraded"})
    statuses = []
    router.subscribe(STATUS_TOPIC, lambda t, m: statuses.append(m))
    handle.connector.publish(STATUS_TOPIC, {"status": "degraded"})
    assert statuses[0]["replica"] == handle.name
    assert "replica" not in got[0]


def test_router_replace_connector_rewires_fan_in():
    """A restarted replica comes back on a fresh connector: rewiring must
    re-subscribe the fan-in there, or its results silently vanish."""
    handles = _handles(1)
    router = TopicRouter(handles, metrics=Metrics())
    got = []
    router.subscribe(RESULT_TOPIC, lambda t, m: got.append(m))
    fresh = FakeConnector()
    router.replace_connector("replica-0", fresh)
    router.publish("camera/a", {"frame": "x", "meta": {"seq": 9}})
    assert any(t == FRAME_TOPIC for t, _m in fresh.sent)  # routed anew
    fresh.publish(RESULT_TOPIC, {"meta": {"seq": 9}, "faces": []})
    assert got and got[0]["meta"]["seq"] == 9  # fan-in reached upstream
    with pytest.raises(KeyError):
        router.replace_connector("nope", FakeConnector())


def test_router_budget_spills_to_next_replica():
    metrics = Metrics()
    handles = _handles(2, budget_fps=1.0)  # burst 1: one token each
    router = TopicRouter(handles, metrics=metrics)
    first = router.route("camera/a")
    second = router.route("camera/a")  # first's bucket is empty: spill
    assert first is not None and second is not None
    assert second.name != first.name
    assert metrics.counter("router_budget_spills") == 1
    # Both exhausted: rejected with the budget reason.
    assert router.route("camera/a") is None
    assert metrics.counter("router_rejected_budget") == 1


def test_router_health_failover_and_recovery():
    state = {"replica-0": STATE_OK, "replica-1": STATE_OK}
    metrics = Metrics()
    handles = _handles(2, health=[lambda: state["replica-0"],
                                  lambda: state["replica-1"]])
    router = TopicRouter(handles, metrics=metrics)
    router.check_health()
    assert all(h.healthy for h in handles)
    # One replica goes critical: excluded, counted, topics move.
    state["replica-0"] = STATE_CRITICAL
    router.check_health()
    assert not handles[0].healthy
    assert metrics.counter("router_failovers") == 1
    assert router.route("camera/x").name == "replica-1"
    assert metrics.gauge("router_healthy_replicas") == 1
    # A RAISING probe also fails the replica closed.
    handles[1].health_fn = lambda: (_ for _ in ()).throw(OSError("down"))
    router.check_health()
    assert not handles[1].healthy
    assert metrics.counter("router_health_probe_failures") == 1
    assert router.route("camera/x") is None
    assert metrics.counter("router_rejected_no_replica") == 1
    # Recovery reinstates.
    state["replica-0"] = STATE_OK
    handles[1].health_fn = lambda: STATE_OK
    router.check_health()
    assert all(h.healthy for h in handles)
    assert metrics.counter("router_recoveries") == 2


def test_router_registry_and_expo_replicas_endpoint():
    import urllib.error
    import urllib.request

    from opencv_facerecognizer_tpu.runtime.expo import ExpoServer

    handles = _handles(2)
    router = TopicRouter(handles, metrics=Metrics())
    router.publish("camera/a", {"frame": "x"})
    registry = router.registry()
    assert {r["name"] for r in registry} == {"replica-0", "replica-1"}
    assert sum(r["routed"] for r in registry) == 1
    routed_topics = [t for r in registry for t in r["topics"]]
    assert routed_topics == ["camera/a"]
    expo = ExpoServer(metrics=Metrics(), router=router, port=0)
    expo.start()
    try:
        with urllib.request.urlopen(
                f"http://{expo.host}:{expo.port}/replicas", timeout=5) as r:
            body = json.loads(r.read())
        assert {x["name"] for x in body["replicas"]} == {"replica-0",
                                                         "replica-1"}
        # Unwired router answers the null shape, not a 404.
        bare = ExpoServer(metrics=Metrics(), port=0)
        bare.start()
        try:
            with urllib.request.urlopen(
                    f"http://{bare.host}:{bare.port}/replicas",
                    timeout=5) as r:
                assert json.loads(r.read())["replicas"] is None
        finally:
            bare.stop()
    finally:
        expo.stop()


# ---------- verify_checkpoint --follow ----------


def test_verify_follow_validates_live_tail(tmp_path, mesh):
    rng = np.random.default_rng(3)
    state, wg, wnames = _writer(tmp_path, mesh)
    for i in range(2):
        _enroll(state, wg, wnames, rng, i)
    verify = _load_script("verify_checkpoint")
    stop = threading.Event()

    def keep_enrolling():
        i = 2
        while not stop.is_set():
            _enroll(state, wg, wnames, rng, i)
            i += 1
            time.sleep(0.05)

    writer_thread = threading.Thread(target=keep_enrolling, daemon=True)
    writer_thread.start()
    try:
        report = verify.follow_wal(str(tmp_path), duration_s=0.6,
                                   poll_s=0.05)
    finally:
        stop.set()
        writer_thread.join(timeout=5.0)
    assert report["ok"], report
    assert report["valid_records"] >= 2
    assert report["corrupt_records"] == 0
    assert report["polls"] > 1
    state.close()


def test_verify_follow_flags_corrupt_acked_record(tmp_path, mesh):
    rng = np.random.default_rng(4)
    state, wg, wnames = _writer(tmp_path, mesh)
    _enroll(state, wg, wnames, rng, 0)
    # A parseable enroll record with a broken crc: acked-then-unreadable.
    with open(os.path.join(str(tmp_path), "enroll.wal"), "a") as fh:
        fh.write(json.dumps({"kind": "enroll", "seq": 99, "n": 1,
                             "dim": DIM, "labels": [0], "label": 0,
                             "subject": "x", "emb": "AAAA", "crc32": 1,
                             "ts": time.time()}) + "\n")
    verify = _load_script("verify_checkpoint")
    report = verify.follow_wal(str(tmp_path), duration_s=0.1, poll_s=0.05)
    assert not report["ok"]
    assert report["corrupt_records"] == 1
    assert report["valid_records"] == 1
    # The CLI surfaces it as rc 2 (same contract as the static sweep).
    rc = verify.main([str(tmp_path), "--follow", "--duration", "0.1"])
    assert rc == 2
    state.close()


# ---------- the replication chaos scenario (fast tier-1 variant) ----------


def test_replication_soak_fast_deterministic():
    """Tier-1 variant of ``--scenario replication``: 1 writer + 2 read
    replicas under routed traffic; a reader dies mid-traffic, the writer
    dies mid-enrollment and restarts; survivor p99 holds, every acked
    enrollment is bit-equal on every survivor, the ledgers settle
    exactly, and a REAL second process's writer-lease grab fails closed."""
    chaos_soak = _load_script("chaos_soak")
    report = chaos_soak.run_replication(seconds=3.0, seed=7)
    assert report["ok"], report["failures"]
    assert report["split_brain_rc"] == 3
    assert report["acked_enrollments"] > 0
    assert report["router"].get("router_failovers", 0) >= 1
