"""Oracle tests for ops.distance vs pure NumPy (SURVEY.md §4 prescription)."""

import numpy as np
import pytest

from opencv_facerecognizer_tpu.ops import distance as D

RNG = np.random.default_rng(0)
P = RNG.uniform(0.1, 1.0, size=(5, 32)).astype(np.float32)
Q = RNG.uniform(0.1, 1.0, size=(7, 32)).astype(np.float32)


def _numpy_pairwise(fn):
    return np.array([[fn(p, q) for q in Q] for p in P], dtype=np.float32)


ORACLES = {
    "euclidean": lambda p, q: np.linalg.norm(p - q),
    "squared_euclidean": lambda p, q: np.sum((p - q) ** 2),
    "cosine": lambda p, q: -np.dot(p, q) / (np.linalg.norm(p) * np.linalg.norm(q)),
    "normalized_correlation": lambda p, q: 1.0 - np.corrcoef(p, q)[0, 1],
    "chi_square": lambda p, q: np.sum((p - q) ** 2 / (p + q)),
    "histogram_intersection": lambda p, q: -np.sum(np.minimum(p, q)),
    # Bin-ratio family: upstream-lineage formula with the 2|1-p.q|pq cross
    # term (couples each bin to the whole-vector dot product).
    "bin_ratio": lambda p, q: abs(np.sum(
        ((p - q) ** 2 + 2 * abs(1 - np.dot(p, q)) * p * q) / (p + q) ** 2)),
    "l1_bin_ratio": lambda p, q: abs(np.sum(
        np.abs(p - q) * ((p - q) ** 2 + 2 * abs(1 - np.dot(p, q)) * p * q) / (p + q) ** 2)),
    "chi_square_brd": lambda p, q: abs(np.sum(
        ((p - q) ** 2 / (p + q))
        * ((p - q) ** 2 + 2 * abs(1 - np.dot(p, q)) * p * q) / (p + q) ** 2)),
    "manhattan": lambda p, q: np.sum(np.abs(p - q)),
}


@pytest.mark.parametrize("name", sorted(D.DISTANCES))
def test_pairwise_matches_numpy_oracle(name):
    dist = D.DISTANCES[name]()
    got = np.asarray(dist(P, Q))
    want = _numpy_pairwise(ORACLES[name])
    assert got.shape == (5, 7)
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def test_scalar_contract_on_vector_pair():
    dist = D.EuclideanDistance()
    got = dist(P[0], Q[0])
    assert np.ndim(got) == 0
    np.testing.assert_allclose(float(got), np.linalg.norm(P[0] - Q[0]), rtol=1e-5)


def test_self_distance_is_minimal():
    # The bin-ratio family's cross term assumes rows summing to 1 (the BRD
    # papers' domain); self-minimality only holds there. NOTE this is NOT
    # what SpatialHistogram emits (it normalizes per grid cell, so rows sum
    # to the cell count) — see the domain caveat in ops/distance.py: BRD on
    # such features needs a 1/S rescale first.
    P_hist = P / P.sum(axis=1, keepdims=True)
    brd_family = {"bin_ratio", "l1_bin_ratio", "chi_square_brd"}
    for name, cls in D.DISTANCES.items():
        data = P_hist if name in brd_family else P
        d = np.asarray(cls()(data, data))
        # diagonal should be the row minimum (self is most similar)
        assert np.all(np.diag(d) <= d.min(axis=1) + 1e-4), name


def test_images_are_flattened():
    imgs_p = P.reshape(5, 4, 8)
    got = np.asarray(D.EuclideanDistance()(imgs_p, Q))
    want = np.asarray(D.EuclideanDistance()(P, Q))
    np.testing.assert_allclose(got, want, rtol=1e-6)
