"""Deadline-bounded backend probe: a hung accelerator init must never wedge
the caller (MULTICHIP_r04 rc=124 postmortem — the round-4 axon outage had a
hang-mode where ``jax.devices()`` blocked forever and the in-process probe
took the CPU-only dryrun down with it). SURVEY.md §5.3 failure handling."""

import json
import os
import subprocess
import sys
import time

import pytest

from opencv_facerecognizer_tpu.utils import backend_probe


@pytest.fixture()
def clean_env(monkeypatch):
    """The override env var must not leak into tests from the ambient shell
    (the documented outage workflow exports it)."""
    monkeypatch.delenv(backend_probe.FORCE_CPU_ENV, raising=False)


def test_hanging_probe_is_killed_at_deadline(clean_env):
    """Simulated hang-mode: the child sleeps far past the deadline; the
    caller must return (False, hang reason) promptly instead of blocking."""
    t0 = time.perf_counter()
    usable, reason = backend_probe.probe_default_backend(
        timeout_s=1.5, probe_source="import time; time.sleep(60)"
    )
    elapsed = time.perf_counter() - t0
    assert not usable
    assert "deadline" in reason
    assert elapsed < 10.0  # killed at ~1.5s, not after the child's 60s


def test_healthy_probe_reports_usable(clean_env):
    usable, reason = backend_probe.probe_default_backend(
        timeout_s=30.0, probe_source="import sys; sys.exit(0)"
    )
    assert usable and reason == "ok"


def test_too_few_devices_rc_maps_to_reason(clean_env):
    usable, reason = backend_probe.probe_default_backend(
        min_devices=8, timeout_s=30.0, probe_source="import sys; sys.exit(3)"
    )
    assert not usable
    assert "fewer than 8" in reason


def test_cpu_fallback_rejected_when_disallowed(clean_env):
    usable, reason = backend_probe.probe_default_backend(
        timeout_s=30.0, allow_cpu=False, probe_source="import sys; sys.exit(4)"
    )
    assert not usable
    assert "CPU" in reason


def test_cpu_fallback_source_detects_cpu_backend(clean_env):
    """Real child (not injected): under this box's forced-CPU test backend
    the allow_cpu=False source must reject with the CPU reason, proving the
    platform check works against an actual silent-CPU default."""
    usable, reason = backend_probe.probe_default_backend(
        timeout_s=120.0,
        allow_cpu=False,
        probe_source=(
            "import jax; jax.config.update('jax_platforms', 'cpu')\n"
            + backend_probe._probe_source(1, allow_cpu=False)
        ),
    )
    assert not usable
    assert "CPU" in reason


def test_init_failure_rc_maps_to_reason(clean_env):
    usable, reason = backend_probe.probe_default_backend(
        timeout_s=30.0, probe_source="import sys; sys.exit(7)"
    )
    assert not usable
    assert "rc=7" in reason


def test_force_cpu_env_skips_probe(monkeypatch):
    """The override must short-circuit without spawning anything — it exists
    for when even the bounded deadline is unwanted latency."""
    monkeypatch.setenv(backend_probe.FORCE_CPU_ENV, "1")
    t0 = time.perf_counter()
    usable, reason = backend_probe.probe_default_backend(
        timeout_s=30.0, probe_source="import time; time.sleep(60)"
    )
    assert not usable
    assert backend_probe.FORCE_CPU_ENV in reason
    assert time.perf_counter() - t0 < 0.5


def test_dryrun_probe_falls_back_without_touching_backend(monkeypatch):
    """__graft_entry__'s usability gate must route through the subprocess
    probe (env override honored => no in-process backend init to hang)."""
    import __graft_entry__ as ge

    monkeypatch.setenv(backend_probe.FORCE_CPU_ENV, "1")
    assert ge._default_backend_usable(8) is False


@pytest.mark.slow
def test_bench_fast_fails_structured_when_backend_down():
    """bench.py with the backend forced-unusable must emit ONE structured
    JSON line (error=backend_unavailable) and exit rc=3 quickly — not hang,
    not traceback (BENCH_r04.json failure mode)."""
    env = dict(os.environ)
    env[backend_probe.FORCE_CPU_ENV] = "1"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "bench.py")],
        capture_output=True, text=True, timeout=120, env=env, cwd=repo,
    )
    assert proc.returncode == 3, proc.stderr[-2000:]
    line = proc.stdout.strip().splitlines()[-1]
    payload = json.loads(line)
    assert payload["error"] == "backend_unavailable"
    assert payload["value"] is None
