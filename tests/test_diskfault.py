"""Storage-fault tolerance suite (ISSUE 15): the disk STAYS broken and
the writer must degrade, not die.

Covers the ``storage`` fault boundary (enospc/eio/slow_fsync/read_error,
one injector threaded through every durable path), the degraded-
durability state machine (refused-closed enrollments, per-sink shed
accounting, probe re-arm), the disk-pressure watermark ladder, the
journal torn-tail seal-at-open satellite, the checkpoint-GC error
counter, the offline verifier's unreadable-vs-corrupt rc split, tracing
sinks under injected write failure, and the fast deterministic tier-1
variant of ``chaos_soak.py --scenario disk``.
"""

import errno
import importlib.util
import json
import os
import types

import numpy as np
import pytest

from opencv_facerecognizer_tpu.parallel import ShardedGallery, make_mesh
from opencv_facerecognizer_tpu.runtime import (
    DurabilityDegradedError,
    DurabilityMonitor,
    FaultInjector,
    StateLifecycle,
    WALTailer,
    disk_free_objective,
)
from opencv_facerecognizer_tpu.runtime.journal import (
    DeadLetterJournal,
    RotatingJournal,
)
from opencv_facerecognizer_tpu.runtime.resilience import (
    DISK_CRITICAL,
    DISK_OK,
    DISK_WARN,
)
from opencv_facerecognizer_tpu.runtime.state_store import CheckpointStore
from opencv_facerecognizer_tpu.utils import metric_names as mn
from opencv_facerecognizer_tpu.utils.metrics import Metrics
from opencv_facerecognizer_tpu.utils.tracing import Tracer, make_span_journal

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_spec = importlib.util.spec_from_file_location(
    "chaos_soak_disk", os.path.join(REPO_ROOT, "scripts", "chaos_soak.py"))
chaos_soak = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(chaos_soak)

_vspec = importlib.util.spec_from_file_location(
    "verify_checkpoint_disk",
    os.path.join(REPO_ROOT, "scripts", "verify_checkpoint.py"))
verify_checkpoint = importlib.util.module_from_spec(_vspec)
_vspec.loader.exec_module(verify_checkpoint)

DIM = 8


def _lifecycle(tmp_path, metrics=None, injector=None, tracer=None):
    mesh = make_mesh()
    gallery = ShardedGallery(capacity=64, dim=DIM, mesh=mesh)
    names = []
    state = StateLifecycle(str(tmp_path), metrics=metrics,
                           checkpoint_wal_rows=1 << 30,
                           checkpoint_every_s=1e9,
                           fault_injector=injector, tracer=tracer)
    state.recover(gallery, names)
    return state, gallery, names


def _enroll(state, gallery, names, rng, subject):
    emb = rng.normal(size=(2, DIM)).astype(np.float32)
    label = len(names)
    labels = np.full(2, label, np.int32)
    seq = state.append_enrollment(
        emb, labels, subject=subject, label=label,
        apply_fn=lambda: gallery.add(emb, labels))
    names.append(subject)
    return seq, emb, labels


# ---------------- the storage fault boundary ----------------


def test_storage_boundary_write_faults_raise_the_right_errno():
    inj = FaultInjector(seed=0)
    inj.script("storage", "enospc", "eio")
    with pytest.raises(OSError) as exc:
        inj.on_storage("unit")
    assert exc.value.errno == errno.ENOSPC
    with pytest.raises(OSError) as exc:
        inj.on_storage("unit")
    assert exc.value.errno == errno.EIO
    inj.on_storage("unit")  # queue drained: passthrough
    assert inj.summary() == {"storage:enospc": 1, "storage:eio": 1}


def test_storage_boundary_filters_read_vs_write_kinds():
    """A scripted read_error waits for a READ crossing instead of being
    burned by a write, and vice versa — one queue, two directions."""
    inj = FaultInjector(seed=0)
    inj.script("storage", "read_error")
    inj.on_storage("write-crossing")  # must NOT consume the read fault
    with pytest.raises(OSError):
        inj.on_storage_read("read-crossing")
    inj.script("storage", "enospc")
    inj.on_storage_read("read-crossing")  # must NOT consume the write fault
    with pytest.raises(OSError):
        inj.on_storage("write-crossing")


def test_storage_slow_fsync_stalls_but_succeeds(tmp_path):
    import time as _time

    inj = FaultInjector(seed=0, slow_fsync_s=0.05)
    inj.script("storage", "slow_fsync")
    t0 = _time.monotonic()
    inj.on_storage("unit")
    assert _time.monotonic() - t0 >= 0.04  # stalled, not raised


def test_storage_rates_validate_at_construction():
    with pytest.raises(ValueError):
        FaultInjector(rates={"storage": {"bogus": 0.5}})


# ---------------- WAL under ENOSPC: the degraded flip ----------------


def test_sustained_wal_enospc_flips_degraded_and_probe_rearms(tmp_path):
    rng = np.random.default_rng(7)
    metrics = Metrics()
    inj = FaultInjector(seed=7)
    state, gallery, names = _lifecycle(tmp_path, metrics=metrics,
                                       injector=inj)
    statuses = []
    mon = DurabilityMonitor(state, metrics=metrics, degraded_after=2,
                            probe_interval_s=0.01, fault_injector=inj,
                            publish=statuses.append)
    assert state.durability is mon
    _enroll(state, gallery, names, rng, "clean")
    acked_rows = int(gallery.size)

    inj.rates["storage"] = {"enospc": 1.0}
    refusals = []
    for i in range(5):
        with pytest.raises((OSError, DurabilityDegradedError)) as exc:
            _enroll(state, gallery, names, rng, f"doomed_{i}")
        refusals.append(exc.value)
    # Exactly degraded_after OSErrors before the flip; refused closed after.
    assert sum(isinstance(e, OSError)
               and not isinstance(e, DurabilityDegradedError)
               for e in refusals) == 2
    assert sum(isinstance(e, DurabilityDegradedError)
               for e in refusals) == 3
    assert mon.degraded and mon.degraded_reason == "wal_append_failures"
    assert metrics.counter(mn.WAL_APPEND_ERRORS) == 2
    assert metrics.counter(mn.ENROLLMENTS_REFUSED_DEGRADED) == 3
    assert metrics.counter(mn.DURABILITY_DEGRADED_TRANSITIONS) == 1
    assert [s["status"] for s in statuses] == ["durability_degraded"]
    # Nothing refused ever touched the gallery — the ack never lies.
    assert int(gallery.size) == acked_rows

    # Probe fails while the fault persists; re-arms the moment it clears.
    assert not mon.probe_now()
    assert metrics.counter(mn.DURABILITY_PROBE_FAILURES) == 1
    inj.rates["storage"] = {}
    assert mon.probe_now()
    assert not mon.degraded
    assert metrics.counter(mn.DURABILITY_REARMS) == 1
    assert statuses[-1]["status"] == "durability_restored"
    seq, emb, labels = _enroll(state, gallery, names, rng, "after")

    # Zero acked loss across a restart: only the acked rows come back.
    g2 = ShardedGallery(capacity=64, dim=DIM, mesh=make_mesh())
    names2 = []
    StateLifecycle(str(tmp_path), metrics=Metrics()).recover(g2, names2)
    assert int(g2.size) == acked_rows + 2
    assert names2 == names


def test_serving_tick_never_probes(tmp_path):
    """The serving loop's tick (probe=False) must never run the recovery
    probe: a blocking fsync against a disk known broken would wedge the
    very serving degraded mode exists to protect. Probing is the
    background thread's job (tick(probe=True))."""
    metrics = Metrics()
    state = types.SimpleNamespace(state_dir=str(tmp_path), durability=None)
    mon = DurabilityMonitor(state, metrics=metrics, degraded_after=1,
                            probe_interval_s=0.0)
    mon.note_wal_failure(OSError(errno.ENOSPC, "boom"))
    assert mon.degraded
    mon.tick(force=True)  # the serving-loop form
    assert metrics.counter(mn.DURABILITY_PROBES) == 0
    assert mon.degraded
    mon.tick(force=True, probe=True)  # the background-thread form
    assert metrics.counter(mn.DURABILITY_PROBES) == 1
    assert not mon.degraded


def test_wal_success_resets_the_failure_streak(tmp_path):
    rng = np.random.default_rng(3)
    inj = FaultInjector(seed=3)
    metrics = Metrics()
    state, gallery, names = _lifecycle(tmp_path, metrics=metrics,
                                       injector=inj)
    mon = DurabilityMonitor(state, metrics=metrics, degraded_after=2,
                            fault_injector=inj)
    # fail, succeed, fail: never two CONSECUTIVE failures -> never flips.
    # (each failed append also burns one scripted fault on its abort
    # tombstone, so queue two per failure)
    for i in range(2):
        inj.script("storage", "eio", "eio")
        with pytest.raises(OSError):
            _enroll(state, gallery, names, rng, f"fail_{i}")
        _enroll(state, gallery, names, rng, f"ok_{i}")
    assert not mon.degraded
    assert metrics.counter(mn.WAL_APPEND_ERRORS) == 2


# ---------------- disk-pressure watermarks ----------------


def _fake_statvfs(holder):
    def fn(_path):
        return types.SimpleNamespace(f_bavail=int(holder["free"]),
                                     f_frsize=1)

    return fn


def test_watermark_ladder_warn_critical_and_recovery(tmp_path):
    rng = np.random.default_rng(5)
    metrics = Metrics()
    state, gallery, names = _lifecycle(tmp_path, metrics=metrics)
    _enroll(state, gallery, names, rng, "seed")
    watermark = 1 << 20
    disk = {"free": float(watermark * 4)}
    mon = DurabilityMonitor(state, metrics=metrics, degraded_after=2,
                            probe_interval_s=0.01,
                            low_watermark_bytes=watermark,
                            statvfs_fn=_fake_statvfs(disk))
    tracer = Tracer(dump_dir=str(tmp_path / "flight"), metrics=metrics)
    journal = DeadLetterJournal(str(tmp_path / "dl.jsonl"), metrics=metrics)
    mon.attach_sinks(journal=journal, tracer=tracer)
    keep_before = state.store.keep
    dumps_before = tracer.keep_dumps

    # No background thread in this test: manual ticks always win the
    # claim, so single-tick assertions are exact here. probe=True takes
    # the background thread's role (the serving loop never probes).
    mon.tick(force=True)
    assert mon.disk_state == DISK_OK
    assert mon.free_bytes() == watermark * 4  # the gauge's shared sample

    # Warn: ONE preemptive compaction + ONE retention shrink per episode.
    disk["free"] = watermark * 0.5
    mon.tick(force=True)
    mon.tick(force=True)  # second tick inside the episode: no double fire
    assert mon.disk_state == DISK_WARN
    assert metrics.counter(mn.DISK_PRESSURE_COMPACTIONS) == 1
    assert metrics.counter(mn.DISK_PRESSURE_RETENTION_SHRINKS) == 1
    assert state.store.keep == 1
    assert tracer.keep_dumps == 1
    assert journal.backups == 0
    assert not mon.degraded  # warn is pressure relief, not refusal

    # Critical pre-empts the degraded flip BEFORE any ENOSPC lands.
    disk["free"] = watermark / 12.0
    mon.tick(force=True)
    assert mon.disk_state == DISK_CRITICAL
    assert mon.degraded and mon.degraded_reason == "disk_critical"
    with pytest.raises(DurabilityDegradedError):
        _enroll(state, gallery, names, rng, "refused")
    # The probe REFUSES to re-arm while the disk stays critical.
    assert mon.probe_now()
    assert mon.degraded

    # Space returns: retention restored, probe re-arms, enrolls flow.
    disk["free"] = float(watermark * 4)
    mon.tick(force=True, probe=True)
    assert mon.disk_state == DISK_OK
    assert state.store.keep == keep_before
    assert tracer.keep_dumps == dumps_before
    assert not mon.degraded
    _enroll(state, gallery, names, rng, "recovered")


def test_disk_free_objective_burn_semantics():
    holder = {"free": 6e6}
    obj = disk_free_objective(lambda: holder["free"], 1e6)
    assert obj.value_fn() == pytest.approx(1 / 6)
    holder["free"] = 1e6  # exactly the watermark: burn 1.0 (warn)
    assert obj.value_fn() == pytest.approx(1.0)
    holder["free"] = 1e6 / 6  # a sixth of it: burn 6.0 (critical)
    assert obj.value_fn() == pytest.approx(6.0)
    holder["free"] = float("inf")  # no sample yet: no data is not a breach
    assert obj.value_fn() == 0.0
    with pytest.raises(ValueError):
        disk_free_objective(lambda: 1.0, 0)


# ---------------- satellite: journal torn-tail seal-at-open ----------------


def test_journal_enospc_torn_line_sealed_at_next_open(tmp_path):
    """An ENOSPC-torn line is sealed at next open, never replayed, never
    double-counted — and the record that follows parses cleanly."""
    path = str(tmp_path / "dl.jsonl")
    j1 = DeadLetterJournal(path, metrics=Metrics())
    j1.append("first", [{"meta": 1}])
    j1.close()
    # A partial record with no newline: exactly what ENOSPC leaves.
    with open(path, "a") as fh:
        fh.write('{"ts": 1, "reason": "torn_by_enosp')
    metrics = Metrics()
    j2 = DeadLetterJournal(path, metrics=metrics)
    j2.append("second", [{"meta": 2}])
    j2.close()
    assert metrics.counter(mn.JOURNAL_TORN_TAILS) == 1
    reasons = [r["reason"] for r in DeadLetterJournal(path).records()]
    assert reasons == ["first", "second"]  # torn remnant skipped exactly
    with open(path) as fh:
        lines = [l for l in fh.read().split("\n") if l]
    assert len(lines) == 3  # first + isolated torn line + second
    json.loads(lines[0]), json.loads(lines[2])
    with pytest.raises(json.JSONDecodeError):
        json.loads(lines[1])


def test_journal_injected_enospc_partial_append_never_corrupts(tmp_path):
    """In-process ENOSPC on an append: the NEXT successful record must not
    glue onto whatever partial bytes landed."""
    inj = FaultInjector(seed=0)
    metrics = Metrics()
    journal = DeadLetterJournal(str(tmp_path / "dl.jsonl"), metrics=metrics,
                                fault_injector=inj)
    journal.append("before", [])
    inj.script("storage", "enospc")
    journal.append("lost", [])  # swallowed (non-strict), counted
    assert metrics.counter(mn.JOURNAL_ERRORS) == 1
    journal.append("after", [])
    journal.close()
    reasons = [r["reason"] for r in journal.records()]
    assert reasons == ["before", "after"]


def test_journal_sheds_with_exact_count_while_degraded(tmp_path):
    metrics = Metrics()
    journal = DeadLetterJournal(str(tmp_path / "dl.jsonl"), metrics=metrics)
    degraded = {"on": True}
    journal.shed_fn = lambda: degraded["on"]
    for _ in range(3):
        journal.append("shed_me", [])
    assert metrics.counter(mn.JOURNAL_SHED) == 3
    assert metrics.counter(mn.JOURNAL_RECORDS) == 0
    assert not os.path.exists(journal.path)  # no disk touched
    degraded["on"] = False
    journal.append("kept", [])
    journal.close()
    assert [r["reason"] for r in journal.records()] == ["kept"]


def test_wal_strict_appends_never_shed(tmp_path):
    """The WAL is the signal, not a sheddable sink: strict appends ignore
    shed_fn by contract."""
    j = RotatingJournal(str(tmp_path / "j.jsonl"), metrics=Metrics())
    j.shed_fn = lambda: True
    assert j.append_line('{"k": 1}', strict=True)
    j.close()
    assert [r["k"] for r in j.records()] == [1]


# ---------------- satellite: checkpoint-GC error accounting ----------------


def test_checkpoint_gc_errors_counted_not_swallowed(tmp_path, monkeypatch):
    metrics = Metrics()
    store = CheckpointStore(str(tmp_path), keep=1, metrics=metrics)
    store.save(b"one", {"n": 1})
    real_remove = os.remove

    def failing_remove(path):
        if path.endswith(".ckpt"):
            raise OSError(errno.EIO, "injected unlink failure")
        real_remove(path)

    monkeypatch.setattr(os, "remove", failing_remove)
    store.save(b"two", {"n": 2})  # retention tries to prune ckpt 1
    assert metrics.counter(mn.CHECKPOINT_GC_ERRORS) >= 1
    monkeypatch.undo()
    assert len(store.checkpoint_files()) == 2  # the prune really failed


# ---------------- satellite: verifier unreadable vs corrupt ----------------


def _make_state_with_checkpoint(tmp_path, rng):
    state, gallery, names = _lifecycle(tmp_path)
    _enroll(state, gallery, names, rng, "subject")
    assert state.checkpoint_now(wait=True)
    return state


def test_verifier_unreadable_is_cannot_verify_rc3(tmp_path):
    rng = np.random.default_rng(11)
    _make_state_with_checkpoint(tmp_path, rng)
    ckpt_dir = tmp_path / "checkpoints"
    # A directory named like a checkpoint: open() raises IsADirectoryError
    # (an OSError) — unreadable, and provably NOT corrupt.
    os.mkdir(str(ckpt_dir / "ckpt-00000099.ckpt"))
    report = verify_checkpoint.verify_state_dir(str(tmp_path))
    assert not report["ok"]
    assert report["cannot_verify"]
    assert len(report["unreadable"]) == 1
    assert report["corrupt"] == []  # never misreported as corrupt
    rc = verify_checkpoint.main([str(tmp_path)])
    assert rc == 3


def test_verifier_corruption_beats_cannot_verify_rc2(tmp_path):
    rng = np.random.default_rng(12)
    state = _make_state_with_checkpoint(tmp_path, rng)
    ckpt_dir = tmp_path / "checkpoints"
    os.mkdir(str(ckpt_dir / "ckpt-00000099.ckpt"))  # unreadable
    newest = next(p for _s, p in state.store.checkpoint_files()
                  if os.path.isfile(p))
    blob = open(newest, "rb").read()
    with open(newest, "wb") as fh:  # real damage alongside
        fh.write(blob[: len(blob) // 2])
    rc = verify_checkpoint.main([str(tmp_path)])
    assert rc == 2  # restore-from-backup beats fix-the-mount


def test_verifier_clean_state_still_rc0(tmp_path):
    rng = np.random.default_rng(13)
    _make_state_with_checkpoint(tmp_path, rng)
    assert verify_checkpoint.main([str(tmp_path)]) == 0


def test_store_verify_separates_unreadable(tmp_path):
    store = CheckpointStore(str(tmp_path), keep=3)
    store.save(b"payload", {"n": 1})
    os.mkdir(str(tmp_path / "ckpt-00000099.ckpt"))
    sweep = store.verify()
    assert len(sweep["ok"]) == 1
    assert len(sweep["unreadable"]) == 1
    assert sweep["corrupt"] == []


# ---------------- satellite: tracing sinks under write failure ----------------


def test_flight_dump_write_failure_counts_and_never_raises(tmp_path):
    metrics = Metrics()
    inj = FaultInjector(seed=0)
    tracer = Tracer(dump_dir=str(tmp_path / "flight"), metrics=metrics,
                    fault_injector=inj)
    tracer.emit(tracer.new_trace(), "unit")
    inj.script("storage", "eio")
    assert tracer.dump("broken", force=True) is None  # shed, not raised
    assert metrics.counter(mn.TRACE_DUMP_ERRORS) == 1
    assert tracer.dump("works", force=True) is not None
    assert metrics.counter(mn.TRACE_DUMPS) == 1


def test_span_sink_write_failure_counts_per_sink(tmp_path):
    metrics = Metrics()
    inj = FaultInjector(seed=0)
    sink = make_span_journal(str(tmp_path / "spans.jsonl"), metrics=metrics,
                             fault_injector=inj)
    tracer = Tracer(span_sink=sink, metrics=metrics)
    inj.script("storage", "enospc")
    tracer.emit(tracer.new_trace(), "doomed")  # must NOT raise
    assert metrics.counter(mn.TRACE_SPAN_ERRORS) == 1
    assert metrics.counter(mn.JOURNAL_ERRORS) == 0  # per-sink, not shared
    tracer.emit(tracer.new_trace(), "fine")
    sink.close()
    assert sum(1 for _ in sink.records()) == 1


def test_dump_and_span_shed_while_degraded(tmp_path):
    metrics = Metrics()
    sink = make_span_journal(str(tmp_path / "spans.jsonl"), metrics=metrics)
    tracer = Tracer(dump_dir=str(tmp_path / "flight"), span_sink=sink,
                    metrics=metrics)
    state = types.SimpleNamespace(state_dir=str(tmp_path), durability=None)
    mon = DurabilityMonitor(state, metrics=metrics, degraded_after=1)
    mon.attach_sinks(span_sink=sink, tracer=tracer)
    mon.note_wal_failure(OSError(errno.ENOSPC, "boom"))
    assert mon.degraded
    tracer.emit(tracer.new_trace(), "shed_me")
    assert tracer.dump("shed_me", force=True) is None
    assert metrics.counter(mn.TRACE_SPANS_SHED) == 1
    assert metrics.counter(mn.TRACE_DUMPS_SHED) == 1
    assert mon.probe_now()  # tmp-dir probe write succeeds -> re-arm
    tracer.emit(tracer.new_trace(), "kept")
    assert tracer.dump("kept", force=True) is not None


# ---------------- tailer reads + rollout stage writes ----------------


def test_tailer_read_error_is_counted_poll_error(tmp_path):
    wal = tmp_path / "enroll.wal"
    wal.write_text('{"kind": "enroll", "seq": 1}\n')
    metrics = Metrics()
    inj = FaultInjector(seed=0)
    tailer = WALTailer(str(wal), metrics=metrics, fault_injector=inj)
    inj.script("storage", "read_error")
    records, info = tailer.poll()
    assert records == [] and info.get("error")
    assert metrics.counter(mn.REPLICATION_POLL_ERRORS) == 1
    records, _info = tailer.poll()  # transient: the next poll recovers
    assert len(records) == 1


def test_rollout_stage_append_enospc_never_advances_watermark(tmp_path):
    from opencv_facerecognizer_tpu.runtime.rollout import ReEmbedStage

    inj = FaultInjector(seed=0)
    stage = ReEmbedStage(str(tmp_path), to_version=2, dim=DIM,
                         metrics=Metrics(), fault_injector=inj)
    emb = np.ones((4, DIM), np.float32)
    labels = np.zeros(4, np.int32)
    inj.script("storage", "enospc")
    with pytest.raises(OSError):
        stage.stage_chunk(0, emb, labels)
    assert stage.watermark == 0  # the ack (watermark) never lies
    stage.stage_chunk(0, emb, labels)
    assert stage.watermark == 4


# ---------------- registry plumbing ----------------


def test_new_metric_names_registered_and_unique():
    for name in ("durability_state", "durability_degraded_transitions",
                 "durability_rearms", "durability_probes",
                 "durability_probe_failures", "enrollments_refused_degraded",
                 "disk_free_bytes", "disk_pressure_state",
                 "disk_pressure_compactions",
                 "disk_pressure_retention_shrinks", "wal_append_errors",
                 "checkpoint_gc_errors", "journal_torn_tails",
                 "journal_shed", "trace_span_errors", "trace_spans_shed",
                 "trace_dumps_shed"):
        assert name in mn.all_names(), name
    names = mn.all_names()
    assert len(names) == len(set(names))


# ---------------- the fast deterministic chaos variant (tier-1) ----------------


def test_disk_chaos_fast_deterministic():
    """`chaos_soak.py --scenario disk` in miniature: seed 7, 2 simulated
    seconds — full disk mid-enrollment, EIO mid-checkpoint, slow fsync
    under load, watermark ladder, recovery — passing only with zero
    acked loss, exact ledger + per-sink accounting, refused-enrollment
    statuses during the outage, and a clean automatic re-arm."""
    report = chaos_soak.run_disk(seconds=2.0, seed=7)
    assert report["ok"], report["failures"]
    assert report["acked_enrollments"] >= 5
    assert report["enospc_refusals"] == {"oserror": 2, "closed": 4}
    acct = report["sink_accounting"]
    assert acct["wal_append_errors"] == 2
    assert acct["checkpoint_failures"] == 1
    assert acct["durability_degraded_transitions"] == 2  # enospc + critical
    assert acct["durability_rearms"] == 2
    assert acct["journal_shed"] >= 1
    assert acct["trace_dumps_shed"] >= 1
    assert acct["trace_spans_shed"] >= 1
    ledger = report["shutdown"]["ledger"]
    assert ledger["admitted"] == ledger["completed"] > 0
    assert report["verify"]["ok"]
