"""Crash-safe state lifecycle suite (runtime.state_store): atomic
checksummed checkpoints with corrupt-fallback, the enrollment WAL's
write-ahead/replay/torn-tail semantics, background checkpointing's
single-flight guard, graceful shutdown, and the seeded crash-recovery
chaos scenario (``scripts/chaos_soak.py --scenario recovery`` — fast
deterministic variant in tier-1, the long randomized soak marked slow,
mirroring the PR 1/PR 3 chaos split)."""

import importlib.util
import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from opencv_facerecognizer_tpu.parallel import ShardedGallery, make_mesh
from opencv_facerecognizer_tpu.runtime import (
    FakeConnector,
    RecognizerService,
    StateLifecycle,
    graceful_shutdown,
)
from opencv_facerecognizer_tpu.runtime.connector import encode_frame
from opencv_facerecognizer_tpu.runtime.fakes import InstantPipeline
from opencv_facerecognizer_tpu.runtime.faults import (
    FaultInjector,
    InjectedCrashError,
)
from opencv_facerecognizer_tpu.runtime.recognizer import (
    FRAME_TOPIC,
    RESULT_TOPIC,
)
from opencv_facerecognizer_tpu.runtime.state_store import (
    CheckpointStore,
    EnrollmentWAL,
)
from opencv_facerecognizer_tpu.utils.metrics import Metrics

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_spec = importlib.util.spec_from_file_location(
    "chaos_soak_recovery", os.path.join(REPO_ROOT, "scripts", "chaos_soak.py"))
chaos_soak = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(chaos_soak)

_vspec = importlib.util.spec_from_file_location(
    "verify_checkpoint", os.path.join(REPO_ROOT, "scripts",
                                      "verify_checkpoint.py"))
verify_checkpoint = importlib.util.module_from_spec(_vspec)
_vspec.loader.exec_module(verify_checkpoint)

DIM = 8
RNG = np.random.default_rng(31)


@pytest.fixture(scope="module")
def mesh():
    return make_mesh()


def _gallery(mesh, capacity=64, store_dtype=None):
    kwargs = {} if store_dtype is None else {"store_dtype": store_dtype}
    return ShardedGallery(capacity=capacity, dim=DIM, mesh=mesh, **kwargs)


def _wait(cond, timeout=10.0, interval=0.02) -> bool:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return cond()


# ---------- CheckpointStore ----------


def test_checkpoint_store_roundtrip_retention_and_seq(tmp_path):
    m = Metrics()
    store = CheckpointStore(str(tmp_path), keep=3, metrics=m)
    for i in range(5):
        store.save(f"payload-{i}".encode(), {"i": i})
    files = store.checkpoint_files()
    assert len(files) == 3  # retention pruned the two oldest
    assert [seq for seq, _ in files] == [5, 4, 3]
    header, payload, path = store.load_latest()
    assert payload == b"payload-4"
    assert header["meta"]["i"] == 4
    assert header["seq"] == 5
    assert m.counter("checkpoints_written") == 5
    # seq survives a "restart" (fresh store over the same dir)
    assert CheckpointStore(str(tmp_path)).next_seq() == 6
    # no tmp leftovers from the atomic writes
    assert not [n for n in os.listdir(tmp_path) if n.endswith(".tmp")]


def test_checkpoint_store_falls_back_past_corrupt_newest(tmp_path):
    m = Metrics()
    store = CheckpointStore(str(tmp_path), keep=3, metrics=m)
    store.save(b"old-good", {"gen": "old"})
    newest = store.save(b"new-doomed", {"gen": "new"})
    blob = open(newest, "rb").read()
    open(newest, "wb").write(blob[: len(blob) // 2])  # torn media
    header, payload, _path = store.load_latest()
    assert payload == b"old-good"
    assert m.counter("checkpoints_corrupt") == 1
    quarantined = [n for n in os.listdir(tmp_path) if n.endswith(".corrupt")]
    assert len(quarantined) == 1
    # Quarantine means the corrupt file is not re-counted on a re-scan.
    store.load_latest()
    assert m.counter("checkpoints_corrupt") == 1


def test_checkpoint_store_rejects_garbage_and_checksum_flip(tmp_path):
    m = Metrics()
    store = CheckpointStore(str(tmp_path), keep=3, metrics=m)
    path = store.save(b"real", {})
    # Flip a payload byte WITHOUT touching the framing: sha256 must catch.
    blob = bytearray(open(path, "rb").read())
    blob[-1] ^= 0xFF
    open(path, "wb").write(bytes(blob))
    garbage = os.path.join(str(tmp_path), "ckpt-00009999.ckpt")
    open(garbage, "wb").write(b"not a checkpoint at all")
    assert store.load_latest() is None
    assert m.counter("checkpoints_corrupt") == 2


def test_newer_format_checkpoint_skipped_not_quarantined(tmp_path):
    """Review fix: a binary downgrade finds newer-format checkpoints —
    they are intact, so the scan must fall back past them WITHOUT
    quarantining (retention would otherwise prune valid newer state)."""
    from opencv_facerecognizer_tpu.runtime.state_store import (
        _encode_checkpoint,
    )

    m = Metrics()
    store = CheckpointStore(str(tmp_path), keep=3, metrics=m)
    store.save(b"v1-state", {})
    payload = b"future"
    import hashlib as _h
    header = {"format_version": 99, "seq": 2, "payload_bytes": len(payload),
              "sha256": _h.sha256(payload).hexdigest(), "meta": {}}
    future = os.path.join(str(tmp_path), "ckpt-00000002.ckpt")
    open(future, "wb").write(_encode_checkpoint(header, payload))
    _header, got, _path = store.load_latest()
    assert got == b"v1-state"  # fell back past the newer file
    assert m.counter("checkpoints_version_skipped") == 1
    assert m.counter("checkpoints_corrupt") == 0
    assert os.path.exists(future)  # NOT quarantined — intact for the
    # newer binary that wrote it
    sweep = store.verify()
    assert len(sweep["newer_version"]) == 1 and not sweep["corrupt"]


def test_verify_checkpoint_rc_contract_on_bad_paths(tmp_path):
    """Review fix: a typo'd path must exit 2 with a JSON report (not
    traceback rc 1), and an empty/mistyped directory must NOT pass."""
    assert verify_checkpoint.main([str(tmp_path / "nope")]) == 2
    empty = tmp_path / "empty"
    empty.mkdir()
    assert verify_checkpoint.main([str(empty)]) == 2


def test_checkpoint_header_bitflip_detected(tmp_path):
    """Review fix: the header carries its own sha256 — a bit flip in e.g.
    the header's wal_seq digits (payload checksum untouched) must read as
    corrupt, not silently mis-dedup WAL replay."""
    from opencv_facerecognizer_tpu.runtime.state_store import (
        CHECKPOINT_MAGIC,
    )

    m = Metrics()
    store = CheckpointStore(str(tmp_path), keep=3, metrics=m)
    store.save(b"old", {"wal_seq": 3})
    newest = store.save(b"new", {"wal_seq": 7})
    blob = bytearray(open(newest, "rb").read())
    # Flip one byte INSIDE the header json region (after MAGIC + u32).
    blob[len(CHECKPOINT_MAGIC) + 4 + 5] ^= 0x01
    open(newest, "wb").write(bytes(blob))
    header, payload, _path = store.load_latest()
    assert payload == b"old"  # fell back past the header-corrupt newest
    assert m.counter("checkpoints_corrupt") == 1
    # Non-object header JSON is corruption too, never a stray crash.
    bad = os.path.join(str(tmp_path), "ckpt-00000031.ckpt")
    import hashlib as _h
    hdr = b"null"
    open(bad, "wb").write(CHECKPOINT_MAGIC + len(hdr).to_bytes(4, "big")
                          + hdr + _h.sha256(hdr).digest() + b"x")
    header2, payload2, _ = store.load_latest()
    assert payload2 == b"old"
    assert m.counter("checkpoints_corrupt") == 2


# ---------- EnrollmentWAL ----------


def _append(wal, seq, n=2, label=0, subject=None):
    emb = RNG.normal(size=(n, DIM)).astype(np.float32)
    wal.append_enroll(seq, emb, np.full(n, label, np.int32),
                      subject=subject, label=label)
    return emb


def test_wal_roundtrip_preserves_exact_rows(tmp_path):
    path = str(tmp_path / "enroll.wal")
    wal = EnrollmentWAL(path, metrics=Metrics())
    want = [_append(wal, seq, n=seq, label=seq - 1, subject=f"s{seq}")
            for seq in (1, 2, 3)]
    wal.close()
    got = list(EnrollmentWAL(path).enrollments())
    assert [r["seq"] for r in got] == [1, 2, 3]
    for rec, emb in zip(got, want):
        np.testing.assert_array_equal(rec["embeddings"], emb)  # bit-exact
    assert got[2]["subject"] == "s3" and got[2]["label"] == 2


def test_wal_torn_tail_is_sealed_and_skipped(tmp_path):
    path = str(tmp_path / "enroll.wal")
    m = Metrics()
    wal = EnrollmentWAL(path, metrics=m, fault_injector=None)
    _append(wal, 1)
    injector = FaultInjector(seed=0)
    injector.script("wal", "torn")
    wal._faults = injector
    with pytest.raises(InjectedCrashError):
        _append(wal, 2)
    wal.close()
    # "Restart": the torn tail must be sealed so the NEXT append cannot
    # concatenate onto it, and replay must skip it.
    m2 = Metrics()
    wal2 = EnrollmentWAL(path, metrics=m2)
    assert m2.counter("wal_torn_tails_sealed") == 1
    emb3 = _append(wal2, 3)
    records = list(wal2.enrollments())
    assert [r["seq"] for r in records] == [1, 3]
    np.testing.assert_array_equal(records[1]["embeddings"], emb3)


def test_wal_crc_guard_skips_bitflipped_record(tmp_path):
    path = str(tmp_path / "enroll.wal")
    wal = EnrollmentWAL(path, metrics=Metrics())
    _append(wal, 1)
    _append(wal, 2)
    wal.close()
    lines = open(path).read().splitlines()
    rec = json.loads(lines[0])
    b64 = rec["emb"]
    rec["emb"] = ("A" if b64[0] != "A" else "B") + b64[1:]  # payload bitflip
    lines[0] = json.dumps(rec)
    open(path, "w").write("\n".join(lines) + "\n")
    m = Metrics()
    survivors = list(EnrollmentWAL(path, metrics=m).enrollments())
    assert [r["seq"] for r in survivors] == [2]
    assert m.counter("wal_corrupt_records") == 1


def test_wal_truncate_below_compacts(tmp_path):
    path = str(tmp_path / "enroll.wal")
    wal = EnrollmentWAL(path, metrics=Metrics())
    for seq in (1, 2, 3, 4):
        _append(wal, seq)
    wal.truncate_below(2)
    assert [r["seq"] for r in wal.enrollments()] == [3, 4]
    wal.truncate_below(4)
    assert list(wal.enrollments()) == []
    # still appendable after full truncation
    _append(wal, 5)
    assert [r["seq"] for r in wal.enrollments()] == [5]


def test_wal_failed_append_seals_before_next_record(tmp_path):
    """Review fix: partial bytes landed by a FAILED append (ENOSPC mid-
    write) must be newline-sealed by the next append in the same write —
    otherwise a later acknowledged record glues onto them and both read
    as one torn line."""
    path = str(tmp_path / "enroll.wal")
    wal = EnrollmentWAL(path, metrics=Metrics())
    _append(wal, 1)
    # Simulate the failed-append aftermath: torn bytes on disk, flag set
    # (append_line sets it whenever _append_locked raises).
    with wal._lock:
        wal._append_locked('{"kind": "enroll", "seq": 2, "torn', newline=False)
    wal._needs_seal = True
    emb3 = _append(wal, 3)
    records = list(wal.enrollments())
    assert [r["seq"] for r in records] == [1, 3]  # 3 survived, isolated
    np.testing.assert_array_equal(records[1]["embeddings"], emb3)


def test_wal_reads_are_corruption_total(tmp_path):
    """Review fix: invalid UTF-8 bytes and JSON-parseable-but-non-object
    lines must be skipped by every read path (records/enrollments/max_seq/
    truncate_below), never raise out of a recovery loop."""
    path = str(tmp_path / "enroll.wal")
    wal = EnrollmentWAL(path, metrics=Metrics())
    _append(wal, 1)
    wal.close()
    with open(path, "ab") as fh:
        fh.write(b"\xff\xfe not utf8 \xf0\n")
        fh.write(b"null\n")
        fh.write(b"1234\n")
        fh.write(b'{"kind": "abort", "seq": null}\n')
    wal2 = EnrollmentWAL(path, metrics=Metrics())
    assert [r["seq"] for r in wal2.enrollments()] == [1]
    assert wal2.max_seq() == 1
    wal2.truncate_below(0)  # must not raise; garbage lines dropped
    assert [r["seq"] for r in wal2.enrollments()] == [1]
    report = verify_checkpoint.verify_state_dir(str(tmp_path))
    assert report["wal"]["valid_records"] == 1  # and the tool survives too


def test_checkpoint_read_error_raises_not_quarantines(tmp_path, monkeypatch):
    """Review fix: a transient read failure (EIO) proves nothing about the
    bytes — recovery must fail loudly, not quarantine a possibly-valid
    newest checkpoint whose WAL delta was already truncated."""
    import builtins

    m = Metrics()
    store = CheckpointStore(str(tmp_path), metrics=m)
    path = store.save(b"precious", {})
    real_open = builtins.open

    def flaky_open(file, *args, **kwargs):
        if str(file) == path:
            raise OSError(5, "Input/output error")
        return real_open(file, *args, **kwargs)

    monkeypatch.setattr(builtins, "open", flaky_open)
    with pytest.raises(OSError):
        store.load_latest()
    monkeypatch.undo()
    assert m.counter("checkpoint_read_errors") == 1
    assert m.counter("checkpoints_corrupt") == 0
    header, payload, _p = store.load_latest()  # intact after the blip
    assert payload == b"precious"


def test_journal_fsync_policy_validated(tmp_path):
    with pytest.raises(ValueError):
        EnrollmentWAL(str(tmp_path / "w"), fsync="sometimes")
    for policy in ("never", "interval", "always"):
        EnrollmentWAL(str(tmp_path / f"w-{policy}"), fsync=policy).close()


def test_wal_never_rotates_acked_records_away(tmp_path):
    """Review fix: the size bound must not unlink acknowledged records
    when checkpoints persistently fail — it warns (wal_over_bytes) and
    keeps appending instead."""
    path = str(tmp_path / "enroll.wal")
    m = Metrics()
    wal = EnrollmentWAL(path, max_bytes=256, metrics=m)
    for seq in range(1, 9):  # each record is far over 256/8 bytes
        _append(wal, seq)
    assert [r["seq"] for r in wal.enrollments()] == list(range(1, 9))
    assert not os.path.exists(path + ".1")  # nothing rotated, ever
    assert m.counter("wal_over_bytes") == 1  # warned exactly once


def test_wal_abort_tombstone_blocks_replay(tmp_path):
    """Review fix: an apply_fn failure after the (durable) append
    tombstones the record — replay must not resurrect rows the live
    gallery rolled back."""
    path = str(tmp_path / "enroll.wal")
    wal = EnrollmentWAL(path, metrics=Metrics())
    _append(wal, 1)
    _append(wal, 2)
    wal.append_abort(2)
    _append(wal, 3)
    assert [r["seq"] for r in wal.enrollments()] == [1, 3]


def test_atomic_write_failure_keeps_previous_installed(tmp_path, monkeypatch):
    """Review fix: with keep_previous, rotation happens only after the new
    bytes are durable — any failure leaves the previous file under the
    expected name, never only under .1."""
    from opencv_facerecognizer_tpu.utils import serialization

    target = tmp_path / "model.ckpt"
    serialization.atomic_write_bytes(str(target), b"v1")
    serialization.atomic_write_bytes(str(target), b"v2", keep_previous=2)
    assert target.read_bytes() == b"v2"
    assert (tmp_path / "model.ckpt.1").read_bytes() == b"v1"

    def boom(fd):
        raise OSError("injected: disk full at fsync")

    monkeypatch.setattr(serialization.os, "fsync", boom)
    with pytest.raises(OSError):
        serialization.atomic_write_bytes(str(target), b"v3", keep_previous=2)
    monkeypatch.undo()
    assert target.read_bytes() == b"v2"  # still installed
    assert (tmp_path / "model.ckpt.1").read_bytes() == b"v1"  # not shifted


# ---------- StateLifecycle ----------


def test_lifecycle_recover_dedups_after_late_crash(tmp_path, mesh):
    """The checkpoint-landed-but-WAL-not-truncated window ('late' kill):
    replay must skip every record the checkpoint's wal_seq already
    covers — no duplicate gallery rows."""
    g = _gallery(mesh)
    names = []
    injector = FaultInjector(seed=0)
    st = StateLifecycle(str(tmp_path), metrics=Metrics(),
                        checkpoint_wal_rows=1 << 30,
                        checkpoint_every_s=1e9, fault_injector=injector)
    st.bind(g, names)
    emb = RNG.normal(size=(3, DIM)).astype(np.float32)
    st.append_enrollment(emb, np.zeros(3, np.int32), subject="a", label=0,
                         apply_fn=lambda: g.add(emb, np.zeros(3, np.int32)))
    names.append("a")
    injector.script("checkpoint", "late")
    with pytest.raises(InjectedCrashError):
        st.checkpoint_now(wait=True)
    # WAL still holds the record; the installed checkpoint covers it.
    assert len(list(st.wal.enrollments())) == 1
    m = Metrics()
    g2 = _gallery(mesh)
    names2 = []
    rep = StateLifecycle(str(tmp_path), metrics=m).recover(g2, names2)
    assert rep["skipped_records"] == 1 and rep["replayed_records"] == 0
    assert g2.size == 3  # exactly once, not twice
    assert names2 == ["a"]


def test_lifecycle_apply_failure_never_resurrects_on_recovery(tmp_path, mesh):
    """Review fix end-to-end: gallery apply raises after the WAL append —
    the caller sees the failure (no ack), and a restart must NOT replay
    the tombstoned record as phantom rows."""
    g = _gallery(mesh)
    st = StateLifecycle(str(tmp_path), metrics=Metrics())
    st.bind(g, [])
    ok_emb = RNG.normal(size=(2, DIM)).astype(np.float32)
    st.append_enrollment(ok_emb, np.zeros(2, np.int32), subject="ok", label=0,
                         apply_fn=lambda: g.add(ok_emb, np.zeros(2, np.int32)))

    def failing_apply():
        raise RuntimeError("device fell over mid-add")

    bad = RNG.normal(size=(3, DIM)).astype(np.float32)
    with pytest.raises(RuntimeError, match="fell over"):
        st.append_enrollment(bad, np.ones(3, np.int32), subject="ghost",
                             label=1, apply_fn=failing_apply)
    g2 = _gallery(mesh)
    names2 = []
    StateLifecycle(str(tmp_path), metrics=Metrics()).recover(g2, names2)
    assert g2.size == 2  # the ghost's 3 rows never materialize
    assert "ghost" not in names2


def test_lifecycle_checkpoint_deferred_while_rows_pending(tmp_path):
    """Review fix: staged-but-unlanded async-grow rows (pending_rows > 0,
    e.g. a failed grow awaiting retry) must DEFER the checkpoint — a
    snapshot without them that truncated their WAL records would lose
    acknowledged enrollments."""

    class PendingGallery:
        dim = DIM
        size = 0
        pending_rows = 4

        def wait_ready(self, timeout=None):
            return True  # a FAILED grow also returns True with pending>0

        def snapshot(self):
            raise AssertionError("must not snapshot while rows are pending")

    m = Metrics()
    st = StateLifecycle(str(tmp_path), metrics=m)
    st.bind(PendingGallery(), [])
    assert st.checkpoint_now(wait=True) is False
    assert m.counter("checkpoints_deferred_pending") == 1
    assert m.counter("checkpoints_written") == 0


def test_lifecycle_checkpoint_single_flight(tmp_path, mesh):
    g = _gallery(mesh)
    m = Metrics()
    st = StateLifecycle(str(tmp_path), metrics=m)
    st.bind(g, [])
    emb = RNG.normal(size=(1, DIM)).astype(np.float32)
    st.append_enrollment(emb, np.zeros(1, np.int32),
                         apply_fn=lambda: g.add(emb, np.zeros(1, np.int32)))
    assert st._ckpt_lock.acquire(blocking=False)  # simulate one in flight
    try:
        assert st.maybe_checkpoint(force=True) is False
        assert st.checkpoint_now() is False
        assert m.counter("checkpoints_skipped_inflight") == 2
    finally:
        st._ckpt_lock.release()
    assert st.checkpoint_now(wait=True) is True
    assert list(st.wal.enrollments()) == []  # truncated after the save


def test_wal_seq_not_reused_after_abort_across_restart(tmp_path, mesh):
    """Review fix (empirically reproduced loss): recovery must seed
    _wal_seq from ALL records including abort tombstones — seeding from
    surviving enrollments would hand the aborted seq to the next
    acknowledged enrollment, which the tombstone then filters on the
    following restart."""
    g = _gallery(mesh)
    st = StateLifecycle(str(tmp_path), metrics=Metrics())
    st.bind(g, [])
    a = RNG.normal(size=(1, DIM)).astype(np.float32)
    st.append_enrollment(a, np.zeros(1, np.int32), subject="a", label=0,
                         apply_fn=lambda: g.add(a, np.zeros(1, np.int32)))
    with pytest.raises(RuntimeError):
        st.append_enrollment(a, np.ones(1, np.int32), subject="b", label=1,
                             apply_fn=lambda: (_ for _ in ()).throw(
                                 RuntimeError("apply died")))
    # Restart 1: enroll C — its seq must NOT collide with the tombstone.
    g2 = _gallery(mesh)
    st2 = StateLifecycle(str(tmp_path), metrics=Metrics())
    st2.recover(g2, [])
    assert st2.wal_seq == 2  # tombstoned seq counted, never reissued
    c = RNG.normal(size=(1, DIM)).astype(np.float32)
    st2.append_enrollment(c, np.ones(1, np.int32), subject="c", label=1,
                          apply_fn=lambda: g2.add(c, np.ones(1, np.int32)))
    # Restart 2: C must survive (the old bug filtered it as aborted).
    g3 = _gallery(mesh)
    names3 = []
    StateLifecycle(str(tmp_path), metrics=Metrics()).recover(g3, names3)
    assert g3.size == 2, g3.size
    assert names3[1] == "c"


def test_recover_falls_back_past_checksum_valid_but_undecodable(tmp_path, mesh):
    """Review fix: a checkpoint whose sha256 verifies but whose payload
    msgpack rejects must be quarantined and recovery must fall back to
    the next-older VALID checkpoint, not degrade to WAL-only."""
    from opencv_facerecognizer_tpu.runtime.state_store import (
        _encode_checkpoint,
    )
    import hashlib

    g = _gallery(mesh)
    st = StateLifecycle(str(tmp_path), metrics=Metrics())
    names = []
    st.bind(g, names)
    emb = RNG.normal(size=(3, DIM)).astype(np.float32)
    st.append_enrollment(emb, np.zeros(3, np.int32), subject="a", label=0,
                         apply_fn=lambda: g.add(emb, np.zeros(3, np.int32)))
    names.append("a")  # the enrolling service grows its own list
    assert st.checkpoint_now(wait=True)
    # Craft a NEWER checkpoint with a valid checksum over garbage payload.
    payload = b"this is not msgpack"
    header = {"format_version": 1, "seq": 99, "payload_bytes": len(payload),
              "sha256": hashlib.sha256(payload).hexdigest(),
              "meta": {"kind": "gallery", "size": 0, "capacity": 64,
                       "dim": DIM, "subject_names": [], "wal_seq": 7}}
    bad = os.path.join(str(tmp_path), "checkpoints", "ckpt-00000099.ckpt")
    open(bad, "wb").write(_encode_checkpoint(header, payload))
    m = Metrics()
    g2 = _gallery(mesh)
    names2 = []
    rep = StateLifecycle(str(tmp_path), metrics=m).recover(g2, names2)
    assert g2.size == 3  # the older VALID checkpoint won
    assert names2 == ["a"]
    assert rep["recovered_checkpoint"].endswith("ckpt-00000001.ckpt")
    assert m.counter("checkpoints_corrupt") == 1
    assert os.path.exists(bad + ".corrupt")  # quarantined


def test_append_failure_burns_seq_and_tombstones(tmp_path, mesh, monkeypatch):
    """Review fix: a failed strict append may still have landed its full
    bytes — the seq must be burned (and tombstoned best-effort), never
    reissued to the next acknowledged enrollment (two enroll records
    sharing a seq are indistinguishable on replay)."""
    g = _gallery(mesh)
    st = StateLifecycle(str(tmp_path), metrics=Metrics())
    st.bind(g, [])
    a = RNG.normal(size=(1, DIM)).astype(np.float32)
    st.append_enrollment(a, np.zeros(1, np.int32), subject="a", label=0,
                         apply_fn=lambda: g.add(a, np.zeros(1, np.int32)))
    real_append = st.wal.append_enroll

    def failing_append(*args, **kwargs):
        raise OSError("fsync blew up after the bytes landed")

    monkeypatch.setattr(st.wal, "append_enroll", failing_append)
    with pytest.raises(OSError):
        st.append_enrollment(a, np.ones(1, np.int32), subject="b", label=1,
                             apply_fn=lambda: None)
    monkeypatch.setattr(st.wal, "append_enroll", real_append)
    assert st.wal_seq == 2  # burned, not rolled back
    c = RNG.normal(size=(1, DIM)).astype(np.float32)
    seq_c = st.append_enrollment(
        c, np.ones(1, np.int32), subject="c", label=1,
        apply_fn=lambda: g.add(c, np.ones(1, np.int32)))
    assert seq_c == 3  # never reuses the burned seq
    g2 = _gallery(mesh)
    names2 = []
    StateLifecycle(str(tmp_path), metrics=Metrics()).recover(g2, names2)
    assert g2.size == 2 and names2[1] == "c"


def test_supervisor_inmemory_restore_replays_acked_tail(tmp_path, mesh):
    """Review fix: the supervisor's in-memory snapshot restore must
    replay enrollments acknowledged AFTER the snapshot's WAL stamp —
    otherwise they vanish from serving and the next durable checkpoint
    truncates their records (permanent acked loss)."""
    from opencv_facerecognizer_tpu.runtime import ServiceSupervisor

    gallery, state, service, connector, metrics = _service_stack(
        tmp_path, mesh, checkpoint_wal_rows=1 << 30, checkpoint_every_s=1e9)
    supervisor = ServiceSupervisor(service, state=state)
    supervisor.checkpoint()  # last-known-good BEFORE the enrollment
    emb = RNG.normal(size=(2, DIM)).astype(np.float32)
    state.append_enrollment(emb, np.zeros(2, np.int32), subject="late",
                            label=0,
                            apply_fn=lambda: gallery.add(emb, np.zeros(2, np.int32)))
    service.subject_names.append("late")
    assert gallery.size == 2
    # Crash restore path: rolls to the stamped snapshot, then MUST replay
    # the acknowledged tail.
    supervisor._restore_gallery()
    assert gallery.size == 2, "acked enrollment vanished from serving"
    assert service.subject_names[0] == "late"
    # The next durable checkpoint + restart must still hold it.
    assert state.checkpoint_now(wait=True)
    g2 = _gallery(mesh)
    names2 = []
    StateLifecycle(str(tmp_path), metrics=Metrics()).recover(g2, names2)
    assert g2.size == 2 and names2[0] == "late"
    state.close()


def test_forced_checkpoint_latches_past_inflight_one(tmp_path, mesh):
    """Review fix: a FORCED checkpoint (reload swap) colliding with an
    in-flight background one must stay pending — the in-flight snapshot
    may predate the swap — and be retried by the next tick."""
    g = _gallery(mesh)
    m = Metrics()
    st = StateLifecycle(str(tmp_path), metrics=m)
    st.bind(g, [])
    emb = RNG.normal(size=(1, DIM)).astype(np.float32)
    st.append_enrollment(emb, np.zeros(1, np.int32),
                         apply_fn=lambda: g.add(emb, np.zeros(1, np.int32)))
    assert st._ckpt_lock.acquire(blocking=False)  # simulate one in flight
    try:
        assert st.maybe_checkpoint(force=True) is False
        assert st._force_pending is True
        assert st.checkpoint_due()  # ticks will keep retrying
    finally:
        st._ckpt_lock.release()
    assert st.checkpoint_now(wait=True) is True
    assert st._force_pending is False  # satisfied by a post-request snapshot


def test_checkpoint_failure_backs_off(tmp_path, mesh, monkeypatch):
    """Review fix: a persistently failing save must not re-snapshot and
    re-serialize the gallery on every tick — exponential retry backoff."""
    g = _gallery(mesh)
    m = Metrics()
    st = StateLifecycle(str(tmp_path), metrics=m)
    st.bind(g, [])
    emb = RNG.normal(size=(1, DIM)).astype(np.float32)
    st.append_enrollment(emb, np.zeros(1, np.int32),
                         apply_fn=lambda: g.add(emb, np.zeros(1, np.int32)))
    # Tighten AFTER the append (so the append itself spawned nothing):
    # from here one uncovered row makes a checkpoint due.
    st.checkpoint_wal_rows = 1
    assert st.checkpoint_due() is True

    def failing_save(payload, meta, fault=None):
        raise OSError("disk full")

    monkeypatch.setattr(st.store, "save", failing_save)
    assert st.checkpoint_now(wait=True) is False
    assert m.counter("checkpoint_failures") == 1
    assert st.checkpoint_due() is False  # inside the backoff window
    assert st.tick() is None and m.counter("checkpoint_failures") == 1
    monkeypatch.undo()
    st._ckpt_retry_at = 0.0  # backoff elapsed
    assert st.checkpoint_due() is True  # rows still uncovered
    assert st.checkpoint_now(wait=True) is True
    assert st._ckpt_retry_backoff_s == 1.0  # reset on success


def test_lifecycle_dim_mismatch_is_operator_error(tmp_path, mesh):
    g = _gallery(mesh)
    st = StateLifecycle(str(tmp_path), metrics=Metrics())
    st.bind(g, [])
    emb = RNG.normal(size=(1, DIM)).astype(np.float32)
    st.append_enrollment(emb, np.zeros(1, np.int32),
                         apply_fn=lambda: g.add(emb, np.zeros(1, np.int32)))
    assert st.checkpoint_now(wait=True)
    wrong = ShardedGallery(capacity=32, dim=DIM * 2, mesh=mesh)
    with pytest.raises(ValueError, match="dim"):
        StateLifecycle(str(tmp_path), metrics=Metrics()).recover(wrong, [])


def test_bf16_serving_gallery_restores_f32_checkpoint_from_disk(tmp_path, mesh):
    """Satellite: the PR 1 swap_from cast path, exercised via
    restore-from-disk — an f32 trainer-default gallery's durable
    checkpoint recovers into a bf16 serving gallery (host mirrors stay f32
    truth; the device snapshot installs at the SERVING width) and matching
    agrees with the f32 original."""
    import jax.numpy as jnp

    f32 = _gallery(mesh, store_dtype=jnp.float32)
    emb = RNG.normal(size=(12, DIM)).astype(np.float32)
    labels = (np.arange(12) % 4).astype(np.int32)
    f32.add(emb, labels)
    st = StateLifecycle(str(tmp_path), metrics=Metrics())
    st.bind(f32, [f"s{i}" for i in range(4)])
    assert st.checkpoint_now(wait=True)

    bf16 = _gallery(mesh, store_dtype=jnp.bfloat16)
    names = []
    rep = StateLifecycle(str(tmp_path), metrics=Metrics()).recover(bf16, names)
    assert rep["checkpoint_size"] == 12
    assert bf16.size == 12
    assert bf16.data.embeddings.dtype == jnp.bfloat16  # serving width
    assert bf16._host_emb.dtype == np.float32  # host truth stays f32
    q = emb[:8] / np.linalg.norm(emb[:8], axis=-1, keepdims=True)
    l32, s32, i32 = (np.asarray(v) for v in f32.match(q, k=1))
    l16, s16, i16 = (np.asarray(v) for v in bf16.match(q, k=1))
    np.testing.assert_array_equal(l32, l16)
    np.testing.assert_array_equal(i32, i16)
    np.testing.assert_allclose(s32, s16, atol=2e-2)  # bf16 matmul on both


def test_snapshot_roundtrip_survives_second_restore(tmp_path, mesh):
    """Mid-restore kill: recovery is read-only on durable files, so a
    restore interrupted (discarded) and rerun lands identically."""
    g = _gallery(mesh)
    st = StateLifecycle(str(tmp_path), metrics=Metrics())
    st.bind(g, [])
    emb = RNG.normal(size=(4, DIM)).astype(np.float32)
    st.append_enrollment(emb, np.zeros(4, np.int32), subject="a", label=0,
                         apply_fn=lambda: g.add(emb, np.zeros(4, np.int32)))
    for _ in range(2):  # first "killed" (discarded), second must match
        g2 = _gallery(mesh)
        StateLifecycle(str(tmp_path), metrics=Metrics()).recover(g2, [])
        assert g2.size == 4
        np.testing.assert_allclose(g2.snapshot()[0][:4],
                                   g.snapshot()[0][:4], atol=0)


# ---------- service integration ----------


def _service_stack(tmp_path, mesh, **state_kwargs):
    metrics = Metrics()
    gallery = _gallery(mesh)
    pipe = InstantPipeline((16, 16))
    pipe.gallery = gallery
    state = StateLifecycle(str(tmp_path), metrics=metrics, **state_kwargs)
    connector = FakeConnector()
    service = RecognizerService(
        pipe, connector, batch_size=2, frame_shape=(16, 16),
        flush_timeout=0.02, metrics=metrics, state_store=state)
    return gallery, state, service, connector, metrics


def test_serving_loop_background_checkpoint_on_row_threshold(tmp_path, mesh):
    gallery, state, service, connector, metrics = _service_stack(
        tmp_path, mesh, checkpoint_wal_rows=3, checkpoint_every_s=1e9)
    service.start(warmup=False)
    try:
        emb = RNG.normal(size=(4, DIM)).astype(np.float32)
        state.append_enrollment(
            emb, np.zeros(4, np.int32), subject="a", label=0,
            apply_fn=lambda: gallery.add(emb, np.zeros(4, np.int32)))
        frame = np.zeros((16, 16), np.float32)
        connector.inject(FRAME_TOPIC, {**encode_frame(frame), "meta": {}})
        # The serving loop's tick must notice the over-threshold WAL and
        # background-checkpoint without any explicit call.
        assert _wait(lambda: metrics.counter("checkpoints_written") >= 1), \
            "serving loop never triggered the threshold checkpoint"
        assert _wait(
            lambda: len(list(state.wal.enrollments())) == 0), \
            "WAL not truncated after the background checkpoint"
    finally:
        service.stop()
        state.close()


def test_graceful_shutdown_drains_checkpoints_and_settles_ledger(tmp_path, mesh):
    gallery, state, service, connector, metrics = _service_stack(
        tmp_path, mesh, checkpoint_wal_rows=1 << 30, checkpoint_every_s=1e9)
    service.start(warmup=False)
    frame = np.zeros((16, 16), np.float32)
    for i in range(10):
        connector.inject(FRAME_TOPIC,
                         {**encode_frame(frame), "meta": {"seq": i}})
    emb = RNG.normal(size=(2, DIM)).astype(np.float32)
    state.append_enrollment(emb, np.zeros(2, np.int32), subject="a", label=0,
                            apply_fn=lambda: gallery.add(emb, np.zeros(2, np.int32)))
    report = graceful_shutdown(service, state=state, drain_timeout=30.0)
    assert report["clean"], report
    assert report["ledger"]["in_system"] == 0
    assert len(connector.messages(RESULT_TOPIC)) == 10  # all published
    assert report["final_checkpoint"] is True
    assert list(EnrollmentWAL(os.path.join(str(tmp_path),
                                           "enroll.wal")).enrollments()) == []
    # Restart recovers the enrollment from the final checkpoint alone.
    g2 = _gallery(mesh)
    rep = StateLifecycle(str(tmp_path), metrics=Metrics()).recover(g2, [])
    assert rep["replayed_records"] == 0 and g2.size == 2


def test_sigterm_subprocess_drains_and_exits_zero(tmp_path, mesh):
    """Real-signal end-to-end: a serving process over the fake backend
    gets SIGTERM mid-stream and must drain, write a final checkpoint, and
    exit 0 — the deploy-level stop contract."""
    script = f"""
import os, signal, sys, threading, time
import numpy as np
os.environ.setdefault("JAX_PLATFORMS", "cpu")
from opencv_facerecognizer_tpu.parallel import ShardedGallery, make_mesh
from opencv_facerecognizer_tpu.runtime import (
    FakeConnector, RecognizerService, StateLifecycle, graceful_shutdown)
from opencv_facerecognizer_tpu.runtime.fakes import InstantPipeline
from opencv_facerecognizer_tpu.runtime.connector import encode_frame
from opencv_facerecognizer_tpu.runtime.recognizer import FRAME_TOPIC

term = threading.Event()
signal.signal(signal.SIGTERM, lambda s, f: term.set())
gallery = ShardedGallery(capacity=32, dim=8, mesh=make_mesh())
pipe = InstantPipeline((16, 16))
pipe.gallery = gallery
state = StateLifecycle({str(tmp_path)!r})
connector = FakeConnector()
service = RecognizerService(pipe, connector, batch_size=2,
                            frame_shape=(16, 16), flush_timeout=0.02,
                            state_store=state)
service.start(warmup=False)
frame = np.zeros((16, 16), np.float32)
emb = np.ones((1, 8), np.float32)
state.append_enrollment(emb, np.zeros(1, np.int32), subject="s", label=0,
                        apply_fn=lambda: gallery.add(emb, np.zeros(1, np.int32)))
print("READY", flush=True)
i = 0
while not term.is_set():
    connector.inject(FRAME_TOPIC, dict(encode_frame(frame), meta=dict(seq=i)))
    i += 1
    time.sleep(0.01)
report = graceful_shutdown(service, state=state, drain_timeout=30.0)
print("REPORT", report["clean"], report["ledger"]["in_system"], flush=True)
sys.exit(0 if report["clean"] else 3)
"""
    proc = subprocess.Popen([sys.executable, "-c", script], cwd=REPO_ROOT,
                            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                            text=True, env={**os.environ,
                                            "JAX_PLATFORMS": "cpu"})
    try:
        deadline = time.monotonic() + 120
        line = ""
        while time.monotonic() < deadline:
            line = proc.stdout.readline()
            if "READY" in line:
                break
        assert "READY" in line, "subprocess never came up"
        time.sleep(0.3)  # let some frames flow
        proc.send_signal(signal.SIGTERM)
        out, err = proc.communicate(timeout=120)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.communicate()
    assert proc.returncode == 0, (out, err)
    assert "REPORT True" in out, (out, err)
    # The state dir holds a verified final checkpoint.
    report = verify_checkpoint.verify_state_dir(str(tmp_path))
    assert report["ok"], report
    g2 = _gallery(mesh)
    rep = StateLifecycle(str(tmp_path), metrics=Metrics()).recover(g2, [])
    assert g2.size == 1 and rep["replayed_records"] == 0


# ---------- offline verification ----------


def test_verify_checkpoint_is_strictly_read_only(tmp_path, mesh):
    """Review fix: the offline verifier must not mutate the state it
    verifies — in particular it must NOT seal a torn WAL tail (a live
    writer could be mid-append on those exact bytes)."""
    g = _gallery(mesh)
    st = StateLifecycle(str(tmp_path), metrics=Metrics())
    st.bind(g, [])
    emb = RNG.normal(size=(1, DIM)).astype(np.float32)
    st.append_enrollment(emb, np.zeros(1, np.int32),
                         apply_fn=lambda: g.add(emb, np.zeros(1, np.int32)))
    st.checkpoint_now(wait=True)
    wal_path = os.path.join(str(tmp_path), "enroll.wal")
    with open(wal_path, "a") as fh:
        fh.write('{"kind": "enroll", "seq": 99, "torn...')  # no newline
    before = open(wal_path, "rb").read()
    mtimes = {p: os.path.getmtime(p)
              for _s, p in st.store.checkpoint_files()}
    report = verify_checkpoint.verify_state_dir(str(tmp_path))
    assert open(wal_path, "rb").read() == before  # byte-identical
    for _s, p in st.store.checkpoint_files():
        assert os.path.getmtime(p) == mtimes[p]
    assert report["ok"]  # a torn line is the expected crash signature
    assert report["wal"]["torn_lines"] == 1
    assert report["wal"]["corrupt_records"] == 0


def test_verify_checkpoint_wal_semantics(tmp_path, mesh):
    """Review fix: a SEALED torn line mid-file (crash remnant + restart +
    later appends) stays a warning — only a parseable-but-crc-broken
    (i.e. acknowledged, now unreadable) record fails verification."""
    g = _gallery(mesh)
    st = StateLifecycle(str(tmp_path), metrics=Metrics())
    st.bind(g, [])
    emb = RNG.normal(size=(1, DIM)).astype(np.float32)
    st.append_enrollment(emb, np.zeros(1, np.int32),
                         apply_fn=lambda: g.add(emb, np.zeros(1, np.int32)))
    wal_path = os.path.join(str(tmp_path), "enroll.wal")
    with open(wal_path, "a") as fh:
        fh.write('{"kind": "enroll", "seq": 9, "torn...')  # crash remnant
    st.wal.close()
    # Restart seals the torn tail; a post-restart enrollment appends
    # AFTER it — the torn line is now mid-file.
    st2 = StateLifecycle(str(tmp_path), metrics=Metrics())
    st2.bind(g, [])
    st2._wal_seq = 1
    emb2 = RNG.normal(size=(1, DIM)).astype(np.float32)
    st2.append_enrollment(emb2, np.zeros(1, np.int32),
                          apply_fn=lambda: g.add(emb2, np.zeros(1, np.int32)))
    report = verify_checkpoint.verify_state_dir(str(tmp_path))
    assert report["ok"], report  # healthy despite the sealed remnant
    assert report["wal"]["torn_lines"] == 1
    assert report["wal"]["valid_records"] == 2
    # Now bitflip an ACKED record's payload: real corruption, rc 2.
    lines = open(wal_path).read().splitlines()
    rec = json.loads(lines[0])
    rec["emb"] = ("A" if rec["emb"][0] != "A" else "B") + rec["emb"][1:]
    lines[0] = json.dumps(rec)
    open(wal_path, "w").write("\n".join(lines) + "\n")
    assert verify_checkpoint.main([str(tmp_path)]) == 2


def test_verify_checkpoint_script_rc_semantics(tmp_path, mesh):
    g = _gallery(mesh)
    st = StateLifecycle(str(tmp_path), metrics=Metrics())
    st.bind(g, [])
    emb = RNG.normal(size=(2, DIM)).astype(np.float32)
    st.append_enrollment(emb, np.zeros(2, np.int32),
                         apply_fn=lambda: g.add(emb, np.zeros(2, np.int32)))
    assert st.checkpoint_now(wait=True)
    assert verify_checkpoint.main([str(tmp_path)]) == 0
    # Corrupt the installed checkpoint: rc must flip nonzero.
    seq, path = st.store.checkpoint_files()[0]
    blob = open(path, "rb").read()
    open(path, "wb").write(blob[: len(blob) - 7])
    assert verify_checkpoint.main([str(tmp_path)]) == 2


# ---------- the chaos scenario ----------


def test_recovery_scenario_fast_deterministic():
    """Tier-1 variant of ``--scenario recovery``: pinned seed whose kill
    schedule covers EVERY durability kill point — torn/crash WAL appends,
    torn/crash/late checkpoints, post-rename media corruption with
    fallback, mid-restore kills — and still recovers every acknowledged
    enrollment bit-exactly, then passes the graceful-drain phase."""
    report = chaos_soak.run_recovery(seconds=4.0, seed=1)
    assert report["ok"], report["failures"]
    counts = report["counts"]
    for key in ("wal_torn", "wal_crash", "ckpt_torn", "ckpt_crash",
                "ckpt_late", "media_corrupt", "mid_restore_kills"):
        assert counts[key] >= 1, (key, counts)
    assert counts["checkpoints_corrupt"] >= 1  # fallback actually exercised
    assert report["verify"]["ok"]
    assert report["drain"]["results"] == report["drain"]["sent"]


@pytest.mark.slow
def test_recovery_scenario_long_randomized():
    report = chaos_soak.run_recovery(seconds=12.0)
    assert report["ok"], report["failures"]
