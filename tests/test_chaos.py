"""Chaos suite: the serving loop under injected faults (runtime.faults /
runtime.resilience).

The acceptance scenario (ISSUE 1): one stuck readback, three consecutive
UNAVAILABLE dispatches, and a corrupt frame into a running
RecognizerService over FakeConnector — the service never deadlocks,
dead-letters exactly the stuck batch, retries then enters degraded mode
with a STATUS_TOPIC message, and every healthy frame submitted afterwards
still gets a result, with metrics matching the injected fault counts
exactly. Plus: supervisor restart with gallery restore, degraded-mode
backend probe + CPU fallback, the fault injector's determinism contract,
and the seed-logged chaos soak (fast deterministic variant in tier-1, the
long randomized soak marked slow).
"""

import importlib.util
import os
import sys
import time

import numpy as np
import pytest

from opencv_facerecognizer_tpu.runtime import (
    FakeConnector,
    FaultInjector,
    RecognizerService,
    ResiliencePolicy,
    ServiceSupervisor,
)
from opencv_facerecognizer_tpu.runtime.connector import encode_frame
from opencv_facerecognizer_tpu.runtime.faults import (
    InjectedUnavailableError,
    StuckReadback,
)
from opencv_facerecognizer_tpu.runtime.recognizer import (
    FRAME_TOPIC,
    RESULT_TOPIC,
    STATUS_TOPIC,
)
from opencv_facerecognizer_tpu.runtime.resilience import is_transient_error

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_spec = importlib.util.spec_from_file_location(
    "chaos_soak", os.path.join(REPO_ROOT, "scripts", "chaos_soak.py"))
chaos_soak = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(chaos_soak)

FRAME_SHAPE = (64, 64)
RNG = np.random.default_rng(11)


def _wait(cond, timeout=20.0, interval=0.02) -> bool:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return cond()


@pytest.fixture(scope="module")
def chaos_stack():
    """Tiny untrained serving stack — chaos tests exercise control flow,
    not recognition quality (see scripts/chaos_soak.build_stack)."""
    return chaos_soak.build_stack(frame_shape=FRAME_SHAPE, seed=0)


def _frame_msg(meta=None):
    frame = RNG.uniform(0, 255, FRAME_SHAPE).astype(np.float32)
    return {**encode_frame(frame), "meta": meta}


def _make_service(pipe, injector=None, policy=None, **kwargs):
    connector = FakeConnector()
    service = RecognizerService(
        pipe, connector, batch_size=2, frame_shape=FRAME_SHAPE,
        # Wide enough that two back-to-back injects always land in ONE
        # batch (the acceptance assertions count whole batches).
        flush_timeout=0.08, inflight_depth=2,
        resilience=policy or ResiliencePolicy(
            dispatch_retries=3, backoff_base_s=0.01, backoff_max_s=0.05,
            readback_deadline_s=0.6, degraded_after=3,
        ),
        fault_injector=injector,
        **kwargs,
    )
    return service, connector


# ---------- the acceptance scenario ----------


def test_chaos_acceptance_stuck_unavailable_corrupt(chaos_stack):
    pipe, _ = chaos_stack
    injector = FaultInjector(seed=1)
    service, connector = _make_service(pipe, injector)
    metrics = service.metrics
    service.start()
    try:
        # (a) one stuck readback: the whole batch must be dead-lettered at
        # its deadline — and ONLY that batch.
        injector.script("readback", "stuck")
        connector.inject(FRAME_TOPIC, _frame_msg({"phase": "stuck", "i": 0}))
        connector.inject(FRAME_TOPIC, _frame_msg({"phase": "stuck", "i": 1}))
        assert _wait(lambda: metrics.counter("batches_dead_lettered") >= 1), \
            "stuck readback was never dead-lettered (loop wedged?)"
        assert metrics.counter("batches_dead_lettered") == 1

        # (b) three consecutive UNAVAILABLE dispatches: retried with
        # backoff, degraded mode published at the third failure, then the
        # fourth attempt succeeds and the service recovers.
        injector.script("dispatch", "unavailable", "unavailable", "unavailable")
        connector.inject(FRAME_TOPIC, _frame_msg({"phase": "unavail", "i": 0}))
        connector.inject(FRAME_TOPIC, _frame_msg({"phase": "unavail", "i": 1}))
        assert _wait(lambda: metrics.counter("degraded_recoveries") >= 1), \
            "service never recovered from the UNAVAILABLE burst"
        statuses = [m["status"] for m in connector.messages(STATUS_TOPIC)]
        assert "degraded" in statuses and "recovered" in statuses
        degraded = next(m for m in connector.messages(STATUS_TOPIC)
                        if m["status"] == "degraded")
        assert degraded["consecutive_failures"] == 3

        # (c) one corrupt frame: counted malformed, never batched.
        injector.script("receive", "corrupt")
        connector.inject(FRAME_TOPIC, _frame_msg({"phase": "corrupt"}))
        assert _wait(lambda: metrics.counter("frames_malformed") >= 1)

        # Every healthy frame submitted afterwards still gets a result.
        n_before = len(connector.messages(RESULT_TOPIC))
        for i in range(4):
            connector.inject(FRAME_TOPIC, _frame_msg({"phase": "healthy", "i": i}))
        assert _wait(lambda: len(
            [m for m in connector.messages(RESULT_TOPIC)
             if (m.get("meta") or {}).get("phase") == "healthy"]) >= 4), \
            "healthy frames after the fault sequence got no results"
    finally:
        service.stop()

    # Metrics match the injected fault counts EXACTLY.
    injected = injector.summary()
    counters = metrics.counters()
    assert injected == {"readback:stuck": 1, "dispatch:unavailable": 3,
                        "receive:corrupt": 1}
    assert counters["batches_dead_lettered"] == injected["readback:stuck"]
    assert counters["frames_dead_lettered"] == 2  # both frames of the batch
    assert counters["dispatch_failures"] == injected["dispatch:unavailable"]
    assert counters["dispatch_retries"] == 3
    assert counters.get("batches_failed", 0) == 0  # retried, never abandoned
    assert counters["frames_malformed"] == injected["receive:corrupt"]
    assert counters["degraded_transitions"] == 1
    assert counters["degraded_recoveries"] == 1
    # The unavailable-phase and healthy-phase frames all published.
    metas = [m.get("meta") or {} for m in connector.messages(RESULT_TOPIC)]
    assert sum(m.get("phase") == "unavail" for m in metas) == 2
    assert sum(m.get("phase") == "healthy" for m in metas) == 4
    assert sum(m.get("phase") == "stuck" for m in metas) == 0  # dead-lettered


def test_receive_drop_and_duplicate(chaos_stack):
    pipe, _ = chaos_stack
    injector = FaultInjector(seed=2)
    service, connector = _make_service(pipe, injector)
    service.start()
    try:
        injector.script("receive", "drop", "duplicate")
        connector.inject(FRAME_TOPIC, _frame_msg({"k": "dropped"}))
        connector.inject(FRAME_TOPIC, _frame_msg({"k": "doubled"}))
        assert _wait(lambda: len(connector.messages(RESULT_TOPIC)) >= 2)
    finally:
        service.stop()
    metas = [m.get("meta") or {} for m in connector.messages(RESULT_TOPIC)]
    assert sum(m.get("k") == "doubled" for m in metas) == 2
    assert sum(m.get("k") == "dropped" for m in metas) == 0


def test_poisoned_batch_put_boundary(chaos_stack):
    """A frame corrupted at the batcher-put boundary is dropped by shape
    validation (counted on the shared metrics surface) and never poisons
    its batch — peers still get results."""
    pipe, _ = chaos_stack
    injector = FaultInjector(seed=3)
    service, connector = _make_service(pipe, injector)
    service.start()
    try:
        injector.script("put", "corrupt")
        connector.inject(FRAME_TOPIC, _frame_msg({"k": "poisoned"}))
        connector.inject(FRAME_TOPIC, _frame_msg({"k": "fine"}))
        assert _wait(lambda: len(connector.messages(RESULT_TOPIC)) >= 1)
    finally:
        service.stop()
    counters = service.metrics.counters()
    assert counters["batcher_dropped_malformed"] == 1
    assert counters["frames_dropped"] == 1  # the service-side mirror
    metas = [m.get("meta") or {} for m in connector.messages(RESULT_TOPIC)]
    assert sum(m.get("k") == "fine" for m in metas) == 1
    assert sum(m.get("k") == "poisoned" for m in metas) == 0


def test_dispatch_exhaustion_abandons_batch(chaos_stack):
    """More consecutive UNAVAILABLEs than the retry budget: the batch is
    abandoned (batches_failed), the loop keeps serving."""
    pipe, _ = chaos_stack
    injector = FaultInjector(seed=4)
    policy = ResiliencePolicy(dispatch_retries=1, backoff_base_s=0.01,
                              backoff_max_s=0.02, readback_deadline_s=0.6,
                              degraded_after=2)
    service, connector = _make_service(pipe, injector, policy)
    service.start()
    try:
        injector.script("dispatch", "unavailable", "unavailable")
        connector.inject(FRAME_TOPIC, _frame_msg({"k": "doomed"}))
        connector.inject(FRAME_TOPIC, _frame_msg({"k": "doomed"}))
        assert _wait(lambda: service.metrics.counter("batches_failed") >= 1)
        connector.inject(FRAME_TOPIC, _frame_msg({"k": "after"}))
        connector.inject(FRAME_TOPIC, _frame_msg({"k": "after"}))
        assert _wait(lambda: len(
            [m for m in connector.messages(RESULT_TOPIC)
             if (m.get("meta") or {}).get("k") == "after"]) >= 2)
    finally:
        service.stop()
    counters = service.metrics.counters()
    assert counters["batches_failed"] == 1
    assert counters["dispatch_failures"] == 2
    assert counters["degraded_transitions"] == 1  # hit degraded_after=2


def test_slow_readbacks_pipeline_through_worker(chaos_stack):
    """Injected slow readbacks (delayed-ready, not stuck) must neither
    dead-letter nor serialize the loop: the readback worker waits them out
    event-driven while the dispatch loop keeps feeding the in-flight
    queue, and every frame still publishes exactly once."""
    pipe, _ = chaos_stack
    injector = FaultInjector(seed=11, slow_readback_s=0.15)
    service, connector = _make_service(pipe, injector)
    service.start()
    try:
        injector.script("readback", "slow", "slow", "slow")
        t0 = time.monotonic()
        for i in range(6):  # three 2-frame batches, all slow
            connector.inject(FRAME_TOPIC, _frame_msg({"k": "slow", "i": i}))
        assert _wait(lambda: len(
            [m for m in connector.messages(RESULT_TOPIC)
             if (m.get("meta") or {}).get("k") == "slow"]) >= 6)
        elapsed = time.monotonic() - t0
    finally:
        service.stop()
    assert injector.summary() == {"readback:slow": 3}
    counters = service.metrics.counters()
    assert counters.get("batches_dead_lettered", 0) == 0
    assert counters["batches_dispatched"] >= 3
    # Overlap check: three 150 ms readbacks served well under 3 x 150 ms
    # plus slack would only hold if they pipelined; allow generous CI
    # headroom while still ruling out full serialization with the 80 ms
    # batch window on top (serialized would be >= ~0.7 s).
    assert elapsed < 3 * 0.15 + 0.35, elapsed


def test_fallback_inline_path_preserves_fault_semantics(chaos_stack):
    """readback_worker=False (the pre-worker inline poll drain, now the
    documented fallback mode with named poll knobs) must keep the same
    fault semantics: a stuck readback dead-letters at its deadline and
    healthy traffic afterwards still serves."""
    pipe, _ = chaos_stack
    injector = FaultInjector(seed=12)
    service, connector = _make_service(pipe, injector,
                                       readback_worker=False,
                                       readback_poll_s=0.002)
    service.start()
    try:
        injector.script("readback", "stuck")
        connector.inject(FRAME_TOPIC, _frame_msg({"k": "stuck"}))
        connector.inject(FRAME_TOPIC, _frame_msg({"k": "stuck"}))
        assert _wait(lambda: service.metrics.counter(
            "batches_dead_lettered") >= 1)
        connector.inject(FRAME_TOPIC, _frame_msg({"k": "ok"}))
        connector.inject(FRAME_TOPIC, _frame_msg({"k": "ok"}))
        assert _wait(lambda: len(
            [m for m in connector.messages(RESULT_TOPIC)
             if (m.get("meta") or {}).get("k") == "ok"]) >= 2)
    finally:
        service.stop()
    assert service._worker is None  # truly the non-threaded path
    metas = [m.get("meta") or {} for m in connector.messages(RESULT_TOPIC)]
    assert sum(m.get("k") == "stuck" for m in metas) == 0


# ---------- supervisor ----------


class _CrashOnceConnector(FakeConnector):
    """Raises from the first RESULT publish — an exception escaping the
    loop body via a subscriber, the crash class the supervisor exists for."""

    def __init__(self):
        super().__init__()
        self.crashes_left = 1

    def publish(self, topic, message):
        if topic == RESULT_TOPIC and self.crashes_left:
            self.crashes_left -= 1
            raise RuntimeError("result consumer blew up")
        super().publish(topic, message)

    inject = publish


def test_supervisor_restarts_crashed_loop_and_restores_gallery(chaos_stack):
    pipe, _ = chaos_stack
    connector = _CrashOnceConnector()
    service = RecognizerService(
        pipe, connector, batch_size=2, frame_shape=FRAME_SHAPE,
        flush_timeout=0.02,
        resilience=ResiliencePolicy(readback_deadline_s=5.0),
    )
    supervisor = ServiceSupervisor(service, max_restarts=3,
                                   poll_interval_s=0.05)
    supervisor.start()
    size_at_checkpoint = pipe.gallery.size
    try:
        # Rows added after the checkpoint simulate a half-done enrolment
        # the crash interrupts; the restart must roll them back.
        pipe.gallery.add(RNG.normal(size=(3, 16)).astype(np.float32),
                         np.full(3, 3, np.int32))
        assert pipe.gallery.size == size_at_checkpoint + 3
        connector.inject(FRAME_TOPIC, _frame_msg({"k": "crash-bait"}))
        connector.inject(FRAME_TOPIC, _frame_msg({"k": "crash-bait"}))
        assert _wait(lambda: service.metrics.counter("supervisor_restarts") >= 1), \
            "supervisor never restarted the crashed loop"
        assert pipe.gallery.size == size_at_checkpoint  # restored
        # The restarted loop still serves.
        connector.inject(FRAME_TOPIC, _frame_msg({"k": "after-restart"}))
        connector.inject(FRAME_TOPIC, _frame_msg({"k": "after-restart"}))
        assert _wait(lambda: len(
            [m for m in connector.messages(RESULT_TOPIC)
             if (m.get("meta") or {}).get("k") == "after-restart"]) >= 2)
    finally:
        supervisor.stop()
    assert service.metrics.counter("loop_crashes") == 1
    assert supervisor.restarts == 1
    assert not supervisor.gave_up
    statuses = [m["status"] for m in connector.messages(STATUS_TOPIC)]
    assert "crashed" in statuses and "supervisor_restart" in statuses


def test_degraded_probe_and_cpu_fallback(chaos_stack):
    pipe, _ = chaos_stack
    injector = FaultInjector(seed=5)
    policy = ResiliencePolicy(dispatch_retries=3, backoff_base_s=0.01,
                              backoff_max_s=0.02, readback_deadline_s=0.6,
                              degraded_after=3,
                              probe_backend_on_degraded=True)
    fallbacks = []
    service, connector = _make_service(
        pipe, injector, policy,
        backend_probe_fn=lambda: (False, "injected-dead"),
        cpu_fallback=lambda svc: fallbacks.append(svc),
    )
    service.start()
    try:
        injector.script("dispatch", "unavailable", "unavailable", "unavailable")
        connector.inject(FRAME_TOPIC, _frame_msg({"k": "x"}))
        connector.inject(FRAME_TOPIC, _frame_msg({"k": "x"}))
        assert _wait(lambda: service.metrics.counter("degraded_recoveries") >= 1)
    finally:
        service.stop()
    degraded = next(m for m in connector.messages(STATUS_TOPIC)
                    if m["status"] == "degraded")
    assert degraded["backend_usable"] is False
    assert degraded["backend_reason"] == "injected-dead"
    assert degraded["cpu_fallback"] is True
    assert fallbacks == [service]
    assert service.metrics.counter("cpu_fallbacks") == 1


# ---------- fault injector contract ----------


def test_fault_injector_scripted_order_and_counts():
    fi = FaultInjector(seed=0)
    fi.script("receive", "drop", "duplicate", "corrupt")
    msg = {"__frame__": "x", "shape": [1], "dtype": "uint8", "meta": 7}
    assert fi.on_receive(msg) == []
    assert fi.on_receive(msg) == [msg, msg]
    corrupted = fi.on_receive(msg)
    assert len(corrupted) == 1 and corrupted[0]["__frame__"] != "x"
    assert corrupted[0]["meta"] == 7  # provenance survives corruption
    assert fi.on_receive(msg) == [msg]  # script exhausted -> passthrough
    with pytest.raises(ValueError):
        fi.script("dispatch", "stuck")  # wrong boundary
    with pytest.raises(ValueError):
        fi.script("bogus", "drop")
    assert fi.summary() == {"receive:drop": 1, "receive:duplicate": 1,
                            "receive:corrupt": 1}


def test_fault_injector_seeded_rates_reproducible():
    rates = {"dispatch": {"unavailable": 0.5}}
    outcomes = []
    for _ in range(2):
        fi = FaultInjector(seed=42, rates=rates)
        run = []
        for _ in range(32):
            try:
                fi.on_dispatch()
                run.append(False)
            except InjectedUnavailableError:
                run.append(True)
        outcomes.append(run)
    assert outcomes[0] == outcomes[1]  # same seed, same fault sequence
    assert any(outcomes[0]) and not all(outcomes[0])


def test_fault_injector_flood_amplifies_delivery():
    fi = FaultInjector(seed=0, flood_factor=4)
    fi.script("receive", "flood")
    msg = {"__frame__": "x", "shape": [1], "dtype": "uint8", "meta": 3}
    assert fi.on_receive(msg) == [msg] * 4
    assert fi.on_receive(msg) == [msg]  # script exhausted -> passthrough
    assert fi.summary() == {"receive:flood": 1}
    # Rates accept it too (the overload soak's knob).
    fi2 = FaultInjector(seed=1, rates={"receive": {"flood": 1.0}},
                        flood_factor=3)
    assert fi2.on_receive(msg) == [msg] * 3


def test_fault_injector_disarm():
    fi = FaultInjector(seed=0, rates={"dispatch": {"unavailable": 1.0}})
    fi.script("readback", "stuck")
    fi.disarm()
    fi.on_dispatch()  # no raise
    arr = np.zeros(2)
    assert fi.on_readback(arr) is arr
    assert fi.summary() == {}
    fi.arm()
    assert isinstance(fi.on_readback(arr), StuckReadback)


def test_stuck_readback_never_materializes_silently():
    stuck = StuckReadback(np.zeros(3))
    assert stuck.is_ready() is False
    stuck.copy_to_host_async()  # no-op, no raise
    with pytest.raises(RuntimeError, match="stuck"):
        np.asarray(stuck)


def test_transient_error_classification():
    assert is_transient_error(InjectedUnavailableError())
    assert is_transient_error(RuntimeError("UNAVAILABLE: socket closed"))
    assert is_transient_error(ConnectionResetError("connection reset by peer"))
    assert not is_transient_error(ValueError("shape mismatch [8, 64, 64]"))
    assert not is_transient_error(TypeError("not an array"))


def test_probe_for_recovery_injectable_and_bounded():
    from opencv_facerecognizer_tpu.utils.backend_probe import probe_for_recovery

    usable, reason = probe_for_recovery(
        timeout_s=30.0, probe_source="import sys; sys.exit(0)")
    assert usable and reason == "ok"
    t0 = time.monotonic()
    usable, reason = probe_for_recovery(
        timeout_s=0.5, probe_source="import time; time.sleep(30)")
    assert not usable and "hang-mode" in reason
    assert time.monotonic() - t0 < 5.0  # bounded, killed at the deadline


# ---------- chaos soak ----------


def test_chaos_soak_fast_deterministic():
    """Tier-1 variant: short chaos window, pinned seed — rc-0 semantics of
    scripts/chaos_soak.py (no wedge, no unsupervised crash, accounting,
    and the admission ledger reconciling exactly at quiescence)."""
    report = chaos_soak.run_soak(seconds=1.5, seed=7)
    assert report["ok"], report["failures"]
    assert report["seed"] == 7
    assert report["results"] > 0
    assert report["ledger"]["in_system"] == 0


def test_overload_soak_fast_deterministic():
    """Tier-1 overload smoke: the ``--scenario overload`` flood soak
    (seed-logged receive:flood amplification to ~4x a deterministic
    capacity wall) passes its whole criteria set — no wedge, no crash,
    interactive p99 within 2x unloaded, explicit sheds, exact ledger,
    journal covering every shed."""
    report = chaos_soak.run_overload(seconds=2.0, seed=7)
    assert report["ok"], report["failures"]
    # Under ~4x offered load bulk must actually shed (reject or brownout).
    shed = (sum(report["rejected"].values())
            + sum(report["ledger"]["drops_by_reason"].values()))
    assert shed > 0
    assert report["ledger"]["in_system"] == 0
    # Every journaled frame carries its reason (replayable).
    assert report["journal_frames"] == sum(
        report["counters"].get(k, 0) for k in (
            "frames_dead_lettered", "frames_failed",
            "frames_dropped_brownout", "batcher_dropped_stale",
            "batcher_dropped_overflow"))


@pytest.mark.slow
def test_chaos_soak_long_randomized():
    report = chaos_soak.run_soak(seconds=30.0)
    assert report["ok"], report["failures"]


@pytest.mark.slow
def test_overload_soak_long_randomized():
    report = chaos_soak.run_overload(seconds=15.0)
    assert report["ok"], report["failures"]


@pytest.mark.slow
def test_replication_soak_long_randomized():
    """Random-seed replication soak (``--scenario replication``): 1 writer
    + 2 WAL-tailing read replicas behind the topic router, reader killed
    mid-traffic, writer killed mid-enrollment and restarted — survivor
    p99, zero acked loss on every survivor, split-brain fail-closed, and
    per-replica ledger exactness, at a fresh seed per run (the fast
    pinned-seed variant lives in tests/test_replication.py)."""
    report = chaos_soak.run_replication(seconds=10.0)
    assert report["ok"], report["failures"]


# ---------- review-hardening: degraded-path edges ----------


def test_status_subscriber_raising_never_crashes_loop(chaos_stack):
    """Degraded/recovered/dead-letter statuses publish from the serving
    thread into arbitrary app subscribers — one that raises must cost a
    logged error, not the serving loop."""
    pipe, _ = chaos_stack
    injector = FaultInjector(seed=6)
    service, connector = _make_service(pipe, injector)

    def angry_subscriber(topic, message):
        raise RuntimeError("status consumer blew up")

    connector.subscribe(STATUS_TOPIC, angry_subscriber)
    service.start()
    try:
        # Both degraded entry and recovery publish through the subscriber.
        injector.script("dispatch", "unavailable", "unavailable", "unavailable")
        connector.inject(FRAME_TOPIC, _frame_msg({"k": "x"}))
        connector.inject(FRAME_TOPIC, _frame_msg({"k": "x"}))
        assert _wait(lambda: service.metrics.counter("degraded_recoveries") >= 1)
        # ...and a dead-letter announcement too.
        injector.script("readback", "stuck")
        connector.inject(FRAME_TOPIC, _frame_msg({"k": "y"}))
        connector.inject(FRAME_TOPIC, _frame_msg({"k": "y"}))
        assert _wait(lambda: service.metrics.counter("batches_dead_lettered") >= 1)
        connector.inject(FRAME_TOPIC, _frame_msg({"k": "alive"}))
        connector.inject(FRAME_TOPIC, _frame_msg({"k": "alive"}))
        assert _wait(lambda: len(
            [m for m in connector.messages(RESULT_TOPIC)
             if (m.get("meta") or {}).get("k") == "alive"]) >= 2)
    finally:
        service.stop()
    assert service.metrics.counter("loop_crashes") == 0


def test_cpu_fallback_rebuilds_pipeline_and_keeps_serving(chaos_stack):
    """The stock rebuild_pipeline_on_cpu hook (what ocvf-recognize wires
    for --probe-on-degraded): a dead-backend verdict swaps in a pipeline
    on a single host CPU device with the gallery copied through the
    host-mirror snapshot path, and serving continues on it."""
    from opencv_facerecognizer_tpu.runtime.resilience import (
        rebuild_pipeline_on_cpu,
    )

    pipe, _ = chaos_stack
    injector = FaultInjector(seed=8)
    policy = ResiliencePolicy(dispatch_retries=3, backoff_base_s=0.01,
                              backoff_max_s=0.02, readback_deadline_s=0.6,
                              degraded_after=3,
                              probe_backend_on_degraded=True)
    service, connector = _make_service(
        pipe, injector, policy,
        backend_probe_fn=lambda: (False, "injected-dead"),
        cpu_fallback=rebuild_pipeline_on_cpu,
    )
    old_pipe = service.pipeline
    old_size = old_pipe.gallery.size
    service.start()
    try:
        injector.script("dispatch", "unavailable", "unavailable", "unavailable")
        connector.inject(FRAME_TOPIC, _frame_msg({"k": "x"}))
        connector.inject(FRAME_TOPIC, _frame_msg({"k": "x"}))
        assert _wait(lambda: service.metrics.counter("cpu_fallbacks") >= 1)
        # The swap is visible and serving continues on the new pipeline.
        assert service.pipeline is not old_pipe
        assert service.pipeline.gallery.mesh.size == 1
        assert service.pipeline.gallery.size == old_size
        # The injector MOVED with the swap (an armed one left behind would
        # leak faults into the next service built on the shared pipeline).
        assert old_pipe.fault_injector is None
        assert service.pipeline.fault_injector is injector
        # The enrolment embed graph follows to the fallback device too.
        assert service._embed_device is not None
        # The recompile watchdog stayed armed across the swap: the new
        # pipeline's ladder was prewarmed inside the hook, so the
        # fallback's own compiles never fire it and later mid-serving
        # compiles still would.
        assert service._warmed
        assert service.metrics.counter("recompiles_post_warmup") == 0
        chunk = np.zeros((service._enrol_chunk, *service.pipeline.face_size),
                         np.float32)
        emb = np.asarray(service._run_embed_chunk(
            service.pipeline.embed_params, chunk))
        assert emb.shape[0] == service._enrol_chunk
        connector.inject(FRAME_TOPIC, _frame_msg({"k": "after"}))
        connector.inject(FRAME_TOPIC, _frame_msg({"k": "after"}))
        assert _wait(lambda: len(
            [m for m in connector.messages(RESULT_TOPIC)
             if (m.get("meta") or {}).get("k") == "after"]) >= 2, timeout=60)
    finally:
        service.stop()
    degraded = next(m for m in connector.messages(STATUS_TOPIC)
                    if m["status"] == "degraded")
    assert degraded["cpu_fallback"] is True
    assert service.metrics.counter("loop_crashes") == 0


def test_supervisor_recheckpoints_on_committed_changes(chaos_stack):
    """A committed enrolment/reload advances last-known-good: a crash
    afterwards must restore the post-commit gallery, not roll back every
    subject enrolled since startup."""
    pipe, _ = chaos_stack
    connector = _CrashOnceConnector()
    service = RecognizerService(
        pipe, connector, batch_size=2, frame_shape=FRAME_SHAPE,
        flush_timeout=0.02,
        resilience=ResiliencePolicy(readback_deadline_s=5.0),
    )
    supervisor = ServiceSupervisor(service, max_restarts=3,
                                   poll_interval_s=0.05)
    supervisor.start()
    base_size = pipe.gallery.size
    try:
        # Commit rows exactly as _finish_enrolment does: gallery change,
        # then the service's commit hooks fire (direct callback — wire
        # connectors never dispatch their own publishes locally, so this
        # must NOT depend on a status subscription).
        checkpoints = service.metrics.counter("supervisor_checkpoints")
        pipe.gallery.add(RNG.normal(size=(2, 16)).astype(np.float32),
                         np.full(2, 3, np.int32))
        service._run_commit_hooks()
        assert _wait(lambda: service.metrics.counter("supervisor_checkpoints")
                     > checkpoints)
        # Crash the loop AFTER the commit checkpoint (first RESULT publish
        # raises): restore must keep the enrolled rows.
        connector.inject(FRAME_TOPIC, _frame_msg({"k": "crash-bait"}))
        connector.inject(FRAME_TOPIC, _frame_msg({"k": "crash-bait"}))
        assert _wait(lambda: service.metrics.counter("supervisor_restarts") >= 1)
        assert pipe.gallery.size == base_size + 2
    finally:
        supervisor.stop()


def test_supervisor_stall_watchdog_surfaces_no_progress(chaos_stack):
    """Call-time-hang surfacing: frames pending with zero processing
    progress past stall_warn_s publishes a one-shot 'stalled' status —
    the deploy-level liveness signal (the shape cannot be fixed
    in-process; see ServiceSupervisor docstring)."""
    pipe, _ = chaos_stack
    connector = FakeConnector()
    service = RecognizerService(pipe, connector, batch_size=2,
                                frame_shape=FRAME_SHAPE, flush_timeout=0.02)
    supervisor = ServiceSupervisor(service)
    supervisor.stall_warn_s = 0.1
    # Loop never started: queued frames can make no progress — the stall
    # signature, without needing a real native-code hang.
    service.batcher.put(np.zeros(FRAME_SHAPE, np.float32))
    supervisor._check_stall(service, STATUS_TOPIC)  # baselines progress
    time.sleep(0.15)
    supervisor._check_stall(service, STATUS_TOPIC)
    assert service.metrics.counter("supervisor_stalls") == 1
    stalled = [m for m in connector.messages(STATUS_TOPIC)
               if m["status"] == "stalled"]
    assert len(stalled) == 1 and stalled[0]["pending_frames"] == 1
    # One-shot: no repeat warning while still stalled.
    supervisor._check_stall(service, STATUS_TOPIC)
    assert service.metrics.counter("supervisor_stalls") == 1
    # An abandoned batch IS progress: a loop surviving a fast-fail outage
    # (dispatch fails, batch abandoned) is degraded, not stalled.
    service.metrics.incr("batches_failed")
    supervisor._check_stall(service, STATUS_TOPIC)  # progress: re-arms
    time.sleep(0.15)
    service.metrics.incr("batches_failed")
    supervisor._check_stall(service, STATUS_TOPIC)  # still advancing
    assert service.metrics.counter("supervisor_stalls") == 1


def test_supervisor_waits_for_crashed_thread_to_exit(chaos_stack):
    """A crash flag raised while the serving thread is still unwinding
    (slow 'crashed'-status subscriber) must not burn phantom restarts:
    restart_loop would no-op on the alive thread, desyncing restarts vs
    loop_crashes — the soak's unsupervised-crash signature."""
    pipe, _ = chaos_stack
    connector = FakeConnector()
    service = RecognizerService(
        pipe, connector, batch_size=2, frame_shape=FRAME_SHAPE,
        flush_timeout=0.02,
        resilience=ResiliencePolicy(readback_deadline_s=5.0),
    )
    supervisor = ServiceSupervisor(service, max_restarts=3,
                                   poll_interval_s=0.05)
    supervisor.start()
    try:
        service._crashed = True  # flag up, thread alive and healthy
        time.sleep(0.4)  # several monitor polls
        assert supervisor.restarts == 0
        assert service.metrics.counter("supervisor_restarts") == 0
        service._crashed = False
        connector.inject(FRAME_TOPIC, _frame_msg({"k": "fine"}))
        connector.inject(FRAME_TOPIC, _frame_msg({"k": "fine"}))
        assert _wait(lambda: len(
            [m for m in connector.messages(RESULT_TOPIC)
             if (m.get("meta") or {}).get("k") == "fine"]) >= 2)
    finally:
        supervisor.stop()


# ---------- dynamic lock-order backstop (ocvf-lint cross-check) ----------


def test_debug_lock_backstop_no_inversions(chaos_stack):
    """Dynamic backstop to the static ``lock-order`` rule: run real traffic
    through a service whose locks are swapped for instrumented DebugLocks
    (named with the same ids the static analyzer uses), then assert (a) no
    acquisition-order inversion was *observed* at runtime, and (b) the
    union of the observed edges with the statically derived graph is still
    free of two-lock cycles — orders the AST can't see (hooks, callbacks)
    get checked here, orders the runtime didn't happen to exercise stay
    covered statically."""
    import threading

    from opencv_facerecognizer_tpu.utils.debug_lock import LockOrderMonitor

    pipe, _ = chaos_stack
    monitor = LockOrderMonitor()
    service, connector = _make_service(pipe)

    m = service.metrics
    m._lock = monitor.debug_lock("utils.metrics.Metrics._lock")
    m._sink_lock = monitor.debug_lock("utils.metrics.Metrics._sink_lock")
    service._enrol_lock = monitor.debug_lock(
        "runtime.recognizer.RecognizerService._enrol_lock")
    service._reject_lock = monitor.debug_lock(
        "runtime.recognizer.RecognizerService._reject_lock")
    service._inflight_cv = threading.Condition(monitor.debug_lock(
        "runtime.recognizer.RecognizerService._inflight_cv"))
    batcher = service.batcher
    batcher_lock = monitor.debug_lock("runtime.batcher.FrameBatcher._lock")
    batcher._lock = batcher_lock
    batcher._not_empty = threading.Condition(batcher_lock)
    gallery = pipe.gallery
    saved_write_lock = gallery._write_lock  # module-scoped fixture: restore
    gallery._write_lock = monitor.debug_lock(
        "parallel.gallery.ShardedGallery._write_lock")

    service.start()
    try:
        for i in range(10):
            connector.inject(FRAME_TOPIC, _frame_msg({"k": f"f{i}"}))
        assert _wait(lambda: len(connector.messages(RESULT_TOPIC)) >= 10)
    finally:
        service.stop()
        gallery._write_lock = saved_write_lock

    # The clean path keeps metrics OUT of lock bodies (that discipline is
    # the point); the closed-batcher drop is the one sanctioned nesting —
    # drive it so the cross-check below is provably non-vacuous.
    assert batcher.put(np.zeros(FRAME_SHAPE, np.float32)) is False
    assert service.metrics.counter("batcher_dropped_closed") >= 1

    monitor.check()  # no runtime inversion among the instrumented locks
    observed = monitor.edges()
    assert observed, "instrumentation was vacuous — no edges recorded"

    sys.path.insert(0, REPO_ROOT)
    from tools.ocvf_lint.checkers.lock_order import build_lock_graph

    static_edges = set(build_lock_graph(
        [os.path.join(REPO_ROOT, "opencv_facerecognizer_tpu")]))
    # The static analyzer names the batcher's Condition `_not_empty` and its
    # Lock `_lock` as two nodes; physically they are ONE lock
    # (Condition(self._lock) in FrameBatcher.__init__).  Merge the alias
    # before combining, or an inversion split across the two names would
    # form no cycle and slip through.
    alias = {"runtime.batcher.FrameBatcher._not_empty":
             "runtime.batcher.FrameBatcher._lock"}

    def canon(node):
        return alias.get(node, node)

    combined = ({(canon(a), canon(b)) for a, b in static_edges}
                | {(canon(a), canon(b)) for a, b in observed})
    # sanity: the two sources actually share the namespace — a silent
    # divergence (e.g. checkout-dir-prefixed static ids) would make this
    # cross-check vacuous
    static_nodes = {n for e in static_edges for n in e}
    observed_nodes = {canon(n) for e in observed for n in e}
    assert static_nodes & observed_nodes, (
        f"static and dynamic graphs share no nodes:\n{static_nodes}\n"
        f"{observed_nodes}")
    inverted = sorted((a, b) for (a, b) in combined
                      if a != b and (b, a) in combined)
    assert not inverted, f"static+dynamic lock graph has cycles: {inverted}"
