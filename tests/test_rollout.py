"""Embedder-rollout tests (``runtime.rollout`` + the version-fenced
state machinery): crash-safe staged re-embed with durable resume, version
fencing at every layer (gallery swap, WAL append, replay, replica tail,
offline verifier), the dual-score parity gate, the WAL-fenced atomic
cutover with recovery completion, rollback-as-the-same-mechanism, the
router cordon drain, and the fast deterministic tier-1 variant of
``scripts/chaos_soak.py --scenario rollout``."""

import importlib.util
import json
import os
import time

import numpy as np
import pytest

from opencv_facerecognizer_tpu.parallel import (
    EmbeddingDimMismatchError,
    ShardedGallery,
    make_mesh,
)
from opencv_facerecognizer_tpu.runtime import (
    EmbedderVersionMismatchError,
    FakeConnector,
    FaultInjector,
    ReadReplica,
    RecognizerService,
    ReplicaHandle,
    RolloutCoordinator,
    RolloutGateError,
    StateLifecycle,
    TopicRouter,
)
from opencv_facerecognizer_tpu.runtime.fakes import InstantPipeline
from opencv_facerecognizer_tpu.runtime.faults import InjectedCrashError
from opencv_facerecognizer_tpu.runtime.recognizer import FRAME_TOPIC
from opencv_facerecognizer_tpu.runtime.rollout import (
    ReEmbedStage,
    RolloutStateError,
    load_stage,
    stage_path,
)
from opencv_facerecognizer_tpu.utils.metrics import Metrics

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DIM = 8


def _load_script(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(_REPO, "scripts", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def mesh():
    return make_mesh()


@pytest.fixture(scope="module")
def rotation():
    rng = np.random.default_rng(42)
    q, _ = np.linalg.qr(rng.normal(size=(DIM, DIM)))
    return q.astype(np.float32)


def _writer(tmp_path, mesh, **kw):
    gallery = ShardedGallery(capacity=64, dim=DIM, mesh=mesh)
    names = []
    state = StateLifecycle(str(tmp_path), metrics=kw.pop("metrics", Metrics()),
                           checkpoint_wal_rows=1 << 30,
                           checkpoint_every_s=1e9, **kw)
    state.bind(gallery, names)
    return state, gallery, names


def _enroll(state, gallery, names, rng, i, n=1):
    emb = rng.normal(size=(n, DIM)).astype(np.float32)
    labels = np.full(n, i, np.int32)
    names.append(f"s{i}")
    state.append_enrollment(emb, labels, subject=f"s{i}", label=i,
                            apply_fn=lambda e=emb, l=labels:
                                gallery.add(e, l))
    return emb


def _norm(rows):
    return rows / np.maximum(np.linalg.norm(rows, axis=-1, keepdims=True),
                             1e-12)


def _expected_new(embs, rotation):
    want = _norm(np.concatenate(embs))
    return _norm(want @ rotation)


def _coordinator(state, gallery, rotation, to_version=2, **kw):
    kw.setdefault("chunk_rows", 3)
    kw.setdefault("metrics", Metrics())
    return RolloutCoordinator(state, gallery,
                              lambda rows: rows @ rotation, to_version, **kw)


# ---------- staged re-embed: durability + resume ----------


def test_stage_resume_after_torn_append(tmp_path):
    injector = FaultInjector(seed=0)
    stage = ReEmbedStage(str(tmp_path), 2, dim=DIM, metrics=Metrics(),
                         fault_injector=injector)
    rng = np.random.default_rng(0)
    stage.stage_chunk(0, rng.normal(size=(3, DIM)).astype(np.float32),
                      np.arange(3, dtype=np.int32))
    stage.stage_chunk(3, rng.normal(size=(2, DIM)).astype(np.float32),
                      np.arange(2, dtype=np.int32))
    assert stage.watermark == 5
    # Torn append: partial line lands, watermark must NOT advance.
    injector.script("stage", "torn")
    with pytest.raises(InjectedCrashError):
        stage.stage_chunk(5, rng.normal(size=(2, DIM)).astype(np.float32),
                          np.arange(2, dtype=np.int32))
    # "Restart": a fresh stage over the same dir seals the torn tail and
    # resumes exactly at the durable watermark.
    resumed = ReEmbedStage(str(tmp_path), 2, dim=DIM, metrics=Metrics())
    assert resumed.resumed
    assert resumed.watermark == 5
    emb, labels = resumed.arrays()
    assert emb.shape == (5, DIM) and labels.shape == (5,)
    # Re-staging the same chunk (deterministic re-embed) extends cleanly.
    resumed.stage_chunk(5, np.ones((1, DIM), np.float32),
                        np.zeros(1, np.int32))
    assert resumed.watermark == 6


def test_load_stage_fails_closed_on_gaps(tmp_path):
    stage = ReEmbedStage(str(tmp_path), 2, dim=DIM)
    stage.stage_chunk(0, np.ones((2, DIM), np.float32),
                      np.zeros(2, np.int32))
    # Promise more rows than the contiguous coverage: refuse.
    with pytest.raises(RolloutStateError):
        load_stage(str(tmp_path), 2, expect_rows=5, expect_dim=DIM)
    emb, labels = load_stage(str(tmp_path), 2, expect_rows=2,
                             expect_dim=DIM)
    assert emb.shape == (2, DIM)
    # Missing file entirely: refuse with the operator-facing error.
    with pytest.raises(RolloutStateError):
        load_stage(str(tmp_path / "nowhere"), 2, expect_rows=1,
                   expect_dim=DIM)


# ---------- version fencing ----------


def test_swap_from_dim_mismatch_fails_closed(mesh):
    serving = ShardedGallery(capacity=16, dim=DIM, mesh=mesh)
    donor = ShardedGallery(capacity=16, dim=DIM * 2, mesh=mesh)
    with pytest.raises(EmbeddingDimMismatchError, match="staged re-embed"):
        serving.swap_from(donor)
    # Still a ValueError subclass: pre-rollout callers keep working.
    with pytest.raises(ValueError):
        serving.swap_from(donor)


def test_swap_from_adopts_donor_version(mesh):
    serving = ShardedGallery(capacity=16, dim=DIM, mesh=mesh)
    donor = ShardedGallery(capacity=16, dim=DIM, mesh=mesh,
                           embedder_version=3)
    donor.add(np.ones((2, DIM), np.float32), np.zeros(2, np.int32))
    serving.swap_from(donor)
    assert serving.embedder_version == 3


def test_append_enrollment_version_fence(tmp_path, mesh):
    metrics = Metrics()
    state, gallery, names = _writer(tmp_path, mesh, metrics=metrics)
    seq_before = state.wal_seq
    with pytest.raises(EmbedderVersionMismatchError):
        state.append_enrollment(np.ones((1, DIM), np.float32),
                                np.zeros(1, np.int32), embedder_version=9)
    # Failed closed BEFORE any sequence burned or record appended.
    assert state.wal_seq == seq_before
    assert metrics.counter("rollout_version_mismatches") == 1
    assert list(state.wal.enrollments()) == []
    # The matching version passes.
    state.append_enrollment(np.ones((1, DIM), np.float32),
                            np.zeros(1, np.int32), embedder_version=1,
                            apply_fn=lambda: gallery.add(
                                np.ones((1, DIM), np.float32),
                                np.zeros(1, np.int32)))
    records = list(state.wal.enrollments())
    assert records[0]["embedder_version"] == 1
    state.close()


# ---------- cutover: atomic swap + crash-recovery completion ----------


def test_cutover_swaps_and_checkpoint_carries_version(tmp_path, mesh,
                                                      rotation):
    rng = np.random.default_rng(1)
    state, gallery, names = _writer(tmp_path, mesh)
    embs = [_enroll(state, gallery, names, rng, i, n=2) for i in range(4)]
    co = _coordinator(state, gallery, rotation)
    co.run_stage()
    assert co.caught_up
    seq = co.cutover(force=True)  # no parity embedders wired: force
    assert gallery.embedder_version == 2
    got, lab, _v, size = gallery.snapshot()
    assert np.allclose(got[:size], _expected_new(embs, rotation), atol=1e-5)
    # The stage file is gone (the post-cutover checkpoint landed)...
    assert not os.path.exists(stage_path(str(tmp_path), 2))
    # ...and a fresh recovery lands straight on v2 off the checkpoint.
    g2 = ShardedGallery(capacity=64, dim=DIM, mesh=mesh)
    names2 = []
    report = StateLifecycle(str(tmp_path), metrics=Metrics()).recover(
        g2, names2)
    assert report["embedder_version"] == 2
    assert report.get("completed_cutover") is None
    assert g2.embedder_version == 2
    got2, _l, _v2, size2 = g2.snapshot()
    assert np.allclose(got2[:size2], _expected_new(embs, rotation),
                       atol=1e-5)
    assert names2 == names
    assert seq == state.wal_seq  # the fence was the last record
    state.close()


def test_crash_after_fence_record_recovery_completes(tmp_path, mesh,
                                                     rotation):
    rng = np.random.default_rng(2)
    injector = FaultInjector(seed=2)
    metrics = Metrics()
    state, gallery, names = _writer(tmp_path, mesh, metrics=metrics,
                                    fault_injector=injector)
    embs = [_enroll(state, gallery, names, rng, i) for i in range(3)]
    assert state.checkpoint_now(wait=True)  # an old-version anchor
    embs.append(_enroll(state, gallery, names, rng, 3))  # WAL-only row
    co = _coordinator(state, gallery, rotation,
                      fault_injector=injector)
    co.run_stage()
    injector.script("cutover", "crash_after_record")
    with pytest.raises(InjectedCrashError):
        co.cutover(force=True)
    assert gallery.embedder_version == 1  # the dying process never swapped
    # "Restart": recovery must complete the cutover from the stage.
    g2 = ShardedGallery(capacity=64, dim=DIM, mesh=mesh)
    names2 = []
    m2 = Metrics()
    report = StateLifecycle(str(tmp_path), metrics=m2).recover(g2, names2)
    assert report["completed_cutover"]["to_version"] == 2
    assert report["embedder_version"] == 2
    assert m2.counter("rollout_cutovers_completed_recovery") == 1
    got, _l, _v, size = g2.snapshot()
    assert np.allclose(got[:size], _expected_new(embs, rotation), atol=1e-5)
    assert names2 == names
    state.close()


def test_crash_before_fence_record_stays_old_version(tmp_path, mesh,
                                                     rotation):
    rng = np.random.default_rng(3)
    injector = FaultInjector(seed=3)
    state, gallery, names = _writer(tmp_path, mesh,
                                    fault_injector=injector)
    embs = [_enroll(state, gallery, names, rng, i) for i in range(3)]
    co = _coordinator(state, gallery, rotation, fault_injector=injector)
    co.run_stage()
    injector.script("cutover", "crash_before_record")
    with pytest.raises(InjectedCrashError):
        co.cutover(force=True)
    g2 = ShardedGallery(capacity=64, dim=DIM, mesh=mesh)
    report = StateLifecycle(str(tmp_path), metrics=Metrics()).recover(g2, [])
    # No fence record landed: the fleet stays on v1, zero loss.
    assert report["embedder_version"] == 1
    assert report.get("completed_cutover") is None
    got, _l, _v, size = g2.snapshot()
    assert np.allclose(got[:size], _norm(np.concatenate(embs)), atol=1e-6)
    state.close()


def test_recovery_fails_closed_on_damaged_stage(tmp_path, mesh, rotation):
    rng = np.random.default_rng(4)
    injector = FaultInjector(seed=4)
    state, gallery, names = _writer(tmp_path, mesh,
                                    fault_injector=injector)
    for i in range(3):
        _enroll(state, gallery, names, rng, i)
    co = _coordinator(state, gallery, rotation, fault_injector=injector)
    co.run_stage()
    injector.script("cutover", "crash_after_record")
    with pytest.raises(InjectedCrashError):
        co.cutover(force=True)
    # Media damage: the staged shard set vanishes after the fence fsynced.
    os.remove(stage_path(str(tmp_path), 2))
    g2 = ShardedGallery(capacity=64, dim=DIM, mesh=mesh)
    with pytest.raises(RolloutStateError):
        StateLifecycle(str(tmp_path), metrics=Metrics()).recover(g2, [])
    state.close()


# ---------- the parity gate ----------


def _crop_for(row):
    return _norm(row[None])[0].reshape(2, 4)


def test_parity_gate_blocks_disagreeing_embedder(tmp_path, mesh, rotation):
    rng = np.random.default_rng(5)
    state, gallery, names = _writer(tmp_path, mesh)
    embs = [_enroll(state, gallery, names, rng, i, n=2) for i in range(4)]

    def old_embed(crops):
        return np.asarray(crops, np.float32).reshape(len(crops), -1)[:, :DIM]

    # A BROKEN "new embedder": random vectors — identities scramble.
    def broken_embed(crops):
        return np.random.default_rng(99).normal(
            size=(len(crops), DIM)).astype(np.float32)

    metrics = Metrics()
    co = RolloutCoordinator(state, gallery, lambda r: r @ rotation, 2,
                            old_embed_fn=old_embed,
                            new_embed_fn=broken_embed,
                            parity_min_samples=4, parity_threshold=0.9,
                            chunk_rows=8, metrics=metrics)
    co.run_stage()
    co.score_parity([_crop_for(e[0]) for e in embs])
    assert not co.parity_ok()
    with pytest.raises(RolloutGateError, match="parity gate"):
        co.cutover()
    assert metrics.counter("rollout_cutover_blocked") == 1
    assert gallery.embedder_version == 1  # nothing moved
    # The consistent pair clears the same gate.
    co2 = RolloutCoordinator(state, gallery, lambda r: r @ rotation, 2,
                             old_embed_fn=old_embed,
                             new_embed_fn=lambda c: old_embed(c) @ rotation,
                             parity_min_samples=4, parity_threshold=0.9,
                             chunk_rows=8, metrics=Metrics())
    co2.run_stage()
    co2.score_parity([_crop_for(e[0]) for e in embs])
    assert co2.parity_ok()
    co2.cutover()
    assert gallery.embedder_version == 2
    state.close()


def test_live_parity_rides_publish_path(tmp_path, mesh, rotation):
    """The recognizer's publish hook samples detected face crops into the
    rollout thread's queue — parity accumulates from live traffic."""
    rng = np.random.default_rng(6)
    state, gallery, names = _writer(tmp_path, mesh)
    for i in range(3):
        _enroll(state, gallery, names, rng, i)

    def old_embed(crops):
        flat = np.asarray(crops, np.float32).reshape(len(crops), -1)
        return flat[:, :DIM]

    co = RolloutCoordinator(state, gallery, lambda r: r @ rotation, 2,
                            old_embed_fn=old_embed,
                            new_embed_fn=lambda c: old_embed(c) @ rotation,
                            parity_min_samples=1, chunk_rows=8,
                            live_sample_interval_s=0.0, metrics=Metrics())
    co.run_stage()
    pipe = InstantPipeline((16, 16), faces_per_frame=1)
    pipe.gallery = gallery
    connector = FakeConnector()
    service = RecognizerService(pipe, connector, batch_size=4,
                                frame_shape=(16, 16), flush_timeout=0.02,
                                metrics=Metrics())
    service.rollout = co
    co.start()
    service.start(warmup=False)
    try:
        from opencv_facerecognizer_tpu.runtime.connector import encode_frame

        frame = np.zeros((16, 16), np.float32)
        for i in range(8):
            connector.inject(FRAME_TOPIC,
                             {**encode_frame(frame), "meta": {"seq": i}})
        deadline = time.monotonic() + 5.0
        while co.parity.samples == 0 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert co.parity.samples > 0
    finally:
        service.stop()
        co.stop()
    state.close()


# ---------- rollback: the same mechanism, prior space ----------


def test_rollback_restores_prior_space(tmp_path, mesh, rotation):
    rng = np.random.default_rng(7)
    state, gallery, names = _writer(tmp_path, mesh)
    embs = [_enroll(state, gallery, names, rng, i) for i in range(3)]
    co = _coordinator(state, gallery, rotation)
    co.run_stage()
    co.cutover(force=True)
    assert gallery.embedder_version == 2
    # Rollback = a NEW rollout whose reembed inverts the map, at the next
    # monotonic version.
    back = co.rollback(lambda rows: rows @ rotation.T)
    assert back.to_version == 3
    back.run_stage()
    back.cutover(force=True)
    assert gallery.embedder_version == 3
    got, _l, _v, size = gallery.snapshot()
    want = _norm(np.concatenate(embs))
    assert np.allclose(got[:size], want, atol=1e-5)
    state.close()


# ---------- fleet: replica fence + router cordon ----------


def test_replica_parks_on_fence_then_reanchors(tmp_path, mesh, rotation):
    rng = np.random.default_rng(8)
    state, wg, wnames = _writer(tmp_path, mesh)
    for i in range(3):
        _enroll(state, wg, wnames, rng, i)
    rg = ShardedGallery(capacity=64, dim=DIM, mesh=mesh)
    rmetrics = Metrics()
    rep = ReadReplica(str(tmp_path), rg, [], metrics=rmetrics,
                      poll_interval_s=0.0, name="r")
    rep.poll(force=True)
    assert rep.embedder_version == 1
    co = _coordinator(state, wg, rotation)
    co.run_stage()
    # Suppress the automatic post-cutover checkpoint so the fence window
    # is observable: perform the locked swap directly.
    state.perform_cutover(2, lambda: _build_arrays(wg))
    out = rep.poll(force=True)
    assert out.get("awaiting_version") == 2 or \
        rep.stats()["awaiting_cutover"] is not None
    assert rep.embedder_version == 1  # still serving pure old-version rows
    assert rmetrics.gauge("rollout_replica_awaiting") == 1
    # Enrollments landing at v2 while parked must NOT apply.
    _enroll(state, wg, wnames, rng, 3)
    rep.poll(force=True)
    assert rep.gallery.size == 3
    # The new-version checkpoint lands: the replica re-anchors and
    # catches the v2 tail up.
    assert state.checkpoint_now(wait=True)
    rep.poll(force=True)
    assert rep.embedder_version == 2
    assert rmetrics.counter("rollout_replica_reanchors") == 1
    assert rmetrics.gauge("rollout_replica_awaiting") == 0
    deadline = time.monotonic() + 5.0
    while rep.applied_seq < state.wal_seq and time.monotonic() < deadline:
        rep.poll(force=True)
        time.sleep(0.01)
    _assert_equal_galleries(wg, rg)
    state.close()


def _build_arrays(gallery):
    emb, lab, val, size = gallery.snapshot()
    return emb, lab, val, size


def _assert_equal_galleries(a, b):
    ae, al, _av, asz = a.snapshot()
    be, bl, _bv, bsz = b.snapshot()
    assert asz == bsz
    assert np.array_equal(al[:asz], bl[:bsz])
    assert np.allclose(ae[:asz], be[:bsz], rtol=0, atol=1e-6)


def test_router_cordon_drains_and_hands_back():
    metrics = Metrics()
    handles = [ReplicaHandle(f"replica-{i}", FakeConnector())
               for i in range(2)]
    router = TopicRouter(handles, metrics=metrics)
    topics = [f"camera/{i}" for i in range(32)]
    before = {t: router.route(t).name for t in topics}
    router.set_cordon("replica-0", True)
    during = {t: router.route(t).name for t in topics}
    assert all(v == "replica-1" for v in during.values())
    # Cordon is choreography, not an incident: counted as a drain, never
    # a failover.
    assert metrics.counter("router_cutover_drains") == 1
    assert not metrics.counter("router_failovers")
    router.set_cordon("replica-0", False)
    after = {t: router.route(t).name for t in topics}
    assert after == before  # exactly its own topics hand back
    with pytest.raises(KeyError):
        router.set_cordon("nope", True)
    # The on_resync adapter wires begin/end to cordon/uncordon.
    hook = router.cordon_hook("replica-1")
    hook("begin")
    assert handles[1].cordoned
    hook("end")
    assert not handles[1].cordoned


# ---------- offline verifier: the version fence ----------


def test_verify_checkpoint_version_fence(tmp_path, mesh, rotation):
    rng = np.random.default_rng(9)
    state, gallery, names = _writer(tmp_path, mesh)
    for i in range(2):
        _enroll(state, gallery, names, rng, i)
    verify = _load_script("verify_checkpoint")
    report = verify.verify_state_dir(str(tmp_path))
    assert report["ok"]
    assert report["wal"]["version_violations"] == []
    # A legitimate cutover keeps the walk clean.
    co = _coordinator(state, gallery, rotation)
    co.run_stage()
    state.perform_cutover(2, lambda: _build_arrays(gallery))
    _enroll(state, gallery, names, rng, 2)  # a v2 row past the fence
    report = verify.verify_state_dir(str(tmp_path))
    assert report["ok"], report
    assert report["wal"]["cutover_records"] == 1
    # A row spanning versions WITHOUT a fence is the rc-2 breach.
    state.wal.append_enroll(99, np.ones((1, DIM), np.float32),
                            np.zeros(1, np.int32), embedder_version=7)
    report = verify.verify_state_dir(str(tmp_path))
    assert not report["ok"]
    assert report["wal"]["version_violations"]
    assert verify.main([str(tmp_path)]) == 2
    state.close()


def test_verify_checkpoint_bad_version_header(tmp_path, mesh):
    rng = np.random.default_rng(10)
    state, gallery, names = _writer(tmp_path, mesh)
    _enroll(state, gallery, names, rng, 0)
    assert state.checkpoint_now(wait=True)
    verify = _load_script("verify_checkpoint")
    report = verify.verify_state_dir(str(tmp_path))
    assert report["ok"] and report["embedder_version"] == 1
    state.close()


# ---------- the trainer's multibatch fine-tune ----------


def test_finetune_embedder_multibatch():
    from opencv_facerecognizer_tpu.runtime.trainer import TheTrainer

    rng = np.random.default_rng(11)
    images = rng.uniform(0, 255, size=(24, 16, 16)).astype(np.float32)
    labels = np.repeat(np.arange(4, dtype=np.int32), 6)
    trainer = TheTrainer(model="cnn", kfold=0, image_size=(16, 16),
                         embed_dim=8, train_steps=2,
                         cnn_kwargs={"stem_features": 4,
                                     "stage_features": (4,),
                                     "stage_blocks": (1,),
                                     "batch_size": 8})
    trainer.train(images, labels, [f"s{i}" for i in range(4)],
                  validate=False)
    old_emb = np.asarray(trainer.model.feature.extract(images[:4]))
    new_feature = trainer.finetune_embedder(
        images, labels, steps=3, identities_per_batch=3,
        samples_per_identity=2, learning_rate=1e-3, seed=1)
    # The fine-tune returns a NEW feature; the serving model is untouched.
    assert new_feature is not trainer.model.feature
    assert np.allclose(
        np.asarray(trainer.model.feature.extract(images[:4])), old_emb)
    new_emb = np.asarray(new_feature.extract(images[:4]))
    assert new_emb.shape == old_emb.shape
    assert not np.allclose(new_emb, old_emb)  # it actually trained
    # The source-store reembed_fn is index-aware and deterministic.
    reembed = TheTrainer.make_reembed_fn(new_feature, images)
    a = reembed(np.zeros((3, 8), np.float32), 2)
    b = reembed(np.zeros((3, 8), np.float32), 2)
    assert np.array_equal(a, b)
    assert a.shape == (3, 8)


# ---------- the fast deterministic tier-1 soak ----------


def test_rollout_soak_fast_deterministic():
    """Tier-1 variant of ``--scenario rollout``: kills mid-re-embed (with
    durable-watermark resume), mid-cutover (recovery completes the fenced
    swap), and a reader mid-re-anchor; zero acked loss on writer /
    surviving reader / replacement, monotonic per-replica version stamps
    (no mixed-version scores), serving continuity through the cutover
    window, and a clean offline version-fence verification."""
    chaos_soak = _load_script("chaos_soak")
    report = chaos_soak.run_rollout(seconds=3.0, seed=7)
    assert report["ok"], report["failures"]
    assert report["stale_enroll_refused"]
    assert report["verify"]["embedder_version"] == 2
    assert report["cutover_window_max_gap_s"] < 2.0
    for name, stamp in report["result_stamps"].items():
        assert set(stamp["versions"]) <= {1, 2}, (name, stamp)
