"""ocvf-lint framework tests: per-rule fixture snippets (positive, negative,
suppressed), suppression hygiene, CLI exit-code contract, and the tier-1
gate that the real tree is clean.

The fixture tests assert exact (rule, line) pairs — the acceptance bar is
that a deliberately seeded violation of every rule is detected at the
correct file:line, not merely that "something" fires."""

from __future__ import annotations

import json
import os
import subprocess
import sys
import textwrap
import threading

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

from tools.ocvf_lint import core  # noqa: E402


def lint_tree(tmp_path, files, rules=None):
    """Write {relpath: source} under tmp_path and lint the tree."""
    for rel, src in files.items():
        path = tmp_path / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(textwrap.dedent(src))
    return core.run([str(tmp_path)], rules=rules).findings


def lint_source(tmp_path, source, rules=None):
    return lint_tree(tmp_path, {"mod.py": source}, rules=rules)


def rules_and_lines(findings):
    return [(f.rule, f.line) for f in findings]


# ---------------- blocking-under-lock ----------------


def test_blocking_under_lock_positive(tmp_path):
    findings = lint_source(tmp_path, """\
        import time

        class S:
            def bad(self):
                with self._lock:
                    time.sleep(0.1)
        """, rules=["blocking-under-lock"])
    assert rules_and_lines(findings) == [("blocking-under-lock", 6)]


def test_blocking_under_lock_negatives(tmp_path):
    findings = lint_source(tmp_path, """\
        import time

        class S:
            def sleep_outside(self):
                with self._lock:
                    x = 1
                time.sleep(0.1)

            def nested_def_resets(self):
                with self._lock:
                    def later():
                        time.sleep(0.1)  # runs outside the lock
                    self.hook = later

            def str_join_is_not_io(self):
                with self._lock:
                    return ", ".join(["a"])
        """, rules=["blocking-under-lock"])
    assert findings == []


def test_blocking_under_lock_io_and_suppression(tmp_path):
    findings = lint_source(tmp_path, """\
        import os

        class S:
            def fsyncs(self, fh):
                with self._lock:
                    os.fsync(fh.fileno())

            def justified(self, fh):
                with self._lock:  # ocvf-lint: disable-block=blocking-under-lock -- this lock exists to serialize these writes
                    fh.write(b"x")
                    fh.flush()
        """, rules=["blocking-under-lock"])
    assert rules_and_lines(findings) == [("blocking-under-lock", 6)]


# ---------------- lock-order ----------------


def test_lock_order_inversion_detected(tmp_path):
    findings = lint_source(tmp_path, """\
        class S:
            def ab(self):
                with self._a_lock:
                    with self._b_lock:
                        pass

            def ba(self):
                with self._b_lock:
                    with self._a_lock:
                        pass
        """, rules=["lock-order"])
    assert len(findings) == 1
    assert findings[0].rule == "lock-order"
    assert findings[0].line == 4  # the first edge site
    assert "inversion" in findings[0].message


def test_lock_order_consistent_is_clean(tmp_path):
    findings = lint_source(tmp_path, """\
        class S:
            def one(self):
                with self._a_lock:
                    with self._b_lock:
                        pass

            def two(self):
                with self._a_lock:
                    with self._b_lock:
                        pass
        """, rules=["lock-order"])
    assert findings == []


def test_lock_order_re_entry_detected(tmp_path):
    findings = lint_source(tmp_path, """\
        class S:
            def re_enter(self):
                with self._lock:
                    with self._lock:
                        pass
        """, rules=["lock-order"])
    assert rules_and_lines(findings) == [("lock-order", 4)]
    assert "re-acquired" in findings[0].message


def test_lock_order_call_propagation(tmp_path):
    """An inversion only visible through a method call: ab() nests
    lexically, ba() holds b and CALLS a helper that takes a."""
    findings = lint_source(tmp_path, """\
        class S:
            def ab(self):
                with self._a_lock:
                    with self._b_lock:
                        pass

            def take_a(self):
                with self._a_lock:
                    pass

            def ba(self):
                with self._b_lock:
                    self.take_a()
        """, rules=["lock-order"])
    assert len(findings) == 1
    assert "inversion" in findings[0].message


def test_lock_order_suppression_at_any_edge(tmp_path):
    findings = lint_source(tmp_path, """\
        class S:
            def ab(self):
                with self._a_lock:
                    with self._b_lock:  # ocvf-lint: disable=lock-order -- ordered handoff proven safe by construction here
                        pass

            def ba(self):
                with self._b_lock:
                    with self._a_lock:
                        pass
        """, rules=["lock-order"])
    assert findings == []


# ---------------- non-atomic-write ----------------


def test_non_atomic_write_positive(tmp_path):
    findings = lint_source(tmp_path, """\
        import json

        def save(path, obj):
            with open(path, "w") as fh:
                json.dump(obj, fh)
        """, rules=["non-atomic-write"])
    assert rules_and_lines(findings) == [("non-atomic-write", 4)]


def test_non_atomic_write_negatives(tmp_path):
    findings = lint_source(tmp_path, """\
        def fine(path):
            with open(path) as fh:
                data = fh.read()
            with open(path, "rb") as fh:
                blob = fh.read()
            with open(path, "a") as fh:  # append = journal-style, exempt
                fh.write("x")
            return data, blob
        """, rules=["non-atomic-write"])
    assert findings == []


def test_non_atomic_write_exempt_layers_and_suppression(tmp_path):
    findings = lint_tree(tmp_path, {
        "utils/serialization.py": """\
            def atomic_write_bytes(path, blob):
                with open(path + ".tmp", "wb") as fh:  # the helper itself
                    fh.write(blob)
            """,
        "app.py": """\
            def dump(path, text):
                # ocvf-lint: disable=non-atomic-write -- throwaway debug artifact, torn file is harmless
                with open(path, "w") as fh:
                    fh.write(text)
            """,
        "pathlib_user.py": """\
            def bad(p):
                p.write_text("hello")
            """,
    }, rules=["non-atomic-write"])
    assert [(f.rule, os.path.basename(f.path), f.line) for f in findings] == [
        ("non-atomic-write", "pathlib_user.py", 2)]


# ---------------- metrics-registry ----------------

METRIC_FIXTURE_REGISTRY = """\
    GOOD = "good_metric"
    OTHER = "other_metric"
    FAMILY_PREFIX = "fam_"
    """


def test_metrics_registry_literals(tmp_path):
    findings = lint_tree(tmp_path, {
        "utils/metric_names.py": METRIC_FIXTURE_REGISTRY,
        "app.py": """\
            def f(metrics, reason):
                metrics.incr("good_metric")
                metrics.incr("bad_typo_metric")
                metrics.observe("other_metric", 1.0)
                metrics.incr(f"fam_{reason}")
                metrics.incr(f"unregistered_{reason}")
            """,
    }, rules=["metrics-registry"])
    assert rules_and_lines(findings) == [("metrics-registry", 3),
                                         ("metrics-registry", 6)]


def test_metrics_registry_constants_and_prefix_concat(tmp_path):
    findings = lint_tree(tmp_path, {
        "utils/metric_names.py": METRIC_FIXTURE_REGISTRY,
        "app.py": """\
            import utils.metric_names as mn
            from utils.metric_names import GOOD

            def f(metrics, reason, name):
                metrics.incr(mn.GOOD)
                metrics.incr(GOOD)
                metrics.incr(mn.FAMILY_PREFIX + reason)
                metrics.incr(mn.DOES_NOT_EXIST)
                metrics.incr(name)
            """,
    }, rules=["metrics-registry"])
    assert rules_and_lines(findings) == [("metrics-registry", 8),
                                         ("metrics-registry", 9)]


def test_metrics_registry_prefix_strictness(tmp_path):
    """Prefix/name pools stay disjoint: a bare prefix is not a counter
    name, a full name is not a prefix, and concatenation requires a
    *_PREFIX constant (or its literal value) on the left."""
    findings = lint_tree(tmp_path, {
        "utils/metric_names.py": METRIC_FIXTURE_REGISTRY,
        "app.py": """\
            import utils.metric_names as mn

            def f(metrics, reason):
                metrics.incr("fam_" + reason)          # literal prefix: ok
                metrics.incr(mn.FAMILY_PREFIX + reason)
                metrics.incr(mn.GOOD + reason)          # full name + x: drift
                metrics.incr("fam_")                    # bare prefix as name
                metrics.counters_with_prefix("good_metric")  # name as prefix
            """,
    }, rules=["metrics-registry"])
    assert rules_and_lines(findings) == [("metrics-registry", 6),
                                         ("metrics-registry", 7),
                                         ("metrics-registry", 8)]


def test_metrics_registry_checks_count_shim_sites(tmp_path):
    findings = lint_tree(tmp_path, {
        "utils/metric_names.py": METRIC_FIXTURE_REGISTRY,
        "app.py": """\
            def f(conn):
                conn._count("good_metric")
                conn._count("conector_reconects")  # the typo class
            """,
    }, rules=["metrics-registry"])
    assert rules_and_lines(findings) == [("metrics-registry", 3)]


def test_metrics_registry_read_sites_and_np_percentile(tmp_path):
    findings = lint_tree(tmp_path, {
        "utils/metric_names.py": METRIC_FIXTURE_REGISTRY,
        "app.py": """\
            import numpy as np

            def f(metrics, ts):
                metrics.counter("good_metric")
                metrics.counter("typo_metric")
                metrics.counters_with_prefix("fam_")
                return np.percentile(ts, 50)  # not a Metrics read
            """,
    }, rules=["metrics-registry"])
    assert rules_and_lines(findings) == [("metrics-registry", 5)]


# ---------------- swallowed-exception ----------------


def test_swallowed_exception_positive(tmp_path):
    findings = lint_source(tmp_path, """\
        def f():
            try:
                work()
            except Exception:
                pass
            try:
                work()
            except:
                return None
        """, rules=["swallowed-exception"])
    assert rules_and_lines(findings) == [("swallowed-exception", 4),
                                         ("swallowed-exception", 8)]


def test_swallowed_exception_accounted_forms_pass(tmp_path):
    findings = lint_source(tmp_path, """\
        def f(metrics, log, q):
            try:
                work()
            except Exception:
                metrics.incr("errors")
            try:
                work()
            except Exception:
                raise RuntimeError("wrapped")
            try:
                work()
            except Exception as e:
                q["error"] = repr(e)  # exception is read -> recorded
            try:
                work()
            except ValueError:
                pass  # narrow except is out of scope for this rule
        """, rules=["swallowed-exception"])
    assert findings == []


def test_swallowed_exception_suppression(tmp_path):
    findings = lint_source(tmp_path, """\
        def f():
            try:
                work()
            except Exception:  # ocvf-lint: disable=swallowed-exception -- teardown is best-effort by contract
                pass
        """, rules=["swallowed-exception"])
    assert findings == []


# ---------------- suppression hygiene ----------------


def test_bare_suppression_is_inert_and_flagged(tmp_path):
    findings = lint_source(tmp_path, """\
        import time

        class S:
            def bad(self):
                with self._lock:
                    time.sleep(0.1)  # ocvf-lint: disable=blocking-under-lock
        """, rules=["blocking-under-lock"])
    got = rules_and_lines(findings)
    assert ("suppression", 6) in got          # the bare disable is a finding
    assert ("blocking-under-lock", 6) in got  # and it suppressed NOTHING


def test_short_justification_counts_as_bare(tmp_path):
    findings = lint_source(tmp_path, """\
        import time

        class S:
            def bad(self):
                with self._lock:
                    time.sleep(0.1)  # ocvf-lint: disable=blocking-under-lock -- ok
        """, rules=["blocking-under-lock"])
    assert ("suppression", 6) in rules_and_lines(findings)


def test_unknown_rule_in_suppression_flagged(tmp_path):
    findings = lint_source(tmp_path, """\
        x = 1  # ocvf-lint: disable=no-such-rule -- justification text here
        """)
    assert [(f.rule, f.line) for f in findings] == [("suppression", 1)]
    assert "unknown rule" in findings[0].message


def test_disable_file_covers_everything(tmp_path):
    findings = lint_source(tmp_path, """\
        # ocvf-lint: disable-file=non-atomic-write -- scratch artifact writer, torn output is harmless
        def a(p):
            open(p, "w").write("x")

        def b(p):
            open(p, "w").write("y")
        """, rules=["non-atomic-write"])
    assert findings == []


def test_disable_block_covers_whole_statement(tmp_path):
    findings = lint_source(tmp_path, """\
        import os

        class S:
            def f(self, fh):
                with self._lock:  # ocvf-lint: disable-block=blocking-under-lock -- serializing these writes is the purpose of this lock
                    fh.write(b"a")
                    fh.flush()
                    os.fsync(fh.fileno())
                with self._lock:
                    fh.write(b"b")
        """, rules=["blocking-under-lock"])
    assert rules_and_lines(findings) == [("blocking-under-lock", 10)]


def test_suppression_meta_rule_cannot_be_suppressed(tmp_path):
    findings = lint_source(tmp_path, """\
        x = 1  # ocvf-lint: disable=unknown-thing -- long enough justification ; ocvf-lint: disable=suppression -- nice try
        """)
    assert any(f.rule == "suppression" for f in findings)


def test_parse_error_is_a_finding_not_a_crash(tmp_path):
    findings = lint_source(tmp_path, "def broken(:\n")
    assert [f.rule for f in findings] == ["parse-error"]


# ---------------- CLI contract ----------------


def _cli(*args, cwd=REPO_ROOT):
    return subprocess.run(
        [sys.executable, "-m", "tools.ocvf_lint", *args],
        cwd=cwd, capture_output=True, text=True, timeout=120,
        env={**os.environ, "PYTHONPATH": REPO_ROOT
             + os.pathsep + os.environ.get("PYTHONPATH", "")})


def test_cli_exit_0_on_clean(tmp_path):
    clean = tmp_path / "clean.py"
    clean.write_text("x = 1\n")
    proc = _cli(str(clean))
    assert proc.returncode == 0, proc.stderr


def test_cli_exit_1_on_findings_and_json(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text('def f(p):\n    open(p, "w").write("x")\n')
    proc = _cli("--json", str(bad))
    assert proc.returncode == 1
    doc = json.loads(proc.stdout)
    assert doc["findings"][0]["rule"] == "non-atomic-write"
    assert doc["findings"][0]["line"] == 2


def test_cli_exit_2_on_internal_error(tmp_path):
    proc = _cli(str(tmp_path / "does-not-exist"))
    assert proc.returncode == 2


ALL_RULES = ("lock-order", "blocking-under-lock", "non-atomic-write",
             "metrics-registry", "swallowed-exception",
             "jit-recompile-hazard", "host-sync", "prng-discipline",
             "epoch-pairing", "wal-before-mutate",
             "settle-once", "resource-pairing", "fence-ordering",
             "ledger-registry-coherence")


def test_cli_list_rules_names_all_fourteen(tmp_path):
    proc = _cli("--list-rules")
    assert proc.returncode == 0
    for rule in ALL_RULES:
        assert rule in proc.stdout


# ---------------- the tier-1 gate: the real tree is clean ----------------


def test_real_tree_has_zero_findings():
    """The acceptance bar: ``python -m tools.ocvf_lint
    opencv_facerecognizer_tpu scripts`` exits 0 at head, with all
    FOURTEEN rules active (v2 added jit-recompile-hazard / host-sync /
    prng-discipline / epoch-pairing / wal-before-mutate; v3 added
    settle-once / resource-pairing / fence-ordering /
    ledger-registry-coherence) and every suppression/boundary
    justified."""
    proc = _cli("opencv_facerecognizer_tpu", "scripts", "--json",
                "--no-cache")
    assert proc.returncode == 0, f"lint found issues:\n{proc.stdout}\n{proc.stderr}"
    doc = json.loads(proc.stdout)
    assert doc["findings"] == []
    assert set(doc["rules"]) >= set(ALL_RULES)
    assert doc["files_scanned"] > 40
    # the v2 hot-path rules are live, not vacuous: the designed boundary
    # sites (sacrificial blocker, prewarm, the one per-batch materialize,
    # offline gallery builders) are annotated and honored
    assert doc["boundaries_used"] >= 20


def test_baseline_ratchet_enforced_at_head():
    """LINT_BASELINE.json is the checked-in ratchet: the gate run passes
    against it, it covers every v2 rule, and at head every frozen count is
    already zero (counts may only shrink — never edit them upward; new
    findings must be fixed or suppressed with justification)."""
    baseline_path = os.path.join(REPO_ROOT, "LINT_BASELINE.json")
    with open(baseline_path) as fh:
        doc = json.load(fh)
    assert set(doc["rules"]) >= set(ALL_RULES)
    assert all(v == 0 for v in doc["rules"].values()), doc["rules"]
    proc = _cli("opencv_facerecognizer_tpu", "scripts", "--no-cache",
                "--baseline", baseline_path)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_real_lock_graph_is_nonempty_and_acyclic():
    """The static inter-module lock graph over the real runtime must keep
    seeing the known edges (StateLifecycle -> WAL/journal/gallery/metrics)
    — if this goes empty the lock-order rule has silently gone blind."""
    from tools.ocvf_lint.checkers.lock_order import build_lock_graph

    edges = set(build_lock_graph(
        [os.path.join(REPO_ROOT, "opencv_facerecognizer_tpu")]))
    assert any(a.endswith("StateLifecycle._enroll_lock") for a, _ in edges)
    assert any(b.endswith("Metrics._lock") for _, b in edges)
    inverted = [(a, b) for (a, b) in edges if a != b and (b, a) in edges]
    assert not inverted


# ---------------- metric_names registry sanity ----------------


def test_metric_names_registry_no_duplicates():
    from opencv_facerecognizer_tpu.utils import metric_names as mn

    names = mn.all_names()
    assert len(names) == len(set(names)), "duplicate metric name values"
    assert len(names) > 50
    prefixes = mn.all_prefixes()
    assert all(p.endswith("_") for p in prefixes)
    # no full name may collide into a prefix family ambiguously with itself
    assert len(prefixes) == len(set(prefixes))


# ---------------- DebugLock dynamic backstop unit tests ----------------


def test_debug_lock_records_edges_and_detects_inversion():
    from opencv_facerecognizer_tpu.utils.debug_lock import (
        DebugLock, LockOrderError, LockOrderMonitor)

    monitor = LockOrderMonitor()
    a = monitor.debug_lock("A")
    b = monitor.debug_lock("B")
    with a:
        with b:
            pass
    assert monitor.edges() == {("A", "B")}
    monitor.check()  # consistent so far
    with b:
        with a:
            pass
    assert monitor.inversions() == [("A", "B")]
    with pytest.raises(LockOrderError):
        monitor.check()


def test_debug_lock_re_entry_raises_immediately():
    from opencv_facerecognizer_tpu.utils.debug_lock import (
        LockOrderError, LockOrderMonitor)

    monitor = LockOrderMonitor()
    a = monitor.debug_lock("A")
    with a:
        with pytest.raises(LockOrderError):
            a.acquire()


def test_debug_lock_backs_a_condition_variable():
    from opencv_facerecognizer_tpu.utils.debug_lock import LockOrderMonitor

    monitor = LockOrderMonitor()
    inner = monitor.debug_lock("CV")
    cv = threading.Condition(inner)
    hits = []

    def waiter():
        with cv:
            while not hits:
                cv.wait(timeout=5.0)

    t = threading.Thread(target=waiter)
    t.start()
    with cv:
        hits.append(1)
        cv.notify()
    t.join(timeout=5.0)
    assert not t.is_alive()
    monitor.check()


# ===================== v2: JAX-aware dataflow rules =====================

# ---------------- jit-recompile-hazard ----------------


def test_jit_hazard_branch_and_interprocedural_materialize(tmp_path):
    findings = lint_source(tmp_path, """\
        import jax
        import functools

        @jax.jit
        def bad(x):
            if x > 0:
                return x
            return -x

        def helper(y):
            return float(y)

        @jax.jit
        def bad2(x):
            return helper(x)

        @functools.partial(jax.jit, static_argnames=("flag",))
        def ok_static(x, flag):
            if flag:
                return x
            return -x

        @jax.jit
        def ok_shape(x):
            if x.shape[0] > 8:
                return x
            return x.reshape((-1,))
        """, rules=["jit-recompile-hazard"])
    assert rules_and_lines(findings) == [("jit-recompile-hazard", 6),
                                         ("jit-recompile-hazard", 11)]
    assert "branch" in findings[0].message
    assert "float()" in findings[1].message  # found INSIDE the callee


def test_jit_hazard_call_form_and_nested_step(tmp_path):
    """The pipeline idiom: a nested ``step`` wrapped by jax.jit(step)."""
    findings = lint_source(tmp_path, """\
        import jax
        import numpy as np

        def build():
            def step(params, frames):
                frames = frames.astype("float32")
                n = np.asarray(frames)
                return frames

            return jax.jit(step)
        """, rules=["jit-recompile-hazard"])
    assert rules_and_lines(findings) == [("jit-recompile-hazard", 7)]
    assert "np.asarray" in findings[0].message


def test_jit_hazard_hot_path_construction_needs_boundary(tmp_path):
    findings = lint_tree(tmp_path, {
        "parallel/pipeline.py": """\
            import jax

            def build(step):
                return jax.jit(step)

            def build_ok(step):
                return jax.jit(step)  # ocvf-lint: boundary=jit-recompile-hazard -- cache-keyed builder, warmed for every ladder bucket before serving
            """,
        "models/other.py": """\
            import jax

            def build(step):
                return jax.jit(step)  # not a hot-path module: fine
            """,
    }, rules=["jit-recompile-hazard"])
    assert [(f.rule, os.path.basename(f.path), f.line) for f in findings] == [
        ("jit-recompile-hazard", "pipeline.py", 4)]


# ---------------- host-sync ----------------

HOT_SYNC_FIXTURE = """\
    import numpy as np

    class S:
        def serve(self, frames):
            frames = np.asarray(frames)
            packed = self.pipeline.recognize_batch_packed(frames)
            self._inflight.append((packed, 1))

        def drain(self):
            packed, n = self._inflight[0]
            arr = np.asarray(packed)
            return arr

        def probe(self, packed):
            return packed.item()
    """


def test_host_sync_taint_through_inflight_deque(tmp_path):
    findings = lint_tree(tmp_path, {"runtime/recognizer.py": HOT_SYNC_FIXTURE},
                         rules=["host-sync"])
    # np.asarray(frames) at line 5 is a HOST value — no finding; the
    # dispatched batch popped back out of self._inflight IS device-tainted,
    # and .item() is unconditionally a sync in hot-path modules.
    assert rules_and_lines(findings) == [("host-sync", 11), ("host-sync", 15)]


def test_host_sync_scope_and_boundary_annotation(tmp_path):
    findings = lint_tree(tmp_path, {
        "runtime/other.py": HOT_SYNC_FIXTURE,  # not a hot-path module
        "runtime/batcher.py": """\
            import numpy as np

            class B:
                def put(self, frame):
                    frame = np.asarray(frame)  # host frame: clean
                    return frame

                def wait(self, out):
                    out.block_until_ready()  # ocvf-lint: boundary=host-sync -- fixture: designed sync point for this test
            """,
    }, rules=["host-sync"])
    assert findings == []


def test_host_sync_block_until_ready_flagged(tmp_path):
    findings = lint_tree(tmp_path, {
        "parallel/pipeline.py": """\
            def prewarm(out):
                out.block_until_ready()
            """,
    }, rules=["host-sync"])
    assert rules_and_lines(findings) == [("host-sync", 2)]


# ---------------- prng-discipline ----------------


def test_prng_reuse_loop_and_nondet_seed(tmp_path):
    findings = lint_source(tmp_path, """\
        import jax
        import time

        def bad(seed):
            key = jax.random.PRNGKey(seed)
            a = jax.random.normal(key, (3,))
            b = jax.random.uniform(key, (3,))
            return a, b

        def loop_bad(seed):
            key = jax.random.PRNGKey(seed)
            out = []
            for i in range(3):
                out.append(jax.random.normal(key, (3,)))
            return out

        def nondet():
            return jax.random.PRNGKey(int(time.time()))
        """, rules=["prng-discipline"])
    assert rules_and_lines(findings) == [("prng-discipline", 7),
                                         ("prng-discipline", 14),
                                         ("prng-discipline", 18)]
    assert "reused" in findings[0].message or "consumed again" in findings[0].message
    assert "loop" in findings[1].message
    assert "time.time" in findings[2].message


def test_prng_split_fold_in_and_loop_resplit_are_clean(tmp_path):
    findings = lint_source(tmp_path, """\
        import jax
        import numpy as np

        def ok(seed):
            key = jax.random.PRNGKey(seed)
            k1, k2 = jax.random.split(key)
            return jax.random.normal(k1, (3,)), jax.random.uniform(k2, (3,))

        def ok_fold(seed):
            rng = jax.random.PRNGKey(seed)
            a = jax.random.normal(jax.random.fold_in(rng, 1), (3,))
            b = jax.random.normal(jax.random.fold_in(rng, 2), (3,))
            return a, b

        def ok_loop(seed):
            key = jax.random.PRNGKey(seed)
            out = []
            for i in range(3):
                key, sub = jax.random.split(key)
                out.append(jax.random.normal(sub, (3,)))
            return out

        def np_random_is_not_jax(rng):
            return np.random.normal(0.0, 1.0, (3,))
        """, rules=["prng-discipline"])
    assert findings == []


def test_prng_nondet_seed_exempt_in_tests(tmp_path):
    findings = lint_tree(tmp_path, {
        "tests/test_something.py": """\
            import jax
            import time

            def make_key():
                return jax.random.PRNGKey(int(time.time()))
            """,
    }, rules=["prng-discipline"])
    assert findings == []


# ---------------- epoch-pairing ----------------


def test_epoch_pairing_guarded_fields_and_raw_quantizer(tmp_path):
    findings = lint_tree(tmp_path, {
        "mod.py": """\
            def bad(gallery):
                return gallery._epoch

            def bad2(self):
                return self.gallery.quantizer.data

            def bad3(gallery):
                emb = gallery.embeddings
                lab = gallery.labels
                return emb, lab

            def ok(gallery):
                data = gallery.data
                return data.embeddings, data.labels

            class Unrelated:
                def own_private_data_is_fine(self):
                    return self._data
            """,
        "parallel/gallery.py": """\
            class ShardedGallery:
                def bump(self):
                    self._epoch += 1
            """,
        "parallel/quantizer.py": """\
            class CoarseQuantizer:
                def publish(self, data):
                    self._data = data
            """,
    }, rules=["epoch-pairing"])
    assert [(f.rule, os.path.basename(f.path), f.line) for f in findings] == [
        ("epoch-pairing", "mod.py", 2),
        ("epoch-pairing", "mod.py", 5),
        ("epoch-pairing", "mod.py", 9)]
    assert "_ivf_data" in findings[1].message
    assert "snapshot" in findings[2].message


def test_epoch_pairing_suppression(tmp_path):
    findings = lint_source(tmp_path, """\
        def debug_dump(gallery):
            return gallery._epoch  # ocvf-lint: disable=epoch-pairing -- offline debug dump, no serving thread can race this tool
        """, rules=["epoch-pairing"])
    assert findings == []


# ---------------- wal-before-mutate ----------------


def test_wal_before_mutate_positive_and_apply_fn_route(tmp_path):
    findings = lint_tree(tmp_path, {
        "mod.py": """\
            class S:
                def bad(self, emb, labels):
                    self.gallery.add(emb, labels)

                def good(self, emb, labels):
                    self.state.append_enrollment(
                        emb, labels,
                        apply_fn=lambda: self.gallery.add(emb, labels))

                def bad_wal(self, rec):
                    self.wal.append(rec)

                def reads_are_fine(self):
                    return self.wal.replay()
            """,
        "runtime/state_store.py": """\
            class StateLifecycle:
                def replay(self, gallery, rec):
                    gallery.add(rec["emb"], rec["labels"])
            """,
    }, rules=["wal-before-mutate"])
    assert [(f.rule, os.path.basename(f.path), f.line) for f in findings] == [
        ("wal-before-mutate", "mod.py", 3),
        ("wal-before-mutate", "mod.py", 11)]


def test_wal_before_mutate_boundary_for_nondurable_gallery(tmp_path):
    findings = lint_source(tmp_path, """\
        def bench(gallery, rows, labs):
            gallery.add(rows, labs)  # ocvf-lint: boundary=wal-before-mutate -- synthetic bench gallery, no state dir, nothing durable at stake
        """, rules=["wal-before-mutate"])
    assert findings == []


# ---------------- boundary annotation hygiene ----------------


def test_boundary_requires_justification_and_capability(tmp_path):
    findings = lint_source(tmp_path, """\
        def f(gallery, rows, labs):
            gallery.add(rows, labs)  # ocvf-lint: boundary=wal-before-mutate
        """, rules=["wal-before-mutate"])
    got = rules_and_lines(findings)
    assert ("suppression", 2) in got           # bare boundary is a finding
    assert ("wal-before-mutate", 2) in got     # and it sanctioned NOTHING

    findings = lint_source(tmp_path, """\
        def f():
            try:
                work()
            except Exception:  # ocvf-lint: boundary=swallowed-exception -- boundaries are not defined for this rule
                pass
        """, rules=["swallowed-exception"])
    got = rules_and_lines(findings)
    assert ("suppression", 4) in got           # rule defines no boundaries
    assert ("swallowed-exception", 4) in got   # so nothing was sanctioned


def test_boundary_counts_reported_separately(tmp_path):
    path = tmp_path / "parallel" / "pipeline.py"
    path.parent.mkdir(parents=True)
    path.write_text(textwrap.dedent("""\
        def prewarm(out):
            out.block_until_ready()  # ocvf-lint: boundary=host-sync -- prewarm thread blocks by design in this fixture
        """))
    result = core.run([str(tmp_path)], rules=["host-sync"])
    assert result.findings == []
    assert result.boundaries_used == 1
    assert result.suppressions_used == 0


# ---------------- settle-once (v3) ----------------

#: minimal ledger registry shared by the settle-once fixtures: the rule
#: resolves terminal statuses through these tables, not hard-coded names.
_MN_FIXTURE = """\
    FRAMES_COMPLETED = "frames_completed"
    FRAMES_FAILED = "frames_failed"
    BATCHER_DROPPED_PREFIX = "batcher_dropped_"
    FRAMES_ADMITTED = "frames_admitted"
    LEDGER_COMPLETION_COUNTERS = (FRAMES_COMPLETED,)
    LEDGER_DROP_COUNTERS = (FRAMES_FAILED,)
    PROM_FOLDED_PREFIXES = ()
    """


def test_settle_once_unsettled_incr_on_exit_path(tmp_path):
    findings = lint_tree(tmp_path, {
        "utils/metric_names.py": _MN_FIXTURE,
        "runtime/service.py": """\
            from utils import metric_names as mn

            class RecognizerService:
                def fail_path(self, tids, count):
                    self.metrics.incr(mn.FRAMES_FAILED, count)
                    return count
            """,
    }, rules=["settle-once"])
    assert rules_and_lines(findings) == [("settle-once", 5)]
    assert "without a matching settle sink" in findings[0].message


def test_settle_once_double_settlement_on_crash_path(tmp_path):
    findings = lint_tree(tmp_path, {
        "utils/metric_names.py": _MN_FIXTURE,
        "runtime/service.py": """\
            from utils import metric_names as mn

            class RecognizerService:
                def crash(self, tid):
                    self.metrics.incr(mn.FRAMES_FAILED)
                    self._trace_settle([tid], mn.FRAMES_FAILED, "a")
                    self._trace_settle([tid], mn.FRAMES_FAILED, "b")
                    raise RuntimeError("boom")
            """,
    }, rules=["settle-once"])
    # the raising path skips balance (crash handlers settle elsewhere)
    # but a double settlement of the same basis+status still fires.
    assert rules_and_lines(findings) == [("settle-once", 7)]
    assert "settles the same frame run twice" in findings[0].message


def test_settle_once_balanced_paths_and_prefix_family_clean(tmp_path):
    findings = lint_tree(tmp_path, {
        "utils/metric_names.py": _MN_FIXTURE,
        "runtime/service.py": """\
            from utils import metric_names as mn

            class FrameBatcher:
                def drop(self, entry, reason):
                    self.metrics.incr(mn.BATCHER_DROPPED_PREFIX + reason)
                    self._emit_settle(entry[3],
                                      mn.BATCHER_DROPPED_PREFIX + reason,
                                      "batcher")
                    return False

            class RecognizerService:
                def publish(self, tids, published, rejected):
                    self.metrics.incr(mn.FRAMES_ADMITTED)
                    try:
                        self.emit(tids)
                    finally:
                        self.metrics.incr(mn.FRAMES_COMPLETED, published)
                        self._trace_settle(tids, mn.FRAMES_COMPLETED, "ok")
                    if published < len(rejected):
                        self.metrics.incr(mn.FRAMES_FAILED)
                        self._trace_settle(tids, mn.FRAMES_FAILED, "fail")
            """,
    }, rules=["settle-once"])
    # FRAMES_ADMITTED is not terminal; both terminal incrs pair exactly.
    assert findings == []


def test_settle_once_literal_status_is_flagged(tmp_path):
    findings = lint_tree(tmp_path, {
        "utils/metric_names.py": _MN_FIXTURE,
        "runtime/service.py": """\
            from utils import metric_names as mn

            class RecognizerService:
                def fail_path(self, tid):
                    self.metrics.incr(mn.FRAMES_FAILED)
                    self._trace_settle([tid], "frames_failed", "x")
            """,
    }, rules=["settle-once"])
    # balance holds (the literal still pairs) — only hygiene fires.
    assert rules_and_lines(findings) == [("settle-once", 6)]
    assert "string literal" in findings[0].message


def test_settle_once_suppression(tmp_path):
    findings = lint_tree(tmp_path, {
        "utils/metric_names.py": _MN_FIXTURE,
        "runtime/service.py": """\
            from utils import metric_names as mn

            class RecognizerService:
                def fail_path(self, tids, count):
                    self.metrics.incr(mn.FRAMES_FAILED, count)  # ocvf-lint: disable=settle-once -- settled by the caller's crash handler in this fixture
                    return count
            """,
    }, rules=["settle-once"])
    assert findings == []


# ---------------- resource-pairing (v3) ----------------


def test_resource_pairing_custody_leak_and_boundary(tmp_path):
    source = """\
        class FrameBatcher:
            def pop(self, count):
                buf = self._ring.acquire(count)
                data = self.fill(count)
                if data is None:
                    return None
                self.out.append((data, buf))
                return data
        """
    findings = lint_tree(tmp_path, {"runtime/batcher.py": source},
                         rules=["resource-pairing"])
    # anchored at the acquire, with the leaking exit as an also-site.
    assert rules_and_lines(findings) == [("resource-pairing", 3)]
    assert findings[0].also == ((str(tmp_path / "runtime" / "batcher.py"), 6),)
    # a boundary annotation on the leaking EXIT line sanctions the path.
    suppressed = source.replace(
        "return None",
        "return None  # ocvf-lint: boundary=resource-pairing -- fixture: caller inherits the buffer through self.pending on this path")
    findings = lint_tree(tmp_path / "b", {"runtime/batcher.py": suppressed},
                         rules=["resource-pairing"])
    assert findings == []


def test_resource_pairing_release_forfeit_and_handoff_clean(tmp_path):
    findings = lint_tree(tmp_path, {
        "runtime/batcher.py": """\
            class FrameBatcher:
                def pop(self, count):
                    buf = self._ring.acquire(count)
                    try:
                        data = self.fill(count)
                    except Exception:
                        self._ring.forfeit(buf)
                        raise
                    self._ring.recycle(buf)
                    return data

                def pop_handoff(self, count):
                    buf = self._ring.acquire(count)
                    return self.pack(buf)
            """,
    }, rules=["resource-pairing"])
    assert findings == []


def test_resource_pairing_forfeit_missing_on_crash_path(tmp_path):
    findings = lint_tree(tmp_path, {
        "runtime/batcher.py": """\
            class FrameBatcher:
                def pop(self, count):
                    buf = self._ring.acquire(count)
                    try:
                        data = self.fill(count)
                    except Exception:
                        self.log("fill failed")
                        raise
                    self._ring.recycle(buf)
                    return data
            """,
    }, rules=["resource-pairing"])
    # the normal path releases; the crash path leaks the buffer.
    assert rules_and_lines(findings) == [("resource-pairing", 3)]
    assert "crash paths" in findings[0].message


def test_resource_pairing_discarded_acquire(tmp_path):
    findings = lint_tree(tmp_path, {
        "runtime/batcher.py": """\
            class FrameBatcher:
                def warm(self, count):
                    self._ring.acquire(count)
            """,
    }, rules=["resource-pairing"])
    assert rules_and_lines(findings) == [("resource-pairing", 3)]
    assert "discarded" in findings[0].message


def test_resource_pairing_seq_burn_and_watermark(tmp_path):
    findings = lint_tree(tmp_path, {
        "runtime/state_store.py": """\
            class StateLifecycle:
                def enroll(self, rows):
                    seq = self._wal_seq = self._wal_seq + 1
                    if not rows:
                        raise ValueError("empty enrollment")
                    self.wal.append_enroll(seq, rows)
                    return seq

                def adopt(self, highest):
                    self._wal_seq = max(self._wal_seq, int(highest))
                    return self._wal_seq
            """,
    }, rules=["resource-pairing"])
    # the early raise leaks the burned seq; watermark seeding is NOT a
    # burn (max(), not the increment idiom) and stays silent.
    assert rules_and_lines(findings) == [("resource-pairing", 3)]
    assert "append_*" in findings[0].message


def test_resource_pairing_seq_burn_abort_path_clean(tmp_path):
    findings = lint_tree(tmp_path, {
        "runtime/state_store.py": """\
            class StateLifecycle:
                def enroll(self, rows):
                    seq = self._wal_seq = self._wal_seq + 1
                    try:
                        self.wal.append_enroll(seq, rows)
                    except BaseException:
                        self.wal.append_abort(seq)
                        raise
                    return seq
            """,
    }, rules=["resource-pairing"])
    assert findings == []


def test_resource_pairing_lifecycle_needs_with(tmp_path):
    findings = lint_tree(tmp_path, {
        "mod.py": """\
            class Worker:
                def bad(self):
                    span = self._tracer.lifecycle("swap")
                    return span

                def good(self):
                    with self._tracer.lifecycle("swap"):
                        return 1
            """,
    }, rules=["resource-pairing"])
    assert rules_and_lines(findings) == [("resource-pairing", 3)]
    assert "contextmanager" in findings[0].message


# ---------------- fence-ordering (v3) ----------------


def test_fence_ordering_install_before_fence(tmp_path):
    findings = lint_tree(tmp_path, {
        "runtime/state_store.py": """\
            class StateLifecycle:
                def perform_cutover(self, to_version, emb):
                    seq = self.alloc()
                    self.gallery.load_snapshot(emb, to_version)
                    self.wal.append_cutover(seq, to_version)
                    return seq
            """,
    }, rules=["fence-ordering"])
    assert rules_and_lines(findings) == [("fence-ordering", 4)]
    assert "before the WAL fence append" in findings[0].message


def test_fence_ordering_fence_first_with_faults_clean(tmp_path):
    findings = lint_tree(tmp_path, {
        "runtime/state_store.py": """\
            class StateLifecycle:
                def perform_cutover(self, to_version, emb, fault):
                    seq = self.alloc()
                    if fault == "before":
                        raise RuntimeError("crash before record")
                    self.wal.append_cutover(seq, to_version)
                    if fault == "after":
                        raise RuntimeError("crash after record")
                    self.gallery.load_snapshot(emb, to_version)
                    return seq

                def perform_registry_cutover(self, role, install_fn):
                    seq = self.alloc()
                    self.wal.append_registry_cutover(seq, role)
                    self.registry.install(role)
                    install_fn()
                    return seq
            """,
    }, rules=["fence-ordering"])
    assert findings == []


def test_fence_ordering_installer_callback_before_fence(tmp_path):
    findings = lint_tree(tmp_path, {
        "runtime/state_store.py": """\
            class StateLifecycle:
                def perform_registry_cutover(self, role, install_fn):
                    seq = self.alloc()
                    install_fn()
                    self.wal.append_registry_cutover(seq, role)
                    return seq
            """,
    }, rules=["fence-ordering"])
    assert rules_and_lines(findings) == [("fence-ordering", 4)]


def test_fence_ordering_durable_writer_needs_atomic_helper(tmp_path):
    findings = lint_tree(tmp_path, {
        "runtime/registry.py": """\
            class ModelRegistry:
                def _save_locked(self):
                    with open(self.path, "w") as fh:
                        fh.write(self.blob)
            """,
    }, rules=["fence-ordering"])
    got = rules_and_lines(findings)
    # the bare write-mode open AND the missing atomic_write_* both fire.
    assert ("fence-ordering", 3) in got
    assert ("fence-ordering", 2) in got
    clean = lint_tree(tmp_path / "b", {
        "runtime/registry.py": """\
            class ModelRegistry:
                def _save_locked(self):
                    atomic_write_json(self.path, self.blob)
            """,
    }, rules=["fence-ordering"])
    assert clean == []


# ---------------- ledger-registry-coherence (v3) ----------------

_COHERENT_TREE = {
    "utils/metric_names.py": """\
        FRAMES_COMPLETED = "frames_completed"
        FRAMES_COMPLETED_EMPTY = "frames_completed_empty"
        FRAMES_FAILED = "frames_failed"
        REJ_PREFIX = "frames_rejected_"
        LEDGER_COMPLETION_COUNTERS = (FRAMES_COMPLETED,
                                      FRAMES_COMPLETED_EMPTY)
        LEDGER_DROP_COUNTERS = (FRAMES_FAILED,)
        PROM_FOLDED_PREFIXES = (REJ_PREFIX,)
        """,
    "utils/tracing.py": """\
        OUTCOME_COMPLETED = "completed"
        OUTCOME_COMPLETED_EMPTY = "completed_empty"

        def account_spans(spans):
            return {OUTCOME_COMPLETED: 0, OUTCOME_COMPLETED_EMPTY: 0}
        """,
    "runtime/recognizer.py": """\
        from utils import metric_names as mn

        class RecognizerService:
            LEDGER_DROP_COUNTERS = mn.LEDGER_DROP_COUNTERS

            def ledger(self):
                return (mn.FRAMES_COMPLETED, mn.FRAMES_COMPLETED_EMPTY,
                        self.LEDGER_DROP_COUNTERS)

            def frames_in_system(self):
                return (mn.FRAMES_COMPLETED, mn.FRAMES_COMPLETED_EMPTY,
                        self.LEDGER_DROP_COUNTERS)
        """,
    "runtime/promtext.py": """\
        from utils import metric_names as mn

        _LABEL_FAMILIES = ((mn.REJ_PREFIX, "frames_rejected", "reason"),)
        """,
    "scripts/chaos_soak.py": """\
        def _check_span_accounting(acct):
            assert acct["completed"] >= 0
            assert acct["completed_empty"] >= 0
        """,
}


def test_coherence_full_tree_is_clean(tmp_path):
    findings = lint_tree(tmp_path, dict(_COHERENT_TREE),
                         rules=["ledger-registry-coherence"])
    assert findings == []


def test_coherence_missing_tracing_mirror_and_reducer_ref(tmp_path):
    tree = dict(_COHERENT_TREE)
    tree["utils/tracing.py"] = """\
        OUTCOME_COMPLETED = "completed"

        def account_spans(spans):
            return {OUTCOME_COMPLETED: 0}
        """
    findings = lint_tree(tmp_path, tree,
                         rules=["ledger-registry-coherence"])
    assert [f.rule for f in findings] == ["ledger-registry-coherence"]
    assert "no OUTCOME_* mirror" in findings[0].message
    assert "completed_empty" in findings[0].message


def test_coherence_recognizer_drop_tuple_drift(tmp_path):
    tree = dict(_COHERENT_TREE)
    tree["runtime/recognizer.py"] = """\
        from utils import metric_names as mn

        class RecognizerService:
            LEDGER_DROP_COUNTERS = (mn.FRAMES_FAILED, mn.FRAMES_BOGUS)

            def ledger(self):
                return (mn.FRAMES_COMPLETED, mn.FRAMES_COMPLETED_EMPTY,
                        self.LEDGER_DROP_COUNTERS)

            def frames_in_system(self):
                return (mn.FRAMES_COMPLETED, mn.FRAMES_COMPLETED_EMPTY,
                        self.LEDGER_DROP_COUNTERS)
        """
    findings = lint_tree(tmp_path, tree,
                         rules=["ledger-registry-coherence"])
    assert rules_and_lines(findings) == [("ledger-registry-coherence", 4)]
    assert "drifted from the registry table" in findings[0].message


def test_coherence_missing_completion_in_ledger_surface(tmp_path):
    tree = dict(_COHERENT_TREE)
    tree["runtime/recognizer.py"] = """\
        from utils import metric_names as mn

        class RecognizerService:
            LEDGER_DROP_COUNTERS = mn.LEDGER_DROP_COUNTERS

            def ledger(self):
                return (mn.FRAMES_COMPLETED, self.LEDGER_DROP_COUNTERS)

            def frames_in_system(self):
                return (mn.FRAMES_COMPLETED, mn.FRAMES_COMPLETED_EMPTY,
                        self.LEDGER_DROP_COUNTERS)
        """
    findings = lint_tree(tmp_path, tree,
                         rules=["ledger-registry-coherence"])
    assert rules_and_lines(findings) == [("ledger-registry-coherence", 6)]
    assert "FRAMES_COMPLETED_EMPTY" in findings[0].message


def test_coherence_promtext_family_drift(tmp_path):
    tree = dict(_COHERENT_TREE)
    tree["runtime/promtext.py"] = """\
        from utils import metric_names as mn

        _LABEL_FAMILIES = ()
        """
    findings = lint_tree(tmp_path, tree,
                         rules=["ledger-registry-coherence"])
    assert rules_and_lines(findings) == [("ledger-registry-coherence", 3)]
    assert "REJ_PREFIX" in findings[0].message


def test_coherence_chaos_soak_missing_outcome(tmp_path):
    tree = dict(_COHERENT_TREE)
    tree["scripts/chaos_soak.py"] = """\
        def _check_span_accounting(acct):
            assert acct["completed"] >= 0
        """
    findings = lint_tree(tmp_path, tree,
                         rules=["ledger-registry-coherence"])
    assert rules_and_lines(findings) == [("ledger-registry-coherence", 1)]
    assert "completed_empty" in findings[0].message


def test_coherence_sites_absent_from_subset_lint_are_skipped(tmp_path):
    tree = {k: v for k, v in _COHERENT_TREE.items()
            if k in ("utils/metric_names.py", "scripts/chaos_soak.py")}
    findings = lint_tree(tmp_path, tree,
                         rules=["ledger-registry-coherence"])
    assert findings == []


# ---------------- v3 scratch-copy deletion gates ----------------
# The acceptance demonstration: delete ONE settlement call / custody
# overwrite / fence append from a copy of the REAL tree and the matching
# rule must fire at the mutated site.


def _real_source(rel):
    with open(os.path.join(REPO_ROOT, rel), "r", encoding="utf-8") as fh:
        return fh.read()


def test_scratch_delete_settlement_call_fires_settle_once(tmp_path):
    src = _real_source("opencv_facerecognizer_tpu/runtime/recognizer.py")
    needle = ('self._trace_settle(trace_ids[:count], mn.FRAMES_FAILED,\n'
              '                                   "dispatch.abandoned", '
              'batch=batch_tid)\n                ')
    assert needle in src, "recognizer settle site moved; update the fixture"
    mutated = src.replace(needle, "", 1)
    path = tmp_path / "runtime" / "recognizer.py"
    path.parent.mkdir(parents=True)
    path.write_text(mutated)
    findings = core.run([str(tmp_path)], rules=["settle-once"]).findings
    incr_line = mutated.splitlines().index(
        "                self.metrics.incr(mn.FRAMES_FAILED, count)") + 1
    assert ("settle-once", incr_line) in rules_and_lines(findings)


def test_scratch_break_custody_overwrite_fires_resource_pairing(tmp_path):
    src = _real_source("opencv_facerecognizer_tpu/runtime/batcher.py")
    assert "buf = _EXHAUSTED" in src, "batcher custody site moved"
    # the exhausted-branch overwrite is what ENDS custody of the acquired
    # buffer on the retry path; renaming it leaks custody to `return None`
    mutated = src.replace("buf = _EXHAUSTED", "buf_retry = _EXHAUSTED", 1)
    path = tmp_path / "runtime" / "batcher.py"
    path.parent.mkdir(parents=True)
    path.write_text(mutated)
    findings = core.run([str(tmp_path)],
                        rules=["resource-pairing"]).findings
    acquire_line = next(i for i, line in enumerate(mutated.splitlines(), 1)
                        if "self._ring.acquire(" in line)
    assert ("resource-pairing", acquire_line) in rules_and_lines(findings)


def test_scratch_delete_fence_append_fires_fence_ordering(tmp_path):
    src = _real_source("opencv_facerecognizer_tpu/runtime/state_store.py")
    needle = ("""self.wal.append_cutover(seq, from_version, int(to_version),
                                    rows=int(size), dim=int(emb.shape[1]))""")
    assert needle in src, "cutover fence site moved; update the fixture"
    mutated = src.replace(needle, "_ = seq", 1)
    path = tmp_path / "runtime" / "state_store.py"
    path.parent.mkdir(parents=True)
    path.write_text(mutated)
    findings = core.run([str(tmp_path)],
                        rules=["fence-ordering"]).findings
    lines = mutated.splitlines()
    mark = next(i for i, line in enumerate(lines, 1)
                if line.strip() == "_ = seq")
    install_line = next(i for i, line in enumerate(lines, 1)
                        if i > mark and "load_snapshot(" in line)
    assert ("fence-ordering", install_line) in rules_and_lines(findings)


# ---------------- incremental cache ----------------


def _cache_tree(tmp_path):
    tree = tmp_path / "tree"
    files = {
        "a.py": 'def f(p):\n    open(p, "w").write("x")\n',
        "b.py": "def g():\n    try:\n        work()\n    except Exception:\n"
                "        pass\n",
        "c.py": "x = 1\n",
    }
    for rel, src in files.items():
        p = tree / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(src)
    return tree


def test_cache_returns_identical_findings_to_cold_run(tmp_path):
    from tools.ocvf_lint.cache import LintCache

    tree = _cache_tree(tmp_path)
    cold = core.run([str(tree)])
    cache = LintCache(str(tmp_path / "cache"))
    warm1 = core.run([str(tree)], cache=cache)     # populates
    cache2 = LintCache(str(tmp_path / "cache"))    # reload from disk
    warm2 = core.run([str(tree)], cache=cache2)    # full run-layer hit
    as_dicts = lambda r: [f.to_dict() for f in r.findings]  # noqa: E731
    assert as_dicts(cold) == as_dicts(warm1) == as_dicts(warm2)
    assert cold.rule_counts() == warm2.rule_counts()
    assert warm2.cache.get("run_hit") is True
    assert warm2.suppressions_used == cold.suppressions_used


def test_cache_file_layer_replays_unchanged_files(tmp_path):
    from tools.ocvf_lint.cache import LintCache

    tree = _cache_tree(tmp_path)
    cache = LintCache(str(tmp_path / "cache"))
    core.run([str(tree)], cache=cache)
    # edit ONE file: its findings refresh, the others replay by hash
    (tree / "c.py").write_text('def h(p):\n    open(p, "w").write("y")\n')
    cache2 = LintCache(str(tmp_path / "cache"))
    warm = core.run([str(tree)], cache=cache2)
    assert warm.cache["run_hit"] is False
    assert warm.cache["file_hits"] == 2
    assert warm.cache["file_misses"] == 1
    cold = core.run([str(tree)])
    assert [f.to_dict() for f in warm.findings] == \
        [f.to_dict() for f in cold.findings]
    assert any(f.path.endswith("c.py") for f in warm.findings)


def test_cache_invalidated_by_tool_fingerprint(tmp_path):
    from tools.ocvf_lint import cache as cache_mod

    tree = _cache_tree(tmp_path)
    cache = cache_mod.LintCache(str(tmp_path / "cache"))
    core.run([str(tree)], cache=cache)
    # simulate a linter edit: a different fingerprint must see an EMPTY cache
    stale = cache_mod.LintCache(str(tmp_path / "cache"))
    stale.fingerprint = "not-the-real-fingerprint"
    stale.data = {"tool": stale.fingerprint, "files": {}, "runs": {}}
    warm = core.run([str(tree)], cache=stale)
    assert warm.cache["run_hit"] is False
    assert warm.cache["file_misses"] == 3


def test_cached_rerun_meets_runtime_budget():
    """The tier-1 gate must stay fast as rules multiply: an unchanged-tree
    re-run rides the run-layer cache.  Budget is wall-clock generous (this
    box has one CPU core and the subprocess pays interpreter startup) but
    far below a cold run with ten rules over 60+ files."""
    import shutil
    import time

    cache_dir = os.path.join(REPO_ROOT, ".ocvf_lint_cache_test")
    shutil.rmtree(cache_dir, ignore_errors=True)
    try:
        warm = _cli("opencv_facerecognizer_tpu", "scripts", "--json",
                    "--cache-dir", cache_dir)
        assert warm.returncode == 0, warm.stdout + warm.stderr
        t0 = time.perf_counter()
        hit = _cli("opencv_facerecognizer_tpu", "scripts", "--json",
                   "--cache-dir", cache_dir)
        elapsed = time.perf_counter() - t0
        assert hit.returncode == 0
        doc = json.loads(hit.stdout)
        assert doc["cache"]["run_hit"] is True
        assert doc["findings"] == []
        assert elapsed < 15.0, f"cached lint re-run took {elapsed:.1f}s"
    finally:
        shutil.rmtree(cache_dir, ignore_errors=True)


# ---------------- baseline / ratchet ----------------


def test_baseline_regression_fails_and_update_refuses_growth(tmp_path):
    from tools.ocvf_lint import baseline as baseline_mod

    bad = tmp_path / "bad.py"
    bad.write_text('def f(p):\n    open(p, "w").write("x")\n')
    base = tmp_path / "base.json"

    # frozen at the current count: rc 0 even though findings exist
    proc = _cli(str(bad), "--baseline", str(base), "--update-baseline",
                "--baseline-allow-growth")
    assert proc.returncode == 0, proc.stderr
    allowed = baseline_mod.load(str(base))
    assert allowed["non-atomic-write"] == 1
    proc = _cli(str(bad), "--baseline", str(base))
    assert proc.returncode == 0, proc.stdout + proc.stderr

    # a SECOND finding regresses past the frozen count: rc 1
    bad.write_text('def f(p):\n    open(p, "w").write("x")\n'
                   'def g(p):\n    open(p, "w").write("y")\n')
    proc = _cli(str(bad), "--baseline", str(base), "--no-cache")
    assert proc.returncode == 1
    assert "REGRESSION" in proc.stderr

    # and --update-baseline refuses to freeze the regression in
    proc = _cli(str(bad), "--baseline", str(base), "--update-baseline",
                "--no-cache")
    assert proc.returncode == 1
    assert "refusing to grow" in proc.stderr
    assert baseline_mod.load(str(base))["non-atomic-write"] == 1

    # fixing back down passes, and the ratchet can tighten
    bad.write_text("x = 1\n")
    proc = _cli(str(bad), "--baseline", str(base), "--no-cache")
    assert proc.returncode == 0
    proc = _cli(str(bad), "--baseline", str(base), "--update-baseline",
                "--no-cache")
    assert proc.returncode == 0
    assert baseline_mod.load(str(base))["non-atomic-write"] == 0


# ---------------- SARIF output ----------------


def test_sarif_output_structure(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text('def f(p):\n    open(p, "w").write("x")\n')
    proc = _cli("--sarif", str(bad), "--no-cache")
    assert proc.returncode == 1
    doc = json.loads(proc.stdout)
    assert doc["version"] == "2.1.0"
    run = doc["runs"][0]
    assert run["tool"]["driver"]["name"] == "ocvf-lint"
    rule_ids = {r["id"] for r in run["tool"]["driver"]["rules"]}
    assert "non-atomic-write" in rule_ids
    result = run["results"][0]
    assert result["ruleId"] == "non-atomic-write"
    loc = result["locations"][0]["physicalLocation"]
    assert loc["region"]["startLine"] == 2


def test_cache_is_path_sensitive_for_location_dependent_rules(tmp_path):
    """Identical bytes mean different things at different paths (tests/
    exemption, owner-module suffixes) — the file layer must key on BOTH,
    or moving a file across an exemption boundary replays a stale clean
    verdict."""
    from tools.ocvf_lint.cache import LintCache

    src = ("import jax\nimport time\n\n"
           "def make_key():\n"
           "    return jax.random.PRNGKey(int(time.time()))\n")
    tree = tmp_path / "tree"
    exempt = tree / "tests" / "test_x.py"
    exempt.parent.mkdir(parents=True)
    exempt.write_text(src)
    cache = LintCache(str(tmp_path / "cache"))
    clean = core.run([str(tree)], rules=["prng-discipline"], cache=cache)
    assert clean.findings == []  # tests/ is exempt from the seed rule
    # same BYTES promoted out of tests/: must be a finding on a warm cache
    promoted = tree / "keys.py"
    promoted.write_text(src)
    exempt.unlink()
    cache2 = LintCache(str(tmp_path / "cache"))
    warm = core.run([str(tree)], rules=["prng-discipline"], cache=cache2)
    assert [(f.rule, f.line) for f in warm.findings] == \
        [("prng-discipline", 5)]


def test_update_baseline_with_rules_subset_preserves_other_counts(tmp_path):
    from tools.ocvf_lint import baseline as baseline_mod

    base = tmp_path / "base.json"
    err = baseline_mod.update(str(base), {"lock-order": 2, "host-sync": 1},
                              ["lock-order", "host-sync"])
    assert err is None
    # a subset run measuring only host-sync must not wipe lock-order's
    # frozen reserve
    err = baseline_mod.update(str(base), {"host-sync": 0}, ["host-sync"])
    assert err is None
    allowed = baseline_mod.load(str(base))
    assert allowed == {"lock-order": 2, "host-sync": 0}


def test_run_cache_key_covers_fallback_metric_registry(tmp_path):
    """metrics-registry reads utils/metric_names.py from disk when it is
    not among the linted files — that out-of-tree input must be folded
    into the run-cache key, or editing the registry replays a stale clean
    verdict for subset lints (run_lint.sh --changed)."""
    from tools.ocvf_lint.cache import LintCache
    from tools.ocvf_lint.checkers.metrics_registry import MetricsRegistryChecker

    checker = MetricsRegistryChecker()
    fp = checker.extra_cache_fingerprint(["scripts/chaos_soak.py"])
    assert fp.startswith("metrics-registry:")
    assert len(fp) > len("metrics-registry:")
    # registry in the linted set: its hash is already a key input
    assert checker.extra_cache_fingerprint(
        ["opencv_facerecognizer_tpu/utils/metric_names.py"]) == ""
    cache = LintCache(str(tmp_path / "cache"))
    k1 = cache.run_key(["metrics-registry"], [("a.py", "h")], extra=fp)
    k2 = cache.run_key(["metrics-registry"], [("a.py", "h")],
                       extra="metrics-registry:different")
    assert k1 != k2


def test_jit_hazard_partial_decorator_reported_once(tmp_path):
    findings = lint_tree(tmp_path, {
        "parallel/pipeline.py": """\
            import functools

            import jax

            @functools.partial(jax.jit, static_argnames=("flag",))
            def step(x, flag):
                return x
            """,
    }, rules=["jit-recompile-hazard"])
    assert len(findings) == 1, rules_and_lines(findings)
    assert "@jit-decorated" in findings[0].message


def test_update_baseline_refuses_corrupt_existing(tmp_path):
    from tools.ocvf_lint import baseline as baseline_mod

    base = tmp_path / "base.json"
    base.write_text("{this is not json")
    err = baseline_mod.update(str(base), {"host-sync": 3}, ["host-sync"])
    assert err is not None and "unreadable" in err
    assert base.read_text() == "{this is not json"  # nothing rewritten
    # the explicit override path may rebuild from scratch
    err = baseline_mod.update(str(base), {"host-sync": 3}, ["host-sync"],
                              allow_growth=True)
    assert err is None
    assert baseline_mod.load(str(base)) == {"host-sync": 3}
